from .nexmark import (  # noqa: F401
    AUCTION_SCHEMA, BID_SCHEMA, PERSON_SCHEMA, NexmarkConfig, NexmarkGenerator,
)
