"""NEXmark as a seekable split source.

The reference's NEXmark connector partitions the event stream into splits
by ``event_id % n_splits`` (reference:
src/connector/src/source/nexmark/split.rs, source/reader.rs:41). Here the
generator is already vectorized (connector/nexmark.py) and deterministic
given (seed, chunk index), so a single split with offset = number of
emitted chunks suffices for checkpointing; ``seek`` replays the generator
to the offset (cheap: vectorized generation, no IO).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..common.chunk import StreamChunk
from .base import SplitReader
from .nexmark import NexmarkConfig, NexmarkGenerator


class NexmarkReader(SplitReader):
    def __init__(self, table: str, chunk_capacity: int = 1024,
                 seed: int = 42):
        self.table = table.lower()
        self.chunk_capacity = chunk_capacity
        self.seed = seed
        self._gen = NexmarkGenerator(
            NexmarkConfig(chunk_capacity=chunk_capacity), seed=seed)
        self._n = 0

    def _fn(self, gen: NexmarkGenerator):
        return {"bid": gen.next_bid_chunk,
                "auction": gen.next_auction_chunk,
                "person": gen.next_person_chunk}[self.table]

    def splits(self) -> List[str]:
        return ["0"]

    @property
    def offsets(self) -> Dict[str, int]:
        return {"0": self._n}

    def seek(self, offsets: Dict[str, int]) -> None:
        target = int(offsets.get("0", 0))
        if target < self._n:
            self._gen = NexmarkGenerator(
                NexmarkConfig(chunk_capacity=self.chunk_capacity),
                seed=self.seed)
            self._n = 0
        fn = self._fn(self._gen)
        while self._n < target:
            fn()
            self._n += 1

    def rows_emitted(self) -> int:
        return self._n * self.chunk_capacity

    def next_chunk(self) -> Optional[StreamChunk]:
        chunk = self._fn(self._gen)()
        if chunk is not None:
            self._n += 1
        return chunk
