"""TPC-H order/lineitem event stream generated ON DEVICE — the q3 bench
source (reference workload: e2e_test/tpch/ streaming q3).

Same design as ``DeviceBidGenerator`` (connector/nexmark.py): the datagen
is a compute kernel, so fused epochs pay two scalars of host→device
traffic per epoch. Events interleave one ORDER row followed by
``lineitems_per_order`` LINEITEM rows of that order (orders always
precede their lineitems — the stream-order guarantee
``ops/stream_q3.Q3Core`` relies on). All attribute randomness is
counter-based splitmix64 hashing of the event/order id, so generation is
deterministic and replayable from the event id alone (no PRNG key
threading needed; the ``key`` argument of ``chunk_fn`` is accepted and
ignored for interface parity with the NEXmark source).

Value distributions (synthetic, selectivity-tuned rather than
spec-exact): o_orderdate uniform in [cutoff-30, cutoff+30) days — the
``o_orderdate < cutoff`` filter passes ~50%; o_mktsegment uniform over 5
segments (segment 0 = 'BUILDING', ~20% pass); l_shipdate = o_orderdate +
[-10, 40) days; prices in cents, discounts in basis points (int64
end-to-end — see stream_q3.py on integral money)."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.hashing import _splitmix64
from ..common.types import INT64, Schema

#: unified order/lineitem event schema (kind 0 = order, 1 = lineitem;
#: order rows zero the l_* columns and vice versa)
Q3_EVENT_SCHEMA = Schema.of(
    ("kind", INT64), ("orderkey", INT64), ("o_orderdate", INT64),
    ("o_shippriority", INT64), ("o_mktsegment", INT64),
    ("l_extendedprice", INT64), ("l_discount_bp", INT64),
    ("l_shipdate", INT64),
)

#: 1995-03-15 as days since the unix epoch — the q3 date parameter
Q3_CUTOFF_DAYS = 9204


@dataclasses.dataclass
class TpchQ3Config:
    chunk_capacity: int = 1024
    lineitems_per_order: int = 3
    cutoff_days: int = Q3_CUTOFF_DAYS
    n_segments: int = 5            # o_mktsegment ∈ [0, n_segments)


class DeviceQ3Generator:
    """Traceable q3 event chunks; compose ``chunk_fn()`` inside a fused
    epoch (ops/fused_epoch.fused_source_q3_epoch)."""

    def __init__(self, config: TpchQ3Config = TpchQ3Config()):
        self.cfg = config

    def chunk_fn(self):
        cfg = self.cfg
        cap = cfg.chunk_capacity
        gsize = 1 + cfg.lineitems_per_order

        def fn(start, key=None):
            eids = start + jnp.arange(cap, dtype=jnp.int64)
            g = eids // gsize                       # orderkey
            pos = eids % gsize
            kind = (pos != 0).astype(jnp.int64)
            ho = _splitmix64(g.astype(jnp.uint64)).astype(jnp.int64)
            ho = ho & jnp.int64(0x7FFFFFFFFFFFFFFF)
            odate = cfg.cutoff_days - 30 + (ho % 60)
            mkt = (ho >> 8) % cfg.n_segments
            prio = (ho >> 16) % 3
            hl = _splitmix64((eids + jnp.int64(0x9E37)).astype(
                jnp.uint64)).astype(jnp.int64)
            hl = hl & jnp.int64(0x7FFFFFFFFFFFFFFF)
            price = 100_00 + hl % 9_000_00          # cents
            disc = (hl >> 20) % 1001                # basis points, ≤ 10%
            ship = odate + ((hl >> 40) % 50) - 10
            is_li = kind == 1

            def mk(vals, on):
                return Column(jnp.where(on, vals, 0),
                              jnp.ones(cap, jnp.bool_))

            cols = (
                Column(kind, jnp.ones(cap, jnp.bool_)),
                Column(g, jnp.ones(cap, jnp.bool_)),
                mk(odate, ~is_li), mk(prio, ~is_li), mk(mkt, ~is_li),
                mk(price, is_li), mk(disc, is_li), mk(ship, is_li),
            )
            return StreamChunk(jnp.zeros(cap, jnp.int8),
                               jnp.ones(cap, jnp.bool_), cols)

        return fn
