"""Sink implementations: where changelogs leave the system.

Counterpart of the reference's sink connectors
(reference: src/connector/src/sink/mod.rs:150-160 — Kafka, Redis,
BlackHole, Remote…). Only host-side IO lives here; the delivery protocol
(log store, epoch tracking, exactly-once truncation) is the SinkExecutor's
job (stream/sink.py).

``FileSink`` is the durable local sink: JSONL/CSV appended per epoch with
a byte-offset handle, so the executor can truncate uncommitted tail bytes
after a crash — the file-system analogue of the reference's two-phase
commit per sink epoch.
"""

from __future__ import annotations

import json
import os
from typing import Any, List, Optional, Sequence, Tuple

from ..common.chunk import OP_DELETE, OP_INSERT, OP_UPDATE_DELETE
from ..common.types import Schema

Row = Tuple[int, tuple]          # (op, values)

_OP_NAMES = {0: "insert", 1: "delete", 2: "update_delete", 3: "update_insert"}


class Sink:
    def write_rows(self, rows: Sequence[Row]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        """Make previous writes durable (fsync/commit)."""

    def position(self) -> int:
        """Opaque monotone delivery position (bytes/rows delivered)."""
        return 0

    def truncate_to(self, position: int) -> None:
        """Recovery: discard deliveries past ``position`` when possible."""

    def close(self) -> None:
        pass


class BlackHoleSink(Sink):
    """Swallow everything; count rows (reference: sink/mod.rs BlackHole)."""

    def __init__(self) -> None:
        self.rows_written = 0

    def write_rows(self, rows: Sequence[Row]) -> None:
        self.rows_written += len(rows)

    def position(self) -> int:
        return self.rows_written

    def truncate_to(self, position: int) -> None:
        self.rows_written = position


class FileSink(Sink):
    def __init__(self, path: str, schema: Schema, fmt: str = "jsonl"):
        self.path = path
        self.schema = schema
        self.fmt = fmt.lower()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a+", encoding="utf-8")

    def _encode(self, op: int, values: tuple) -> str:
        if self.fmt == "csv":
            import csv as _csv
            import io as _io
            buf = _io.StringIO()
            _csv.writer(buf, lineterminator="\n").writerow(
                [_OP_NAMES[op]] + ["" if v is None else v for v in values])
            return buf.getvalue()
        obj = {f.name: v for f, v in zip(self.schema, values)}
        obj["__op"] = _OP_NAMES[op]
        return json.dumps(obj, default=str) + "\n"

    def write_rows(self, rows: Sequence[Row]) -> None:
        for op, values in rows:
            self._f.write(self._encode(op, values))

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def position(self) -> int:
        self._f.flush()
        return self._f.tell()

    def truncate_to(self, position: int) -> None:
        self._f.flush()
        self._f.truncate(position)
        self._f.seek(position)

    def close(self) -> None:
        self._f.close()


class BrokerSink(Sink):
    """Changelog → broker topic as JSON messages with an ``__op`` field
    (reference: the Kafka sink's changelog-JSON shape,
    src/connector/src/sink/kafka.rs). Delivery position = messages
    published; the broker log is append-only so truncation is logical
    (consumers use offsets) — ACROSS CRASHES this is at-least-once like
    the reference's non-transactional Kafka sink.

    Within one process, though, the SinkExecutor's retry loop replays
    the SAME batch after a failed attempt, so a landed-but-unacked
    prefix must not be republished: the sink tracks how many of its
    messages actually reached the partition (the client's offset cursor
    is authoritative even across mid-batch reconnects) and skips that
    prefix on the retry — delivery order is deterministic (log rows in
    (epoch, seq) order), so the prefix is exactly the duplicate set."""

    def __init__(self, address: str, topic: str, schema: Schema,
                 partition: int = 0, reconnect_policy=None):
        from .broker import BrokerClient
        self.client = BrokerClient(address,
                                   reconnect_policy=reconnect_policy)
        self.topic = topic
        self.schema = schema
        self.partition = partition
        self._published = 0          # executor-view position (monotone)
        self._base_off: Optional[int] = None
        self._session_landed = 0     # messages landed by THIS instance
        self._session_published = 0  # ...of which acked to the executor

    def write_rows(self, rows: Sequence[Row]) -> None:
        payloads = []
        for op, values in rows:
            obj = {"__op": _OP_NAMES.get(op, str(op))}
            for f, v in zip(self.schema, values):
                obj[f.name] = v          # already python-typed (sink.py)
            payloads.append(json.dumps(obj, default=str).encode())
        if self._base_off is None:
            self._base_off = self.client.partition_len(
                self.topic, self.partition)
        # retry dedup: a previous failed attempt may have landed a prefix
        # of this same batch — skip exactly those messages
        already = max(0, self._session_landed - self._session_published)
        send = payloads[min(already, len(payloads)):]
        try:
            if send:
                # pipelined batch: one RTT per epoch flush, not per row.
                # One partition per sink keeps the changelog totally
                # ordered (the reference's kafka sink orders per key via
                # key-hash partitioning; pick the partition with the
                # topic.partition option)
                self.client.publish_many(self.topic, self.partition, send)
        finally:
            cur = self.client.published_through(self.topic, self.partition)
            if cur is not None:
                self._session_landed = max(0, cur - self._base_off)
        self._session_published = self._session_landed
        self._published += len(payloads)

    def position(self) -> int:
        return self._published

    def truncate_to(self, position: int) -> None:
        self._published = position

    def close(self) -> None:
        self.client.close()


def build_sink(connector: str, options: dict, schema: Schema,
               fault=None) -> Sink:
    """Sink registry (reference: SinkImpl::new, sink/mod.rs:150).
    ``fault`` (a FaultConfig) tunes boundary retry policies."""
    c = connector.lower()
    if c in ("blackhole", ""):
        return BlackHoleSink()
    if c == "file":
        path = options.get("path")
        if not path:
            raise ValueError("file sink requires path option")
        return FileSink(str(path), schema,
                        fmt=str(options.get("format", "jsonl")))
    if c in ("broker", "kafka"):
        from .broker import parse_broker_options
        address, topic = parse_broker_options(options)
        return BrokerSink(address, topic, schema,
                          partition=int(options.get("topic.partition", 0)),
                          reconnect_policy=(fault.broker_retry_policy()
                                            if fault is not None else None))
    raise ValueError(f"unsupported sink connector {connector!r}")
