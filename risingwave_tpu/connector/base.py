"""Source connector framework: splits, readers, offset state.

Counterpart of the reference's source framework — ``SplitEnumerator`` /
``SplitReader`` traits and the ``SplitImpl`` state enum
(reference: src/connector/src/source/base.rs:295,326,340;
docs/data-source.md). A *split* is the unit of parallel, seekable ingest
(a Kafka partition, a file, a datagen shard); its *offset* is the
checkpointable read position. The runtime persists ``{split_id: offset}``
per source into a split-state table on checkpoint barriers and seeks
readers on recovery — the reference's split-state checkpointing
(src/stream/src/executor/source/state_table_handler.rs).

TPU angle: readers emit fixed-capacity columnar StreamChunks (static
shapes for XLA); ingest-side string interning happens here so device
columns stay integer-typed.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..common.chunk import StreamChunk


class SplitReader:
    """One source instance: a set of splits read round-robin.

    Offsets are *next-to-read* positions: after ``next_chunk`` returns rows
    ``[o, o+n)`` of split s, ``offsets[s] == o+n``. ``seek`` must make the
    subsequent chunks identical to a fresh reader fast-forwarded to the
    same offsets — that determinism is what makes source replay after
    recovery exactly-once end to end.
    """

    def splits(self) -> List[str]:
        raise NotImplementedError

    @property
    def offsets(self) -> Dict[str, int]:
        raise NotImplementedError

    def seek(self, offsets: Dict[str, int]) -> None:
        raise NotImplementedError

    def next_chunk(self) -> Optional[StreamChunk]:
        """Next chunk, or None when (currently) exhausted. Bounded sources
        return None forever once drained; unbounded ones never return None."""
        raise NotImplementedError

    def rows_emitted(self) -> int:
        """Rows emitted through the current offsets — an upper bound is
        acceptable. Used to restart serial row-id assignment above any id
        handed out before a crash (RowIdGen continuation on recovery)."""
        return sum(self.offsets.values())
