"""File source: a directory of JSONL/CSV files, one split per file.

Counterpart of the reference's FsSourceExecutor / S3 file source
(reference: src/stream/src/executor/source/fs_source_executor.rs,
src/connector/src/source/filesystem/). Each file is a split; the offset is
the *line number* next to read, so seek is cheap and replay after recovery
re-reads the same lines — files are assumed append-only between
checkpoints, the same contract the reference's fs source has.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..common.chunk import OP_INSERT, StreamChunk, make_chunk
from ..common.types import Schema
from .base import SplitReader
from .parsers import parse_csv_lines, parse_debezium_line, parse_json_line


class FileSourceReader(SplitReader):
    def __init__(self, schema: Schema, path: str,
                 fmt: str = "jsonl", rows_per_chunk: int = 256,
                 match_pattern: Optional[str] = None):
        self.schema = schema
        self.path = path
        self.fmt = fmt.lower()
        self.rows_per_chunk = rows_per_chunk
        self.match_pattern = match_pattern
        self._offsets: Dict[str, int] = {}
        self.dropped_events = 0      # unparseable debezium lines skipped
        # split → ((mtime_ns, size), line list): re-read only when the
        # file changed, not on every chunk
        self._cache: Dict[str, tuple] = {}
        self._discover()

    def _discover(self) -> None:
        """Split enumeration (reference: SplitEnumerator::list_splits).
        Called lazily per read cycle so files added at runtime are picked
        up, like the reference's periodic enumerator tick."""
        if os.path.isfile(self.path):
            names = [self.path]
        elif os.path.isdir(self.path):
            names = sorted(
                os.path.join(self.path, n) for n in os.listdir(self.path)
                if not n.startswith(".")
                and (self.match_pattern is None
                     or n.endswith(self.match_pattern)))
        else:
            names = []
        for n in names:
            self._offsets.setdefault(n, 0)

    def splits(self) -> List[str]:
        self._discover()
        return list(self._offsets)

    @property
    def offsets(self) -> Dict[str, int]:
        return dict(self._offsets)

    def seek(self, offsets: Dict[str, int]) -> None:
        for s, o in offsets.items():
            self._offsets[s] = int(o)

    def _lines(self, split: str) -> List[str]:
        try:
            st = os.stat(split)
        except OSError:
            return []
        key = (st.st_mtime_ns, st.st_size)
        cached = self._cache.get(split)
        if cached is None or cached[0] != key:
            try:
                with open(split, "r", encoding="utf-8") as f:
                    cached = (key, f.read().splitlines())
            except OSError:
                return []
            self._cache[split] = cached
        return cached[1]

    def _read_split(self, split: str) -> tuple:
        """-> (ops, rows): a changelog slice of the split. JSONL/CSV are
        append-only (all Insert); debezium_json carries the CDC envelope's
        ops (reference: src/connector/src/parser/debezium/)."""
        start = self._offsets[split]
        lines = self._lines(split)
        if self.fmt == "csv":
            # header line is line 0 of every csv split; data offsets start at 1
            if start == 0:
                start = 1
            body = lines[start:start + self.rows_per_chunk]
            header = lines[0] if lines else ""
            rows = parse_csv_lines("\n".join([header] + body), self.schema,
                                   has_header=True)
            ops = [OP_INSERT] * len(rows)
        elif self.fmt in ("debezium", "debezium_json"):
            body = lines[start:start + self.rows_per_chunk]
            ops, rows = [], []
            for ln in body:
                try:
                    entries = parse_debezium_line(ln, self.schema)
                except (ValueError, TypeError, KeyError) as e:
                    # poisoned line: skip, still advance — but LOUDLY:
                    # a dropped changelog event (unlike a dropped insert
                    # line) diverges downstream state from the upstream
                    self.dropped_events += 1
                    import sys
                    sys.stderr.write(
                        f"debezium: dropped unparseable event in "
                        f"{split}: {e}\n")
                    continue
                for op, r in entries:
                    ops.append(op)
                    rows.append(r)
        else:
            body = lines[start:start + self.rows_per_chunk]
            rows = []
            for ln in body:
                try:
                    r = parse_json_line(ln, self.schema)
                except (ValueError, TypeError):
                    # malformed line: skip it but still advance the offset
                    # — a poisoned line must not wedge the whole source
                    continue
                if r is not None:
                    rows.append(r)
            ops = [OP_INSERT] * len(rows)
        if body:
            self._offsets[split] = start + len(body)
        return ops, rows

    def next_chunk(self) -> Optional[StreamChunk]:
        self._discover()
        # most-behind split first: deterministic given offsets alone
        for split in sorted(self._offsets,
                            key=lambda s: (self._offsets[s], s)):
            ops, rows = self._read_split(split)
            if rows:
                phys = [tuple(f.type.to_physical(v) if v is not None else None
                              for f, v in zip(self.schema, r)) for r in rows]
                return make_chunk(self.schema, phys, ops=ops,
                                  capacity=max(self.rows_per_chunk,
                                               len(phys)),
                                  physical=True)
        return None
