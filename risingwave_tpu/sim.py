"""Deterministic simulation / chaos harness.

Counterpart of the reference's madsim deterministic cluster
(reference: src/tests/simulation/src/cluster.rs:129-247 — the whole
cluster in one process under a seeded scheduler, with ``--kill`` randomly
restarting nodes mid-workload; recovery tests
tests/integration_tests/recovery/). Scaled to this build's architecture:
the "cluster" is a durable Session; a *kill* abandons it without any
graceful shutdown and recovers a fresh Session from the same data dir
(crash recovery path), at epochs chosen by a seeded RNG.

Client semantics are honest: DML acknowledged only at FLUSH; statements
not yet flushed when a kill strikes are re-applied by the harness (client
retry), exactly how an at-least-once client driver behaves against the
reference. The end-state cross-check compares every MV against a control
session that never crashed.
"""

from __future__ import annotations

import random
from typing import List, Optional

from .frontend.session import Session


class SimCluster:
    def __init__(self, data_dir: str, seed: int = 0, kill_rate: float = 0.3,
                 checkpoint_frequency: int = 2, **session_kw):
        self.data_dir = data_dir
        self.rng = random.Random(seed)
        self.kill_rate = kill_rate
        self.session_kw = dict(session_kw,
                               checkpoint_frequency=checkpoint_frequency)
        self.session = Session(data_dir=data_dir, **self.session_kw)
        self.kills = 0
        self._unacked: List[str] = []     # DML since the last FLUSH

    # -- client API -----------------------------------------------------------

    def run_sql(self, sql: str) -> list:
        out = self.session.run_sql(sql)
        s = sql.lstrip().lower()
        if s.startswith("insert"):
            self._unacked.append(sql)
        elif s.startswith("flush"):
            self._unacked.clear()
        return out

    def flush(self) -> None:
        self.session.flush()
        self._unacked.clear()

    def tick(self) -> None:
        self.session.tick()

    def mv_rows(self, name: str) -> list:
        return self.session.mv_rows(name)

    # -- chaos ----------------------------------------------------------------

    def maybe_kill(self) -> bool:
        if self.rng.random() < self.kill_rate:
            self.kill()
            return True
        return False

    def kill(self) -> None:
        """Abandon the session with no shutdown (uncommitted state and
        unacked DML are lost), then recover + re-apply unacked DML."""
        self.kills += 1
        # crash semantics: no job shutdown, no flush — but do close the
        # abandoned private event loop so kills don't leak loops
        old = self.session
        try:
            old.loop.close()
        except Exception:   # noqa: BLE001
            pass
        self.session = Session(data_dir=self.data_dir, **self.session_kw)
        for sql in self._unacked:
            self.session.run_sql(sql)

    # -- verification ---------------------------------------------------------

    def verify_against(self, control: Session,
                       mv_names: Optional[List[str]] = None) -> None:
        """Final-state cross-check after both sides flushed."""
        self.flush()
        control.flush()
        names = mv_names or sorted(self.session.catalog.mvs)
        for name in names:
            got = sorted(self.mv_rows(name))
            want = sorted(control.mv_rows(name))
            assert got == want, (
                f"MV {name!r} diverged after {self.kills} kills:\n"
                f"  chaos:   {got[:10]}\n  control: {want[:10]}")
