"""Deterministic simulation / chaos harness.

Counterpart of the reference's madsim deterministic cluster
(reference: src/tests/simulation/src/cluster.rs:129-247 — the whole
cluster in one process under a seeded scheduler, with ``--kill`` randomly
restarting nodes mid-workload; recovery tests
tests/integration_tests/recovery/). Scaled to this build's architecture:
the "cluster" is a durable Session; a *kill* abandons it without any
graceful shutdown and recovers a fresh Session from the same data dir
(crash recovery path), at epochs chosen by a seeded RNG.

Client semantics are honest: DML acknowledged only at FLUSH; statements
not yet flushed when a kill strikes are re-applied by the harness (client
retry), exactly how an at-least-once client driver behaves against the
reference. The end-state cross-check compares every MV against a control
session that never crashed — and, since ISSUE 9, every readable SINK's
delivered output (the surface the ConsistencyAuditor checks), so chaos
entries catch sink dupes/loss, not just MV divergence.

Two DETERMINISTIC modes ride on the network fault plane (rpc/faults.py):

* **named netsplit scenarios** (``run_netsplit``) — seeded
  ``ChaosSchedule``s over a live cluster: partition one exchange edge of
  a spanning 2-worker q5 graph for a window of epochs mid-stream, delay
  acks past the permit budget, duplicate+reorder exchange frames,
  duplicate a batch_task reply. Each run ends in a ConsistencyAuditor
  pass against a no-chaos control and returns its per-link injection
  trace; replaying the same seed reproduces the identical trace.
* **crash-point sweep** (``crash_point_sweep``) — iterate every
  registered failpoint site (common/failpoint.py KNOWN_SITES, including
  both 2PC checkpoint phases), kill the cluster the moment the site
  fires, recover, and audit — FoundationDB-style "die at every
  interesting instruction" coverage.
"""

from __future__ import annotations

import json
import os
import random
from typing import Dict, List, Optional

from .frontend.session import Session
from .rpc.faults import CHAOS_ENV, ChaosRule, ChaosSchedule, install, plane


class CrashPoint(BaseException):
    """Raised by an armed failpoint to simulate process death AT that
    site: BaseException so no intermediate ``except Exception`` recovery
    layer can absorb it — the only handler is the sweep's kill path."""


class SimCluster:
    def __init__(self, data_dir: str, seed: int = 0, kill_rate: float = 0.3,
                 checkpoint_frequency: int = 2, workers: int = 0,
                 transient_fault_rate: float = 0.0,
                 broker=None, broker_restart_rate: float = 0.0,
                 chaos: Optional[ChaosSchedule] = None,
                 **session_kw):
        """``workers`` > 0 runs MV jobs on worker PROCESSES and arms
        per-component kills: the chaos step randomly SIGKILLs one worker
        (scoped heartbeat-TTL recovery) instead of always restarting the
        whole cluster — the madsim individual-node kill
        (reference: cluster.rs:498-510).

        ``transient_fault_rate`` > 0 arms SEEDED transient object-store
        faults for the whole workload (every durable-tier IO may fail and
        be retried — storage/object_store.py FaultInjectingObjectStore
        under the retry layer), proving the exactly-once machinery holds
        under flaky IO, not just clean kills. ``broker`` (a BrokerServer
        with a durable data_dir) + ``broker_restart_rate`` add broker
        restarts to the chaos menu: readers/sinks must survive via the
        reconnecting BrokerClient."""
        self.data_dir = data_dir
        self.rng = random.Random(seed)
        self.kill_rate = kill_rate
        self.session_kw = dict(session_kw,
                               checkpoint_frequency=checkpoint_frequency)
        if workers:
            self.session_kw["workers"] = workers
        if transient_fault_rate > 0.0 and \
                "fault_config" not in self.session_kw:
            from .common.config import FaultConfig
            self.session_kw["fault_config"] = FaultConfig(
                inject_object_store_transient_rate=transient_fault_rate,
                inject_object_store_seed=self.rng.randrange(1 << 30),
                # faults at rate p need attempts n with p**n ≈ 0:
                # 8 attempts at p=0.2 leaves ~3e-6 per op
                io_retry_attempts=8, io_retry_base_ms=1.0,
                io_retry_max_ms=20.0)
        self.broker = broker
        self.broker_restart_rate = broker_restart_rate
        self.broker_restarts = 0
        # network fault plane: install the schedule in THIS process and
        # export it so worker subprocesses (including recovery respawns)
        # adopt it at bring-up; injection traces persist under data_dir
        # so a killed process's trace survives for replay comparison
        self.chaos = chaos
        self._chaos_env_set = False
        if chaos is not None:
            os.environ[CHAOS_ENV] = chaos.to_json()
            self._chaos_env_set = True
            install(chaos, trace_path=os.path.join(
                data_dir, "chaos_trace_session.jsonl"))
        self.session = Session(data_dir=data_dir, **self.session_kw)
        self.kills = 0
        self.worker_kills = 0
        self.spanning_kills = 0
        self._unacked: List[str] = []     # DML since the last FLUSH

    # -- client API -----------------------------------------------------------

    def run_sql(self, sql: str) -> list:
        out = self.session.run_sql(sql)
        s = sql.lstrip().lower()
        if s.startswith("insert"):
            self._unacked.append(sql)
        elif s.startswith("flush"):
            self._unacked.clear()
        return out

    def flush(self) -> None:
        self.session.flush()
        self._unacked.clear()

    def tick(self) -> None:
        self.session.tick()

    def mv_rows(self, name: str) -> list:
        return self.session.mv_rows(name)

    # -- chaos ----------------------------------------------------------------

    def maybe_kill(self) -> bool:
        # broker restarts draw independently: a flaky broker AND a
        # crashing cluster may strike in the same step
        if (self.broker is not None and self.broker_restart_rate > 0
                and self.rng.random() < self.broker_restart_rate):
            self.restart_broker()
        if self.rng.random() >= self.kill_rate:
            return False
        if getattr(self.session, "workers", None) and \
                self.rng.random() < 0.5:
            # spanning fragment graphs get their own chaos entry: kill a
            # worker that hosts ONE fragment of a multi-worker graph
            # (scoped rebuild of that graph, every other job untouched)
            if getattr(self.session, "_spanning_specs", None) and \
                    self.rng.random() < 0.5:
                self.kill_spanning_worker()
            else:
                self.kill_worker()
        else:
            self.kill()
        return True

    def restart_broker(self) -> None:
        """Bounce the external broker on the SAME address (durable
        segments reload): in-flight client commands fail and must be
        absorbed by BrokerClient's reconnect-with-backoff."""
        from .connector.broker import BrokerServer
        old = self.broker
        host, port = old.host, old.port
        old.close()
        self.broker = BrokerServer(
            host=host, port=port, n_partitions=old.n_partitions,
            data_dir=old.data_dir).start()
        self.broker_restarts += 1

    def kill_worker(self) -> None:
        """SIGKILL one worker process (per-component failure): the
        session survives; the heartbeat TTL declares the worker's jobs
        dead and scoped recovery respawns it on subsequent ticks."""
        w = self.rng.choice(self.session.workers)
        w.kill9()
        self.worker_kills += 1
        for _ in range(12):               # TTL + respawn happen in-tick
            self.session.tick()
            if not w.dead:
                return
        raise AssertionError("killed worker was not recovered")

    def kill_spanning_worker(self) -> None:
        """SIGKILL one worker hosting a FRAGMENT of a spanning graph:
        surviving peers report PEER_LOST on their exchange edges, the
        TTL declares the job dead, and scoped recovery must rebuild ONLY
        the affected fragment graph (respawned worker + surviving
        fragments reloaded at the last commit) and converge — asserted
        here, cross-checked against the control session by the caller."""
        specs = self.session._spanning_specs
        name = self.rng.choice(sorted(specs))
        w = self.rng.choice(specs[name]["workers"])
        w.kill9()
        self.worker_kills += 1
        self.spanning_kills += 1
        for _ in range(16):               # TTL + scoped rebuild in-tick
            self.session.tick()
            job = self.session.jobs.get(name)
            if not w.dead and job is not None and job._failure is None:
                return
        raise AssertionError(
            f"spanning job {name!r} did not converge after a "
            "participant kill")

    def kill(self) -> None:
        """Abandon the session with no shutdown (uncommitted state and
        unacked DML are lost), then recover + re-apply unacked DML."""
        self.kills += 1
        # crash semantics: no job shutdown, no flush — but kill the old
        # worker PROCESSES (their parent is gone, like a machine reboot)
        # and close the abandoned private event loop so kills don't leak
        old = self.session
        for w in getattr(old, "workers", []) or []:
            try:
                w.kill9()
            except Exception:   # noqa: BLE001
                pass
        try:
            old.loop.close()
        except Exception:   # noqa: BLE001
            pass
        self.session = Session(data_dir=self.data_dir, **self.session_kw)
        for sql in self._unacked:
            self.session.run_sql(sql)

    # -- verification ---------------------------------------------------------

    def verify_against(self, control: Session,
                       mv_names: Optional[List[str]] = None) -> None:
        """Final-state cross-check after both sides flushed: every MV
        bit-equal AND every readable sink's DELIVERED output equal as a
        multiset (the surface the ConsistencyAuditor checks — a chaos
        run that re-delivered or lost sink rows fails here even when
        the MVs converged)."""
        from .common.audit import fold_changelog, sink_delivered_rows
        self.flush()
        control.flush()
        names = mv_names or sorted(self.session.catalog.mvs)
        for name in names:
            got = sorted(self.mv_rows(name))
            want = sorted(control.mv_rows(name))
            assert got == want, (
                f"MV {name!r} diverged after {self.kills} kills:\n"
                f"  chaos:   {got[:10]}\n  control: {want[:10]}")
        for name in sorted(set(self.session.catalog.sinks)
                           & set(control.catalog.sinks)):
            got_s = sink_delivered_rows(self.session, name)
            want_s = sink_delivered_rows(control, name)
            if got_s is None or want_s is None:
                continue               # backend not readable: skip
            assert fold_changelog(got_s) == fold_changelog(want_s), (
                f"sink {name!r} delivery diverged after {self.kills} "
                f"kills: {len(got_s)} rows delivered vs {len(want_s)} "
                "expected (dupes or loss in the folded changelog)")

    def close(self) -> None:
        """Tear down the cluster and clear the exported chaos schedule
        (so later sessions in this process spawn clean workers)."""
        if self._chaos_env_set:
            os.environ.pop(CHAOS_ENV, None)
            self._chaos_env_set = False
            install(None)
        try:
            self.session.close()
        except Exception:   # noqa: BLE001 - best-effort teardown
            pass


# ---------------------------------------------------------------------------
# Named netsplit scenarios (deterministic network-fault runs)
# ---------------------------------------------------------------------------

_BID_DDL = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, "
            "price BIGINT, channel VARCHAR, url VARCHAR, "
            "date_time TIMESTAMP, extra VARCHAR) "
            "WITH (connector = 'nexmark', nexmark_table = 'bid')")

_Q5 = """CREATE MATERIALIZED VIEW q5 AS
    SELECT AuctionBids.auction, AuctionBids.num FROM (
        SELECT bid.auction, count(*) AS num, window_start AS starttime
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY window_start, bid.auction
    ) AS AuctionBids
    JOIN (
        SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
        FROM (
            SELECT count(*) AS num, window_start AS starttime_c
            FROM HOP(bid, date_time, INTERVAL '2' SECOND,
                     INTERVAL '10' SECOND)
            GROUP BY bid.auction, window_start
        ) AS CountBids
        GROUP BY CountBids.starttime_c
    ) AS MaxBids
    ON AuctionBids.starttime = MaxBids.starttime_c
       AND AuctionBids.num = MaxBids.maxn"""

_AGG = ("CREATE MATERIALIZED VIEW q AS SELECT auction, count(*) AS n, "
        "max(price) AS mx FROM bid GROUP BY auction")

#: named scenarios: mv SQL, which schedule to arm, and whether the
#: injection is expected to force a scoped recovery (partition) or be
#: absorbed transparently by the hardening (dedup/reorder/keepalive)
NETSPLIT_SCENARIOS: Dict[str, dict] = {
    # partition ONE exchange edge of the spanning 2-worker q5 graph for
    # 3 epochs mid-stream: barrier collection on the starved consumer
    # trips the epoch deadline, scoped recovery rebuilds the graph from
    # per-worker durable state, sources replay, and the MV converges
    # bit-exact with a no-chaos control (the ISSUE 9 acceptance run)
    "q5_exchange_partition": {
        "sql": _Q5, "mv": "q5", "expect_recovery": True,
        "rules": lambda e0: [ChaosRule(
            kind="partition", link="w0->w1", types=["exg_data"],
            epochs=[e0, e0 + 3])],
    },
    # duplicate + reorder exchange frames on the w0<->w1 edges: the
    # per-channel seq layer dedups and re-sequences, so the run needs NO
    # recovery and stays bit-exact (exactly-once from at-least-once)
    "exchange_dup_reorder": {
        "sql": _AGG, "mv": "q", "expect_recovery": False,
        "rules": lambda e0: [
            ChaosRule(kind="duplicate", link="w0<->w1",
                      types=["exg_data"], prob=0.3),
            ChaosRule(kind="delay", link="w0<->w1",
                      types=["exg_data:chunk"], prob=0.25,
                      delay_frames=2),
        ],
    },
    # delay consumption acks on the exchange edges: producers stall on
    # permits (permits_waited grows) but nothing is lost — backpressure
    # is the correct, convergent behavior
    "ack_delay": {
        "sql": _AGG, "mv": "q", "expect_recovery": False,
        "rules": lambda e0: [ChaosRule(
            kind="delay", link="w0<->w1", types=["exg_ack"],
            delay_ms=30.0)],
    },
    # duplicate every worker→session reply frame: request/reply rid
    # dedup keeps batch_task / scan results exactly-once at the caller.
    # The query runs the serving plane's TWO-PHASE path over the
    # sharded-root spanning MV, so real batch_task replies (one per
    # slice-holding worker) cross the faulty link and get duplicated.
    "dup_batch_reply": {
        "sql": _AGG, "mv": "q", "expect_recovery": False,
        "query": "SELECT auction, count(*) AS c FROM q GROUP BY auction",
        "rules": lambda e0: [ChaosRule(
            kind="duplicate", link="w*->s", types=["reply"])],
    },
}


def netsplit_schedule(name: str, seed: int,
                      base_ticks: int = 2) -> ChaosSchedule:
    """Build the seeded schedule for one named scenario. The fault
    window is expressed in ABSOLUTE epochs: the setup below (DDL, then
    ``base_ticks`` lockstep ticks, then FLUSH) lands the cluster at
    epoch ``base_ticks + 2``, so the window opens on the next epoch —
    mid-stream, after a committed checkpoint cut."""
    spec = NETSPLIT_SCENARIOS[name]
    e0 = base_ticks + 3
    return ChaosSchedule(seed, spec["rules"](e0), name=name)


def _collect_trace(data_dir: str) -> Dict[str, list]:
    """Collect every persisted injection trace under ``data_dir``
    (chaos_trace.jsonl per worker incarnation, chaos_trace_session.jsonl
    for the session process), grouped per stream. Each plane install
    wrote an incarnation marker; events carry their incarnation index so
    two incarnations of the same stream (per-stream seqs restart at 0
    after a respawn) never collapse into one event. Per-stream
    per-incarnation event lists are the deterministic replay unit."""
    events: List[tuple] = []
    for root, _dirs, files in os.walk(data_dir):
        for f in sorted(files):
            if not (f.startswith("chaos_trace") and f.endswith(".jsonl")):
                continue
            inc = -1
            with open(os.path.join(root, f), encoding="utf-8") as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    ev = json.loads(line)
                    if ev.get("marker") == "install":
                        inc += 1
                        continue
                    events.append((ev["link"], max(inc, 0), ev["seq"],
                                   ev["kind"], ev["type"], ev["rule"]))
    if not events:
        # no persisted files (plane installed without a trace_path):
        # fall back to the in-memory trace, one incarnation
        events = [(ev["link"], 0, ev["seq"], ev["kind"], ev["type"],
                   ev["rule"]) for ev in plane().trace]
    by_link: Dict[str, set] = {}
    for link, inc, seq, kind, ftype, rule in events:
        by_link.setdefault(link, set()).add((inc, seq, kind, ftype,
                                             rule))
    return {k: sorted(v) for k, v in by_link.items()}


def run_netsplit(name: str, seed: int = 7, data_dir: Optional[str] = None,
                 base_ticks: int = 2, post_ticks: int = 2,
                 chunk_capacity: int = 64,
                 session_kw: Optional[dict] = None) -> dict:
    """Run one named netsplit scenario end to end and machine-check the
    result: build a 2-worker cluster with the seeded schedule installed,
    run the scenario's MV as a spanning graph, let the injection strike
    (riding out a scoped recovery when the scenario forces one), then
    audit against a no-chaos single-process control. Returns a report
    with the per-link injection trace — re-running the same (name, seed)
    reproduces it identically."""
    import tempfile

    from .common.audit import ConsistencyAuditor
    from .common.config import FaultConfig
    from .frontend.build import BuildConfig

    spec = NETSPLIT_SCENARIOS[name]
    data_dir = data_dir or tempfile.mkdtemp(prefix="rwtpu_netsplit_")
    schedule = netsplit_schedule(name, seed, base_ticks)
    # short deadlines: a partitioned edge must trip the epoch deadline
    # in seconds, not the production 300s. NOT too short though: the
    # first data epoch of a fresh worker process pays XLA compilation,
    # and a deadline under that cost reads as a dead worker and spins
    # recovery forever (found by this very harness) — the shared
    # compilation cache below keeps RESPAWNED workers fast
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(data_dir, "jax_cache"))
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    # keepalive probing stays OFF here: detection rides the epoch
    # deadline (the probe's own regression test sets up a controlled
    # idle link instead — under q5's compute-bound epochs an aggressive
    # prober would race the busy event loop)
    fc = FaultConfig(worker_epoch_timeout_s=15.0,
                     worker_request_timeout_s=60.0,
                     exchange_keepalive_s=0.0)
    sim = SimCluster(data_dir, seed=seed, kill_rate=0.0, workers=2,
                     chaos=schedule, source_chunk_capacity=chunk_capacity,
                     checkpoint_frequency=2, fault_config=fc,
                     config=BuildConfig(fragment_parallelism=2),
                     **(session_kw or {}))
    control = Session(seed=42, source_chunk_capacity=chunk_capacity,
                      checkpoint_frequency=2)
    mv = spec["mv"]
    try:
        for sess in (sim.session, control):
            sess.run_sql(_BID_DDL)
            sess.run_sql(spec["sql"])
        assert mv in sim.session._spanning_specs, \
            f"{mv} did not deploy as a 2-worker spanning graph"
        for _ in range(base_ticks):
            sim.tick()
            control.tick()
        sim.flush()                    # committed cut before the window
        control.flush()
        recovered = False
        if spec["expect_recovery"]:
            # the window opens on the next epoch: tick the chaos side
            # alone until the starved graph died AND scoped recovery
            # rebuilt it (dead-window ticks feed the job nothing, and
            # the wedged epoch's uncommitted generate replays from the
            # committed offsets — so the control is NOT ticked here)
            for _ in range(40):
                sim.tick()
                s = sim.session
                job = s.jobs.get(mv)
                healthy = (job is not None and job._failure is None
                           and mv not in s._dead_jobs
                           and not any(w.dead for w in s.workers))
                if recovered and healthy:
                    break
                if not healthy:
                    recovered = True   # strike observed; await rebuild
            else:
                raise AssertionError(
                    f"netsplit {name!r} never recovered")
            assert recovered, f"netsplit {name!r} never struck"
        for _ in range(post_ticks):
            sim.tick()
            control.tick()
        # read MVs through the chaos side BEFORE auditing so a remote
        # scan path exercises the (possibly still chaotic) reply links
        _ = sim.mv_rows(mv)
        query_ok = None
        if spec.get("query"):
            # batch query through the chaos side's serving plane (two-
            # phase batch_task frames over the faulty links) must equal
            # the control's answer EXACTLY ONCE — a duplicated reply
            # that slipped rid-dedup would double rows here
            got_q = sorted(sim.session.run_sql(spec["query"]))
            want_q = sorted(control.run_sql(spec["query"]))
            assert got_q == want_q, (
                f"query diverged under chaos: {got_q[:5]} vs "
                f"{want_q[:5]}")
            query_ok = True
        sim.verify_against(control, [mv])
        report = ConsistencyAuditor(sim.session).audit(control=control)
        report.assert_ok()
        metrics = sim.session.metrics()
        out = {
            "scenario": name, "seed": seed,
            "schedule": schedule.to_json(),
            "recovered": recovered,
            "rows": len(sim.mv_rows(mv)),
            "query_ok": query_ok,
            "chaos": metrics["chaos"],
            "audit": {k: v.get("ok") for k, v in report.checks.items()},
        }
    finally:
        sim.close()
        control.close()
    out["trace"] = _collect_trace(data_dir)
    return out


# ---------------------------------------------------------------------------
# Traffic-spike scenario (elastic scaling plane, docs/scaling.md)
# ---------------------------------------------------------------------------

def run_traffic_spike(seed: int = 7, data_dir: Optional[str] = None,
                      workers: int = 4, warmup_ticks: int = 2,
                      spike_rate: int = 8, settle_ticks: int = 6,
                      chunk_capacity: int = 32) -> dict:
    """The scaling plane's acceptance scenario: a spanning grouped-agg
    job runs at parallelism 2 on a ``workers``-process cluster with the
    autoscaler armed; a seeded traffic spike (source rate jumps to
    ``spike_rate`` chunks/tick over a tiny exchange permit budget)
    drives permits_waited up, the autoscaler scales the job out 2→4 via
    LIVE vnode migration (only the changed ranges move — asserted from
    the migration metrics), and when the load subsides the policy's
    cooldown + scale-in laziness keep it from flapping. The end state is
    cross-checked bit-exact against a no-spike-plumbing control and the
    ConsistencyAuditor must come back green."""
    import tempfile

    from .common.audit import ConsistencyAuditor
    from .common.config import AutoscalerConfig, FaultConfig
    from .frontend.build import BuildConfig

    data_dir = data_dir or tempfile.mkdtemp(prefix="rwtpu_spike_")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(data_dir, "jax_cache"))
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    acfg = AutoscalerConfig(
        enabled=True, high_permits_waited=1, hysteresis=2, cooldown=8,
        scale_in_after=64, max_parallelism=min(4, workers))
    fc = FaultConfig(worker_epoch_timeout_s=60.0,
                     worker_request_timeout_s=120.0,
                     exchange_keepalive_s=0.0)
    sim = SimCluster(data_dir, seed=seed, kill_rate=0.0, workers=workers,
                     source_chunk_capacity=chunk_capacity,
                     checkpoint_frequency=2, fault_config=fc,
                     config=BuildConfig(fragment_parallelism=2,
                                        exchange_permits=2),
                     autoscaler_config=acfg)
    control = Session(seed=42, source_chunk_capacity=chunk_capacity,
                      checkpoint_frequency=2)
    mv = "q"
    try:
        for sess in (sim.session, control):
            sess.run_sql(_BID_DDL)
            sess.run_sql(_AGG)
        assert mv in sim.session._spanning_specs, \
            f"{mv} did not deploy as a spanning graph"
        spec = sim.session._spanning_specs[mv]
        assert max(len(a) for a in
                   spec["placement"].actors.values()) == 2

        def par() -> int:
            return max(len(a) for a in spec["placement"].actors.values())

        for _ in range(warmup_ticks):
            sim.tick()
            control.tick()
        # SPIKE: raise the source rate on chaos side AND control — the
        # control consumes the same rows without the scaling plumbing
        sim.session.set_source_rate(spike_rate)
        control.chunks_per_tick = spike_rate
        spike_ticks = 0
        for _ in range(24):
            sim.tick()
            control.tick()
            spike_ticks += 1
            if par() == acfg.max_parallelism:
                break
        assert par() == acfg.max_parallelism, (
            f"autoscaler never scaled out (parallelism {par()}, "
            f"status {sim.session.autoscaler.status()})")
        decisions_at_peak = len(sim.session.autoscaler.decisions)
        last = sim.session._rescale_stats["last"]
        moved = last["moved_vnodes"]
        from .common.hashing import VNODE_COUNT
        # only the CHANGED ranges moved: one sharded fragment halves its
        # per-actor ranges, so exactly half the ring changes owner
        assert moved == VNODE_COUNT // 2, (
            f"expected {VNODE_COUNT // 2} moved vnodes, got {moved}: "
            f"{last['moved_ranges']}")
        # SUBSIDE: load returns to 1 chunk/tick; cooldown + scale-in
        # laziness must keep the topology steady (no flapping)
        sim.session.set_source_rate(1)
        control.chunks_per_tick = 1
        for _ in range(settle_ticks):
            sim.tick()
            control.tick()
        assert par() == acfg.max_parallelism, "autoscaler flapped"
        assert len(sim.session.autoscaler.decisions) == \
            decisions_at_peak, "autoscaler flapped after load subsided"
        sim.verify_against(control, [mv])
        report = ConsistencyAuditor(sim.session).audit(control=control)
        report.assert_ok()
        metrics = sim.session.metrics()
        return {
            "scenario": "traffic_spike", "seed": seed,
            "parallelism": par(), "moved_vnodes": moved,
            "pause_ms": last["pause_ms"],
            "spike_ticks": spike_ticks,
            "decisions": list(sim.session.autoscaler.decisions),
            "rows": len(sim.mv_rows(mv)),
            "audit": {k: v.get("ok") for k, v in report.checks.items()},
        }
    finally:
        sim.close()
        control.close()


# ---------------------------------------------------------------------------
# Crash-point sweep (die at every registered failpoint, audit after each)
# ---------------------------------------------------------------------------

def _sweep_tax(v):
    """Module-level so the UDF plane ships it to the server BY REFERENCE
    (udf/registry.py) — the sweep's UDF workload step."""
    return v * 2 + 1


def _chaos_tax(v):
    """Module-level → ships to the UDF server by reference (the chaos
    scenario's and the soak's workload UDF)."""
    return v * 3 + 7


def _ensure_udf(name: str, fn) -> None:
    """Register a harness UDF once per process (INT64 → INT64)."""
    from .expr.expr import _REGISTRY
    if name not in _REGISTRY:
        from .common.types import INT64
        from .expr.udf import register_udf
        register_udf(name, fn, [INT64], INT64)


def _sweep_workload_stmts(sink_path: str) -> List[tuple]:
    """(sql, kind) steps: DDL first, then interleaved DML/FLUSH with a
    mid-stream CREATE (so meta-store txns fire mid-workload too) and a
    UDF-evaluating SELECT (so the udf.* client failpoint sites fire
    mid-workload — ISSUE 15)."""
    steps: List[tuple] = [
        ("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)", "ddl:t"),
        ("CREATE MATERIALIZED VIEW m AS SELECT sum(v) AS n FROM t",
         "ddl:m"),
        (f"CREATE SINK snk FROM m WITH (connector = 'file', "
         f"path = '{sink_path}')", "ddl:snk"),
    ]
    for i in range(1, 9):
        steps.append((f"INSERT INTO t VALUES ({i}, {10 * i})", "dml"))
        if i % 2 == 0:
            steps.append(("FLUSH", "flush"))
        if i == 4:
            steps.append(
                ("CREATE MATERIALIZED VIEW m2 AS "
                 "SELECT count(*) AS c FROM t", "ddl:m2"))
        if i == 6:
            steps.append(("SELECT k, sweep_tax(v) FROM t", "query"))
    steps.append(("FLUSH", "flush"))
    return steps


def _exists(session: Session, kind: str) -> bool:
    name = kind.split(":", 1)[1]
    cat = session.catalog
    return (name in cat.tables or name in cat.mvs or name in cat.sinks)


def crash_point_sweep(base_dir: str, sites: Optional[List[str]] = None,
                      seed: int = 0,
                      audit: bool = True) -> Dict[str, dict]:
    """FoundationDB-style sweep: for EVERY registered failpoint site run
    the same durable workload, crash the cluster the moment the site
    fires (``CrashPoint`` is a BaseException no recovery layer can
    absorb), recover, finish the workload, and let the
    ``ConsistencyAuditor`` assert exactly-once sinks / MV parity /
    monotone barriers / pin leak-freedom against an unharmed control.
    Sites the workload never executes are reported ``not_hit`` honestly.
    Worker-resident sites (the 2PC prepare/commit phases of a SPANNING
    graph) are exercised by ``crash_point_sweep_spanning``."""
    from .common.audit import ConsistencyAuditor
    from .common.failpoint import arm, disarm, registered_sites

    _ensure_udf("sweep_tax", _sweep_tax)
    sites = sites if sites is not None else registered_sites()
    results: Dict[str, dict] = {}
    for i, site in enumerate(sites):
        tier = ("hummock" if site.startswith(("hummock.", "compactor."))
                else "segment")
        d = os.path.join(base_dir, f"site_{i:02d}")
        sink_chaos = os.path.join(d, "sink_chaos.jsonl")
        sink_ctl = os.path.join(d, "sink_ctl.jsonl")
        steps = _sweep_workload_stmts(sink_chaos)
        control = Session(data_dir=os.path.join(d, "ctl"), seed=seed,
                          checkpoint_frequency=2, state_store=tier)
        sim = SimCluster(os.path.join(d, "chaos"), seed=seed,
                         kill_rate=0.0, checkpoint_frequency=2,
                         state_store=tier)
        hit = [False]

        def _trip(_site=site, _hit=hit):
            _hit[0] = True
            raise CrashPoint(_site)

        try:
            # control first, UNARMED: the failpoint registry is
            # process-global, so arming before the control ran would
            # crash the control too
            for sql, _kind in steps:
                control.run_sql(sql.replace(sink_chaos, sink_ctl))
            control.flush()
            if site.startswith("udf."):
                # the UDF server is process-global and the control's
                # SELECT just spawned it — tear it down so the ARMED
                # run exercises udf.spawn (and the others) itself
                from .udf.client import udf_plane
                udf_plane().shutdown_server()
            for sql, kind in steps:
                if kind == "ddl:snk":
                    # arm AFTER setup DDL: the sweep's subject is the
                    # running cluster, not bootstrap
                    arm(site, _trip, once=True)
                try:
                    sim.run_sql(sql)
                except BaseException:
                    # CrashPoint propagates directly from IO-path sites;
                    # a site inside a stream actor surfaces as the job's
                    # failure (RuntimeError) instead — either way, if
                    # the armed site JUST fired this IS the simulated
                    # crash. Errors before the site fired, or after its
                    # one crash was already taken, are real bugs.
                    if not hit[0] or _ARMED_SWEEP_KILLED.get(site):
                        raise
                    _ARMED_SWEEP_KILLED[site] = True
                    sim.kill()         # die AT the site; recover; retry
                    if kind.startswith("ddl") \
                            and not _exists(sim.session, kind):
                        sim.run_sql(sql)   # client retries a lost DDL
                if hit[0] and not _ARMED_SWEEP_KILLED.get(site):
                    # the site fired on a BACKGROUND thread (inline
                    # compaction): the thread died, the main path did
                    # not — still crash the cluster at this moment
                    _ARMED_SWEEP_KILLED[site] = True
                    sim.kill()
            try:
                sim.flush()
            except BaseException:       # armed-once site fired at the
                if not hit[0] or _ARMED_SWEEP_KILLED.get(site):
                    raise               # closing flush: die there too,
                _ARMED_SWEEP_KILLED[site] = True
                sim.kill()              # recover, and flush clean
                sim.flush()
            status: dict = {"hit": hit[0], "kills": sim.kills}
            sim.verify_against(control)
            if audit:
                report = ConsistencyAuditor(sim.session).audit(
                    control=control)
                report.assert_ok()
                status["audit"] = "ok"
            results[site] = status
        finally:
            disarm(site)
            _ARMED_SWEEP_KILLED.pop(site, None)
            sim.close()
            control.close()
    return results


_ARMED_SWEEP_KILLED: Dict[str, bool] = {}


def crash_point_sweep_spanning(base_dir: str, seed: int = 3,
                               sites: Optional[List[str]] = None
                               ) -> Dict[str, dict]:
    """The 2PC checkpoint phases fire inside WORKER processes of a
    spanning graph. For each phase site, arm a REAL process exit at the
    site via the RWTPU_FAILPOINTS env (the worker dies with ``os._exit``
    the first time it reaches the site — a marker file keeps the
    respawned worker from dying forever), then prove the heartbeat-TTL
    scoped recovery converges and the auditor passes against a no-chaos
    control."""
    from .common.audit import ConsistencyAuditor
    from .common.config import FaultConfig
    from .frontend.build import BuildConfig

    # checkpoint.prepare = phase 1 (durable staging before the ack);
    # checkpoint.settle = phase 2 (the commit frame promoting the
    # staged epoch) — settle, not append, is the prepared-epoch path
    sites = sites or ["checkpoint.prepare", "checkpoint.settle"]
    results: Dict[str, dict] = {}
    for i, site in enumerate(sites):
        d = os.path.join(base_dir, f"span_{i:02d}")
        os.makedirs(d, exist_ok=True)
        marker = os.path.join(d, "died_once.marker")
        # ONE deterministic victim (worker 1): phase-2 commit frames
        # broadcast to every participant, and an unscoped exit would
        # race over how many workers die
        os.environ["RWTPU_FAILPOINTS"] = json.dumps(
            {site: {"action": "exit", "once_marker": marker,
                    "worker": 1}})
        # shared compile cache + generous deadline: a respawned worker's
        # first epoch pays XLA compilation (see run_netsplit)
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                              os.path.join(base_dir, "jax_cache"))
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
        fc = FaultConfig(worker_epoch_timeout_s=15.0,
                         worker_request_timeout_s=60.0,
                         exchange_keepalive_s=0.0)
        # checkpoint ONLY at the explicit flush below, so the armed 2PC
        # site fires at a known point in the lockstep schedule (an
        # early-tick checkpoint would kill the worker mid-warmup and
        # desynchronize the control's generate accounting)
        sim = SimCluster(os.path.join(d, "chaos"), seed=seed,
                         kill_rate=0.0, workers=2,
                         source_chunk_capacity=64,
                         checkpoint_frequency=1000, fault_config=fc,
                         config=BuildConfig(fragment_parallelism=2))
        control = Session(seed=42, source_chunk_capacity=64,
                          checkpoint_frequency=1000)
        try:
            for sess in (sim.session, control):
                sess.run_sql(_BID_DDL)
                sess.run_sql(_AGG)
            assert "q" in sim.session._spanning_specs
            for _ in range(2):
                sim.tick()
            # the flush's checkpoint reaches the armed site in worker 1:
            # it EXITS there; the TTL + scoped recovery rebuild the
            # graph from the DECIDED cut. The two phases differ — that
            # is the contract under test:
            #   prepare-death: the victim never acked, so the epoch was
            #     never decided; every participant's prepared state is
            #     DISCARDED and the pre-flush ticks replay from zero
            #     (nothing earlier committed in this schedule);
            #   commit-death: every participant prepared + acked, so
            #     the epoch was decided; the victim's prepared state
            #     ROLLS FORWARD at recovery and the pre-flush ticks
            #     survive the crash.
            sim.flush()
            died = os.path.exists(marker)
            for _ in range(40):
                job = sim.session.jobs.get("q")
                if not any(w.dead for w in sim.session.workers) \
                        and job is not None and job._failure is None \
                        and "q" not in sim.session._dead_jobs \
                        and died:
                    break
                sim.tick()
                died = died or os.path.exists(marker)
            assert died, f"no worker reached site {site!r}"
            for _ in range(2):
                sim.tick()
            # effective generate ticks the chaos side's MV reflects:
            # post-recovery ticks, plus the rolled-forward pre-flush
            # ticks iff the decided epoch survived the crash
            pre_survived = 2 if site == "checkpoint.settle" else 0
            for _ in range(pre_survived + 2):
                control.tick()
            sim.verify_against(control, ["q"])
            report = ConsistencyAuditor(sim.session).audit(
                control=control)
            report.assert_ok()
            results[site] = {"hit": True, "audit": "ok",
                             "worker_kills": 1,
                             "rolled_forward": bool(pre_survived)}
        finally:
            os.environ.pop("RWTPU_FAILPOINTS", None)
            sim.close()
            control.close()
    return results


# ---------------------------------------------------------------------------
# UDF-plane chaos + soak (ISSUE 15 — the udf link joins the fault estate)
# ---------------------------------------------------------------------------

def udf_chaos_schedule(seed: int) -> ChaosSchedule:
    """Seeded faults on the UDF link: dropped call frames (the client's
    deadline trips → kill + seeded respawn + batch replay), delayed
    calls, and duplicated replies (the (gen, rid) fence drops the
    extras). Registration frames are deliberately NOT dropped (types
    filter) so a respawn's replay always lands — the drop rule models a
    flaky data path, the respawn protocol is what absorbs it. The drop
    rule is COUNT-capped below the retry budget (the same discipline
    the netsplit scenarios apply with bounded windows): per-seq seeded
    draws can otherwise align with the retry cadence (register/retry
    alternate seqs) and starve ANY bounded retry ladder — a statement
    about the schedule, not the plane."""
    return ChaosSchedule(seed, [
        ChaosRule(kind="drop", link="s->udf", types=["udf_call"],
                  prob=0.3, count=3),
        ChaosRule(kind="delay", link="s->udf", types=["udf_call"],
                  prob=0.3, delay_ms=5.0),
        ChaosRule(kind="duplicate", link="udf->s", prob=0.25),
    ], name="udf_link_chaos")


_UDF_T_DDL = "CREATE TABLE ut (k BIGINT PRIMARY KEY, v BIGINT)"
_UDF_MV = ("CREATE MATERIALIZED VIEW mu AS "
           "SELECT k, chaos_tax(v) AS tv FROM ut")
_COSCHED_MV = ("CREATE MATERIALIZED VIEW cq AS "
               "SELECT auction, count(*) AS n FROM bid GROUP BY auction")


def run_udf_chaos(seed: int = 11, data_dir: Optional[str] = None,
                  ticks: int = 6, kill_at: int = 3,
                  pipeline_depth: int = 1,
                  coschedule: bool = False) -> dict:
    """The UDF link's netsplit-style scenario: run a UDF-projecting MV
    (plus, optionally, a co-scheduled fused MV under the pipelined tick
    plane — ``pipeline_depth=2`` + ``coschedule=True`` is the ISSUE 15
    acceptance composition) under a seeded udf-link ChaosSchedule, with
    the server SIGKILLed mid-run, then audit bit-exact against a
    no-chaos control and return the per-link injection trace — the same
    (seed, workload) reproduces it identically.

    Unlike the exchange netsplits, chaos and control run SEQUENTIALLY:
    the UDF plane is process-global, so a lockstep control would share
    the faulty link."""
    import tempfile

    from .common.audit import ConsistencyAuditor
    from .common.config import UdfConfig
    from .frontend.build import BuildConfig
    from .udf.client import udf_plane

    _ensure_udf("chaos_tax", _chaos_tax)
    data_dir = data_dir or tempfile.mkdtemp(prefix="rwtpu_udfchaos_")
    plane_cfg = UdfConfig(call_timeout_s=2.0, max_retries=4,
                          spawn_timeout_s=30.0)
    udf_plane().configure(plane_cfg, trace_dir=data_dir)
    udf_plane().shutdown_server()     # fresh incarnation under chaos
    base_stats = dict(udf_plane().snapshot())
    session_kw: dict = {"pipeline_depth": pipeline_depth}
    if coschedule:
        session_kw["config"] = BuildConfig(coschedule=True)

    def workload(run_sql, tick, kill=None):
        run_sql(_UDF_T_DDL)
        if coschedule:
            run_sql(_BID_DDL)
            run_sql(_COSCHED_MV)
        run_sql(_UDF_MV)
        for i in range(ticks):
            run_sql(f"INSERT INTO ut VALUES ({i + 1}, {100 * (i + 1)})")
            if kill is not None and i == kill_at:
                kill()          # SIGKILL the server; next batch respawns
            tick()
        run_sql("SELECT k, chaos_tax(v) FROM ut")

    schedule = udf_chaos_schedule(seed)
    sim = SimCluster(data_dir, seed=seed, kill_rate=0.0,
                     chaos=schedule, checkpoint_frequency=2,
                     **session_kw)
    try:
        workload(sim.run_sql, sim.tick, kill=udf_plane().kill_server)
        sim.flush()
        trace = {k: v for k, v in _collect_trace(data_dir).items()
                 if k.split("#")[0] in ("s->udf", "udf->s")}
        injections = dict(plane().injections)
        cosched_groups = len(
            sim.session.metrics().get("coschedule") or {})
        # the chaos phase's plane deltas are final HERE — the control
        # phase below must not fold into them
        stats = udf_plane().snapshot()
        # chaos OFF for the control phase: clear the client plane AND
        # retire the chaos-era server — it installed the schedule from
        # RWTPU_CHAOS at spawn, so keeping it would duplicate the
        # control's replies (the control would not actually be
        # chaos-free)
        install(None)
        os.environ.pop(CHAOS_ENV, None)
        sim._chaos_env_set = False
        udf_plane().shutdown_server()
        control = Session(checkpoint_frequency=2, **session_kw)
        try:
            workload(control.run_sql, control.tick)
            control.flush()
            mvs = ["mu"] + (["cq"] if coschedule else [])
            sim.verify_against(control, mvs)
            report = ConsistencyAuditor(sim.session).audit(
                control=control)
            report.assert_ok()
            return {
                "scenario": "udf_link_chaos", "seed": seed,
                "pipeline_depth": pipeline_depth,
                "coschedule": coschedule,
                "cosched_groups": cosched_groups,
                "respawns": stats["respawns"] - base_stats["respawns"],
                "spawns": stats["spawns"] - base_stats["spawns"],
                "timeouts": stats["timeouts"] - base_stats["timeouts"],
                "stale_replies_dropped":
                    stats["stale_replies_dropped"]
                    - base_stats["stale_replies_dropped"],
                "injections": injections,
                "trace": trace,
                "rows": len(sim.mv_rows("mu")),
                "audit": {k: v.get("ok")
                          for k, v in report.checks.items()},
            }
        finally:
            control.close()
    finally:
        sim.close()


def meta_chaos_schedule(seed: int) -> ChaosSchedule:
    """Seeded delays on the session→meta RPC link (meta/client.py
    META_LINK). Delay-only BY DESIGN: the meta protocol is sequential
    request/reply on one socket with no per-request ids, so the
    absorb-or-degrade contract under latency is "ticks slow down,
    nothing diverges" — frame drops/dups model a failed meta process,
    which is the kill -9 restart test's job
    (tests/test_meta_control_plane.py), not a frame-level fault."""
    from .meta.client import META_LINK
    return ChaosSchedule(seed, [
        ChaosRule(kind="delay", link=META_LINK, prob=0.4, delay_ms=3.0),
        # EVERY lease heartbeat delayed too (the lease.* frames ride
        # their own `meta#clease` chaos stream — meta/client.py): a slow
        # meta link slows renewals down but must NEVER expire a live
        # writer's lease, or latency alone would trigger failovers —
        # run_meta_chaos asserts the term never moved
        ChaosRule(kind="delay", link=META_LINK, types=["lease.renew"],
                  prob=1.0, delay_ms=2.0),
    ], name="meta_link_delay")


def run_meta_chaos(seed: int = 13, data_dir: Optional[str] = None,
                   ticks: int = 5) -> dict:
    """Meta-link latency scenario (docs/control-plane.md): a writer
    session attached to a STANDALONE MetaServer runs DDL + DML + ticks
    while every meta RPC frame is seeded-delayed; a serving session then
    attaches over the same slow link and must converge on the writer's
    catalog and data. Audited bit-exact against an in-process control
    (which never touches the faulty link). Returns the per-link
    injection trace — the same seed reproduces it identically."""
    import tempfile

    from .common.audit import ConsistencyAuditor
    from .meta.client import META_LINK
    from .meta.server import MetaServer

    data_dir = data_dir or tempfile.mkdtemp(prefix="rwtpu_metachaos_")
    install(meta_chaos_schedule(seed))
    meta = MetaServer(data_dir=os.path.join(data_dir, "meta"))
    addr = meta.start()
    writer = Session(data_dir=data_dir, meta_addr=addr,
                     state_store="hummock", checkpoint_frequency=2)
    control = Session(checkpoint_frequency=2)
    reader: Optional[Session] = None
    try:
        for s in (writer, control):
            s.run_sql("CREATE TABLE mt (k BIGINT, v BIGINT)")
            s.run_sql("CREATE MATERIALIZED VIEW mq AS SELECT k, "
                      "count(*) AS n, sum(v) AS s FROM mt GROUP BY k")
        for i in range(ticks):
            stmt = f"INSERT INTO mt VALUES ({i % 3}, {i * 10})"
            writer.run_sql(stmt)
            control.run_sql(stmt)
            writer.tick()
            control.tick()
        writer.flush()
        control.flush()
        # a reader attaching OVER the slow link still converges: its
        # catalog load + snapshot adoption are plain meta RPCs
        reader = Session(data_dir=data_dir, meta_addr=addr,
                         role="serving")
        got = sorted(reader.run_sql("SELECT * FROM mq"))
        want = sorted(control.run_sql("SELECT * FROM mq"))
        assert got == want, (
            f"reader diverged under meta-link delay: {got[:5]} vs "
            f"{want[:5]}")
        report = ConsistencyAuditor(writer).audit(control=control)
        report.assert_ok()
        # a slow meta link is NOT a dead writer: with every renewal
        # delayed (schedule rule 2) the lease must still be held at
        # term 1 with zero failovers — latency degrades tick rate, never
        # leadership (docs/control-plane.md "Election")
        lease = writer.meta.lease_info()
        assert lease.get("term") == 1 and not lease.get("failovers"), (
            f"slow meta link caused a spurious failover: {lease}")
        injections = dict(plane().injections)
        # replay compares ONLY the deterministic request stream (key
        # exactly META_LINK): the wall-clock-paced side streams —
        # lease heartbeats (#clease), subscription dials (#csub),
        # notification-driven pin reports (#cpins) — legitimately vary
        # run to run
        trace = {k: v for k, v in _collect_trace(data_dir).items()
                 if k == META_LINK}
        return {
            "scenario": "meta_link_delay", "seed": seed,
            "rows": len(got),
            "injections": injections,
            "meta_requests": writer.meta.stats["requests"],
            "lease_term": lease.get("term"),
            "failovers": lease.get("failovers", 0),
            "audit": {k: v.get("ok") for k, v in report.checks.items()},
            "trace": trace,
        }
    finally:
        install(None)
        if reader is not None:
            reader.close()
        writer.close()
        control.close()
        meta.stop()


_FAILOVER_TABLE_DDL = "CREATE TABLE ft (k BIGINT, v BIGINT)"
_FAILOVER_MV_DDL = ("CREATE MATERIALIZED VIEW fmv AS SELECT k, "
                    "count(*) AS n, sum(v) AS s FROM ft GROUP BY k")


def failover_chaos_schedule(seed: int) -> ChaosSchedule:
    """Seeded chaos the DOOMED writer of ``run_failover`` conducts
    under. The meta-RPC delays are confined to the first 20 frames of
    the deterministic request stream — a window that closes during DDL
    (before the insert loop, whose tail is truncated at the wall-clock
    SIGKILL instant), so the injection trace replays identically even
    though the kill lands at a different frame each run. The second
    rule delays EVERY lease heartbeat; those ride their own
    ``meta#clease`` stream (wall-clock-paced, excluded from the replay
    comparison) and must not expire the lease while the writer lives."""
    from .meta.client import META_LINK
    return ChaosSchedule(seed, [
        ChaosRule(kind="delay", link=META_LINK, prob=0.5, delay_ms=2.0,
                  frames=[0, 20]),
        ChaosRule(kind="delay", link=META_LINK, types=["lease.renew"],
                  prob=1.0, delay_ms=1.0),
    ], name="failover_writer_chaos")


def _failover_writer_main(data_dir: str, addr: str, seed: int) -> int:
    """Entry for the doomed-writer CHILD process of ``run_failover``
    (spawned as ``sim --failover-writer DIR ADDR SEED`` and SIGKILLed
    mid-stream — kill -9, no demotion, no goodbye). Chaos installs HERE
    only; the parent's standbys run chaos-free. Reports readiness and
    every committed epoch on stdout so the parent can time the kill."""
    install(failover_chaos_schedule(seed), trace_path=os.path.join(
        data_dir, "chaos_trace_writer.jsonl"))
    w = Session(data_dir=data_dir, meta_addr=addr, state_store="hummock",
                checkpoint_frequency=2)
    w.run_sql(_FAILOVER_TABLE_DDL)
    w.run_sql(_FAILOVER_MV_DDL)
    print("WRITER_READY", flush=True)
    i = 0
    while True:
        w.run_sql(f"INSERT INTO ft VALUES ({i % 5}, {i})")
        w.tick()
        i += 1
        print(f"WRITER_COMMITTED {w.store.committed_epoch}", flush=True)


def run_failover(seed: int = 7, data_dir: Optional[str] = None,
                 lease_ttl_s: float = 1.0,
                 kill_after_commits: int = 3,
                 tail_inserts: int = 6) -> dict:
    """Leader-failover acceptance scenario (docs/control-plane.md,
    ISSUE 18): SIGKILL the writer PROCESS mid-stream while it conducts
    under seeded chaos → the meta server's TTL detector pushes one
    ``leader_down`` → two chaos-free standbys race ``lease.acquire`` at
    term+1 → exactly one promotes in place and resumes conduction, with
    NO operator action. The monitor (a plain MetaClient subscribed to
    the barrier/checkpoint/leader channels) is the split-brain probe:
    conduction terms never move backwards, per-term epochs and committed
    epochs stay strictly increasing across the handover. Exactly-once is
    audited bit-exact: the committed table rows replayed into a fresh
    in-process control must yield the same MV — the killed writer's
    in-flight epoch either committed once or left no trace."""
    import subprocess
    import sys as _sys
    import tempfile
    import threading
    import time as _time

    from .common.audit import ConsistencyAuditor
    from .meta.client import META_LINK, MetaClient
    from .meta.server import MetaServer

    data_dir = data_dir or tempfile.mkdtemp(prefix="rwtpu_failover_")
    meta = MetaServer(data_dir=os.path.join(data_dir, "meta"),
                      lease_ttl_s=lease_ttl_s)
    addr = meta.start()

    mon = MetaClient(addr, session_id="failover-monitor")
    events: List[tuple] = []
    ev_lock = threading.Lock()

    def _watch(channel: str) -> None:
        def cb(_version, info, _ch=channel):
            with ev_lock:
                events.append((_ch, _time.monotonic(), info))
        mon.notifications.subscribe(channel, cb)

    for ch in ("barrier", "checkpoint", "leader", "leader_down"):
        _watch(ch)

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    child_err = open(os.path.join(data_dir, "writer.stderr"), "w",
                     encoding="utf-8")
    child = subprocess.Popen(
        [_sys.executable, "-m", "risingwave_tpu.sim",
         "--failover-writer", data_dir, addr, str(seed)],
        stdout=subprocess.PIPE, stderr=child_err, text=True, env=env)
    state = {"ready": False, "committed": 0}

    def _drain() -> None:
        # a dedicated drain keeps the child's stdout pipe from filling
        # (a blocked writer would stop heartbeating and die of TTL
        # expiry BEFORE the kill — a different scenario)
        for line in child.stdout:
            line = line.strip()
            if line == "WRITER_READY":
                state["ready"] = True
            elif line.startswith("WRITER_COMMITTED"):
                state["committed"] = int(line.split()[1])

    threading.Thread(target=_drain, daemon=True).start()

    def _wait(cond, timeout_s: float, what: str) -> None:
        deadline = _time.monotonic() + timeout_s
        while not cond():
            if child.poll() is not None:
                raise AssertionError(
                    f"doomed writer died early (rc={child.returncode}) "
                    f"waiting for {what}; see "
                    f"{data_dir}/writer.stderr")
            if _time.monotonic() >= deadline:
                raise AssertionError(f"timed out waiting for {what}")
            _time.sleep(0.02)

    standbys: List[Session] = []
    control: Optional[Session] = None
    try:
        _wait(lambda: state["ready"], 180.0, "writer DDL")
        # standbys attach once the catalog exists; chaos-free, serving
        # reads until the election
        standbys = [Session(data_dir=data_dir, meta_addr=addr,
                            role="standby", checkpoint_frequency=2)
                    for _ in range(2)]
        _wait(lambda: state["committed"] >= kill_after_commits,
              120.0, f"{kill_after_commits} committed epochs")
        killed_at = state["committed"]
        kill_t = _time.monotonic()
        child.kill()
        child.wait(timeout=30)

        def _promoted():
            return next((s for s in standbys
                         if s._leadership["promotions"]), None)

        deadline = kill_t + lease_ttl_s * 10 + 60
        while _promoted() is None and _time.monotonic() < deadline:
            _time.sleep(0.02)
        promoted = _promoted()
        assert promoted is not None, (
            "no standby promoted after the writer kill: "
            f"{[s._leadership for s in standbys]}")
        mttr_ms = (_time.monotonic() - kill_t) * 1e3
        # let every candidate's election thread settle before judging
        # the race — a loser mid-acquire is not yet a loser
        _wait_settled = _time.monotonic() + 30
        while any(s._election_busy for s in standbys) \
                and _time.monotonic() < _wait_settled:
            _time.sleep(0.02)
        assert sum(s._leadership["promotions"] for s in standbys) == 1, (
            "split brain: more than one standby promoted: "
            f"{[s._leadership for s in standbys]}")
        loser = next(s for s in standbys if s is not promoted)
        assert loser.role == "serving", loser.role

        # the promoted writer resumes conduction under term 2 — and the
        # losing standby keeps serving reads throughout
        for j in range(tail_inserts):
            promoted.run_sql(
                f"INSERT INTO ft VALUES ({j % 5}, {10_000 + j})")
            promoted.tick()
        promoted.flush()
        rows = promoted.run_sql("SELECT k, v FROM ft")
        vs = [r[1] for r in rows]
        assert len(vs) == len(set(vs)), (
            "duplicate rows survived the failover: an epoch applied "
            "twice")
        assert len(loser.run_sql("SELECT k, n, s FROM fmv")) > 0

        # exactly-once, bit-exact: the committed rows replayed into a
        # fresh control must rebuild the same MV state the promoted
        # writer recovered + maintained across the handover
        control = Session(checkpoint_frequency=2)
        control.run_sql(_FAILOVER_TABLE_DDL)
        control.run_sql(_FAILOVER_MV_DDL)
        ordered = sorted(rows, key=lambda r: r[1])
        for off in range(0, len(ordered), 8):
            chunk = ordered[off:off + 8]
            control.run_sql("INSERT INTO ft VALUES " + ", ".join(
                f"({k}, {v})" for k, v in chunk))
            control.tick()
        control.flush()
        report = ConsistencyAuditor(promoted).audit(
            control=control, mv_names=["fmv"])
        report.assert_ok()

        # -- the monitor's split-brain probe --------------------------------
        with ev_lock:
            evs = list(events)
        downs = [e for e in evs if e[0] == "leader_down"]
        assert len(downs) == 1 and downs[0][2]["term"] == 1, downs
        leader_terms = [int(e[2]["term"]) for e in evs
                        if e[0] == "leader"]
        assert leader_terms == sorted(set(leader_terms)), (
            f"leader terms not strictly increasing: {leader_terms}")
        assert [e[2]["reason"] for e in evs
                if e[0] == "leader"].count("election") == 1
        pub_terms = [int(e[2]["term"]) for e in evs
                     if e[0] in ("barrier", "checkpoint")
                     and e[2].get("term") is not None]
        assert all(a <= b for a, b in zip(pub_terms, pub_terms[1:])), (
            f"conduction terms moved backwards: {pub_terms}")
        by_term: Dict[int, List[int]] = {}
        for e in evs:
            if e[0] == "barrier" and e[2].get("term") is not None:
                by_term.setdefault(int(e[2]["term"]), []).append(
                    int(e[2]["epoch"]))
        for term, epochs in by_term.items():
            assert all(a < b for a, b in zip(epochs, epochs[1:])), (
                f"term {term} epochs not strictly increasing: {epochs}")
        commits = [int(e[2]["committed_epoch"]) for e in evs
                   if e[0] == "checkpoint"]
        assert all(a < b for a, b in zip(commits, commits[1:])), (
            f"committed epochs not strictly increasing: {commits}")
        detect_ms = (downs[0][1] - kill_t) * 1e3
        ckpt_times = [e[1] for e in evs if e[0] == "checkpoint"]
        gaps = [(b - a) * 1e3
                for a, b in zip(ckpt_times, ckpt_times[1:])]

        info = mon.lease_info()
        assert info["failovers"] == 1 and info["term"] == 2, info
        trace = {k: v for k, v in _collect_trace(data_dir).items()
                 if k == META_LINK}
        return {
            "scenario": "leader_failover", "seed": seed,
            "lease_ttl_s": lease_ttl_s,
            "killed_at_commit": killed_at,
            "rows": len(rows),
            "terms": sorted(by_term),
            "failovers": info["failovers"],
            "detect_ms": round(detect_ms, 3),
            "mttr_ms": round(mttr_ms, 3),
            "unavail_ms": round(max(gaps), 3) if gaps else None,
            "gap_samples_ms": [round(g, 3) for g in gaps],
            "elections_lost": sum(s._leadership["elections_lost"]
                                  for s in standbys),
            "audit": {k: v.get("ok") for k, v in report.checks.items()},
            "trace": trace,
        }
    finally:
        mon.close()
        for s in standbys:
            s.close()
        if control is not None:
            control.close()
        if child.poll() is None:
            child.kill()
        child_err.close()
        meta.stop()


def run_udf_soak(duration_s: float = 45.0, seed: int = 5,
                 data_dir: Optional[str] = None,
                 kill_every: int = 6,
                 min_ticks: int = 12) -> dict:
    """Soak seed (ROADMAP item 5's standing gauntlet, first brick): RPC
    chaos on the worker exchange links (dup + reorder — absorbed by the
    seq layer, no recovery expected) + periodic UDF-server SIGKILLs +
    concurrent serving readers (one of them crossing the UDF boundary),
    all live for ``duration_s``, then a bit-exact audit against a
    no-chaos control. Returns a SCHEMA-STABLE numeric record shaped for
    ``BENCH_partial.json`` (`ctl bench trend` folds it as phase
    ``udf_soak``)."""
    import tempfile
    import threading
    import time as _time

    from .common.audit import ConsistencyAuditor
    from .common.config import FaultConfig, UdfConfig
    from .frontend.build import BuildConfig
    from .udf.client import udf_plane

    _ensure_udf("soak_tax", _chaos_tax)
    data_dir = data_dir or tempfile.mkdtemp(prefix="rwtpu_udfsoak_")
    udf_plane().configure(UdfConfig(call_timeout_s=5.0, max_retries=4),
                          trace_dir=data_dir)
    base = dict(udf_plane().snapshot())
    schedule = ChaosSchedule(seed, [
        ChaosRule(kind="duplicate", link="w0<->w1", types=["exg_data"],
                  prob=0.2),
        ChaosRule(kind="delay", link="w0<->w1",
                  types=["exg_data:chunk"], prob=0.2, delay_frames=2),
    ], name="udf_soak")
    fc = FaultConfig(worker_epoch_timeout_s=60.0,
                     exchange_keepalive_s=0.0)
    sim = SimCluster(data_dir, seed=seed, kill_rate=0.0, workers=2,
                     chaos=schedule, checkpoint_frequency=4,
                     source_chunk_capacity=64, fault_config=fc,
                     config=BuildConfig(fragment_parallelism=2))
    control = None
    stop = threading.Event()
    reader_stats = {"queries": 0, "errors": 0}

    def reader() -> None:
        while not stop.is_set():
            try:
                sim.session.run_sql(
                    "SELECT auction, num FROM q WHERE auction >= 0")
                sim.session.run_sql("SELECT k, soak_tax(v) FROM ut")
                reader_stats["queries"] += 2
            except Exception:  # noqa: BLE001 - counted, asserted == 0
                reader_stats["errors"] += 1
            _time.sleep(0.05)

    t0 = _time.monotonic()
    ticks = 0
    threads = []
    try:
        control = Session(seed=42, source_chunk_capacity=64,
                          checkpoint_frequency=4)
        for sess in (sim.session, control):
            sess.run_sql(_BID_DDL)
            sess.run_sql(
                "CREATE MATERIALIZED VIEW q AS SELECT auction, "
                "count(*) AS num FROM bid GROUP BY auction")
            sess.run_sql(_UDF_T_DDL)
            sess.run_sql("CREATE MATERIALIZED VIEW mu AS "
                         "SELECT k, soak_tax(v) AS tv FROM ut")
        assert "q" in sim.session._spanning_specs, \
            "soak MV did not deploy as a spanning graph"
        threads = [threading.Thread(target=reader, daemon=True)]
        for t in threads:
            t.start()
        while ticks < min_ticks or \
                _time.monotonic() - t0 < duration_s:
            sim.run_sql(
                f"INSERT INTO ut VALUES ({ticks + 1}, {ticks * 11})")
            control.run_sql(
                f"INSERT INTO ut VALUES ({ticks + 1}, {ticks * 11})")
            sim.tick()
            control.tick()
            ticks += 1
            if kill_every and ticks % kill_every == 0:
                udf_plane().kill_server()
        stop.set()
        for t in threads:
            t.join(timeout=10)
        sim.verify_against(control, ["q", "mu"])
        report = ConsistencyAuditor(sim.session).audit(control=control)
        report.assert_ok()
        wall = _time.monotonic() - t0
        stats = udf_plane().snapshot()
        # exchange-link injections happen in the WORKER processes'
        # planes; the session federates their snapshots in metrics()
        chaos_m = sim.session.metrics().get("chaos", {})
        inj = dict(chaos_m.get("injections") or {})
        for wst in (chaos_m.get("workers") or {}).values():
            for k, v in (wst.get("injections") or {}).items():
                inj[k] = inj.get(k, 0) + v
        return {
            "seed": seed,
            "duration_s": round(wall, 3),
            "ticks": ticks,
            "rows_per_sec": round(
                ticks * 64 / wall, 3) if wall > 0 else 0.0,
            "udf_calls": stats["calls"] - base["calls"],
            "udf_spawns": stats["spawns"] - base["spawns"],
            "udf_respawns": stats["respawns"] - base["respawns"],
            "udf_timeouts": stats["timeouts"] - base["timeouts"],
            "udf_stale_drops": stats["stale_replies_dropped"]
            - base["stale_replies_dropped"],
            "reader_queries": reader_stats["queries"],
            "reader_errors": reader_stats["errors"],
            "chaos_injections": sum(inj.values()),
            "mv_rows": len(sim.mv_rows("q")),
            "audit_ok": int(all(v.get("ok")
                                for v in report.checks.values())),
        }
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        sim.close()
        if control is not None:
            control.close()


def main(argv=None) -> int:
    """CLI for replaying seeds: ``python -m risingwave_tpu.sim
    --netsplit q5_exchange_partition --seed 7 [--replay]`` or
    ``--sweep [--sites a,b]`` (docs/robustness.md)."""
    import argparse
    import tempfile
    ap = argparse.ArgumentParser()
    ap.add_argument("--netsplit", choices=sorted(NETSPLIT_SCENARIOS))
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--replay", action="store_true",
                    help="run the scenario twice and assert the "
                         "injection traces are identical")
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--spanning-sweep", action="store_true")
    ap.add_argument("--traffic-spike", action="store_true",
                    help="run the elastic-scaling acceptance scenario: "
                         "seeded load spike → autoscaler live-rescales "
                         "2→4 → no flap on subside → audit green "
                         "(docs/scaling.md)")
    ap.add_argument("--sites", default=None,
                    help="comma-separated failpoint subset for --sweep")
    ap.add_argument("--udf-chaos", action="store_true",
                    help="run the UDF-link chaos scenario: seeded "
                         "drop/delay/duplicate on s->udf plus a server "
                         "SIGKILL mid-run, audited bit-exact against a "
                         "no-chaos control (docs/robustness.md)")
    ap.add_argument("--meta-chaos", action="store_true",
                    help="run the meta-link latency scenario: a writer "
                         "attached to a standalone MetaServer plus a "
                         "serving reader over a seeded-delayed RPC "
                         "link, audited bit-exact against an "
                         "in-process control (docs/control-plane.md)")
    ap.add_argument("--failover", action="store_true",
                    help="run the leader-failover acceptance scenario: "
                         "kill -9 the writer process mid-stream under "
                         "seeded chaos → a standby auto-promotes within "
                         "the lease TTL with no operator action, "
                         "exactly-once audited, split-brain probe green "
                         "(docs/control-plane.md)")
    ap.add_argument("--failover-writer", nargs=3,
                    metavar=("DIR", "ADDR", "SEED"),
                    help=argparse.SUPPRESS)
    ap.add_argument("--udf-soak", action="store_true",
                    help="run the soak seed: RPC chaos + UDF-server "
                         "kills + serving readers live together, "
                         "auditor green; emits the BENCH_partial-shaped "
                         "udf_soak record")
    ap.add_argument("--duration", type=float, default=45.0,
                    help="--udf-soak wall-clock duration in seconds")
    args = ap.parse_args(argv)
    if args.failover_writer:
        d, addr, s = args.failover_writer
        return _failover_writer_main(d, addr, int(s))
    if args.netsplit:
        r1 = run_netsplit(args.netsplit, seed=args.seed,
                          data_dir=tempfile.mkdtemp(prefix="rwtpu_ns1_"))
        print(json.dumps({k: r1[k] for k in
                          ("scenario", "seed", "recovered", "audit")},
                         indent=2))
        if args.replay:
            r2 = run_netsplit(args.netsplit, seed=args.seed,
                              data_dir=tempfile.mkdtemp(
                                  prefix="rwtpu_ns2_"))
            assert r1["trace"] == r2["trace"], (
                "seeded replay diverged:\n"
                f"run1: {r1['trace']}\nrun2: {r2['trace']}")
            print(f"replay OK: {sum(len(v) for v in r1['trace'].values())}"
                  " injections reproduced identically")
    if args.sweep:
        sites = args.sites.split(",") if args.sites else None
        res = crash_point_sweep(tempfile.mkdtemp(prefix="rwtpu_sweep_"),
                                sites=sites, seed=args.seed)
        print(json.dumps(res, indent=2))
    if args.spanning_sweep:
        res = crash_point_sweep_spanning(
            tempfile.mkdtemp(prefix="rwtpu_span_"))
        print(json.dumps(res, indent=2))
    if args.traffic_spike:
        res = run_traffic_spike(
            seed=args.seed,
            data_dir=tempfile.mkdtemp(prefix="rwtpu_spike_"))
        print(json.dumps(res, indent=2, default=str))
    if args.udf_chaos:
        r1 = run_udf_chaos(seed=args.seed,
                           data_dir=tempfile.mkdtemp(
                               prefix="rwtpu_udfc1_"))
        print(json.dumps({k: r1[k] for k in
                          ("scenario", "seed", "respawns", "timeouts",
                           "injections", "audit")}, indent=2))
        if args.replay:
            r2 = run_udf_chaos(seed=args.seed,
                               data_dir=tempfile.mkdtemp(
                                   prefix="rwtpu_udfc2_"))
            assert r1["trace"] == r2["trace"], (
                "seeded udf-chaos replay diverged:\n"
                f"run1: {r1['trace']}\nrun2: {r2['trace']}")
            print(f"replay OK: "
                  f"{sum(len(v) for v in r1['trace'].values())} "
                  "injections reproduced identically")
    if args.meta_chaos:
        r1 = run_meta_chaos(seed=args.seed,
                            data_dir=tempfile.mkdtemp(
                                prefix="rwtpu_metac1_"))
        print(json.dumps({k: r1[k] for k in
                          ("scenario", "seed", "rows", "injections",
                           "audit")}, indent=2))
        if args.replay:
            r2 = run_meta_chaos(seed=args.seed,
                                data_dir=tempfile.mkdtemp(
                                    prefix="rwtpu_metac2_"))
            assert r1["trace"] == r2["trace"], (
                "seeded meta-chaos replay diverged:\n"
                f"run1: {r1['trace']}\nrun2: {r2['trace']}")
            print(f"replay OK: "
                  f"{sum(len(v) for v in r1['trace'].values())} "
                  "injections reproduced identically")
    if args.failover:
        r1 = run_failover(seed=args.seed,
                          data_dir=tempfile.mkdtemp(
                              prefix="rwtpu_fo1_"))
        print(json.dumps({k: r1[k] for k in
                          ("scenario", "seed", "killed_at_commit",
                           "terms", "failovers", "detect_ms",
                           "mttr_ms", "unavail_ms", "rows", "audit")},
                         indent=2))
        if args.replay:
            r2 = run_failover(seed=args.seed,
                              data_dir=tempfile.mkdtemp(
                                  prefix="rwtpu_fo2_"))
            assert r1["trace"] == r2["trace"], (
                "seeded failover replay diverged:\n"
                f"run1: {r1['trace']}\nrun2: {r2['trace']}")
            print(f"replay OK: "
                  f"{sum(len(v) for v in r1['trace'].values())} "
                  "injections reproduced identically")
    if args.udf_soak:
        res = run_udf_soak(duration_s=args.duration, seed=args.seed)
        print(json.dumps({"phase": "udf_soak", "record": res}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
