"""Deterministic simulation / chaos harness.

Counterpart of the reference's madsim deterministic cluster
(reference: src/tests/simulation/src/cluster.rs:129-247 — the whole
cluster in one process under a seeded scheduler, with ``--kill`` randomly
restarting nodes mid-workload; recovery tests
tests/integration_tests/recovery/). Scaled to this build's architecture:
the "cluster" is a durable Session; a *kill* abandons it without any
graceful shutdown and recovers a fresh Session from the same data dir
(crash recovery path), at epochs chosen by a seeded RNG.

Client semantics are honest: DML acknowledged only at FLUSH; statements
not yet flushed when a kill strikes are re-applied by the harness (client
retry), exactly how an at-least-once client driver behaves against the
reference. The end-state cross-check compares every MV against a control
session that never crashed.
"""

from __future__ import annotations

import random
from typing import List, Optional

from .frontend.session import Session


class SimCluster:
    def __init__(self, data_dir: str, seed: int = 0, kill_rate: float = 0.3,
                 checkpoint_frequency: int = 2, workers: int = 0,
                 transient_fault_rate: float = 0.0,
                 broker=None, broker_restart_rate: float = 0.0,
                 **session_kw):
        """``workers`` > 0 runs MV jobs on worker PROCESSES and arms
        per-component kills: the chaos step randomly SIGKILLs one worker
        (scoped heartbeat-TTL recovery) instead of always restarting the
        whole cluster — the madsim individual-node kill
        (reference: cluster.rs:498-510).

        ``transient_fault_rate`` > 0 arms SEEDED transient object-store
        faults for the whole workload (every durable-tier IO may fail and
        be retried — storage/object_store.py FaultInjectingObjectStore
        under the retry layer), proving the exactly-once machinery holds
        under flaky IO, not just clean kills. ``broker`` (a BrokerServer
        with a durable data_dir) + ``broker_restart_rate`` add broker
        restarts to the chaos menu: readers/sinks must survive via the
        reconnecting BrokerClient."""
        self.data_dir = data_dir
        self.rng = random.Random(seed)
        self.kill_rate = kill_rate
        self.session_kw = dict(session_kw,
                               checkpoint_frequency=checkpoint_frequency)
        if workers:
            self.session_kw["workers"] = workers
        if transient_fault_rate > 0.0 and \
                "fault_config" not in self.session_kw:
            from .common.config import FaultConfig
            self.session_kw["fault_config"] = FaultConfig(
                inject_object_store_transient_rate=transient_fault_rate,
                inject_object_store_seed=self.rng.randrange(1 << 30),
                # faults at rate p need attempts n with p**n ≈ 0:
                # 8 attempts at p=0.2 leaves ~3e-6 per op
                io_retry_attempts=8, io_retry_base_ms=1.0,
                io_retry_max_ms=20.0)
        self.broker = broker
        self.broker_restart_rate = broker_restart_rate
        self.broker_restarts = 0
        self.session = Session(data_dir=data_dir, **self.session_kw)
        self.kills = 0
        self.worker_kills = 0
        self.spanning_kills = 0
        self._unacked: List[str] = []     # DML since the last FLUSH

    # -- client API -----------------------------------------------------------

    def run_sql(self, sql: str) -> list:
        out = self.session.run_sql(sql)
        s = sql.lstrip().lower()
        if s.startswith("insert"):
            self._unacked.append(sql)
        elif s.startswith("flush"):
            self._unacked.clear()
        return out

    def flush(self) -> None:
        self.session.flush()
        self._unacked.clear()

    def tick(self) -> None:
        self.session.tick()

    def mv_rows(self, name: str) -> list:
        return self.session.mv_rows(name)

    # -- chaos ----------------------------------------------------------------

    def maybe_kill(self) -> bool:
        # broker restarts draw independently: a flaky broker AND a
        # crashing cluster may strike in the same step
        if (self.broker is not None and self.broker_restart_rate > 0
                and self.rng.random() < self.broker_restart_rate):
            self.restart_broker()
        if self.rng.random() >= self.kill_rate:
            return False
        if getattr(self.session, "workers", None) and \
                self.rng.random() < 0.5:
            # spanning fragment graphs get their own chaos entry: kill a
            # worker that hosts ONE fragment of a multi-worker graph
            # (scoped rebuild of that graph, every other job untouched)
            if getattr(self.session, "_spanning_specs", None) and \
                    self.rng.random() < 0.5:
                self.kill_spanning_worker()
            else:
                self.kill_worker()
        else:
            self.kill()
        return True

    def restart_broker(self) -> None:
        """Bounce the external broker on the SAME address (durable
        segments reload): in-flight client commands fail and must be
        absorbed by BrokerClient's reconnect-with-backoff."""
        from .connector.broker import BrokerServer
        old = self.broker
        host, port = old.host, old.port
        old.close()
        self.broker = BrokerServer(
            host=host, port=port, n_partitions=old.n_partitions,
            data_dir=old.data_dir).start()
        self.broker_restarts += 1

    def kill_worker(self) -> None:
        """SIGKILL one worker process (per-component failure): the
        session survives; the heartbeat TTL declares the worker's jobs
        dead and scoped recovery respawns it on subsequent ticks."""
        w = self.rng.choice(self.session.workers)
        w.kill9()
        self.worker_kills += 1
        for _ in range(12):               # TTL + respawn happen in-tick
            self.session.tick()
            if not w.dead:
                return
        raise AssertionError("killed worker was not recovered")

    def kill_spanning_worker(self) -> None:
        """SIGKILL one worker hosting a FRAGMENT of a spanning graph:
        surviving peers report PEER_LOST on their exchange edges, the
        TTL declares the job dead, and scoped recovery must rebuild ONLY
        the affected fragment graph (respawned worker + surviving
        fragments reloaded at the last commit) and converge — asserted
        here, cross-checked against the control session by the caller."""
        specs = self.session._spanning_specs
        name = self.rng.choice(sorted(specs))
        w = self.rng.choice(specs[name]["workers"])
        w.kill9()
        self.worker_kills += 1
        self.spanning_kills += 1
        for _ in range(16):               # TTL + scoped rebuild in-tick
            self.session.tick()
            job = self.session.jobs.get(name)
            if not w.dead and job is not None and job._failure is None:
                return
        raise AssertionError(
            f"spanning job {name!r} did not converge after a "
            "participant kill")

    def kill(self) -> None:
        """Abandon the session with no shutdown (uncommitted state and
        unacked DML are lost), then recover + re-apply unacked DML."""
        self.kills += 1
        # crash semantics: no job shutdown, no flush — but kill the old
        # worker PROCESSES (their parent is gone, like a machine reboot)
        # and close the abandoned private event loop so kills don't leak
        old = self.session
        for w in getattr(old, "workers", []) or []:
            try:
                w.kill9()
            except Exception:   # noqa: BLE001
                pass
        try:
            old.loop.close()
        except Exception:   # noqa: BLE001
            pass
        self.session = Session(data_dir=self.data_dir, **self.session_kw)
        for sql in self._unacked:
            self.session.run_sql(sql)

    # -- verification ---------------------------------------------------------

    def verify_against(self, control: Session,
                       mv_names: Optional[List[str]] = None) -> None:
        """Final-state cross-check after both sides flushed."""
        self.flush()
        control.flush()
        names = mv_names or sorted(self.session.catalog.mvs)
        for name in names:
            got = sorted(self.mv_rows(name))
            want = sorted(control.mv_rows(name))
            assert got == want, (
                f"MV {name!r} diverged after {self.kills} kills:\n"
                f"  chaos:   {got[:10]}\n  control: {want[:10]}")
