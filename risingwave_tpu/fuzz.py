"""SqlSmith-lite: seeded random SQL against the session.

Counterpart of the reference's SqlSmith fuzzing
(reference: src/tests/sqlsmith/src/{sql_gen,runner.rs} — generate random
valid SQL, execute, shrink on failure; run in CI as a crash hunt). This
generator covers the subset the frontend supports and adds a stronger
oracle than crash-freedom: every generated query is run BOTH as a batch
SELECT and as a streaming MATERIALIZED VIEW over the same data, and the
two results must agree — the stream/batch unification invariant.
"""

from __future__ import annotations

import random
from typing import List, Optional, Tuple


class SqlGen:
    """Random SELECTs over tables t0(k,a,b), t1(k,c). Deterministic per
    seed."""

    def __init__(self, seed: int):
        self.rng = random.Random(seed)

    def scalar(self, cols: List[str], depth: int = 0) -> str:
        r = self.rng
        if depth >= 2 or r.random() < 0.4:
            if r.random() < 0.6:
                return r.choice(cols)
            return str(r.randint(-5, 20))
        kind = r.choice(["arith", "case", "neg"])
        if kind == "arith":
            op = r.choice(["+", "-", "*"])
            return (f"({self.scalar(cols, depth + 1)} {op} "
                    f"{self.scalar(cols, depth + 1)})")
        if kind == "neg":
            return f"(- {self.scalar(cols, depth + 1)})"
        return (f"(CASE WHEN {self.predicate(cols, depth + 1)} "
                f"THEN {self.scalar(cols, depth + 1)} "
                f"ELSE {self.scalar(cols, depth + 1)} END)")

    def predicate(self, cols: List[str], depth: int = 0) -> str:
        r = self.rng
        cmp = r.choice(["<", "<=", ">", ">=", "=", "<>"])
        left = f"{self.scalar(cols, depth + 1)} {cmp} " \
               f"{self.scalar(cols, depth + 1)}"
        if depth < 1 and r.random() < 0.3:
            conj = r.choice(["AND", "OR"])
            return f"({left}) {conj} ({self.predicate(cols, depth + 1)})"
        return left

    def query(self) -> str:
        r = self.rng
        joined = r.random() < 0.35
        if joined:
            frm = "t0 JOIN t1 ON t0.k = t1.k"
            cols = ["a", "b", "c"]
        else:
            frm = "t0"
            cols = ["k", "a", "b"]
        where = (f" WHERE {self.predicate(cols)}"
                 if r.random() < 0.6 else "")
        if r.random() < 0.45:
            gk = r.choice(cols)
            aggs = r.sample(
                [f"count(*)", f"sum({r.choice(cols)})",
                 f"min({r.choice(cols)})", f"max({r.choice(cols)})",
                 f"approx_count_distinct({r.choice(cols)})"],
                k=r.randint(1, 2))
            items = [f"{gk} AS g"] + [
                f"{a} AS x{i}" for i, a in enumerate(aggs)]
            return (f"SELECT {', '.join(items)} FROM {frm}{where} "
                    f"GROUP BY {gk}")
        items = [f"{self.scalar(cols)} AS x{i}"
                 for i in range(r.randint(1, 3))]
        return f"SELECT {', '.join(items)} FROM {frm}{where}"


def run_fuzz(n_queries: int = 40, seed: int = 0,
             session=None) -> Tuple[int, List[str]]:
    """Returns (n_checked, failures). A failure is a query whose MV result
    diverged from its batch result, or that crashed the session."""
    from .frontend.session import Session
    s = session or Session()
    rng = random.Random(seed ^ 0x5EED)
    s.run_sql("CREATE TABLE t0 (k BIGINT PRIMARY KEY, a BIGINT, b BIGINT)")
    s.run_sql("CREATE TABLE t1 (k BIGINT PRIMARY KEY, c BIGINT)")
    for i in range(25):
        s.run_sql(f"INSERT INTO t0 VALUES ({i}, {rng.randint(-9, 9)}, "
                  f"{rng.randint(0, 5)})")
    for i in range(0, 25, 2):
        s.run_sql(f"INSERT INTO t1 VALUES ({i}, {rng.randint(-3, 12)})")
    s.flush()

    gen = SqlGen(seed)
    failures: List[str] = []
    checked = 0
    for qi in range(n_queries):
        sql = gen.query()
        try:
            batch = sorted(s.run_sql(sql))
        except Exception as e:  # noqa: BLE001 - crash IS the finding
            failures.append(f"batch crash: {sql!r}: {type(e).__name__} {e}")
            continue
        mv_name = f"fz{qi}"
        try:
            s.run_sql(f"CREATE MATERIALIZED VIEW {mv_name} AS {sql}")
            s.flush()
            mv = sorted(tuple(r) for r in s.mv_rows(mv_name))
            s.run_sql(f"DROP MATERIALIZED VIEW {mv_name}")
        except Exception as e:  # noqa: BLE001
            failures.append(f"mv crash: {sql!r}: {type(e).__name__} {e}")
            continue
        if mv != batch:
            failures.append(
                f"divergence: {sql!r}\n  batch={batch[:5]}\n  mv={mv[:5]}")
        checked += 1
    return checked, failures
