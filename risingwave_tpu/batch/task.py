"""BatchTaskManager: parallel batch tasks over vnode partitions.

Counterpart of the reference's batch task layer
(reference: src/batch/src/task/task_manager.rs:42,93 ``fire_task`` —
per-task output channels consumed via gRPC exchange; the frontend
scheduler splits a scan stage into vnode-partitioned tasks). Here a task
is a thread running a batch plan over a vnode slice; the "exchange" is
the in-process result list. Device work inside a task is host-driven
numpy/jnp over snapshot chunks, so thread-parallel tasks genuinely
overlap on the scan/decode portions.
"""

from __future__ import annotations

import concurrent.futures
import itertools
from typing import Callable, Dict, List, Optional

from ..common.hashing import VNODE_COUNT
from .executors import BatchExecutor, run_batch


def vnode_partitions(n_tasks: int) -> List[List[int]]:
    """Split the vnode space into ``n_tasks`` contiguous slices
    (reference: the scheduler's vnode bitmaps per task)."""
    n_tasks = max(1, min(n_tasks, VNODE_COUNT))
    per = VNODE_COUNT // n_tasks
    extra = VNODE_COUNT % n_tasks
    out, lo = [], 0
    for i in range(n_tasks):
        hi = lo + per + (1 if i < extra else 0)
        out.append(list(range(lo, hi)))
        lo = hi
    return out


class BatchTaskManager:
    def __init__(self, max_workers: int = 4):
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers)
        self._ids = itertools.count(1)
        self._tasks: Dict[int, concurrent.futures.Future] = {}

    def fire_task(self, plan_factory: Callable[[Optional[List[int]]],
                                               BatchExecutor],
                  vnodes: Optional[List[int]] = None) -> int:
        """Run ``plan_factory(vnodes)``'s plan asynchronously; returns a
        task id to ``collect``."""
        task_id = next(self._ids)
        self._tasks[task_id] = self._pool.submit(
            lambda: run_batch(plan_factory(vnodes)))
        return task_id

    def fire_partitioned(self, plan_factory, n_tasks: int) -> List[int]:
        """One task per vnode slice (a full scan stage)."""
        return [self.fire_task(plan_factory, part)
                for part in vnode_partitions(n_tasks)]

    def collect(self, task_id: int, timeout: Optional[float] = None):
        """Wait for one task's result. The entry stays registered until
        the task OUTCOME is actually retrieved: popping before the wait
        (the old behavior) leaked the future on timeout — a slow task
        became permanently uncollectable even though it finished moments
        later. A task's own exception counts as retrieval (the entry is
        dropped); only a collect timeout keeps it collectable."""
        fut = self._tasks[task_id]
        try:
            result = fut.result(timeout=timeout)
        except concurrent.futures.TimeoutError:
            raise                      # not done yet: entry stays
        except BaseException:
            self._tasks.pop(task_id, None)   # outcome delivered: failed
            raise
        self._tasks.pop(task_id, None)
        return result

    def discard(self, task_id: int) -> None:
        """Abandon a fired task: cancel if still queued, drop the entry
        either way (callers that stop collecting after a sibling failed
        use this so the remaining futures don't leak)."""
        fut = self._tasks.pop(task_id, None)
        if fut is not None:
            fut.cancel()

    def collect_all(self, task_ids: List[int]) -> List[tuple]:
        rows: List[tuple] = []
        for t in task_ids:
            rows.extend(self.collect(t))
        return rows

    def pending(self) -> int:
        """Tasks fired but not yet collected (observability/tests)."""
        return len(self._tasks)

    def shutdown(self, wait: bool = False) -> None:
        """Stop the pool (``Session.close`` calls this): queued-but-idle
        tasks are cancelled; running ones finish but their results are
        dropped with the task map."""
        self._tasks.clear()
        try:
            self._pool.shutdown(wait=wait, cancel_futures=True)
        except TypeError:              # cancel_futures needs py3.9+
            self._pool.shutdown(wait=wait)
