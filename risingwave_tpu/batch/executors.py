"""Batch executors: one-shot vectorized query operators over snapshots.

Counterpart of the reference's batch engine
(reference: src/batch/src/executor/ — RowSeqScan over vnode-partitioned
StorageTable ranges, Filter/Project/HashAgg/Sort/TopN/Limit…;
src/batch/src/task/task_manager.rs:42 fire_task). Where the reference
streams row batches through pull-based executors, the TPU design
evaluates each operator as ONE whole-snapshot device computation: a scan
materializes the table's rows into fixed-capacity chunks, and every
downstream operator is a vectorized jnp transformation over those chunks
— there is no per-batch pull loop to schedule, XLA fuses the operator
bodies instead.

Wired into ``Session.query`` via batch/lower.py: scan / filter / project
/ agg / top-n plans run here; the stream-fold path remains the engine
for plans with stream-only operators (joins, windows, EOWC).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np

from ..common.chunk import StreamChunk, chunk_to_rows, physical_chunk
from ..common.hashing import VNODE_COUNT, vnode_of
from ..common.types import Schema
from ..expr.agg import AggCall
from ..expr.expr import Expr
from ..ops.topn import OrderSpec
from ..storage.state_table import StateTable


class BatchExecutor:
    schema: Schema

    def execute(self) -> Iterator[List[tuple]]:
        """Yields row batches (physical tuples)."""
        raise NotImplementedError


class RowSeqScan(BatchExecutor):
    """Full / vnode-partitioned snapshot scan over a StateTable
    (reference: row_seq_scan.rs — scan ranges are vnode partitions so
    parallel tasks split the key space)."""

    def __init__(self, table: StateTable,
                 vnodes: Optional[Sequence[int]] = None,
                 batch_size: int = 4096):
        self.table = table
        self.schema = table.schema
        self.vnodes = None if vnodes is None else set(vnodes)
        self.batch_size = batch_size

    def execute(self):
        buf: List[tuple] = []
        for row in self.table.scan_all():
            buf.append(row)
            if len(buf) >= self.batch_size:
                yield from self._emit(buf)
                buf = []
        if buf:
            yield from self._emit(buf)

    def _emit(self, rows: List[tuple]):
        if self.vnodes is None:
            yield rows
            return
        # vectorized vnode of the pk columns for the whole batch — the
        # same device hash the streaming shuffle uses, so batch-task
        # partitions line up with stream shards
        pk = list(self.table.pk_indices)
        pk_schema = self.schema.select(pk)
        chunk = physical_chunk(
            pk_schema, [tuple(r[i] for i in pk) for r in rows], len(rows))
        vn = np.asarray(vnode_of(list(chunk.columns)))
        out = [r for r, v in zip(rows, vn) if int(v) in self.vnodes]
        if out:
            yield out


class _SingleInput(BatchExecutor):
    def __init__(self, input: BatchExecutor):
        self.input = input
        self.schema = input.schema


class BatchFilter(_SingleInput):
    def __init__(self, input: BatchExecutor, predicate: Expr):
        super().__init__(input)
        self.predicate = predicate

    def execute(self):
        for rows in self.input.execute():
            chunk = physical_chunk(self.schema, rows, max(len(rows), 1))
            cond = self.predicate.eval(chunk)
            keep = np.asarray(cond.data & cond.mask)[:len(rows)]
            out = [r for r, k in zip(rows, keep) if k]
            if out:
                yield out


class BatchProject(_SingleInput):
    def __init__(self, input: BatchExecutor, exprs: Sequence[Expr],
                 names: Sequence[str] = ()):
        super().__init__(input)
        from ..common.types import Field
        self.exprs = list(exprs)
        names = tuple(names) or tuple(f"expr{i}" for i in range(len(exprs)))
        self.schema = Schema(tuple(
            Field(n, e.type) for n, e in zip(names, self.exprs)))

    def execute(self):
        for rows in self.input.execute():
            chunk = physical_chunk(self.input.schema, rows,
                                   max(len(rows), 1))
            cols = [e.eval(chunk) for e in self.exprs]
            datas = [np.asarray(c.data) for c in cols]
            masks = [np.asarray(c.mask) for c in cols]
            out = [
                tuple(d[i].item() if m[i] else None
                      for d, m in zip(datas, masks))
                for i in range(len(rows))
            ]
            yield out


class BatchHashAgg(_SingleInput):
    """Hash aggregation over the whole input (one shot, no retraction)."""

    def __init__(self, input: BatchExecutor, group_keys: Sequence[int],
                 agg_calls: Sequence[AggCall]):
        super().__init__(input)
        from ..common.types import Field
        self.group_keys = tuple(group_keys)
        self.agg_calls = tuple(agg_calls)
        fields = tuple(input.schema[i] for i in self.group_keys) + tuple(
            Field(f"agg{i}", a.output_type)
            for i, a in enumerate(self.agg_calls))
        self.schema = Schema(fields)

    def execute(self):
        groups: dict = {}
        if not self.group_keys:
            # global agg emits one row even over empty input
            # (count()=0, others NULL) — matching the streaming SimpleAgg
            groups[()] = [(0, None, None, None)] * len(self.agg_calls)
        for rows in self.input.execute():
            for row in rows:
                key = tuple(row[i] for i in self.group_keys)
                accs = groups.setdefault(
                    key, [(0, None, None, None)] * len(self.agg_calls))
                for i, a in enumerate(self.agg_calls):
                    v = 1 if a.arg < 0 else row[a.arg]
                    if v is None:
                        continue
                    cnt, s, mn, mx = accs[i]
                    accs[i] = (cnt + 1, (s or 0) + v,
                               v if mn is None else min(mn, v),
                               v if mx is None else max(mx, v))
        out = []
        for key, accs in groups.items():
            vals = []
            for a, (cnt, s, mn, mx) in zip(self.agg_calls, accs):
                if a.kind == "count":
                    vals.append(cnt)
                elif a.kind == "sum":
                    vals.append(s if cnt else None)
                elif a.kind == "min":
                    vals.append(mn)
                elif a.kind == "max":
                    vals.append(mx)
                else:   # avg
                    vals.append(s / cnt if cnt else None)
            out.append(key + tuple(vals))
        if out:
            yield out


class BatchSort(_SingleInput):
    def __init__(self, input: BatchExecutor, order: Sequence[OrderSpec]):
        super().__init__(input)
        self.order = list(order)

    def execute(self):
        allrows = [r for rows in self.input.execute() for r in rows]

        def key(row):
            k = []
            for spec in self.order:
                v = row[spec.col]
                null_rank = 1 if spec.nulls_last else -1
                k.append((null_rank, 0) if v is None
                         else (0, -v if spec.desc else v))
            return tuple(k)

        allrows.sort(key=key)
        if allrows:
            yield allrows


class BatchLimit(_SingleInput):
    def __init__(self, input: BatchExecutor, limit: int, offset: int = 0):
        super().__init__(input)
        self.limit = limit
        self.offset = offset

    def execute(self):
        skipped = taken = 0
        for rows in self.input.execute():
            out = []
            for r in rows:
                if skipped < self.offset:
                    skipped += 1
                    continue
                if taken >= self.limit:
                    break
                out.append(r)
                taken += 1
            if out:
                yield out
            if taken >= self.limit:
                return


def run_batch(root: BatchExecutor) -> List[tuple]:
    """Collect a batch plan's full result."""
    return [r for rows in root.execute() for r in rows]
