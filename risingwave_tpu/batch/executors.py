"""Batch executors: one-shot vectorized query operators over snapshots.

Counterpart of the reference's batch engine
(reference: src/batch/src/executor/ — RowSeqScan over vnode-partitioned
StorageTable ranges, Filter/Project/HashAgg/HashJoin/Sort/TopN/Limit…;
src/batch/src/task/task_manager.rs:42 fire_task). Where the reference
streams row batches through pull-based executors, the TPU design moves
DEVICE CHUNKS through the operator chain: a scan materializes the table's
rows into fixed-capacity chunks once (the host-decode edge), and every
downstream operator — filter, project, hash agg, hash join — is a jitted
device computation over those chunks. Rows reappear only at the
presentation edge (sort/limit/output), which is output-sized, not
input-sized.

The hash agg reuses the streaming engine's AggCore (one scatter-reduce
kernel, shared with stream/hash_agg.py); the hash join is a one-shot
build-and-gather over a DeviceHashTable (reference:
src/batch/src/executor/join/hash_join.rs). The join requires UNIQUE build
keys (the TPC-H shapes: joins against a pk side); duplicate build keys
raise ``BatchFallback`` and the session re-runs the SELECT through the
streaming fold, which handles arbitrary multiplicity.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import (
    Column, StreamChunk, chunk_to_rows, physical_chunk,
)
from ..common.hashing import VNODE_COUNT, vnode_of
from ..common.types import Field, Schema
from ..expr.agg import AggCall
from ..expr.expr import Expr, uses_host_callback
from ..ops.grouped_agg import AggCore
from ..ops.hash_table import (
    ht_lookup, ht_lookup_or_insert, ht_new, scatter_reduce,
)
from ..ops.topn import OrderSpec
from ..storage.state_table import StateTable


class BatchFallback(Exception):
    """Raised at run time when a plan shape needs the streaming fold
    (e.g. duplicate build keys in a batch hash join)."""


def _bucket_capacity(n: int) -> int:
    """Round a row count up to a power-of-two chunk capacity (min 16):
    tail chunks otherwise carry their exact row count as the device
    shape, and every distinct snapshot size forces a fresh XLA compile
    of every downstream jitted step — fatal for the serving plane, where
    cached plans re-execute against a growing table. Bucketing bounds
    the shape set to O(log n); padded rows ride invisible."""
    cap = 16
    while cap < n:
        cap *= 2
    return cap


class BatchExecutor:
    schema: Schema

    def execute_chunks(self) -> Iterator[StreamChunk]:
        """Yields device chunks (visibility-masked)."""
        raise NotImplementedError

    def execute(self) -> Iterator[List[tuple]]:
        """Row view (physical tuples) — the presentation edge."""
        for chunk in self.execute_chunks():
            rows = chunk_to_rows(chunk, self.schema, physical=True)
            if rows:
                yield rows


class _SingleInput(BatchExecutor):
    def __init__(self, input: BatchExecutor):
        self.input = input
        self.schema = input.schema


class RowSeqScan(BatchExecutor):
    """Full / vnode-partitioned snapshot scan over a StateTable
    (reference: row_seq_scan.rs — scan ranges are vnode partitions so
    parallel tasks split the key space). The one host-decode edge: rows
    become device chunks here and stay on device through the plan."""

    def __init__(self, table: StateTable,
                 vnodes: Optional[Sequence[int]] = None,
                 batch_size: int = 4096,
                 prefix: Optional[Sequence] = None):
        """``prefix``: values of the first len(prefix) pk columns —
        restricts the scan to that sorted-key range (the index point
        lookup path; reference: row_seq_scan.rs scan_range)."""
        self.table = table
        self.schema = table.schema
        self.vnodes = None if vnodes is None else sorted(set(vnodes))
        self.batch_size = batch_size
        self.prefix = None if prefix is None else list(prefix)

    def execute_chunks(self):
        buf: List[tuple] = []
        it = (self.table.scan_all() if self.prefix is None
              else self.table.scan_prefix(self.prefix, len(self.prefix)))
        for row in it:
            buf.append(row)
            if len(buf) >= self.batch_size:
                yield self._chunk(buf)
                buf = []
        if buf:
            yield self._chunk(buf)

    def _chunk(self, rows: List[tuple]) -> StreamChunk:
        chunk = physical_chunk(self.schema, rows,
                               _bucket_capacity(len(rows)))
        if self.vnodes is None:
            return chunk
        # device vnode mask over the pk columns — the same hash the
        # streaming shuffle uses, so batch partitions line up with shards
        pk_cols = [chunk.columns[i] for i in self.table.pk_indices]
        vn = vnode_of(pk_cols)
        sel = jnp.zeros(VNODE_COUNT, jnp.bool_).at[
            jnp.asarray(self.vnodes, jnp.int32)].set(True)
        return chunk.with_vis(chunk.vis & sel[vn])


class BatchRows(BatchExecutor):
    """Physical rows from a provider callable — the session-side face of
    a remote batch stage (the provider runs the worker task)."""

    def __init__(self, schema: Schema, provider, batch_size: int = 4096):
        self.schema = schema
        self.provider = provider
        self.batch_size = batch_size

    def execute_chunks(self):
        rows = self.provider()
        for i in range(0, len(rows), self.batch_size):
            part = rows[i:i + self.batch_size]
            yield physical_chunk(self.schema, part,
                                 _bucket_capacity(len(part)))


class BatchFilter(_SingleInput):
    def __init__(self, input: BatchExecutor, predicate: Expr):
        super().__init__(input)
        self.predicate = predicate

        def _step(chunk: StreamChunk) -> StreamChunk:
            cond = predicate.eval(chunk)
            return chunk.with_vis(chunk.vis & cond.data & cond.mask)

        self._step = _step if uses_host_callback(predicate) \
            else jax.jit(_step)

    def execute_chunks(self):
        for chunk in self.input.execute_chunks():
            yield self._step(chunk)


class BatchProject(_SingleInput):
    def __init__(self, input: BatchExecutor, exprs: Sequence[Expr],
                 names: Sequence[str] = ()):
        super().__init__(input)
        self.exprs = list(exprs)
        names = tuple(names) or tuple(f"expr{i}" for i in range(len(exprs)))
        self.schema = Schema(tuple(
            Field(n, e.type) for n, e in zip(names, self.exprs)))

        def _step(chunk: StreamChunk) -> StreamChunk:
            cols = tuple(e.eval(chunk) for e in self.exprs)
            return chunk.with_columns(cols)

        self._step = _step if any(uses_host_callback(e) for e in exprs) \
            else jax.jit(_step)

    def execute_chunks(self):
        for chunk in self.input.execute_chunks():
            yield self._step(chunk)


class BatchHashAgg(_SingleInput):
    """One-shot grouped/global aggregation — the streaming AggCore's
    scatter-reduce kernel applied over the whole snapshot, then one
    output materialization of the (small) group set."""

    def __init__(self, input: BatchExecutor, group_keys: Sequence[int],
                 agg_calls: Sequence[AggCall],
                 table_capacity: int = 1 << 16):
        super().__init__(input)
        self.group_keys = tuple(group_keys)
        self.agg_calls = tuple(agg_calls)
        fields = tuple(input.schema[i] for i in self.group_keys) + tuple(
            Field(f"agg{i}", a.output_type)
            for i, a in enumerate(self.agg_calls))
        self.schema = Schema(fields)
        self.capacity = table_capacity
        self._needs_ranks = any(c.is_string_minmax for c in self.agg_calls)
        if self.group_keys:
            key_types = tuple(
                input.schema[i].type for i in self.group_keys)
            self.core = AggCore(key_types, self.group_keys, self.agg_calls,
                                table_capacity, out_capacity=1024)
            self._apply = jax.jit(self.core.apply_chunk)
        else:
            # global agg: scalar lanes folded per chunk (the streaming
            # SimpleAgg's lane algebra, one reduction per chunk)
            from ..stream.simple_agg import _AggLanes
            self.lanes_def = _AggLanes(self.agg_calls)

            def _fold(lanes, chunk, str_ranks=None):
                deltas = self.lanes_def.chunk_deltas(chunk, str_ranks)
                return self.lanes_def.merge(lanes, deltas, str_ranks)

            self._fold = jax.jit(_fold)

    def _ranks(self):
        if not self._needs_ranks:
            return None
        from ..common.types import GLOBAL_STRING_DICT
        return GLOBAL_STRING_DICT.device_ranks()

    def execute_chunks(self):
        if not self.group_keys:
            lanes = self.lanes_def.init_lanes()
            for chunk in self.input.execute_chunks():
                lanes = self._fold(lanes, chunk, self._ranks())
            # one row always, even over empty input (count()=0, others
            # NULL — PG semantics, matching the streaming SimpleAgg)
            outs = self.lanes_def.outputs(lanes)
            cols = tuple(
                Column(jnp.asarray(d).reshape(1),
                       jnp.asarray(m).reshape(1))
                for d, m in outs)
            yield StreamChunk(jnp.zeros(1, jnp.int8),
                              jnp.ones(1, jnp.bool_), cols)
            return
        state = self.core.init_state()
        for chunk in self.input.execute_chunks():
            state = self._apply(state, chunk, self._ranks())
        if bool(state.overflow):
            raise BatchFallback(
                f"batch agg table overflow (capacity {self.capacity})")
        live = np.asarray(state.table.occupied & (state.lanes[0] > 0))
        idx = np.nonzero(live)[0]
        if not len(idx):
            return
        outs = self.core.outputs(state.lanes)
        key_data = [np.asarray(kd)[idx] for kd in state.table.key_data]
        key_mask = [np.asarray(km)[idx] for km in state.table.key_mask]
        out_data = [np.asarray(d)[idx] for d, _ in outs]
        out_mask = [np.asarray(m)[idx] for _, m in outs]
        n = len(idx)
        cols = tuple(
            Column(jnp.asarray(d), jnp.asarray(m))
            for d, m in zip(key_data + out_data, key_mask + out_mask))
        yield StreamChunk(jnp.zeros(n, jnp.int8),
                          jnp.ones(n, jnp.bool_), cols)


def partial_agg_fields(input_schema: Schema, group_keys: Sequence[int],
                       agg_calls: Sequence[AggCall]) -> tuple:
    """Transport schema of a PARTIAL grouped agg: group-key fields, the
    row-count lane, then one field per agg state lane (the AggCore lane
    layout flattened into columns). Lane transport types: int64/float64
    lanes ride INT64/FLOAT64; string MIN/MAX lanes ride the arg's VARCHAR
    type so the row codec re-interns dictionary ids across processes.
    MIN/MAX lanes are NULL when the group saw no value (the on-device
    sentinel never crosses the wire)."""
    from ..common.types import FLOAT64, INT64
    fields = [input_schema[i] for i in group_keys]
    fields.append(Field("_rows", INT64))
    for i, c in enumerate(agg_calls):
        for j, dt in enumerate(c.state_dtypes()):
            if c.is_string_minmax:
                t = c.arg_type
            elif np.dtype(dt) == np.dtype(np.int64):
                t = INT64
            else:
                t = FLOAT64
            fields.append(Field(f"_p{i}_{j}", t))
    return tuple(fields)


def partial_supported(group_keys: Sequence[int],
                      agg_calls: Sequence[AggCall]) -> bool:
    """True when a grouped agg can split into partial + merge phases:
    every call's state is fixed lanes merging by add/min/max (count, sum,
    min, max, avg-as-sum+count, approx_count_distinct registers)."""
    return bool(group_keys) and all(
        not c.lanes_unsupported for c in agg_calls)


class BatchPartialAgg(_SingleInput):
    """Phase 1 of the two-phase distributed aggregation: the same
    AggCore scatter-reduce fold BatchHashAgg runs, but emitting the raw
    per-group STATE LANES instead of projected outputs — one row per
    live group in ``partial_agg_fields`` transport layout. Runs where
    the vnode slice lives (a local vnode-partitioned task thread or a
    worker's ``batch_task`` frame); ``BatchMergeAgg`` in the session
    folds any number of partial row sets into the exact single-phase
    state (reference: the partial/final agg split of
    src/frontend/src/scheduler/distributed/query.rs:69-115)."""

    def __init__(self, input: BatchExecutor, group_keys: Sequence[int],
                 agg_calls: Sequence[AggCall],
                 table_capacity: int = 1 << 16):
        super().__init__(input)
        self.group_keys = tuple(group_keys)
        self.agg_calls = tuple(agg_calls)
        if not partial_supported(self.group_keys, self.agg_calls):
            raise BatchFallback("agg shape has no partial/merge split")
        self.schema = Schema(partial_agg_fields(
            input.schema, self.group_keys, self.agg_calls))
        self.capacity = table_capacity
        key_types = tuple(input.schema[i].type for i in self.group_keys)
        self.core = AggCore(key_types, self.group_keys, self.agg_calls,
                            table_capacity, out_capacity=1024)
        self._apply = jax.jit(self.core.apply_chunk)
        self._needs_ranks = any(c.is_string_minmax for c in self.agg_calls)

    def _ranks(self):
        if not self._needs_ranks:
            return None
        from ..common.types import GLOBAL_STRING_DICT
        return GLOBAL_STRING_DICT.device_ranks()

    def execute_chunks(self):
        state = self.core.init_state()
        for chunk in self.input.execute_chunks():
            state = self._apply(state, chunk, self._ranks())
        if bool(state.overflow):
            raise BatchFallback(
                f"partial agg table overflow (capacity {self.capacity})")
        live = np.asarray(state.table.occupied & (state.lanes[0] > 0))
        idx = np.nonzero(live)[0]
        if not len(idx):
            return
        n = len(idx)
        cols = []
        for kd, km in zip(state.table.key_data, state.table.key_mask):
            cols.append(Column(jnp.asarray(np.asarray(kd)[idx]),
                               jnp.asarray(np.asarray(km)[idx])))
        ones = np.ones(n, np.bool_)
        cols.append(Column(jnp.asarray(np.asarray(state.lanes[0])[idx]),
                           jnp.asarray(ones)))
        for call, ofs in zip(self.agg_calls, self.core.call_lane_ofs):
            for j in range(call.num_lanes):
                lane = np.asarray(state.lanes[ofs + j])[idx]
                if call.kind in ("min", "max"):
                    sent = call._minmax_sentinel()
                    if call._integral_arg() or call.is_string_minmax:
                        valid = lane != sent
                    else:
                        valid = np.isfinite(lane)
                    data = np.where(valid, lane, 0)
                    if call.is_string_minmax:
                        data = data.astype(call.arg_type.np_dtype)
                    cols.append(Column(jnp.asarray(data),
                                       jnp.asarray(valid)))
                else:
                    cols.append(Column(jnp.asarray(lane),
                                       jnp.asarray(ones)))
        yield StreamChunk(jnp.zeros(n, jnp.int8), jnp.ones(n, jnp.bool_),
                          tuple(cols))


class BatchMergeAgg(_SingleInput):
    """Phase 2: fold partial-state rows (``partial_agg_fields`` layout,
    any number of upstream tasks concatenated) back into one AggCore
    state with each lane's own reduce op — add for counts/sums/avg,
    min/max in packed rank|id space for string MIN/MAX, register-max for
    HLL — then project outputs EXACTLY like the single-phase
    BatchHashAgg. Lane merging is associative and the vnode slices are
    disjoint, so the merged state is bit-identical to the single-phase
    fold for every exactly-represented lane (all-integer lanes always;
    float sums up to f64 addition order)."""

    def __init__(self, input: BatchExecutor, key_types: Sequence,
                 agg_calls: Sequence[AggCall],
                 table_capacity: int = 1 << 16):
        super().__init__(input)
        self.key_types = tuple(key_types)
        self.agg_calls = tuple(agg_calls)
        nk = len(self.key_types)
        self.nk = nk
        self.core = AggCore(self.key_types, tuple(range(nk)),
                            self.agg_calls, table_capacity,
                            out_capacity=1024)
        fields = tuple(
            Field(input.schema[i].name, self.key_types[i])
            for i in range(nk)) + tuple(
            Field(f"agg{i}", a.output_type)
            for i, a in enumerate(self.agg_calls))
        self.schema = Schema(fields)
        self.capacity = table_capacity
        self._needs_ranks = any(c.is_string_minmax for c in self.agg_calls)

        def _merge(state, chunk, str_ranks=None):
            key_cols = [chunk.columns[i] for i in range(nk)]
            table, slots, _is_new, ovf = ht_lookup_or_insert(
                state.table, key_cols, chunk.vis)
            lanes = list(state.lanes)
            c0 = chunk.columns[nk]
            lanes[0] = scatter_reduce(
                lanes[0], slots,
                jnp.where(chunk.vis, c0.data, 0), "add")
            pos = nk + 1
            for call, ofs in zip(self.agg_calls, self.core.call_lane_ofs):
                for j, op in enumerate(call.reduce_ops()):
                    col = chunk.columns[pos]
                    pos += 1
                    have = chunk.vis & col.mask
                    lane = lanes[ofs + j]
                    if op == "add":
                        contrib = jnp.where(have, col.data, 0)
                        lanes[ofs + j] = scatter_reduce(
                            lane, slots, contrib, "add")
                        continue
                    if call.kind in ("min", "max"):
                        ident = call._minmax_sentinel()
                    else:            # HLL registers: max over rho >= 0
                        ident = 0
                    v = jnp.where(have, col.data.astype(lane.dtype), ident)
                    if call.is_string_minmax:
                        cur = call.pack_lane(lane, str_ranks)
                        vv = call.pack_lane(v, str_ranks)
                        lanes[ofs + j] = call.unpack_lane(
                            scatter_reduce(cur, slots, vv, op))
                    else:
                        lanes[ofs + j] = scatter_reduce(lane, slots, v, op)
            return state.replace(table=table, lanes=tuple(lanes),
                                 overflow=state.overflow | ovf)

        self._merge = jax.jit(_merge)

    def _ranks(self):
        if not self._needs_ranks:
            return None
        from ..common.types import GLOBAL_STRING_DICT
        return GLOBAL_STRING_DICT.device_ranks()

    def execute_chunks(self):
        state = self.core.init_state()
        for chunk in self.input.execute_chunks():
            state = self._merge(state, chunk, self._ranks())
        if bool(state.overflow):
            raise BatchFallback(
                f"merge agg table overflow (capacity {self.capacity})")
        live = np.asarray(state.table.occupied & (state.lanes[0] > 0))
        idx = np.nonzero(live)[0]
        if not len(idx):
            return
        outs = self.core.outputs(state.lanes)
        key_data = [np.asarray(kd)[idx] for kd in state.table.key_data]
        key_mask = [np.asarray(km)[idx] for km in state.table.key_mask]
        out_data = [np.asarray(d)[idx] for d, _ in outs]
        out_mask = [np.asarray(m)[idx] for _, m in outs]
        n = len(idx)
        cols = tuple(
            Column(jnp.asarray(d), jnp.asarray(m))
            for d, m in zip(key_data + out_data, key_mask + out_mask))
        yield StreamChunk(jnp.zeros(n, jnp.int8),
                          jnp.ones(n, jnp.bool_), cols)


class BatchHashJoin(BatchExecutor):
    """One-shot hash join over every join shape (reference:
    src/batch/src/executor/join/hash_join.rs — inner / left / right /
    full outer / left semi / left anti).

    Two build layouts, both fully jitted device steps:

    * **unique** (W=1): build columns scatter into [cap] slot arrays —
      the TPC-H q3/q10 shape joining against a pk side; probe is a
      gather.
    * **bucketed** (W>1): duplicate-keyed build sides store up to W rows
      per key in [cap·W] lanes; probes gather all W candidates and emit
      an N·W expansion with a validity mask — the same dense-lane bet the
      streaming join arena makes, amortized once for the whole query.
      W starts small and the build retries at 8× on overflow before
      giving up to the streaming fold (BatchFallback).

    RIGHT joins run as probe-side-outer with the sides swapped; FULL
    outer additionally tracks per-build-lane matched flags during the
    probe and emits unmatched build rows in a tail pass."""

    MAX_BUCKET_W = 512

    def __init__(self, left: BatchExecutor, right: BatchExecutor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 join_type: str = "inner",
                 condition: Optional[Expr] = None,
                 table_capacity: int = 1 << 16,
                 prefer_build: str = "right",
                 null_aware: bool = False):
        if join_type not in ("inner", "left", "right", "full",
                             "left_semi", "left_anti"):
            raise BatchFallback(f"batch join type {join_type!r}")
        self.left, self.right = left, right
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)
        self.join_type = join_type
        self.condition = condition
        #: PG NOT IN: a NULL on the build (subquery) side means no probe
        #: row passes the anti join at all (planner.py _plan_in_subquery)
        self.null_aware = null_aware and join_type == "left_anti"
        self.capacity = table_capacity
        # plan-time hint (pk covers the join key ⇒ provably unique):
        # avoids a wasted trial build; probe-side-outer shapes fix the
        # build side (right joins build LEFT and probe right)
        if join_type == "inner":
            self.prefer_build = prefer_build
        elif join_type == "right":
            self.prefer_build = "left"
        else:
            self.prefer_build = "right"
        if join_type in ("left_semi", "left_anti"):
            self.schema = Schema(tuple(left.schema))
        else:
            self.schema = Schema(tuple(left.schema) + tuple(right.schema))
        self._eager = condition is not None and uses_host_callback(condition)
        self._steps = {}    # (swapped, W) -> (build_step, probe_step)

    #: total build lanes (cap·W) held on device per trial build
    LANE_BUDGET = 1 << 22

    def _cap_for(self, W: int) -> int:
        cap = min(self.capacity, max(1024, self.LANE_BUDGET // W))
        # round down to a power of two (hash table requirement)
        p = 1
        while p * 2 <= cap:
            p *= 2
        return p

    def _mk_steps(self, swapped: bool, W: int):
        key = (swapped, W)
        if key in self._steps:
            return self._steps[key]
        build_keys = self.left_keys if swapped else self.right_keys
        probe_keys = self.right_keys if swapped else self.left_keys
        cap = self._cap_for(W)
        cond = self.condition
        join_type = self.join_type
        probe_outer = join_type in ("left", "right", "full")

        def _build_step(table, counts, cols_acc, masks_acc, chunk):
            key_cols = [chunk.columns[i] for i in build_keys]
            # SQL semantics: NULL join keys never match (the streaming
            # join enforces the same) — null-keyed build rows are skipped
            keyed = chunk.vis
            for c in key_cols:
                keyed = keyed & c.mask
            table, slots, _is_new, ovf = ht_lookup_or_insert(
                table, key_cols, keyed)
            n = slots.shape[0]
            # occurrence ordinal among this chunk's earlier same-key rows
            # ([N,N] comparison — MXU-friendly dense form, one-shot cost)
            same = ((slots[:, None] == slots[None, :])
                    & keyed[:, None] & keyed[None, :])
            lower = jnp.tril(jnp.ones((n, n), jnp.bool_), -1)
            occ = jnp.sum(same & lower, axis=1).astype(jnp.int32)
            pos = counts[jnp.clip(slots, 0, cap - 1)] + occ
            lane_over = jnp.any(keyed & (pos >= W))
            idx = jnp.where(keyed & (pos < W), slots * W + pos, cap * W)
            cols_acc = tuple(
                acc.at[idx].set(c.data, mode="drop")
                for acc, c in zip(cols_acc, chunk.columns))
            masks_acc = tuple(
                acc.at[idx].set(c.mask, mode="drop")
                for acc, c in zip(masks_acc, chunk.columns))
            counts = counts.at[jnp.where(keyed, slots, cap)].add(
                keyed.astype(jnp.int32), mode="drop")
            return table, counts, cols_acc, masks_acc, lane_over | ovf

        def _probe_step(table, counts, cols_acc, masks_acc, matched,
                        chunk):
            key_cols = [chunk.columns[i] for i in probe_keys]
            keyed = chunk.vis
            for c in key_cols:
                keyed = keyed & c.mask
            slots, found = ht_lookup(table, key_cols, keyed)
            found = found & keyed          # NULL probe keys never match
            safe = jnp.clip(slots, 0, cap - 1)
            n = found.shape[0]
            lanes = (safe[:, None] * W
                     + jnp.arange(W, dtype=jnp.int32)[None, :])
            flat = lanes.reshape(n * W)
            cnt = counts[safe]
            cand = ((jnp.arange(W, dtype=jnp.int32)[None, :] < cnt[:, None])
                    & found[:, None]).reshape(n * W)
            vis_rep = jnp.repeat(chunk.vis, W)
            ops_rep = jnp.repeat(chunk.ops, W)
            bcols = tuple(
                Column(acc[flat], m[flat] & cand)
                for acc, m in zip(cols_acc, masks_acc))
            pcols = tuple(
                Column(jnp.repeat(c.data, W), jnp.repeat(c.mask, W))
                for c in chunk.columns)
            # columns in schema order (left ++ right) regardless of the
            # built side — the condition indexes into it
            all_cols = (bcols + pcols) if swapped else (pcols + bcols)
            wide = StreamChunk(ops_rep, vis_rep, all_cols)
            if cond is not None:
                c = cond.eval(wide)
                match = cand & c.data & c.mask
            else:
                match = cand
            match = match & vis_rep
            row_any = jnp.any(match.reshape(n, W), axis=1)
            lane0 = (jnp.arange(n * W, dtype=jnp.int32) % W) == 0
            midx = jnp.where(match, flat, cap * W)
            matched = matched.at[midx].set(True, mode="drop")
            if join_type in ("left_semi", "left_anti"):
                keep = jnp.repeat(
                    row_any if join_type == "left_semi" else
                    ~row_any, W)
                out = StreamChunk(ops_rep, vis_rep & lane0 & keep, pcols)
            elif probe_outer:
                # pad rows (no surviving candidate) must carry NULL build
                # columns — masking with `cand` alone leaks values when a
                # key matched but the non-equi condition rejected it
                pad = lane0 & jnp.repeat(~row_any, W)
                b_nulled = tuple(
                    Column(c.data, c.mask & match) for c in bcols)
                all2 = ((b_nulled + pcols) if swapped
                        else (pcols + b_nulled))
                out = StreamChunk(ops_rep, match | (vis_rep & pad), all2)
            else:
                out = wide.with_vis(match)
            return out, matched

        def _tail_step(counts, cols_acc, masks_acc, matched):
            # FULL outer: occupied-but-unmatched build lanes with NULL
            # probe columns
            lane_no = jnp.arange(cap * W, dtype=jnp.int32) % W
            occupied = lane_no < jnp.repeat(counts, W)
            vis = occupied & ~matched
            bcols = tuple(Column(acc, m & vis)
                          for acc, m in zip(cols_acc, masks_acc))
            return vis, bcols

        trio = ((_build_step, _probe_step, _tail_step) if self._eager
                else (jax.jit(_build_step), jax.jit(_probe_step),
                      jax.jit(_tail_step)))
        self._steps[key] = trio
        return trio

    def _try_build(self, side: BatchExecutor, swapped: bool, W: int,
                   chunks: list):
        build_keys = self.left_keys if swapped else self.right_keys
        key_types = tuple(side.schema[i].type for i in build_keys)
        build_step, _, _ = self._mk_steps(swapped, W)
        cap = self._cap_for(W)
        table = ht_new(key_types, cap)
        counts = jnp.zeros(cap, jnp.int32)
        cols_acc = tuple(
            jnp.zeros(cap * W, f.type.dtype) for f in side.schema)
        masks_acc = tuple(
            jnp.zeros(cap * W, jnp.bool_) for _ in side.schema)
        bad = jnp.zeros((), jnp.bool_)
        for chunk in chunks:
            table, counts, cols_acc, masks_acc, step_bad = build_step(
                table, counts, cols_acc, masks_acc, chunk)
            bad = bad | step_bad
        if bool(bad):
            return None
        return table, counts, cols_acc, masks_acc

    def execute_chunks(self):
        swapped = self.prefer_build == "left"
        build_side = self.left if swapped else self.right
        build_chunks = list(build_side.execute_chunks())
        built = None
        W = 1
        # W=1 is the unique fast path; duplicates escalate the bucket
        # width (shrinking table capacity to hold the lane budget)
        while built is None and W <= self.MAX_BUCKET_W:
            built = self._try_build(build_side, swapped, W, build_chunks)
            if built is None:
                W *= 8
        if built is None:
            raise BatchFallback(
                "batch hash join build side exceeds the bucket budget "
                f"(> {self.MAX_BUCKET_W} rows per key or too many keys); "
                "falling back to the streaming join")
        table, counts, cols_acc, masks_acc = built
        if self.null_aware:
            # NOT IN semantics: any null-keyed build row poisons the
            # whole anti join — x <> NULL is unknown for every x, so PG
            # returns zero rows. One host sync over the (already
            # materialized) build chunks, taken before they are freed.
            build_keys = self.left_keys if swapped else self.right_keys
            for chunk in build_chunks:
                keyed = chunk.vis
                for i in build_keys:
                    keyed = keyed & chunk.columns[i].mask
                if bool(jnp.any(chunk.vis & ~keyed)):
                    return
        null_keyed = []
        if self.join_type == "full":
            # null-keyed build rows never match (skipped by the build),
            # but FULL outer must still emit them with NULL probe columns
            build_keys = self.left_keys if swapped else self.right_keys
            probe_schema = (self.right.schema if swapped
                            else self.left.schema)
            for chunk in build_chunks:
                unkeyed = chunk.vis
                keyed = chunk.vis
                for i in build_keys:
                    keyed = keyed & chunk.columns[i].mask
                unkeyed = unkeyed & ~keyed
                if bool(jnp.any(unkeyed)):
                    nulls = tuple(
                        Column(jnp.zeros(chunk.capacity, f.type.dtype),
                               jnp.zeros(chunk.capacity, jnp.bool_))
                        for f in probe_schema)
                    cols = ((tuple(chunk.columns) + nulls) if swapped
                            else (nulls + tuple(chunk.columns)))
                    null_keyed.append(
                        StreamChunk(chunk.ops, unkeyed, cols))
        del build_chunks          # scattered into cols_acc; free the copy
        _, probe_step, tail_step = self._mk_steps(swapped, W)
        cap = self._cap_for(W)
        matched = jnp.zeros(cap * W, jnp.bool_)
        probe_side = self.right if swapped else self.left
        for chunk in probe_side.execute_chunks():
            out, matched = probe_step(table, counts, cols_acc, masks_acc,
                                      matched, chunk)
            yield out
        if self.join_type == "full":
            yield from null_keyed
            vis, bcols = tail_step(counts, cols_acc, masks_acc, matched)
            # the NULL-padded side is the PROBE side
            probe_schema = (self.right.schema if swapped
                            else self.left.schema)
            piece = 1 << 16
            total = cap * W
            for lo in range(0, total, piece):
                hi = min(lo + piece, total)
                pv = vis[lo:hi]
                if not bool(jnp.any(pv)):
                    continue
                pb = tuple(Column(c.data[lo:hi], c.mask[lo:hi])
                           for c in bcols)
                nulls = tuple(
                    Column(jnp.zeros(hi - lo, f.type.dtype),
                           jnp.zeros(hi - lo, jnp.bool_))
                    for f in probe_schema)
                cols = (pb + nulls) if swapped else (nulls + pb)
                yield StreamChunk(jnp.zeros(hi - lo, jnp.int8),
                                  pv, cols)


def _host_order_key(t):
    """Host-side orderable key for one physical value of type ``t``:
    identity for numerics, dictionary-rank lookup for VARCHAR/BYTEA (raw
    ids are insertion-ordered and must never feed an ordering op)."""
    if t is None or not t.is_string:
        return lambda v: v
    from ..common.types import GLOBAL_STRING_DICT
    ranks = GLOBAL_STRING_DICT.ranks()
    return lambda v: int(ranks[v])


class BatchSort(_SingleInput):
    """Presentation edge: output-sized host sort over the row view."""

    def __init__(self, input: BatchExecutor, order: Sequence[OrderSpec]):
        super().__init__(input)
        self.order = list(order)

    def execute_chunks(self):  # pragma: no cover - row-based operator
        raise NotImplementedError("BatchSort is a row-edge operator")

    def execute(self):
        allrows = [r for rows in self.input.execute() for r in rows]
        keyfns = [_host_order_key(self.input.schema[s.col].type)
                  for s in self.order]

        def key(row):
            k = []
            for spec, kf in zip(self.order, keyfns):
                v = row[spec.col]
                null_rank = 1 if spec.nulls_last else -1
                k.append((null_rank, 0) if v is None
                         else (0, -kf(v) if spec.desc else kf(v)))
            return tuple(k)

        allrows.sort(key=key)
        if allrows:
            yield allrows


class BatchLimit(_SingleInput):
    def __init__(self, input: BatchExecutor, limit: int, offset: int = 0):
        super().__init__(input)
        self.limit = limit
        self.offset = offset

    def execute_chunks(self):  # pragma: no cover - row-based operator
        raise NotImplementedError("BatchLimit is a row-edge operator")

    def execute(self):
        skipped = taken = 0
        for rows in self.input.execute():
            out = []
            for r in rows:
                if skipped < self.offset:
                    skipped += 1
                    continue
                if taken >= self.limit:
                    break
                out.append(r)
                taken += 1
            if out:
                yield out
            if taken >= self.limit:
                return


def run_batch(root: BatchExecutor) -> List[tuple]:
    """Collect a batch plan's full result."""
    return [r for rows in root.execute() for r in rows]
