from .executors import (  # noqa: F401
    BatchFilter, BatchHashAgg, BatchLimit, BatchMergeAgg, BatchPartialAgg,
    BatchProject, BatchSort, RowSeqScan, partial_agg_fields,
    partial_supported, run_batch,
)
from .task import BatchTaskManager, vnode_partitions  # noqa: F401
