from .executors import (  # noqa: F401
    BatchFilter, BatchHashAgg, BatchLimit, BatchProject, BatchSort,
    RowSeqScan, run_batch,
)
from .task import BatchTaskManager  # noqa: F401
