"""Plan lowering: stream plan tree → batch executor chain.

Counterpart of the reference's to_batch optimizer phase
(reference: src/frontend/src/optimizer/mod.rs — the same logical plan
lowers to either stream or batch physical operators). ``lower_plan``
returns None for shapes only the streaming engine supports (EOWC,
DISTINCT aggs, WITH TIES, window functions — those SELECTs keep using
the session's stream-fold path), so it is always safe to try. Joined
SELECTs lower to the one-shot BatchHashJoin; joins it cannot serve
(non-unique build keys, outer-right shapes) raise BatchFallback at run
time and the session re-runs through the streaming fold."""

from __future__ import annotations

from typing import Optional

from ..frontend import planner as P
from ..storage.state_table import StateTable
from .executors import (
    BatchExecutor, BatchFilter, BatchHashAgg, BatchHashJoin, BatchLimit,
    BatchProject, BatchSort, RowSeqScan,
)


def lower_plan(plan: P.PlanNode, store) -> Optional[BatchExecutor]:
    if isinstance(plan, (P.PTableScan, P.PMvScan)):
        d = plan.table if isinstance(plan, P.PTableScan) else plan.mv
        return RowSeqScan(StateTable(store, d.table_id, d.schema,
                                     list(d.pk)))
    if isinstance(plan, P.PProject):
        inp = lower_plan(plan.input, store)
        if inp is None:
            return None
        return BatchProject(inp, list(plan.exprs), names=plan.schema.names)
    if isinstance(plan, P.PFilter):
        inp = lower_plan(plan.input, store)
        if inp is None:
            return None
        return BatchFilter(inp, plan.predicate)
    if isinstance(plan, P.PAgg):
        if plan.eowc or any(c.distinct for c in plan.agg_calls):
            return None
        inp = lower_plan(plan.input, store)
        if inp is None:
            return None
        return BatchHashAgg(inp, list(plan.group_keys),
                            list(plan.agg_calls))
    if isinstance(plan, P.PJoin):
        if plan.kind not in ("inner", "left"):
            return None
        left = lower_plan(plan.left, store)
        right = lower_plan(plan.right, store)
        if left is None or right is None:
            return None
        # pick the build side STATICALLY when pk metadata proves
        # uniqueness, so no trial build is wasted at run time (the
        # runtime dup check stays as a safety net)
        r_unique = bool(plan.right.pk) and \
            set(plan.right.pk) <= set(plan.right_keys)
        l_unique = bool(plan.left.pk) and \
            set(plan.left.pk) <= set(plan.left_keys)
        prefer = ("left" if (plan.kind == "inner"
                             and not r_unique and l_unique)
                  else "right")
        return BatchHashJoin(left, right, list(plan.left_keys),
                             list(plan.right_keys), join_type=plan.kind,
                             condition=plan.condition,
                             prefer_build=prefer)
    if isinstance(plan, P.PTopN):
        if plan.with_ties or plan.group_by:
            return None
        inp = lower_plan(plan.input, store)
        if inp is None:
            return None
        return BatchLimit(BatchSort(inp, list(plan.order)),
                          limit=plan.limit, offset=plan.offset)
    return None
