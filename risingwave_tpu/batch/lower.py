"""Plan lowering: stream plan tree → batch executor chain.

Counterpart of the reference's to_batch optimizer phase
(reference: src/frontend/src/optimizer/mod.rs — the same logical plan
lowers to either stream or batch physical operators). ``lower_plan``
returns None for shapes only the streaming engine supports (EOWC,
DISTINCT aggs, WITH TIES, window functions — those SELECTs keep using
the session's stream-fold path), so it is always safe to try. Joined
SELECTs lower to the one-shot BatchHashJoin; joins it cannot serve
(non-unique build keys, outer-right shapes) raise BatchFallback at run
time and the session re-runs through the streaming fold."""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..expr.expr import FunctionCall, InputRef, Literal
from ..frontend import planner as P
from ..storage.state_table import StateTable
from .executors import (
    BatchExecutor, BatchFilter, BatchHashAgg, BatchHashJoin, BatchLimit,
    BatchMergeAgg, BatchPartialAgg, BatchProject, BatchSort, RowSeqScan,
    partial_agg_fields, partial_supported,
)


def _index_scan(plan: P.PFilter, catalog, store) -> Optional[BatchExecutor]:
    """Filter-over-scan with constant equality on an index prefix →
    prefix scan of the index arrangement (reference: the index-selection
    rule, src/frontend/src/optimizer/rule/index_selection_rule.rs scaled
    to equality prefixes). Returns executor in BASE schema order with the
    full predicate re-applied (harmless superset filtering)."""
    base = plan.input
    # column pruning may interpose a pure-InputRef projection over the
    # scan; compose its column mapping instead of giving up
    mapping = None
    if (isinstance(base, P.PProject)
            and all(isinstance(e, InputRef) for e in base.exprs)
            and isinstance(base.input, (P.PTableScan, P.PMvScan))):
        mapping = [e.index for e in base.exprs]
        base = base.input
    if not isinstance(base, (P.PTableScan, P.PMvScan)):
        return None
    d = base.table if isinstance(base, P.PTableScan) else base.mv
    if getattr(d, "n_visible", len(d.schema)) != len(d.schema):
        return None                       # hidden cols: mapping unsafe
    if mapping is None:
        mapping = list(range(len(d.schema)))
    # constant-equality conjuncts: BASE col idx -> literal value
    from ..frontend.optimizer import conjuncts_of
    eq: dict = {}
    for c in conjuncts_of(plan.predicate):
        if (isinstance(c, FunctionCall) and c.name == "equal"
                and len(c.args) == 2):
            a, b = c.args
            if isinstance(a, Literal) and isinstance(b, InputRef):
                a, b = b, a
            if (isinstance(a, InputRef) and isinstance(b, Literal)
                    and b.value is not None):
                eq.setdefault(mapping[a.index], b)
    if not eq:
        return None
    col_names = [f.name for f in d.schema]
    base_name = getattr(d, "name", None)
    best = None
    for ix in catalog.indexes.values():
        if ix.table != base_name or not ix.mv_name:
            continue
        mv = catalog.mvs.get(ix.mv_name)
        if mv is None:
            continue
        # how many leading index columns are equality-bound?
        vals = []
        for cname in ix.columns:
            pos = col_names.index(cname)
            if pos in eq:
                vals.append(eq[pos])
            else:
                break
        if vals and (best is None or len(vals) > len(best[1])):
            best = (mv, vals, ix)
    if best is None:
        return None
    mv, lits, ix = best
    prefix = [lit.type.to_physical(lit.value) for lit in lits]
    scan = RowSeqScan(StateTable(store, mv.table_id, mv.schema,
                                 list(mv.pk)), prefix=prefix)
    # permute index-MV columns into the filter's INPUT order — the base
    # scan's schema through the (possibly pruned) projection mapping
    mv_names = [f.name for f in mv.schema]
    exprs = [InputRef(mv_names.index(col_names[bi]),
                      d.schema[bi].type) for bi in mapping]
    proj = BatchProject(scan, exprs,
                        names=[col_names[bi] for bi in mapping])
    return BatchFilter(proj, plan.predicate)


def lower_plan(plan: P.PlanNode, store,
               catalog=None, vnodes=None) -> Optional[BatchExecutor]:
    """``vnodes``: restrict every base scan to this vnode slice — the
    per-task restriction of the two-phase serving plane (a worker's
    ``batch_task`` frame or a local partitioned task carries its slice
    here; reference: per-task vnode bitmaps in the distributed batch
    scheduler)."""
    if isinstance(plan, (P.PTableScan, P.PMvScan)):
        d = plan.table if isinstance(plan, P.PTableScan) else plan.mv
        return RowSeqScan(StateTable(store, d.table_id, d.schema,
                                     list(d.pk)), vnodes=vnodes)
    if isinstance(plan, P.PRemoteFragment):
        from .executors import BatchRows
        return BatchRows(plan.schema, plan.fetch)
    if isinstance(plan, P.PProject):
        inp = lower_plan(plan.input, store, catalog, vnodes)
        if inp is None:
            return None
        return BatchProject(inp, list(plan.exprs), names=plan.schema.names)
    if isinstance(plan, P.PFilter):
        if catalog is not None and vnodes is None:
            ix = _index_scan(plan, catalog, store)
            if ix is not None:
                return ix
        inp = lower_plan(plan.input, store, catalog, vnodes)
        if inp is None:
            return None
        return BatchFilter(inp, plan.predicate)
    if isinstance(plan, P.PAgg):
        if vnodes is not None and plan.phase != "partial":
            # a single-phase agg over one slice computes per-slice
            # groups; unioning slices would duplicate them — only
            # PARTIAL aggs (whose outputs are merge-folded) may run
            # under a vnode restriction
            return None
        if plan.eowc or any(c.distinct for c in plan.agg_calls):
            return None
        inp = lower_plan(plan.input, store, catalog, vnodes)
        if inp is None:
            return None
        if plan.phase == "partial":
            if not partial_supported(plan.group_keys, plan.agg_calls):
                return None
            return BatchPartialAgg(inp, list(plan.group_keys),
                                   list(plan.agg_calls))
        return BatchHashAgg(inp, list(plan.group_keys),
                            list(plan.agg_calls))
    if isinstance(plan, P.PJoin):
        if vnodes is not None:
            # a vnode slice partitions by the BASE table's key — joins and
            # limits over a slice would drop cross-slice matches; only
            # slice-safe chains (scan/filter/project/partial-agg) may run
            # per slice
            return None
        if plan.kind not in ("inner", "left", "right", "full",
                             "left_semi", "left_anti"):
            return None
        left = lower_plan(plan.left, store, catalog)
        right = lower_plan(plan.right, store, catalog)
        if left is None or right is None:
            return None
        # pick the build side STATICALLY when pk metadata proves
        # uniqueness, so no trial build is wasted at run time (the
        # runtime dup check stays as a safety net)
        r_unique = bool(plan.right.pk) and \
            set(plan.right.pk) <= set(plan.right_keys)
        l_unique = bool(plan.left.pk) and \
            set(plan.left.pk) <= set(plan.left_keys)
        prefer = ("left" if (plan.kind == "inner"
                             and not r_unique and l_unique)
                  else "right")
        return BatchHashJoin(left, right, list(plan.left_keys),
                             list(plan.right_keys), join_type=plan.kind,
                             condition=plan.condition,
                             prefer_build=prefer,
                             null_aware=getattr(plan, "null_aware", False))
    if isinstance(plan, P.PTopN):
        if vnodes is not None:
            return None               # a sliced top-n is not the top-n
        if plan.with_ties or plan.group_by:
            return None
        inp = lower_plan(plan.input, store, catalog)
        if inp is None:
            return None
        return BatchLimit(BatchSort(inp, list(plan.order)),
                          limit=plan.limit, offset=plan.offset)
    return None


# -- two-phase split (the distributed serving plane's planner half) ----------

@dataclasses.dataclass
class TwoPhaseSplit:
    """A grouped-agg plan split into shippable halves.

    ``partial_plan``: the PAgg(phase="partial") subtree over the original
    input chain — lowering it (optionally with a ``vnodes`` slice) yields
    a task emitting partial-state rows in ``partial_schema`` layout.
    ``merge_input_schema``/``key_types``/``agg_calls`` parameterize the
    session-side BatchMergeAgg; ``tail`` is the row-wise chain that sat
    ABOVE the agg (projections / HAVING filters), re-applied over the
    merged output in original order."""

    partial_plan: P.PAgg
    partial_schema: object
    key_types: tuple
    agg_calls: tuple
    base: P.PlanNode                  # the scan leaf under the agg input
    tail: tuple                       # (PProject | PFilter) nodes, top→down

    def merge_executor(self, partial_rows_provider,
                       batch_size: int = 4096) -> BatchExecutor:
        """Session-side tail of the split: BatchRows over the collected
        partial rows → BatchMergeAgg → the original row-wise tail."""
        from .executors import BatchRows
        ex: BatchExecutor = BatchMergeAgg(
            BatchRows(self.partial_schema, partial_rows_provider,
                      batch_size=batch_size),
            self.key_types, self.agg_calls)
        for node in reversed(self.tail):
            if isinstance(node, P.PProject):
                ex = BatchProject(ex, list(node.exprs),
                                  names=node.schema.names)
            else:
                ex = BatchFilter(ex, node.predicate)
        return ex


def _slice_safe(node: P.PlanNode) -> bool:
    """True when ``node`` is a chain of row-wise operators over exactly
    one base scan — running it per disjoint vnode slice and unioning the
    outputs equals running it once."""
    while isinstance(node, (P.PProject, P.PFilter)):
        node = node.input
    return isinstance(node, (P.PTableScan, P.PMvScan))


def split_two_phase(plan: P.PlanNode) -> Optional[TwoPhaseSplit]:
    """Split ``plan`` into per-vnode-slice partial agg tasks + a final
    session-side merge, when it has the shape
    ``[Project|Filter]* → HashAgg → [Project|Filter]* → Scan`` with
    lane-mergeable agg calls. Returns None for every other shape (the
    caller keeps the single-phase path)."""
    from ..common.types import Schema
    tail = []
    node = plan
    while isinstance(node, (P.PProject, P.PFilter)):
        tail.append(node)
        node = node.input
    if not isinstance(node, P.PAgg) or node.phase != "single":
        return None
    if node.eowc or not partial_supported(node.group_keys, node.agg_calls):
        return None
    if not _slice_safe(node.input):
        return None
    fields = partial_agg_fields(node.input.schema, node.group_keys,
                                node.agg_calls)
    pschema = Schema(fields)
    nk = len(node.group_keys)
    partial = dataclasses.replace(
        node, phase="partial", schema=pschema, pk=tuple(range(nk)))
    base = node.input
    while isinstance(base, (P.PProject, P.PFilter)):
        base = base.input
    key_types = tuple(node.input.schema[i].type for i in node.group_keys)
    return TwoPhaseSplit(partial_plan=partial, partial_schema=pschema,
                         key_types=key_types,
                         agg_calls=tuple(node.agg_calls),
                         base=base, tail=tuple(tail))
