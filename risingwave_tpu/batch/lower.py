"""Plan lowering: stream plan tree → batch executor chain.

Counterpart of the reference's to_batch optimizer phase
(reference: src/frontend/src/optimizer/mod.rs — the same logical plan
lowers to either stream or batch physical operators). ``lower_plan``
returns None for shapes only the streaming engine supports (EOWC,
DISTINCT aggs, WITH TIES, window functions — those SELECTs keep using
the session's stream-fold path), so it is always safe to try. Joined
SELECTs lower to the one-shot BatchHashJoin; joins it cannot serve
(non-unique build keys, outer-right shapes) raise BatchFallback at run
time and the session re-runs through the streaming fold."""

from __future__ import annotations

from typing import Optional

from ..expr.expr import FunctionCall, InputRef, Literal
from ..frontend import planner as P
from ..storage.state_table import StateTable
from .executors import (
    BatchExecutor, BatchFilter, BatchHashAgg, BatchHashJoin, BatchLimit,
    BatchProject, BatchSort, RowSeqScan,
)


def _index_scan(plan: P.PFilter, catalog, store) -> Optional[BatchExecutor]:
    """Filter-over-scan with constant equality on an index prefix →
    prefix scan of the index arrangement (reference: the index-selection
    rule, src/frontend/src/optimizer/rule/index_selection_rule.rs scaled
    to equality prefixes). Returns executor in BASE schema order with the
    full predicate re-applied (harmless superset filtering)."""
    base = plan.input
    # column pruning may interpose a pure-InputRef projection over the
    # scan; compose its column mapping instead of giving up
    mapping = None
    if (isinstance(base, P.PProject)
            and all(isinstance(e, InputRef) for e in base.exprs)
            and isinstance(base.input, (P.PTableScan, P.PMvScan))):
        mapping = [e.index for e in base.exprs]
        base = base.input
    if not isinstance(base, (P.PTableScan, P.PMvScan)):
        return None
    d = base.table if isinstance(base, P.PTableScan) else base.mv
    if getattr(d, "n_visible", len(d.schema)) != len(d.schema):
        return None                       # hidden cols: mapping unsafe
    if mapping is None:
        mapping = list(range(len(d.schema)))
    # constant-equality conjuncts: BASE col idx -> literal value
    from ..frontend.optimizer import conjuncts_of
    eq: dict = {}
    for c in conjuncts_of(plan.predicate):
        if (isinstance(c, FunctionCall) and c.name == "equal"
                and len(c.args) == 2):
            a, b = c.args
            if isinstance(a, Literal) and isinstance(b, InputRef):
                a, b = b, a
            if (isinstance(a, InputRef) and isinstance(b, Literal)
                    and b.value is not None):
                eq.setdefault(mapping[a.index], b)
    if not eq:
        return None
    col_names = [f.name for f in d.schema]
    base_name = getattr(d, "name", None)
    best = None
    for ix in catalog.indexes.values():
        if ix.table != base_name or not ix.mv_name:
            continue
        mv = catalog.mvs.get(ix.mv_name)
        if mv is None:
            continue
        # how many leading index columns are equality-bound?
        vals = []
        for cname in ix.columns:
            pos = col_names.index(cname)
            if pos in eq:
                vals.append(eq[pos])
            else:
                break
        if vals and (best is None or len(vals) > len(best[1])):
            best = (mv, vals, ix)
    if best is None:
        return None
    mv, lits, ix = best
    prefix = [lit.type.to_physical(lit.value) for lit in lits]
    scan = RowSeqScan(StateTable(store, mv.table_id, mv.schema,
                                 list(mv.pk)), prefix=prefix)
    # permute index-MV columns into the filter's INPUT order — the base
    # scan's schema through the (possibly pruned) projection mapping
    mv_names = [f.name for f in mv.schema]
    exprs = [InputRef(mv_names.index(col_names[bi]),
                      d.schema[bi].type) for bi in mapping]
    proj = BatchProject(scan, exprs,
                        names=[col_names[bi] for bi in mapping])
    return BatchFilter(proj, plan.predicate)


def lower_plan(plan: P.PlanNode, store,
               catalog=None) -> Optional[BatchExecutor]:
    if isinstance(plan, (P.PTableScan, P.PMvScan)):
        d = plan.table if isinstance(plan, P.PTableScan) else plan.mv
        return RowSeqScan(StateTable(store, d.table_id, d.schema,
                                     list(d.pk)))
    if isinstance(plan, P.PRemoteFragment):
        from .executors import BatchRows
        return BatchRows(plan.schema, plan.fetch)
    if isinstance(plan, P.PProject):
        inp = lower_plan(plan.input, store, catalog)
        if inp is None:
            return None
        return BatchProject(inp, list(plan.exprs), names=plan.schema.names)
    if isinstance(plan, P.PFilter):
        if catalog is not None:
            ix = _index_scan(plan, catalog, store)
            if ix is not None:
                return ix
        inp = lower_plan(plan.input, store, catalog)
        if inp is None:
            return None
        return BatchFilter(inp, plan.predicate)
    if isinstance(plan, P.PAgg):
        if plan.eowc or any(c.distinct for c in plan.agg_calls):
            return None
        inp = lower_plan(plan.input, store, catalog)
        if inp is None:
            return None
        return BatchHashAgg(inp, list(plan.group_keys),
                            list(plan.agg_calls))
    if isinstance(plan, P.PJoin):
        if plan.kind not in ("inner", "left", "right", "full",
                             "left_semi", "left_anti"):
            return None
        left = lower_plan(plan.left, store, catalog)
        right = lower_plan(plan.right, store, catalog)
        if left is None or right is None:
            return None
        # pick the build side STATICALLY when pk metadata proves
        # uniqueness, so no trial build is wasted at run time (the
        # runtime dup check stays as a safety net)
        r_unique = bool(plan.right.pk) and \
            set(plan.right.pk) <= set(plan.right_keys)
        l_unique = bool(plan.left.pk) and \
            set(plan.left.pk) <= set(plan.left_keys)
        prefer = ("left" if (plan.kind == "inner"
                             and not r_unique and l_unique)
                  else "right")
        return BatchHashJoin(left, right, list(plan.left_keys),
                             list(plan.right_keys), join_type=plan.kind,
                             condition=plan.condition,
                             prefer_build=prefer,
                             null_aware=getattr(plan, "null_aware", False))
    if isinstance(plan, P.PTopN):
        if plan.with_ties or plan.group_by:
            return None
        inp = lower_plan(plan.input, store, catalog)
        if inp is None:
            return None
        return BatchLimit(BatchSort(inp, list(plan.order)),
                          limit=plan.limit, offset=plan.offset)
    return None
