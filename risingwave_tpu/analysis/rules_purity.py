"""Deep checkers no grep can express: dispatch-discipline and
trace-purity.

dispatch-discipline is the static twin of the runtime
``common/dispatch_count.py`` guard: the whole performance story of the
fused epochs (docs/performance.md) is ONE dispatch per epoch, and the
ways to silently break it are host↔device transfers
(``jax.device_get``, ``.item()``, ``np.asarray``, scalar coercion) or
a nested ``jax.jit`` inside a function reachable from the epoch-builder
registries. The runtime guard only sees paths a test happened to
execute; this rule covers the full static closure.

trace-purity guards determinism: a ``time.time()`` / ``random.*`` call
or a mutable default argument inside a jit/vmap/shard_map-traced
function is baked in at trace time — the replayable chaos plane and the
bit-exactness contracts (solo vs co-scheduled vs sharded) both rest on
traced code being pure.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from .callgraph import Func, FunctionIndex, build_index
from .core import Finding, Module, Package, Rule, register

PKG = "risingwave_tpu"

#: the epoch-builder registries the one-dispatch invariant hangs off
REGISTRIES = (
    ("ops/fused_epoch.py", "EPOCH_BUILDERS"),
    ("ops/fused_sharded.py", "SHARDED_EPOCH_BUILDERS"),
    ("ops/fused_hetero.py", "HETERO_EPOCH_BUILDERS"),
)

#: builders outside the registries that still own a one-dispatch
#: surface: the co-scheduled multi-job epochs (stream/coschedule.py
#: resolves them directly, not via a registry dict)
EXTRA_BUILDERS = (
    ("ops/fused_multi.py", "fused_multi_agg_epoch"),
    ("ops/fused_multi.py", "fused_multi_join_epoch"),
    ("ops/fused_multi.py", "build_group_epoch"),
)

_JIT_WRAPPERS = {"jax.jit", "jax.pmap"}
_TRACE_WRAPPERS = {
    "jax.jit", "jax.pmap", "jax.vmap",
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.pallas.pallas_call",
    f"{PKG}.parallel.sharded_agg.shard_map_compat",
}


def _callee_qn(package: Package, mod: Module,
               node: ast.Call) -> Optional[str]:
    return package.canonical(mod.imports.resolve(node.func))


def registry_builders(package: Package, index: FunctionIndex
                      ) -> Dict[str, Dict[str, Func]]:
    """Statically parse the two builder registries: registry name ->
    {surface key -> builder Func}. The acceptance contract is that
    this sees EXACTLY the entries the runtime dicts hold —
    tests/test_rwlint.py cross-checks it against the imported
    registries, so a builder added to the dict without lint coverage
    fails the tier-1 wiring test."""
    out: Dict[str, Dict[str, Func]] = {}
    for rel, reg_name in REGISTRIES:
        mod = package.module(rel)
        if mod is None:
            continue
        entry: Dict[str, Func] = {}
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Dict):
                continue
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if reg_name not in names:
                continue
            for k, v in zip(node.value.keys, node.value.values):
                if not isinstance(k, ast.Constant):
                    continue
                fn = index.lookup(mod.imports.resolve_or_local(v)) \
                    if v is not None else None
                if fn is not None:
                    entry[str(k.value)] = fn
        out[reg_name] = entry
    extra: Dict[str, Func] = {}
    for rel, name in EXTRA_BUILDERS:
        mod = package.module(rel)
        if mod is None:
            continue
        fn = index.by_qualname.get(f"{mod.qualname}.{name}")
        if fn is not None:
            extra[name] = fn
    out["COSCHEDULED_BUILDERS"] = extra
    return out


def _device_region(package: Package, index: FunctionIndex,
                   builders: List[Func]) -> Set[Func]:
    """Everything reachable from the builders, except the builder
    bodies themselves (they run at build time on the host and own the
    ONE legitimate ``jax.jit`` call), plus every ``lax.scan`` body in
    ops/ (scan bodies are traced even when a registry does not reach
    them yet)."""
    region = index.reachable(builders) - set(builders)
    for rel, mod in package.modules.items():
        if not rel.startswith("ops/"):
            continue
        for node in mod.walk():
            if isinstance(node, ast.Call) and \
                    _callee_qn(package, mod, node) == "jax.lax.scan" \
                    and node.args:
                owner = _enclosing_func(index, mod, node)
                if owner is None:
                    continue
                for fn in index.resolve_ref(owner, node.args[0]):
                    region |= index.reachable([fn])
    return region


def _enclosing_func(index: FunctionIndex, mod: Module,
                    node: ast.AST) -> Optional[Func]:
    best: Optional[Func] = None
    for fn in index.by_qualname.values():
        if fn.module is not mod:
            continue
        n = fn.node
        if n.lineno <= node.lineno <= (n.end_lineno or n.lineno):
            if best is None or n.lineno > best.node.lineno:
                best = fn
    return best


@register
class DispatchDiscipline(Rule):
    name = "dispatch-discipline"
    title = "no host transfer / nested jit reachable from epoch builders"
    ci_label = "dispatch-discipline"
    doc = """The fused-epoch contract (PRs 4/6/7, docs/performance.md)
is ONE XLA dispatch per epoch; the runtime dispatch_count guard checks
it on executed paths only. This rule walks the static closure of every
function reachable from EPOCH_BUILDERS / SHARDED_EPOCH_BUILDERS (plus
every lax.scan body in ops/) and flags the constructs that smuggle a
host round-trip or a second dispatch into the traced region:
``jax.device_get`` / ``jax.device_put``, ``.block_until_ready()``,
``np.asarray``, ``.item()``, ``int()/float()`` on an indexed/attribute
device value, and nested ``jax.jit``/``jax.pmap``. Coverage is
cross-checked against the runtime registries by the wiring test."""

    def coverage(self, package: Package) -> Dict[str, Dict[str, list]]:
        index = build_index(package)
        regs = registry_builders(package, index)
        out: Dict[str, Dict[str, list]] = {}
        for reg_name, entries in regs.items():
            out[reg_name] = {
                key: sorted(f.qualname
                            for f in index.reachable([fn]))
                for key, fn in entries.items()}
        return out

    def check(self, package: Package) -> Iterator[Finding]:
        index = build_index(package)
        regs = registry_builders(package, index)
        builders = [fn for entries in regs.values()
                    for fn in entries.values()]
        region = _device_region(package, index, builders)
        for fn in sorted(region, key=lambda f: f.qualname):
            yield from self._check_func(package, index, fn)

    def _check_func(self, package: Package, index: FunctionIndex,
                    fn: Func) -> Iterator[Finding]:
        mod = fn.module
        where = f"in {fn.qualname.removeprefix(PKG + '.')} " \
                "(reachable from the epoch-builder registries)"
        for node in index._own_body_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            qn = _callee_qn(package, mod, node)
            f = node.func
            if qn in ("jax.device_get", "jax.device_put"):
                yield self._f(mod, node,
                              f"host↔device transfer {qn}() {where}")
            elif qn in _JIT_WRAPPERS:
                yield self._f(mod, node,
                              f"nested {qn}() {where} — a second "
                              "dispatch inside the one-dispatch region")
            elif qn in ("numpy.asarray", "numpy.array"):
                yield self._f(mod, node,
                              f"{qn}() forces device→host "
                              f"materialization {where}")
            elif isinstance(f, ast.Attribute) and \
                    f.attr == "block_until_ready":
                yield self._f(mod, node,
                              f".block_until_ready() {where} — host "
                              "sync inside the traced region")
            elif isinstance(f, ast.Attribute) and f.attr == "item" \
                    and not node.args:
                yield self._f(mod, node,
                              f".item() pulls a device scalar {where}")
            elif isinstance(f, ast.Name) and f.id in ("int", "float") \
                    and len(node.args) == 1 and \
                    isinstance(node.args[0],
                               (ast.Subscript, ast.Attribute)):
                yield self._f(mod, node,
                              f"{f.id}() on an indexed/attribute value "
                              f"{where} — device-scalar coercion blocks "
                              "on the dispatch")

    def _f(self, mod: Module, node: ast.AST, msg: str) -> Finding:
        return Finding(self.name, mod.rel, node.lineno,
                       node.col_offset, msg)


@register
class TracePurity(Rule):
    name = "trace-purity"
    title = "no wall-clock/RNG/mutable-default capture in traced code"
    ci_label = "trace-purity"
    doc = """A function traced by jax.jit / vmap / pmap / shard_map
executes its Python body ONCE; a ``time.time()``, ``random.*`` or
``np.random.*`` call inside it bakes one sample into the compiled
artifact, and a mutable default argument is shared trace state. Both
silently break the determinism contracts: seeded chaos replay
(docs/robustness.md) and the solo/co-scheduled/sharded bit-exactness
pins. Device-side randomness belongs to ``jax.random`` with threaded
keys; wall-clock belongs outside the epoch and rides in as data."""

    _IMPURE_PREFIXES = ("random.", "numpy.random.")
    _IMPURE_CALLS = {
        "time.time", "time.monotonic", "time.perf_counter",
        "time.time_ns", "time.monotonic_ns",
        "datetime.datetime.now", "datetime.datetime.utcnow",
    }

    def check(self, package: Package) -> Iterator[Finding]:
        # Purity is a closure property, same as dispatch-discipline: an
        # impure call one helper away from the traced root is frozen at
        # trace time exactly as if it were inline, so we walk the full
        # static reachability of every traced root, not just its
        # lexically nested defs.
        index = build_index(package)
        seen: Set[Func] = set()
        for root in self._traced_roots(package, index):
            for fn in index.reachable([root]):
                if fn in seen:
                    continue
                seen.add(fn)
                yield from self._check_func(package, index, fn)

    def _traced_roots(self, package: Package,
                      index: FunctionIndex) -> List[Func]:
        roots: List[Func] = []
        for fn in index.by_qualname.values():
            mod = fn.module
            for dec in getattr(fn.node, "decorator_list", ()):
                target = dec.func if isinstance(dec, ast.Call) else dec
                qn = package.canonical(mod.imports.resolve(target))
                if qn in _TRACE_WRAPPERS:
                    roots.append(fn)
                elif qn == "functools.partial" and \
                        isinstance(dec, ast.Call) and dec.args and \
                        package.canonical(
                            mod.imports.resolve(dec.args[0])
                        ) in _TRACE_WRAPPERS:
                    # @functools.partial(jax.jit, static_argnames=...)
                    roots.append(fn)
        for rel, mod in package.modules.items():
            for node in mod.walk():
                if not isinstance(node, ast.Call):
                    continue
                if _callee_qn(package, mod, node) not in _TRACE_WRAPPERS:
                    continue
                for arg in node.args[:1]:
                    owner = _enclosing_func(index, mod, node)
                    if owner is not None:
                        roots.extend(index.resolve_ref(owner, arg))
                    else:
                        hit = index.lookup(
                            mod.imports.resolve_or_local(arg))
                        if hit is not None:
                            roots.append(hit)
        return roots

    def _check_func(self, package: Package, index: FunctionIndex,
                    fn: Func) -> Iterator[Finding]:
        mod = fn.module
        short = fn.qualname.removeprefix(PKG + ".")
        args = fn.node.args
        for default in list(args.defaults) + \
                [d for d in args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                yield Finding(
                    self.name, mod.rel, default.lineno,
                    default.col_offset,
                    f"mutable default argument on traced function "
                    f"{short} — shared state is captured at trace time")
        for node in index._own_body_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            qn = _callee_qn(package, mod, node)
            if qn is None:
                continue
            impure = qn in self._IMPURE_CALLS or \
                any(qn.startswith(p) for p in self._IMPURE_PREFIXES)
            if impure:
                yield Finding(
                    self.name, mod.rel, node.lineno, node.col_offset,
                    f"impure call {qn}() inside traced function {short} "
                    "— the sample/time is frozen at trace time (use "
                    "jax.random with threaded keys, or pass the value "
                    "in as data)")
