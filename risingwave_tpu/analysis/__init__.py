"""rwlint — AST-grounded invariant checker for the dispatch, barrier,
and boundary planes.

Run it as ``python -m risingwave_tpu.analysis`` (CI alias:
``scripts/rwlint``). The rules, their rationale, and the suppression
pragma format are documented in docs/static-analysis.md; per-rule
rationale is also available via ``--explain RULE``.

Programmatic surface (used by tests/test_rwlint.py and scripts):

    from risingwave_tpu.analysis import lint_package
    findings, counts, package = lint_package()          # whole package
    findings, counts, package = lint_package("/some/pkg_root")
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, Optional, Tuple

from .core import (Finding, Package, Rule, RULES, all_rules,
                   load_package, register, run_rules)

__all__ = [
    "Finding", "Package", "Rule", "RULES", "all_rules", "register",
    "load_package", "run_rules", "lint_package", "package_root",
]


def package_root() -> Path:
    """The risingwave_tpu package directory this module ships inside."""
    return Path(__file__).resolve().parents[1]


def lint_package(root=None, rules: Optional[Iterable[Rule]] = None
                 ) -> Tuple[list, dict, Package]:
    """Lint ``root`` (default: the installed package) with ``rules``
    (default: all registered). Returns (findings, per-rule counts,
    the parsed Package)."""
    package = load_package(Path(root) if root is not None
                           else package_root())
    findings, counts = run_rules(package, rules)
    return findings, counts, package
