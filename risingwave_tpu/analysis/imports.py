"""Import/alias resolution — the piece that makes AST lints beat grep.

``from ..stream.dispatch import PermitChannel as PC`` binds the local
name ``PC`` to the qualified name
``risingwave_tpu.stream.dispatch.PermitChannel``; a grep for
``PermitChannel(`` never sees the ``PC(...)`` call, this resolver does.
Conversely a docstring that *mentions* the class never produces a
``Call`` node, so the alias-aware rule stays quiet where grep fired.

Resolution is purely static and per-module: an ``ImportMap`` maps local
names to dotted qualified names, and ``resolve()`` flattens a
``Name``/``Attribute`` chain through it. Cross-module re-export chains
are then collapsed by ``Package.canonical`` (core.py).
"""

from __future__ import annotations

import ast
from typing import Dict, Optional

__all__ = ["ImportMap", "dotted"]


def dotted(node: ast.AST) -> Optional[str]:
    """Flatten ``a.b.c`` Name/Attribute chains to ``"a.b.c"``."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


class ImportMap:
    """Local name -> fully qualified dotted name, for one module."""

    def __init__(self, module) -> None:
        self.module = module
        self.aliases: Dict[str, str] = {}
        # the module's own package ("a.b.c" -> package "a.b" for a
        # plain module, "a.b.c" itself for a package __init__)
        qn = module.qualname
        if module.rel.endswith("__init__.py"):
            self._pkg = qn
        else:
            self._pkg = qn.rpartition(".")[0]
        self._collect(module.tree)

    def _collect(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    self.aliases[local] = target
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(node)
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    self.aliases[local] = f"{base}.{a.name}" if base \
                        else a.name

    def _resolve_from(self, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module or ""
        # relative import: level 1 = current package, 2 = parent, ...
        parts = self._pkg.split(".") if self._pkg else []
        up = node.level - 1
        if up > len(parts):
            return None
        base_parts = parts[:len(parts) - up] if up else parts
        if node.module:
            base_parts = base_parts + node.module.split(".")
        return ".".join(base_parts)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Qualified dotted name for a Name/Attribute chain, through
        this module's import aliases; ``None`` if the head is not an
        imported/module-level name (e.g. a local variable)."""
        d = dotted(node)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        if head in self.aliases:
            base = self.aliases[head]
            return f"{base}.{rest}" if rest else base
        return None

    def resolve_or_local(self, node: ast.AST) -> Optional[str]:
        """Like resolve(), but a bare unimported head falls back to a
        name in the current module (module-level def/class/assign)."""
        qn = self.resolve(node)
        if qn is not None:
            return qn
        d = dotted(node)
        if d is None:
            return None
        return f"{self.module.qualname}.{d}"
