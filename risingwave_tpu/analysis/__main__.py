"""rwlint CLI: ``python -m risingwave_tpu.analysis [root] [flags]``.

Exit status 0 = every rule clean, 1 = findings, 2 = usage error.

``--ci`` prints the per-rule ``<rule> lint: OK`` lines scripts/check.sh
has always emitted (kept byte-compatible for the five migrated grep
lints so CI output stays diffable across the migration), ``--json``
emits the machine-readable report, ``--list-rules`` / ``--explain``
surface the registry and per-rule docs.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from . import all_rules, lint_package, RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="rwlint",
        description="AST-grounded invariant checker for the dispatch, "
                    "barrier, and boundary planes "
                    "(docs/static-analysis.md)")
    ap.add_argument("root", nargs="?", default=None,
                    help="package root to lint (default: the installed "
                         "risingwave_tpu package)")
    ap.add_argument("--ci", action="store_true",
                    help="per-rule OK lines, diffable CI output")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="NAME", help="run only this rule "
                    "(repeatable)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--explain", metavar="RULE", default=None,
                    help="print a rule's long-form rationale and exit")
    ap.add_argument("--coverage", action="store_true",
                    help="dump the dispatch-discipline reachability "
                         "closure per registry entry (JSON)")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:20s} {r.title}")
        return 0
    if args.explain is not None:
        r = RULES.get(args.explain)
        if r is None:
            print(f"rwlint: unknown rule {args.explain!r} "
                  f"(try --list-rules)", file=sys.stderr)
            return 2
        print(f"{r.name} — {r.title}\n")
        print(r.doc.strip())
        return 0
    if args.rule:
        unknown = [n for n in args.rule if n not in RULES]
        if unknown:
            print(f"rwlint: unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [RULES[n] for n in args.rule]

    t0 = time.monotonic()
    findings, counts, package = lint_package(args.root, rules)
    elapsed = time.monotonic() - t0

    if args.coverage:
        from .rules_purity import DispatchDiscipline
        print(json.dumps(
            DispatchDiscipline().coverage(package), indent=2))
        return 0

    if args.as_json:
        print(json.dumps({
            "ok": not findings,
            "files": len(package.modules),
            "elapsed_s": round(elapsed, 3),
            "rules": counts,
            "findings": [f.to_json() for f in findings],
        }, indent=2))
        return 1 if findings else 0

    for f in findings:
        print(f.render())
    if args.ci:
        for r in rules:
            if counts.get(r.name, 0) == 0:
                print(f"{r.ci_label or r.name} lint: OK")
    if findings:
        print(f"rwlint: {len(findings)} finding(s) across "
              f"{len({f.path for f in findings})} file(s) "
              f"[{len(package.modules)} files linted, {elapsed:.2f}s]")
        return 1
    if not args.ci:
        print(f"rwlint: OK ({len(rules)} rules, "
              f"{len(package.modules)} files, {elapsed:.2f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
