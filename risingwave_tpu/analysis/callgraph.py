"""Whole-package function index + conservative call graph.

The dispatch-discipline rule needs *reachability*: "no host transfer in
any function reachable from the ``EPOCH_BUILDERS`` registries" is a
closure property, not a per-line pattern. This module builds the index
once per lint run (``Package.shared``) and answers:

* which function object does this Name/Attribute refer to?
* what does function F reference (call OR pass as a value — a
  ``lax.scan(body, ...)`` body is reached without ever being "called"
  by name)?

Resolution is deliberately OVER-approximate: for ``obj.method(...)``
where ``obj``'s type is unknowable statically, we fall back to "every
class method with that bare name in the package" (minus a denylist of
jnp-array/builtin method names that would drag the whole package in).
For a lint, over-approximation errs toward flagging — the pragma system
absorbs the rare deliberate exception; silent non-coverage would rot
the invariant.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from .core import Module, Package

__all__ = ["Func", "FunctionIndex", "build_index"]

#: attribute-call names that are overwhelmingly jnp-array / builtin
#: container methods — method-name fallback on these would connect
#: epoch bodies to unrelated package classes (e.g. a meta-store
#: ``set``) and poison reachability.
_FALLBACK_DENY = {
    "set", "get", "add", "pop", "update", "items", "keys", "values",
    "append", "extend", "remove", "clear", "copy", "astype", "reshape",
    "sum", "min", "max", "mean", "any", "all", "take", "dot", "ravel",
    "flatten", "squeeze", "transpose", "clip", "round", "cumsum",
    "sort", "argsort", "nonzero", "tolist", "view", "item", "at",
    "block_until_ready", "join", "split", "format", "strip", "read",
    "write", "close", "encode", "decode", "startswith", "endswith",
}

#: unknown-receiver method fallback is restricted to the device-plane
#: subtree: the core/state classes an epoch body dispatches into
#: (AggCore, Q3Core, hash tables, Expr.eval ...) all live under ops/
#: and expr/. Without the restriction, a generic verb like ``.flush()``
#: inside an epoch body would edge into Session.flush and drag the
#: whole frontend into the "traced" region.
_FALLBACK_SCOPES = ("ops/", "expr/")

#: externals we never index into (their attrs are not package funcs)
_EXTERNAL_HEADS = ("jax.", "numpy.", "functools.", "math.", "os.",
                   "sys.", "typing.", "collections.", "itertools.",
                   "threading.", "time.", "asyncio.", "json.", "struct.",
                   "socket.", "contextlib.", "dataclasses.")


class Func:
    """One function/method/nested-def in the package."""

    __slots__ = ("qualname", "module", "node", "cls", "parent", "nested")

    def __init__(self, qualname: str, module: Module, node: ast.AST,
                 cls: Optional[str], parent: Optional["Func"]):
        self.qualname = qualname
        self.module = module
        self.node = node
        self.cls = cls            # bare class name if a method
        self.parent = parent
        self.nested: List["Func"] = []

    @property
    def name(self) -> str:
        return self.node.name

    def __repr__(self) -> str:
        return f"<Func {self.qualname}>"


class FunctionIndex:
    def __init__(self, package: Package):
        self.package = package
        self.by_qualname: Dict[str, Func] = {}
        self.methods_by_name: Dict[str, List[Func]] = {}
        self._edges: Dict[str, Set[str]] = {}
        for mod in package.modules.values():
            self._index_module(mod)

    # -- construction -----------------------------------------------------

    def _index_module(self, mod: Module) -> None:
        def visit(node: ast.AST, prefix: str, cls: Optional[str],
                  parent: Optional[Func]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qn = f"{prefix}.{child.name}"
                    fn = Func(qn, mod, child, cls, parent)
                    self.by_qualname[qn] = fn
                    if cls is not None and parent is None:
                        self.methods_by_name.setdefault(
                            child.name, []).append(fn)
                    if parent is not None:
                        parent.nested.append(fn)
                    visit(child, qn, None, fn)
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}.{child.name}",
                          child.name, None)
                elif not isinstance(child, (ast.Lambda,)):
                    visit(child, prefix, cls, parent)

        visit(mod.tree, mod.qualname, None, None)

    # -- lookup -----------------------------------------------------------

    def lookup(self, qualname: Optional[str]) -> Optional[Func]:
        if qualname is None:
            return None
        return self.by_qualname.get(
            self.package.canonical(qualname) or qualname)

    def resolve_ref(self, func: Func, node: ast.AST) -> Set[Func]:
        """Funcs a Name/Attribute reference inside ``func`` may denote."""
        out: Set[Func] = set()
        mod = func.module
        if isinstance(node, ast.Name):
            # nested function in the lexical scope chain
            cur: Optional[Func] = func
            while cur is not None:
                for n in cur.nested:
                    if n.name == node.id:
                        return {n}
                cur = cur.parent
            hit = self.lookup(mod.imports.resolve_or_local(node))
            if hit is not None:
                out.add(hit)
            return out
        if isinstance(node, ast.Attribute):
            # self.method() -> same-class method, precisely
            if isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and func.cls is not None:
                for cand in self.methods_by_name.get(node.attr, []):
                    if cand.cls == func.cls and cand.module is mod:
                        return {cand}
            qn = mod.imports.resolve(node)
            if qn is not None:
                if qn.startswith(_EXTERNAL_HEADS):
                    return out
                hit = self.lookup(qn)
                if hit is not None:
                    out.add(hit)
                    return out
            # unknown receiver: bare-method-name fallback, device-plane
            # classes only (see _FALLBACK_SCOPES)
            if node.attr not in _FALLBACK_DENY:
                out.update(
                    cand for cand in self.methods_by_name.get(
                        node.attr, [])
                    if cand.module.rel.startswith(_FALLBACK_SCOPES))
            return out
        return out

    # -- edges / reachability ---------------------------------------------

    def references(self, func: Func) -> Set[Func]:
        """Every Func that ``func``'s body references — called OR
        passed as a value OR defined nested (over-approximation)."""
        cached = self._edges.get(func.qualname)
        if cached is not None:
            return {self.by_qualname[q] for q in cached}
        out: Set[Func] = set(func.nested)
        for node in self._own_body_walk(func):
            if isinstance(node, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ast.Load):
                out.update(self.resolve_ref(func, node))
        self._edges[func.qualname] = {f.qualname for f in out}
        return out

    def _own_body_walk(self, func: Func):
        """Walk func's body without descending into nested defs (they
        are separate Funcs, linked via ``nested``)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(func.node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def reachable(self, roots: Iterable[Func]) -> Set[Func]:
        seen: Set[Func] = set()
        stack = list(roots)
        while stack:
            f = stack.pop()
            if f in seen:
                continue
            seen.add(f)
            stack.extend(self.references(f) - seen)
        return seen


def build_index(package: Package) -> FunctionIndex:
    return package.shared("function_index", FunctionIndex)
