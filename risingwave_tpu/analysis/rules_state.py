"""State-protocol rules: seqlock-discipline and failpoint-honesty.

seqlock-discipline guards the serving plane's lock-free read path
(PR 8): optimistic readers in frontend/serving.py accept a scan only
when the same EVEN ``_data_version`` spans it, which is sound only if
every writer (a) bumps the version through the two bracket methods and
(b) leaves the odd section on EVERY exit path. A stray increment, or an
``_enter_mutation()`` whose exit is not in a ``finally``, breaks reader
correctness only under races/exceptions — exactly the bugs tests miss.

failpoint-honesty moves the declared⊇executed registry check from
test-time (the old TestFailpointRegistry grep in tests/test_net_faults
.py) to lint-time, and tightens it to declared==executed: a site added
in code but not declared is invisible to the crash-point sweep; a
declared site with no call site is sweep time wasted on a no-op.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import Finding, Module, Package, Rule, register

PKG = "risingwave_tpu"


@register
class SeqlockDiscipline(Rule):
    name = "seqlock-discipline"
    title = "Session seqlock mutated only via the bracket methods"
    ci_label = "seqlock-discipline"
    doc = """The data-version seqlock (frontend/session.py): EVEN =
stores quiescent, ODD = a mutation in flight. Serving readers
(frontend/serving.py) spin on it instead of taking the API lock. The
rule enforces, allowlist-driven: (1) ``_data_version`` /
``_mutation_depth`` are written ONLY inside __init__ /
_enter_mutation / _exit_mutation of Session; (2) any method calling
``_enter_mutation()`` pairs every call with an ``_exit_mutation()``
that sits in a ``finally`` block — an exception escaping the odd
section would otherwise wedge every optimistic reader forever; (3) no
module outside frontend/session.py writes either attribute."""

    SESSION = "frontend/session.py"
    GUARDED = {"_data_version", "_mutation_depth"}
    ALLOWED_METHODS = {"__init__", "_enter_mutation", "_exit_mutation"}

    def check(self, package: Package) -> Iterator[Finding]:
        for rel, mod in package.modules.items():
            yield from self._check_writes(mod, rel)
        sess = package.module(self.SESSION)
        if sess is not None:
            yield from self._check_balance(sess)

    # (1) + (3): direct writes to the seqlock words
    def _check_writes(self, mod: Module, rel: str) -> Iterator[Finding]:
        for node in mod.walk():
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if not (isinstance(t, ast.Attribute) and
                        t.attr in self.GUARDED):
                    continue
                meth = self._method_of(mod, node)
                if rel == self.SESSION and meth in self.ALLOWED_METHODS:
                    continue
                yield Finding(
                    self.name, mod.rel, node.lineno, node.col_offset,
                    f"write to seqlock word .{t.attr} outside the "
                    "bracket methods (_enter_mutation/_exit_mutation) "
                    "— readers infer quiescence from this word")

    # (2): every enter is covered by a finally'd exit. Counting
    # enters/exits per function is not enough — a balanced count says
    # nothing about WHICH finally protects WHICH enter, so a stray
    # try/finally elsewhere in the same method could launder an
    # unprotected odd section. Each enter is checked structurally: it
    # must sit inside a try whose finally exits, or be the statement
    # immediately before one (the canonical
    # ``_enter_mutation(); try: ... finally: _exit_mutation()`` idiom).
    def _check_balance(self, mod: Module) -> Iterator[Finding]:
        for node in mod.walk():
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name in self.ALLOWED_METHODS:
                continue
            enters = self._bracket_calls(node, "_enter_mutation")
            if not enters:
                continue
            parents = {child: parent for parent in ast.walk(node)
                       for child in ast.iter_child_nodes(parent)}
            for call in enters:
                if self._finally_protected(node, call, parents):
                    continue
                yield Finding(
                    self.name, mod.rel, call.lineno, call.col_offset,
                    f"{node.name}: _enter_mutation() not covered by an "
                    "_exit_mutation() in a finally (enclosing it or "
                    "immediately following it) — an exception escaping "
                    "the odd section leaves _data_version odd and every "
                    "optimistic reader spins forever")

    @classmethod
    def _finally_protected(cls, fn: ast.AST, call: ast.Call,
                           parents: Dict[ast.AST, ast.AST]) -> bool:
        # (a) the enter sits inside the BODY of a try whose finally
        # exits (finalbody/handlers/orelse don't count: an enter there
        # runs after/outside the protection)
        node: ast.AST = call
        stmt: Optional[ast.stmt] = None
        while node is not fn:
            parent = parents.get(node)
            if parent is None:
                break
            if stmt is None and isinstance(node, ast.stmt):
                stmt = node
            if isinstance(parent, ast.Try) and \
                    any(node is s for s in parent.body) and \
                    cls._exits_in(parent.finalbody):
                return True
            node = parent
        # (b) canonical idiom: the very next statement is a
        # try/finally that exits
        if stmt is None:
            return False
        holder = parents.get(stmt)
        for lst in cls._stmt_lists(holder):
            for i, s in enumerate(lst):
                if s is stmt:
                    nxt = lst[i + 1] if i + 1 < len(lst) else None
                    return isinstance(nxt, ast.Try) and \
                        cls._exits_in(nxt.finalbody)
        return False

    @staticmethod
    def _stmt_lists(holder: Optional[ast.AST]) -> List[List[ast.stmt]]:
        if holder is None:
            return []
        lists = []
        for attr in ("body", "orelse", "finalbody"):
            val = getattr(holder, attr, None)
            if isinstance(val, list):
                lists.append(val)
        return lists

    @classmethod
    def _exits_in(cls, stmts: List[ast.stmt]) -> bool:
        return any(cls._bracket_calls(s, "_exit_mutation")
                   for s in stmts)

    @staticmethod
    def _bracket_calls(fn: ast.AST, name: str) -> List[ast.Call]:
        out = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == name:
                out.append(node)
        return out

    @staticmethod
    def _method_of(mod: Module, node: ast.AST) -> Optional[str]:
        best: Optional[ast.AST] = None
        for fn in ast.walk(mod.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and fn.lineno <= node.lineno <= \
                    (fn.end_lineno or fn.lineno):
                if best is None or fn.lineno > best.lineno:
                    best = fn
        return best.name if best is not None else None


@register
class FailpointHonesty(Rule):
    name = "failpoint-honesty"
    title = "fail_point() sites == the declared registry"
    ci_label = "failpoint-honesty"
    doc = """The crash-point sweep (sim.py --sweep) and the chaos
plane's coverage claims iterate ``DECLARED_SITES`` in
common/failpoint.py; the sweep only proves what the registry names.
This rule equates the declared set with the set of ``fail_point("...")``
string literals in the package, both directions, at lint time: an
undeclared executed site is chaos coverage silently lost, a declared
never-executed site is a sweep slot that tests nothing. Dynamic
(non-literal) site names are flagged too — they defeat the whole
static accounting. Replaces the test-time regex check that lived in
tests/test_net_faults.py."""

    FAILPOINT_MOD = "common/failpoint.py"
    DECL_NAMES = ("DECLARED_SITES", "KNOWN_SITES")
    CALL = f"{PKG}.common.failpoint.fail_point"
    REGISTER = f"{PKG}.common.failpoint.register_site"

    def declared(self, package: Package
                 ) -> Tuple[Set[str], int, Optional[Module]]:
        mod = package.module(self.FAILPOINT_MOD)
        if mod is None:
            return set(), 0, None
        for node in mod.tree.body:
            names: List[str] = []
            if isinstance(node, ast.Assign):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                names, value = [node.target.id], node.value
            else:
                continue
            if not any(n in self.DECL_NAMES for n in names) or \
                    value is None:
                continue
            sites = {c.value for c in ast.walk(value)
                     if isinstance(c, ast.Constant) and
                     isinstance(c.value, str)}
            return sites, node.lineno, mod
        return set(), 0, mod

    def executed(self, package: Package
                 ) -> Tuple[Dict[str, Tuple[Module, ast.Call]],
                            List[Tuple[Module, ast.Call]]]:
        sites: Dict[str, Tuple[Module, ast.Call]] = {}
        dynamic: List[Tuple[Module, ast.Call]] = []
        for rel, mod in package.modules.items():
            if rel == self.FAILPOINT_MOD:
                continue
            for node in mod.walk():
                if not isinstance(node, ast.Call):
                    continue
                qn = package.canonical(
                    mod.imports.resolve_or_local(node.func))
                if qn not in (self.CALL, self.REGISTER):
                    continue
                # keyword form fail_point(name="x") counts the same as
                # positional — a site must not dodge the accounting by
                # calling convention
                values = list(node.args) + \
                    [kw.value for kw in node.keywords]
                for arg in values:
                    if isinstance(arg, ast.Constant) and \
                            isinstance(arg.value, str):
                        sites.setdefault(arg.value, (mod, node))
                    else:
                        dynamic.append((mod, node))
        return sites, dynamic

    def check(self, package: Package) -> Iterator[Finding]:
        declared, decl_line, decl_mod = self.declared(package)
        executed, dynamic = self.executed(package)
        if decl_mod is None:
            return
        if not declared:
            yield Finding(self.name, decl_mod.rel, 1, 0,
                          "no DECLARED_SITES/KNOWN_SITES literal found "
                          "in common/failpoint.py")
            return
        for mod, call in dynamic:
            yield Finding(
                self.name, mod.rel, call.lineno, call.col_offset,
                "non-literal failpoint site name — the crash-point "
                "sweep cannot account for dynamic sites")
        for site in sorted(set(executed) - declared):
            mod, call = executed[site]
            yield Finding(
                self.name, mod.rel, call.lineno, call.col_offset,
                f'failpoint site "{site}" is not in DECLARED_SITES '
                "(common/failpoint.py) — the crash-point sweep will "
                "never kill here")
        for site in sorted(declared - set(executed)):
            yield Finding(
                self.name, decl_mod.rel, decl_line, 0,
                f'declared failpoint site "{site}" has no '
                "fail_point() call site — stale registry entry wastes "
                "a sweep slot")
