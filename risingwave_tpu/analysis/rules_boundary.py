"""The five migrated boundary lints — AST-grounded replacements for the
grep blocks that used to live in scripts/check.sh.

Each rule here guards a subsystem *boundary*: a constructor or call
that must only appear inside the one module that owns the invariant.
The grep versions matched byte patterns, so they fired on docstrings
and comments (false positives) and went blind the moment anyone wrote
``from ..stream.dispatch import PermitChannel as PC`` (false
negatives). These match resolved call expressions: an alias is caught,
a mention in prose is not. tests/test_rwlint.py pins one
grep-beats-nothing case of each kind per rule.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Sequence, Set

from .core import Finding, Package, Rule, register

PKG = "risingwave_tpu"


def _call_sites(package: Package, *, targets: Set[str],
                exempt: Sequence[str] = ()):
    """Yield (module, call) for calls whose callee resolves — through
    import aliases and re-export chains — to one of ``targets``."""
    for rel, mod in package.modules.items():
        if rel in exempt:
            continue
        for node in mod.walk():
            if not isinstance(node, ast.Call):
                continue
            qn = package.canonical(
                mod.imports.resolve_or_local(node.func))
            if qn in targets:
                yield mod, node


@register
class ExchangeBoundary(Rule):
    name = "exchange-boundary"
    title = "PermitChannel constructed only inside the dispatch fabric"
    ci_label = "exchange-boundary"
    doc = """Every exchange edge must go through the dispatch fabric
(stream/dispatch.py open_channel / the frontend fragment builders). A
raw ``PermitChannel(...)`` anywhere else means a module wired its own
flow control outside the subsystem boundary — its frames would dodge
backpressure accounting and the chaos plane. Guards the PR-2 exchange
subsystem; replaces the check.sh grep that missed import aliases."""

    TARGET = f"{PKG}.stream.dispatch.PermitChannel"
    EXEMPT = ("stream/dispatch.py", "frontend/fragments.py")

    def check(self, package: Package) -> Iterator[Finding]:
        for mod, call in _call_sites(package, targets={self.TARGET},
                                     exempt=self.EXEMPT):
            yield Finding(self.name, mod.rel, call.lineno,
                          call.col_offset,
                          "raw PermitChannel construction outside the "
                          "dispatch fabric (use stream/dispatch."
                          "open_channel or the fragment builders)")


@register
class WireBoundary(Rule):
    name = "wire-boundary"
    title = "socket IO only inside rpc/wire.py (or the broker)"
    ci_label = "wire-boundary"
    doc = """Every internal RPC frame must flow through rpc/wire.py,
where the network fault plane's per-link FaultyTransport hooks live.
A ``.sendall(...)`` / socket ``.recv(...)`` call anywhere else is a
wire path chaos schedules cannot reach. connector/broker.py is exempt:
it is an EXTERNAL boundary with its own line protocol, hardened by the
PR-3 reconnect layer instead. The old grep matched only receivers
literally named ``sock`` — any other variable name slipped through."""

    EXEMPT = ("rpc/wire.py", "connector/broker.py")
    #: unambiguous socket methods — no other object family in this
    #: codebase has them
    ALWAYS = {"sendall", "recv_into", "sendmsg", "recvmsg"}

    def check(self, package: Package) -> Iterator[Finding]:
        for rel, mod in package.modules.items():
            if rel in self.EXEMPT:
                continue
            for node in mod.walk():
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                # socket.recv(bufsize) REQUIRES a size argument; the
                # dispatch fabric's async channel .recv() takes none —
                # an argument-free recv is a channel receive, not wire
                # IO. This is the discrimination grep could not make.
                sockety = attr in self.ALWAYS or (
                    attr == "recv" and (node.args or node.keywords))
                if not sockety:
                    continue
                qn = mod.imports.resolve(node.func)
                if qn is not None and not qn.startswith(PKG):
                    continue
                yield Finding(self.name, mod.rel, node.lineno,
                              node.col_offset,
                              f"raw socket .{attr}() outside the "
                              "rpc/wire.py fault-plane boundary")


@register
class PlacementMutation(Rule):
    name = "placement-mutation"
    title = "placement state mutated only via the scaling plane"
    ci_label = "placement-mutation"
    doc = """Fragment→worker placement must equal routing at all times;
the diff math that guarantees it lives in meta/rescale.py
commit_placement, and the raw ``"placement/"`` meta-store keyspace
belongs to meta/service.py alone. A direct key write or a
``save_placement(...)`` call elsewhere bypasses the live-migration
fencing from PR 10. meta/server.py is exempt on the call side: it is
the wire far-side of the scaling plane, forwarding a MetaClient's
``save_placement`` RPC (issued from rescale.py) to the one owning
MetaService. The grep version fired on every docstring that
mentioned the keyspace; this rule skips docstrings (no Call / no
non-doc string constant) and still sees f-string key construction."""

    KEY_EXEMPT = ("meta/service.py",)
    CALL_EXEMPT = ("meta/service.py", "meta/rescale.py", "meta/server.py")
    TARGET = f"{PKG}.meta.service.MetaService.save_placement"

    def check(self, package: Package) -> Iterator[Finding]:
        for rel, mod in package.modules.items():
            docs = None
            if rel not in self.KEY_EXEMPT:
                docs = mod.docstring_linenos()
                for node in mod.walk():
                    lit = self._placement_literal(node)
                    if lit is None or node.lineno in docs:
                        continue
                    yield Finding(
                        self.name, mod.rel, node.lineno, node.col_offset,
                        'raw "placement/" meta-store key outside '
                        "meta/service.py")
            if rel not in self.CALL_EXEMPT:
                for node in mod.walk():
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "save_placement":
                        yield Finding(
                            self.name, mod.rel, node.lineno,
                            node.col_offset,
                            "placement mutation outside meta/rescale.py "
                            "commit_placement")

    #: the keyspace prefix this rule polices; spelled once so the
    #: detector's own source carries exactly one (annotated) literal
    PREFIX = \
        "placement/"  # rwlint: allow(placement-mutation): the rule itself must name the keyspace it matches

    @classmethod
    def _placement_literal(cls, node: ast.AST) -> Optional[str]:
        # plain Constant covers both bare strings and the constant
        # segments inside an f-string (ast.walk visits JoinedStr parts
        # as Constant nodes), so f"placement/{job}" keys are seen too
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value.startswith(cls.PREFIX):
            return node.value
        return None


@register
class ServingCache(Rule):
    name = "serving-cache"
    title = "batch SELECTs lower only through the serving plane"
    ci_label = "serving-cache"
    doc = """Every batch SELECT must lower through frontend/serving.py
so the version-pinned plan cache sees it; a direct ``lower_plan(...)``
call inside frontend/session.py bypasses the cache layer and its
0-recompile + two-phase guarantees (PR 8). Alias-aware: importing
``lower_plan as _lp`` is still caught — the old grep was not."""

    ONLY = ("frontend/session.py",)
    TARGET = f"{PKG}.batch.lower.lower_plan"

    def check(self, package: Package) -> Iterator[Finding]:
        for rel in self.ONLY:
            mod = package.module(rel)
            if mod is None:
                continue
            for node in mod.walk():
                if not isinstance(node, ast.Call):
                    continue
                qn = package.canonical(
                    mod.imports.resolve_or_local(node.func))
                named = isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "lower_plan"
                if qn == self.TARGET or named:
                    yield Finding(
                        self.name, mod.rel, node.lineno, node.col_offset,
                        "direct lower_plan call in Session bypasses the "
                        "serving cache")


@register
class UdfBoundary(Rule):
    name = "udf-boundary"
    title = "user UDF callables invoked only behind the client boundary"
    ci_label = "udf-boundary"
    doc = """A registered UDF callable may only run behind the client
boundary (udf/client.py), which owns the deadlines / respawn+replay /
fencing / backpressure contract of ISSUE 15 — a tick-path module
calling user code directly reintroduces exactly the wedge class the
out-of-process plane exists to kill. Two shapes are flagged: a call
resolving to ``udf.runtime.eval_udf_batch`` (the one sanctioned
evaluator) anywhere outside the evaluator itself and the server (the
wire's far side) — the client's opt-in inproc path carries the package's
ONE reasoned allow; and grabbing a spec's raw callable out of the
registry (``get_udf(...).fn(...)`` / ``UDF_SPECS[...].fn(...)``)."""

    TARGET = f"{PKG}.udf.runtime.eval_udf_batch"
    EXEMPT = ("udf/runtime.py", "udf/server.py")
    REG_GET = f"{PKG}.udf.registry.get_udf"
    REG_MAP = f"{PKG}.udf.registry.UDF_SPECS"

    def check(self, package: Package) -> Iterator[Finding]:
        for mod, call in _call_sites(package, targets={self.TARGET},
                                     exempt=self.EXEMPT):
            yield Finding(self.name, mod.rel, call.lineno,
                          call.col_offset,
                          "direct eval_udf_batch call outside the UDF "
                          "client boundary (route through "
                          "udf/client.py UdfPlane.call)")
        for rel, mod in package.modules.items():
            if rel in self.EXEMPT:
                continue
            for node in mod.walk():
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute) or \
                        node.func.attr != "fn":
                    continue
                v = node.func.value
                qn = None
                if isinstance(v, ast.Call):
                    qn = package.canonical(
                        mod.imports.resolve_or_local(v.func))
                elif isinstance(v, ast.Subscript):
                    qn = package.canonical(
                        mod.imports.resolve_or_local(v.value))
                if qn in (self.REG_GET, self.REG_MAP):
                    yield Finding(
                        self.name, mod.rel, node.lineno,
                        node.col_offset,
                        "registered UDF callable invoked directly from "
                        "the registry (route through udf/client.py "
                        "UdfPlane.call)")


@register
class MetaBoundary(Rule):
    name = "meta-boundary"
    title = "the meta store is constructed only inside meta/"
    ci_label = "meta-boundary"
    doc = """The control plane owns its durable store: every consumer
reaches meta state through a ``MetaService`` (in-process) or a
``MetaClient`` (remote, `ctl meta serve`), both of which serialize
writes and publish notifications. A raw ``FileMetaStore(...)``
constructed outside meta/ opens the JSONL behind the control plane's
back — its writes fire no notifications (serving sessions go stale)
and race the server's CAS transactions. Alias-aware like the rest of
this family. Pairs with the placement-mutation rule, which polices the
``placement/`` keyspace within an already-obtained store."""

    TARGET = f"{PKG}.meta.store.FileMetaStore"

    def check(self, package: Package) -> Iterator[Finding]:
        for mod, call in _call_sites(package, targets={self.TARGET}):
            if mod.rel.startswith("meta/"):
                continue
            yield Finding(self.name, mod.rel, call.lineno,
                          call.col_offset,
                          "raw FileMetaStore construction outside meta/ "
                          "(go through MetaService or MetaClient so "
                          "writes notify and serialize)")


@register
class BoundaryIO(Rule):
    name = "boundary-io"
    title = "object stores opened only behind the retry boundary"
    ci_label = "boundary-IO"
    doc = """Every durable-tier consumer must open its store via
open_object_store/wrap_object_store (the retry boundary from PR 3). A
raw ``LocalFsObjectStore(...)`` anywhere else performs unwrapped
single-shot IO on the barrier path — one transient EIO becomes a
failed checkpoint. Alias-aware like the rest of this family."""

    TARGET = f"{PKG}.storage.object_store.LocalFsObjectStore"
    EXEMPT = ("storage/object_store.py",)

    def check(self, package: Package) -> Iterator[Finding]:
        for mod, call in _call_sites(package, targets={self.TARGET},
                                     exempt=self.EXEMPT):
            yield Finding(self.name, mod.rel, call.lineno,
                          call.col_offset,
                          "raw object-store construction outside the "
                          "retry boundary (use open_object_store / "
                          "wrap_object_store)")
