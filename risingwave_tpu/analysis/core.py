"""rwlint core: the rule registry, the parsed-package model, and the
suppression-pragma machinery.

Why a framework and not five more greps: the invariants this package
guards (one dispatch per fused epoch, every frame through the
chaos-injectable wire boundary, placement mutated only via the scaling
plane, durable IO only behind the retry wrapper) are *semantic* — they
are statements about call expressions, import aliases, and
reachability, not about byte patterns. A grep false-positives on a
docstring that *mentions* ``PermitChannel(`` and false-negatives on
``from ..stream.dispatch import PermitChannel as PC``; an AST rule with
alias resolution gets both right. See docs/static-analysis.md.

Model
-----
``Module``    — one parsed source file: AST, import map, suppressions.
``Package``   — every module under the package root, plus shared lazy
                analyses (export canonicalisation, the call graph) that
                individual rules request through ``Package.shared``.
``Rule``      — a named check. ``check(package)`` yields ``Finding``s;
                the driver filters them through inline suppressions.

Suppressions
------------
``# rwlint: allow(rule): reason`` on the flagged line (or alone on the
line directly above it) suppresses that rule there. The reason is
MANDATORY — an allow without a justification is itself a finding
(rule ``pragma``), because an unexplained exemption is how invariants
rot. ``# rwlint: allow-file(rule): reason`` anywhere in a file exempts
the whole file. ``allow(*)`` suppresses every rule on that line.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

__all__ = [
    "Finding", "Module", "Package", "Rule", "RULES", "register",
    "load_package", "run_rules", "all_rules",
]

_PRAGMA_RE = re.compile(
    r"#\s*rwlint:\s*(allow|allow-file)\(([^)]*)\)\s*(?::\s*(.*))?$")

#: Every rule target is expressed against this package name. Module
#: qualnames are normalised to it regardless of what directory the
#: linted tree happens to be rooted at (a fixture copy, a vendored
#: checkout), so rooting the tree at ``/tmp/copy`` cannot silently
#: disable every boundary rule.
CANONICAL_PKG = "risingwave_tpu"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source location."""
    rule: str
    path: str          # package-root-relative posix path
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class _Pragma:
    __slots__ = ("rules", "reason", "line", "file_wide")

    def __init__(self, rules: Tuple[str, ...], reason: str, line: int,
                 file_wide: bool):
        self.rules = rules
        self.reason = reason
        self.line = line
        self.file_wide = file_wide

    def covers(self, rule: str) -> bool:
        return "*" in self.rules or rule in self.rules


class Module:
    """One parsed source file plus everything rules need per-file."""

    def __init__(self, package: "Package", abspath: Path, rel: str):
        self.package = package
        self.abspath = abspath
        self.rel = rel                      # posix, relative to pkg root
        self.source = abspath.read_text(encoding="utf-8")
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(abspath))
        # dotted module qualname: pkgname.sub.mod (pkgname/__init__.py
        # -> pkgname), with the root segment pinned to CANONICAL_PKG —
        # rule targets are written against it, not the root dir name
        parts = [CANONICAL_PKG] + rel[:-3].split("/")
        if parts[-1] == "__init__":
            parts.pop()
        self.qualname = ".".join(parts)
        self.pragmas: List[_Pragma] = []
        self.pragma_findings: List[Finding] = []
        self._scan_pragmas()
        from .imports import ImportMap
        self.imports = ImportMap(self)

    # -- suppressions -----------------------------------------------------

    def _scan_pragmas(self) -> None:
        # Pragmas live in COMMENT tokens only: a docstring that *shows*
        # the pragma syntax (docs, this module's own header) must never
        # register a live suppression, so we tokenize rather than
        # regex raw lines.
        for i, text in self._comment_tokens():
            m = _PRAGMA_RE.search(text)
            if not m:
                if "rwlint: allow" in text:
                    self.pragma_findings.append(Finding(
                        "pragma", self.rel, i, 0,
                        "malformed rwlint pragma (expected "
                        "'# rwlint: allow(rule): reason')"))
                continue
            kind, rules_s, reason = m.group(1), m.group(2), m.group(3)
            rules = tuple(r.strip() for r in rules_s.split(",") if r.strip())
            if not rules:
                self.pragma_findings.append(Finding(
                    "pragma", self.rel, i, 0,
                    "rwlint allow pragma names no rule"))
                continue
            if not (reason or "").strip():
                self.pragma_findings.append(Finding(
                    "pragma", self.rel, i, 0,
                    f"rwlint allow({rules_s}) without a reason — every "
                    "exemption must carry its justification"))
                continue
            self.pragmas.append(_Pragma(rules, reason.strip(), i,
                                        kind == "allow-file"))

    def _comment_tokens(self) -> Iterator[Tuple[int, str]]:
        """(lineno, text) for every ``#`` comment in the source."""
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except tokenize.TokenError:
            # ast.parse already succeeded, so this is unreachable in
            # practice; fail open (no pragmas) rather than crash.
            return

    def suppressed(self, rule: str, line: int) -> bool:
        for p in self.pragmas:
            if not p.covers(rule):
                continue
            if p.file_wide:
                return True
            # pragma on the flagged line, or alone on the line above it
            if p.line == line:
                return True
            if p.line == line - 1:
                stripped = self.lines[p.line - 1].lstrip()
                if stripped.startswith("#"):
                    return True
        return False

    # -- AST helpers ------------------------------------------------------

    def walk(self) -> Iterator[ast.AST]:
        return ast.walk(self.tree)

    def docstring_linenos(self) -> "set[int]":
        """Line numbers covered by docstrings (module/class/function) —
        the classic grep false-positive surface."""
        covered: "set[int]" = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Module, ast.ClassDef,
                                 ast.FunctionDef, ast.AsyncFunctionDef)):
                body = node.body
                if body and isinstance(body[0], ast.Expr) and \
                        isinstance(body[0].value, ast.Constant) and \
                        isinstance(body[0].value.value, str):
                    d = body[0].value
                    covered.update(range(d.lineno,
                                         (d.end_lineno or d.lineno) + 1))
        return covered


class Package:
    """Every module under one package root, plus shared lazy analyses."""

    def __init__(self, root: Path):
        self.root = root.resolve()
        self.name = self.root.name
        self.modules: Dict[str, Module] = {}
        self.parse_errors: List[Finding] = []
        for p in sorted(self.root.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            rel = p.relative_to(self.root).as_posix()
            try:
                self.modules[rel] = Module(self, p, rel)
            except SyntaxError as e:
                self.parse_errors.append(Finding(
                    "parse", rel, e.lineno or 0, e.offset or 0,
                    f"syntax error: {e.msg}"))
        self._shared: Dict[str, object] = {}
        self._exports: Optional[Dict[str, Dict[str, str]]] = None

    def module(self, rel: str) -> Optional[Module]:
        return self.modules.get(rel)

    def shared(self, key: str, build: Callable[["Package"], object]):
        """Memoize a package-wide analysis (e.g. the call graph) so
        several rules can share one construction."""
        if key not in self._shared:
            self._shared[key] = build(self)
        return self._shared[key]

    # -- export canonicalisation -----------------------------------------

    def _export_table(self) -> Dict[str, Dict[str, str]]:
        """module qualname -> exported name -> source qualified name.

        Covers both definitions (``name`` defined in ``mod`` maps to
        ``mod.name``) and re-exports (``from .dispatch import
        PermitChannel`` in ``stream/__init__.py`` maps
        ``stream.PermitChannel`` back to ``stream.dispatch
        .PermitChannel``), so a rule target stays matchable through any
        alias chain."""
        if self._exports is None:
            table: Dict[str, Dict[str, str]] = {}
            for mod in self.modules.values():
                entry: Dict[str, str] = {}
                for node in mod.tree.body:
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                        entry[node.name] = f"{mod.qualname}.{node.name}"
                    elif isinstance(node, ast.Assign):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                entry[t.id] = f"{mod.qualname}.{t.id}"
                    elif isinstance(node, ast.AnnAssign) and \
                            isinstance(node.target, ast.Name):
                        entry[node.target.id] = \
                            f"{mod.qualname}.{node.target.id}"
                # imports may shadow/define exported names too
                for name, qn in mod.imports.aliases.items():
                    entry.setdefault(name, qn)
                table[mod.qualname] = entry
            self._exports = table
        return self._exports

    def canonical(self, qualname: Optional[str]) -> Optional[str]:
        """Follow re-export chains to the defining module's name."""
        if qualname is None:
            return None
        table = self._export_table()
        seen = set()
        while qualname not in seen:
            seen.add(qualname)
            head, _, attr = qualname.rpartition(".")
            nxt = table.get(head, {}).get(attr)
            if nxt is None or nxt == qualname:
                break
            qualname = nxt
        return qualname


# -- rule registry --------------------------------------------------------


class Rule:
    """Base class: subclass, set the class attrs, implement check()."""

    #: registry key, used in pragmas and --rule filters
    name: str = ""
    #: one-line summary, shown by --list-rules
    title: str = ""
    #: label used for the per-rule CI OK line (defaults to name)
    ci_label: str = ""
    #: long-form rationale, shown by --explain (markdown-ish)
    doc: str = ""

    def check(self, package: Package) -> Iterator[Finding]:
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    inst = cls()
    assert inst.name and inst.name not in RULES, inst.name
    RULES[inst.name] = inst
    return cls


def all_rules() -> List[Rule]:
    # import the rule modules for their registration side effect
    from . import (  # noqa: F401
        rules_boundary, rules_purity, rules_state, rules_tick,
    )
    return [RULES[k] for k in sorted(RULES)]


# -- driver ---------------------------------------------------------------


def load_package(root) -> Package:
    return Package(Path(root))


def run_rules(package: Package,
              rules: Optional[Iterable[Rule]] = None
              ) -> Tuple[List[Finding], Dict[str, int]]:
    """Run rules over the package; returns (findings, per-rule counts).

    Findings already filtered through inline suppressions; pragma
    syntax errors and file parse errors ride along under the ``pragma``
    / ``parse`` pseudo-rules so they can never be silently ignored.
    """
    if rules is None:
        rules = all_rules()
    findings: List[Finding] = list(package.parse_errors)
    counts: Dict[str, int] = {}
    for mod in package.modules.values():
        findings.extend(mod.pragma_findings)
    for rule in rules:
        counts[rule.name] = 0
        for f in rule.check(package):
            mod = package.module(f.path)
            if mod is not None and mod.suppressed(f.rule, f.line):
                continue
            findings.append(f)
            counts[rule.name] += 1
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, counts
