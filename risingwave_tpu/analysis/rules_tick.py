"""sync-fetch-discipline: blocking device→host fetches on the tick
path must go through the async fetch helper.

The pipelined tick (docs/performance.md "Pipelined tick") lives or dies
on the host never synchronizing with the device accidentally: one
``jax.device_get`` in a per-tick method stalls the dispatch queue and
silently reverts the overlap the pipeline bought. The blessed crossing
is ``common/fetch.py`` — ``async_fetch`` starts the copy at dispatch
time, ``FetchFuture.result()``/``fetch()`` resolve it at flush/barrier
time — so this rule walks the closure reachable from
``Session._tick_impl`` through the fused engines' per-tick methods and
flags the raw blocking spellings:

* ``jax.device_get(...)``
* ``.block_until_ready()``
* ``np.asarray(...)`` over a call/attribute expression inside the
  engine-driver modules (the np.asarray-on-a-device-value idiom; a
  plain ``np.asarray(name)`` over host data is not flagged)

``common/fetch.py`` itself is exempt (its ``result()`` IS the one
legitimate device_get), and the grow-retry drain keeps one reasoned
``# rwlint: allow`` — after a routing-overflow replay the packed flags
must validate before anything else dispatches, so that re-fetch is
deliberately synchronous.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from .callgraph import Func, FunctionIndex, build_index
from .core import Finding, Module, Package, Rule, register

PKG = "risingwave_tpu"

#: the tick path's root set: Session's tick drivers plus every fused
#: engine's per-tick surface (the callgraph cannot statically type
#: ``group.run_epoch(...)`` receivers, so the engine methods are roots
#: in their own right — "reachable from _tick_impl through the
#: engines"). Method-name sets keep checkpoint/recovery/debug surfaces
#: (export_host, merged_group_values) out of scope: they run on the
#: durable path, not per tick.
TICK_ROOTS = (
    ("frontend/session.py", ("Session",),
     ("_tick_impl", "_cosched_tick", "_shardfused_tick",
      "_complete_oldest_impl", "_drain_fused_pipeline",
      "_push_cosched_outs", "_push_shardfused_outs")),
    ("stream/coschedule.py", ("CoGroup",),
     ("run_epoch", "flush", "begin_flush", "finish_flush")),
    ("parallel/fused.py", None,      # every engine class in the module
     ("run_epoch", "flush", "begin_flush", "finish_flush",
      "_settle", "_settled_packed")),
)

#: the one module allowed to call jax.device_get on the tick path
EXEMPT_MODULES = ("common/fetch.py",)

#: modules where a bare np.asarray(<call>/<attr>) is treated as a
#: device-value materialization (the engine drivers); elsewhere
#: np.asarray over host rows is routine
DEVICE_DRIVER_MODULES = ("stream/coschedule.py", "parallel/fused.py",
                         "ops/", "frontend/session.py")


def _callee_qn(package: Package, mod: Module, node: ast.Call):
    return package.canonical(mod.imports.resolve(node.func))


def tick_roots(package: Package, index: FunctionIndex) -> List[Func]:
    roots: List[Func] = []
    for rel, classes, methods in TICK_ROOTS:
        mod = package.module(rel)
        if mod is None:
            continue
        for fn in index.by_qualname.values():
            if fn.module is not mod or fn.cls is None:
                continue
            if classes is not None and fn.cls not in classes:
                continue
            if fn.name in methods:
                roots.append(fn)
    return roots


@register
class SyncFetchDiscipline(Rule):
    name = "sync-fetch-discipline"
    title = "tick-path device fetches go through common/fetch.py"
    ci_label = "sync-fetch-discipline"
    doc = """The asynchronous epoch pipeline overlaps device compute
with host flush decode by starting every device→host copy at dispatch
time (common/fetch.py async_fetch) and resolving it at flush/barrier
time. A raw blocking fetch — jax.device_get, .block_until_ready(),
np.asarray on a device value — anywhere in the closure reachable from
Session._tick_impl through the fused engines' per-tick methods stalls
the dispatch queue and silently reverts the overlap. This rule walks
that closure and flags the raw spellings; common/fetch.py is the one
blessed crossing, and the sharded grow-retry drain carries the one
reasoned allow (a replayed epoch must validate synchronously before
anything else dispatches)."""

    def check(self, package: Package) -> Iterator[Finding]:
        index = build_index(package)
        roots = tick_roots(package, index)
        seen: Set[Func] = set()
        for fn in sorted(index.reachable(roots),
                         key=lambda f: f.qualname):
            if fn in seen:
                continue
            seen.add(fn)
            if fn.module.rel in EXEMPT_MODULES:
                continue
            yield from self._check_func(package, index, fn)

    def _check_func(self, package: Package, index: FunctionIndex,
                    fn: Func) -> Iterator[Finding]:
        mod = fn.module
        where = (f"in {fn.qualname.removeprefix(PKG + '.')} "
                 "(tick path — reachable from Session._tick_impl "
                 "through the fused engines)")
        in_driver = mod.rel.startswith(DEVICE_DRIVER_MODULES)
        for node in index._own_body_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            qn = _callee_qn(package, mod, node)
            f = node.func
            if qn == "jax.device_get":
                yield self._f(mod, node,
                              f"blocking jax.device_get() {where} — "
                              "start the copy at dispatch time via "
                              "common/fetch.async_fetch and resolve at "
                              "flush time")
            elif isinstance(f, ast.Attribute) and \
                    f.attr == "block_until_ready":
                yield self._f(mod, node,
                              f".block_until_ready() {where} — host "
                              "sync on the tick path; fetch the value "
                              "through common/fetch.py instead")
            elif qn in ("numpy.asarray", "numpy.array") and in_driver \
                    and node.args and \
                    isinstance(node.args[0], ast.Attribute):
                # np.asarray(self.some_device_state): synchronous
                # materialization. Call args are NOT flagged — the
                # common post-refactor shape is np.asarray over an
                # already-host fetch result (fetch(...)/(...).result())
                yield self._f(mod, node,
                              f"{qn}() over a device value {where} — "
                              "materializes device→host synchronously; "
                              "route it through common/fetch.py")

    def _f(self, mod: Module, node: ast.AST, msg: str) -> Finding:
        return Finding(self.name, mod.rel, node.lineno,
                       node.col_offset, msg)
