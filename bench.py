"""Benchmark: NEXmark q5/q7/q8 + TPC-H q3 fused-epoch throughput plus a
many-small-MVs co-scheduling phase, TPU vs CPU stand-in, plus p99
barrier latency.

Runs the hot paths of NEXmark q5 (tumble-window COUNT aggregation), q7
(bids joined with the per-window MAX(price)), q8 (session-gap windows
over bidders — ops/session_window.py) and a streaming TPC-H q3 MV
(orders⋈lineitem revenue top-10 — ops/stream_q3.py), each as ONE fused
``lax.scan`` dispatch per epoch, and a "many small MVs" phase measuring
16 co-scheduled MVs batched into one dispatch per epoch vs the same 16
dispatched sequentially (stream/coschedule.py — ROADMAP item 4).

Design for a chip behind a network tunnel (and against tunnel outages —
VERDICT r3 weak #1; BENCH_r03–r05 all lost the round to a wedged
backend, hence the hardening below):

* Source chunks are generated ON DEVICE (``DeviceBidGenerator`` /
  ``DeviceQ3Generator``): the only per-epoch host→device traffic is two
  scalars, so the chip never waits on host ingest (VERDICT r3 item 1c).
* Each epoch is ONE ``lax.scan`` dispatch; host↔device round-trips per
  epoch are O(1).
* EVERY measurement phase runs in its own subprocess. The parent process
  never initializes a JAX backend, so a wedged PJRT init cannot take the
  whole bench down. The TPU phase is retried with backoff (a tunnel blip
  does not erase the round's record), and on persistent failure the CPU
  stand-in numbers are still emitted alongside an explicit ``tpu_error``
  field.
* A cheap SMOKE PROBE (tiny jit in a fresh subprocess) runs before each
  full TPU attempt: a wedged backend is discovered in minutes, not a
  full phase timeout.
* Every completed phase's record is appended to ``BENCH_partial.json``
  (JSON lines) AS IT FINISHES — a mid-run wedge or kill still leaves
  every completed phase on disk.
* TPU attempts share one ``JAX_COMPILATION_CACHE_DIR``: a retry after a
  mid-phase tunnel blip reuses the previous attempt's XLA compilations
  instead of paying full compile time again.

``vs_baseline`` is measured, not assumed: the SAME pipeline runs in a
JAX_PLATFORMS=cpu subprocess first (the documented stand-in for the
reference's Rust CPU engine — BASELINE.md config 2 wants ≥10× a 16-vCPU CPU
engine), and the ratio reported is tpu_rows_per_sec / cpu_rows_per_sec.

``--smoke`` runs one tiny in-process phase (seconds, CPU) for CI
(scripts/check.sh): fused q5/q8/q3 epochs + a 4-job co-scheduled group,
with the 1-dispatch-per-epoch invariant asserted.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import asyncio
import json
import os
import subprocess
import sys
import threading
import time

WATCHDOG_SECS = 1500
# backend init either completes in seconds or is wedged on the tunnel —
# a short init watchdog keeps a dead-tunnel retry cycle to minutes, not
# 3 x 25 min
INIT_WATCHDOG_SECS = 300
TPU_ATTEMPTS = 3
TPU_BACKOFFS = (60, 120)          # sleep between attempts
# must exceed INIT_WATCHDOG_SECS + WATCHDOG_SECS with slack so the
# child's diagnostic fail line always beats the parent's kill
PHASE_TIMEOUT = 2100              # per-subprocess wall clock
# smoke probe: backend init + one tiny jit; anything slower is wedged
PROBE_TIMEOUT = INIT_WATCHDOG_SECS + 180

CHUNK = 4096
WINDOW_US = 10_000_000  # 10s tumble as the q5 core window
# Epoch cadence: ~1M rows per barrier so a barrier closes roughly every
# second at the target throughput — the reference's default 1 s barrier
# interval (src/common/src/config.rs:595) at saturation.
N_CHUNKS = 1024
WARMUP_CHUNKS = 256
CHUNKS_PER_EPOCH = 256
CPU_N_CHUNKS = 256      # stand-in run is shorter; it reports a rate
Q7_N_CHUNKS = 512       # join consumes every event on both sides
Q7_CPU_N_CHUNKS = 128
# q7 window: 5 ms of event time ≈ 50 bids/window at the generator's
# 10K events/s. The probe side stores every bid of a live window under ONE
# join key, and the bucketed arena bounds per-key cardinality by its lane
# width — the 10 s window of the full q7 (100K rows/key) needs the sharded
# join + watermark cleaning, not a single-chip dense arena; window size is
# a bench parameter of the join core, not of its throughput semantics.
Q7_WINDOW_US = 5_000
# fused q7 (ops/interval_join.py): ring of window buckets + lane width.
# One epoch spans 256 chunks x 4096 events x 100 us ≈ 105 s ≈ 21K windows
# of 5 ms; the ring must outlast an epoch so a slot is never reclaimed
# while its flush delta is pending (1.5x margin). 128 lanes hold the ~50
# bids per window with chunk-straddle headroom.
Q7_BUCKETS = 1 << 15
Q7_LANES = 128
# q8 session windows (ops/session_window.py): 0.5 s session gap — hot
# bidders (90% of bids) never gap out; cold bidders' ~1 s inter-event
# spacing closes a steady session stream. Closed buffer must hold one
# epoch's closures (≈10% of events worst case); key table bounds
# distinct bidders over the whole run (id clock drifts 1 per 50 events).
Q8_N_CHUNKS = 512
Q8_CPU_N_CHUNKS = 128
Q8_GAP_US = 500_000
Q8_TABLE_CAP = 1 << 18
Q8_CLOSED_CAP = 1 << 17
# TPC-H q3 (ops/stream_q3.py + connector/tpch.py): ~10% of orders
# qualify (segment 1-of-5 x date ~1/2); capacities bound QUALIFYING
# orders / live revenue groups over the run.
Q3_N_CHUNKS = 512
Q3_CPU_N_CHUNKS = 128
Q3_ORDERS_CAP = 1 << 17
Q3_AGG_CAP = 1 << 17
# many-small-MVs co-scheduling phase (stream/coschedule.py): 16 q5-shaped
# MVs with SMALL chunks and tables — the per-job-overhead-bound regime
# where hundreds of MVs ticking together live. Measured END TO END
# through the Session: the same 16 CREATE MATERIALIZED VIEWs ticked with
# [streaming] coschedule = true (the whole group's epoch in ONE vmapped
# dispatch) vs false (16 executor pipelines, each dispatching its own
# epochs — the pre-coscheduler behavior).
COSCHED_JOBS = 16
COSCHED_CHUNK = 64             # rows per chunk (the "small MV" shape)
COSCHED_CHUNKS_PER_TICK = 8
COSCHED_TABLE_CAP = 1 << 11
COSCHED_TICKS = 12
COSCHED_WARMUP_TICKS = 3
COSCHED_SMOKE_CHUNK = 256      # ops-level shapes for --smoke
COSCHED_SMOKE_TABLE = 1 << 12
# heterogeneous tick-compiler phase (stream/tick_compiler.py): N
# DISSIMILAR small MVs — mixed skeletons, widths, window literals — in
# one Session, ticked with [streaming] tick_compiler = true (the
# compiler buckets them into shape-class padded supergroups + jitted
# mega-epochs: a handful of dispatches per tick) vs false (N executor
# pipelines, each dispatching its own epochs).
HETERO_JOBS = 12
HETERO_TICKS = 12
# mesh-sharded fused phase (ops/fused_sharded.py + parallel/fused.py):
# the fused q5/q7 epochs promoted to the whole mesh — one dispatch per
# epoch across all chips, state hash-partitioned via the in-dispatch
# all_to_all. On the CPU stand-in the mesh is virtual
# (XLA_FLAGS=--xla_force_host_platform_device_count); on a healthy chip
# it is the real slice. Aggregate rows/s recorded per shard count.
SHARDED_SHARD_COUNTS = (1, 4, 8)
SHARDED_N_CHUNKS = 128
SHARDED_WARMUP_CHUNKS = 32
SHARDED_Q7_N_CHUNKS = 64
COSCHED_SHARDED_JOBS = 4       # K jobs × S shards phase (surface 6)
SHARDED_VIRTUAL_DEVICES = 8    # CPU stand-in virtual mesh size
# serving phase (frontend/serving.py — ROADMAP item 3): concurrent
# point-lookups + small group-by reads over a LIVE q5 MV while the
# stream keeps ticking. Cached+two-phase (the serving plane) vs the
# uncached single-phase baseline ([batch] serving_cache_size = 0,
# serving_tasks = 1 — every query replans/relowers under the API lock,
# the pre-serving-plane behavior). QPS + p50/p99 per run.
SERVING_SECONDS = 3.0          # measured wall clock per variant
SERVING_THREADS = 4            # concurrent reader threads
SERVING_TICK_S = 0.1           # live-stream tick cadence during reads
SERVING_WARM_TICKS = 3

# fleet phase (docs/control-plane.md): one standalone MetaServer + one
# writer session share a durable dir with N serving FRONTEND PROCESSES,
# each serving cached MV reads over pgwire to several connections —
# the multi-tenant deployment shape, measured end to end (attach,
# notification-driven catalog, admission control, merged QPS/p99).
FLEET_SECONDS = 3.0            # measured wall clock
FLEET_FRONTENDS = 2            # serving frontend PROCESSES
FLEET_CONNS = 4                # pgwire connections per frontend


def _emit(obj: dict) -> None:
    print(json.dumps(obj))
    sys.stdout.flush()


def _fail_line(msg: str) -> dict:
    return {"metric": "nexmark_q5_core_throughput", "value": 0.0,
            "unit": "rows/s", "vs_baseline": 0.0, "error": msg}


def _watchdog_fire():
    # A daemon-thread timer (not SIGALRM): a hang inside native PJRT/XLA
    # code never returns to the bytecode loop, so a Python signal handler
    # would be deferred forever.
    _emit(_fail_line(
        "watchdog timeout: backend init or compile hung (chip held?)"))
    os._exit(2)


# ---------------------------------------------------------------------------
# Child phase: actual measurement on whatever backend this process gets
# ---------------------------------------------------------------------------

class _DeviceBidSource:
    """Source executor over the on-device generator: one ChunkBatch + one
    barrier per epoch. Fresh scripts are configured via reset()."""

    def __init__(self, n_chunks: int, first_epoch: int, cfg=None):
        from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig
        from risingwave_tpu.connector.nexmark import DeviceBidGenerator
        self.schema = BID_SCHEMA
        self.gen = DeviceBidGenerator(cfg or NexmarkConfig(
            chunk_capacity=CHUNK))
        self.n_chunks = n_chunks
        self.first_epoch = first_epoch

    def reset(self, n_chunks: int, first_epoch: int) -> None:
        self.n_chunks = n_chunks
        self.first_epoch = first_epoch

    async def execute(self):
        from risingwave_tpu.stream import Barrier
        yield Barrier.new(self.first_epoch)
        epoch = self.first_epoch
        for i in range(0, self.n_chunks, CHUNKS_PER_EPOCH):
            k = min(CHUNKS_PER_EPOCH, self.n_chunks - i)
            yield self.gen.next_batch(k)
            epoch += 1
            yield Barrier.new(epoch)


def _q5_pipeline(src):
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.stream import HashAggExecutor, ProjectExecutor
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(WINDOW_US, INT64)),
        col(0, INT64),
    ]
    proj = ProjectExecutor(src, exprs, names=("window_start", "auction"))
    agg = HashAggExecutor(proj, [0, 1], [count_star()],
                          table_capacity=1 << 21, out_capacity=CHUNK)
    return exprs, agg


def measure_q5(n_chunks: int) -> float:
    """Sustained source rows/s of the q5-core EXECUTOR pipeline (three
    dispatches per epoch: generate / project / agg-scan)."""
    import jax

    src = _DeviceBidSource(WARMUP_CHUNKS, 1)
    _, agg = _q5_pipeline(src)

    async def drive() -> float:
        async for _ in agg.execute():  # warmup pass compiles every step
            pass
        jax.block_until_ready(agg.state.lanes)
        src.reset(n_chunks, WARMUP_CHUNKS // CHUNKS_PER_EPOCH + 2)
        t0 = time.perf_counter()
        async for _ in agg.execute():
            pass
        jax.block_until_ready(agg.state.lanes)
        return time.perf_counter() - t0

    elapsed = asyncio.run(drive())
    return n_chunks * CHUNK / elapsed


def measure_q5_fused(n_chunks: int) -> float:
    """Sustained source rows/s of the q5 core with the WHOLE epoch —
    generation, projection, aggregation — fused into one lax.scan
    dispatch (ops/fused_epoch.py; the BASELINE.md headroom item). The
    barrier path (probe + flush-window gathers + finish) mirrors
    HashAggExecutor.on_barrier exactly so the work per barrier matches
    the executor pipeline."""
    import jax
    import jax.numpy as jnp
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.ops.fused_epoch import fused_source_agg_epoch

    src = _DeviceBidSource(1, 1)
    exprs, agg = _q5_pipeline(src)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CHUNK))
    fused = fused_source_agg_epoch(gen.chunk_fn(), exprs, agg.core, CHUNK)

    def run(state, n, start_event, batch_no):
        done = 0
        while done < n:
            per = min(CHUNKS_PER_EPOCH, n - done)  # remainder epoch kept
            done += per
            key = jax.random.fold_in(jax.random.PRNGKey(17), batch_no)
            batch_no += 1
            state = fused(state, jnp.int64(start_event), key, per)
            start_event += per * CHUNK
            packed, rank = agg._probe(state)
            n_dirty, overflow, _live = (
                int(x) for x in jax.device_get(packed))
            if overflow:
                raise RuntimeError("q5 fused: group table overflow")
            lo = 0
            while lo < n_dirty:
                agg._gather(state, rank, jnp.int64(lo))
                lo += agg.core.groups_per_chunk
            state = agg._finish(state)
        return state, start_event, batch_no

    state, start_event, batch_no = run(
        agg.state, WARMUP_CHUNKS, 0, 0)        # compile everything
    jax.block_until_ready(state.lanes)
    t0 = time.perf_counter()
    state, _, _ = run(state, n_chunks, start_event, batch_no)
    jax.block_until_ready(state.lanes)
    elapsed = time.perf_counter() - t0
    return n_chunks * CHUNK / elapsed


def measure_q7(n_chunks: int) -> float:
    """Sustained source rows/s of the q7-core windowed join: bids joined
    with the per-window MAX(price) (BASELINE.md config 3). Each source
    event feeds both join sides (two device generators with the same seed
    produce identical streams); the rate reported is source events/s."""
    import jax
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import agg
    from risingwave_tpu.stream import (
        HashAggExecutor, HashJoinExecutor, ProjectExecutor,
    )

    warm = 64

    def pipeline():
        probe_src = _DeviceBidSource(warm, 1)
        probe = ProjectExecutor(probe_src, [
            call("tumble_start", col(5, TIMESTAMP),
                 Literal(Q7_WINDOW_US, INT64)),
            col(0, INT64),
            col(2, INT64),
        ], names=("window_start", "auction", "price"))
        build_src = _DeviceBidSource(warm, 1)
        build_pre = ProjectExecutor(build_src, [
            call("tumble_start", col(5, TIMESTAMP),
                 Literal(Q7_WINDOW_US, INT64)),
            col(2, INT64),
        ], names=("window_start", "price"))
        build = HashAggExecutor(build_pre, [0], [agg("max", 1, INT64)],
                                table_capacity=1 << 16, out_capacity=CHUNK)
        cond = call("equal", col(2, INT64), col(4, INT64))  # price = max
        join = HashJoinExecutor(
            probe, build, [0], [0], condition=cond,
            key_capacity=1 << 16, bucket_width=128, out_capacity=CHUNK)
        return probe_src, build_src, join

    probe_src, build_src, join = pipeline()

    async def drive() -> float:
        async for _ in join.execute():   # warmup compiles all steps
            pass
        jax.block_until_ready(join.state.left.occupied)
        first = (warm + CHUNKS_PER_EPOCH - 1) // CHUNKS_PER_EPOCH + 2
        probe_src.reset(n_chunks, first)
        build_src.reset(n_chunks, first)
        t0 = time.perf_counter()
        async for _ in join.execute():
            pass
        jax.block_until_ready(join.state.left.occupied)
        return time.perf_counter() - t0

    elapsed = asyncio.run(drive())
    return n_chunks * CHUNK / elapsed


def measure_q7_fused(n_chunks: int) -> float:
    """Sustained source rows/s of the q7 core with the WHOLE pipeline —
    generation, projection, the bucketed interval join, and the
    per-window max flush — fused into one lax.scan dispatch per epoch
    (ops/interval_join.py + fused_source_join_epoch; the dispatch-ladder
    elimination q5 got, extended to the join family). Per epoch the host
    reads ONE packed stats vector and gathers the emitted windows."""
    import jax
    import jax.numpy as jnp
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.common.chunk import (
        flatten_shards, gather_units_window,
    )
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.ops.fused_epoch import fused_source_join_epoch
    from risingwave_tpu.ops.interval_join import IntervalJoinCore

    exprs = [
        call("tumble_start", col(5, TIMESTAMP),
             Literal(Q7_WINDOW_US, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    probe_schema = Schema((Field("window_start", TIMESTAMP),
                           Field("auction", INT64), Field("price", INT64)))
    core = IntervalJoinCore(probe_schema, ts_col=0, val_col=2,
                            window_us=Q7_WINDOW_US, n_buckets=Q7_BUCKETS,
                            lane_width=Q7_LANES)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CHUNK))
    fused = fused_source_join_epoch(gen.chunk_fn(), exprs, core, CHUNK)
    gather_flush = jax.jit(core.gather_flush,
                           static_argnames=("out_capacity",))
    probe_gather = jax.jit(lambda po, lo: gather_units_window(
        flatten_shards(po), lo, CHUNK))

    def run(state, n, start_event, batch_no):
        last = None
        done = 0
        while done < n:
            per = min(CHUNKS_PER_EPOCH, n - done)   # remainder epoch kept
            done += per
            key = jax.random.fold_in(jax.random.PRNGKey(23), batch_no)
            batch_no += 1
            (state, probe_out, del_m, ins_m, old_emitted,
             packed) = fused(state, jnp.int64(start_event), key, per)
            start_event += per * CHUNK
            n_flush, ovf, clobber, sawdel, n_probe = (
                int(x) for x in jax.device_get(packed))
            if ovf or clobber or sawdel:
                raise RuntimeError(
                    f"q7 fused: flags ovf={ovf} clobber={clobber} "
                    f"sawdel={sawdel}")
            # drain both emission surfaces (what downstream would consume)
            lo = 0
            while lo < n_probe:
                last = probe_gather(probe_out, jnp.int64(lo))
                lo += CHUNK // 2
            lo = 0
            while lo < n_flush:
                last = gather_flush(state, del_m, ins_m, old_emitted,
                                    jnp.int64(lo), out_capacity=CHUNK)
                lo += CHUNK
        if last is not None:
            jax.block_until_ready(last)
        return state, start_event, batch_no

    state, start_event, batch_no = run(
        core.init_state(), WARMUP_CHUNKS, 0, 0)    # compile everything
    jax.block_until_ready(state.cur_max)
    t0 = time.perf_counter()
    state, _, _ = run(state, n_chunks, start_event, batch_no)
    jax.block_until_ready(state.cur_max)
    elapsed = time.perf_counter() - t0
    return n_chunks * CHUNK / elapsed


def measure_q8_fused(n_chunks: int) -> float:
    """Sustained source rows/s of the q8 core: bidder session-gap windows
    (ops/session_window.py) with generation, projection, sessionization
    AND the watermark close fused into one lax.scan dispatch per epoch
    (fused_source_session_epoch). Per epoch the host reads ONE packed
    stats vector and gathers the closed-session windows."""
    import jax
    import jax.numpy as jnp
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import col
    from risingwave_tpu.ops.fused_epoch import EPOCH_BUILDERS
    from risingwave_tpu.ops.session_window import SessionWindowCore

    exprs = [col(1, INT64), col(5, TIMESTAMP)]   # bidder, date_time
    schema = Schema((Field("bidder", INT64), Field("ts", TIMESTAMP)))
    core = SessionWindowCore(schema, key_col=0, ts_col=1,
                             gap_us=Q8_GAP_US, capacity=Q8_TABLE_CAP,
                             closed_capacity=Q8_CLOSED_CAP)
    cfg = NexmarkConfig(chunk_capacity=CHUNK)
    gen = DeviceBidGenerator(cfg)
    fused = EPOCH_BUILDERS["source_session"](gen.chunk_fn(), exprs, core,
                                             CHUNK)
    gather = jax.jit(core.gather_closed, static_argnames=("out_capacity",))
    us_per_event = max(1_000_000 // max(cfg.events_per_second, 1), 1)

    def run(state, n, start_event, batch_no):
        last = None
        done = 0
        while done < n:
            per = min(CHUNKS_PER_EPOCH, n - done)
            done += per
            key = jax.random.fold_in(jax.random.PRNGKey(31), batch_no)
            batch_no += 1
            end_event = start_event + per * CHUNK
            wm = cfg.start_time_us + end_event * us_per_event - Q8_GAP_US
            state, snap, packed = fused(state, jnp.int64(start_event),
                                        key, per, jnp.int64(wm))
            start_event = end_event
            n_closed, ovf, covf, sawdel, ooo = (
                int(x) for x in jax.device_get(packed))
            if ovf or covf or sawdel or ooo:
                raise RuntimeError(
                    f"q8 fused: flags table_ovf={ovf} closed_ovf={covf} "
                    f"saw_delete={sawdel} out_of_order={ooo}")
            lo = 0
            while lo < n_closed:
                last = gather(snap, jnp.int64(n_closed), jnp.int64(lo),
                              out_capacity=CHUNK)
                lo += CHUNK
        if last is not None:
            jax.block_until_ready(last)
        return state, start_event, batch_no

    state, start_event, batch_no = run(
        core.init_state(), WARMUP_CHUNKS, 0, 0)    # compile everything
    jax.block_until_ready(state.last_ts)
    t0 = time.perf_counter()
    state, _, _ = run(state, n_chunks, start_event, batch_no)
    jax.block_until_ready(state.last_ts)
    elapsed = time.perf_counter() - t0
    return n_chunks * CHUNK / elapsed


def measure_q3_fused(n_chunks: int) -> float:
    """Sustained source rows/s of the TPC-H q3 streaming MV: orders-table
    build + lineitem probe + revenue agg + top-10 churn fused into one
    dispatch per epoch (ops/stream_q3.py + fused_source_q3_epoch). The
    flush output is a fixed 20-row churn chunk returned BY the dispatch —
    zero extra gathers."""
    import jax
    import jax.numpy as jnp
    from risingwave_tpu.connector.tpch import (
        DeviceQ3Generator, Q3_CUTOFF_DAYS, TpchQ3Config,
    )
    from risingwave_tpu.ops.fused_epoch import EPOCH_BUILDERS
    from risingwave_tpu.ops.stream_q3 import Q3Core

    gen = DeviceQ3Generator(TpchQ3Config(chunk_capacity=CHUNK))
    core = Q3Core(Q3_CUTOFF_DAYS, orders_capacity=Q3_ORDERS_CAP,
                  agg_capacity=Q3_AGG_CAP)
    fused = EPOCH_BUILDERS["source_q3"](gen.chunk_fn(), core, CHUNK)

    def run(state, n, start_event, batch_no):
        last = None
        done = 0
        while done < n:
            per = min(CHUNKS_PER_EPOCH, n - done)
            done += per
            key = jax.random.fold_in(jax.random.PRNGKey(37), batch_no)
            batch_no += 1
            state, out, packed = fused(state, jnp.int64(start_event),
                                       key, per)
            start_event += per * CHUNK
            _n_out, o_ovf, a_ovf, sawdel = (
                int(x) for x in jax.device_get(packed))
            if o_ovf or a_ovf or sawdel:
                raise RuntimeError(
                    f"q3 fused: flags orders_ovf={o_ovf} agg_ovf={a_ovf} "
                    f"saw_delete={sawdel}")
            last = out
        if last is not None:
            jax.block_until_ready(last)
        return state, start_event, batch_no

    state, start_event, batch_no = run(
        core.init_state(), WARMUP_CHUNKS, 0, 0)
    jax.block_until_ready(state.odate)
    t0 = time.perf_counter()
    state, _, _ = run(state, n_chunks, start_event, batch_no)
    jax.block_until_ready(state.odate)
    elapsed = time.perf_counter() - t0
    return n_chunks * CHUNK / elapsed


def measure_q5_sharded_fused(n_chunks: int, n_shards: int) -> float:
    """Aggregate source rows/s of the q5 core MESH-SHARDED: generation,
    projection, the in-dispatch vnode all_to_all shuffle, and per-shard
    aggregation fused into one dispatch per epoch across ``n_shards``
    devices (ops/fused_sharded.py). The flush is one packed fetch for
    every shard + per-shard churn gathers — the solo fused barrier
    cadence, at mesh width."""
    import jax
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.ops.grouped_agg import AggCore
    from risingwave_tpu.parallel.fused import ShardedFusedAgg
    from risingwave_tpu.parallel.sharded_agg import make_mesh

    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(WINDOW_US, INT64)),
        col(0, INT64),
    ]
    # capacities are PER SHARD: the group set partitions across the mesh
    core = AggCore([INT64, INT64], [0, 1], [count_star()],
                   max((1 << 21) // n_shards, 1 << 16), CHUNK)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CHUNK))
    sf = ShardedFusedAgg(make_mesh(n_shards), core, gen.chunk_fn(),
                         exprs, CHUNK)

    def run(n, start_event, batch_no):
        done = 0
        while done < n:
            per = min(CHUNKS_PER_EPOCH, n - done)
            done += per
            key = jax.random.fold_in(jax.random.PRNGKey(17), batch_no)
            batch_no += 1
            sf.run_epoch(start_event, key, per)
            start_event += per * CHUNK
            sf.flush()
        return start_event, batch_no

    start_event, batch_no = run(SHARDED_WARMUP_CHUNKS, 0, 0)
    jax.block_until_ready(sf.stacked.lanes)
    t0 = time.perf_counter()
    run(n_chunks, start_event, batch_no)
    jax.block_until_ready(sf.stacked.lanes)
    return n_chunks * CHUNK / (time.perf_counter() - t0)


def measure_q7_sharded_fused(n_chunks: int, n_shards: int) -> float:
    """Aggregate source rows/s of the q7 core MESH-SHARDED: the bucketed
    interval join's ring partitions by window vnode across the mesh
    (per-shard ring ≈ solo/n — windows spread uniformly under the hash),
    and one dispatch per epoch covers every shard's ingest AND flush
    plan; ONE [n, 6] packed fetch covers all flags and counts."""
    import jax
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.ops.interval_join import IntervalJoinCore
    from risingwave_tpu.parallel.fused import ShardedFusedJoin
    from risingwave_tpu.parallel.sharded_agg import make_mesh

    exprs = [
        call("tumble_start", col(5, TIMESTAMP),
             Literal(Q7_WINDOW_US, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    probe_schema = Schema((Field("window_start", TIMESTAMP),
                           Field("auction", INT64), Field("price", INT64)))
    core = IntervalJoinCore(
        probe_schema, ts_col=0, val_col=2, window_us=Q7_WINDOW_US,
        # per-shard ring: 2x the expected windows-per-shard share
        n_buckets=max(2 * Q7_BUCKETS // n_shards, 1 << 10),
        lane_width=Q7_LANES)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CHUNK))
    sf = ShardedFusedJoin(make_mesh(n_shards), core, gen.chunk_fn(),
                          exprs, CHUNK)

    def run(n, start_event, batch_no):
        last = None
        done = 0
        while done < n:
            per = min(CHUNKS_PER_EPOCH, n - done)
            done += per
            key = jax.random.fold_in(jax.random.PRNGKey(23), batch_no)
            batch_no += 1
            sf.run_epoch(start_event, key, per)
            start_event += per * CHUNK
            probe, churn = sf.flush(out_capacity=CHUNK)
            if churn:
                last = churn[-1]
            elif probe:
                last = probe[-1]
        if last is not None:
            jax.block_until_ready(last)
        return start_event, batch_no

    start_event, batch_no = run(SHARDED_WARMUP_CHUNKS, 0, 0)
    jax.block_until_ready(sf.stacked.cur_max)
    t0 = time.perf_counter()
    run(n_chunks, start_event, batch_no)
    jax.block_until_ready(sf.stacked.cur_max)
    return n_chunks * CHUNK / (time.perf_counter() - t0)


def measure_q8_sharded_fused(n_chunks: int, n_shards: int) -> float:
    """Aggregate source rows/s of the q8 session-window core
    MESH-SHARDED (ops/fused_sharded.sharded_session_epoch): generation,
    projection, the in-dispatch vnode all_to_all route by session key,
    per-shard sessionization AND the watermark close in one dispatch
    per epoch; ONE [n, 6] packed fetch covers all flags and counts."""
    import jax
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import col
    from risingwave_tpu.ops.session_window import SessionWindowCore
    from risingwave_tpu.parallel.fused import ShardedFusedSession
    from risingwave_tpu.parallel.sharded_agg import make_mesh

    exprs = [col(1, INT64), col(5, TIMESTAMP)]   # bidder, date_time
    schema = Schema((Field("bidder", INT64), Field("ts", TIMESTAMP)))
    # capacities are PER SHARD: keys partition across the mesh
    core = SessionWindowCore(
        schema, key_col=0, ts_col=1, gap_us=Q8_GAP_US,
        capacity=max(Q8_TABLE_CAP // n_shards, 1 << 14),
        closed_capacity=max(Q8_CLOSED_CAP // n_shards, 1 << 14))
    cfg = NexmarkConfig(chunk_capacity=CHUNK)
    gen = DeviceBidGenerator(cfg)
    sf = ShardedFusedSession(make_mesh(n_shards), core, gen.chunk_fn(),
                             exprs, CHUNK)
    us_per_event = max(1_000_000 // max(cfg.events_per_second, 1), 1)

    def run(n, start_event, batch_no):
        done = 0
        while done < n:
            per = min(CHUNKS_PER_EPOCH, n - done)
            done += per
            key = jax.random.fold_in(jax.random.PRNGKey(31), batch_no)
            batch_no += 1
            end_event = start_event + per * CHUNK
            wm = cfg.start_time_us + end_event * us_per_event - Q8_GAP_US
            sf.run_epoch(start_event, key, per, wm)
            start_event = end_event
            sf.flush(out_capacity=CHUNK)
        return start_event, batch_no

    start_event, batch_no = run(SHARDED_WARMUP_CHUNKS, 0, 0)
    jax.block_until_ready(sf.stacked.last_ts)
    t0 = time.perf_counter()
    run(n_chunks, start_event, batch_no)
    jax.block_until_ready(sf.stacked.last_ts)
    return n_chunks * CHUNK / (time.perf_counter() - t0)


def measure_q3_sharded_fused(n_chunks: int, n_shards: int) -> float:
    """Aggregate source rows/s of the TPC-H q3 streaming MV
    MESH-SHARDED (ops/fused_sharded.sharded_q3_epoch): orders +
    lineitems route by orderkey, per-shard build/probe/agg, and the
    GLOBAL top-10 churn (local top-k → all_gather → shared recompute)
    all inside one dispatch per epoch."""
    import jax
    from risingwave_tpu.connector.tpch import (
        DeviceQ3Generator, Q3_CUTOFF_DAYS, TpchQ3Config,
    )
    from risingwave_tpu.ops.stream_q3 import Q3Core
    from risingwave_tpu.parallel.fused import ShardedFusedQ3
    from risingwave_tpu.parallel.sharded_agg import make_mesh

    gen = DeviceQ3Generator(TpchQ3Config(chunk_capacity=CHUNK))
    core = Q3Core(Q3_CUTOFF_DAYS,
                  orders_capacity=max(Q3_ORDERS_CAP // n_shards, 1 << 14),
                  agg_capacity=max(Q3_AGG_CAP // n_shards, 1 << 14))
    sf = ShardedFusedQ3(make_mesh(n_shards), core, gen.chunk_fn(), CHUNK)

    def run(n, start_event, batch_no):
        done = 0
        while done < n:
            per = min(CHUNKS_PER_EPOCH, n - done)
            done += per
            key = jax.random.fold_in(jax.random.PRNGKey(37), batch_no)
            batch_no += 1
            sf.run_epoch(start_event, key, per)
            start_event += per * CHUNK
            sf.flush()
        return start_event, batch_no

    start_event, batch_no = run(SHARDED_WARMUP_CHUNKS, 0, 0)
    jax.block_until_ready(sf.stacked.odate)
    t0 = time.perf_counter()
    run(n_chunks, start_event, batch_no)
    jax.block_until_ready(sf.stacked.odate)
    return n_chunks * CHUNK / (time.perf_counter() - t0)


def measure_cosched_sharded(n_chunks: int, n_shards: int,
                            n_jobs: int) -> float:
    """Aggregate source rows/s of ``n_jobs`` signature-equal q5-shaped
    MVs × ``n_shards`` mesh shards — the SIXTH fusion surface
    (ops/fused_sharded.build_sharded_group_epoch): the whole K×S group
    is ONE dispatch per epoch, so rows/s counts every job's stream."""
    import jax
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.ops.grouped_agg import AggCore
    from risingwave_tpu.parallel.fused import ShardedCoGroup
    from risingwave_tpu.parallel.sharded_agg import make_mesh
    from risingwave_tpu.stream.coschedule import FusedJobSpec

    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(WINDOW_US, INT64)),
        col(0, INT64),
    ]
    core = AggCore([INT64, INT64], [0, 1], [count_star()],
                   max((1 << 21) // n_shards, 1 << 16), CHUNK)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CHUNK))
    spec = FusedJobSpec("agg", ("bench_sharded_cosched",),
                        gen.chunk_fn(), tuple(exprs), core, CHUNK, seed=0)
    group = ShardedCoGroup(make_mesh(n_shards), spec)
    for j in range(n_jobs):
        group.add(f"mv{j}", seed=j)

    def run(n):
        done = 0
        while done < n:
            per = min(CHUNKS_PER_EPOCH, n - done)
            done += per
            group.run_epoch(per)
            group.flush()

    run(SHARDED_WARMUP_CHUNKS)
    jax.block_until_ready(group.stacked.lanes)
    t0 = time.perf_counter()
    run(n_chunks)
    jax.block_until_ready(group.stacked.lanes)
    return n_jobs * n_chunks * CHUNK / (time.perf_counter() - t0)


def run_sharded_phase(n_chunks: int, q7_chunks: int) -> None:
    """Child entry for the mesh-sharded fused phase: measure q5 at
    every shard count this process's backend can host, and the heavier
    surfaces — q7, q8, q3, and the K×S co-scheduled group — once at
    the widest mesh; print one JSON line (MULTICHIP-style: n_devices +
    ok + per-shard-count rates)."""
    import jax
    n_devices = len(jax.devices())
    by_shards: dict = {}
    for n in SHARDED_SHARD_COUNTS:
        if n > n_devices:
            continue
        entry = {"q5_rows_per_sec": round(
            measure_q5_sharded_fused(n_chunks, n), 1)}
        if n == max(c for c in SHARDED_SHARD_COUNTS if c <= n_devices):
            # the slow measurements run once, at the widest mesh
            entry["q7_rows_per_sec"] = round(
                measure_q7_sharded_fused(q7_chunks, n), 1)
            entry["q8_rows_per_sec"] = round(
                measure_q8_sharded_fused(q7_chunks, n), 1)
            entry["q3_rows_per_sec"] = round(
                measure_q3_sharded_fused(q7_chunks, n), 1)
            entry["cosched_rows_per_sec"] = round(
                measure_cosched_sharded(q7_chunks, n,
                                        COSCHED_SHARDED_JOBS), 1)
        by_shards[str(n)] = entry
    widest = max((int(k) for k in by_shards), default=0)
    top = by_shards.get(str(widest), {})
    _emit({
        "metric": "sharded_fused_epochs",
        "unit": "rows/s",
        "n_devices": n_devices,
        "ok": bool(by_shards),
        "backend": jax.default_backend(),
        "sharded_fused_shards": widest,
        "sharded_fused_by_shards": by_shards,
        "q5_sharded_fused_rows_per_sec": top.get("q5_rows_per_sec"),
        "q7_sharded_fused_rows_per_sec": top.get("q7_rows_per_sec"),
        "q8_sharded_fused_rows_per_sec": top.get("q8_rows_per_sec"),
        "q3_sharded_fused_rows_per_sec": top.get("q3_rows_per_sec"),
        "cosched_sharded_rows_per_sec": top.get("cosched_rows_per_sec"),
        "cosched_sharded_jobs": (COSCHED_SHARDED_JOBS
                                 if "cosched_rows_per_sec" in top
                                 else None),
    })


def _cosched_parts():
    """Ops-level build for the --smoke dispatch-count check: one small
    q5-shaped agg core + projection over the device bid source."""
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.expr import Literal, call, col
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.stream import HashAggExecutor, ProjectExecutor
    from risingwave_tpu.stream.source import MockSource

    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(WINDOW_US, INT64)),
        col(0, INT64),
    ]
    proj = ProjectExecutor(MockSource(BID_SCHEMA, []), exprs,
                           names=("window_start", "auction"))
    agg = HashAggExecutor(proj, [0, 1], [count_star()],
                          table_capacity=COSCHED_SMOKE_TABLE,
                          out_capacity=COSCHED_SMOKE_CHUNK)
    gen = DeviceBidGenerator(
        NexmarkConfig(chunk_capacity=COSCHED_SMOKE_CHUNK))
    return exprs, agg, gen.chunk_fn()


_COSCHED_SOURCE_SQL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT,
    price BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
    extra VARCHAR) WITH (connector = 'nexmark', nexmark_table = 'bid')"""


def _cosched_session_rate(coschedule: bool, n_jobs: int, n_ticks: int,
                          warmup_ticks: int, pipeline_depth: int = 1,
                          data_dir=None,
                          checkpoint_frequency: int = 10):
    """Aggregate source rows/s (plus the measured window's barrier
    latency snapshot) of ``n_jobs`` small q5-shaped MVs ticked
    end-to-end through one Session. ``coschedule`` toggles group-batched
    fused dispatch vs per-MV executor pipelines; ``pipeline_depth``
    toggles the asynchronous epoch pipeline; ``data_dir`` makes the
    session durable (the pipelined checkpoint-encode offload only
    exists on a durable tier)."""
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig

    s = Session(config=BuildConfig(coschedule=coschedule,
                                   agg_table_capacity=COSCHED_TABLE_CAP,
                                   chunk_capacity=COSCHED_CHUNK),
                source_chunk_capacity=COSCHED_CHUNK,
                checkpoint_frequency=checkpoint_frequency,
                chunks_per_tick=COSCHED_CHUNKS_PER_TICK,
                pipeline_depth=pipeline_depth,
                data_dir=data_dir)
    try:
        s.run_sql(_COSCHED_SOURCE_SQL)
        for j in range(n_jobs):
            s.run_sql(f"CREATE MATERIALIZED VIEW cosched_mv{j} AS "
                      "SELECT auction, count(*) AS n FROM bid "
                      "GROUP BY auction")
        for _ in range(warmup_ticks):     # jit compiles land here
            s.tick()
        s.barrier_latency.samples.clear()
        t0 = time.perf_counter()
        for _ in range(n_ticks):
            s.tick()
        elapsed = time.perf_counter() - t0
        lat = s.barrier_latency.snapshot()
    finally:
        s.close()
    return (n_jobs * n_ticks * COSCHED_CHUNKS_PER_TICK * COSCHED_CHUNK
            / elapsed, lat)


def measure_coscheduled(n_jobs: int, n_ticks: int) -> dict:
    """The many-small-MVs phase: ``n_jobs`` identical NEXmark-shaped MVs
    in one Session, co-scheduled ([streaming] coschedule = true — the
    whole group's epoch is ONE vmapped dispatch per tick,
    stream/coschedule.py) vs sequential (the same CREATEs with the flag
    off: one executor pipeline per MV, each dispatching its own epochs —
    exactly the pre-coscheduler session). End-to-end rows/s through
    materialization, so the ratio is the user-visible win."""
    seq, _ = _cosched_session_rate(False, n_jobs, n_ticks,
                                   COSCHED_WARMUP_TICKS)
    cos, _ = _cosched_session_rate(True, n_jobs, n_ticks,
                                   COSCHED_WARMUP_TICKS)
    return {
        "coscheduled_mvs_rows_per_sec": round(cos, 1),
        "coscheduled_sequential_rows_per_sec": round(seq, 1),
        "coschedule_speedup": round(cos / seq, 2),
        "coscheduled_n_mvs": n_jobs,
    }


def _hetero_mv_sql(j: int) -> str:
    """The j-th DISSIMILAR small MV: three skeletons (sum-with-literal,
    count+max over another key, plain count) with a per-j literal so
    same-skeleton MVs still differ — the tick compiler must lift the
    literal into a parameter hole to fuse them."""
    kind = j % 3
    if kind == 0:
        return (f"CREATE MATERIALIZED VIEW hetero_mv{j} AS "
                f"SELECT auction, sum(price + {100 + j}) AS s "
                "FROM bid GROUP BY auction")
    if kind == 1:
        return (f"CREATE MATERIALIZED VIEW hetero_mv{j} AS "
                "SELECT bidder, count(*) AS c, max(price) AS m "
                "FROM bid GROUP BY bidder")
    return (f"CREATE MATERIALIZED VIEW hetero_mv{j} AS "
            "SELECT auction, count(*) AS c FROM bid GROUP BY auction")


def _hetero_session_rate(tick_compiler: bool, n_jobs: int, n_ticks: int,
                         warmup_ticks: int):
    """Aggregate source rows/s of ``n_jobs`` DISSIMILAR small MVs
    ticked end-to-end through one Session; ``tick_compiler`` toggles
    the compiled minimal-dispatch schedule vs per-MV executor
    pipelines. Returns ``(rows_per_sec, dispatches_per_tick)`` —
    dispatches_per_tick is None on the baseline."""
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig

    s = Session(config=BuildConfig(tick_compiler=tick_compiler,
                                   agg_table_capacity=COSCHED_TABLE_CAP,
                                   chunk_capacity=COSCHED_CHUNK),
                source_chunk_capacity=COSCHED_CHUNK,
                chunks_per_tick=COSCHED_CHUNKS_PER_TICK)
    try:
        s.run_sql(_COSCHED_SOURCE_SQL)
        for j in range(n_jobs):
            s.run_sql(_hetero_mv_sql(j))
        for _ in range(warmup_ticks):     # jit compiles land here
            s.tick()
        t0 = time.perf_counter()
        for _ in range(n_ticks):
            s.tick()
        elapsed = time.perf_counter() - t0
        dpt = (s.metrics()["hetero"]["dispatches_per_tick"]
               if tick_compiler else None)
    finally:
        s.close()
    return (n_jobs * n_ticks * COSCHED_CHUNKS_PER_TICK * COSCHED_CHUNK
            / elapsed, dpt)


def measure_hetero(n_jobs: int, n_ticks: int) -> dict:
    """The heterogeneous many-small-MVs phase (ISSUE 19): ``n_jobs``
    DISSIMILAR NEXmark-shaped MVs in one Session, tick-compiled
    ([streaming] tick_compiler = true — shape-class padded supergroups
    + jitted mega-epochs, stream/tick_compiler.py) vs sequential (the
    same CREATEs with the flag off: one executor pipeline per MV).
    End-to-end rows/s through materialization."""
    seq, _ = _hetero_session_rate(False, n_jobs, n_ticks,
                                  COSCHED_WARMUP_TICKS)
    het, dpt = _hetero_session_rate(True, n_jobs, n_ticks,
                                    COSCHED_WARMUP_TICKS)
    return {
        "hetero_rows_per_sec": round(het, 1),
        "hetero_sequential_rows_per_sec": round(seq, 1),
        "hetero_speedup": round(het / seq, 2),
        "hetero_dispatches_per_tick": dpt,
        "hetero_n_mvs": n_jobs,
    }


def run_hetero_phase(n_jobs: int, n_ticks: int) -> None:
    """Child entry for ``--hetero-phase``: the heterogeneous
    tick-compiler measurement alone, one JSON line."""
    out = {"metric": "hetero_tick_compiler_rows_per_sec",
           "unit": "rows/s"}
    out.update(measure_hetero(n_jobs, n_ticks))
    out["value"] = out["hetero_rows_per_sec"]
    _emit(out)


def measure_pipelined(n_jobs: int, n_ticks: int) -> dict:
    """The asynchronous-epoch-pipeline phase (docs/performance.md
    "Pipelined tick"): the SAME 16-MV co-scheduled workload, durable
    (tempdir segment store, checkpoint every 5th barrier), measured
    with ``[streaming] pipeline_depth`` 1 vs 2 — the only variable.
    Depth 2 defers each packed flush fetch one tick (epoch N+1's
    dispatch launches before epoch N's stats resolve) and moves the
    checkpoint segment encode+write onto a worker thread, so both
    rows/s and the checkpoint-tick latency tail (p99) are reported."""
    import shutil
    import tempfile

    dirs = [tempfile.mkdtemp(prefix="rwtpu_bench_pipe_")
            for _ in range(2)]
    try:
        off, off_lat = _cosched_session_rate(
            True, n_jobs, n_ticks, COSCHED_WARMUP_TICKS,
            pipeline_depth=1, data_dir=dirs[0], checkpoint_frequency=5)
        on, on_lat = _cosched_session_rate(
            True, n_jobs, n_ticks, COSCHED_WARMUP_TICKS,
            pipeline_depth=2, data_dir=dirs[1], checkpoint_frequency=5)
    finally:
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)
    return {
        "pipeline_on_rows_per_sec": round(on, 1),
        "pipeline_off_rows_per_sec": round(off, 1),
        "pipeline_speedup": round(on / off, 2),
        "pipeline_on_p50_barrier_ms": on_lat.get("p50_ms"),
        "pipeline_on_p99_barrier_ms": on_lat.get("p99_ms"),
        "pipeline_off_p50_barrier_ms": off_lat.get("p50_ms"),
        "pipeline_off_p99_barrier_ms": off_lat.get("p99_ms"),
        "pipeline_depth": 2,
    }


def measure_barrier_latency(in_flight: int = 1) -> dict:
    """p99 barrier latency under a live Session-driven NEXmark MV at the
    reference's defaults (checkpoint every 10th barrier — BASELINE.md
    methodology / docs/metrics.md semantics)."""
    from risingwave_tpu.frontend import Session
    s = Session(source_chunk_capacity=CHUNK, checkpoint_frequency=10,
                in_flight_barriers=in_flight)
    s.run_sql("""CREATE SOURCE bid (auction BIGINT, price BIGINT)
                 WITH (connector = 'nexmark', nexmark_table = 'bid')""")
    s.run_sql("""CREATE MATERIALIZED VIEW m AS
        SELECT auction, count(*) AS n FROM bid GROUP BY auction""")
    for _ in range(5):
        s.tick()                    # warmup: jit compiles land here
    s._drain_inflight()
    s.barrier_latency.samples.clear()
    for _ in range(30):
        s.tick()
    s._drain_inflight()
    snap = s.barrier_latency.snapshot()
    # per-stage waterfall percentiles from the barrier ledger (ISSUE 16)
    # ride along so the trend record shows WHERE latency moved, not just
    # that it moved
    snap["stages"] = s._barrier_ledger.stage_percentiles()
    s.close()
    return snap


_SERVING_BID_DDL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT,
price BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
extra VARCHAR) WITH (connector = 'nexmark', nexmark_table = 'bid')"""

_SERVING_Q5 = """CREATE MATERIALIZED VIEW q5 AS
    SELECT AuctionBids.auction, AuctionBids.num FROM (
        SELECT bid.auction, count(*) AS num, window_start AS starttime
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY window_start, bid.auction
    ) AS AuctionBids
    JOIN (
        SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
        FROM (
            SELECT count(*) AS num, window_start AS starttime_c
            FROM HOP(bid, date_time, INTERVAL '2' SECOND,
                     INTERVAL '10' SECOND)
            GROUP BY bid.auction, window_start
        ) AS CountBids
        GROUP BY CountBids.starttime_c
    ) AS MaxBids
    ON AuctionBids.starttime = MaxBids.starttime_c
       AND AuctionBids.num = MaxBids.maxn"""


def _serving_run(cached: bool, seconds: float, n_threads: int) -> dict:
    """One serving variant end to end: live q5 MV, a tick thread keeping
    the stream moving, ``n_threads`` readers issuing point-lookups and
    small group-by reads through ``Session.query``. ``cached=False``
    zeroes the plan cache and the two-phase split — every query replans,
    relowers, and runs single-phase under the API lock (the
    pre-serving-plane read path)."""
    from risingwave_tpu.common.config import load_config
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.parser import parse_sql

    overrides = {"streaming.chunk_capacity": 512}
    if not cached:
        overrides.update({"batch.serving_cache_size": 0,
                          "batch.serving_tasks": 1})
    s = Session(rw_config=load_config(None, **overrides))
    s.run_sql(_SERVING_BID_DDL)
    s.run_sql(_SERVING_Q5)
    for _ in range(SERVING_WARM_TICKS):
        s.tick()
    s.flush()
    rows = s.mv_rows("q5")
    key = rows[0][0] if rows else 1000
    point = parse_sql(f"SELECT num FROM q5 WHERE auction = {key}")[0].select
    group = parse_sql("SELECT auction % 8, count(*), sum(num) "
                      "FROM q5 GROUP BY auction % 8")[0].select
    s.query(point)                      # warm: compiles land here
    s.query(group)

    stop = threading.Event()

    def ticker():
        while not stop.is_set():
            s.tick()
            stop.wait(SERVING_TICK_S)

    lat: dict = {0: [], 1: []}
    counts = [0] * n_threads
    errors: list = []
    t_tick = threading.Thread(target=ticker, daemon=True)

    def reader(idx: int, deadline: float):
        sels = (point, group)
        mine = ([], [])
        i = 0
        try:
            while time.perf_counter() < deadline:
                t0 = time.perf_counter()
                s.query(sels[i % 2])
                mine[i % 2].append(time.perf_counter() - t0)
                i += 1
        except BaseException as e:  # noqa: BLE001 - fails the phase
            errors.append(f"reader {idx}: {type(e).__name__}: {e}")
        counts[idx] = i
        lat[0].extend(mine[0])
        lat[1].extend(mine[1])

    t0 = time.perf_counter()
    deadline = t0 + seconds
    t_tick.start()
    threads = [threading.Thread(target=reader, args=(i, deadline))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    t_tick.join()
    wall = time.perf_counter() - t0
    m = s.metrics()["serving"]
    s.close()
    if errors:
        # a dead reader would silently skew QPS/p99 — attribute it like
        # every other phase failure instead
        raise RuntimeError("; ".join(errors))
    allq = sorted(lat[0] + lat[1])

    def pct(xs, q):
        return round(xs[min(len(xs) - 1, int(q * len(xs)))] * 1e3, 3) \
            if xs else None

    return {
        "qps": round(sum(counts) / wall, 1),
        "point_qps": round(len(lat[0]) / wall, 1),
        "group_qps": round(len(lat[1]) / wall, 1),
        "p50_ms": pct(allq, 0.5),
        "p99_ms": pct(allq, 0.99),
        "cache_hits": m["cache_hits"],
        "cache_misses": m["cache_misses"],
        "reexecutions": m["reexecutions"],
        "tasks_fired_local": m["tasks_fired_local"],
    }


def run_serving_phase(seconds: float, n_threads: int) -> None:
    """Child entry for --serving-phase: cached+two-phase vs uncached
    single-phase, one JSON line."""
    base = _serving_run(False, seconds, n_threads)
    served = _serving_run(True, seconds, n_threads)
    out = {
        "metric": "serving_qps", "unit": "queries/s",
        "value": served["qps"],
        "serving_qps": served["qps"],
        "serving_point_qps": served["point_qps"],
        "serving_group_qps": served["group_qps"],
        "serving_p50_ms": served["p50_ms"],
        "serving_p99_ms": served["p99_ms"],
        "serving_baseline_qps": base["qps"],
        "serving_baseline_p99_ms": base["p99_ms"],
        "serving_speedup": (round(served["qps"] / base["qps"], 2)
                            if base["qps"] else None),
        "serving_threads": n_threads,
        "serving_cache_hits": served["cache_hits"],
        "serving_reexecutions": served["reexecutions"],
    }
    _emit(out)


def _pg_startup(sock) -> None:
    """Minimal pgwire client startup (trust auth) on a raw socket."""
    import struct
    body = struct.pack("!I", 196608) + b"user\x00bench\x00\x00"
    sock.sendall(struct.pack("!I", len(body) + 4) + body)
    buf = b""
    while b"Z\x00\x00\x00\x05I" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            raise ConnectionError("pgwire startup EOF")
        buf += chunk


def _pg_query(sock, sql: str) -> bytes:
    """One simple-protocol query; returns the raw response bytes
    (ending with ReadyForQuery)."""
    import struct
    body = sql.encode() + b"\x00"
    sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
    buf = b""
    while not buf.endswith(b"Z\x00\x00\x00\x05I"):
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("pgwire EOF mid-query")
        buf += chunk
    return buf


def run_fleet_frontend(meta_addr: str, data_dir: str) -> None:
    """Hidden child entry for --fleet-frontend: attach ONE read-only
    serving session to the fleet's meta + shared state dir, serve it
    over pgwire on an ephemeral port, print ``FLEET_READY <port>``,
    run until the parent writes a line on stdin, then print
    ``FLEET_STATS {json}`` (admission counters + serving-cache hits)
    and exit."""
    import asyncio as _asyncio

    from risingwave_tpu.frontend.pgwire import PgWireServer
    from risingwave_tpu.frontend.session import Session

    sess = Session(data_dir=data_dir, meta_addr=meta_addr, role="serving")
    srv = PgWireServer(sess, port=0)
    loop = _asyncio.new_event_loop()
    _asyncio.set_event_loop(loop)
    loop.run_until_complete(srv.start())
    port = srv._server.sockets[0].getsockname()[1]
    print(f"FLEET_READY {port}", flush=True)

    def wait_stdin():
        sys.stdin.readline()           # parent writes STOP (or closes)
        loop.call_soon_threadsafe(loop.stop)

    threading.Thread(target=wait_stdin, daemon=True).start()
    loop.run_forever()
    loop.run_until_complete(srv.close())
    m = sess.metrics()["serving"]
    print("FLEET_STATS " + json.dumps(
        {"admission": srv.admission.snapshot(),
         "cache_hits": m["cache_hits"],
         "cache_misses": m["cache_misses"]}), flush=True)
    sess.close()


def run_fleet_phase(seconds: float, n_frontends: int,
                    n_conns: int) -> None:
    """Child entry for --fleet-phase: the multi-tenant control plane end
    to end — a standalone MetaServer and one writer session build an MV
    over a shared durable hummock dir; ``n_frontends`` serving frontend
    PROCESSES attach read-only and serve it over pgwire; ``n_conns``
    connections per frontend hammer the same cached point read. Emits
    merged fleet QPS + p50/p99 and the admission counters (queued /
    shed) summed across frontends. One JSON line."""
    import socket
    import tempfile

    from risingwave_tpu.frontend.session import Session
    from risingwave_tpu.meta.server import MetaServer

    d = tempfile.mkdtemp(prefix="rwtpu_bench_fleet_")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(d, "jax_cache"))
    meta = MetaServer(data_dir=os.path.join(d, "meta"))
    addr = meta.start()
    writer = Session(data_dir=d, meta_addr=addr, state_store="hummock")
    procs: list = []
    lats: list = []
    stats: list = []
    try:
        writer.run_sql("CREATE TABLE ft (k BIGINT, v BIGINT)")
        writer.run_sql("INSERT INTO ft VALUES " + ", ".join(
            f"({i % 64}, {i})" for i in range(512)))
        writer.run_sql(
            "CREATE MATERIALIZED VIEW fleet_mv AS SELECT k, "
            "count(*) AS n, sum(v) AS s FROM ft GROUP BY k")
        writer.flush()

        ports = []
        for _ in range(n_frontends):
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--fleet-frontend", addr, d],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                text=True,
                cwd=os.path.dirname(os.path.abspath(__file__))))
        for pr in procs:
            while True:
                line = pr.stdout.readline()
                if not line:
                    raise RuntimeError("fleet frontend died during attach")
                if line.startswith("FLEET_READY "):
                    ports.append(int(line.split()[1]))
                    break

        lat_lock = threading.Lock()
        stop_at = time.perf_counter() + seconds

        def reader(port: int) -> None:
            sock = socket.create_connection(("127.0.0.1", port))
            try:
                _pg_startup(sock)
                sql = "SELECT k, n, s FROM fleet_mv WHERE k = 7"
                _pg_query(sock, sql)          # warm the plan cache
                mine = []
                while time.perf_counter() < stop_at:
                    t0 = time.perf_counter()
                    _pg_query(sock, sql)
                    mine.append((time.perf_counter() - t0) * 1e3)
                with lat_lock:
                    lats.extend(mine)
            finally:
                sock.close()

        threads = [threading.Thread(target=reader, args=(p,))
                   for p in ports for _ in range(n_conns)]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        for pr in procs:
            try:
                pr.stdin.write("STOP\n")
                pr.stdin.flush()
            except OSError:
                pass
            out, _ = pr.communicate(timeout=60)
            for line in out.splitlines():
                if line.startswith("FLEET_STATS "):
                    stats.append(json.loads(line[len("FLEET_STATS "):]))
    finally:
        for pr in procs:
            if pr.poll() is None:
                pr.kill()
        writer.close()
        meta.stop()

    lats.sort()

    def pct(q: float):
        if not lats:
            return None
        return round(lats[min(len(lats) - 1, int(q * len(lats)))], 2)

    _emit({
        "metric": "fleet_qps", "unit": "queries/s",
        "value": round(len(lats) / wall, 1) if lats else 0.0,
        "fleet_qps": round(len(lats) / wall, 1) if lats else 0.0,
        "fleet_p50_ms": pct(0.50),
        "fleet_p99_ms": pct(0.99),
        "fleet_queued": sum(s["admission"]["queued"] for s in stats),
        "fleet_shed": sum(s["admission"]["shed"] for s in stats),
        "fleet_frontends": n_frontends,
        "fleet_conns_per_frontend": n_conns,
        "fleet_cache_hits": sum(s["cache_hits"] for s in stats),
    })


def run_rescale_phase(ticks: int = 6, cap: int = 256) -> None:
    """Child entry for --rescale-phase: one LIVE 2→4 vnode migration of
    a spanning grouped-agg job on a 4-worker cluster (docs/scaling.md),
    recording rows/s before / during / after plus the migration pause
    (drain→init wall time) and the moved vnode count. One JSON line."""
    import tempfile

    from risingwave_tpu.frontend.build import BuildConfig
    from risingwave_tpu.frontend.session import Session

    d = tempfile.mkdtemp(prefix="rwtpu_bench_rescale_")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(d, "jax_cache"))
    s = Session(workers=4, seed=42, data_dir=d, source_chunk_capacity=cap,
                config=BuildConfig(fragment_parallelism=2))
    try:
        s.run_sql(
            "CREATE SOURCE bid (auction BIGINT, bidder BIGINT, "
            "price BIGINT, channel VARCHAR, url VARCHAR, "
            "date_time TIMESTAMP, extra VARCHAR) "
            "WITH (connector = 'nexmark', nexmark_table = 'bid')")
        s.run_sql("CREATE MATERIALIZED VIEW q AS SELECT auction, "
                  "count(*) AS n, max(price) AS mx FROM bid "
                  "GROUP BY auction")
        assert "q" in s._spanning_specs, "q did not span workers"

        def run_ticks(n: int) -> float:
            t0 = time.perf_counter()
            for _ in range(n):
                s.tick()
            return (n * s.chunks_per_tick * cap) / (
                time.perf_counter() - t0)

        run_ticks(2)                       # warm the compiled graphs
        before = run_ticks(ticks)
        t0 = time.perf_counter()
        out = s.rescale("q", 4)
        mid = run_ticks(ticks)
        during_wall = time.perf_counter() - t0
        # "during" folds the migration pause into the window's rate —
        # the number a serving operator actually experiences
        during = (ticks * s.chunks_per_tick * cap) / during_wall
        after = run_ticks(ticks)
        _emit({
            "metric": "rescale_pause_ms", "unit": "ms",
            "value": out["pause_ms"],
            "rescale_pause_ms": out["pause_ms"],
            "rescale_moved_vnodes": out["moved_vnodes"],
            "rescale_rows_per_sec_before": round(before, 1),
            "rescale_rows_per_sec_during": round(during, 1),
            "rescale_rows_per_sec_after": round(after, 1),
            "rescale_parallelism": out["parallelism"],
            "rescale_mid_window_rows_per_sec": round(mid, 1),
        })
    finally:
        s.close()


def run_failover_phase(seed: int = 7) -> None:
    """Child entry for --failover-phase: one full leader-failover
    acceptance run (sim.run_failover — kill -9 the writer process
    mid-stream, a standby auto-promotes, exactly-once audited),
    recording the recovery-time numbers ISSUE 18 publishes: MTTR
    (kill → standby conducting), leader-down detection latency, and the
    p99 gap between committed checkpoints over the whole run — the
    unavailability window a serving operator actually experiences
    (dominated by the failover gap). One JSON line."""
    import tempfile

    from risingwave_tpu.sim import run_failover

    r = run_failover(seed=seed,
                     data_dir=tempfile.mkdtemp(prefix="rwtpu_benchfo_"))
    gaps = sorted(r.get("gap_samples_ms") or [0.0])
    p99 = gaps[min(len(gaps) - 1, int(0.99 * len(gaps)))]
    _emit({
        "metric": "failover_mttr_ms", "unit": "ms",
        "value": r["mttr_ms"],
        "failover_mttr_ms": r["mttr_ms"],
        "failover_detect_ms": r["detect_ms"],
        "failover_p99_unavail_ms": round(p99, 3),
        "failover_lease_ttl_s": r["lease_ttl_s"],
        "failover_terms": r["terms"],
        "failover_elections_lost": r["elections_lost"],
        "failover_audit_ok": int(all(r["audit"].values())),
        "failovers": r["failovers"],
    })


def run_phase(n_chunks: int, q7_chunks: int, q8_chunks: int,
              q3_chunks: int) -> None:
    """Child entry: measure everything on this process's backend, print one
    JSON line."""
    out = {"metric": "nexmark_q5_core_throughput", "unit": "rows/s"}
    # fused single-dispatch epochs are the headline for EVERY query; the
    # q5/q7 executor paths are kept as secondaries so the fusion win
    # stays visible in the record
    out["value"] = round(measure_q5_fused(n_chunks), 1)
    out["q5_executor_rows_per_sec"] = round(measure_q5(n_chunks), 1)
    out["q7_rows_per_sec"] = round(measure_q7_fused(2 * q7_chunks), 1)
    out["q7_executor_rows_per_sec"] = round(measure_q7(q7_chunks), 1)
    out["q8_rows_per_sec"] = round(measure_q8_fused(q8_chunks), 1)
    out["q3_rows_per_sec"] = round(measure_q3_fused(q3_chunks), 1)
    out.update(measure_coscheduled(COSCHED_JOBS, COSCHED_TICKS))
    out.update(measure_hetero(HETERO_JOBS, HETERO_TICKS))
    out.update(measure_pipelined(COSCHED_JOBS, COSCHED_TICKS))
    # p50/p99 barrier latency is measured on EVERY backend (VERDICT weak
    # #3: tunnel-outage rounds must still record a latency trend)
    lat = measure_barrier_latency(in_flight=1)
    out["p99_barrier_ms"] = lat.get("p99_ms")
    out["p50_barrier_ms"] = lat.get("p50_ms")
    for stage in ("inject", "pending", "collect", "commit"):
        pct = (lat.get("stages") or {}).get(stage) or {}
        out[f"barrier_{stage}_p50_ms"] = pct.get("p50_ms")
        out[f"barrier_{stage}_p99_ms"] = pct.get("p99_ms")
    lat4 = measure_barrier_latency(in_flight=4)
    out["p99_barrier_ms_inflight4"] = lat4.get("p99_ms")
    _emit(out)


def run_probe() -> None:
    """Child entry for the cheap smoke probe: prove the backend can
    compile + run ONE tiny jit, print one JSON line. Costs seconds on a
    healthy backend; a wedged one trips the init watchdog instead of
    burning a full phase timeout."""
    import jax
    import jax.numpy as jnp
    y = jax.jit(lambda x: x * 2 + 1)(jnp.arange(8))
    jax.block_until_ready(y)
    _emit({"probe": "ok", "backend": jax.default_backend(),
           "n_devices": len(jax.devices())})


# ---------------------------------------------------------------------------
# Parent: subprocess orchestration (never initializes a JAX backend)
# ---------------------------------------------------------------------------

#: per-phase diagnostics, emitted in EVERY result JSON: three rounds of
#: BENCH_*.json showed ``rc=2, value 0.0`` with the real error truncated
#: to uselessness — now each phase records its rc and the full stderr
#: tail so a failing round is debuggable from the record alone.
PHASE_LOG: dict = {}

#: per-phase persistence (BENCH_r03–r05 lost EVERYTHING to a wedged
#: backend): each completed phase's record is appended here as a JSON
#: line the moment it finishes, so a mid-run wedge/kill still leaves
#: every completed phase on disk.
PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_partial.json")


def _persist_phase(name: str, record: dict) -> None:
    try:
        with open(PARTIAL_PATH, "a") as f:
            f.write(json.dumps(
                {"phase": name,
                 "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                 "record": record}) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:                    # persistence must never kill
        sys.stderr.write(f"bench: partial persist failed: {e}\n")


def _spawn_phase(name: str, env_overrides: dict, args_tail: list,
                 timeout: float = PHASE_TIMEOUT) -> dict:
    env = dict(os.environ)
    for k, v in env_overrides.items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    args = [sys.executable, os.path.abspath(__file__)] + args_tail
    t0 = time.monotonic()
    rec: dict = {"env": {k: v for k, v in env_overrides.items()
                         if v is not None}}
    PHASE_LOG[name] = rec
    try:
        res = subprocess.run(
            args, env=env, capture_output=True, text=True,
            timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        rec.update({"rc": "timeout", "duration_s": round(
            time.monotonic() - t0, 1),
            "stderr_tail": ((e.stderr or b"").decode(errors="replace")
                            if isinstance(e.stderr, bytes)
                            else (e.stderr or ""))[-4000:]})
        _persist_phase(name, rec)
        raise RuntimeError(
            f"phase {name} timed out after {timeout}s") from None
    rec["rc"] = res.returncode
    rec["duration_s"] = round(time.monotonic() - t0, 1)
    if res.returncode != 0:
        rec["stderr_tail"] = (res.stderr or "")[-4000:]
        rec["stdout_tail"] = (res.stdout or "")[-1000:]
        # the child's diagnostic fail-line (if it got that far) carries
        # the root cause as structured JSON on stdout — surface it
        for line in reversed((res.stdout or "").strip().splitlines()):
            try:
                parsed = json.loads(line)
            except ValueError:
                continue
            if isinstance(parsed, dict) and "error" in parsed:
                rec["error"] = parsed["error"]
            break
        _persist_phase(name, rec)
        raise RuntimeError(
            f"phase {name} rc={res.returncode}: "
            f"{rec.get('error') or (res.stderr or res.stdout or '')[-500:]}")
    line = res.stdout.strip().splitlines()[-1]
    parsed = json.loads(line)
    if "error" in parsed:
        rec["error"] = parsed["error"]
        rec["stderr_tail"] = (res.stderr or "")[-4000:]
        _persist_phase(name, rec)
        raise RuntimeError(parsed["error"])
    _persist_phase(name, parsed)
    return parsed


def _measure_args(n_chunks: int, q7: int, q8: int, q3: int) -> list:
    return ["--phase", str(n_chunks), str(q7), str(q8), str(q3)]


def measure_cpu_standin() -> dict:
    """Run the same pipelines under JAX_PLATFORMS=cpu in a fresh subprocess.
    The agent image's sitecustomize force-registers the TPU plugin when
    PALLAS_AXON_POOL_IPS/TPU_LIBRARY_PATH are set, ignoring JAX_PLATFORMS —
    so those are stripped from the child env."""
    env = {"JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": None, "TPU_LIBRARY_PATH": None}
    return _spawn_phase("cpu_standin", env,
                        _measure_args(CPU_N_CHUNKS, Q7_CPU_N_CHUNKS,
                                      Q8_CPU_N_CHUNKS, Q3_CPU_N_CHUNKS))


_SHARDED_RESULT_FIELDS = (
    "sharded_fused_shards", "sharded_fused_by_shards",
    "q5_sharded_fused_rows_per_sec", "q7_sharded_fused_rows_per_sec",
    "q8_sharded_fused_rows_per_sec", "q3_sharded_fused_rows_per_sec",
    "cosched_sharded_rows_per_sec", "cosched_sharded_jobs",
)

_SERVING_RESULT_FIELDS = (
    "serving_qps", "serving_point_qps", "serving_group_qps",
    "serving_p50_ms", "serving_p99_ms",
    "serving_baseline_qps", "serving_baseline_p99_ms", "serving_speedup",
)

_RESCALE_RESULT_FIELDS = (
    "rescale_pause_ms", "rescale_moved_vnodes",
    "rescale_rows_per_sec_before", "rescale_rows_per_sec_during",
    "rescale_rows_per_sec_after",
)

_FLEET_RESULT_FIELDS = (
    "fleet_qps", "fleet_p50_ms", "fleet_p99_ms",
    "fleet_queued", "fleet_shed", "fleet_frontends",
)

_FAILOVER_RESULT_FIELDS = (
    "failover_mttr_ms", "failover_p99_unavail_ms",
    "failover_detect_ms",
)


def measure_failover_cpu() -> dict:
    """The leader-failover phase on the CPU stand-in: one full
    sim.run_failover acceptance run (standalone meta + doomed writer
    process + 2 standbys; a control-plane measurement — fresh
    subprocess like every phase, which itself spawns the writer
    process it kills)."""
    env = {"JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": None, "TPU_LIBRARY_PATH": None}
    return _spawn_phase("failover_cpu", env, ["--failover-phase"])


def measure_fleet_cpu() -> dict:
    """The multi-tenant fleet phase on the CPU stand-in: standalone
    meta + writer + 2 serving frontend processes × several pgwire
    connections each (a Session/control-plane measurement; fresh
    subprocess like every phase — which itself spawns the frontend
    processes)."""
    env = {"JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": None, "TPU_LIBRARY_PATH": None}
    return _spawn_phase("fleet_cpu", env,
                        ["--fleet-phase", str(FLEET_SECONDS),
                         str(FLEET_FRONTENDS), str(FLEET_CONNS)])


def measure_rescale_cpu() -> dict:
    """The elastic-scaling phase on the CPU stand-in: a live 2→4 vnode
    migration of a spanning job mid-stream, measuring the migration
    pause and rows/s before/during/after (a Session-level measurement;
    fresh subprocess like every phase)."""
    env = {"JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": None, "TPU_LIBRARY_PATH": None}
    return _spawn_phase("rescale_cpu", env, ["--rescale-phase"])


def measure_serving_cpu() -> dict:
    """The serving phase on the CPU stand-in (a Session-level
    measurement: plan cache + two-phase reads vs the uncached
    single-phase baseline, concurrent with live ticks). Runs in a fresh
    subprocess like every phase."""
    env = {"JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": None, "TPU_LIBRARY_PATH": None}
    return _spawn_phase("serving_cpu", env,
                        ["--serving-phase", str(SERVING_SECONDS),
                         str(SERVING_THREADS)])


def measure_sharded_cpu() -> dict:
    """The mesh-sharded fused phase on the CPU stand-in: a virtual
    8-device mesh (XLA_FLAGS=--xla_force_host_platform_device_count) in
    a fresh subprocess. The record persisted to BENCH_partial.json is the
    MULTICHIP-style sub-record (n_devices / ok / per-shard-count rates)
    the driver's dryrun artifacts established."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count="
                 f"{SHARDED_VIRTUAL_DEVICES}").strip()
    env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags,
           "PALLAS_AXON_POOL_IPS": None, "TPU_LIBRARY_PATH": None}
    return _spawn_phase("sharded_fused_cpu", env,
                        ["--sharded-phase", str(SHARDED_N_CHUNKS),
                         str(SHARDED_Q7_N_CHUNKS)])


def measure_sharded_tpu(cache_env: dict) -> tuple:
    """(result | None, error | None): one attempt of the sharded phase on
    the real mesh — only meaningful on a multi-chip slice; a single-chip
    backend still records a 1-shard point. Non-fatal: a failure here
    never costs the round its headline numbers."""
    try:
        return _spawn_phase("sharded_fused_tpu", dict(cache_env),
                            ["--sharded-phase", str(SHARDED_N_CHUNKS),
                             str(SHARDED_Q7_N_CHUNKS)]), None
    except Exception as e:  # noqa: BLE001 - attributed, not fatal
        sys.stderr.write(f"bench: sharded tpu phase: {e}\n")
        return None, str(e)


def _tpu_cache_env() -> dict:
    """One persistent XLA compilation cache shared by EVERY tpu attempt
    of this run: a retry after a mid-phase wedge skips the compiles the
    previous attempt already paid for (min-compile-time 0 so even small
    executables cache)."""
    import tempfile
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache:
        cache = tempfile.mkdtemp(prefix="rwtpu_jaxcache_")
        # memoize for the run: every later phase (retries, the sharded
        # TPU phase) must land in the SAME cache dir
        os.environ["JAX_COMPILATION_CACHE_DIR"] = cache
    return {"JAX_COMPILATION_CACHE_DIR": cache,
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0"}


def measure_tpu() -> tuple:
    """(result | None, error | None): bounded retry with backoff — each
    attempt is a FRESH process, so a failed/cached PJRT init can't poison
    the next attempt (VERDICT r3 item 1a). Before each full attempt a
    CHEAP smoke probe (tiny jit, short timeout) runs in its own process:
    a wedged backend costs minutes, not a full phase timeout. Attempt 1
    runs with the Pallas rank kernel (the TPU default); if it fails —
    e.g. a backend that rejects the kernel — later attempts force the
    pre-kernel jnp path so a kernel problem can't cost the round its
    chip number. All attempts share one compilation cache dir."""
    last_err = None
    cache_env = _tpu_cache_env()
    for attempt in range(TPU_ATTEMPTS):
        env = dict(cache_env)
        if attempt > 0:
            env["RWTPU_PALLAS"] = "0"
        try:
            probe = _spawn_phase(f"tpu_probe{attempt + 1}", env,
                                 ["--probe"], timeout=PROBE_TIMEOUT)
            if probe.get("backend") != "tpu":
                raise RuntimeError(
                    f"probe landed on {probe.get('backend')!r}, not tpu "
                    "(plugin not registered?)")
            res = _spawn_phase(f"tpu_attempt{attempt + 1}", env,
                               _measure_args(N_CHUNKS, Q7_N_CHUNKS,
                                             Q8_N_CHUNKS, Q3_N_CHUNKS))
            # attribution: which code path produced the number
            res["rank_kernel"] = ("pallas" if attempt == 0
                                  else "jnp_fallback")
            return res, None
        except Exception as e:
            last_err = f"attempt {attempt + 1}/{TPU_ATTEMPTS}: {e}"
            sys.stderr.write(f"bench: tpu {last_err}\n")
            if attempt < TPU_ATTEMPTS - 1:
                time.sleep(TPU_BACKOFFS[min(attempt, len(TPU_BACKOFFS) - 1)])
    return None, last_err


#: fields every result JSON must carry on EVERY backend — the fallback
#: record stays schema-stable across outages (PR-4 did this for p50/p99;
#: this round adds q8/q3 fused + the co-scheduling phase)
_SHARED_FIELDS = (
    "q5_executor_rows_per_sec", "q7_executor_rows_per_sec",
    "q8_rows_per_sec", "q3_rows_per_sec",
    "coscheduled_mvs_rows_per_sec",
    "coscheduled_sequential_rows_per_sec", "coschedule_speedup",
    "coscheduled_n_mvs",
    # heterogeneous tick compiler (stream/tick_compiler.py): N
    # DISSIMILAR small MVs fused into a minimal dispatch schedule vs
    # per-MV executor pipelines, present on every backend so the
    # TPU-outage fallback record stays schema-stable
    "hetero_rows_per_sec", "hetero_sequential_rows_per_sec",
    "hetero_speedup", "hetero_dispatches_per_tick", "hetero_n_mvs",
    # asynchronous epoch pipeline ([streaming] pipeline_depth = 2 vs 1
    # on the durable 16-MV co-scheduled workload — rows/s + the
    # checkpoint-tick latency tail; docs/performance.md "Pipelined
    # tick"), present on every backend so the TPU-outage fallback
    # record stays schema-stable
    "pipeline_on_rows_per_sec", "pipeline_off_rows_per_sec",
    "pipeline_speedup", "pipeline_depth",
    "pipeline_on_p50_barrier_ms", "pipeline_on_p99_barrier_ms",
    "pipeline_off_p50_barrier_ms", "pipeline_off_p99_barrier_ms",
    "p99_barrier_ms", "p50_barrier_ms", "p99_barrier_ms_inflight4",
    # barrier-observatory waterfall (common/barrier_ledger.py): per-stage
    # p50/p99 over the same measured window, present on every backend (a
    # Session-level CPU measurement) so the fallback record stays
    # schema-stable
    "barrier_inject_p50_ms", "barrier_inject_p99_ms",
    "barrier_pending_p50_ms", "barrier_pending_p99_ms",
    "barrier_collect_p50_ms", "barrier_collect_p99_ms",
    "barrier_commit_p50_ms", "barrier_commit_p99_ms",
    # mesh-sharded fused epochs (ops/fused_sharded.py): aggregate rows/s
    # + shard counts — the whole ladder (q5/q7/q8/q3 + the K×S
    # co-scheduled group, PR 13) — present on EVERY backend so the
    # TPU-outage fallback record stays schema-stable
    "sharded_fused_shards", "sharded_fused_by_shards",
    "q5_sharded_fused_rows_per_sec", "q7_sharded_fused_rows_per_sec",
    "q8_sharded_fused_rows_per_sec", "q3_sharded_fused_rows_per_sec",
    "cosched_sharded_rows_per_sec", "cosched_sharded_jobs",
    # serving plane (frontend/serving.py): cached+two-phase QPS with
    # p50/p99 vs the uncached single-phase baseline, present on every
    # backend (a Session-level CPU measurement) so the fallback record
    # stays schema-stable
    "serving_qps", "serving_point_qps", "serving_group_qps",
    "serving_p50_ms", "serving_p99_ms",
    "serving_baseline_qps", "serving_baseline_p99_ms", "serving_speedup",
    # elastic scaling plane (meta/rescale.py): live-migration pause +
    # throughput around a 2→4 rescale, present on every backend (a
    # Session-level CPU measurement) so the TPU-outage fallback record
    # stays schema-stable
    "rescale_pause_ms", "rescale_moved_vnodes",
    "rescale_rows_per_sec_before", "rescale_rows_per_sec_during",
    "rescale_rows_per_sec_after",
    # multi-tenant frontend fleet (docs/control-plane.md): merged QPS +
    # p99 across 2 serving frontend processes over one standalone meta,
    # plus the admission counters — present on every backend (a
    # control-plane CPU measurement) so the fallback record stays
    # schema-stable
    "fleet_qps", "fleet_p99_ms", "fleet_queued",
    # leader failover (docs/control-plane.md "Election"): kill -9 →
    # standby auto-promotion MTTR + the p99 committed-checkpoint gap
    # (the unavailability window), present on every backend (a
    # control-plane CPU measurement) so the fallback record stays
    # schema-stable
    "failover_mttr_ms", "failover_p99_unavail_ms", "failover_detect_ms",
)


def main() -> int:
    # fresh per-phase persistence file for this run (appended as phases
    # finish; survives any later wedge/kill)
    try:
        open(PARTIAL_PATH, "w").close()
    except OSError:
        pass
    try:
        cpu = measure_cpu_standin()
    except Exception as e:
        out = _fail_line(f"cpu stand-in failed: {e}")
        out["phases"] = PHASE_LOG
        _emit(out)
        return 2
    # mesh-sharded fused phase (virtual 8-device mesh): merged into the
    # CPU record so the shared-field copy below keeps the fallback
    # record schema-stable; non-fatal — a sharded regression must not
    # cost the round its headline numbers
    try:
        sharded_cpu = measure_sharded_cpu()
        for f in _SHARDED_RESULT_FIELDS:
            cpu[f] = sharded_cpu.get(f)
    except Exception as e:  # noqa: BLE001 - attributed below
        sys.stderr.write(f"bench: sharded cpu phase failed: {e}\n")
        cpu["sharded_fused_error"] = str(e)
    # serving phase (Session-level, CPU): merged into the CPU record the
    # same way; non-fatal — a serving regression must not cost the round
    # its headline numbers
    try:
        serving = measure_serving_cpu()
        for f in _SERVING_RESULT_FIELDS:
            cpu[f] = serving.get(f)
    except Exception as e:  # noqa: BLE001 - attributed below
        sys.stderr.write(f"bench: serving phase failed: {e}\n")
        cpu["serving_error"] = str(e)
    # elastic-scaling phase (Session-level, CPU): live 2→4 migration
    # pause + rows/s around it; non-fatal like the serving phase
    try:
        rescale = measure_rescale_cpu()
        for f in _RESCALE_RESULT_FIELDS:
            cpu[f] = rescale.get(f)
    except Exception as e:  # noqa: BLE001 - attributed below
        sys.stderr.write(f"bench: rescale phase failed: {e}\n")
        cpu["rescale_error"] = str(e)
    # fleet phase (control-plane-level, CPU): standalone meta + writer +
    # serving frontend processes; non-fatal like the serving phase
    try:
        fleet = measure_fleet_cpu()
        for f in _FLEET_RESULT_FIELDS:
            cpu[f] = fleet.get(f)
    except Exception as e:  # noqa: BLE001 - attributed below
        sys.stderr.write(f"bench: fleet phase failed: {e}\n")
        cpu["fleet_error"] = str(e)
    # leader-failover phase (control-plane-level, CPU): kill -9 the
    # writer process, time the standby's auto-promotion; non-fatal like
    # the serving phase
    try:
        failover = measure_failover_cpu()
        for f in _FAILOVER_RESULT_FIELDS:
            cpu[f] = failover.get(f)
    except Exception as e:  # noqa: BLE001 - attributed below
        sys.stderr.write(f"bench: failover phase failed: {e}\n")
        cpu["failover_error"] = str(e)
    cpu_rps, cpu_q7 = cpu["value"], cpu["q7_rows_per_sec"]
    tpu, tpu_err = measure_tpu()
    if tpu is not None:
        sharded_env = _tpu_cache_env()
        if tpu.get("rank_kernel") == "jnp_fallback":
            # the main TPU phase only succeeded with the Pallas kernels
            # disabled — the sharded phase must run the same way or it
            # re-hits the kernel failure and loses the whole record
            sharded_env["RWTPU_PALLAS"] = "0"
        sharded_tpu, sharded_tpu_err = measure_sharded_tpu(sharded_env)
        if sharded_tpu is not None:
            for f in _SHARDED_RESULT_FIELDS:
                tpu[f] = sharded_tpu.get(f)
            tpu["sharded_fused_n_devices"] = sharded_tpu.get("n_devices")
        else:
            tpu["sharded_fused_error"] = sharded_tpu_err
            # keep the record schema-stable with the stand-in's numbers
            for f in _SHARDED_RESULT_FIELDS:
                tpu.setdefault(f, cpu.get(f))
        # serving/rescale/fleet are Session/control-plane-level CPU
        # measurements; the TPU record carries the stand-in's numbers
        # for schema stability
        for f in (_SERVING_RESULT_FIELDS + _RESCALE_RESULT_FIELDS
                  + _FLEET_RESULT_FIELDS + _FAILOVER_RESULT_FIELDS):
            tpu.setdefault(f, cpu.get(f))
    if tpu is None:
        # tunnel/chip unavailable: fall back to the CPU streaming
        # measurement as the round's headline — a real, nonzero number
        # with the failure attributed, instead of a bare value 0.0. The
        # CPU phase carries the FULL field set (q7/q8/q3 fused, the
        # co-scheduling phase, p50/p99) so an outage round still records
        # every trend (VERDICT weak #3) and result JSONs stay
        # schema-stable across backends.
        out = {
            "metric": "nexmark_q5_core_throughput",
            "value": round(cpu_rps, 1),
            "unit": "rows/s",
            "vs_baseline": 1.0,
            "backend": "cpu_standin_fallback",
            "baseline_kind": "same pipeline, JAX_PLATFORMS=cpu "
                             "(TPU unavailable; value IS the stand-in)",
            "cpu_standin_rows_per_sec": round(cpu_rps, 1),
            "q7_rows_per_sec": round(cpu_q7, 1),
            "q7_cpu_standin_rows_per_sec": round(cpu_q7, 1),
            "q7_join": "fused single-dispatch epochs (gen+project+"
                       "bucketed interval join+max flush in one lax.scan; "
                       "ops/interval_join.py)",
            "tpu_error": tpu_err,
            "phases": PHASE_LOG,
        }
        for f in _SHARED_FIELDS:
            out[f] = cpu.get(f)
        _emit(out)
        return 0
    out = {
        "metric": "nexmark_q5_core_throughput",
        "value": tpu["value"],
        "unit": "rows/s",
        "vs_baseline": round(tpu["value"] / cpu_rps, 2),
        "baseline_kind": "same pipeline, JAX_PLATFORMS=cpu "
                         "(Rust-engine stand-in)",
        "cpu_standin_rows_per_sec": round(cpu_rps, 1),
        "q5_cpu_executor_rows_per_sec": cpu.get("q5_executor_rows_per_sec"),
        "chunks_per_dispatch": CHUNKS_PER_EPOCH,
        "ingest": "fused single-dispatch epochs (gen+project+agg in one "
                  "lax.scan; ops/fused_epoch.py)",
        "q7_join": "fused single-dispatch epochs (gen+project+bucketed "
                   "interval join+max flush in one lax.scan; "
                   "ops/interval_join.py)",
        "q7_join_rows_per_sec": tpu["q7_rows_per_sec"],
        "q7_vs_baseline": round(tpu["q7_rows_per_sec"] / cpu_q7, 2),
        "q7_cpu_standin_rows_per_sec": round(cpu_q7, 1),
        "q7_cpu_executor_rows_per_sec": cpu.get("q7_executor_rows_per_sec"),
        "q8_cpu_rows_per_sec": cpu.get("q8_rows_per_sec"),
        "q3_cpu_rows_per_sec": cpu.get("q3_rows_per_sec"),
        "cpu_coschedule_speedup": cpu.get("coschedule_speedup"),
        "cpu_pipeline_speedup": cpu.get("pipeline_speedup"),
        "cpu_p99_barrier_ms": cpu.get("p99_barrier_ms"),
        "cpu_p50_barrier_ms": cpu.get("p50_barrier_ms"),
        "rank_kernel": tpu.get("rank_kernel"),
        "phases": PHASE_LOG,
    }
    for f in _SHARED_FIELDS:
        out[f] = tpu.get(f)
    qv = tpu.get("q8_rows_per_sec")
    if qv and cpu.get("q8_rows_per_sec"):
        out["q8_vs_baseline"] = round(qv / cpu["q8_rows_per_sec"], 2)
    qv = tpu.get("q3_rows_per_sec")
    if qv and cpu.get("q3_rows_per_sec"):
        out["q3_vs_baseline"] = round(qv / cpu["q3_rows_per_sec"], 2)
    _emit(out)
    return 0


# ---------------------------------------------------------------------------
# --smoke: one tiny in-process phase for CI (scripts/check.sh) — seconds,
# CPU, asserts the 1-dispatch-per-epoch invariant on every fused surface
# ---------------------------------------------------------------------------


def run_smoke() -> int:
    import jax
    import jax.numpy as jnp
    from risingwave_tpu.common import INT64, TIMESTAMP
    from risingwave_tpu.common.dispatch_count import count_dispatches
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.connector import NexmarkConfig
    from risingwave_tpu.connector.nexmark import DeviceBidGenerator
    from risingwave_tpu.connector.tpch import (
        DeviceQ3Generator, Q3_CUTOFF_DAYS, TpchQ3Config,
    )
    from risingwave_tpu.expr import col
    from risingwave_tpu.ops.fused_epoch import (
        fused_source_q3_epoch, fused_source_session_epoch,
    )
    from risingwave_tpu.ops.session_window import SessionWindowCore
    from risingwave_tpu.ops.stream_q3 import Q3Core
    from risingwave_tpu.stream.coschedule import CoGroup, FusedJobSpec

    t0 = time.perf_counter()
    cap, k, jobs = 128, 4, 4
    checks = []
    with count_dispatches() as c:
        # q5-shaped co-scheduled group: 1 dispatch per epoch for J jobs
        exprs, agg, chunk_fn = _cosched_parts()
        spec = FusedJobSpec("agg", ("smoke",), chunk_fn, tuple(exprs),
                            agg.core, COSCHED_SMOKE_CHUNK, seed=0)
        group = CoGroup(spec)
        for j in range(jobs):
            group.add(f"mv{j}", agg.core.init_state(), seed=j)
        group.run_epoch(k)
        group.flush()
        c.reset()
        group.run_epoch(k)
        n = c.counts["build_group_epoch.<locals>.coscheduled_epoch"]
        assert n == 1, f"cosched epoch took {n} dispatches"
        checks.append(f"cosched[{jobs}]=1 dispatch/epoch")

        # heterogeneous tick compiler (stream/tick_compiler.py): 200
        # DISSIMILAR small jobs must compile to a <= 8-dispatch
        # schedule, and a live run must issue exactly one dispatch per
        # compiled group per epoch (cross-checked against the profiler)
        from risingwave_tpu.expr.agg import agg as _agg, count_star
        from risingwave_tpu.ops.grouped_agg import AggCore
        from risingwave_tpu.stream.tick_compiler import (
            MEGA_EPOCH_FN, PADDED_EPOCH_FN, TickCompiler,
        )
        from risingwave_tpu.common import INT64 as _I64
        from risingwave_tpu.expr import Literal, call as _call, col as _col
        from risingwave_tpu.common.types import TIMESTAMP as _TS
        hcap, hrows = 256, 64
        hgen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=hrows))

        def _hspec(j):
            kind = j % 4
            if kind == 0:       # tumble window, per-j literal (holes)
                exprs = [_call("tumble_start", _col(5, _TS),
                               Literal(1_000_000 + j, _I64)),
                         _col(0, _I64)]
                core = AggCore((_I64, _I64), (0, 1), [count_star()],
                               table_capacity=hcap, out_capacity=hrows)
            elif kind == 1:     # sum with per-j literal over auction
                exprs = [_col(0, _I64),
                         _call("add", _col(2, _I64),
                               Literal(100 + j, _I64))]
                core = AggCore((_I64,), (0,),
                               [count_star(), _agg("sum", 1, _I64)],
                               table_capacity=hcap, out_capacity=hrows)
            elif kind == 2:     # max over bidder (no holes)
                exprs = [_col(1, _I64), _col(2, _I64)]
                core = AggCore((_I64,), (0,), [_agg("max", 1, _I64)],
                               table_capacity=hcap, out_capacity=hrows)
            else:               # plain count over auction
                exprs = [_col(0, _I64)]
                core = AggCore((_I64,), (0,), [count_star()],
                               table_capacity=hcap, out_capacity=hrows)
            return FusedJobSpec(
                "agg", ("smoke-hetero", kind), hgen.chunk_fn(),
                tuple(exprs), core, hrows, seed=j), core

        tc = TickCompiler()
        for j in range(200):
            spec_j, core_j = _hspec(j)
            tc.add(f"h{j}", spec_j, core_j.init_state(),
                   n_source_cols=7)
        # two UNIQUE skeletons: singletons that must pack into one
        # mega-epoch (tier 2) rather than get a dispatch each
        for nm, aggs in (("h_min", [_agg("min", 1, _I64)]),
                         ("h_sum", [_agg("sum", 1, _I64)])):
            core_s = AggCore((_I64,), (0,), aggs,
                             table_capacity=hcap, out_capacity=hrows)
            spec_s = FusedJobSpec(
                "agg", ("smoke-hetero", nm), hgen.chunk_fn(),
                (_col(1, _I64), _col(2, _I64)), core_s, hrows, seed=0)
            tc.add(nm, spec_s, core_s.init_state(), n_source_cols=7)
        tc.ensure_compiled()
        hstats = tc.stats()
        assert hstats["jobs"] == 202
        assert sorted(g["kind"] for g in hstats["groups"]) == \
            ["mega", "padded", "padded", "padded", "padded"]
        assert hstats["dispatches_per_tick"] <= 8, \
            f"200 MVs need {hstats['dispatches_per_tick']} dispatches"
        c.reset()
        for g in tc.groups:
            g.run_epoch(2)
        got = (c.counts.get(PADDED_EPOCH_FN, 0)
               + c.counts.get(MEGA_EPOCH_FN, 0))
        assert got == hstats["dispatches_per_tick"], \
            f"epoch took {got} dispatches, schedule promised " \
            f"{hstats['dispatches_per_tick']}"
        checks.append(
            f"hetero[202]={hstats['dispatches_per_tick']} "
            "dispatches/tick (<=8)")

        # q8 session epoch
        sw = SessionWindowCore(
            Schema((Field("bidder", INT64), Field("ts", TIMESTAMP))),
            0, 1, gap_us=5_000, capacity=1 << 10,
            closed_capacity=1 << 10)
        gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=cap))
        q8 = fused_source_session_epoch(
            gen.chunk_fn(), [col(1, INT64), col(5, TIMESTAMP)], sw, cap)
        st, snap, packed = q8(sw.init_state(), jnp.int64(0),
                              jax.random.PRNGKey(0), k, jnp.int64(0))
        c.reset()
        st, snap, packed = q8(st, jnp.int64(k * cap),
                              jax.random.PRNGKey(1), k, jnp.int64(0))
        n = c.counts["fused_source_session_epoch.<locals>.epoch"]
        assert n == 1, f"q8 epoch took {n} dispatches"
        assert not any(int(x) for x in jax.device_get(packed)[1:])
        checks.append("q8=1 dispatch/epoch")

        # q3 epoch
        q3core = Q3Core(Q3_CUTOFF_DAYS, orders_capacity=1 << 10,
                        agg_capacity=1 << 10)
        q3gen = DeviceQ3Generator(TpchQ3Config(chunk_capacity=cap))
        q3 = fused_source_q3_epoch(q3gen.chunk_fn(), q3core, cap)
        st3, out3, packed3 = q3(q3core.init_state(), jnp.int64(0),
                                jax.random.PRNGKey(0), k)
        c.reset()
        st3, out3, packed3 = q3(st3, jnp.int64(k * cap),
                                jax.random.PRNGKey(0), k)
        n = c.counts["fused_source_q3_epoch.<locals>.epoch"]
        assert n == 1, f"q3 epoch took {n} dispatches"
        assert not any(int(x) for x in jax.device_get(packed3)[1:])
        checks.append("q3=1 dispatch/epoch")

        # mesh-sharded fused epochs (ops/fused_sharded.py) on whatever
        # mesh this backend can host (CI pins CPU without a virtual
        # mesh, so usually 1 device — the invariant is identical)
        from risingwave_tpu.parallel.fused import (
            ShardedCoGroup, ShardedFusedAgg, ShardedFusedQ3,
            ShardedFusedSession,
        )
        from risingwave_tpu.parallel.sharded_agg import make_mesh
        n_dev = min(len(jax.devices()), 4)
        mesh = make_mesh(n_dev)
        exprs2, agg2, chunk_fn2 = _cosched_parts()
        sf = ShardedFusedAgg(mesh, agg2.core, chunk_fn2,
                             exprs2, COSCHED_SMOKE_CHUNK)
        sf.run_epoch(0, jax.random.PRNGKey(0), k)
        sf.flush()
        c.reset()
        sf.run_epoch(k * COSCHED_SMOKE_CHUNK, jax.random.PRNGKey(1), k)
        n = c.counts["sharded_agg_epoch.<locals>.epoch"]
        assert n == 1, f"sharded epoch took {n} dispatches"
        sf.flush()
        checks.append(f"sharded[{n_dev}]=1 dispatch/epoch")

        # sharded q8 session epoch: ONE dispatch regardless of shards/k
        sw8 = SessionWindowCore(
            Schema((Field("bidder", INT64), Field("ts", TIMESTAMP))),
            0, 1, gap_us=5_000, capacity=1 << 10,
            closed_capacity=1 << 10)
        gen8 = DeviceBidGenerator(NexmarkConfig(chunk_capacity=cap))
        sfs = ShardedFusedSession(
            mesh, sw8, gen8.chunk_fn(),
            [col(1, INT64), col(5, TIMESTAMP)], cap)
        sfs.run_epoch(0, jax.random.PRNGKey(0), k, 0)
        sfs.flush(out_capacity=cap)
        c.reset()
        sfs.run_epoch(k * cap, jax.random.PRNGKey(1), k, 0)
        n = c.counts["sharded_session_epoch.<locals>.epoch"]
        assert n == 1, f"sharded q8 epoch took {n} dispatches"
        sfs.flush(out_capacity=cap)
        checks.append(f"sharded-q8[{n_dev}]=1 dispatch/epoch")

        # sharded q3 epoch (incl. the global top-n flush): ONE dispatch
        q3s = Q3Core(Q3_CUTOFF_DAYS, orders_capacity=1 << 10,
                     agg_capacity=1 << 10)
        sfq3 = ShardedFusedQ3(
            mesh, q3s,
            DeviceQ3Generator(TpchQ3Config(chunk_capacity=cap)).chunk_fn(),
            cap)
        sfq3.run_epoch(0, jax.random.PRNGKey(0), k)
        sfq3.flush()
        c.reset()
        sfq3.run_epoch(k * cap, jax.random.PRNGKey(0), k)
        n = c.counts["sharded_q3_epoch.<locals>.epoch"]
        assert n == 1, f"sharded q3 epoch took {n} dispatches"
        sfq3.flush()
        checks.append(f"sharded-q3[{n_dev}]=1 dispatch/epoch")

        # K×S co-scheduled group (fusion surface 6): J jobs × S shards,
        # still exactly ONE dispatch per epoch
        exprs3, agg3, chunk_fn3 = _cosched_parts()
        spec3 = FusedJobSpec("agg", ("smoke-sharded",), chunk_fn3,
                             tuple(exprs3), agg3.core,
                             COSCHED_SMOKE_CHUNK, seed=0)
        sgroup = ShardedCoGroup(mesh, spec3)
        for j in range(jobs):
            sgroup.add(f"mv{j}", seed=j)
        sgroup.run_epoch(k)
        sgroup.flush()
        c.reset()
        sgroup.run_epoch(k)
        n = c.counts[
            "build_sharded_group_epoch.<locals>.sharded_coscheduled_epoch"]
        assert n == 1, f"sharded group epoch took {n} dispatches"
        sgroup.flush()
        checks.append(
            f"sharded-cosched[{jobs}x{n_dev}]=1 dispatch/epoch")

        # generic sharded-fused equi-join: k chunks in ONE dispatch
        from risingwave_tpu.common.chunk import physical_chunk
        from risingwave_tpu.common.types import Schema as _Schema
        from risingwave_tpu.ops.join_state import JoinType
        from risingwave_tpu.parallel.sharded_join import ShardedHashJoin
        ls = _Schema((Field("k", INT64), Field("v", INT64)))
        rs = _Schema((Field("k", INT64), Field("w", INT64)))
        shj = ShardedHashJoin(mesh, ls, rs, [0], [0], JoinType.INNER,
                              key_capacity=1 << 8, bucket_width=8)
        def _jb(lo):
            return shj.batch_chunks([
                physical_chunk(ls, [(lo + 16 * s + r, r) for r in range(16)],
                               16) for s in range(n_dev)])
        shj.step_epoch("left", [_jb(0), _jb(1000)])
        c.reset()
        shj.step_epoch("left", [_jb(2000), _jb(3000)])
        n = c.counts["sharded_equi_join_epoch.<locals>.epoch"]
        assert n == 1, f"sharded equi-join epoch took {n} dispatches"
        checks.append(f"sharded-equijoin[{n_dev}]=1 dispatch/epoch")
    # device profiling plane (common/profiling.py): ON by default, and
    # every 1-dispatch assertion above ran THROUGH its wrappers — so the
    # invariants passing IS the proof that profiling adds zero
    # dispatches. Cross-check its live counters against the same
    # qualnames the dispatch counter keyed.
    from risingwave_tpu.common.profiling import GLOBAL_PROFILER
    assert GLOBAL_PROFILER.enabled, "profiling plane is off by default"
    prof = GLOBAL_PROFILER.counts()
    for qn in ("build_group_epoch.<locals>.coscheduled_epoch",
               "build_padded_group_epoch.<locals>.padded_epoch",
               "build_mega_epoch.<locals>.mega_epoch",
               "fused_source_session_epoch.<locals>.epoch",
               "fused_source_q3_epoch.<locals>.epoch",
               "sharded_agg_epoch.<locals>.epoch",
               "sharded_session_epoch.<locals>.epoch",
               "sharded_q3_epoch.<locals>.epoch",
               "sharded_equi_join_epoch.<locals>.epoch",
               "build_sharded_group_epoch.<locals>"
               ".sharded_coscheduled_epoch"):
        assert prof.get(qn, 0) >= 1, \
            f"profiler missed dispatches for {qn}: {prof}"
    checks.append("profiling on: counters live, 0 added dispatches")
    # asynchronous epoch pipeline ([streaming] pipeline_depth = 2):
    # the SAME co-scheduled workload must be BIT-EXACT vs the
    # synchronous path after the drain (flush) AND add ZERO dispatches
    # (identical per-qualname counts — the pipeline reorders dispatches
    # across ticks, it must never add one)
    from risingwave_tpu.frontend.build import BuildConfig

    def _pipe_run(depth: int):
        from risingwave_tpu.frontend import Session
        with count_dispatches() as pc:
            s = Session(config=BuildConfig(coschedule=True),
                        chunks_per_tick=2, source_chunk_capacity=128,
                        checkpoint_frequency=4, pipeline_depth=depth)
            s.run_sql(_COSCHED_SOURCE_SQL)
            for j in range(2):
                s.run_sql(f"CREATE MATERIALIZED VIEW pipe_mv{j} AS "
                          "SELECT auction, count(*) AS n FROM bid "
                          "GROUP BY auction")
            for _ in range(9):
                s.tick()
            s.flush()
            rows = [sorted(s.run_sql(f"SELECT * FROM pipe_mv{j}"))
                    for j in range(2)]
            counts = dict(pc.counts)
            s.close()
        return rows, counts

    rows_sync, counts_sync = _pipe_run(1)
    rows_pipe, counts_pipe = _pipe_run(2)
    assert rows_sync == rows_pipe, \
        "pipeline_depth=2 diverged from the synchronous path"
    for qn in ("build_group_epoch.<locals>.coscheduled_epoch",
               "multi_agg_probe.<locals>.probe",
               "multi_agg_finish.<locals>.finish",
               "gather_job_flush_chunk.<locals>.gather"):
        assert counts_sync.get(qn) == counts_pipe.get(qn) \
            and counts_sync.get(qn), (
            f"pipelining changed the dispatch count for {qn}: "
            f"sync={counts_sync.get(qn)} pipe={counts_pipe.get(qn)}")
    checks.append("pipeline[depth=2]: bit-exact, 0 added dispatches")
    # serving plane: a repeated identical SELECT must create ZERO new
    # jit wrappers (plan+compilation cache, frontend/serving.py) — and a
    # write in between re-executes the SAME cached executors, still
    # zero. Warm OUTSIDE the counter: count_dispatches counts calls of
    # functions jitted inside it, so any replan/relower on the repeats
    # would surface as nonzero counts.
    from risingwave_tpu.frontend import Session
    s = Session()
    s.run_sql("CREATE TABLE st (a BIGINT, b BIGINT)")
    s.run_sql("INSERT INTO st VALUES (1, 10), (2, 20), (1, 30)")
    s.flush()
    sql = "SELECT a, count(*), sum(b) FROM st GROUP BY a"
    s.run_sql(sql)                      # warm: plan + lower + jit
    with count_dispatches() as c:
        assert s.run_sql(sql) == s.run_sql(sql)
        assert c.total == 0, \
            f"cached SELECT re-jitted: {dict(c.counts)}"
        s.run_sql("INSERT INTO st VALUES (3, 5)")
        s.flush()
        rows = s.run_sql(sql)
        assert c.total == 0, \
            f"version-bump re-execution re-jitted: {dict(c.counts)}"
    assert sorted(rows) == [(1, 2, 40), (2, 1, 20), (3, 1, 5)], rows
    m = s.metrics()["serving"]
    assert m["cache_hits"] >= 2 and m["reexecutions"] >= 1, m
    s.close()
    checks.append("serving cache: 0 new jits on repeat + re-exec")
    _emit({"metric": "bench_smoke", "value": round(
        time.perf_counter() - t0, 2), "unit": "s",
        "backend": jax.default_backend(), "checks": checks})
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] in ("--phase", "--probe",
                                             "--sharded-phase",
                                             "--serving-phase",
                                             "--rescale-phase",
                                             "--fleet-phase",
                                             "--fleet-frontend",
                                             "--failover-phase",
                                             "--hetero-phase"):
        watchdog = threading.Timer(INIT_WATCHDOG_SECS, _watchdog_fire)
        watchdog.daemon = True
        watchdog.start()
        import jax
        try:
            _ = jax.devices()  # may hang on a wedged tunnel; watchdog covers
        except Exception as e:
            _emit(_fail_line(f"jax backend init failed: {e!r}"))
            raise SystemExit(2)
        watchdog.cancel()
        if sys.argv[1] == "--probe":
            try:
                run_probe()
            except Exception as e:
                _emit(_fail_line(f"probe failed: {type(e).__name__}: {e}"))
                raise SystemExit(2)
            raise SystemExit(0)
        if sys.argv[1] == "--serving-phase":
            watchdog = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
            watchdog.daemon = True
            watchdog.start()
            try:
                run_serving_phase(
                    float(sys.argv[2]) if len(sys.argv) > 2
                    else SERVING_SECONDS,
                    int(sys.argv[3]) if len(sys.argv) > 3
                    else SERVING_THREADS)
            except Exception as e:
                _emit(_fail_line(
                    f"serving phase failed: {type(e).__name__}: {e}"))
                raise SystemExit(2)
            finally:
                watchdog.cancel()
            raise SystemExit(0)
        if sys.argv[1] == "--fleet-frontend":
            # hidden child of --fleet-phase: line-oriented protocol on
            # stdout (FLEET_READY / FLEET_STATS), not a JSON result line
            run_fleet_frontend(sys.argv[2], sys.argv[3])
            raise SystemExit(0)
        if sys.argv[1] == "--fleet-phase":
            watchdog = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
            watchdog.daemon = True
            watchdog.start()
            try:
                run_fleet_phase(
                    float(sys.argv[2]) if len(sys.argv) > 2
                    else FLEET_SECONDS,
                    int(sys.argv[3]) if len(sys.argv) > 3
                    else FLEET_FRONTENDS,
                    int(sys.argv[4]) if len(sys.argv) > 4
                    else FLEET_CONNS)
            except Exception as e:
                _emit(_fail_line(
                    f"fleet phase failed: {type(e).__name__}: {e}"))
                raise SystemExit(2)
            finally:
                watchdog.cancel()
            raise SystemExit(0)
        if sys.argv[1] == "--failover-phase":
            watchdog = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
            watchdog.daemon = True
            watchdog.start()
            try:
                run_failover_phase(
                    int(sys.argv[2]) if len(sys.argv) > 2 else 7)
            except Exception as e:
                _emit(_fail_line(
                    f"failover phase failed: {type(e).__name__}: {e}"))
                raise SystemExit(2)
            finally:
                watchdog.cancel()
            raise SystemExit(0)
        if sys.argv[1] == "--hetero-phase":
            watchdog = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
            watchdog.daemon = True
            watchdog.start()
            try:
                run_hetero_phase(
                    int(sys.argv[2]) if len(sys.argv) > 2
                    else HETERO_JOBS,
                    int(sys.argv[3]) if len(sys.argv) > 3
                    else HETERO_TICKS)
            except Exception as e:
                _emit(_fail_line(
                    f"hetero phase failed: {type(e).__name__}: {e}"))
                raise SystemExit(2)
            finally:
                watchdog.cancel()
            raise SystemExit(0)
        if sys.argv[1] == "--rescale-phase":
            watchdog = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
            watchdog.daemon = True
            watchdog.start()
            try:
                run_rescale_phase(
                    int(sys.argv[2]) if len(sys.argv) > 2 else 6)
            except Exception as e:
                _emit(_fail_line(
                    f"rescale phase failed: {type(e).__name__}: {e}"))
                raise SystemExit(2)
            finally:
                watchdog.cancel()
            raise SystemExit(0)
        if sys.argv[1] == "--sharded-phase":
            watchdog = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
            watchdog.daemon = True
            watchdog.start()
            try:
                run_sharded_phase(int(sys.argv[2]), int(sys.argv[3]))
            except Exception as e:
                _emit(_fail_line(
                    f"sharded phase failed: {type(e).__name__}: {e}"))
                raise SystemExit(2)
            finally:
                watchdog.cancel()
            raise SystemExit(0)
        n = int(sys.argv[2])
        n7 = int(sys.argv[3])
        n8 = int(sys.argv[4]) if len(sys.argv) > 4 else Q8_CPU_N_CHUNKS
        n3 = int(sys.argv[5]) if len(sys.argv) > 5 else Q3_CPU_N_CHUNKS
        watchdog = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
        watchdog.daemon = True
        watchdog.start()
        try:
            run_phase(n, n7, n8, n3)
        except Exception as e:
            _emit(_fail_line(f"phase failed: {type(e).__name__}: {e}"))
            raise SystemExit(2)
        finally:
            watchdog.cancel()
        raise SystemExit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "--smoke":
        # same wedged-backend protection as the measurement phases: CI
        # (scripts/check.sh) pins CPU, but a bare `bench.py --smoke` on
        # the bench host could land on a dead tunnel
        watchdog = threading.Timer(INIT_WATCHDOG_SECS, _watchdog_fire)
        watchdog.daemon = True
        watchdog.start()
        try:
            rc = run_smoke()
        finally:
            watchdog.cancel()
        raise SystemExit(rc)
    raise SystemExit(main())
