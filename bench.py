"""Benchmark: NEXmark q5-core hash aggregation throughput, TPU vs CPU stand-in.

Runs the hot path of NEXmark q5 (tumble-window projection + per-(window,
auction) COUNT(*) incremental aggregation — reference workload
src/tests/simulation/src/nexmark/q5.sql) through the streaming executor stack
and reports sustained source rows/sec.

Chunks flow as ChunkBatch messages (16 stacked chunks per epoch): the whole
epoch's aggregation is ONE lax.scan dispatch, so the number of host→device
round-trips per epoch is constant — this is what buys throughput when the
chip sits behind a network tunnel (VERDICT r2 weak #2: 42 ms/chunk was
dispatch latency, not compute).

``vs_baseline`` is measured, not assumed: the SAME pipeline runs in a
JAX_PLATFORMS=cpu subprocess first (the documented stand-in for the
reference's Rust CPU engine — BASELINE.md config 2 wants ≥10× a 16-vCPU CPU
engine), and the ratio reported is tpu_rows_per_sec / cpu_rows_per_sec.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import asyncio
import json
import os
import subprocess
import sys
import threading
import time

import jax  # module import is cheap; backend init (jax.devices()) is what can hang

WATCHDOG_SECS = 1800

CHUNK = 4096
WINDOW_US = 10_000_000  # 10s tumble as the q5 core window
# Epoch cadence: ~1M rows per barrier so a barrier closes roughly every
# second at the target throughput — the reference's default 1 s barrier
# interval (src/common/src/config.rs:595) at saturation. Every host sync on
# a tunneled chip costs ~100 ms RTT, so the barrier path is built to sync
# exactly once per epoch.
N_CHUNKS = 1024
WARMUP_CHUNKS = 256
CHUNKS_PER_EPOCH = 256
CPU_N_CHUNKS = 256      # stand-in run is shorter; it reports a rate
Q7_N_CHUNKS = 512       # join consumes every event on both sides
Q7_CPU_N_CHUNKS = 128
# q7 window: 5 ms of event time ≈ 50 bids/window at the generator's
# 10K events/s. The probe side stores every bid of a live window under ONE
# join key, and the bucketed arena bounds per-key cardinality by its lane
# width — the 10 s window of the full q7 (100K rows/key) needs the sharded
# join + watermark cleaning, not a single-chip dense arena; window size is
# a bench parameter of the join core, not of its throughput semantics.
Q7_WINDOW_US = 5_000


def _emit_failure(msg: str) -> None:
    """One parseable JSON line even on failure (VERDICT round-1 item 1:
    round 1 crashed with no output when the chip was held)."""
    print(json.dumps({
        "metric": "nexmark_q5_core_throughput", "value": 0.0,
        "unit": "rows/s", "vs_baseline": 0.0, "error": msg,
    }))
    sys.stdout.flush()


def _watchdog_fire():
    # A daemon-thread timer (not SIGALRM): a hang inside native PJRT/XLA
    # code never returns to the bytecode loop, so a Python signal handler
    # would be deferred forever — exactly the round-1 failure mode.
    _emit_failure("watchdog timeout: backend init or compile hung (chip held?)")
    import os
    os._exit(2)


from risingwave_tpu.common import INT64, TIMESTAMP
from risingwave_tpu.common.chunk import stack_chunks
from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig, NexmarkGenerator
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import agg, count_star
from risingwave_tpu.stream import (
    Barrier, HashAggExecutor, HashJoinExecutor, MockSource, ProjectExecutor,
)


def build_messages(gen, n_chunks, first_epoch):
    """Message script: one ChunkBatch + barrier per epoch."""
    msgs = [Barrier.new(first_epoch)]
    epoch = first_epoch
    for i in range(0, n_chunks, CHUNKS_PER_EPOCH):
        k = min(CHUNKS_PER_EPOCH, n_chunks - i)
        msgs.append(stack_chunks([gen.next_bid_chunk() for _ in range(k)]))
        epoch += 1
        msgs.append(Barrier.new(epoch))
    return msgs, epoch


def measure_q5(n_chunks: int) -> float:
    """Sustained source rows/s of the q5-core pipeline on this backend."""
    gen = NexmarkGenerator(NexmarkConfig(chunk_capacity=CHUNK))
    warm_msgs, last_epoch = build_messages(gen, WARMUP_CHUNKS, 1)
    main_msgs, _ = build_messages(gen, n_chunks, last_epoch + 1)

    # ONE pipeline instance: the warmup messages compile every jitted step the
    # measured messages reuse (jit caches are per-instance closures).
    src = MockSource(BID_SCHEMA, warm_msgs)
    proj = ProjectExecutor(src, [
        call("tumble_start", col(5, TIMESTAMP), Literal(WINDOW_US, INT64)),
        col(0, INT64),
    ], names=("window_start", "auction"))
    agg = HashAggExecutor(proj, [0, 1], [count_star()],
                          table_capacity=1 << 21, out_capacity=CHUNK)

    async def drive() -> float:
        async for _ in agg.execute():  # warmup pass
            pass
        jax.block_until_ready(agg.state.lanes)
        src.reset(main_msgs)
        t0 = time.perf_counter()
        async for _ in agg.execute():
            pass
        jax.block_until_ready(agg.state.lanes)
        return time.perf_counter() - t0

    elapsed = asyncio.run(drive())
    return n_chunks * CHUNK / elapsed


def measure_q7(n_chunks: int) -> float:
    """Sustained source rows/s of the q7-core windowed join: bids joined
    with the per-window MAX(price) (reference workload
    src/tests/simulation/src/nexmark/q7.sql — BASELINE.md config 3). Each
    source event feeds both join sides; the rate reported is source
    events/s."""
    gen = NexmarkGenerator(NexmarkConfig(chunk_capacity=CHUNK))
    warm_msgs, last_epoch = build_messages(gen, 64, 1)
    main_msgs, _ = build_messages(gen, n_chunks, last_epoch + 1)

    def pipeline(side_msgs):
        # probe side: (window, auction, price); build side: per-window max
        probe_src = MockSource(BID_SCHEMA, side_msgs)
        probe = ProjectExecutor(probe_src, [
            call("tumble_start", col(5, TIMESTAMP), Literal(Q7_WINDOW_US, INT64)),
            col(0, INT64),
            col(2, INT64),
        ], names=("window_start", "auction", "price"))
        build_src = MockSource(BID_SCHEMA, side_msgs)
        build_pre = ProjectExecutor(build_src, [
            call("tumble_start", col(5, TIMESTAMP), Literal(Q7_WINDOW_US, INT64)),
            col(2, INT64),
        ], names=("window_start", "price"))
        build = HashAggExecutor(build_pre, [0], [agg("max", 1, INT64)],
                                table_capacity=1 << 16, out_capacity=CHUNK)
        cond = call("equal", col(2, INT64), col(4, INT64))  # price = max
        join = HashJoinExecutor(
            probe, build, [0], [0], condition=cond,
            key_capacity=1 << 16, bucket_width=128, out_capacity=CHUNK)
        return probe_src, build_src, join

    probe_src, build_src, join = pipeline(warm_msgs)

    async def drive() -> float:
        async for _ in join.execute():   # warmup compiles all steps
            pass
        jax.block_until_ready(join.state.left.occupied)
        probe_src.reset(main_msgs)
        build_src.reset(main_msgs)
        t0 = time.perf_counter()
        async for _ in join.execute():
            pass
        jax.block_until_ready(join.state.left.occupied)
        return time.perf_counter() - t0

    elapsed = asyncio.run(drive())
    return n_chunks * CHUNK / elapsed


def measure_barrier_latency() -> dict:
    """p99 barrier latency under a live Session-driven NEXmark MV at the
    reference's defaults (checkpoint every 10th barrier —
    BASELINE.md methodology / docs/metrics.md semantics)."""
    from risingwave_tpu.frontend import Session
    s = Session(source_chunk_capacity=CHUNK, checkpoint_frequency=10)
    s.run_sql("""CREATE SOURCE bid (auction BIGINT, price BIGINT)
                 WITH (connector = 'nexmark', nexmark_table = 'bid')""")
    s.run_sql("""CREATE MATERIALIZED VIEW m AS
        SELECT auction, count(*) AS n FROM bid GROUP BY auction""")
    for _ in range(5):
        s.tick()                    # warmup: jit compiles land here
    s._drain_inflight()
    s.barrier_latency.samples.clear()
    for _ in range(30):
        s.tick()
    s._drain_inflight()
    snap = s.barrier_latency.snapshot()
    s.close()
    return snap


def measure_cpu_standin() -> dict:
    """Run the same pipelines under JAX_PLATFORMS=cpu in a fresh subprocess
    (the in-process backend is already bound to the TPU)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the agent image's sitecustomize force-registers the TPU plugin when
    # these are set, ignoring JAX_PLATFORMS
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("TPU_LIBRARY_PATH", None)
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--rate-only",
         str(CPU_N_CHUNKS), str(Q7_CPU_N_CHUNKS)],
        env=env, capture_output=True, text=True, timeout=1500,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if res.returncode != 0:
        raise RuntimeError(f"cpu stand-in failed: {res.stderr[-500:]}")
    return json.loads(res.stdout.strip().splitlines()[-1])


def main(rearm=lambda: None):
    cpu = measure_cpu_standin()
    cpu_rps, cpu_q7 = cpu["value"], cpu["q7_rows_per_sec"]
    rearm()  # fresh watchdog budget for the TPU phase (the stand-in
    #          subprocess has its own 1500s timeout)
    tpu_rps = measure_q5(N_CHUNKS)
    rearm()
    tpu_q7 = measure_q7(Q7_N_CHUNKS)
    rearm()
    lat = measure_barrier_latency()
    print(json.dumps({
        "metric": "nexmark_q5_core_throughput",
        "value": round(tpu_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 2),
        "baseline_kind": "same pipeline, JAX_PLATFORMS=cpu (Rust-engine stand-in)",
        "cpu_standin_rows_per_sec": round(cpu_rps, 1),
        "chunks_per_dispatch": CHUNKS_PER_EPOCH,
        "q7_join_rows_per_sec": round(tpu_q7, 1),
        "q7_vs_baseline": round(tpu_q7 / cpu_q7, 2),
        "q7_cpu_standin_rows_per_sec": round(cpu_q7, 1),
        "p99_barrier_ms": lat.get("p99_ms"),
        "p50_barrier_ms": lat.get("p50_ms"),
    }))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--rate-only":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else CPU_N_CHUNKS
        n7 = int(sys.argv[3]) if len(sys.argv) > 3 else Q7_CPU_N_CHUNKS
        rps = measure_q5(n)
        q7 = measure_q7(n7)
        print(json.dumps({"metric": "nexmark_q5_core_throughput",
                          "value": round(rps, 1), "unit": "rows/s",
                          "q7_rows_per_sec": round(q7, 1)}))
        raise SystemExit(0)
    watchdog = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
    watchdog.daemon = True
    watchdog.start()

    def rearm():
        nonlocal_box[0].cancel()
        t = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
        t.daemon = True
        t.start()
        nonlocal_box[0] = t

    nonlocal_box = [watchdog]
    try:
        _ = jax.devices()  # may hang on a wedged tunnel; watchdog covers it
    except Exception as e:
        _emit_failure(f"jax backend init failed: {e!r}")
        raise SystemExit(2)
    try:
        main(rearm)
    except SystemExit:
        raise
    except Exception as e:
        _emit_failure(f"bench failed: {type(e).__name__}: {e}")
        raise SystemExit(2)
    finally:
        nonlocal_box[0].cancel()
