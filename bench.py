"""Benchmark: NEXmark q5-core hash aggregation throughput on one chip.

Runs the hot path of NEXmark q5 (tumble-window projection + per-(window,
auction) COUNT(*) incremental aggregation — reference workload
src/tests/simulation/src/nexmark/q5.sql) through the streaming executor stack
on the real device and reports sustained source rows/sec.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` compares against the reference harness's fixed simulation
source rate of 5_000 events/s (src/tests/simulation/src/nexmark.rs:24) — the
repo publishes no absolute numbers (BASELINE.md), so that rate is the only
in-tree reference point.
"""

import asyncio
import json
import sys
import threading
import time

import jax  # module import is cheap; backend init (jax.devices()) is what can hang

WATCHDOG_SECS = 900


def _emit_failure(msg: str) -> None:
    """One parseable JSON line even on failure (VERDICT round-1 item 1:
    round 1 crashed with no output when the chip was held)."""
    print(json.dumps({
        "metric": "nexmark_q5_core_throughput", "value": 0.0,
        "unit": "rows/s", "vs_baseline": 0.0, "error": msg,
    }))
    sys.stdout.flush()


def _watchdog_fire():
    # A daemon-thread timer (not SIGALRM): a hang inside native PJRT/XLA
    # code never returns to the bytecode loop, so a Python signal handler
    # would be deferred forever — exactly the round-1 failure mode.
    _emit_failure("watchdog timeout: backend init or compile hung (chip held?)")
    import os
    os._exit(2)

from risingwave_tpu.common import INT64, TIMESTAMP
from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig, NexmarkGenerator
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import count_star
from risingwave_tpu.stream import (
    Barrier, HashAggExecutor, MockSource, ProjectExecutor,
)

CHUNK = 4096
WINDOW_US = 10_000_000  # 10s tumble as the q5 core window
N_CHUNKS = 200
WARMUP_CHUNKS = 8
CHUNKS_PER_EPOCH = 16


def build_messages(gen, n_chunks, first_epoch):
    msgs = [Barrier.new(first_epoch)]
    epoch = first_epoch
    for i in range(n_chunks):
        msgs.append(gen.next_bid_chunk())
        if (i + 1) % CHUNKS_PER_EPOCH == 0:
            epoch += 1
            msgs.append(Barrier.new(epoch))
    epoch += 1
    msgs.append(Barrier.new(epoch))
    return msgs, epoch


def main():
    gen = NexmarkGenerator(NexmarkConfig(chunk_capacity=CHUNK))
    warm_msgs, last_epoch = build_messages(gen, WARMUP_CHUNKS, 1)
    main_msgs, _ = build_messages(gen, N_CHUNKS, last_epoch + 1)

    # ONE pipeline instance: the warmup messages compile every jitted step the
    # measured messages reuse (jit caches are per-instance closures).
    src = MockSource(BID_SCHEMA, warm_msgs)
    proj = ProjectExecutor(src, [
        call("tumble_start", col(5, TIMESTAMP), Literal(WINDOW_US, INT64)),
        col(0, INT64),
    ], names=("window_start", "auction"))
    agg = HashAggExecutor(proj, [0, 1], [count_star()],
                          table_capacity=1 << 18, out_capacity=CHUNK)

    async def drive() -> float:
        async for _ in agg.execute():  # warmup pass
            pass
        jax.block_until_ready(agg.state.lanes)
        src._messages = main_msgs   # same executors, fresh message script
        t0 = time.perf_counter()
        async for _ in agg.execute():
            pass
        jax.block_until_ready(agg.state.lanes)
        return time.perf_counter() - t0

    elapsed = asyncio.run(drive())
    rows = N_CHUNKS * CHUNK
    rps = rows / elapsed
    print(json.dumps({
        "metric": "nexmark_q5_core_throughput",
        "value": round(rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(rps / 5000.0, 2),
    }))


if __name__ == "__main__":
    watchdog = threading.Timer(WATCHDOG_SECS, _watchdog_fire)
    watchdog.daemon = True
    watchdog.start()
    try:
        _ = jax.devices()  # may hang on a wedged tunnel; watchdog covers it
    except Exception as e:
        _emit_failure(f"jax backend init failed: {e!r}")
        raise SystemExit(2)
    try:
        main()
    except SystemExit:
        raise
    except Exception as e:
        _emit_failure(f"bench failed: {type(e).__name__}: {e}")
        raise SystemExit(2)
    finally:
        watchdog.cancel()
