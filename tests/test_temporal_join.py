"""Temporal (process-time lookup) join — coverage #22.
FOR SYSTEM_TIME AS OF PROCTIME(): enrichment against current table rows,
no retractions when the table changes."""

import pytest

from risingwave_tpu.frontend import Session


class TestTemporalJoin:
    def _setup(self):
        s = Session()
        s.run_sql("CREATE TABLE price (item BIGINT PRIMARY KEY, p BIGINT)")
        s.run_sql("CREATE TABLE orders (oid BIGINT PRIMARY KEY, "
                  "item BIGINT, qty BIGINT) WITH (appendonly = 'true')")
        s.run_sql("INSERT INTO price VALUES (1, 100), (2, 200)")
        s.flush()
        return s

    def test_enrichment_no_retraction(self):
        s = self._setup()
        s.run_sql("""CREATE MATERIALIZED VIEW enriched AS
            SELECT oid, qty * p AS total
            FROM orders JOIN price FOR SYSTEM_TIME AS OF PROCTIME()
            ON orders.item = price.item""")
        s.run_sql("INSERT INTO orders VALUES (10, 1, 3)")
        s.flush()
        assert s.mv_rows("enriched") == [(10, 300)]
        # price change: existing output does NOT retract...
        s.run_sql("INSERT INTO price VALUES (1, 999)")   # pk upsert
        s.flush()
        assert s.mv_rows("enriched") == [(10, 300)]
        # ...but new orders see the current price
        s.run_sql("INSERT INTO orders VALUES (11, 1, 1)")
        s.flush()
        assert sorted(s.mv_rows("enriched")) == [(10, 300), (11, 999)]

    def test_left_temporal_join_pads_nulls(self):
        s = self._setup()
        s.run_sql("""CREATE MATERIALIZED VIEW e AS
            SELECT oid, p
            FROM orders LEFT JOIN price FOR SYSTEM_TIME AS OF PROCTIME()
            ON orders.item = price.item""")
        s.run_sql("INSERT INTO orders VALUES (10, 7, 1)")   # no price row
        s.flush()
        assert s.mv_rows("e") == [(10, None)]

    def test_batch_select_temporal(self):
        s = self._setup()
        s.run_sql("INSERT INTO orders VALUES (10, 2, 4)")
        s.flush()
        rows = s.run_sql(
            "SELECT oid, qty * p FROM orders "
            "JOIN price FOR SYSTEM_TIME AS OF PROCTIME() "
            "ON orders.item = price.item")
        assert rows == [(10, 800)]

    def test_requires_materialized_right(self):
        s = Session()
        s.run_sql("CREATE SOURCE src (k BIGINT) WITH (connector='datagen')")
        s.run_sql("CREATE TABLE o (oid BIGINT PRIMARY KEY, k BIGINT) "
                  "WITH (appendonly = 'true')")
        with pytest.raises(Exception, match="materialized"):
            s.run_sql("SELECT * FROM o JOIN src FOR SYSTEM_TIME AS OF "
                      "PROCTIME() ON o.k = src.k")


class TestJoinWatermarkOrdering:
    def test_watermark_does_not_overtake_pending_output(self):
        """Optimistic batched join emission must flush before forwarding a
        watermark (EOWC downstreams finalize windows on watermarks)."""
        import asyncio
        from risingwave_tpu.common.chunk import make_chunk
        from risingwave_tpu.common.types import INT64, Field, Schema
        from risingwave_tpu.stream.hash_join import HashJoinExecutor
        from risingwave_tpu.stream.message import Barrier, Watermark
        from risingwave_tpu.stream.source import MockSource
        from risingwave_tpu.common.chunk import StreamChunk

        S = Schema((Field("k", INT64), Field("ts", INT64)))
        left = MockSource(S, [
            Barrier.new(1),
            make_chunk(S, [(1, 10)], capacity=2),
            Watermark(1, 100),
            Barrier.new(2),
        ])
        right = MockSource(S, [
            Barrier.new(1),
            make_chunk(S, [(1, 11)], capacity=2),
            Barrier.new(2),
        ])
        join = HashJoinExecutor(left, right, [0], [0], out_capacity=8)

        async def run():
            seq = []
            async for m in join.execute():
                if isinstance(m, StreamChunk):
                    import jax.numpy as jnp
                    if bool(jnp.any(m.vis)):
                        seq.append("chunk")
                elif isinstance(m, Watermark):
                    seq.append("wm")
            return seq

        seq = asyncio.run(run())
        assert "wm" in seq and "chunk" in seq
        assert seq.index("chunk") < seq.index("wm")


class TestAppendOnlyGuard:
    def test_retracting_probe_side_rejected_at_plan_time(self):
        s = Session()
        s.run_sql("CREATE TABLE price (item BIGINT PRIMARY KEY, p BIGINT)")
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, item BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW agg AS "
                  "SELECT item, count(*) AS c FROM t GROUP BY item")
        with pytest.raises(Exception, match="append-only"):
            s.run_sql("SELECT * FROM agg JOIN price FOR SYSTEM_TIME AS OF "
                      "PROCTIME() ON agg.item = price.item")
