"""Dispatchers + permit exchange + merge (coverage #33/#35): hash split
with update-pair degradation, backpressure, barrier-aligned fan-in."""

import asyncio

import pytest

from risingwave_tpu.common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, chunk_to_rows,
    make_chunk,
)
from risingwave_tpu.common.types import INT64, Field, Schema
from risingwave_tpu.stream.dispatch import (
    BroadcastDispatcher, ChannelSource, HashDispatcher, MergeExecutor,
    PermitChannel, RoundRobinDispatcher,
)
from risingwave_tpu.stream.message import Barrier, Watermark

S = Schema((Field("k", INT64), Field("v", INT64)))


async def _collect(ch, n):
    out = []
    for _ in range(n):
        out.append(await ch.recv())
    return out


class TestHashDispatcher:
    def test_rows_partition_and_barriers_broadcast(self):
        outs = [PermitChannel(), PermitChannel(), PermitChannel()]
        d = HashDispatcher(outs, [0], S)
        rows = [(i, i * 10) for i in range(30)]
        chunk = make_chunk(S, rows, capacity=32)

        async def go():
            await d.dispatch(chunk)
            await d.dispatch(Barrier.new(1))
            seen = []
            for ch in outs:
                msgs = await _collect(ch, 2)
                part = chunk_to_rows(msgs[0], S)
                seen.extend(part)
                assert isinstance(msgs[1], Barrier)
            assert sorted(seen) == rows        # disjoint cover

        asyncio.run(go())

    def test_update_pair_split_across_shards_degrades(self):
        outs = [PermitChannel(), PermitChannel()]
        d = HashDispatcher(outs, [0], S)
        # find two keys landing on different shards
        import numpy as np
        from risingwave_tpu.common.hashing import vnode_of, vnode_to_shard
        probe = make_chunk(S, [(i, 0) for i in range(16)], capacity=16)
        shards = np.asarray(vnode_to_shard(
            vnode_of([probe.columns[0]]), 2))
        a = 0
        b = next(i for i in range(16) if shards[i] != shards[a])
        chunk = make_chunk(S, [(a, 1), (b, 2)],
                           ops=[OP_UPDATE_DELETE, OP_UPDATE_INSERT],
                           capacity=4)

        async def go():
            await d.dispatch(chunk)
            ops = []
            for ch in outs:
                msg = (await _collect(ch, 1))[0]
                ops.extend(
                    op for op, _ in chunk_to_rows(msg, S, with_ops=True))
            # the pair crossed shards: U-/U+ became plain Delete/Insert
            assert sorted(ops) == sorted([OP_DELETE, OP_INSERT])
            assert OP_UPDATE_DELETE not in ops
            assert OP_UPDATE_INSERT not in ops

        asyncio.run(go())

    def test_update_pair_same_shard_preserved(self):
        outs = [PermitChannel(), PermitChannel()]
        d = HashDispatcher(outs, [0], S)
        chunk = make_chunk(S, [(5, 1), (5, 2)],
                           ops=[OP_UPDATE_DELETE, OP_UPDATE_INSERT],
                           capacity=4)
        async def go():
            await d.dispatch(chunk)
            ops = []
            for ch in outs:
                msg = (await _collect(ch, 1))[0]
                ops.extend(
                    op for op, _ in chunk_to_rows(msg, S, with_ops=True))
            assert ops == [OP_UPDATE_DELETE, OP_UPDATE_INSERT]

        asyncio.run(go())


class TestPermits:
    def test_backpressure_blocks_sender_not_barriers(self):
        ch = PermitChannel(permits=2)
        c1 = make_chunk(S, [(1, 1)], capacity=2)

        async def go():
            await ch.send(c1)
            await ch.send(c1)
            # 3rd data send must block until a recv releases a permit
            blocked = asyncio.ensure_future(ch.send(c1))
            await asyncio.sleep(0.01)
            assert not blocked.done()
            # barriers pass regardless of data budget
            await asyncio.wait_for(ch.send(Barrier.new(1)), timeout=1)
            await ch.recv()                     # releases one permit
            await asyncio.wait_for(blocked, timeout=1)

        asyncio.run(go())


class TestMerge:
    def test_barrier_alignment_across_upstreams(self):
        chs = [PermitChannel(), PermitChannel()]
        merge = MergeExecutor(chs, S)
        c = make_chunk(S, [(1, 1)], capacity=2)

        async def go():
            order = []

            async def consume():
                async for m in merge.execute():
                    order.append(type(m).__name__)

            task = asyncio.ensure_future(consume())
            await chs[0].send(c)
            await chs[0].send(Barrier.new(1))   # held: ch1 not ready
            await asyncio.sleep(0.01)
            assert "Barrier" not in order
            await chs[1].send(c)
            await chs[1].send(Barrier.new(1))   # releases the barrier
            await asyncio.sleep(0.05)
            assert order.count("Barrier") == 1
            from risingwave_tpu.stream.message import Mutation, MutationKind
            stop = Barrier.new(
                2, mutation=Mutation(MutationKind.STOP))
            await chs[0].send(stop)
            await chs[1].send(stop)
            await asyncio.wait_for(task, timeout=2)
            assert order[-1] == "Barrier"

        asyncio.run(go())

    def test_round_robin_and_broadcast(self):
        outs = [PermitChannel(), PermitChannel()]
        rr = RoundRobinDispatcher(outs)
        c = make_chunk(S, [(1, 1)], capacity=2)

        async def go():
            await rr.dispatch(c)
            await rr.dispatch(c)
            assert await _collect(outs[0], 1)
            assert await _collect(outs[1], 1)
            bc = BroadcastDispatcher(outs)
            await bc.dispatch(Watermark(0, 5))
            assert isinstance((await _collect(outs[0], 1))[0], Watermark)
            assert isinstance((await _collect(outs[1], 1))[0], Watermark)

        asyncio.run(go())
