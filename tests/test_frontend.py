"""SQL frontend tests: parser, planner shapes, and end-to-end SQL → MV
(reference: src/sqlparser/test_runner + src/frontend/planner_test golden
style, and e2e_test/streaming/ sqllogictest style, scaled down)."""

import pytest

from risingwave_tpu.frontend import Session, parse_sql
from risingwave_tpu.frontend import sqlast as A
from risingwave_tpu.frontend.parser import parse_one
from risingwave_tpu.frontend.planner import (
    PAgg, PDynFilter, PFilter, PHopWindow, PJoin, PProject, PSource, PTopN,
    Planner,
)


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


def test_parse_select_shapes():
    q = parse_one("""
        SELECT auction, count(*) AS n, sum(price)
        FROM bid
        WHERE price > 100 AND channel = 'Google'
        GROUP BY auction HAVING count(*) > 2
        ORDER BY n DESC LIMIT 10
    """)
    sel = q.select
    assert len(sel.items) == 3
    assert sel.items[1].alias == "n"
    assert isinstance(sel.where, A.BinaryOp) and sel.where.op == "AND"
    assert len(sel.group_by) == 1 and sel.having is not None
    assert sel.order_by[0].desc and sel.limit == 10


def test_parse_create_source_and_mv():
    stmts = parse_sql("""
        CREATE SOURCE s (a BIGINT, t TIMESTAMP,
            WATERMARK FOR t AS t - INTERVAL '5 seconds')
        WITH (connector = 'nexmark', nexmark_table = 'bid');
        CREATE MATERIALIZED VIEW v AS SELECT a FROM s;
    """)
    src, mv = stmts
    assert isinstance(src, A.CreateSource)
    assert src.watermark is not None and src.watermark[0] == "t"
    assert isinstance(mv, A.CreateMaterializedView) and mv.name == "v"


def test_parse_interval_and_tvf():
    q = parse_one("""
        SELECT window_start FROM TUMBLE(bid, date_time, INTERVAL '10 seconds')
    """)
    tvf = q.select.from_
    assert isinstance(tvf, A.WindowTVF) and tvf.kind == "tumble"
    assert tvf.args[0].value == 10_000_000


def test_parse_join_and_subquery():
    q = parse_one("""
        SELECT a.x FROM a JOIN b ON a.k = b.k
        WHERE a.x > (SELECT max(y) FROM c)
    """)
    assert isinstance(q.select.from_, A.Join)
    conj = q.select.where
    assert isinstance(conj.right, A.ScalarSubquery)


def test_parse_case_in_between():
    q = parse_one("""
        SELECT CASE WHEN a > 1 THEN 'hi' ELSE 'lo' END
        FROM t WHERE a IN (1, 2, 3) AND b BETWEEN 5 AND 10
               AND c IS NOT NULL
    """)
    assert isinstance(q.select.items[0].expr, A.Case)


# ---------------------------------------------------------------------------
# planner (golden-ish shape tests)
# ---------------------------------------------------------------------------


NEXMARK_DDL = """
CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,
  channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'bid')
"""


def _planner():
    s = Session()
    s.run_sql(NEXMARK_DDL)
    return s, Planner(s.catalog)


def test_plan_q1_projection():
    s, planner = _planner()
    plan = planner.plan_select(parse_one(
        "SELECT auction, price * 100 AS p FROM bid").select)
    assert isinstance(plan, PProject)
    assert isinstance(plan.input, PSource)
    # hidden _row_id pk appended
    assert plan.schema.names[-1].startswith("_pk")
    assert plan.pk == (2,)


def test_plan_agg_shape():
    s, planner = _planner()
    plan = planner.plan_select(parse_one(
        "SELECT auction, count(*) FROM bid GROUP BY auction").select)
    assert isinstance(plan, PProject)
    agg = plan.input
    assert isinstance(agg, PAgg) and agg.group_keys == (0,)
    assert agg.agg_calls[0].kind == "count"
    assert plan.pk == (0,)   # group key is the stream key, already visible


def test_plan_topn_and_dynfilter():
    s, planner = _planner()
    plan = planner.plan_select(parse_one(
        "SELECT auction, price FROM bid ORDER BY price DESC LIMIT 3").select)
    assert isinstance(plan, PTopN) and plan.limit == 3
    plan2 = planner.plan_select(parse_one(
        "SELECT auction FROM bid WHERE price > (SELECT max(price) FROM bid)"
    ).select)
    assert isinstance(plan2, PProject)
    assert isinstance(plan2.input, PDynFilter)


def test_plan_hop_window():
    s, planner = _planner()
    plan = planner.plan_select(parse_one("""
        SELECT auction, window_start
        FROM HOP(bid, date_time, INTERVAL '2 seconds', INTERVAL '10 seconds')
    """).select)
    assert isinstance(plan, PProject)
    assert isinstance(plan.input, PHopWindow)
    assert plan.input.slide == 2_000_000 and plan.input.size == 10_000_000


# ---------------------------------------------------------------------------
# end-to-end SQL
# ---------------------------------------------------------------------------


def test_e2e_source_mv_agg_and_select():
    s = Session(source_chunk_capacity=64)
    s.run_sql(NEXMARK_DDL)
    s.run_sql("""CREATE MATERIALIZED VIEW counts AS
        SELECT auction % 4 AS b, count(*) AS n, max(price) AS top
        FROM bid GROUP BY auction % 4""")
    s.run_sql("""CREATE MATERIALIZED VIEW q1 AS
        SELECT auction, price * 100 AS cents FROM bid""")
    for _ in range(3):
        s.tick()
    q1 = s.mv_rows("q1")
    counts = s.mv_rows("counts")
    assert len(q1) == 3 * 64
    assert sum(r[1] for r in counts) == len(q1)
    res = s.run_sql("SELECT b, n FROM counts ORDER BY n DESC LIMIT 2")
    assert len(res) == 2 and res[0][1] >= res[1][1]


def test_e2e_table_insert_join():
    s = Session(source_chunk_capacity=32)
    s.run_sql("CREATE TABLE person (id BIGINT PRIMARY KEY, name VARCHAR)")
    s.run_sql("CREATE TABLE orders (oid BIGINT PRIMARY KEY, pid BIGINT, amt BIGINT)")
    s.run_sql("INSERT INTO person VALUES (1, 'alice'), (2, 'bob')")
    s.run_sql("INSERT INTO orders VALUES (10, 1, 100), (11, 1, 50), (12, 3, 9)")
    s.run_sql("""CREATE MATERIALIZED VIEW by_person AS
        SELECT p.name, sum(o.amt) AS total
        FROM orders o JOIN person p ON o.pid = p.id
        GROUP BY p.name""")
    s.tick()
    assert sorted(s.mv_rows("by_person")) == [("alice", 150)]
    # late-arriving person 3 joins retroactively
    s.run_sql("INSERT INTO person VALUES (3, 'carol')")
    s.tick()
    assert sorted(s.mv_rows("by_person")) == [("alice", 150), ("carol", 9)]


def test_e2e_mv_on_mv_and_drop():
    s = Session(source_chunk_capacity=32)
    s.run_sql(NEXMARK_DDL)
    s.run_sql("CREATE MATERIALIZED VIEW base AS SELECT auction, price FROM bid")
    s.tick()
    s.run_sql("""CREATE MATERIALIZED VIEW derived AS
        SELECT auction, count(*) AS n FROM base GROUP BY auction""")
    s.tick()
    base = s.mv_rows("base")
    derived = s.mv_rows("derived")
    assert sum(r[1] for r in derived) == len(base)
    s.run_sql("DROP MATERIALIZED VIEW derived")
    assert "derived" not in s.catalog.mvs
    s.tick()   # remaining jobs still run


def test_e2e_values_and_union():
    s = Session()
    s.run_sql("CREATE TABLE a (x BIGINT PRIMARY KEY)")
    s.run_sql("CREATE TABLE b (x BIGINT PRIMARY KEY)")
    s.run_sql("INSERT INTO a VALUES (1), (2)")
    s.run_sql("INSERT INTO b VALUES (3)")
    s.flush()
    res = s.run_sql("SELECT x FROM a UNION ALL SELECT x FROM b ORDER BY x")
    assert res == [(1,), (2,), (3,)]
    res = s.run_sql("SELECT 1 + 1 AS two")
    assert res == [(2,)]


def test_e2e_distinct_and_where():
    s = Session()
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    s.run_sql("INSERT INTO t VALUES (1, 5), (2, 5), (3, 7), (4, 8)")
    s.flush()
    res = s.run_sql("SELECT DISTINCT v FROM t WHERE v < 8 ORDER BY v")
    assert res == [(5,), (7,)]
