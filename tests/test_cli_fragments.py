"""The --fragment-parallelism CLI knob and its rw_config twin actually
turn the multi-fragment build path on (frontend/build.py:59 defaulted to 1
and nothing ever flipped it — VERDICT weak #6)."""

import argparse

from risingwave_tpu.common.config import load_config


def test_build_session_passes_fragment_parallelism():
    from risingwave_tpu.cli import _build_session
    args = argparse.Namespace(data_dir=None, fragment_parallelism=2)
    s = _build_session(args)
    try:
        assert s.config.fragment_parallelism == 2
    finally:
        s.close()


def test_playground_parser_default_is_parallel():
    from risingwave_tpu.cli import main  # noqa: F401 — import side effects
    import risingwave_tpu.cli as cli
    p = argparse.ArgumentParser(prog="x")
    # re-derive the parser default through the public entrypoint: parse
    # only, no session (playground would start a server)
    import sys
    from unittest import mock
    captured = {}

    def fake_playground(args):
        captured["fp"] = args.fragment_parallelism
        return 0

    with mock.patch.object(cli, "_playground", fake_playground):
        assert cli.main(["playground"]) == 0
    assert captured["fp"] == 2          # flipped >1 by default


def test_rw_config_fragment_parallelism_flows_to_build_config():
    from risingwave_tpu.frontend.session import Session
    cfg = load_config(**{"streaming.fragment_parallelism": 3})
    s = Session(rw_config=cfg)
    try:
        assert s.config.fragment_parallelism == 3
    finally:
        s.close()


def test_fragmented_mv_end_to_end_via_config():
    """A grouped-agg MV built under the flipped default actually runs as a
    multi-fragment job and produces correct results."""
    from risingwave_tpu.cli import _build_session
    args = argparse.Namespace(data_dir=None, fragment_parallelism=2)
    s = _build_session(args)
    try:
        s.run_sql("CREATE TABLE t (k BIGINT, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, count(*) AS n, sum(v) AS sv FROM t GROUP BY k")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20), (1, 30)")
        s.tick()
        rows = sorted(s.run_sql("SELECT k, n, sv FROM m ORDER BY k"))
        assert rows == [(1, 2, 40), (2, 1, 20)]
    finally:
        s.close()
