"""ObjectStore abstraction + background compaction (VERDICT r3 item 7).

The durable checkpoint log is parameterized by ObjectStore (reference:
src/object_store/src/object/mod.rs:93-136); segments fold on a background
thread off the barrier path (reference: standalone compactor,
src/storage/compactor/src/server.rs:57) while ticks keep committing.
"""

import threading

import pytest

from risingwave_tpu.storage.checkpoint import CheckpointLog, DurableStateStore
from risingwave_tpu.storage.object_store import (
    LocalFsObjectStore, MemObjectStore,
)


class TestObjectStoreBackends:
    @pytest.mark.parametrize("mk", [
        lambda tmp: MemObjectStore(),
        lambda tmp: LocalFsObjectStore(str(tmp / "objs")),
    ])
    def test_put_get_list_delete(self, tmp_path, mk):
        st = mk(tmp_path)
        assert st.get("a/x") is None and not st.exists("a/x")
        st.put("a/x", b"1")
        st.put("a/y", b"22")
        st.put("b/z", b"333")
        assert st.get("a/y") == b"22" and st.exists("a/x")
        assert st.list("a/") == ["a/x", "a/y"]
        assert st.list() == ["a/x", "a/y", "b/z"]
        st.atomic_put("a/x", b"new")
        assert st.get("a/x") == b"new"
        st.delete("a/x")
        assert st.get("a/x") is None
        st.delete("missing")          # idempotent

    def test_atomic_put_leaves_no_tmp_visible(self, tmp_path):
        st = LocalFsObjectStore(str(tmp_path / "objs"))
        st.atomic_put("m.json", b"{}")
        assert st.list() == ["m.json"]


class TestCheckpointLogOverObjectStore:
    def test_mem_backend_round_trip(self):
        store = MemObjectStore()
        log = CheckpointLog(object_store=store)
        log.append_epoch(2, {1: {b"k1": b"v1", b"k2": b"v2"}})
        log.append_epoch(4, {1: {b"k2": None}, 2: {b"a": b"b"}})
        log.log_ddl("CREATE TABLE t")
        epoch, tables = CheckpointLog(object_store=store).load_tables()
        assert epoch == 4
        assert tables[1] == {b"k1": b"v1"} and tables[2] == {b"a": b"b"}
        assert CheckpointLog(object_store=store).ddl() == ["CREATE TABLE t"]

    def test_durable_store_over_mem_object_store(self):
        store = MemObjectStore()
        s = DurableStateStore(object_store=store)
        s.ingest(7, 3, {b"k": ("row",)}, set())
        # value must be bytes for durability; emulate the table layer
        s._pending[3][7][b"k"] = b"row-bytes"
        s.commit(3)
        s2 = DurableStateStore(object_store=store)
        assert s2.committed_epoch == 3
        assert s2.get(7, b"k") == b"row-bytes"


class TestBackgroundCompaction:
    def test_fold_runs_off_thread_and_appends_interleave(self, tmp_path):
        log = CheckpointLog(str(tmp_path / "d"), compact_after=4)
        for e in range(1, 8):
            log.append_epoch(e, {1: {f"k{e}".encode(): b"v"}})
        log.wait_compaction()
        m = log._read_manifest()
        assert len(m["segments"]) <= 5          # folded under the threshold
        epoch, tables = log.load_tables()
        assert epoch == 7
        assert tables[1] == {f"k{e}".encode(): b"v" for e in range(1, 8)}

    def test_concurrent_appends_during_fold_survive(self, tmp_path):
        log = CheckpointLog(str(tmp_path / "d"), compact_after=2)
        n_appends = 40
        errs = []

        def appender():
            try:
                for e in range(100, 100 + n_appends):
                    log.append_epoch(e, {1: {f"c{e}".encode(): b"x"}})
            except BaseException as ex:   # noqa: BLE001
                errs.append(ex)

        t = threading.Thread(target=appender)
        t.start()
        while t.is_alive():               # folds race the appends
            log.compact()
        t.join()
        log.wait_compaction()
        assert not errs
        _, tables = log.load_tables()
        # every appended key survived every fold
        assert sorted(tables[1]) == [
            f"c{e}".encode() for e in range(100, 100 + n_appends)]
        assert all(v == b"x" for v in tables[1].values())

    def test_dropped_tables_discarded_in_fold(self, tmp_path):
        log = CheckpointLog(str(tmp_path / "d"))
        log.append_epoch(1, {1: {b"a": b"1"}, 2: {b"b": b"2"}})
        log.append_epoch(2, {1: {b"c": b"3"}})
        log.drop_table(1)
        log.compact()
        _, tables = log.load_tables()
        assert 1 not in tables and tables[2] == {b"b": b"2"}
