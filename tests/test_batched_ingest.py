"""Batched single-dispatch ingest parity: a ChunkBatch scanned on device
by HashJoinExecutor / TopNExecutor must produce EXACTLY the outputs of the
default unstack-and-loop path (same chunks, same order), including the
rewind-and-regrow path when the scanned batch overflows mid-way."""

import asyncio

from risingwave_tpu.common import INT64, Schema, chunk_to_rows, make_chunk
from risingwave_tpu.common.chunk import (
    OP_DELETE, OP_INSERT, StreamChunk, stack_chunks,
)
from risingwave_tpu.ops import JoinType
from risingwave_tpu.ops.topn import OrderSpec
from risingwave_tpu.stream import (
    Barrier, HashJoinExecutor, MockSource, TopNExecutor,
)

L_SCHEMA = Schema.of(("k", INT64), ("a", INT64))
R_SCHEMA = Schema.of(("k", INT64), ("b", INT64))
CAP = 32


def lchunk(rows, ops=None):
    return make_chunk(L_SCHEMA, rows, ops=ops, capacity=CAP)


def rchunk(rows, ops=None):
    return make_chunk(R_SCHEMA, rows, ops=ops, capacity=CAP)


def drive_join(left_msgs, right_msgs, batch_chunks=None, **kw):
    kw.setdefault("key_capacity", 64)
    kw.setdefault("bucket_width", 4)
    kw.setdefault("out_capacity", 32)
    ex = HashJoinExecutor(
        MockSource(L_SCHEMA, left_msgs), MockSource(R_SCHEMA, right_msgs),
        [0], [0], JoinType.INNER, **kw)
    if batch_chunks is not None:
        ex.batch_chunks = batch_chunks

    async def drain():
        out = []
        async for m in ex.execute():
            if isinstance(m, StreamChunk):
                out.extend(chunk_to_rows(m, ex.schema, with_ops=True))
        return out

    return asyncio.run(drain()), ex


LEFT_CHUNKS = [
    lchunk([(1, 100), (2, 200), (1, 101)]),
    lchunk([(3, 300)]),
    lchunk([(1, 100)], ops=[OP_DELETE]),
    lchunk([(4, 400), (2, 201)]),
    lchunk([(5, 500)]),
]


def _join_msgs(batched: bool):
    # build rows land in epoch 1, the probe batch in epoch 2 — barrier
    # alignment pins the apply order, so batched and unbatched runs are
    # comparable chunk-for-chunk (intra-epoch interleaving of the two
    # sides is otherwise a valid-but-arbitrary schedule)
    right = [Barrier.new(1),
             rchunk([(1, 10), (2, 20), (3, 30), (4, 40), (5, 50)]),
             Barrier.new(2), Barrier.new(3)]
    if batched:
        left = [Barrier.new(1), Barrier.new(2), stack_chunks(LEFT_CHUNKS),
                Barrier.new(3)]
    else:
        left = [Barrier.new(1), Barrier.new(2), *LEFT_CHUNKS,
                Barrier.new(3)]
    return left, right


def test_join_batch_matches_per_chunk():
    base, _ = drive_join(*_join_msgs(batched=False))
    got, ex = drive_join(*_join_msgs(batched=True), batch_chunks=2)
    assert got == base
    assert ex.stats.batches_in == 1
    assert ex.stats.batch_chunks_in == len(LEFT_CHUNKS)
    # the join actually produced rows (deletes included)
    assert any(op == OP_DELETE for op, _ in base)


def test_join_batch_overflow_rewinds_and_grows():
    # key_capacity 4 with 5 distinct keys: the scanned sub-batch overflows
    # and must rewind + replay through the growing path, bit-identically
    base, _ = drive_join(*_join_msgs(batched=False), key_capacity=4,
                         bucket_width=2)
    got, ex = drive_join(*_join_msgs(batched=True), batch_chunks=4,
                         key_capacity=4, bucket_width=2)
    assert got == base
    assert ex.core.capacity > 4      # growth actually happened


def test_join_batch_on_build_side():
    rights = [rchunk([(1, 10)]), rchunk([(1, 11), (2, 20)]),
              rchunk([(1, 10)], ops=[OP_DELETE])]
    left = [Barrier.new(1), lchunk([(1, 100), (2, 200)]), Barrier.new(2),
            Barrier.new(3)]
    right_base = [Barrier.new(1), Barrier.new(2), *rights, Barrier.new(3)]
    right_batch = [Barrier.new(1), Barrier.new(2), stack_chunks(rights),
                   Barrier.new(3)]
    base, _ = drive_join(left, right_base)
    got, _ = drive_join(
        [Barrier.new(1), lchunk([(1, 100), (2, 200)]), Barrier.new(2),
         Barrier.new(3)], right_batch, batch_chunks=2)
    assert got == base


S_SCHEMA = Schema.of(("v", INT64), ("pk", INT64))


def _topn_outputs(msgs):
    ex = TopNExecutor(MockSource(S_SCHEMA, msgs),
                      [OrderSpec(0)], offset=0, limit=3, pk_indices=[1],
                      table_capacity=1 << 10, out_capacity=32)

    async def drain():
        out = []
        async for m in ex.execute():
            if isinstance(m, StreamChunk):
                out.extend(chunk_to_rows(m, ex.schema, with_ops=True))
        return out

    return asyncio.run(drain())


def test_topn_batch_matches_per_chunk():
    chunks = [
        make_chunk(S_SCHEMA, [(5, 1), (3, 2), (8, 3)], capacity=CAP),
        make_chunk(S_SCHEMA, [(1, 4), (9, 5)], capacity=CAP),
        make_chunk(S_SCHEMA, [(3, 2)], ops=[OP_DELETE], capacity=CAP),
        make_chunk(S_SCHEMA, [(2, 6)], capacity=CAP),
    ]
    base = _topn_outputs([Barrier.new(1), *chunks, Barrier.new(2)])
    got = _topn_outputs([Barrier.new(1), stack_chunks(chunks),
                         Barrier.new(2)])
    assert sorted(got) == sorted(base)
    assert any(op == OP_INSERT for op, _ in base)
