"""Mesh-sharded fused epochs (ops/fused_sharded.py + parallel/fused.py):
one dispatch per epoch across the virtual 8-device mesh, bit-exact vs the
solo fused path — merged group values, flush churn (U-/U+ retraction
pairs included), probe emissions, checkpoint export → kill → import, and
mesh-resize re-shard by vnode replay. Plus the mesh-topology recovery gap
(8-device-saved → 4-device-reopened refuses loudly) and the
[streaming] mesh_shape / --mesh opt-in knobs."""

import argparse
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import INT64, TIMESTAMP, chunk_to_rows
from risingwave_tpu.common.config import MeshUnavailableError
from risingwave_tpu.common.dispatch_count import count_dispatches
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.connector import NexmarkConfig
from risingwave_tpu.connector.nexmark import DeviceBidGenerator
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import agg as agg_call, count_star
from risingwave_tpu.ops.fused_epoch import (
    fused_source_agg_epoch, fused_source_join_epoch,
)
from risingwave_tpu.ops.grouped_agg import AggCore
from risingwave_tpu.ops.interval_join import IntervalJoinCore
from risingwave_tpu.parallel.fused import (
    ShardedFusedAgg, ShardedFusedJoin, load_shard_states,
    reshard_join_payloads,
)
from risingwave_tpu.parallel.sharded_agg import make_mesh

CAP = 256
N_DEV = 8
Q5_WINDOW = 1_000_000
Q7_WINDOW = 5_000

Q5_EPOCH_FN = "sharded_agg_epoch.<locals>.epoch"
Q7_EPOCH_FN = "sharded_join_epoch.<locals>.epoch"


def _q5_parts(table_capacity=1 << 12):
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(Q5_WINDOW, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    core = AggCore([INT64, INT64], [0, 1],
                   [count_star(), agg_call("max", 2, INT64)],
                   table_capacity, CAP)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    return exprs, core, gen.chunk_fn()


def _q7_parts(n_buckets=512, lane_width=64):
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(Q7_WINDOW, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    probe_schema = Schema((
        Field("window_start", TIMESTAMP), Field("auction", INT64),
        Field("price", INT64)))
    core = IntervalJoinCore(probe_schema, ts_col=0, val_col=2,
                            window_us=Q7_WINDOW, n_buckets=n_buckets,
                            lane_width=lane_width)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    return exprs, core, gen.chunk_fn()


def _agg_groups(state_h):
    """{key: (lanes...)} of one solo-shaped host AggState."""
    out = {}
    occ = np.asarray(state_h.table.occupied)
    live = np.asarray(state_h.lanes[0]) > 0
    kd = [np.asarray(x) for x in state_h.table.key_data]
    km = [np.asarray(x) for x in state_h.table.key_mask]
    lanes = [np.asarray(x) for x in state_h.lanes]
    return {
        tuple(kd[c][s].item() if km[c][s] else None
              for c in range(len(kd))):
        tuple(l[s].item() for l in lanes)
        for s in np.nonzero(occ & live)[0]
    }


def _rows(chunks, schema):
    out = []
    for c in chunks:
        out.extend(chunk_to_rows(c, schema, with_ops=True, physical=True))
    return sorted(out)


def _solo_q5_epoch_and_flush(solo, core, state, start, key, k):
    """The solo fused q5 epoch + the executor-identical flush: returns
    (state, flush chunks)."""
    probe = jax.jit(lambda st: (jnp.stack(
        [core.flush_rank(st)[-1], st.overflow.astype(jnp.int32)]),
        core.flush_rank(st)))
    gather = jax.jit(core.gather_flush_chunk)
    finish = jax.jit(core.finish_flush)
    state = solo(state, jnp.int64(start), key, k)
    packed, rank = probe(state)
    n_dirty, overflow = (int(x) for x in jax.device_get(packed))
    assert not overflow
    chunks = []
    lo = 0
    while lo < n_dirty:
        chunks.append(gather(state, rank, jnp.int64(lo)))
        lo += core.groups_per_chunk
    return finish(state), chunks


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= N_DEV, "conftest must force 8 CPU devices"
    return make_mesh(N_DEV)


# ---------------------------------------------------------------------------
# q5: bit-exact state + flush churn vs the solo fused path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_shards,k", [(8, 8), (4, 6), (1, 4)])
def test_sharded_agg_bit_exact_vs_solo(mesh8, n_shards, k):
    """Merged per-group values AND the flush churn multiset (U-/U+
    retraction pairs included) equal the solo fused epoch's over two
    epochs — for full meshes, partial meshes and the 1-shard edge, with
    k both divisible and not divisible by the shard count."""
    exprs, core, chunk_fn = _q5_parts()
    mesh = mesh8 if n_shards == N_DEV else make_mesh(n_shards)
    sf = ShardedFusedAgg(mesh, core, chunk_fn, exprs, CAP)
    solo = fused_source_agg_epoch(chunk_fn, exprs, core, CAP,
                                  donate=False)
    flush_schema = Schema(
        (Field("ws", INT64), Field("auction", INT64),
         Field("cnt", INT64), Field("mx", INT64)))
    st = core.init_state()
    start = 0
    for epoch in range(2):
        key = jax.random.fold_in(jax.random.PRNGKey(7), epoch)
        sf.run_epoch(start, key, k)
        got_chunks = sf.flush()
        st, want_chunks = _solo_q5_epoch_and_flush(
            solo, core, st, start, key, k)
        start += k * CAP
        # epoch 2's churn retracts epoch 1's rows: U-/U+ pairs
        assert _rows(got_chunks, flush_schema) == \
            _rows(want_chunks, flush_schema)
    merged = sf.merged_group_values()
    want = _agg_groups(jax.device_get(st))
    assert merged == want and len(merged) > 10


def test_sharded_agg_route_overflow_grows_and_stays_exact(mesh8):
    """NEXmark's hot-auction skew overflows a width-1 receive buffer;
    the driver must grow + retry from the untouched pre-epoch state and
    still produce the solo-exact result."""
    exprs, core, chunk_fn = _q5_parts()
    sf = ShardedFusedAgg(mesh8, core, chunk_fn, exprs, CAP, recv_width=1)
    solo = fused_source_agg_epoch(chunk_fn, exprs, core, CAP,
                                  donate=False)
    key = jax.random.PRNGKey(3)
    sf.run_epoch(0, key, 8)
    sf.flush()
    assert sf.route_grows > 0 and sf.recv_width > 1
    st = solo(core.init_state(), jnp.int64(0), key, 8)
    assert sf.merged_group_values() == _agg_groups(jax.device_get(st))


# ---------------------------------------------------------------------------
# q7: probe emissions + flush churn vs the solo fused join epoch
# ---------------------------------------------------------------------------


def _solo_q7_epoch_rows(solo, core, state, start, key, k):
    from risingwave_tpu.common.chunk import (
        flatten_shards, gather_units_window,
    )
    gather = jax.jit(core.gather_flush,
                     static_argnames=("out_capacity",))
    pgather = jax.jit(lambda po, lo: gather_units_window(
        flatten_shards(po), lo, CAP))
    (state, probe_out, del_m, ins_m, old_emitted, packed) = solo(
        state, jnp.int64(start), key, k)
    n_flush, ovf, clobber, sawdel, n_probe = (
        int(x) for x in jax.device_get(packed))
    assert not (ovf or clobber or sawdel)
    probe_chunks, churn_chunks = [], []
    lo = 0
    while lo < n_probe:
        probe_chunks.append(pgather(probe_out, jnp.int64(lo)))
        lo += CAP // 2
    lo = 0
    while lo < n_flush:
        churn_chunks.append(gather(state, del_m, ins_m, old_emitted,
                                   jnp.int64(lo), out_capacity=CAP))
        lo += CAP
    return state, probe_chunks, churn_chunks


@pytest.mark.parametrize("n_shards", [8, 4])
def test_sharded_join_bit_exact_vs_solo(mesh8, n_shards):
    """Two epochs of the q7 shape: epoch 1 builds per-window maxes,
    epoch 2 emits probe matches against them AND the flush churn
    (delete-vs-old-max / insert-vs-new-max) — every emission surface's
    multiset must equal the solo fused join epoch's."""
    exprs, core, chunk_fn = _q7_parts()
    mesh = mesh8 if n_shards == N_DEV else make_mesh(n_shards)
    sf = ShardedFusedJoin(mesh, core, chunk_fn, exprs, CAP)
    solo = fused_source_join_epoch(chunk_fn, exprs, core, CAP,
                                   donate=False)
    st = core.init_state()
    start = 0
    saw_probe = saw_churn = False
    for epoch in range(2):
        key = jax.random.fold_in(jax.random.PRNGKey(11), epoch)
        sf.run_epoch(start, key, 8)
        got_probe, got_churn = sf.flush(out_capacity=CAP)
        st, want_probe, want_churn = _solo_q7_epoch_rows(
            solo, core, st, start, key, 8)
        start += 8 * CAP
        assert _rows(got_probe, core.out_schema) == \
            _rows(want_probe, core.out_schema)
        assert _rows(got_churn, core.out_schema) == \
            _rows(want_churn, core.out_schema)
        saw_probe |= bool(want_probe)
        saw_churn |= bool(want_churn)
    assert saw_churn          # the build side actually flushed
    # per-shard state equals the solo state bucket-for-bucket: every
    # solo-resident window must appear identically on exactly one shard
    host = jax.device_get(sf.stacked)
    solo_h = jax.device_get(st)
    nb = core.n_buckets
    solo_live = {
        int(w): b for b, w in enumerate(np.asarray(solo_h.win_id))
        if w >= 0 and solo_h.fill[b] > 0
    }
    found = 0
    for s in range(sf.n):
        win = np.asarray(host.win_id[s])
        for b in np.nonzero(win >= 0)[0]:
            w = int(win[b])
            if w not in solo_live or host.fill[s][b] == 0:
                continue
            sb = solo_live[w]
            assert int(host.fill[s][b]) == int(solo_h.fill[sb])
            assert int(host.cur_max[s][b]) == int(solo_h.cur_max[sb])
            W = int(host.fill[s][b])
            for c in range(len(host.row_data)):
                np.testing.assert_array_equal(
                    np.asarray(host.row_data[c][s][b][:W]),
                    np.asarray(solo_h.row_data[c][sb][:W]))
            found += 1
    assert found == len(solo_live) > 0


# ---------------------------------------------------------------------------
# dispatch-count regression: exactly 1 dispatch per sharded epoch,
# independent of shard count and k
# ---------------------------------------------------------------------------


def _nongather_total(counter):
    return sum(n for name, n in counter.counts.items()
               if "gather" not in name)


@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_sharded_agg_epoch_dispatch_count(n_shards):
    with count_dispatches() as c:
        exprs, core, chunk_fn = _q5_parts()
        sf = ShardedFusedAgg(make_mesh(n_shards), core, chunk_fn, exprs,
                             CAP, recv_width=n_shards)
        key = jax.random.PRNGKey(17)
        sf.run_epoch(0, key, 4)
        sf.flush()
        c.reset()
        sf.run_epoch(4 * CAP, key, 4)
        assert c.counts[Q5_EPOCH_FN] == 1
        sf.flush()
        n4 = _nongather_total(c)
        c.reset()
        sf.run_epoch(8 * CAP, key, 8)
        assert c.counts[Q5_EPOCH_FN] == 1
        sf.flush()
        n8 = _nongather_total(c)
        assert n4 == n8   # per-epoch dispatches independent of k


@pytest.mark.parametrize("n_shards", [4, 8])
def test_sharded_join_epoch_dispatch_count(n_shards):
    with count_dispatches() as c:
        exprs, core, chunk_fn = _q7_parts()
        sf = ShardedFusedJoin(make_mesh(n_shards), core, chunk_fn, exprs,
                              CAP, recv_width=n_shards)
        key = jax.random.PRNGKey(19)
        sf.run_epoch(0, key, 4)
        sf.flush(out_capacity=CAP)
        c.reset()
        sf.run_epoch(4 * CAP, key, 4)
        assert c.counts[Q7_EPOCH_FN] == 1
        sf.flush(out_capacity=CAP)
        n4 = _nongather_total(c)
        c.reset()
        sf.run_epoch(8 * CAP, key, 8)
        assert c.counts[Q7_EPOCH_FN] == 1
        sf.flush(out_capacity=CAP)
        n8 = _nongather_total(c)
        assert n4 == n8


# ---------------------------------------------------------------------------
# checkpoint export → kill → import, and mesh-resize re-shard
# ---------------------------------------------------------------------------


def test_sharded_agg_checkpoint_cycle_and_reshard(mesh8):
    """Checkpoint the 8-shard state through a real HashAggExecutor
    persistence engine into one shared state table, 'kill' it, then
    recover TWICE — onto 8 shards and onto a 4-shard mesh — by replaying
    the vnode mapping over the committed rows. Both continuations must
    match the solo path exactly."""
    from risingwave_tpu.storage.state_store import MemoryStateStore
    from risingwave_tpu.storage.state_table import StateTable
    from risingwave_tpu.stream import HashAggExecutor, ProjectExecutor
    from risingwave_tpu.stream.hash_agg import agg_state_schema
    from risingwave_tpu.stream.source import MockSource
    from risingwave_tpu.connector import BID_SCHEMA

    exprs, core, chunk_fn = _q5_parts()
    proj = ProjectExecutor(MockSource(BID_SCHEMA, []), exprs,
                           names=("ws", "auction", "price"))
    store = MemoryStateStore()
    st_table = StateTable(
        store, 7,
        agg_state_schema([proj.schema[0], proj.schema[1]],
                         core.agg_calls), [0, 1])
    engine = HashAggExecutor(proj, [0, 1], list(core.agg_calls),
                             state_table=None, table_capacity=1 << 12,
                             out_capacity=CAP)
    engine.state_table = st_table

    sf = ShardedFusedAgg(mesh8, core, chunk_fn, exprs, CAP)
    key = jax.random.PRNGKey(5)
    sf.run_epoch(0, key, 8)
    sf.flush()
    sf.checkpoint(engine, epoch=2)
    store.commit(2)
    committed = sf.merged_group_values()

    solo = fused_source_agg_epoch(chunk_fn, exprs, core, CAP,
                                  donate=False)
    st = solo(core.init_state(), jnp.int64(0), key, 8)
    key2 = jax.random.fold_in(jax.random.PRNGKey(5), 1)
    st = solo(st, jnp.int64(8 * CAP), key2, 8)
    want = _agg_groups(jax.device_get(st))

    for new_n in (8, 4):    # same-size recovery AND shrink re-shard
        rows = list(st_table.scan_all())
        states = load_shard_states(core, rows, new_n)
        sf2 = ShardedFusedAgg(make_mesh(new_n), core, chunk_fn, exprs,
                              CAP, states=states)
        assert sf2.merged_group_values() == committed
        sf2.run_epoch(8 * CAP, key2, 8)
        sf2.flush()
        assert sf2.merged_group_values() == want


def test_sharded_join_checkpoint_cycle_and_reshard(mesh8):
    """Per-shard IntervalJoinCore payloads round-trip through
    export_host → import_host bit-exactly, and re-bucket onto a 4-shard
    mesh (reshard_join_payloads replays the vnode mapping over each
    resident window) with identical downstream emissions."""
    exprs, core, chunk_fn = _q7_parts()
    sf = ShardedFusedJoin(mesh8, core, chunk_fn, exprs, CAP)
    key = jax.random.PRNGKey(13)
    sf.run_epoch(0, key, 8)
    sf.flush(out_capacity=CAP)
    payloads = sf.export_host()

    key2 = jax.random.fold_in(jax.random.PRNGKey(13), 1)

    def continue_and_rows(sj):
        sj.run_epoch(8 * CAP, key2, 8)
        probe, churn = sj.flush(out_capacity=CAP)
        return (_rows(probe, core.out_schema),
                _rows(churn, core.out_schema))

    # same-size import cycle
    sf2 = ShardedFusedJoin(mesh8, core, chunk_fn, exprs, CAP)
    sf2.import_host(payloads)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(sf.stacked)),
                    jax.tree_util.tree_leaves(jax.device_get(sf2.stacked))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    want = continue_and_rows(sf2)
    assert want[0] or want[1]

    # a different window config must refuse (win_ids copied verbatim
    # would relabel + misroute every resident window)
    other = IntervalJoinCore(core.probe_schema, ts_col=0, val_col=2,
                             window_us=2 * Q7_WINDOW, n_buckets=512,
                             lane_width=64)
    with pytest.raises(ValueError, match="window"):
        reshard_join_payloads(core, payloads, other, 4)

    # shrink to 4 shards: re-bucketed state, identical emissions
    new_core = IntervalJoinCore(core.probe_schema, ts_col=0, val_col=2,
                                window_us=Q7_WINDOW, n_buckets=512,
                                lane_width=64)
    re = reshard_join_payloads(core, payloads, new_core, 4)
    sf4 = ShardedFusedJoin(make_mesh(4), new_core, chunk_fn, exprs, CAP)
    sf4.import_host(re)
    assert continue_and_rows(sf4) == want


# ---------------------------------------------------------------------------
# Session integration: routing, parity with the co-scheduled path,
# durability, refusal in both directions
# ---------------------------------------------------------------------------

SRC_SQL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT,
    price BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
    extra VARCHAR) WITH (connector = 'nexmark', nexmark_table = 'bid')"""
MV_SQL = ("CREATE MATERIALIZED VIEW {n} AS SELECT auction, count(*) AS c "
          "FROM bid GROUP BY auction")


def _session(tmp_path=None, mesh_n=0, coschedule=True, **kw):
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig
    return Session(
        config=BuildConfig(coschedule=coschedule,
                           mesh=make_mesh(mesh_n) if mesh_n else None,
                           agg_table_capacity=1 << 12),
        source_chunk_capacity=CAP,
        data_dir=str(tmp_path) if tmp_path else None, **kw)


def test_session_routes_and_matches_cosched_path():
    """A mesh+coschedule session routes the eligible MV down the
    sharded-fused path; its MV contents are bit-identical to the
    co-scheduled (mesh-less) session's — same CREATE, same seed, same
    device-generated stream, different placement only."""
    s = _session(mesh_n=8)
    try:
        s.run_sql(SRC_SQL)
        s.run_sql(MV_SQL.format(n="m0"))
        assert s.metrics()["shardfused"]["m0"]["shards"] == 8
        assert not s.metrics()["coschedule"]["jobs"]
        # ineligible shape falls back to the mesh EXECUTOR path
        s.run_sql("CREATE MATERIALIZED VIEW raw AS SELECT auction, price "
                  "FROM bid")
        assert "raw" not in s.metrics()["shardfused"]
        for _ in range(3):
            s.tick()
        got = sorted(s.run_sql("SELECT auction, c FROM m0"))
    finally:
        s.close()
    c = _session(mesh_n=0)
    try:
        c.run_sql(SRC_SQL)
        c.run_sql(MV_SQL.format(n="m0"))
        assert c.metrics()["coschedule"]["jobs"] == 1
        for _ in range(3):
            c.tick()
        want = sorted(c.run_sql("SELECT auction, c FROM m0"))
    finally:
        c.close()
    assert got == want and len(got) > 10


def test_session_shardfused_recovery_and_mesh_resize(tmp_path):
    s = _session(tmp_path, mesh_n=8, checkpoint_frequency=2)
    s.run_sql(SRC_SQL)
    s.run_sql(MV_SQL.format(n="m0"))
    for _ in range(5):
        s.tick()
    committed = dict(s.run_sql("SELECT auction, c FROM m0"))
    s.close()

    # reopen on a SMALLER mesh: committed rows re-shard by vnode replay
    s2 = _session(tmp_path, mesh_n=4, checkpoint_frequency=2)
    try:
        assert s2.metrics()["shardfused"]["m0"]["shards"] == 4
        assert dict(s2.run_sql("SELECT auction, c FROM m0")) == committed
        base = sum(committed.values())
        for _ in range(3):
            s2.tick()
        # deterministic cursor resume: exactly 3 * CAP more rows
        assert s2.run_sql("SELECT sum(c) FROM m0") == [(base + 3 * CAP,)]
    finally:
        s2.close()


def test_session_shardfused_refusal_both_directions(tmp_path):
    from risingwave_tpu.frontend.session import SqlError
    s = _session(tmp_path, mesh_n=4, checkpoint_frequency=2)
    s.run_sql(SRC_SQL)
    s.run_sql(MV_SQL.format(n="m0"))
    s.tick()
    s.close()
    # sharded-fused MV reopened WITHOUT a mesh: refuse loudly
    with pytest.raises(SqlError, match="mesh-sharded fused"):
        _session(tmp_path, mesh_n=0, coschedule=False)

    # reverse direction: a co-scheduled (mesh-less) MV reopened WITH a
    # mesh must not be captured by the sharded-fused path — its durable
    # layout decodes on the coschedule path only, which refuses since
    # the mesh session cannot host it
    d2 = tmp_path / "cosched"
    c = _session(d2, mesh_n=0, checkpoint_frequency=2)
    c.run_sql(SRC_SQL)
    c.run_sql(MV_SQL.format(n="m1"))
    c.tick()
    c.close()
    with pytest.raises(SqlError, match="co-scheduled"):
        _session(d2, mesh_n=4)


def test_session_drop_cleans_shardfused(tmp_path):
    s = _session(tmp_path, mesh_n=4)
    try:
        s.run_sql(SRC_SQL)
        s.run_sql(MV_SQL.format(n="m0"))
        s.tick()
        s.run_sql("DROP MATERIALIZED VIEW m0")
        assert not s.metrics()["shardfused"]
        s.tick()
        # a re-CREATE after the drop is a NEW sharded-fused job
        s.run_sql(MV_SQL.format(n="m0"))
        s.tick()
        assert s.metrics()["shardfused"]["m0"]["epochs_run"] >= 1
    finally:
        s.close()


# ---------------------------------------------------------------------------
# mesh-topology recovery gap: 8-device-saved → 4-device-reopened
# ---------------------------------------------------------------------------


def _run_in_n_device_proc(n_devices: int, script: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices}")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("TPU_LIBRARY_PATH", None)
    res = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr[-2000:]
    return json.loads(res.stdout.strip().splitlines()[-1])


def test_mesh_recovery_gap_refuses_loudly(tmp_path):
    """An 8-device-saved reschedule config reopened in a 4-device
    process must refuse loudly (MeshUnavailableError), not silently
    recover unsharded; allow_reshard=True is the explicit escape."""
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig, config_to_json

    d = str(tmp_path / "db")
    s = Session(data_dir=d)
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.run_sql("CREATE MATERIALIZED VIEW g AS "
              "SELECT k % 4 AS grp, sum(v) AS sv FROM t GROUP BY k % 4")
    for i in range(8):
        s.run_sql(f"INSERT INTO t VALUES ({i}, {i * 10})")
    s.flush()
    s.reschedule("g", BuildConfig(mesh=make_mesh(8)))
    s.close()

    cfg_json = config_to_json(BuildConfig(mesh=make_mesh(8)))
    script = f"""
import json
out = {{}}
from risingwave_tpu.common.config import MeshUnavailableError
from risingwave_tpu.frontend.build import config_from_json
try:
    config_from_json({cfg_json!r})
    out["raised"] = False
except MeshUnavailableError as e:
    out["raised"] = True
    out["msg"] = str(e)
cfg = config_from_json({cfg_json!r}, allow_reshard=True)
out["reshard_devices"] = int(cfg.mesh.devices.size)
from risingwave_tpu.frontend import Session
try:
    Session(data_dir={d!r})
    out["session_raised"] = False
except RuntimeError as e:
    out["session_raised"] = True
    out["session_msg"] = str(e)
# the operator's explicit escape: consented shrink onto 4 devices
import os
os.environ["RWTPU_ALLOW_MESH_RESHARD"] = "1"
s = Session(data_dir={d!r})
out["reshard_rows"] = sorted(s.mv_rows("g"))
s.close()
print(json.dumps(out))
"""
    out = _run_in_n_device_proc(4, script)
    assert out["raised"] and "8 devices" in out["msg"]
    assert out["reshard_devices"] == 4          # explicit re-shard path
    assert out["session_raised"]                # loud, not a warning
    assert "reschedule g" in out["session_msg"]
    assert "RWTPU_ALLOW_MESH_RESHARD" in out["session_msg"]
    # the env escape actually reopens the job, re-sharded, rows intact
    want = sorted([i, sum(j * 10 for j in range(8) if j % 4 == i)]
                  for i in range(4))
    assert [list(r) for r in out["reshard_rows"]] == want


# ---------------------------------------------------------------------------
# opt-in without code: [streaming] mesh_shape and --mesh
# ---------------------------------------------------------------------------


def test_cli_mesh_flag_builds_mesh_config():
    from risingwave_tpu.cli import _build_session
    args = argparse.Namespace(data_dir=None, fragment_parallelism=1,
                              mesh=2)
    s = _build_session(args)
    try:
        assert s.config.mesh is not None
        assert s.config.mesh.devices.size == 2
    finally:
        s.close()


def test_cli_mesh_flag_parses():
    import risingwave_tpu.cli as cli
    from unittest import mock
    captured = {}

    def fake_playground(args):
        captured["mesh"] = args.mesh
        return 0

    with mock.patch.object(cli, "_playground", fake_playground):
        assert cli.main(["playground", "--mesh", "4"]) == 0
    assert captured["mesh"] == 4


def test_rw_config_mesh_shape_flows_to_build_config():
    from risingwave_tpu.common.config import load_config
    from risingwave_tpu.frontend.session import Session
    cfg = load_config(**{"streaming.mesh_shape": 2,
                         "streaming.coschedule": True})
    s = Session(rw_config=cfg)
    try:
        assert s.config.mesh is not None
        assert s.config.mesh.devices.size == 2
        assert s.config.coschedule
    finally:
        s.close()
    # mesh_shape = 1 builds a 1-device mesh, agreeing with `--mesh 1`
    # (a durable job created either way recovers under the other)
    s1 = Session(rw_config=load_config(**{"streaming.mesh_shape": 1}))
    try:
        assert s1.config.mesh is not None
        assert s1.config.mesh.devices.size == 1
    finally:
        s1.close()


def test_make_mesh_refuses_when_short_of_devices():
    with pytest.raises(MeshUnavailableError, match="devices"):
        make_mesh(len(jax.devices()) + 1)
