"""Arrow/numpy/DLPack interchange + memory accounting (coverage #2/#9)."""

import numpy as np
import pytest

from risingwave_tpu.common.chunk import make_chunk
from risingwave_tpu.common.interchange import (
    arrow_to_chunk, chunk_to_arrow, chunk_to_numpy, column_to_torch,
    torch_to_column,
)
from risingwave_tpu.common.memory import pipeline_state_bytes
from risingwave_tpu.common.types import (
    DATE, FLOAT64, INT64, VARCHAR, Field, Schema, decimal,
)
from risingwave_tpu.common.chunk import chunk_to_rows
from risingwave_tpu.frontend import Session

SCHEMA = Schema((
    Field("k", INT64), Field("x", FLOAT64), Field("s", VARCHAR),
    Field("d", DATE), Field("m", decimal(2)),
))
ROWS = [
    (1, 1.5, "alpha", 9204, 12.34),
    (2, None, None, None, None),
    (3, -2.25, "beta", 0, -0.05),
]


class TestArrow:
    def test_roundtrip(self):
        chunk = make_chunk(SCHEMA, ROWS, capacity=8)
        batch = chunk_to_arrow(chunk, SCHEMA)
        assert batch.num_rows == 3
        assert batch.column("s").to_pylist() == ["alpha", None, "beta"]
        assert [str(v) if v is not None else None
                for v in batch.column("m").to_pylist()] == \
            ["12.34", None, "-0.05"]
        back = arrow_to_chunk(batch, SCHEMA, capacity=8)
        got = chunk_to_rows(back, SCHEMA)
        assert got == ROWS

    def test_ops_column(self):
        from risingwave_tpu.common.chunk import OP_DELETE, OP_INSERT
        chunk = make_chunk(SCHEMA, ROWS[:2], ops=[OP_INSERT, OP_DELETE],
                           capacity=4)
        batch = chunk_to_arrow(chunk, SCHEMA, with_ops=True)
        assert batch.column("__op").to_pylist() == [0, 1]


class TestNumpyTorch:
    def test_numpy_view(self):
        chunk = make_chunk(SCHEMA, ROWS, capacity=4)
        view = chunk_to_numpy(chunk)
        assert view["vis"].sum() == 3
        data, mask = view["columns"][0]
        assert data[:3].tolist() == [1, 2, 3]

    def test_torch_roundtrip(self):
        chunk = make_chunk(SCHEMA, ROWS, capacity=4)
        t, m = column_to_torch(chunk.columns[0])
        assert t.shape == (4,) and t[0].item() == 1
        col = torch_to_column(t * 2, m)
        assert np.asarray(col.data)[:3].tolist() == [2, 4, 6]


class TestMemoryAccounting:
    def test_state_bytes_in_metrics(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k % 4 AS g, sum(v) AS sv FROM t GROUP BY k % 4")
        s.run_sql("INSERT INTO t VALUES (1, 10)")
        s.flush()
        mem = s.metrics()["state_bytes"]["m"]
        # the grouped-agg device state dominates; must be nonzero and
        # aggregated into _total
        assert mem["_total"] > 0
        assert any(k.startswith("HashAgg") or k.startswith("GroupedAgg")
                   or v > 0 for k, v in mem.items())
