"""The meta tier as the live control plane (VERDICT r3 item 3).

Covers the three integration points: catalog mutations write through to
the MetaStore + versioned notifications, barrier conduction publishes,
and — the headline — the heartbeat detector notices a killed job's actor
task and scoped recovery restores it without restarting the session
(reference: manager/cluster.rs:320-344 heartbeat expiry →
barrier/recovery.rs:110 orchestrated recovery).
"""

import pytest

from risingwave_tpu.frontend import Session

NEXMARK_DDL = """CREATE SOURCE bid (auction BIGINT, price BIGINT)
    WITH (connector = 'nexmark', nexmark_table = 'bid')"""


class TestCatalogWriteThrough:
    def test_ddl_lands_in_meta_store_and_notifies(self):
        s = Session()
        seen = []
        s.meta.notifications.subscribe(
            "catalog", lambda v, info: seen.append((v, info)))
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, v FROM t")
        assert s.meta.store.get("catalog/table/t") is not None
        assert s.meta.store.get("catalog/materialized_view/m") is not None
        ops = [(i["op"], i["kind"], i["name"]) for _, i in seen]
        assert ("create", "table", "t") in ops
        assert ("create", "materialized_view", "m") in ops
        # versions are ordered + monotone
        assert [v for v, _ in seen] == sorted(v for v, _ in seen)
        s.run_sql("DROP MATERIALIZED VIEW m")
        assert s.meta.store.get("catalog/materialized_view/m") is None
        assert ("drop", "materialized_view", "m") in [
            (i["op"], i["kind"], i["name"]) for _, i in seen]

    def test_barrier_conduction_publishes(self):
        s = Session(checkpoint_frequency=2)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
        barriers, ckpts = [], []
        s.meta.notifications.subscribe(
            "barrier", lambda v, i: barriers.append(i))
        s.meta.notifications.subscribe(
            "checkpoint", lambda v, i: ckpts.append(i))
        for _ in range(4):
            s.tick()
        s._drain_inflight()
        assert len(barriers) >= 4
        epochs = [b["epoch"] for b in barriers]
        assert epochs == sorted(epochs)
        # the last checkpoint may trail the newest (non-checkpoint) epoch
        assert ckpts and s.epoch - 2 <= ckpts[-1]["committed_epoch"] <= s.epoch


class TestHeartbeatRecovery:
    def test_killed_job_detected_and_recovered(self):
        """Kill an MV job's actor task mid-stream; the heartbeat detector
        declares it DOWN after the TTL; scoped recovery rebuilds it from
        the last checkpoint and re-seeks its source — the session itself
        never restarts and the MV converges to the correct totals."""
        s = Session(checkpoint_frequency=2, source_chunk_capacity=64)
        s.run_sql(NEXMARK_DDL)
        s.run_sql("""CREATE MATERIALIZED VIEW m AS
            SELECT auction, count(*) AS n FROM bid GROUP BY auction""")
        for _ in range(4):
            s.tick()
        s._drain_inflight()

        # the worker registry tracks the job and it is heartbeating
        workers = {w.host: w for w in s.meta.cluster.workers.values()}
        assert workers["m"].state == "RUNNING"

        s.kill_job("m")
        recovered = []
        s.meta.notifications.subscribe(
            "recovery", lambda v, i: recovered.append(i))
        # TTL epochs must elapse with no heartbeat before expiry fires;
        # ticks keep flowing — the session never stalls on the dead job
        for _ in range(s.meta.HEARTBEAT_TTL_EPOCHS + 2):
            s.tick()
        s._drain_inflight()
        assert recovered and recovered[0]["jobs"] == ["m"]
        workers = {w.host: w for w in s.meta.cluster.workers.values()}
        assert workers["m"].state == "RUNNING"

        # the recovered MV keeps maintaining. Oracle: the MV must equal a
        # fresh session whose deterministic source generated the same
        # number of windows the recovered reader actually reached —
        # replay-from-offset means the MV content is exactly the
        # aggregation of windows [0, final_offset), with the death
        # window's lost rows regenerated, none skipped, none doubled.
        for _ in range(3):
            s.tick()
        s.flush()
        got = sorted(s.mv_rows("m"))
        feed = next(f for f in s.feeds if f.job == "m")
        n_windows = sum(feed.reader.offsets.values())
        assert n_windows > 0

        ref = Session(checkpoint_frequency=2, source_chunk_capacity=64)
        ref.run_sql(NEXMARK_DDL)
        ref.run_sql("""CREATE MATERIALIZED VIEW m AS
            SELECT auction, count(*) AS n FROM bid GROUP BY auction""")
        while sum(next(f for f in ref.feeds if f.job == "m")
                  .reader.offsets.values()) < n_windows:
            ref.tick()
        ref.flush()
        want = sorted(ref.mv_rows("m"))
        assert got == want

    def test_killed_job_with_downstream_mv_recovers_subtree(self):
        """A dead job starves its downstream MVs of barriers: collect must
        skip them (not deadlock), the detector expires the whole subtree,
        and scoped recovery rebuilds it together — found by driving the
        public API end to end (r4)."""
        s = Session(checkpoint_frequency=2)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW up AS "
                  "SELECT g, count(*) AS n FROM t GROUP BY g")
        s.run_sql("CREATE MATERIALIZED VIEW down AS SELECT g, n FROM up")
        s.run_sql("INSERT INTO t VALUES (1, 0), (2, 1), (3, 0)")
        s.flush()
        assert sorted(s.mv_rows("down")) == [(0, 2), (1, 1)]
        s.kill_job("up")
        recovered = []
        s.meta.notifications.subscribe(
            "recovery", lambda v, i: recovered.append(i))
        for _ in range(s.meta.HEARTBEAT_TTL_EPOCHS + 2):
            s.tick()          # must not deadlock on the starved 'down'
        s.flush()
        assert recovered and recovered[0]["jobs"] == ["up", "down"]
        assert {w.host: w.state for w in s.meta.cluster.workers.values()} \
            == {"t": "RUNNING", "up": "RUNNING", "down": "RUNNING"}
        s.run_sql("INSERT INTO t VALUES (4, 1)")
        s.flush()
        assert sorted(s.mv_rows("up")) == [(0, 2), (1, 2)]
        assert sorted(s.mv_rows("down")) == [(0, 2), (1, 2)]

    def test_other_jobs_unaffected_during_death_window(self):
        s = Session(checkpoint_frequency=2, source_chunk_capacity=64)
        s.run_sql(NEXMARK_DDL)
        s.run_sql("CREATE MATERIALIZED VIEW victim AS "
                  "SELECT auction, count(*) AS n FROM bid GROUP BY auction")
        s.run_sql("CREATE MATERIALIZED VIEW healthy AS "
                  "SELECT auction, max(price) AS p FROM bid GROUP BY auction")
        for _ in range(2):
            s.tick()
        s._drain_inflight()
        healthy_before = len(s.mv_rows("healthy"))
        s.kill_job("victim")
        for _ in range(s.meta.HEARTBEAT_TTL_EPOCHS + 2):
            s.tick()
        s.flush()
        # healthy job kept processing throughout the victim's death window
        assert len(s.mv_rows("healthy")) >= healthy_before
        assert {w.host: w.state for w in s.meta.cluster.workers.values()} \
            == {"victim": "RUNNING", "healthy": "RUNNING"}
        # both read the same deterministic stream; the victim is a prefix
        # (its reader froze during the death window), so its auction set
        # is contained in the healthy job's
        assert set(r[0] for r in s.mv_rows("victim")) <= \
            set(r[0] for r in s.mv_rows("healthy"))
        assert len(s.mv_rows("victim")) > 0
