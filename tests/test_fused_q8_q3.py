"""The two new fused surfaces: q8 session windows
(ops/session_window.py) and the TPC-H q3 streaming MV
(ops/stream_q3.py). Each is pinned three ways: semantics against a
plain-Python host model over the SAME generated events, fused epoch
bit-exact against the unfused per-chunk fold (the executor-style
driving of the same cores), and exactly ONE jit dispatch per epoch with
per-epoch dispatch totals independent of k — including across a
checkpoint export/import cycle."""

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common import INT64, TIMESTAMP
from risingwave_tpu.common.chunk import OP_DELETE, OP_INSERT
from risingwave_tpu.common.dispatch_count import count_dispatches
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.connector import NexmarkConfig
from risingwave_tpu.connector.nexmark import DeviceBidGenerator
from risingwave_tpu.connector.tpch import (
    DeviceQ3Generator, Q3_CUTOFF_DAYS, TpchQ3Config,
)
from risingwave_tpu.expr import col
from risingwave_tpu.ops.fused_epoch import (
    fused_source_q3_epoch, fused_source_session_epoch,
)
from risingwave_tpu.ops.session_window import SessionWindowCore
from risingwave_tpu.ops.stream_q3 import Q3Core

CAP = 256
GAP = 5_000
Q8_EPOCH_FN = "fused_source_session_epoch.<locals>.epoch"
Q3_EPOCH_FN = "fused_source_q3_epoch.<locals>.epoch"


def _q8_parts(capacity=1 << 12, closed=1 << 13):
    exprs = [col(1, INT64), col(5, TIMESTAMP)]     # bidder, date_time
    schema = Schema((Field("bidder", INT64), Field("ts", TIMESTAMP)))
    core = SessionWindowCore(schema, key_col=0, ts_col=1, gap_us=GAP,
                             capacity=capacity, closed_capacity=closed)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    return exprs, core, gen


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# q8: session-gap windows
# ---------------------------------------------------------------------------


def test_session_core_matches_host_model():
    """Closed sessions (incl. the watermark close) == a plain python
    per-key sessionization of the same generated events."""
    exprs, core, gen = _q8_parts()
    fused = fused_source_session_epoch(gen.chunk_fn(), exprs, core, CAP)
    key = jax.random.PRNGKey(7)
    k = 8
    end_ts = 1_600_000_000_000_000 + k * CAP * 100
    st, snap, packed = fused(core.init_state(), jnp.int64(0), key, k,
                             jnp.int64(end_ts))
    n = int(packed[0])
    assert not any(int(x) for x in packed[1:])

    fn = gen.chunk_fn()
    events: dict = {}
    for i in range(k):
        ch = fn(jnp.int64(i * CAP), jax.random.fold_in(key, i))
        for b, t in zip(np.asarray(ch.columns[1].data),
                        np.asarray(ch.columns[5].data)):
            events.setdefault(int(b), []).append(int(t))
    expected = set()
    for kk, ts in events.items():
        ts.sort()
        start, last, cnt = ts[0], ts[0], 1
        for t in ts[1:]:
            if t - last > GAP:
                expected.add((kk, start, last, cnt))
                start, last, cnt = t, t, 1
            else:
                last, cnt = t, cnt + 1
        if last + GAP <= end_ts:            # watermark-closed
            expected.add((kk, start, last, cnt))
    got = set()
    ck, cs, ce, cn = (np.asarray(a) for a in snap)
    for j in range(n):
        got.add((int(ck[j]), int(cs[j]), int(ce[j]), int(cn[j])))
    assert got == expected and len(expected) > 0


def test_session_fused_matches_per_chunk_fold():
    exprs, core, gen = _q8_parts()
    fused = fused_source_session_epoch(gen.chunk_fn(), exprs, core, CAP)
    key = jax.random.PRNGKey(11)
    k = 6
    wm = jnp.int64(1_600_000_000_000_000 + k * CAP * 100 - GAP)
    st, snap, packed = fused(core.init_state(), jnp.int64(0), key, k, wm)

    fn = gen.chunk_fn()
    s2 = core.init_state()
    ap = jax.jit(core.apply_chunk)
    for i in range(k):
        ch = fn(jnp.int64(i * CAP), jax.random.fold_in(key, i))
        ch = ch.with_columns(tuple(e.eval(ch) for e in exprs))
        s2 = ap(s2, ch)
    s2, packed2 = jax.jit(core.flush_plan)(s2, wm)
    snap2 = core.snapshot_closed(s2)
    s2 = jax.jit(core.finish_flush)(s2)

    np.testing.assert_array_equal(np.asarray(packed), np.asarray(packed2))
    _assert_tree_equal(snap, snap2)
    _assert_tree_equal(st, s2)

    # gather_closed packs the emission windows as INSERT chunks
    n = int(packed[0])
    out = jax.jit(core.gather_closed,
                  static_argnames=("out_capacity",))(
        snap, jnp.int64(n), jnp.int64(0), out_capacity=512)
    assert int(np.asarray(out.vis).sum()) == min(n, 512)
    assert (np.asarray(out.ops)[np.asarray(out.vis)] == OP_INSERT).all()


def test_session_epoch_one_dispatch_k_independent():
    with count_dispatches() as c:
        exprs, core, gen = _q8_parts()
        fused = fused_source_session_epoch(gen.chunk_fn(), exprs, core,
                                           CAP)

        def epoch(state, start, bno, k):
            key = jax.random.fold_in(jax.random.PRNGKey(3), bno)
            wm = jnp.int64(0)       # nothing watermark-closes; pure count
            state, snap, packed = fused(state, jnp.int64(start), key, k,
                                        wm)
            assert not any(int(x) for x in jax.device_get(packed)[1:])
            return state

        state = epoch(core.init_state(), 0, 0, 4)       # compile
        c.reset()
        state = epoch(state, 4 * CAP, 1, 4)
        assert c.counts[Q8_EPOCH_FN] == 1
        n4 = c.total
        c.reset()
        state = epoch(state, 8 * CAP, 2, 8)
        assert c.counts[Q8_EPOCH_FN] == 1
        assert c.total == n4          # per-epoch dispatches independent of k


def test_session_out_of_order_sets_sticky_flag():
    """A per-key time rewind across chunks (anything but the monotone
    NEXmark clock) trips the sticky out_of_order flag instead of
    silently rewinding sessions."""
    from risingwave_tpu.common.chunk import make_chunk
    _, core, _ = _q8_parts()
    in_schema = Schema((Field("bidder", INT64), Field("ts", TIMESTAMP)))
    ap = jax.jit(core.apply_chunk)
    t0 = 1_000_000
    st = ap(core.init_state(), make_chunk(in_schema, [(7, t0)]))
    assert not bool(st.out_of_order)
    # same key, EARLIER timestamp in a later chunk
    st = ap(st, make_chunk(in_schema, [(7, t0 - 1)]))
    assert bool(st.out_of_order)
    _, packed = jax.jit(core.flush_plan)(st, jnp.int64(0))
    assert int(packed[4]) == 1          # surfaced in the packed fetch


def test_session_checkpoint_roundtrip_bit_exact():
    """export_host → import_host mid-stream, then continue both — the
    recovered path stays bit-exact (checkpoint/recovery cycle)."""
    exprs, core, gen = _q8_parts()
    fused = fused_source_session_epoch(gen.chunk_fn(), exprs, core, CAP)
    key = jax.random.PRNGKey(5)
    k = 4
    wm = jnp.int64(1_600_000_000_000_000 + 4 * CAP * 100 - GAP)
    st, _, _ = fused(core.init_state(), jnp.int64(0), key, k, wm)

    restored = core.import_host(core.export_host(st))
    _assert_tree_equal(st, restored)
    wm2 = jnp.int64(1_600_000_000_000_000 + 8 * CAP * 100 - GAP)
    a = fused(st, jnp.int64(4 * CAP), key, k, wm2)
    b = fused(restored, jnp.int64(4 * CAP), key, k, wm2)
    for x, y in zip(a, b):
        _assert_tree_equal(x, y)


# ---------------------------------------------------------------------------
# TPC-H q3: join + agg + top-n
# ---------------------------------------------------------------------------


def _q3_parts(orders=1 << 11, agg=1 << 11):
    gen = DeviceQ3Generator(TpchQ3Config(chunk_capacity=CAP))
    core = Q3Core(Q3_CUTOFF_DAYS, orders_capacity=orders,
                  agg_capacity=agg)
    return gen, core


def test_q3_core_matches_host_model():
    """Emitted top-10 == a plain python join+filter+agg+sort over the
    same generated order/lineitem events (ties broken by orderkey)."""
    gen, core = _q3_parts()
    fused = fused_source_q3_epoch(gen.chunk_fn(), core, CAP)
    key = jax.random.PRNGKey(0)
    k = 8
    st, out, packed = fused(core.init_state(), jnp.int64(0), key, k)
    assert not any(int(x) for x in jax.device_get(packed)[1:])

    fn = gen.chunk_fn()
    rows = []
    for i in range(k):
        ch = fn(jnp.int64(i * CAP), key)
        rows.extend(zip(*[np.asarray(c.data) for c in ch.columns]))
    orders = {}
    for r in rows:
        if r[0] == 0 and r[2] < Q3_CUTOFF_DAYS and r[4] == 0:
            orders[r[1]] = (int(r[2]), int(r[3]))
    rev: dict = {}
    for r in rows:
        if r[0] == 1 and r[7] > Q3_CUTOFF_DAYS and r[1] in orders:
            rev[r[1]] = rev.get(r[1], 0) + int(
                r[5] * (10000 - r[6]) // 10000)
    top = sorted(rev.items(), key=lambda kv: (-kv[1], kv[0]))[:10]
    expected = [(int(kk), vv) + orders[kk] for kk, vv in top]
    host = jax.device_get(st)
    got = [(int(a), int(b), int(c), int(d)) for a, b, c, d, v in zip(
        host.emitted_key, host.emitted_rev, host.emitted_odate,
        host.emitted_prio, host.emitted_valid) if v]
    assert got == expected and len(got) == 10


def test_q3_fused_matches_per_chunk_fold_and_emits_retractions():
    gen, core = _q3_parts()
    fused = fused_source_q3_epoch(gen.chunk_fn(), core, CAP)
    key = jax.random.PRNGKey(0)
    k = 6
    st, out, packed = fused(core.init_state(), jnp.int64(0), key, k)

    fn = gen.chunk_fn()
    s2 = core.init_state()
    ap = jax.jit(core.apply_chunk)
    for i in range(k):
        s2 = ap(s2, fn(jnp.int64(i * CAP), jax.random.fold_in(key, i)))
    s2, out2, packed2 = jax.jit(core.flush)(s2)
    np.testing.assert_array_equal(np.asarray(packed), np.asarray(packed2))
    _assert_tree_equal(out, out2)
    _assert_tree_equal(st, s2)

    # epoch 1 emits only inserts (nothing previously emitted)...
    ops1 = np.asarray(out.ops)[np.asarray(out.vis)]
    assert (ops1 == OP_INSERT).all() and len(ops1) == 10
    # ...epoch 2's churn retracts departed/changed rows: the top-n output
    # carries retractions even though both inputs are append-only
    st, out, packed = fused(st, jnp.int64(k * CAP), key, k)
    ops2 = np.asarray(out.ops)[np.asarray(out.vis)]
    assert (ops2 == OP_DELETE).any() and (ops2 == OP_INSERT).any()


def test_q3_epoch_one_dispatch_k_independent():
    with count_dispatches() as c:
        gen, core = _q3_parts()
        fused = fused_source_q3_epoch(gen.chunk_fn(), core, CAP)

        def epoch(state, start, bno, k):
            key = jax.random.fold_in(jax.random.PRNGKey(9), bno)
            state, out, packed = fused(state, jnp.int64(start), key, k)
            assert not any(int(x) for x in jax.device_get(packed)[1:])
            return state

        state = epoch(core.init_state(), 0, 0, 4)       # compile
        c.reset()
        state = epoch(state, 4 * CAP, 1, 4)
        assert c.counts[Q3_EPOCH_FN] == 1
        n4 = c.total
        c.reset()
        state = epoch(state, 8 * CAP, 2, 8)
        assert c.counts[Q3_EPOCH_FN] == 1
        assert c.total == n4


def test_q3_checkpoint_roundtrip_bit_exact():
    gen, core = _q3_parts()
    fused = fused_source_q3_epoch(gen.chunk_fn(), core, CAP)
    key = jax.random.PRNGKey(2)
    st, _, _ = fused(core.init_state(), jnp.int64(0), key, 4)

    restored = core.import_host(core.export_host(st))
    _assert_tree_equal(st, restored)
    a = fused(st, jnp.int64(4 * CAP), key, 4)
    b = fused(restored, jnp.int64(4 * CAP), key, 4)
    for x, y in zip(a, b):
        _assert_tree_equal(x, y)


def test_q3_orders_filter_is_join_filter():
    """A lineitem whose order was filtered out (wrong segment / late
    order date) contributes nothing — the at-insert filter IS the join
    filter."""
    gen, core = _q3_parts()
    fn = gen.chunk_fn()
    st = core.init_state()
    ap = jax.jit(core.apply_chunk)
    for i in range(4):
        ch = fn(jnp.int64(i * CAP), None)
        st = ap(st, ch)
    host = jax.device_get(st)
    stored = set(np.asarray(host.orders.key_data[0])[
        np.asarray(host.orders.occupied)].tolist())
    live = np.asarray(host.agg.lanes[0]) > 0
    grouped = set(np.asarray(host.agg.table.key_data[0])[live].tolist())
    assert grouped <= stored            # every revenue group has its order
    # and the filter actually filtered: far fewer stored than seen orders
    n_orders_seen = 4 * CAP // 4        # one order per 4 events
    assert 0 < len(stored) < n_orders_seen // 2
