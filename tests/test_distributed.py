"""Distributed dataflow plane: ONE streaming job's fragment graph
spanning multiple worker processes, with exchange edges crossing the
wire protocol (VERDICT r5 tentpole).

What these tests pin:
  * NEXmark q5 / q7 MVs deploy as fragment graphs over 2 workers
    (vnode-mapped placement, sharded agg fragments, remote merge) and
    stay BIT-EXACT against the single-process pipeline at every epoch
    boundary — including the retraction churn grouped aggs emit (U-/U+
    pairs crossing hash exchanges under the update-pair split rule);
  * kill -9 of one participating worker (root or not) trips PEER_LOST /
    heartbeat-TTL scoped recovery: only the affected fragment graph is
    rebuilt from its per-worker durable state, sources replay the gap,
    and the result matches an uninterrupted control run (exactly-once
    across the remote edges, two-phase checkpoint end-to-end);
  * placement persists in the meta store and a restarted session
    re-places the SAME fragments onto the SAME workers;
  * per-exchange-edge counters surface in metrics()/Prometheus.

The parity harness pins the schedule the way test_interval_join.py does:
both sides run the same generate cadence and are compared at quiesced
epoch boundaries (mv_rows drains in-flight barriers), where streaming
state is schedule-independent.

Reference: exchange_service.rs:74-133, exchange/permit.rs:35-107,
stream_graph placement + scale.rs vnode mappings, recovery.rs:110.
"""

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.build import BuildConfig

CAP = 64

BID_DDL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,
channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'bid')"""

Q5 = """CREATE MATERIALIZED VIEW q5 AS
    SELECT AuctionBids.auction, AuctionBids.num FROM (
        SELECT bid.auction, count(*) AS num, window_start AS starttime
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY window_start, bid.auction
    ) AS AuctionBids
    JOIN (
        SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
        FROM (
            SELECT count(*) AS num, window_start AS starttime_c
            FROM HOP(bid, date_time, INTERVAL '2' SECOND,
                     INTERVAL '10' SECOND)
            GROUP BY bid.auction, window_start
        ) AS CountBids
        GROUP BY CountBids.starttime_c
    ) AS MaxBids
    ON AuctionBids.starttime = MaxBids.starttime_c
       AND AuctionBids.num = MaxBids.maxn"""

Q7 = """CREATE MATERIALIZED VIEW q7 AS
    SELECT B.auction, B.price, B.bidder, B.date_time
    FROM bid B
    JOIN (
        SELECT MAX(price) AS maxprice, window_end as date_time
        FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)
        GROUP BY window_end
    ) B1 ON B.price = B1.maxprice
    WHERE B.date_time BETWEEN B1.date_time - INTERVAL '10' SECOND
          AND B1.date_time"""

AGG = ("CREATE MATERIALIZED VIEW q AS SELECT auction, count(*) AS n, "
       "max(price) AS mx FROM bid GROUP BY auction")


def spanning_session(seed=42, data_dir=None, parallelism=2) -> Session:
    return Session(workers=2, seed=seed, data_dir=data_dir,
                   source_chunk_capacity=CAP,
                   config=BuildConfig(fragment_parallelism=parallelism))


def local_run(mv_sql: str, name: str, ticks: int, seed=42) -> list:
    s = Session(seed=seed, source_chunk_capacity=CAP)
    s.run_sql(BID_DDL)
    s.run_sql(mv_sql)
    rows = []
    for _ in range(ticks):
        s.tick()
    s.flush()
    rows = sorted(s.mv_rows(name))
    s.close()
    return rows


class TestSpanningParity:
    @pytest.mark.slow  # heavy 2-worker graph; check.sh runs this file unfiltered
    def test_q5_spans_two_workers_bit_exact_per_epoch(self):
        """q5 (join of two sharded hop-window aggs) as a 6-fragment graph
        over 2 workers: every hash fragment's actors own disjoint vnode
        ranges on DIFFERENT workers, and the MV is bit-exact vs the
        single-process pipeline at EVERY epoch boundary."""
        s = spanning_session()
        s.run_sql(BID_DDL)
        s.run_sql(Q5)
        assert "q5" in s._spanning_specs, "q5 did not deploy as a span"
        placement = s._spanning_specs["q5"]["placement"]
        assert len(placement.workers()) == 2
        # at least one fragment is sharded: actors on distinct workers
        # with complementary vnode ranges
        sharded = [acts for acts in placement.actors.values()
                   if len(acts) == 2]
        assert sharded, "no fragment was vnode-sharded across workers"
        for acts in sharded:
            assert {a.worker for a in acts} == set(placement.workers())
            assert acts[0].vnode_end == acts[1].vnode_start
            assert (acts[0].vnode_start, acts[1].vnode_end) == (0, 256)

        control = Session(seed=42, source_chunk_capacity=CAP)
        control.run_sql(BID_DDL)
        control.run_sql(Q5)
        try:
            for _ in range(3):
                s.tick()
                control.tick()
                assert sorted(s.mv_rows("q5")) == \
                    sorted(control.mv_rows("q5"))
            s.flush()
            control.flush()
            got = sorted(s.mv_rows("q5"))
            assert got == sorted(control.mv_rows("q5"))
            assert len(got) > 0
        finally:
            s.close()
            control.close()

    def test_retraction_churn_crosses_exchanges(self):
        """Grouped agg over a live stream: every new bid RETRACTS the
        group's previous (count, max) row — those U-/U+ pairs cross the
        hash exchange (update-pair split rule) and the remote merge.
        Bit-exact per epoch against the in-process pipeline."""
        s = spanning_session(seed=11)
        s.run_sql(BID_DDL)
        s.run_sql(AGG)
        assert "q" in s._spanning_specs
        control = Session(seed=11, source_chunk_capacity=CAP)
        control.run_sql(BID_DDL)
        control.run_sql(AGG)
        try:
            for _ in range(4):
                s.tick()
                control.tick()
                assert sorted(s.mv_rows("q")) == sorted(control.mv_rows("q"))
            # retractions actually happened: groups were updated in place
            rows = s.mv_rows("q")
            assert any(n > 1 for _, n, _ in rows)
        finally:
            s.close()
            control.close()


class TestSpanningRecovery:
    @pytest.mark.slow
    def test_q5_kill9_participant_exactly_once(self, tmp_path):
        """checkpoint → kill -9 one NON-root participant → scoped
        recovery (respawn + rebuild ONLY this fragment graph from
        per-worker durable state) → converge bit-exact with an
        uninterrupted control run. Barriers commit exactly-once across
        the remote edges: the torn epoch is never committed."""
        s = spanning_session(seed=7, data_dir=str(tmp_path / "c"))
        s.run_sql(BID_DDL)
        s.run_sql(Q5)
        spec = s._spanning_specs["q5"]
        victim = [w for w in spec["workers"]
                  if w is not spec["root_worker"]][0]
        for _ in range(2):
            s.tick()
        s.flush()                          # checkpoint cut
        _ = s.mv_rows("q5")
        pid0 = victim.proc.pid
        victim.kill9()
        for _ in range(12):                # TTL + scoped rebuild in-tick
            s.tick()
            if not victim.dead and s.jobs["q5"]._failure is None:
                break
        assert not victim.dead, "participant was not respawned"
        assert victim.proc.pid != pid0
        for _ in range(2):
            s.tick()
        s.flush()
        got = sorted(s.mv_rows("q5"))
        s.close()
        # effective generate ticks: 2 pre-kill (committed by the flush)
        # + 2 post-recovery; dead-window ticks feed the job nothing and
        # the uncommitted pre-death generate replays from the seek
        assert got == local_run(Q5, "q5", ticks=4, seed=7)

    @pytest.mark.slow
    def test_q7_kill9_root_worker_exactly_once(self, tmp_path):
        """Same cycle killing the ROOT worker (hosts the materialize):
        q7's join output is keyed by the bid row ids, so replay must
        reproduce the SAME hidden row ids (pinned shard ids) or rows
        would duplicate."""
        s = spanning_session(seed=42, data_dir=str(tmp_path / "c"))
        s.run_sql(BID_DDL)
        s.run_sql(Q7)
        spec = s._spanning_specs["q7"]
        root = spec["root_worker"]
        for _ in range(3):
            s.tick()
        s.flush()
        _ = s.mv_rows("q7")
        root.kill9()
        for _ in range(12):
            s.tick()
            if not root.dead and s.jobs["q7"]._failure is None:
                break
        assert not root.dead, "root worker was not respawned"
        for _ in range(3):
            s.tick()
        s.flush()
        got = sorted(s.mv_rows("q7"))
        s.close()
        want = local_run(Q7, "q7", ticks=6, seed=42)
        assert got == want and len(got) > 0

    @pytest.mark.slow
    def test_sim_chaos_spanning_kill_converges(self, tmp_path):
        """sim.py chaos menu entry: kill one worker of a spanning
        fragment graph mid-workload; the cluster converges and the final
        MV matches a never-killed control session."""
        from risingwave_tpu.sim import SimCluster
        sim = SimCluster(str(tmp_path / "chaos"), seed=3, kill_rate=0.0,
                         workers=2, source_chunk_capacity=CAP,
                         config=BuildConfig(fragment_parallelism=2))
        control = Session(seed=42, source_chunk_capacity=CAP,
                          checkpoint_frequency=2)
        try:
            for sess in (sim.session, control):
                sess.run_sql(BID_DDL)
                sess.run_sql(AGG)
            assert "q" in sim.session._spanning_specs
            for _ in range(2):
                sim.tick()
                control.tick()
            sim.flush()                    # committed == generated
            control.flush()
            sim.kill_spanning_worker()     # in-tick TTL + scoped rebuild
            for _ in range(2):             # aligned post-recovery load
                sim.tick()
                control.tick()
            sim.verify_against(control, ["q"])
            assert sim.spanning_kills == 1
        finally:
            sim.session.close()
            control.close()


class TestTwoPhasePrepare:
    """Durable phase 1 of the cluster checkpoint (CheckpointLog
    prepare/settle): the machinery that keeps a spanning job's cut
    consistent across independent per-worker stores."""

    def test_pipelined_prepares_survive_earlier_commit(self, tmp_path):
        """Phase-2 promotion of epoch N must NOT discard epoch N+1's
        durably prepared segment — with pipelined checkpoints both are
        staged before either commit frame arrives."""
        from risingwave_tpu.storage.checkpoint import DurableStateStore
        d = str(tmp_path / "s")
        st = DurableStateStore(d)
        st.ingest(7, 1, {b"k1": b"v1"}, set())
        st.prepare(1)
        st.ingest(7, 2, {b"k2": b"v2"}, set())
        st.prepare(2)
        st.commit(1)
        assert st.log.prepared_epochs() == [2], \
            "commit(1) destroyed the pipelined prepare of epoch 2"
        st.commit(2)
        assert st.log.prepared_epochs() == []
        re = DurableStateStore(d)
        assert re.committed_epoch == 2
        assert re.committed_view(7) == {b"k1": b"v1", b"k2": b"v2"}

    def test_recovery_rolls_forward_and_discards(self, tmp_path):
        """A participant killed between ack and commit settles on the
        cluster-decided epoch: prepared ≤ decided rolls forward,
        prepared > decided is discarded (never decided)."""
        from risingwave_tpu.storage.checkpoint import DurableStateStore
        d = str(tmp_path / "s")
        st = DurableStateStore(d)
        st.ingest(7, 1, {b"k1": b"v1"}, set())
        st.prepare(1)
        st.ingest(7, 2, {b"k2": b"v2"}, set())
        st.prepare(2)
        # process dies here; the cluster decided epoch 1
        re = DurableStateStore(d, recover_at=1)
        assert re.committed_epoch == 1
        assert re.committed_view(7) == {b"k1": b"v1"}
        assert re.log.prepared_epochs() == []
        committed, prepared = re.log.recovery_info()
        assert (committed, prepared) == (1, [])


class TestSpanningOps:
    @pytest.mark.slow  # heavy 2-worker graph; check.sh runs this file unfiltered
    def test_placement_persists_and_restart_reuses_it(self, tmp_path):
        d = str(tmp_path / "c")
        s = spanning_session(seed=7, data_dir=d)
        s.run_sql(BID_DDL)
        s.run_sql(AGG)
        p1 = {fid: [(a.actor, a.worker, a.vnode_start, a.vnode_end)
                    for a in acts]
              for fid, acts in
              s._spanning_specs["q"]["placement"].actors.items()}
        assert s.meta.load_placement("q") is not None
        for _ in range(3):
            s.tick()
        s.flush()
        r1 = sorted(s.mv_rows("q"))
        s.close()
        s2 = spanning_session(seed=7, data_dir=d)
        try:
            assert "q" in s2._spanning_specs, "restart lost the span"
            p2 = {fid: [(a.actor, a.worker, a.vnode_start, a.vnode_end)
                        for a in acts]
                  for fid, acts in
                  s2._spanning_specs["q"]["placement"].actors.items()}
            assert p1 == p2, "restart re-placed fragments elsewhere"
            assert sorted(s2.mv_rows("q")) == r1
            s2.run_sql("DROP MATERIALIZED VIEW q")
            assert "q" not in s2._spanning_specs
            assert s2.meta.load_placement("q") is None
        finally:
            s2.close()

    def test_exchange_counters_in_metrics_and_prometheus(self):
        from risingwave_tpu.frontend.prometheus import render_metrics
        s = spanning_session(seed=11)
        s.run_sql(BID_DDL)
        s.run_sql(AGG)
        for _ in range(3):
            s.tick()
        s.flush()
        try:
            edges = s.metrics()["exchange"]
            assert edges, "no exchange edges reported"
            outs = [e for e in edges if e["dir"] == "out"]
            ins = [e for e in edges if e["dir"] == "in"]
            assert outs and ins
            assert all(set(e) >= {"edge", "chunks", "bytes",
                                  "permits_waited", "backlog", "worker"}
                       for e in edges)
            assert sum(e["chunks"] for e in outs) > 0
            assert sum(e["bytes"] for e in outs) > 0
            # both endpoints of one edge agree on delivered chunks
            by_edge = {e["edge"]: e for e in outs}
            for e in ins:
                if e["edge"] in by_edge:
                    assert e["chunks"] == by_edge[e["edge"]]["chunks"]
            text = render_metrics(s)
            assert "rw_exchange_stat" in text
        finally:
            s.close()

    def test_table_fed_mv_falls_back_to_whole_job(self):
        """Scan-fed plans keep the session-bus forwarder path: with 2
        workers a table-fed MV still deploys whole onto one worker."""
        s = spanning_session(seed=5)
        try:
            s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
            s.run_sql("CREATE MATERIALIZED VIEW m AS "
                      "SELECT k, v * 2 AS d FROM t")
            assert "m" in s._remote_specs
            assert "m" not in s._spanning_specs
            s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
            s.flush()
            assert sorted(s.mv_rows("m")) == [(1, 20), (2, 40)]
        finally:
            s.close()

    @pytest.mark.slow  # heavy 2-worker graph; check.sh runs this file unfiltered
    def test_ctl_cluster_fragments_dumps_placement(self, tmp_path, capsys):
        d = str(tmp_path / "c")
        s = spanning_session(seed=7, data_dir=d)
        s.run_sql(BID_DDL)
        s.run_sql(AGG)
        s.tick()
        s.flush()
        s.close()
        from risingwave_tpu.cli import main as cli_main
        rc = cli_main(["ctl", "cluster", "fragments", "--data-dir", d])
        out = capsys.readouterr().out
        assert rc == 0
        assert "-- q" in out and "Fragment" in out and "vnodes" in out
        assert "live exchange edges" in out


class TestServingTwoPhase:
    """Distributed two-phase batch aggregation over a SHARDED-ROOT
    spanning MV (frontend/serving.py + meta/fragment.py ``shardable``):
    the MV's materialized table is vnode-distributed across the root
    actors' workers, partial agg tasks run ON those workers over their
    own slices, and the session merges the partial states."""

    def test_root_fragment_shards_and_scan_unions(self):
        s = spanning_session(seed=11)
        control = Session(seed=11, source_chunk_capacity=CAP)
        try:
            for sess in (s, control):
                sess.run_sql(BID_DDL)
                sess.run_sql(AGG)
            assert "q" in s._spanning_specs
            spec = s._spanning_specs["q"]
            roots = spec["placement"].actors[spec["graph"].root_id]
            assert len(roots) == 2, "root (materialize) did not shard"
            assert {a.worker for a in roots} == \
                set(spec["placement"].workers())
            assert roots[0].vnode_end == roots[1].vnode_start
            assert (roots[0].vnode_start, roots[1].vnode_end) == (0, 256)
            for _ in range(3):
                s.tick()
                control.tick()
            s.flush()
            control.flush()
            # the scan RPC unions the per-worker slices bit-exactly
            assert sorted(s.mv_rows("q")) == sorted(control.mv_rows("q"))
        finally:
            s.close()
            control.close()

    @pytest.mark.slow  # heavy 2-worker graph; check.sh runs this file unfiltered
    def test_partial_tasks_run_per_vnode_slice_on_two_workers(self):
        s = spanning_session(seed=11)
        control = Session(seed=11, source_chunk_capacity=CAP)
        try:
            for sess in (s, control):
                sess.run_sql(BID_DDL)
                sess.run_sql(AGG)
                for _ in range(3):
                    sess.tick()
                sess.flush()
            sql = ("SELECT auction % 8, count(*), sum(n), max(mx) "
                   "FROM q GROUP BY auction % 8")
            got = sorted(s.run_sql(sql))
            assert got == sorted(control.run_sql(sql))
            m = s.metrics()["serving"]
            assert m["two_phase_queries"] >= 1
            assert m["tasks_fired_remote"] >= 2
            assert m["partials_merged"] >= 1
            # the partial tasks DEMONSTRABLY executed on BOTH workers,
            # each over its own vnode slice of the MV table
            assert len(m["task_workers"]) >= 2, m["task_workers"]
            # repeat: served from the version-pinned cache
            assert sorted(s.run_sql(sql)) == got
            assert s.metrics()["serving"]["cache_hits"] >= 1
        finally:
            s.close()
            control.close()
