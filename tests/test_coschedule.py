"""Epoch co-scheduler (stream/coschedule.py + ops/fused_multi.py): K
co-scheduled MVs must tick in EXACTLY one jit dispatch per epoch, and
every per-job result — state, flush churn, checkpoint export — must be
bit-exact against the solo fused path (the vmapped body IS the solo
body; these tests pin that contract for K ∈ {1, 4, 16} and across a
checkpoint/recovery cycle, per the round's acceptance criteria)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import INT64, TIMESTAMP
from risingwave_tpu.common.chunk import OP_UPDATE_DELETE, OP_UPDATE_INSERT
from risingwave_tpu.common.dispatch_count import count_dispatches
from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig
from risingwave_tpu.connector.nexmark import DeviceBidGenerator
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import agg as agg_call, count_star
from risingwave_tpu.ops import fused_multi as fm
from risingwave_tpu.ops.fused_epoch import fused_source_agg_epoch
from risingwave_tpu.stream import HashAggExecutor, ProjectExecutor
from risingwave_tpu.stream.coschedule import (
    CoGroup, CoScheduler, FusedJobSpec, agg_signature,
)
from risingwave_tpu.stream.source import MockSource

CAP = 256
GROUP_EPOCH_FN = "build_group_epoch.<locals>.coscheduled_epoch"


def _parts(calls=None, table_capacity=1 << 12):
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(1_000_000, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    proj = ProjectExecutor(MockSource(BID_SCHEMA, []), exprs,
                           names=("ws", "auction", "price"))
    agg = HashAggExecutor(
        proj, [0, 1], list(calls or [count_star()]),
        table_capacity=table_capacity, out_capacity=CAP)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    return exprs, agg, gen.chunk_fn()


def _mk_group(n_jobs, calls=None):
    exprs, agg, chunk_fn = _parts(calls)
    spec = FusedJobSpec(
        "agg", agg_signature(agg.core, exprs, CAP, ("nexmark_bid", CAP)),
        chunk_fn, tuple(exprs), agg.core, CAP, seed=0)
    group = CoGroup(spec)
    for j in range(n_jobs):
        group.add(f"mv{j}", agg.core.init_state(), seed=100 + j)
    return exprs, agg, chunk_fn, group


def _solo_epoch_and_flush(solo, agg, state, start, key, k):
    """The solo fused path's full epoch: one fused dispatch + the
    executor's own jitted flush helpers (bench measure_q5_fused)."""
    state = solo(state, jnp.int64(start), key, k)
    packed, rank = agg._probe(state)
    n_dirty, overflow, _ = (int(x) for x in jax.device_get(packed))
    assert not overflow
    chunks = []
    lo = 0
    while lo < n_dirty:
        chunks.append(agg._gather(state, rank, jnp.int64(lo)))
        lo += agg.core.groups_per_chunk
    return agg._finish(state), chunks


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("n_jobs", [1, 4, 16])
def test_k_jobs_one_dispatch_per_epoch(n_jobs):
    """THE acceptance regression: K co-scheduled MVs = exactly 1 jit
    dispatch per epoch, independent of K, and the whole group's barrier
    probe/finish are 1 vmapped dispatch each (only per-job output
    gathers scale with K — they are per-job data)."""
    with count_dispatches() as c:
        _, agg, _, group = _mk_group(n_jobs)
        group.run_epoch(4)
        group.flush()
        c.reset()
        group.run_epoch(4)
        assert c.counts[GROUP_EPOCH_FN] == 1
        assert c.total == 1          # nothing else dispatched at all
        c.reset()
        group.flush()
        non_gather = sum(n for name, n in c.counts.items()
                         if "gather" not in name)
        assert non_gather == 2       # one vmapped probe + one finish
        c.reset()
        group.run_epoch(8)           # k changes; still one dispatch
        assert c.counts[GROUP_EPOCH_FN] == 1
        assert c.total == 1


@pytest.mark.parametrize("n_jobs", [1, 4, 16])
def test_coscheduled_bit_exact_vs_solo(n_jobs):
    """Per-job states AND flush churn bit-exact vs the solo fused path,
    over several epochs (distinct per-job PRNG seeds / event cursors)."""
    exprs, agg, chunk_fn, group = _mk_group(n_jobs)
    solo = fused_source_agg_epoch(chunk_fn, exprs, agg.core, CAP)
    k = 4
    flushes = []
    for _ in range(3):
        group.run_epoch(k)
        flushes.append(group.flush())
    for j in range(n_jobs):
        st = agg.core.init_state()
        start = 0
        solo_chunks_all = []
        for e in range(3):
            key = jax.random.fold_in(jax.random.PRNGKey(100 + j), e)
            st, chunks = _solo_epoch_and_flush(solo, agg, st, start, key, k)
            start += k * CAP
            solo_chunks_all.append(chunks)
        _assert_tree_equal(group.state_of(f"mv{j}"), st)
        for e in range(3):
            got = flushes[e][f"mv{j}"]
            assert len(got) == len(solo_chunks_all[e])
            for ca, cb in zip(got, solo_chunks_all[e]):
                _assert_tree_equal(ca, cb)


def test_flush_emits_retraction_churn():
    """After the first epoch the flush carries the executor's U-/U+
    retraction pairs for touched groups — the co-scheduled path must
    reproduce that retraction stream, not just inserts."""
    _, agg, _, group = _mk_group(2)
    group.run_epoch(4)
    group.flush()
    group.run_epoch(4)
    outs = group.flush()
    ops = np.concatenate([np.asarray(c.ops)[np.asarray(c.vis)]
                          for c in outs["mv0"]])
    assert (ops == OP_UPDATE_DELETE).any()
    assert (ops == OP_UPDATE_INSERT).any()


def test_checkpoint_recovery_cycle_bit_exact():
    """Export every job's state mid-stream (the checkpoint payload),
    rebuild a fresh group from the exported copies, continue both —
    bit-exact. Proves the job-axis stacking round-trips through
    recovery."""
    exprs, agg, chunk_fn, group = _mk_group(4)
    group.run_epoch(4)
    group.flush()

    spec = FusedJobSpec(
        "agg", agg_signature(agg.core, exprs, CAP, ("nexmark_bid", CAP)),
        chunk_fn, tuple(exprs), agg.core, CAP, seed=0)
    recovered = CoGroup(spec)
    for j in range(4):
        host = jax.device_get(group.state_of(f"mv{j}"))   # checkpoint
        state = jax.tree_util.tree_map(jnp.asarray, host)  # recovery
        recovered.add(f"mv{j}", state, start=group.starts[j],
                      seed=100 + j, batch_no=group.batch_nos[j])

    group.run_epoch(4)
    f1 = group.flush()
    recovered.run_epoch(4)
    f2 = recovered.flush()
    _assert_tree_equal(group.stacked, recovered.stacked)
    for name in f1:
        for ca, cb in zip(f1[name], f2[name]):
            _assert_tree_equal(ca, cb)


def test_signature_separates_incompatible_jobs():
    """Different agg calls / shapes => different trace => different
    group; same signature => same group (the grouping rule)."""
    sched = CoScheduler()
    exprs, agg1, chunk_fn = _parts()
    sig1 = agg_signature(agg1.core, exprs, CAP, ("nexmark_bid", CAP))
    _, agg2, _ = _parts(calls=[count_star(), agg_call("max", 2, INT64)])
    sig2 = agg_signature(agg2.core, exprs, CAP, ("nexmark_bid", CAP))
    assert sig1 != sig2
    g1 = sched.add("a", FusedJobSpec("agg", sig1, chunk_fn, tuple(exprs),
                                     agg1.core, CAP, seed=1),
                   agg1.core.init_state())
    g2 = sched.add("b", FusedJobSpec("agg", sig1, chunk_fn, tuple(exprs),
                                     agg1.core, CAP, seed=2),
                   agg1.core.init_state())
    g3 = sched.add("c", FusedJobSpec("agg", sig2, chunk_fn, tuple(exprs),
                                     agg2.core, CAP, seed=3),
                   agg2.core.init_state())
    assert g1 is g2 and g1 is not g3
    assert sched.stats()["jobs"] == 3
    assert len(sched.stats()["groups"]) == 2
    st = sched.remove("a")
    assert st is not None and g1.n_jobs == 1
    sched.remove("b")
    assert sig1 not in sched.groups


def test_multi_join_epoch_bit_exact_vs_solo():
    """The source+join group shape (ops/fused_multi.fused_multi_join_epoch
    over IntervalJoinCore): one dispatch for J jobs, every output slice
    bit-exact vs the solo fused join epoch."""
    from risingwave_tpu.common.types import Field, Schema
    from risingwave_tpu.ops.fused_epoch import fused_source_join_epoch
    from risingwave_tpu.ops.interval_join import IntervalJoinCore
    from risingwave_tpu.stream.coschedule import join_signature

    W = 5_000
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(W, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    ps = Schema((Field("ws", TIMESTAMP), Field("auction", INT64),
                 Field("price", INT64)))
    core = IntervalJoinCore(ps, ts_col=0, val_col=2, window_us=W,
                            n_buckets=512, lane_width=64)
    # the join-group grouping rule: same core config => same signature,
    # a different window => a different trace => a different group
    other = IntervalJoinCore(ps, ts_col=0, val_col=2, window_us=2 * W,
                             n_buckets=512, lane_width=64)
    sig = join_signature(core, exprs, CAP, ("nexmark_bid", CAP))
    assert sig == join_signature(core, exprs, CAP, ("nexmark_bid", CAP))
    assert sig != join_signature(other, exprs, CAP, ("nexmark_bid", CAP))
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    chunk_fn = gen.chunk_fn()
    solo = fused_source_join_epoch(chunk_fn, exprs, core, CAP)
    with count_dispatches() as c:
        multi = fm.fused_multi_join_epoch(chunk_fn, exprs, core, CAP)
        J, k = 3, 4
        stacked = fm.stack_states([core.init_state() for _ in range(J)])
        starts = jnp.arange(J, dtype=jnp.int64) * 777
        keys = jnp.stack([jax.random.PRNGKey(j) for j in range(J)])
        res = multi(stacked, starts, keys, k)
        c.reset()
        res = multi(res[0], starts + k * CAP, keys, k)
        assert c.counts["fused_multi_join_epoch.<locals>.epoch"] == 1
        assert c.total == 1
    per_job = fm.unstack_states(res[0], J)
    for j in range(J):
        st = core.init_state()
        for e in range(2):
            out = solo(st, jnp.int64(j * 777 + e * k * CAP),
                       jax.random.PRNGKey(j), k)
            st = out[0]
        _assert_tree_equal(per_job[j], st)
        for got, want in zip(res[1:], out[1:]):
            _assert_tree_equal(fm.index_state(got, j), want)


# ---------------------------------------------------------------------------
# Session integration: CREATE MATERIALIZED VIEW routing, ticking, DROP,
# durability (opt-in via BuildConfig.coschedule / [streaming] coschedule)
# ---------------------------------------------------------------------------

SRC_SQL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT,
    price BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
    extra VARCHAR) WITH (connector = 'nexmark', nexmark_table = 'bid')"""
MV_SQL = ("CREATE MATERIALIZED VIEW {n} AS SELECT auction, count(*) AS c "
          "FROM bid GROUP BY auction")


def _session(tmp_path=None, coschedule=True, **kw):
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig
    return Session(config=BuildConfig(coschedule=coschedule,
                                      agg_table_capacity=1 << 12),
                   source_chunk_capacity=CAP,
                   data_dir=str(tmp_path) if tmp_path else None, **kw)


def test_session_groups_and_single_dispatch_per_tick():
    with count_dispatches() as c:
        s = _session()
        try:
            s.run_sql(SRC_SQL)
            for j in range(3):
                s.run_sql(MV_SQL.format(n=f"m{j}"))
            stats = s.metrics()["coschedule"]
            assert stats["jobs"] == 3
            assert [g["jobs"] for g in stats["groups"]] == [
                ["m0", "m1", "m2"]]
            s.tick()
            c.reset()
            s.tick()
            # the whole 3-MV group ingests in ONE dispatch per tick
            assert c.counts[GROUP_EPOCH_FN] == 1
            total = sum(r[1] for r in s.run_sql(
                "SELECT auction, c FROM m1"))
            assert total == 2 * CAP
            # MVs answer independently and identically-shaped
            assert s.run_sql("SELECT sum(c) FROM m0") == [(2 * CAP,)]
        finally:
            s.close()


def test_session_drop_and_solo_fallback():
    s = _session()
    try:
        s.run_sql(SRC_SQL)
        s.run_sql(MV_SQL.format(n="m0"))
        # ineligible shape (no grouped agg over the source) falls back to
        # the executor path and does NOT join the scheduler
        s.run_sql("CREATE MATERIALIZED VIEW raw AS SELECT auction, price "
                  "FROM bid")
        stats = s.metrics()["coschedule"]
        assert stats["jobs"] == 1
        s.tick()
        s.run_sql("DROP MATERIALIZED VIEW m0")
        assert s.metrics()["coschedule"]["jobs"] == 0
        s.tick()                       # scheduler empty; ticking still fine
        assert len(s.run_sql("SELECT * FROM raw")) == 2 * CAP
    finally:
        s.close()


def test_session_coschedule_recovery(tmp_path):
    s = _session(tmp_path, checkpoint_frequency=2)
    s.run_sql(SRC_SQL)
    s.run_sql(MV_SQL.format(n="m0"))
    for _ in range(5):                 # epochs 2..6; checkpoints at 2,4,6
        s.tick()
    committed = dict(s.run_sql("SELECT auction, c FROM m0"))
    s.close()

    s2 = _session(tmp_path, checkpoint_frequency=2)
    try:
        assert s2.metrics()["coschedule"]["jobs"] == 1
        # recovered at the last checkpoint cut, bit-exact
        assert dict(s2.run_sql("SELECT auction, c FROM m0")) == committed
        # deterministic source cursor resumes: 3 more ticks add exactly
        # 3 * CAP rows on top of the recovered cut
        base = sum(committed.values())
        for _ in range(3):
            s2.tick()
        assert s2.run_sql("SELECT sum(c) FROM m0") == [(base + 3 * CAP,)]
    finally:
        s2.close()


def test_session_solo_mv_reopened_with_flag_stays_solo(tmp_path):
    """The reverse recovery direction: an MV created WITHOUT the flag
    must replay down the executor path even when the session reopens
    with coschedule=true — the solo table-id layout only decodes there
    (marker-directed routing in both directions)."""
    s = _session(tmp_path, coschedule=False, checkpoint_frequency=2)
    s.run_sql(SRC_SQL)
    s.run_sql(MV_SQL.format(n="m0"))
    for _ in range(5):
        s.tick()
    committed = dict(s.run_sql("SELECT auction, c FROM m0"))
    s.close()

    s2 = _session(tmp_path, coschedule=True, checkpoint_frequency=2)
    try:
        # recovered on the executor path, NOT captured by the scheduler
        assert s2.metrics()["coschedule"]["jobs"] == 0
        assert dict(s2.run_sql("SELECT auction, c FROM m0")) == committed
        s2.tick()
        # but a NEW eligible MV in the same session co-schedules
        s2.run_sql(MV_SQL.format(n="m1"))
        assert s2.metrics()["coschedule"]["jobs"] == 1
        s2.tick()
    finally:
        s2.close()


def test_session_recovery_refuses_without_flag(tmp_path):
    s = _session(tmp_path, checkpoint_frequency=2)
    s.run_sql(SRC_SQL)
    s.run_sql(MV_SQL.format(n="m0"))
    s.tick()
    s.close()
    from risingwave_tpu.frontend.session import SqlError
    with pytest.raises(SqlError, match="co-scheduled"):
        _session(tmp_path, coschedule=False, checkpoint_frequency=2)
