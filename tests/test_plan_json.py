"""Plan-graph JSON boundary (coverage #79): plans round-trip through the
wire format, rebuild into executors, and produce identical results."""

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.parser import parse_one
from risingwave_tpu.frontend.plan_json import plan_from_json, plan_to_json
from risingwave_tpu.frontend.planner import Planner

QUERIES = [
    "SELECT k, v * 2 FROM t WHERE v > 5",
    "SELECT k % 3 AS g, sum(v) AS s, count(*) AS c FROM t GROUP BY k % 3",
    "SELECT a.k, b.v FROM t a JOIN t b ON a.k = b.k",
    "SELECT k, v FROM t ORDER BY v DESC LIMIT 3",
    "SELECT k, row_number() OVER (PARTITION BY k % 2 ORDER BY v) FROM t",
    "SELECT k, generate_series(1, 2) FROM t",
]


def _session():
    s = Session()
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.run_sql("INSERT INTO t VALUES (1, 3), (2, 8), (3, 12), (4, 1)")
    s.flush()
    return s


class TestPlanJson:
    @pytest.mark.parametrize("sql", QUERIES)
    def test_roundtrip_stable_and_equivalent(self, sql):
        s = _session()
        plan = Planner(s.catalog).plan_select(parse_one(sql).select)
        wire = plan_to_json(plan)
        back = plan_from_json(wire, s.catalog)
        # stable: a second serialization is byte-identical
        assert plan_to_json(back) == wire
        # structurally equal plans explain identically
        assert back.explain() == plan.explain()

    def test_roundtripped_plan_executes(self):
        """The deserialized plan builds a live executor graph that
        produces the same rows as the original (the from_proto path)."""
        s = _session()
        sql = "SELECT k % 2 AS g, sum(v) AS sv FROM t GROUP BY k % 2"
        expected = sorted(s.run_sql(sql))
        plan = Planner(s.catalog).plan_select(parse_one(sql).select)
        back = plan_from_json(plan_to_json(plan), s.catalog)
        # run the deserialized plan through the batch engine
        from risingwave_tpu.batch.lower import lower_plan
        from risingwave_tpu.batch.executors import run_batch
        lowered = lower_plan(back, s.store)
        assert lowered is not None
        rows = sorted(
            tuple(None if v is None else back.schema[i].type.to_python(v)
                  for i, v in enumerate(r))
            for r in run_batch(lowered))
        got = [tuple(r[:2]) for r in rows]
        assert sorted(got) == expected
