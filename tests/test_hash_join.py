"""HashJoin semantics — golden cases mirroring the reference's join unit
tests (reference: src/stream/src/executor/hash_join.rs:1552-3398): insert /
delete / update flows for inner, outer, semi, anti; degree transitions with
duplicate keys in one chunk; non-equi conditions; null join keys."""

import asyncio

import pytest

from risingwave_tpu.common import INT64, Schema, chunk_to_rows, make_chunk
from risingwave_tpu.common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
)
from risingwave_tpu.expr import col
from risingwave_tpu.ops import JoinType
from risingwave_tpu.storage import MemoryStateStore, StateTable
from risingwave_tpu.stream import Barrier, HashJoinExecutor, MockSource

L_SCHEMA = Schema.of(("k", INT64), ("a", INT64))
R_SCHEMA = Schema.of(("k", INT64), ("b", INT64))

CAP = 64  # small chunks keep CPU-sim compiles fast


def lchunk(rows, ops=None):
    return make_chunk(L_SCHEMA, rows, ops=ops, capacity=CAP)


def rchunk(rows, ops=None):
    return make_chunk(R_SCHEMA, rows, ops=ops, capacity=CAP)


def run_join(left_msgs, right_msgs, join_type=JoinType.INNER, **kw):
    """Drive a join over scripted epochs; returns [(op, row), ...]."""
    kw.setdefault("key_capacity", 64)
    kw.setdefault("bucket_width", 4)
    kw.setdefault("out_capacity", 32)
    ex = HashJoinExecutor(
        MockSource(L_SCHEMA, left_msgs), MockSource(R_SCHEMA, right_msgs),
        [0], [0], join_type, **kw)

    async def drain():
        out = []
        async for m in ex.execute():
            from risingwave_tpu.common import StreamChunk
            if isinstance(m, StreamChunk):
                out.extend(chunk_to_rows(m, ex.schema, with_ops=True))
        return out

    return asyncio.run(drain()), ex


def epochs(*sides_per_epoch):
    """Build aligned (left_msgs, right_msgs): each arg is (left_chunks,
    right_chunks) for one epoch."""
    left, right = [], []
    e = 1
    left.append(Barrier.new(e)); right.append(Barrier.new(e))
    for lcs, rcs in sides_per_epoch:
        left.extend(lcs); right.extend(rcs)
        e += 1
        left.append(Barrier.new(e)); right.append(Barrier.new(e))
    return left, right


def test_inner_insert_then_match():
    l, r = epochs(
        ([lchunk([(1, 100), (2, 200)])], []),
        ([], [rchunk([(1, 10), (3, 30)])]),
    )
    rows, _ = run_join(l, r, JoinType.INNER)
    assert rows == [(OP_INSERT, (1, 100, 1, 10))]


def test_inner_multi_match_and_delete():
    l, r = epochs(
        ([lchunk([(1, 100), (1, 101)])], []),
        ([], [rchunk([(1, 10)])]),
        ([], [rchunk([(1, 10)], ops=[OP_DELETE])]),
    )
    rows, _ = run_join(l, r, JoinType.INNER)
    inserts = [x for x in rows if x[0] == OP_INSERT]
    deletes = [x for x in rows if x[0] == OP_DELETE]
    assert sorted(x[1] for x in inserts) == [(1, 100, 1, 10), (1, 101, 1, 10)]
    assert sorted(x[1] for x in deletes) == [(1, 100, 1, 10), (1, 101, 1, 10)]


def test_left_outer_null_pad_then_retract():
    l, r = epochs(
        ([lchunk([(1, 100)])], []),
        ([], [rchunk([(1, 10)])]),
        ([], [rchunk([(1, 10)], ops=[OP_DELETE])]),
    )
    rows, _ = run_join(l, r, JoinType.LEFT_OUTER)
    assert rows == [
        (OP_INSERT, (1, 100, None, None)),
        (OP_UPDATE_DELETE, (1, 100, None, None)),
        (OP_UPDATE_INSERT, (1, 100, 1, 10)),
        (OP_UPDATE_DELETE, (1, 100, 1, 10)),
        (OP_UPDATE_INSERT, (1, 100, None, None)),
    ]


def test_left_outer_second_match_plain_insert():
    """Second right row with the same key emits a plain Insert, not U-/U+
    (degree transition only fires on 0 -> 1)."""
    l, r = epochs(
        ([lchunk([(1, 100)])], []),
        ([], [rchunk([(1, 10), (1, 11)])]),
    )
    rows, _ = run_join(l, r, JoinType.LEFT_OUTER)
    assert rows[0] == (OP_INSERT, (1, 100, None, None))
    assert (OP_UPDATE_DELETE, (1, 100, None, None)) in rows
    pair_ops = [op for op, row in rows[1:]]
    assert pair_ops.count(OP_UPDATE_DELETE) == 1
    assert pair_ops.count(OP_UPDATE_INSERT) == 1
    assert pair_ops.count(OP_INSERT) == 1
    assert (OP_INSERT, (1, 100, 1, 11)) in rows or (OP_INSERT, (1, 100, 1, 10)) in rows


def test_right_outer_mirrors_left():
    l, r = epochs(
        ([], [rchunk([(7, 70)])]),
        ([lchunk([(7, 700)])], []),
    )
    rows, _ = run_join(l, r, JoinType.RIGHT_OUTER)
    assert rows == [
        (OP_INSERT, (None, None, 7, 70)),
        (OP_UPDATE_DELETE, (None, None, 7, 70)),
        (OP_UPDATE_INSERT, (7, 700, 7, 70)),
    ]


def test_full_outer_both_sides_pad():
    l, r = epochs(
        ([lchunk([(1, 100)])], [rchunk([(2, 20)])]),
        ([], [rchunk([(1, 10)])]),
    )
    rows, _ = run_join(l, r, JoinType.FULL_OUTER)
    first_epoch = set(x for x in rows[:2])
    assert (OP_INSERT, (1, 100, None, None)) in first_epoch
    assert (OP_INSERT, (None, None, 2, 20)) in first_epoch
    assert rows[2:] == [
        (OP_UPDATE_DELETE, (1, 100, None, None)),
        (OP_UPDATE_INSERT, (1, 100, 1, 10)),
    ]


def test_left_semi():
    l, r = epochs(
        ([lchunk([(1, 100), (2, 200)])], []),
        ([], [rchunk([(1, 10)])]),
        ([], [rchunk([(1, 11)])]),          # second match: no re-emit
        ([], [rchunk([(1, 10)], ops=[OP_DELETE])]),  # still matched by (1,11)
        ([], [rchunk([(1, 11)], ops=[OP_DELETE])]),  # now unmatched
    )
    rows, _ = run_join(l, r, JoinType.LEFT_SEMI)
    assert rows == [
        (OP_INSERT, (1, 100)),
        (OP_DELETE, (1, 100)),
    ]


def test_left_semi_insert_on_matched_side():
    l, r = epochs(
        ([], [rchunk([(1, 10)])]),
        ([lchunk([(1, 100)])], []),
        ([lchunk([(1, 100)], ops=[OP_DELETE])], []),
    )
    rows, _ = run_join(l, r, JoinType.LEFT_SEMI)
    assert rows == [(OP_INSERT, (1, 100)), (OP_DELETE, (1, 100))]


def test_left_anti():
    l, r = epochs(
        ([lchunk([(1, 100), (2, 200)])], []),
        ([], [rchunk([(1, 10)])]),
        ([], [rchunk([(1, 10)], ops=[OP_DELETE])]),
    )
    rows, _ = run_join(l, r, JoinType.LEFT_ANTI)
    assert rows == [
        (OP_INSERT, (1, 100)),
        (OP_INSERT, (2, 200)),
        (OP_DELETE, (1, 100)),
        (OP_INSERT, (1, 100)),
    ]


def test_duplicate_key_batch_degree_transitions():
    """Two same-key right rows in ONE chunk against a degree-0 left row:
    exactly one U-/U+ transition + one plain insert (rank logic)."""
    l, r = epochs(
        ([lchunk([(1, 100)])], []),
        ([], [rchunk([(1, 10), (1, 11)])]),
    )
    rows, _ = run_join(l, r, JoinType.LEFT_OUTER)
    ops = [op for op, _ in rows]
    assert ops == [OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, OP_INSERT]


def test_update_pair_flows_through():
    l, r = epochs(
        ([lchunk([(1, 100)])], [rchunk([(1, 10)])]),
        ([lchunk([(1, 100), (1, 150)],
                 ops=[OP_UPDATE_DELETE, OP_UPDATE_INSERT])], []),
    )
    rows, _ = run_join(l, r, JoinType.INNER)
    assert (OP_INSERT, (1, 100, 1, 10)) in rows
    assert (OP_DELETE, (1, 100, 1, 10)) in rows
    assert (OP_INSERT, (1, 150, 1, 10)) in rows
    # delete emitted before the replacement insert
    assert rows.index((OP_DELETE, (1, 100, 1, 10))) < rows.index(
        (OP_INSERT, (1, 150, 1, 10)))


def test_non_equi_condition():
    # ON l.k = r.k AND l.a < r.b
    cond = col(1, INT64) < col(3, INT64)
    l, r = epochs(
        ([lchunk([(1, 5), (1, 50)])], []),
        ([], [rchunk([(1, 10)])]),
    )
    rows, _ = run_join(l, r, JoinType.INNER, condition=cond)
    assert rows == [(OP_INSERT, (1, 5, 1, 10))]


def test_condition_affects_outer_degrees():
    cond = col(1, INT64) < col(3, INT64)
    l, r = epochs(
        ([lchunk([(1, 50)])], []),
        ([], [rchunk([(1, 10)])]),   # fails condition -> left stays padded
        ([], [rchunk([(1, 99)])]),   # passes -> transition
    )
    rows, _ = run_join(l, r, JoinType.LEFT_OUTER, condition=cond)
    assert rows == [
        (OP_INSERT, (1, 50, None, None)),
        (OP_UPDATE_DELETE, (1, 50, None, None)),
        (OP_UPDATE_INSERT, (1, 50, 1, 99)),
    ]


def test_null_keys_never_match():
    l, r = epochs(
        ([lchunk([(None, 100)])], [rchunk([(None, 10)])]),
    )
    rows_inner, _ = run_join(l, r, JoinType.INNER)
    assert rows_inner == []
    l, r = epochs(
        ([lchunk([(None, 100)])], [rchunk([(None, 10)])]),
    )
    rows_outer, _ = run_join(l, r, JoinType.LEFT_OUTER)
    assert rows_outer == [(OP_INSERT, (None, 100, None, None))]


def test_checkpoint_and_recovery_rebuild_degrees():
    store = MemoryStateStore()
    lt = StateTable(store, 1, L_SCHEMA, [0, 1])
    rt = StateTable(store, 2, R_SCHEMA, [0, 1])
    l, r = epochs(
        ([lchunk([(1, 100)])], [rchunk([(1, 10)])]),
    )
    # run with checkpoint on the closing stop barrier
    l[-1] = Barrier.new(2, checkpoint=True, mutation=l[-1].mutation)
    r[-1] = Barrier.new(2, checkpoint=True, mutation=r[-1].mutation)
    from risingwave_tpu.stream.message import Mutation, MutationKind
    stop = Mutation(MutationKind.STOP)
    l.append(Barrier.new(3, checkpoint=True, mutation=stop))
    r.append(Barrier.new(3, checkpoint=True, mutation=stop))
    rows1, _ = run_join(l, r, JoinType.LEFT_OUTER,
                        left_state_table=lt, right_state_table=rt)
    store.commit(3)
    assert len(list(lt.scan_all())) == 1
    assert len(list(rt.scan_all())) == 1

    # recover into a fresh executor; delete the right row -> retraction,
    # proving degrees were rebuilt
    lt2 = StateTable(store, 1, L_SCHEMA, [0, 1])
    rt2 = StateTable(store, 2, R_SCHEMA, [0, 1])
    l2, r2 = epochs(
        ([], [rchunk([(1, 10)], ops=[OP_DELETE])]),
    )
    rows2, _ = run_join(l2, r2, JoinType.LEFT_OUTER,
                        left_state_table=lt2, right_state_table=rt2)
    assert rows2 == [
        (OP_UPDATE_DELETE, (1, 100, 1, 10)),
        (OP_UPDATE_INSERT, (1, 100, None, None)),
    ]
