"""JSONB type + operators (reference: src/common/src/array/jsonb_array.rs,
src/expr/src/vector_op/jsonb_access.rs — scaled to the dictionary-encoded
varlen design: canonical JSON text behind int32 ids)."""

import os
import tempfile

from risingwave_tpu.frontend import Session


def _seed(s):
    s.run_sql("CREATE TABLE ev (id BIGINT PRIMARY KEY, payload JSONB)")
    s.run_sql("""INSERT INTO ev VALUES
      (1, '{"user": {"name": "ada", "age": 36}, "tags": ["a", "b"]}'),
      (2, '{"user": {"name": "bob"}, "n": 5}'),
      (3, '[10, 20, 30]')""")
    s.tick()


def test_jsonb_access_operators():
    s = Session()
    _seed(s)
    assert s.run_sql("SELECT id, payload ->> 'n' FROM ev "
                     "WHERE id = 2") == [(2, "5")]
    assert s.run_sql(
        "SELECT payload -> 'user' ->> 'name' AS name FROM ev "
        "WHERE id = 1") == [("ada",)]
    # element access by index, negative wraps (PG semantics)
    assert s.run_sql("SELECT payload ->> 1 FROM ev WHERE id = 3") == [
        ("20",)]
    # -> returns jsonb (canonical text), ->> returns text
    assert s.run_sql("SELECT payload -> 'user' FROM ev WHERE id = 2") == [
        ('{"name":"bob"}',)]
    # missing keys are NULL, not errors
    assert s.run_sql("SELECT payload ->> 'missing' FROM ev "
                     "WHERE id = 1") == [(None,)]
    s.close()


def test_jsonb_null_value_vs_missing_key():
    """A present-but-null field is jsonb 'null' under -> (and typeof
    'null'), while a missing key is SQL NULL; ->> maps a JSON null to
    SQL NULL (PG semantics)."""
    s = Session()
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, j JSONB)")
    s.run_sql("""INSERT INTO t VALUES (1, '{"a": null}')""")
    s.tick()
    assert s.run_sql("SELECT j -> 'a' FROM t") == [("null",)]
    assert s.run_sql("SELECT jsonb_typeof(j -> 'a') FROM t") == [("null",)]
    assert s.run_sql("SELECT j ->> 'a' FROM t") == [(None,)]
    assert s.run_sql("SELECT j -> 'missing' FROM t") == [(None,)]
    assert s.run_sql("SELECT jsonb_typeof(j -> 'missing') FROM t") == [
        (None,)]
    s.close()


def test_jsonb_typeof_and_length():
    s = Session()
    _seed(s)
    rows = sorted(s.run_sql(
        "SELECT id, jsonb_typeof(payload), "
        "jsonb_array_length(payload) FROM ev"))
    assert rows == [(1, "object", None), (2, "object", None),
                    (3, "array", 3)]
    s.close()


def test_jsonb_group_by_path_and_recovery():
    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "data")
        s = Session(data_dir=data)
        _seed(s)
        s.run_sql("""CREATE MATERIALIZED VIEW names AS
          SELECT payload -> 'user' ->> 'name' AS name, count(*) AS n
          FROM ev GROUP BY payload -> 'user' ->> 'name'""")
        s.tick()
        before = sorted(s.mv_rows("names"), key=repr)
        assert before == sorted(
            [("ada", 1), ("bob", 1), (None, 1)], key=repr)
        s.run_sql("FLUSH")
        s.close()
        # jsonb persists by content and recovers in a fresh dictionary
        s2 = Session(data_dir=data)
        assert sorted(s2.mv_rows("names"), key=repr) == before
        s2.run_sql("""INSERT INTO ev VALUES
          (4, '{"user": {"name": "ada"}}')""")
        s2.tick()
        rows = {r[0]: r[1] for r in s2.mv_rows("names")}
        assert rows["ada"] == 2
        s2.close()
