"""SqlSmith-lite fuzz (reference test-strategy layer 6): random SQL must
neither crash nor break the stream/batch equivalence oracle. Query count
is modest because every generated MV compiles a fresh pipeline; run
risingwave_tpu.fuzz.run_fuzz directly for longer hunts."""

from risingwave_tpu.fuzz import run_fuzz


def test_fuzz_stream_batch_equivalence():
    checked, failures = run_fuzz(n_queries=8, seed=3)
    assert not failures, "\n".join(failures[:5])
    assert checked >= 6
