"""HopWindow / Union / Values / Expand / Dedup / RowIdGen / WatermarkFilter /
Sort / Now executor tests (reference: the matching in-module tests under
src/stream/src/executor/)."""

import asyncio

from risingwave_tpu.common import (
    INT64, TIMESTAMP, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
    Schema, chunk_to_rows, make_chunk,
)
from risingwave_tpu.storage import MemoryStateStore, StateTable
from risingwave_tpu.stream import (
    AppendOnlyDedupExecutor, Barrier, ExpandExecutor, HopWindowExecutor,
    MockSource, NowExecutor, RowIdGenExecutor, SortExecutor, UnionExecutor,
    ValuesExecutor, Watermark, WatermarkFilterExecutor, is_chunk, wrap_debug,
)

TS = Schema.of(("id", INT64), ("ts", TIMESTAMP))


def run(coro):
    return asyncio.run(coro)


async def drain(executor):
    chunks, barriers, wms = [], [], []
    async for msg in executor.execute():
        if is_chunk(msg):
            chunks.append(msg)
        elif isinstance(msg, Barrier):
            barriers.append(msg)
        else:
            wms.append(msg)
    return chunks, barriers, wms


def rows_of(chunks, schema, with_ops=False):
    out = []
    for c in chunks:
        out.extend(chunk_to_rows(c, schema, with_ops=with_ops))
    return out


def us(sec):
    return sec * 1_000_000


def test_hop_window_expansion():
    # slide 10s, size 30s -> each row in 3 windows
    src = MockSource(TS, [
        Barrier.new(1),
        make_chunk(TS, [(1, us(25))], capacity=4),
        Barrier.new(2),
    ])
    ex = HopWindowExecutor(src, time_col=1, window_slide=us(10),
                           window_size=us(30))
    chunks, _, _ = run(drain(wrap_debug(ex)))
    rows = sorted(rows_of(chunks, ex.schema))
    assert rows == [
        (1, us(25), us(0), us(30)),
        (1, us(25), us(10), us(40)),
        (1, us(25), us(20), us(50)),
    ]


def test_union_and_watermark_min():
    a = MockSource(TS, [
        Barrier.new(1),
        make_chunk(TS, [(1, 10)], capacity=4),
        Watermark(1, 100),
        Barrier.new(2),
    ])
    b = MockSource(TS, [
        Barrier.new(1),
        make_chunk(TS, [(2, 20)], capacity=4),
        Watermark(1, 50),
        Barrier.new(2),
    ])
    ex = UnionExecutor([a, b])
    chunks, barriers, wms = run(drain(ex))
    assert sorted(rows_of(chunks, ex.schema)) == [(1, 10), (2, 20)]
    assert len(barriers) == 2
    # min across inputs
    assert [(w.col_idx, w.value) for w in wms] == [(1, 50)]


def test_values_emits_once():
    barriers = MockSource(TS, [Barrier.new(1), Barrier.new(2)])
    ex = ValuesExecutor(TS, [(1, 5), (2, 6)], barriers)
    chunks, bs, _ = run(drain(ex))
    assert rows_of(chunks, ex.schema) == [(1, 5), (2, 6)]
    assert len(bs) == 2


def test_expand_subsets():
    src = MockSource(TS, [
        Barrier.new(1),
        make_chunk(TS, [(7, 30)], capacity=2),
        Barrier.new(2),
    ])
    ex = ExpandExecutor(src, [[0], [1]])
    chunks, _, _ = run(drain(ex))
    got = sorted(rows_of(chunks, ex.schema), key=lambda r: r[2])
    assert got == [(7, None, 0), (None, 30, 1)]


def test_append_only_dedup():
    src = MockSource(TS, [
        Barrier.new(1),
        make_chunk(TS, [(1, 10), (2, 20), (1, 30)], capacity=4),
        Barrier.new(2),
        make_chunk(TS, [(2, 40), (3, 50)], capacity=4),
        Barrier.new(3),
    ])
    ex = AppendOnlyDedupExecutor(src, [0], table_capacity=64)
    chunks, _, _ = run(drain(wrap_debug(ex)))
    # keep-first within chunk; cross-chunk dups dropped
    assert rows_of(chunks, ex.schema) == [(1, 10), (2, 20), (3, 50)]


def test_dedup_checkpoint_recovery():
    store = MemoryStateStore()
    pk_schema = Schema.of(("id", INT64))

    def table():
        return StateTable(store, 5, pk_schema, [0])

    src = MockSource(TS, [
        Barrier.new(1),
        make_chunk(TS, [(1, 10)], capacity=4),
        Barrier.new(2, checkpoint=True),
    ])
    ex = AppendOnlyDedupExecutor(src, [0], state_table=table(),
                                 table_capacity=64)
    run(drain(ex))
    store.commit(2)

    src2 = MockSource(TS, [
        Barrier.new(3),
        make_chunk(TS, [(1, 99), (4, 40)], capacity=4),
        Barrier.new(4),
    ])
    ex2 = AppendOnlyDedupExecutor(src2, [0], state_table=table(),
                                  table_capacity=64)
    chunks, _, _ = run(drain(ex2))
    assert rows_of(chunks, ex2.schema) == [(4, 40)]


def test_row_id_gen():
    src = MockSource(TS, [
        Barrier.new(1),
        make_chunk(TS, [(None, 10), (None, 20)], capacity=4),
        make_chunk(TS, [(None, 30)], capacity=4),
        Barrier.new(2),
    ])
    ex = RowIdGenExecutor(src, row_id_index=0, shard_id=3)
    chunks, _, _ = run(drain(ex))
    rows = rows_of(chunks, ex.schema)
    base = 3 << 48
    assert rows == [(base, 10), (base + 1, 20), (base + 2, 30)]


def test_watermark_filter_drops_late_rows():
    src = MockSource(TS, [
        Barrier.new(1),
        make_chunk(TS, [(1, 100), (2, 50)], capacity=4),
        # watermark now 100-20=80; late row ts=70 must drop
        make_chunk(TS, [(3, 70), (4, 130)], capacity=4),
        Barrier.new(2),
    ])
    ex = WatermarkFilterExecutor(src, time_col=1, delay=20)
    chunks, _, wms = run(drain(ex))
    rows = rows_of(chunks, ex.schema)
    assert (3, 70) not in rows  # below announced watermark 80 -> dropped
    assert rows == [(1, 100), (2, 50), (4, 130)]
    assert [w.value for w in wms] == [80, 110]


def test_sort_eowc_emits_in_order():
    src = MockSource(TS, [
        Barrier.new(1),
        make_chunk(TS, [(1, 30), (2, 10), (3, 50)], capacity=4),
        Watermark(1, 35),
        Barrier.new(2),
        make_chunk(TS, [(4, 20)], capacity=4),  # ts=20 < wm: would be late,
        Watermark(1, 60),                        # but Sort just orders by ts
        Barrier.new(3),
    ])
    ex = SortExecutor(src, time_col=1, pk_indices=[0], table_capacity=64,
                      out_capacity=4)
    chunks, _, _ = run(drain(ex))
    rows = rows_of(chunks, ex.schema)
    assert rows == [(2, 10), (1, 30), (4, 20), (3, 50)]


def test_now_executor():
    barriers = MockSource(TS, [Barrier.new(1), Barrier.new(2)])
    ex = NowExecutor(barriers)
    chunks, bs, wms = run(drain(ex))
    rows = rows_of(chunks, ex.schema, with_ops=True)
    assert rows[0][0] == OP_INSERT
    assert rows[1][0] == OP_UPDATE_DELETE and rows[2][0] == OP_UPDATE_INSERT
    assert len(wms) == 2 and wms[0].value < wms[1].value
