"""Data-plane tests: chunk round-trip, visibility, hashing, vnodes.

Mirrors the reference's in-module array/chunk tests
(src/common/src/array/data_chunk.rs tests)."""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common import (
    INT64, FLOAT64, VARCHAR, OP_DELETE, OP_INSERT, Schema, StreamChunk,
    chunk_to_rows, compact_chunk_host, make_chunk, vnode_of, vnode_to_shard,
    hash_columns, VNODE_COUNT,
)


SCHEMA = Schema.of(("id", INT64), ("price", FLOAT64), ("name", VARCHAR))


def test_roundtrip_with_nulls():
    rows = [(1, 2.5, "alice"), (2, None, "bob"), (3, 7.0, None)]
    chunk = make_chunk(SCHEMA, rows, capacity=8)
    assert chunk.capacity == 8
    assert int(chunk.cardinality()) == 3
    assert chunk_to_rows(chunk, SCHEMA) == rows


def test_ops_and_signs():
    rows = [(1, 1.0, "a"), (2, 2.0, "b"), (3, 3.0, "c")]
    chunk = make_chunk(SCHEMA, rows, ops=[OP_INSERT, OP_DELETE, OP_INSERT], capacity=4)
    signs = np.asarray(chunk.signs())
    assert list(signs) == [1, -1, 1, 0]
    got = chunk_to_rows(chunk, SCHEMA, with_ops=True)
    assert got[1] == (OP_DELETE, (2, 2.0, "b"))


def test_vis_masking_and_compact():
    rows = [(i, float(i), "x") for i in range(5)]
    chunk = make_chunk(SCHEMA, rows, capacity=8)
    keep = jnp.asarray([True, False, True, False, True, True, True, True])
    filtered = chunk.mask_vis(keep)
    assert int(filtered.cardinality()) == 3
    compacted = compact_chunk_host(filtered)
    assert chunk_to_rows(compacted, SCHEMA) == [rows[0], rows[2], rows[4]]
    assert bool(np.asarray(compacted.vis)[:3].all())


def test_hash_deterministic_and_null_distinct():
    rows = [(1, 1.0, "a"), (1, 1.0, "a"), (2, 1.0, "a"), (None, 1.0, "a")]
    chunk = make_chunk(SCHEMA, rows, capacity=4)
    h = np.asarray(hash_columns([chunk.columns[0]]))
    assert h[0] == h[1]
    assert h[0] != h[2]
    assert h[3] != h[0] and h[3] != h[2]


def test_vnode_range_and_spread():
    n = 1000
    rows = [(i, 0.0, "") for i in range(n)]
    chunk = make_chunk(SCHEMA, rows, capacity=1024)
    vn = np.asarray(vnode_of([chunk.columns[0]]))[:n]
    assert vn.min() >= 0 and vn.max() < VNODE_COUNT
    # splitmix64 should spread 1000 sequential keys over >200 of 256 vnodes
    assert len(np.unique(vn)) > 200
    shards = np.asarray(vnode_to_shard(jnp.asarray(vn), 8))
    assert shards.min() >= 0 and shards.max() < 8
    # contiguous-range property: vnode // 32 == shard
    assert (shards == vn // 32).all()


def test_project_and_append():
    rows = [(1, 2.0, "a")]
    chunk = make_chunk(SCHEMA, rows, capacity=2)
    p = chunk.project([2, 0])
    assert chunk_to_rows(p, SCHEMA.select([2, 0])) == [("a", 1)]
