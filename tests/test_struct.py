"""STRUCT composite type (reference: src/common/src/array/struct_array.rs,
field access src/expr/src/expr/expr_field.rs) — value-interned field
tuples behind int32 ids, the same varlen strategy as LIST/JSONB."""

import json
import os
import tempfile

from risingwave_tpu.frontend import Session


def test_struct_declare_construct_access():
    s = Session()
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, "
              "who STRUCT<name VARCHAR, age BIGINT>)")
    s.run_sql("INSERT INTO t VALUES (1, ROW('ada', 36)), "
              "(2, ROW('bob', 41)), (3, NULL)")
    s.tick()
    assert sorted(s.run_sql("SELECT id, who FROM t"), key=repr) == sorted(
        [(1, ("ada", 36)), (2, ("bob", 41)), (3, None)], key=repr)
    assert sorted(s.run_sql(
        "SELECT id, (who).name, (who).age FROM t WHERE who IS NOT NULL"
    )) == [(1, "ada", 36), (2, "bob", 41)]
    assert s.run_sql("SELECT (who).name FROM t WHERE (who).age > 40") == [
        ("bob",)]
    # grouped MV keyed on a struct field, maintained incrementally
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT (who).name AS n, "
              "count(*) AS c FROM t WHERE who IS NOT NULL "
              "GROUP BY (who).name")
    s.tick()
    assert sorted(s.mv_rows("m")) == [("ada", 1), ("bob", 1)]
    s.run_sql("DELETE FROM t WHERE id = 2")
    s.tick()
    assert sorted(s.mv_rows("m")) == [("ada", 1)]
    s.close()


def test_struct_persists_by_content():
    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "data")
        s = Session(data_dir=data)
        s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, "
                  "p STRUCT<x BIGINT, label VARCHAR>)")
        s.run_sql("INSERT INTO t VALUES (1, ROW(10, 'hi'))")
        s.tick()
        s.run_sql("FLUSH")
        s.close()
        s2 = Session(data_dir=data)
        assert s2.run_sql("SELECT (p).x, (p).label FROM t") == [(10, "hi")]
        s2.close()


def test_struct_decimal_scale_and_nesting_survive():
    """Field types carry FULL DataTypes: decimal scale is not dropped,
    and nested composites round-trip through persistence."""
    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "data")
        s = Session(data_dir=data)
        s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, "
                  "v STRUCT<amt DECIMAL, inner STRUCT<x BIGINT, "
                  "y BIGINT>>)")
        s.run_sql("INSERT INTO t VALUES (1, ROW(1.5, ROW(7, 8)))")
        s.tick()
        assert s.run_sql("SELECT (v).amt FROM t") == [(1.5,)]
        assert s.run_sql("SELECT ((v).inner).y FROM t") == [(8,)]
        s.run_sql("FLUSH")
        s.close()
        s2 = Session(data_dir=data)
        assert s2.run_sql("SELECT (v).amt, ((v).inner).x FROM t") == [
            (1.5, 7)]
        s2.close()


def test_row_literal_carries_full_field_types():
    """ROW(…) builds its struct type from the items' FULL DataTypes —
    a decimal field keeps its scale (the bare-kind bug decoded
    1.23::decimal as 123 after the scale was dropped), and a literal
    cast inside ROW is const-folded rather than rejected."""
    s = Session()
    assert s.run_sql("SELECT ROW(1.23::decimal)") == [((1.23,),)]
    assert s.run_sql("SELECT ROW('hi'::varchar, 2::bigint)") == [
        (("hi", 2),)]
    # round-trip through a stored struct column and field access
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, "
              "v STRUCT<f1 DECIMAL>)")
    s.run_sql("INSERT INTO t VALUES (1, ROW(1.23::decimal))")
    s.tick()
    assert s.run_sql("SELECT (v).f1 FROM t") == [(1.23,)]
    assert s.run_sql("SELECT v FROM t") == [((1.23,),)]
    # a cast the fold can't represent stays a clean bind error, not a
    # crash inside the type conversion
    import pytest
    with pytest.raises(Exception, match="must be constants"):
        s.run_sql("SELECT ROW(1::varchar)")
    s.close()


def test_struct_arity_mismatch_rejected():
    import pytest
    s = Session()
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, "
              "v STRUCT<a BIGINT, b BIGINT>)")
    with pytest.raises(Exception):
        s.run_sql("INSERT INTO t VALUES (1, ROW(1))")
    s.close()


def test_struct_json_source_ingest(tmp_path):
    path = tmp_path / "events.jsonl"
    path.write_text("\n".join(json.dumps(o) for o in [
        {"id": 1, "who": {"name": "ada", "age": 36}},
        {"id": 2, "who": {"name": "bob", "age": 41}},
        {"id": 3, "who": None},
    ]))
    s = Session()
    s.run_sql(f"""CREATE SOURCE ev (id BIGINT,
        who STRUCT<name VARCHAR, age BIGINT>)
        WITH (connector = 'file', path = '{path}')""")
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT id, (who).name AS n "
              "FROM ev")
    s.tick()
    got = sorted(s.mv_rows("m"), key=repr)
    assert got == sorted([(1, "ada"), (2, "bob"), (3, None)], key=repr)
    s.close()
