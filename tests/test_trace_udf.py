"""Tracing dump, UDFs, telemetry (coverage #85/#14/#8)."""

import pytest

from risingwave_tpu.common.telemetry import TelemetryManager
from risingwave_tpu.common.types import FLOAT64, INT64, VARCHAR
from risingwave_tpu.expr.udf import drop_udf, register_udf
from risingwave_tpu.frontend import Session
from risingwave_tpu.stream.trace import dump_session


class TestTrace:
    def test_dump_shows_pipeline_and_counters(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, sum(v) AS sv FROM t GROUP BY k")
        s.run_sql("INSERT INTO t VALUES (1, 2)")
        s.flush()
        out = dump_session(s)
        assert "job 'm':" in out
        assert "Materialize" in out and "HashAgg" in out
        assert "barriers=" in out
        assert f"completed={s.epoch}" in out


class TestUdf:
    def test_scalar_udf_in_sql(self):
        register_udf("add_tax", lambda v: int(v * 1.1), [INT64], INT64)
        try:
            s = Session()
            s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
            s.run_sql("INSERT INTO t VALUES (1, 100), (2, 200)")
            s.flush()
            rows = dict(s.run_sql("SELECT k, add_tax(v) FROM t"))
            assert rows == {1: 110, 2: 220}
            # strict NULL handling
            s.run_sql("INSERT INTO t VALUES (3, NULL)")
            s.flush()
            rows = dict(s.run_sql("SELECT k, add_tax(v) FROM t"))
            assert rows[3] is None
        finally:
            drop_udf("add_tax")

    def test_varchar_udf_and_mv(self):
        register_udf("shout", lambda s_: s_.upper() + "!", [VARCHAR], VARCHAR)
        try:
            s = Session()
            s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, s VARCHAR)")
            s.run_sql("CREATE MATERIALIZED VIEW m AS "
                      "SELECT k, shout(s) AS x FROM t")
            s.run_sql("INSERT INTO t VALUES (1, 'hey')")
            s.flush()
            assert s.mv_rows("m") == [(1, "HEY!")]
        finally:
            drop_udf("shout")

    def test_vectorized_udf(self):
        import numpy as np
        register_udf("sq", lambda a: a * a, [FLOAT64], FLOAT64,
                     vectorized=True)
        try:
            s = Session()
            s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, x DOUBLE)")
            s.run_sql("INSERT INTO t VALUES (1, 3.0)")
            s.flush()
            assert s.run_sql("SELECT sq(x) FROM t") == [(9.0,)]
        finally:
            drop_udf("sq")

    def test_name_collision_rejected(self):
        with pytest.raises(ValueError, match="already exists"):
            register_udf("lower", lambda s_: s_, [VARCHAR], VARCHAR)


class TestTelemetry:
    def test_disabled_by_default(self):
        tm = TelemetryManager()
        assert tm.report() is None and tm.reports == []

    def test_report_shape(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
        tm = TelemetryManager(enabled=True)
        r = tm.report(s)
        assert r["job_counts"]["tables"] == 1
        assert tm.reports == [r]


class TestDropUdfGuard:
    def test_drop_udf_refuses_builtins(self):
        with pytest.raises(ValueError, match="not a registered UDF"):
            drop_udf("upper")
