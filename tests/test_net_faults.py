"""Network fault plane unit surface (ISSUE 9): deterministic schedules,
per-link transports, frame seq dedup/reorder, duplicated-ack credit
protection, the idle-link keepalive + pool eviction regression, the
failpoint registry, and the ConsistencyAuditor's checks — all fast and
process-local (the cross-process integration lives in test_chaos.py)."""

import asyncio
import json

import pytest

from risingwave_tpu.rpc.faults import (
    ChaosPlane, ChaosRule, ChaosSchedule, FaultyTransport, install, plane,
)


def _mk_plane(rules, seed=7):
    p = ChaosPlane()
    p.install(ChaosSchedule(seed, rules))
    return p


async def _send(p, link, obj, meta=False):
    out = []

    async def emit(b):
        out.append(b)

    t = FaultyTransport(link, p)
    await t.send(obj, json.dumps(obj).encode(), emit, meta=meta)
    return out


class TestChaosSchedule:
    def test_json_round_trip(self):
        s = ChaosSchedule(11, [
            ChaosRule(kind="partition", link="w0<->w1",
                      types=["exg_data"], epochs=[3, 6]),
            ChaosRule(kind="duplicate", link="w*->s", prob=0.5,
                      count=2),
            ChaosRule(kind="delay", link="s->w0", delay_frames=2),
        ], name="x")
        s2 = ChaosSchedule.from_json(s.to_json())
        assert s2.to_json() == s.to_json()
        assert s2.seed == 11 and s2.name == "x"
        assert [r.kind for r in s2.rules] == \
            ["partition", "duplicate", "delay"]

    def test_unknown_kind_refused(self):
        with pytest.raises(ValueError):
            ChaosRule(kind="gremlins")

    def test_bidirectional_link_shorthand(self):
        r = ChaosRule(kind="drop", link="w0<->w1")
        assert r.matches_link("w0->w1") and r.matches_link("w1->w0")
        assert not r.matches_link("w0->w2")

    def test_prob_draws_are_deterministic(self):
        """Same (seed, link, seq) → same decision, across plane
        instances (the cross-process replay property)."""
        rules = [ChaosRule(kind="drop", link="a->b", prob=0.4)]
        traces = []
        for _ in range(2):
            p = _mk_plane(rules, seed=3)
            for i in range(50):
                p.decide("a->b", "exg_data", "exg_data:chunk", None,
                         False)
            traces.append([(e["seq"], e["kind"]) for e in p.trace])
        assert traces[0] == traces[1]
        assert 0 < len(traces[0]) < 50       # prob actually filtered
        # a different seed draws a different injection set
        p2 = _mk_plane(rules, seed=4)
        for i in range(50):
            p2.decide("a->b", "exg_data", "exg_data:chunk", None, False)
        assert [(e["seq"], e["kind"]) for e in p2.trace] != traces[0]

    def test_epoch_window_tracks_per_link_barriers(self):
        p = _mk_plane([ChaosRule(kind="partition", link="a->b",
                                 epochs=[5, 8])])
        # below the window: passes
        acts, _ = p.decide("a->b", "exg_data", "exg_data:chunk", None,
                           False)
        assert not acts
        # a barrier carrying epoch 5 opens the window ON THIS LINK
        acts, _ = p.decide("a->b", "exg_data", "exg_data:barrier", 5,
                           False)
        assert [k for k, _, _ in acts] == ["partition"]
        acts, _ = p.decide("a->b", "exg_data", "exg_data:chunk", None,
                           False)
        assert acts, "window stays open for subsequent frames"
        # other links unaffected
        acts, _ = p.decide("b->a", "exg_data", "exg_data:chunk", None,
                           False)
        assert not acts
        # epoch 8 closes it
        acts, _ = p.decide("a->b", "exg_data", "exg_data:barrier", 8,
                           False)
        assert not acts

    def test_count_caps_rule_fires(self):
        p = _mk_plane([ChaosRule(kind="duplicate", link="*", count=2)])
        fires = 0
        for _ in range(10):
            acts, _ = p.decide("x->y", "reply", "reply", None, False)
            fires += bool(acts)
        assert fires == 2


class TestFaultyTransport:
    def test_drop_and_duplicate(self):
        async def run():
            p = _mk_plane([
                ChaosRule(kind="drop", link="a->b", frames=[1, 2]),
                ChaosRule(kind="duplicate", link="a->b",
                          frames=[2, 3]),
            ])
            assert len(await _send(p, "a->b", {"type": "x"})) == 1
            assert len(await _send(p, "a->b", {"type": "x"})) == 0
            assert len(await _send(p, "a->b", {"type": "x"})) == 2
            return p
        p = asyncio.run(run())
        assert p.injections == {"drop": 1, "duplicate": 1}
        assert [e["kind"] for e in p.trace] == ["drop", "duplicate"]

    def test_delay_frames_reorders(self):
        async def run():
            p = _mk_plane([ChaosRule(kind="delay", link="a->b",
                                     frames=[0, 1], delay_frames=2)])
            sent = []

            async def emit(b):
                sent.append(json.loads(b)["i"])

            t = FaultyTransport("a->b", p)
            for i in range(4):
                obj = {"type": "x", "i": i}
                await t.send(obj, json.dumps(obj).encode(), emit)
            return sent
        # frame 0 held until 2 more frames passed: 1, 2, 0, 3
        assert asyncio.run(run()) == [1, 2, 0, 3]

    def test_meta_frames_skip_seq_and_trace_but_honor_partition(self):
        async def run():
            p = _mk_plane([ChaosRule(kind="sever", link="a->b",
                                     frames=[0, 10 ** 9])])
            out = await _send(p, "a->b", {"type": "exg_ping"},
                              meta=True)
            return p, out
        p, out = asyncio.run(run())
        assert out == []                 # severed: the ping is eaten
        assert p.trace == []             # …but leaves no trace entry
        assert p._links["a->b"].seq == 0  # …and consumes no seq

    def test_uninstalled_plane_passes_through(self):
        async def run():
            p = ChaosPlane()
            return await _send(p, "a->b", {"type": "x"})
        assert len(asyncio.run(run())) == 1


class TestExchangeSeqDiscipline:
    def _mk_input(self):
        from risingwave_tpu.common.types import Field, INT64, Schema
        from risingwave_tpu.rpc.exchange import EdgeStats
        from risingwave_tpu.stream.remote_exchange import ExchangeInput
        stats = EdgeStats("j:f0.0->f1.0", "in", 1)
        return ExchangeInput(7, Schema((Field("a", INT64),)), 16,
                             stats, "j"), stats

    def test_duplicates_dropped_reorders_resequenced(self):
        inp, stats = self._mk_input()
        for seq in (0, 2, 1, 1, 3, 0):
            inp.feed_wire({"i": seq}, None, None, seq=seq)
        # delivered queue holds seqs 0..3 in order
        order = [payload["i"]
                 for (_kind, payload, _w, _l) in list(inp._q._items)]
        assert order == [0, 1, 2, 3]
        assert stats.dup_frames == 2 and stats.reordered == 1

    def test_legacy_frames_without_seq_pass(self):
        inp, stats = self._mk_input()
        inp.feed_wire({"i": 9}, None, None, seq=None)
        assert inp.qsize() == 1 and stats.dup_frames == 0

    def test_barrier_epoch_regression_counted(self):
        from risingwave_tpu.rpc.exchange import EdgeStats
        st = EdgeStats("e", "in", 0)
        st.saw_barrier(4)
        st.saw_barrier(5)
        st.saw_barrier(5)            # duplicate epoch = regression
        st.saw_barrier(3)            # went backwards = regression
        assert st.last_barrier_epoch == 5
        assert st.epoch_regressions == 2
        snap = st.snapshot()
        assert snap["epoch_regressions"] == 2
        assert snap["last_barrier_epoch"] == 5

    def test_channel_source_dedups_session_data(self):
        from risingwave_tpu.worker.host import _ChannelSource
        from risingwave_tpu.common.types import Field, INT64, Schema
        ch = _ChannelSource(None, 3, Schema((Field("a", INT64),)), 16)
        for seq in (0, 1, 1, 3, 2):
            ch.feed({"i": seq}, seq=seq)
        got = []
        while not ch.queue.empty():
            got.append(ch.queue.get_nowait()["i"])
        assert got == [0, 1, 2, 3]
        assert ch.dup_frames == 1 and ch.reordered == 1

    def test_duplicated_ack_does_not_inflate_credit(self):
        """A duplicated ack must not release a second permit (credit
        inflation lets the producer overrun the consumer), but a
        REORDERED genuine ack must still release exactly one — the
        naive seq<expected check misread it as a duplicate and leaked
        its permit forever."""
        from risingwave_tpu.rpc.exchange import AckWatermark
        wm = AckWatermark()
        # in-order dup
        assert [wm.accept(s) for s in (0, 0, 1)] == [True, False, True]
        # reorder: 3 overtakes 2; both are genuine, each accepted once
        assert wm.accept(3) is True
        assert wm.accept(2) is True
        assert wm.accept(2) is False and wm.accept(3) is False
        assert wm.next == 4 and not wm._seen   # compacted, no growth
        # legacy peers without seqs always pass
        assert wm.accept(None) is True

    def test_reorder_buffer_shared_helper(self):
        from risingwave_tpu.rpc.exchange import SeqReorderBuffer
        b = SeqReorderBuffer()
        out = []
        for seq, p in ((0, "a"), (2, "c"), (1, "b"), (1, "b'"),
                       (3, "d")):
            out.extend(b.feed(seq, p))
        assert out == ["a", "b", "c", "d"]
        assert b.dup_frames == 1 and b.reordered == 1
        assert b.feed(None, "x") == ["x"]      # legacy pass-through


class TestKeepaliveEviction:
    def test_half_open_peer_detected_and_pool_evicts(self):
        """Satellite regression: a peer socket that stops answering
        (half-open — no FIN, no pongs) used to look healthy until the
        next send wedged a permit. The keepalive prober must mark the
        client broken and PeerClientPool.get must EVICT it and hand
        back a fresh client."""
        from risingwave_tpu.rpc.exchange import PeerClientPool

        async def run():
            async def silent_server(reader, writer):
                await reader.read(64)        # swallow hello + pings
                await asyncio.sleep(30)

            server = await asyncio.start_server(
                silent_server, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            pool = PeerClientPool(0, keepalive_s=0.05,
                                  keepalive_timeout_s=0.05)
            client = pool.get("127.0.0.1", port, peer_worker=1)
            client.register(1, permits=4)
            await client._ensure_connected()
            for _ in range(100):             # ≤ ~2s for 2 missed pongs
                if client.broken:
                    break
                await asyncio.sleep(0.05)
            assert client.broken, "keepalive never declared the " \
                                  "half-open peer dead"
            fresh = pool.get("127.0.0.1", port, peer_worker=1)
            assert fresh is not client
            assert pool.evictions == 1
            await client.aclose()
            await fresh.aclose()
            server.close()
            await server.wait_closed()
        asyncio.run(run())


class TestFailpointRegistry:
    def test_failpoint_honesty_lint_is_wired(self):
        """The declared==executed registry check moved to lint time
        (rwlint's failpoint-honesty rule, docs/static-analysis.md) — it
        now runs on every scripts/check.sh invocation instead of only
        when this suite does. Keep a thin smoke here that the lint IS
        wired: the rule parses a non-empty declared set, sees the 2PC
        checkpoint sites, and reports the package clean."""
        from risingwave_tpu.analysis import lint_package, RULES, \
            all_rules
        from risingwave_tpu.common.failpoint import declared_sites
        all_rules()
        rule = RULES["failpoint-honesty"]
        findings, counts, package = lint_package(rules=[rule])
        declared, _, _ = rule.declared(package)
        assert {"checkpoint.prepare", "checkpoint.commit"} <= declared
        # the UDF plane's sites joined the registry (ISSUE 15)
        assert {"udf.spawn", "udf.call", "udf.reply", "udf.respawn",
                "udf.server.eval"} <= declared
        # the lint's static parse of the literal must agree with the
        # runtime mirror
        assert declared == set(declared_sites())
        assert counts["failpoint-honesty"] == 0, findings

    def test_arming_undeclared_site_refuses(self):
        """Registry hygiene (ISSUE 15 satellite): arming a site that is
        not in the declared registry used to succeed silently and never
        fire — a typo'd test proved nothing, and a future plane could
        add sites the crash-point sweep never iterates. Now it refuses
        loudly, both directly and via the contextmanager."""
        import pytest as _pytest
        from risingwave_tpu.common.failpoint import (
            arm, disarm, failpoints,
        )
        with _pytest.raises(ValueError, match="not a declared site"):
            arm("udf.totally_bogus", OSError)
        with _pytest.raises(ValueError, match="not a declared site"):
            with failpoints(**{"nope.nope": OSError}):
                pass
        # declared sites still arm/disarm fine
        arm("udf.call", OSError, once=True)
        disarm("udf.call")

    def test_meta_store_txn_failpoint_keeps_atomicity(self, tmp_path):
        from risingwave_tpu.common.failpoint import failpoints
        from risingwave_tpu.meta.store import FileMetaStore
        st = FileMetaStore(str(tmp_path / "meta.jsonl"))
        st.put("a", "1")
        with failpoints(**{"meta.store.txn": OSError}):
            with pytest.raises(OSError):
                st.put("b", "2")
        assert st.get("b") is None      # memory agrees with disk
        st2 = FileMetaStore(str(tmp_path / "meta.jsonl"))
        assert st2.get("a") == "1" and st2.get("b") is None


class TestMetaIoChaos:
    def test_meta_fault_rule_hits_meta_link(self, tmp_path):
        from risingwave_tpu.meta.store import FileMetaStore
        install(ChaosSchedule(3, [ChaosRule(kind="meta_fault",
                                            link="meta", count=1)]))
        try:
            st = FileMetaStore(str(tmp_path / "m.jsonl"))
            with pytest.raises(OSError):
                st.put("k", "v")
            st.put("k2", "v2")          # count=1: next txn passes
            assert st.get("k") is None and st.get("k2") == "v2"
            assert plane().injections.get("meta_fault") == 1
        finally:
            install(None)


class TestAuditorUnits:
    def test_sink_exactly_once_detects_dupes_and_loss(self, tmp_path):
        from risingwave_tpu.common.audit import ConsistencyAuditor

        class _Sink:
            def __init__(self, path):
                self.path, self.fmt = path, "jsonl"

        class _Sess:
            def __init__(self, path, rows):
                self._sink = _Sink(path)
                self.catalog = type("C", (), {"sinks": {"s": None},
                                              "mvs": {}})()
                with open(path, "w") as f:
                    for r in rows:
                        f.write(json.dumps(r) + "\n")

            def sink_of(self, name):
                return self._sink

            def flush(self):
                pass

        a = _Sess(str(tmp_path / "a.jsonl"),
                  [{"k": 1, "__op": "insert"}, {"k": 1, "__op": "insert"},
                   {"k": 2, "__op": "insert"}])
        b = _Sess(str(tmp_path / "b.jsonl"),
                  [{"k": 1, "__op": "insert"}, {"k": 2, "__op": "insert"},
                   {"k": 3, "__op": "insert"}])
        res = ConsistencyAuditor(a).check_sink_exactly_once(b)
        assert not res["ok"]
        v = res["violations"]["s"]
        assert v["duplicated"] == 1 and v["lost"] == 1

    def test_audit_green_on_clean_local_session(self):
        from risingwave_tpu.common.audit import ConsistencyAuditor
        from risingwave_tpu.frontend import Session
        s = Session()
        control = Session()
        try:
            for sess in (s, control):
                sess.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, "
                             "v BIGINT)")
                sess.run_sql("CREATE MATERIALIZED VIEW m AS "
                             "SELECT sum(v) AS n FROM t")
                sess.run_sql("INSERT INTO t VALUES (1, 5)")
                sess.run_sql("FLUSH")
            report = ConsistencyAuditor(s).audit(control=control)
            report.assert_ok()
            assert report.checks["mv_parity"]["ok"]
        finally:
            s.close()
            control.close()

    def test_audit_red_on_diverged_mv(self):
        from risingwave_tpu.common.audit import (
            AuditViolation, ConsistencyAuditor,
        )
        from risingwave_tpu.frontend import Session
        s = Session()
        control = Session()
        try:
            for sess, v in ((s, 5), (control, 6)):
                sess.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, "
                             "v BIGINT)")
                sess.run_sql("CREATE MATERIALIZED VIEW m AS "
                             "SELECT sum(v) AS n FROM t")
                sess.run_sql(f"INSERT INTO t VALUES (1, {v})")
                sess.run_sql("FLUSH")
            report = ConsistencyAuditor(s).audit(control=control)
            assert not report.ok and report.failed() == ["mv_parity"]
            with pytest.raises(AuditViolation):
                report.assert_ok()
        finally:
            s.close()
            control.close()


class TestSessionChaosSurface:
    def test_metrics_chaos_section_without_schedule(self):
        from risingwave_tpu.frontend import Session
        s = Session()
        try:
            m = s.metrics()["chaos"]
            assert m["installed"] is False
            assert m["generation"] == 1
            assert m["stale_acks_dropped"] == 0
        finally:
            s.close()

    def test_generation_persists_across_restart(self, tmp_path):
        from risingwave_tpu.frontend import Session
        d = str(tmp_path / "db")
        s = Session(data_dir=d)
        g1 = s._generation
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
        s.run_sql("FLUSH")
        s.close()
        s2 = Session(data_dir=d)
        try:
            assert s2._generation == g1 + 1   # restart = new generation
        finally:
            s2.close()
