"""Device-vectorized batch engine + one-shot batch hash join
(VERDICT r3 item 6): joined SELECTs run as device build/probe/gather
instead of falling back to the streaming fold; TPC-H q3/q10 evaluate as
pure batch plans matching their streaming-MV results.
"""

import datetime as dt

import pytest

from risingwave_tpu.batch.executors import BatchHashJoin
from risingwave_tpu.batch.lower import lower_plan
from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.parser import parse_one
from risingwave_tpu.frontend.planner import Planner


def _lowered(s, sql):
    plan = Planner(s.catalog).plan_select(parse_one(sql).select)
    return lower_plan(plan, s.store)


def _contains_join(ex):
    if ex is None:
        return False
    if isinstance(ex, BatchHashJoin):
        return True
    for attr in ("input", "left", "right", "probe", "build"):
        child = getattr(ex, attr, None)
        if child is not None and _contains_join(child):
            return True
    return False


class TestBatchJoin:
    def _setup(self):
        s = Session()
        s.run_sql("CREATE TABLE c (ck BIGINT PRIMARY KEY, seg VARCHAR)")
        s.run_sql("CREATE TABLE o (ok BIGINT PRIMARY KEY, ck BIGINT, "
                  "amt BIGINT)")
        s.run_sql("INSERT INTO c VALUES (1, 'a'), (2, 'b'), (3, 'a')")
        s.run_sql("INSERT INTO o VALUES (10, 1, 100), (11, 1, 50), "
                  "(12, 2, 70), (13, 9, 1)")
        s.flush()
        return s

    def test_inner_join_lowered_and_correct(self):
        s = self._setup()
        sql = ("SELECT ok, seg, amt FROM o JOIN c ON o.ck = c.ck")
        assert _contains_join(_lowered(s, sql))
        got = sorted(s.run_sql(sql))
        assert got == [(10, "a", 100), (11, "a", 50), (12, "b", 70)]

    def test_build_side_swap_when_right_not_unique(self):
        """Join written with the non-unique side on the right: the inner
        join builds on the LEFT (pk) side instead of falling back."""
        s = self._setup()
        sql = "SELECT seg, amt FROM c JOIN o ON c.ck = o.ck"
        got = sorted(s.run_sql(sql))
        assert got == [("a", 50), ("a", 100), ("b", 70)]

    def test_duplicate_both_sides_bucketed_build(self):
        """Neither side unique: the bucketed (W>1) build serves the full
        cross product per key IN the batch engine — no streaming
        fallback (VERDICT r4 weak #7)."""
        s = Session()
        s.run_sql("CREATE TABLE x (k BIGINT, v BIGINT)")
        s.run_sql("CREATE TABLE y (k BIGINT, w BIGINT)")
        s.run_sql("INSERT INTO x VALUES (1, 1), (1, 2)")
        s.run_sql("INSERT INTO y VALUES (1, 10), (1, 20)")
        s.flush()
        sql = "SELECT v, w FROM x JOIN y ON x.k = y.k"
        lowered = _lowered(s, sql)
        assert _contains_join(lowered)
        got = sorted(tuple(r)[:2] for ch in lowered.execute()
                     for r in ch)     # raw plan carries hidden pk cols
        assert got == [(1, 10), (1, 20), (2, 10), (2, 20)]
        assert sorted(s.run_sql(sql)) == got

    def test_right_and_full_outer_batch(self):
        s = self._setup()
        # right outer: every ORDER row kept, unmatched get NULL customer
        sql = ("SELECT seg, ok FROM c RIGHT JOIN o ON c.ck = o.ck")
        lowered = _lowered(s, sql)
        assert _contains_join(lowered)
        got = sorted(s.run_sql(sql), key=repr)
        assert got == sorted([("a", 10), ("a", 11), ("b", 12),
                              (None, 13)], key=repr)
        # full outer: plus customers with no orders
        sql = "SELECT seg, ok FROM c FULL JOIN o ON c.ck = o.ck"
        lowered = _lowered(s, sql)
        assert _contains_join(lowered)
        got = sorted(s.run_sql(sql), key=repr)
        assert got == sorted([("a", 10), ("a", 11), ("b", 12),
                              (None, 13), ("a", None)], key=repr)

    def test_semi_anti_batch(self):
        s = self._setup()
        sql = ("SELECT ck FROM c WHERE ck IN "
               "(SELECT ck FROM o WHERE amt >= 70)")
        lowered = _lowered(s, sql)
        assert _contains_join(lowered)
        assert sorted(s.run_sql(sql)) == [(1,), (2,)]
        sql = ("SELECT ck FROM c WHERE ck NOT IN "
               "(SELECT ck FROM o WHERE amt >= 70)")
        assert sorted(s.run_sql(sql)) == [(3,)]

    def test_multi_match_left_join_with_condition(self):
        s = self._setup()
        sql = ("SELECT c.ck, o.ok FROM c LEFT JOIN o "
               "ON c.ck = o.ck AND o.amt > 60")
        got = sorted(s.run_sql(sql), key=repr)
        assert got == sorted([(1, 10), (2, 12), (3, None)], key=repr)

    def test_outer_pad_nulls_when_condition_rejects_all(self):
        """A probe row whose key matches but whose every candidate fails
        the non-equi condition pads with NULLs — found-but-rejected lanes
        must not leak their build values."""
        s = Session()
        s.run_sql("CREATE TABLE c (ck BIGINT PRIMARY KEY)")
        s.run_sql("CREATE TABLE o (ok BIGINT PRIMARY KEY, ck BIGINT, "
                  "amt BIGINT)")
        s.run_sql("INSERT INTO c VALUES (1)")
        s.run_sql("INSERT INTO o VALUES (11, 1, 50), (12, 1, 40)")
        s.flush()
        got = s.run_sql("SELECT c.ck, o.ok FROM c LEFT JOIN o "
                        "ON c.ck = o.ck AND o.amt > 60")
        assert got == [(1, None)], got
        s.close()

    def test_full_outer_keeps_null_keyed_build_rows(self):
        """FULL outer must emit build rows whose join key is NULL (they
        can never match, but they exist)."""
        s = Session()
        s.run_sql("CREATE TABLE c (ck BIGINT PRIMARY KEY, seg VARCHAR)")
        s.run_sql("CREATE TABLE o (ok BIGINT PRIMARY KEY, ck BIGINT)")
        s.run_sql("INSERT INTO c VALUES (1, 'a')")
        s.run_sql("INSERT INTO o VALUES (10, 1), (11, NULL)")
        s.flush()
        got = sorted(s.run_sql(
            "SELECT seg, ok FROM c FULL JOIN o ON c.ck = o.ck"), key=repr)
        assert got == sorted([("a", 10), (None, 11)], key=repr), got
        s.close()

    def test_agg_over_join_device_path(self):
        s = self._setup()
        sql = ("SELECT seg, count(*) AS n, sum(amt) AS t "
               "FROM o JOIN c ON o.ck = c.ck GROUP BY seg")
        assert _lowered(s, sql) is not None
        got = sorted(s.run_sql(sql))
        assert got == [("a", 2, 150), ("b", 1, 70)]


class TestTpchBatchSelect:
    """q3/q10 as pure batch SELECTs — results equal the streaming MVs
    (BASELINE.md config 4 'correctness + speedup' batch side)."""

    def _tpch(self):
        import tests.test_tpch as T
        return T

    def test_q3_select_matches_mv(self):
        T = self._tpch()
        s = T._setup()
        q3 = """SELECT o_orderkey, sum(l_extendedprice * (1 - l_discount))
                       AS revenue,
                   o_orderdate, o_shippriority
            FROM customer, orders, lineitem
            WHERE c_mktsegment = 'BUILDING'
              AND c_custkey = o_custkey
              AND l_orderkey = o_orderkey
              AND o_orderdate < DATE '1995-03-15'
              AND l_shipdate > DATE '1995-03-15'
            GROUP BY o_orderkey, o_orderdate, o_shippriority"""
        s.run_sql(f"CREATE MATERIALIZED VIEW q3 AS {q3}")
        s.flush()
        mv = sorted(tuple(r) for r in s.mv_rows("q3"))
        assert _lowered(s, q3) is not None, \
            "q3 must lower to the batch engine (join + agg device path)"
        sel = sorted(tuple(r) for r in s.run_sql(q3))
        assert sel == mv and len(mv) > 0

    def test_q10_select_matches_mv(self):
        T = self._tpch()
        s = T._setup()
        q10 = """SELECT c_custkey, c_name,
                   sum(l_extendedprice * (1 - l_discount)) AS revenue,
                   c_acctbal, n_name
            FROM customer, orders, lineitem, nation
            WHERE c_custkey = o_custkey
              AND l_orderkey = o_orderkey
              AND o_orderdate >= DATE '1993-10-01'
              AND o_orderdate < DATE '1994-01-01'
              AND l_returnflag = 'R'
              AND c_nationkey = n_nationkey
            GROUP BY c_custkey, c_name, c_acctbal, n_name"""
        s.run_sql(f"CREATE MATERIALIZED VIEW q10 AS {q10}")
        s.flush()
        mv = sorted(tuple(r) for r in s.mv_rows("q10"))
        sel = sorted(tuple(r) for r in s.run_sql(q10))
        assert sel == mv and len(mv) > 0
