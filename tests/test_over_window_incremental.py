"""Incremental over-window maintenance: per-barrier work must scale with
the DELTA, not the partition size (VERDICT r4 weak #6 / item 8; reference:
src/stream/src/executor/over_window/delta_btree_map.rs). The executor
exposes ``positions_recomputed`` so the microbench asserts the actual
recompute volume, not wall clock."""

import asyncio

from risingwave_tpu.common import make_chunk
from risingwave_tpu.common.types import Field, INT64, Schema, TIMESTAMP
from risingwave_tpu.ops.topn import OrderSpec
from risingwave_tpu.stream.over_window import OverWindowExecutor, WindowCall


SCHEMA = Schema((Field("k", INT64), Field("ts", TIMESTAMP),
                 Field("v", INT64), Field("id", INT64)))


class _ScriptSource:
    def __init__(self, schema):
        self.schema = schema
        self.script: list = []

    async def execute(self):
        for m in self.script:
            yield m


def _mk(rows):
    return make_chunk(SCHEMA, rows, capacity=max(8, len(rows)))


def _calls():
    order = (OrderSpec(1, False, True),)
    return (
        WindowCall("row_number", INT64, partition_by=(0,), order_by=order),
        WindowCall("sum", INT64, arg=2, partition_by=(0,), order_by=order),
        WindowCall("lag", INT64, arg=2, offset=1, partition_by=(0,),
                   order_by=order),
    )


def _drive(ex, src, script):
    src.script = script
    out = []

    async def run():
        async for m in ex.execute():
            out.append(m)

    asyncio.run(run())
    return out


def test_incremental_appends_do_not_rescan_partition():
    """Append k in-order rows per barrier to one hot partition of size N:
    recompute volume per barrier must stay O(k), independent of N."""
    from risingwave_tpu.stream.message import Barrier

    src = _ScriptSource(SCHEMA)
    ex = OverWindowExecutor(src, _calls(), pk_indices=(3,))
    n0 = 2048
    base = [(1, i * 10, i, i) for i in range(n0)]
    script = [Barrier.new(1), _mk(base), Barrier.new(2)]
    _drive(ex, src, script)
    assert ex.positions_recomputed >= n0       # initial build pays O(N)

    # steady state: 8 in-order rows per barrier
    ex.positions_recomputed = 0
    deltas = []
    for b in range(8):
        rows = [(1, (n0 + b * 8 + j) * 10, 1, n0 + b * 8 + j)
                for j in range(8)]
        script = [_mk(rows), Barrier.new(3 + b)]
        _drive(ex, src, script)
        deltas.append(ex.positions_recomputed)
        ex.positions_recomputed = 0
    # each barrier recomputes the appended rows + O(1) peer/lead slack —
    # nowhere near the 2048-row partition
    assert max(deltas) <= 8 + 4, deltas


def test_varchar_order_keys_survive_dictionary_growth():
    """Stored sort keys must not go stale when later barriers intern new
    strings (string keys compare by content, not by mutable rank)."""
    from risingwave_tpu.common.chunk import OP_DELETE
    from risingwave_tpu.common.types import VARCHAR
    from risingwave_tpu.stream.message import Barrier

    schema = Schema((Field("k", INT64), Field("s", VARCHAR),
                     Field("id", INT64)))
    src = _ScriptSource(schema)
    order = (OrderSpec(1, False, True, is_string=True),)
    calls = (WindowCall("row_number", INT64, partition_by=(0,),
                        order_by=order),)
    ex = OverWindowExecutor(src, calls, pk_indices=(2,))

    def mk(rows, ops=None):
        return make_chunk(schema, rows, ops=ops, capacity=8)

    _drive(ex, src, [Barrier.new(1), mk([(1, "mango", 1), (1, "pear", 2)]),
                     Barrier.new(2)])
    # interning 'apple' renumbers lexicographic ranks of existing strings
    _drive(ex, src, [mk([(1, "apple", 3)]), Barrier.new(3)])
    # delete the row whose rank shifted — must still be found and retracted
    _drive(ex, src, [mk([(1, "mango", 1)], ops=[OP_DELETE]),
                     Barrier.new(4)])
    got = {pk[0]: vals for pk, (_, vals) in ex._out[(1,)].items()}
    assert got == {3: (1,), 2: (2,)}, got


def test_bare_insert_upserts_live_pk():
    """A bare INSERT for a live pk replaces its row — including a move to
    a DIFFERENT partition — instead of leaving two live entries (the
    pre-incremental executor's upsert contract)."""
    from risingwave_tpu.common.chunk import OP_INSERT
    from risingwave_tpu.stream.message import Barrier

    src = _ScriptSource(SCHEMA)
    ex = OverWindowExecutor(src, _calls(), pk_indices=(3,))
    _drive(ex, src, [Barrier.new(1),
                     _mk([(1, 10, 5, 1), (1, 20, 7, 2)]), Barrier.new(2)])
    # same partition, new order key
    _drive(ex, src, [_mk([(1, 30, 9, 1)], ), Barrier.new(3)])
    got = {pk[0]: vals for pk, (_, vals) in ex._out[(1,)].items()}
    assert got == {2: (1, 7, None), 1: (2, 16, 7)}, got
    # move pk 2 to partition 9: old partition must retract it
    _drive(ex, src, [_mk([(9, 5, 1, 2)]), Barrier.new(4)])
    got1 = {pk[0]: vals for pk, (_, vals) in ex._out[(1,)].items()}
    got9 = {pk[0]: vals for pk, (_, vals) in ex._out[(9,)].items()}
    assert got1 == {1: (1, 9, None)}, got1
    assert got9 == {2: (1, 1, None)}, got9


def test_incremental_matches_full_recompute_under_churn():
    """Random out-of-order inserts and deletes: the incremental outputs
    must equal the full-recompute host model after every barrier."""
    import random

    from risingwave_tpu.stream.message import Barrier
    from risingwave_tpu.stream.over_window import compute_window_values

    rng = random.Random(7)
    src = _ScriptSource(SCHEMA)
    ex = OverWindowExecutor(src, _calls(), pk_indices=(3,))
    live: dict = {}
    next_id = 0
    epoch = 1
    _drive(ex, src, [Barrier.new(epoch)])
    for _ in range(12):
        ops, rows = [], []
        for _ in range(rng.randrange(1, 6)):
            if live and rng.random() < 0.35:
                rid = rng.choice(list(live))
                from risingwave_tpu.common.chunk import OP_DELETE
                ops.append(OP_DELETE)
                rows.append(live.pop(rid))
            else:
                r = (rng.randrange(2), rng.randrange(50) * 7,
                     rng.randrange(100), next_id)
                live[next_id] = r
                next_id += 1
                from risingwave_tpu.common.chunk import OP_INSERT
                ops.append(OP_INSERT)
                rows.append(r)
        epoch += 1
        ch = make_chunk(SCHEMA, rows, ops=ops, capacity=max(8, len(rows)))
        _drive(ex, src, [ch, Barrier.new(epoch)])
        # compare executor cache against the independent full model
        for part in ({(r[0],) for r in live.values()}
                     | set(ex._out.keys())):
            part_rows = [r for r in live.values() if r[0] == part[0]]
            expect = compute_window_values(part_rows, _calls(), (3,))
            got = {pk[0]: vals
                   for pk, (_, vals) in ex._out.get(part, {}).items()}
            expect_keyed = {pk[0]: v for pk, v in expect.items()}
            assert got.keys() == set(expect_keyed.keys())
            for pk, v in expect_keyed.items():
                assert got[pk] == v, (part, pk, got[pk], v)
