"""ISSUE 3 satellite: a worker wedged before replying (SIGSTOP — socket
open, no frames) must trip the epoch deadline + heartbeat-TTL scoped
recovery instead of deadlocking ``wait_epoch``/``handle_create_job``
forever."""

import os
import signal

import pytest

from risingwave_tpu.common.config import FaultConfig
from risingwave_tpu.frontend import Session


@pytest.mark.slow
def test_wedged_worker_trips_scoped_recovery(tmp_path):
    s = Session(data_dir=str(tmp_path / "db"), workers=1,
                checkpoint_frequency=2,
                fault_config=FaultConfig(worker_epoch_timeout_s=2.0,
                                         worker_request_timeout_s=60.0))
    try:
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT sum(v) AS n FROM t")        # worker-hosted
        s.run_sql("INSERT INTO t VALUES (1, 10)")
        s.run_sql("FLUSH")
        assert s.mv_rows("m") == [(10,)]

        w = s.workers[0]
        wedged_pid = w.proc.pid
        os.kill(wedged_pid, signal.SIGSTOP)           # wedged, not dead

        # barriers keep completing: the epoch deadline declares the
        # worker failed (fail-stop) and the TTL detector recovers the job
        # on subsequent ticks — none of these calls may hang
        s.run_sql("INSERT INTO t VALUES (2, 5)")
        recovered = False
        for _ in range(12):
            s.tick()
            if not w.dead and w.proc.pid != wedged_pid:
                recovered = True
                break
        assert recovered, "worker was not respawned after wedging"
        s.run_sql("FLUSH")
        assert s.mv_rows("m") == [(15,)]              # nothing lost
    finally:
        s.close()


def test_request_timeout_raises_instead_of_hanging(tmp_path):
    """A control request against a wedged worker raises WorkerDied after
    the configured deadline (short here) rather than awaiting forever."""
    import pytest

    from risingwave_tpu.frontend.remote import WorkerDied
    s = Session(data_dir=str(tmp_path / "db"), workers=1,
                fault_config=FaultConfig(worker_request_timeout_s=1.5,
                                         worker_epoch_timeout_s=2.0))
    try:
        w = s.workers[0]
        os.kill(w.proc.pid, signal.SIGSTOP)
        with pytest.raises(WorkerDied, match="timed out"):
            s._await(w.request({"type": "scan", "name": "nope"}))
        assert w.dead
    finally:
        s.close()
