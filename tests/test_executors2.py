"""SimpleAgg / StatelessSimpleAgg / TopN / DynamicFilter executor tests —
chunk-in/chunk-out against MockSource, the reference's executor test style
(src/stream/src/executor/{simple_agg,top_n/*,dynamic_filter}.rs tests)."""

import asyncio

from risingwave_tpu.common import (
    INT64, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
    Schema, chunk_to_rows, make_chunk,
)
from risingwave_tpu.expr.agg import agg, count_star
from risingwave_tpu.ops.topn import OrderSpec
from risingwave_tpu.storage import MemoryStateStore, StateTable
from risingwave_tpu.stream import (
    Barrier, DynamicFilterExecutor, MockSource, SimpleAggExecutor,
    StatelessSimpleAggExecutor, TopNExecutor, is_chunk, wrap_debug,
)

KV = Schema.of(("k", INT64), ("v", INT64))


def run(coro):
    return asyncio.run(coro)


async def drain(executor):
    chunks, barriers = [], []
    async for msg in executor.execute():
        if is_chunk(msg):
            chunks.append(msg)
        elif isinstance(msg, Barrier):
            barriers.append(msg)
    return chunks, barriers


def rows_of(chunks, schema):
    out = []
    for c in chunks:
        out.extend(chunk_to_rows(c, schema, with_ops=True))
    return out


def apply_deltas(rows):
    """Fold a change stream into the final multiset of rows."""
    acc: dict = {}
    for op, row in rows:
        if op in (OP_INSERT, OP_UPDATE_INSERT):
            acc[row] = acc.get(row, 0) + 1
        else:
            acc[row] = acc.get(row, 0) - 1
            if acc[row] == 0:
                del acc[row]
    assert all(v > 0 for v in acc.values()), acc
    return sorted(acc)


# ---------------------------------------------------------------------------
# SimpleAgg
# ---------------------------------------------------------------------------


def test_simple_agg_initial_row_then_updates():
    src = MockSource(KV, [
        Barrier.new(1),
        Barrier.new(2),  # no data yet: initial row must still appear
        make_chunk(KV, [(1, 10), (2, 20)]),
        Barrier.new(3),
        make_chunk(KV, [(1, 10)], ops=[OP_DELETE]),
        Barrier.new(4),
    ])
    ex = SimpleAggExecutor(src, [count_star(), agg("sum", 1, INT64),
                                 agg("min", 1, INT64)])
    chunks, _ = run(drain(wrap_debug(ex)))
    rows = rows_of(chunks, ex.schema)
    # first flush: count 0, sum NULL, min NULL
    assert rows[0] == (OP_INSERT, (0, None, None))
    assert (OP_UPDATE_INSERT, (2, 30, 10)) in rows
    # retraction: count/sum exact; min keeps append-only bound (10) — the
    # reference needs MaterializedInput state for exact min under retraction
    assert rows[-1][1][0] == 1 and rows[-1][1][1] == 20
    assert apply_deltas(rows)[0][0] == 1


def test_simple_agg_checkpoint_recovery():
    store = MemoryStateStore()
    from risingwave_tpu.common.types import Field

    def make_table():
        from risingwave_tpu.stream.simple_agg import simple_agg_state_schema
        schema = simple_agg_state_schema([count_star(), agg("sum", 1, INT64)])
        return StateTable(store, 7, schema, [0])

    src = MockSource(KV, [
        Barrier.new(1),
        make_chunk(KV, [(1, 10), (2, 32)]),
        Barrier.new(2, checkpoint=True),
    ])
    ex = SimpleAggExecutor(src, [count_star(), agg("sum", 1, INT64)],
                           state_table=make_table())
    run(drain(ex))
    store.commit(2)  # the barrier conductor's sync_epoch commit

    src2 = MockSource(KV, [
        Barrier.new(3),
        make_chunk(KV, [(3, 8)]),
        Barrier.new(4),
    ])
    ex2 = SimpleAggExecutor(src2, [count_star(), agg("sum", 1, INT64)],
                            state_table=make_table())
    chunks, _ = run(drain(ex2))
    rows = rows_of(chunks, ex2.schema)
    # recovered (2, 42); no initial insert (already emitted pre-failure);
    # the only flush is the update to (3, 50)
    assert rows == [(OP_UPDATE_DELETE, (2, 42)), (OP_UPDATE_INSERT, (3, 50))]


def test_stateless_simple_agg_partials():
    src = MockSource(KV, [
        Barrier.new(1),
        make_chunk(KV, [(1, 10), (2, 20)]),
        make_chunk(KV, [(1, 5)], ops=[OP_DELETE]),
        Barrier.new(2),
    ])
    ex = StatelessSimpleAggExecutor(src, [count_star(), agg("sum", 1, INT64)])
    chunks, _ = run(drain(wrap_debug(ex)))
    rows = rows_of(chunks, ex.schema)
    assert rows == [(OP_INSERT, (2, 30)), (OP_INSERT, (-1, -5))]


# ---------------------------------------------------------------------------
# TopN
# ---------------------------------------------------------------------------

# pk = column 0 (k); order by v
TOPN_IN = Schema.of(("k", INT64), ("v", INT64))


def topn(src, limit, offset=0, order=None, **kw):
    return TopNExecutor(
        src, order or [OrderSpec(1)], offset, limit, pk_indices=[0],
        table_capacity=1 << 10, **kw)


def test_topn_basic_insert_evict():
    src = MockSource(TOPN_IN, [
        Barrier.new(1),
        make_chunk(TOPN_IN, [(1, 50), (2, 30), (3, 40)]),
        Barrier.new(2),
        make_chunk(TOPN_IN, [(4, 10)]),   # evicts (1, 50) from top-3... no, top-2
        Barrier.new(3),
    ])
    ex = topn(src, limit=2)
    chunks, _ = run(drain(wrap_debug(ex)))
    rows = rows_of(chunks, ex.schema)
    # epoch2: top2 = {(2,30),(3,40)}
    assert apply_deltas(rows[:2]) == [(2, 30), (3, 40)]
    # epoch3: (4,10) enters, (3,40) leaves
    assert apply_deltas(rows) == [(2, 30), (4, 10)]


def test_topn_delete_backfills_from_below():
    src = MockSource(TOPN_IN, [
        Barrier.new(1),
        make_chunk(TOPN_IN, [(1, 10), (2, 20), (3, 30), (4, 40)]),
        Barrier.new(2),
        make_chunk(TOPN_IN, [(1, 10)], ops=[OP_DELETE]),
        Barrier.new(3),
    ])
    ex = topn(src, limit=2)
    chunks, _ = run(drain(wrap_debug(ex)))
    rows = rows_of(chunks, ex.schema)
    assert apply_deltas(rows) == [(2, 20), (3, 30)]


def test_topn_offset_and_update():
    src = MockSource(TOPN_IN, [
        Barrier.new(1),
        make_chunk(TOPN_IN, [(1, 10), (2, 20), (3, 30), (4, 40)]),
        Barrier.new(2),
        # update pk=1: 10 -> 99; window [1, 3) shifts
        make_chunk(TOPN_IN, [(1, 10), (1, 99)],
                   ops=[OP_UPDATE_DELETE, OP_UPDATE_INSERT]),
        Barrier.new(3),
    ])
    ex = topn(src, limit=2, offset=1)
    chunks, _ = run(drain(wrap_debug(ex)))
    rows = rows_of(chunks, ex.schema)
    # epoch2: sorted = 10,20,30,40 -> window = {20, 30}
    assert apply_deltas(rows[:2]) == [(2, 20), (3, 30)]
    # epoch3: sorted = 20,30,40,99 -> window = {30, 40}
    assert apply_deltas(rows) == [(3, 30), (4, 40)]


def test_topn_desc_with_ties():
    src = MockSource(TOPN_IN, [
        Barrier.new(1),
        make_chunk(TOPN_IN, [(1, 50), (2, 50), (3, 40), (4, 50), (5, 60)]),
        Barrier.new(2),
    ])
    ex = topn(src, limit=2, order=[OrderSpec(1, desc=True)], with_ties=True)
    chunks, _ = run(drain(wrap_debug(ex)))
    rows = rows_of(chunks, ex.schema)
    # top-2 desc = 60, 50 — and all three 50s tie in
    assert apply_deltas(rows) == [(1, 50), (2, 50), (4, 50), (5, 60)]


GROUP_IN = Schema.of(("g", INT64), ("k", INT64), ("v", INT64))


def test_group_topn():
    src = MockSource(GROUP_IN, [
        Barrier.new(1),
        make_chunk(GROUP_IN, [
            (1, 1, 30), (1, 2, 10), (1, 3, 20),
            (2, 4, 5), (2, 5, 50),
        ]),
        Barrier.new(2),
        make_chunk(GROUP_IN, [(2, 6, 1)]),
        Barrier.new(3),
    ])
    ex = TopNExecutor(src, [OrderSpec(2)], 0, 2, pk_indices=[1],
                      group_by=[0], table_capacity=1 << 10)
    chunks, _ = run(drain(wrap_debug(ex)))
    rows = rows_of(chunks, ex.schema)
    assert apply_deltas(rows[:4]) == [
        (1, 2, 10), (1, 3, 20), (2, 4, 5), (2, 5, 50)]
    assert apply_deltas(rows) == [
        (1, 2, 10), (1, 3, 20), (2, 4, 5), (2, 6, 1)]


def test_topn_checkpoint_recovery():
    store = MemoryStateStore()

    def make_table():
        return StateTable(store, 11, TOPN_IN, [0])

    src = MockSource(TOPN_IN, [
        Barrier.new(1),
        make_chunk(TOPN_IN, [(1, 10), (2, 20), (3, 30)]),
        Barrier.new(2, checkpoint=True),
    ])
    ex = topn(src, limit=2, state_table=make_table())
    chunks1, _ = run(drain(ex))
    store.commit(2)  # the barrier conductor's sync_epoch commit

    src2 = MockSource(TOPN_IN, [
        Barrier.new(3),
        make_chunk(TOPN_IN, [(1, 10)], ops=[OP_DELETE]),
        Barrier.new(4),
    ])
    ex2 = topn(src2, limit=2, state_table=make_table())
    chunks2, _ = run(drain(ex2))
    rows = rows_of(chunks1, ex.schema) + rows_of(chunks2, ex2.schema)
    assert apply_deltas(rows) == [(2, 20), (3, 30)]


# ---------------------------------------------------------------------------
# DynamicFilter
# ---------------------------------------------------------------------------

RHS = Schema.of(("bound", INT64))


def test_dynamic_filter_retroactive_emission():
    left = MockSource(KV, [
        Barrier.new(1),
        make_chunk(KV, [(1, 10), (2, 20), (3, 30)]),
        Barrier.new(2),
        Barrier.new(3),
    ])
    right = MockSource(RHS, [
        Barrier.new(1),
        make_chunk(RHS, [(15,)]),
        Barrier.new(2),
        # bound moves 15 -> 25: row (2,20) must retro-delete
        make_chunk(RHS, [(15,), (25,)],
                   ops=[OP_UPDATE_DELETE, OP_UPDATE_INSERT]),
        Barrier.new(3),
    ])
    ex = DynamicFilterExecutor(left, right, key_col=1, cmp="greater_than",
                               pk_indices=[0], table_capacity=1 << 10)
    chunks, _ = run(drain(ex))
    rows = rows_of(chunks, ex.schema)
    assert apply_deltas(rows[:2]) == [(2, 20), (3, 30)]
    assert (OP_DELETE, (2, 20)) in rows
    assert apply_deltas(rows) == [(3, 30)]


def test_dynamic_filter_no_bound_passes_nothing():
    left = MockSource(KV, [
        Barrier.new(1),
        make_chunk(KV, [(1, 10)]),
        Barrier.new(2),
    ])
    right = MockSource(RHS, [Barrier.new(1), Barrier.new(2)])
    ex = DynamicFilterExecutor(left, right, key_col=1, cmp="less_than",
                               pk_indices=[0], table_capacity=1 << 10)
    chunks, _ = run(drain(ex))
    assert rows_of(chunks, ex.schema) == []


def test_dynamic_filter_lhs_delete_and_bound_move():
    left = MockSource(KV, [
        Barrier.new(1),
        make_chunk(KV, [(1, 10), (2, 20)]),
        Barrier.new(2),
        make_chunk(KV, [(2, 20)], ops=[OP_DELETE]),
        Barrier.new(3),
    ])
    right = MockSource(RHS, [
        Barrier.new(1),
        make_chunk(RHS, [(5,)]),
        Barrier.new(2),
        Barrier.new(3),
    ])
    ex = DynamicFilterExecutor(left, right, key_col=1, cmp="greater_than",
                               pk_indices=[0], table_capacity=1 << 10)
    chunks, _ = run(drain(ex))
    rows = rows_of(chunks, ex.schema)
    assert apply_deltas(rows) == [(1, 10)]
