"""Meta-snapshot backup/restore (VERDICT r4 missing #8; reference:
src/meta/src/backup_restore/backup_manager.rs, src/storage/backup/)."""

import os
import subprocess
import sys
import tempfile

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.storage.backup import (
    BackupError, create_backup, list_backup, restore_backup,
)


def _populate(data):
    s = Session(data_dir=data)
    s.run_sql("CREATE TABLE t (id BIGINT PRIMARY KEY, v BIGINT)")
    s.run_sql("CREATE MATERIALIZED VIEW m AS "
              "SELECT count(*) AS n, sum(v) AS sv FROM t")
    s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    s.tick()
    s.run_sql("FLUSH")
    rows = s.mv_rows("m")
    s.close()
    return rows


def test_backup_restore_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "data")
        bak = os.path.join(d, "bak")
        restored = os.path.join(d, "restored")
        before = _populate(data)

        desc = create_backup(data, bak)
        assert desc["committed_epoch"] is not None
        assert "manifest.json" in desc["files"]
        assert any(f.endswith(".seg") for f in desc["files"])
        assert list_backup(bak)["backup_id"] == desc["backup_id"]

        restore_backup(bak, restored)
        s = Session(data_dir=restored)
        assert s.mv_rows("m") == before
        # the restored cluster is fully live: writes keep flowing
        s.run_sql("INSERT INTO t VALUES (4, 40)")
        s.tick()
        assert s.mv_rows("m") == [(4, 100)]
        s.close()

        # and the ORIGINAL is untouched by the restored cluster's writes
        s0 = Session(data_dir=data)
        assert s0.mv_rows("m") == before
        s0.close()


def test_backup_after_restore_divergence_and_preconditions():
    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "data")
        bak = os.path.join(d, "bak")
        _populate(data)
        create_backup(data, bak)
        with pytest.raises(BackupError):
            create_backup(data, bak)          # double-backup refused
        with pytest.raises(BackupError):
            restore_backup(bak, data)         # non-empty target refused
        with pytest.raises(BackupError):
            list_backup(data)                 # not a backup dir


def test_backup_excludes_orphan_segments():
    """A torn-publish orphan segment (present on disk, absent from the
    manifest) must not be captured — the snapshot is the manifest's
    version, like the reference excluding unreferenced SSTs."""
    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "data")
        bak = os.path.join(d, "bak")
        _populate(data)
        orphan = os.path.join(data, "epoch_999999.seg")
        with open(orphan, "wb") as f:
            f.write(b"torn")
        desc = create_backup(data, bak)
        assert "epoch_999999.seg" not in desc["files"]
        assert not os.path.exists(os.path.join(bak, "epoch_999999.seg"))


def test_ctl_backup_cli():
    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "data")
        bak = os.path.join(d, "bak")
        _populate(data)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("TPU_LIBRARY_PATH", None)
        r = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu", "ctl", "backup",
             "--data-dir", data, "--backup-dir", bak],
            capture_output=True, text=True, env=env, timeout=120,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert r.returncode == 0, r.stderr[-500:]
        assert "backup_id" in r.stdout
