"""Network fault plane integration (ISSUE 9 acceptance): seeded
deterministic netsplits over a live 2-worker cluster, the crash-point
sweep over every registered failpoint, and the ConsistencyAuditor
asserting exactly-once after each run.

Everything here spawns real worker processes and rides real recovery
cycles, so the whole module is ``slow`` — scripts/check.sh runs the
chaos subset (a fast scenario + a bounded sweep) on every CI pass, and
this module is the full acceptance surface:

  * a seeded schedule partitioning ONE exchange edge of a spanning
    2-worker q5 graph for 3 epochs mid-stream converges to bit-exact MV
    parity with a no-chaos control (scoped recovery + replay + fencing);
  * re-running any seed reproduces the identical per-link injection
    trace (the FoundationDB-style repro property);
  * duplicated + reordered exchange frames are absorbed by the seq
    layer with NO recovery needed (at-least-once → exactly-once);
  * duplicated batch_task/scan replies stay exactly-once at the caller
    (rid dedup), including through the serving plane's two-phase path;
  * the crash-point sweep dies at every registered failpoint site —
    including BOTH 2PC checkpoint phases inside worker processes — and
    the auditor passes after every recovery.
"""

import json
import tempfile

import pytest

from risingwave_tpu.sim import (
    NETSPLIT_SCENARIOS, crash_point_sweep, crash_point_sweep_spanning,
    run_netsplit,
)

pytestmark = pytest.mark.slow


class TestNetsplitScenarios:
    def test_q5_exchange_partition_converges_exactly_once(self):
        """THE acceptance run: partition one exchange edge of the
        spanning 2-worker q5 graph for 3 epochs mid-stream; the epoch
        deadline declares the starved graph dead, scoped recovery
        rebuilds it from per-worker durable state (riding out recovery
        attempts made while the window is still open), sources replay,
        and the MV is bit-exact vs a no-chaos control with the full
        auditor green."""
        r = run_netsplit("q5_exchange_partition", seed=7,
                         data_dir=tempfile.mkdtemp())
        assert r["recovered"] is True
        assert all(r["audit"].values()), r["audit"]
        assert r["rows"] > 0
        # the partition actually injected on the targeted link (trace
        # keys are per-channel streams of that link), and the fencing
        # generation advanced through the scoped recoveries
        assert sum(len(v) for v in r["trace"].values()) > 0
        assert all(k.startswith("w0->w1") for k in r["trace"])
        assert r["chaos"]["generation"] > 1

    def test_seeded_replay_reproduces_identical_trace(self):
        """Replay property: same (scenario, seed) → identical per-link
        injection trace; a different seed draws differently."""
        r1 = run_netsplit("exchange_dup_reorder", seed=7,
                          data_dir=tempfile.mkdtemp())
        r2 = run_netsplit("exchange_dup_reorder", seed=7,
                          data_dir=tempfile.mkdtemp())
        assert r1["trace"] == r2["trace"]
        assert sum(len(v) for v in r1["trace"].values()) > 0
        r3 = run_netsplit("exchange_dup_reorder", seed=13,
                          data_dir=tempfile.mkdtemp())
        assert r3["trace"] != r1["trace"]

    def test_dup_reorder_absorbed_without_recovery(self):
        """Duplicated and frame-delayed exchange traffic is healed by
        the per-channel seq layer alone: bit-exact MV, no recovery, no
        barrier-epoch regressions."""
        r = run_netsplit("exchange_dup_reorder", seed=7,
                         data_dir=tempfile.mkdtemp())
        assert r["recovered"] is False
        assert all(r["audit"].values()), r["audit"]
        inj = {}
        for wc in r["chaos"].get("workers", {}).values():
            for k, n in (wc.get("injections") or {}).items():
                inj[k] = inj.get(k, 0) + n
        assert inj.get("duplicate", 0) > 0
        assert inj.get("delay", 0) > 0

    def test_ack_delay_backpressures_not_breaks(self):
        r = run_netsplit("ack_delay", seed=7,
                         data_dir=tempfile.mkdtemp())
        assert r["recovered"] is False
        assert all(r["audit"].values()), r["audit"]

    def test_dup_batch_reply_stays_exactly_once(self):
        """Every worker→session reply duplicated on the wire: rid dedup
        keeps scan results and the serving plane's two-phase batch_task
        answers exactly-once (query result equals the control's)."""
        r = run_netsplit("dup_batch_reply", seed=7,
                         data_dir=tempfile.mkdtemp())
        assert r["query_ok"] is True
        assert all(r["audit"].values()), r["audit"]
        assert r["chaos"]["dup_replies_dropped"] > 0

    def test_scenarios_registry_is_json_replayable(self):
        from risingwave_tpu.rpc.faults import ChaosSchedule
        from risingwave_tpu.sim import netsplit_schedule
        for name in NETSPLIT_SCENARIOS:
            s = netsplit_schedule(name, seed=5)
            assert ChaosSchedule.from_json(s.to_json()).to_json() \
                == s.to_json()


class TestCrashPointSweep:
    def test_full_sweep_audits_green(self):
        """Die at EVERY registered failpoint site over the durable
        workload (hummock tier for storage sites, segment otherwise),
        recover, and pass the ConsistencyAuditor each time. Sites the
        workload cannot reach (worker-resident 2PC phases, compaction
        that never scheduled) report not_hit honestly — the 2PC phases
        get their own spanning sweep below."""
        from risingwave_tpu.common.failpoint import registered_sites
        res = crash_point_sweep(tempfile.mkdtemp(), seed=0)
        assert set(res) == set(registered_sites())
        hit = [s for s, r in res.items() if r["hit"]]
        assert len(hit) >= 8, f"too few sites exercised: {hit}"
        for site, r in res.items():
            if r["hit"]:
                assert r["audit"] == "ok", (site, r)
                assert r["kills"] >= 1, (site, r)

    def test_spanning_2pc_phases_die_and_roll_correctly(self):
        """Kill worker 1 with a REAL process exit at each 2PC phase of
        a spanning checkpoint: prepare-death discards the undecided
        epoch (replay from the previous cut), settle-death rolls the
        prepared epoch forward (the cluster decided it) — both converge
        bit-exact and audit green."""
        res = crash_point_sweep_spanning(tempfile.mkdtemp())
        assert res["checkpoint.prepare"]["hit"]
        assert res["checkpoint.prepare"]["rolled_forward"] is False
        assert res["checkpoint.settle"]["hit"]
        assert res["checkpoint.settle"]["rolled_forward"] is True
        for r in res.values():
            assert r["audit"] == "ok"


class TestChaosCli:
    def test_cli_replay_smoke(self):
        """The documented replay entry point: run a cheap scenario twice
        via the module CLI and assert trace equality (ack_delay's
        per-channel ack streams are fully deterministic)."""
        from risingwave_tpu.sim import main
        assert main(["--netsplit", "ack_delay", "--seed", "3",
                     "--replay"]) == 0
