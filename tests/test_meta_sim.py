"""Meta components (cluster/notification/fragmenter) + deterministic
chaos simulation (coverage #48/#50/#51/#54/#66 + missing item 9)."""

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.parser import parse_one
from risingwave_tpu.frontend.planner import Planner
from risingwave_tpu.meta import (
    ClusterManager, FragmentManager, NotificationManager, fragment_plan,
)
from risingwave_tpu.sim import SimCluster


class TestClusterManager:
    def test_heartbeat_ttl_failure_detector(self):
        now = [0.0]
        cm = ClusterManager(heartbeat_ttl_s=10, clock=lambda: now[0])
        failures = []
        cm.on_failure(failures.append)
        w1 = cm.add_worker("host-a", 4)
        w2 = cm.add_worker("host-b", 4)
        assert cm.total_parallelism == 8

        now[0] = 5.0
        cm.heartbeat(w1.worker_id)
        now[0] = 12.0                       # w2 silent past TTL
        expired = cm.check_heartbeats()
        assert [w.worker_id for w in expired] == [w2.worker_id]
        assert failures and failures[0].worker_id == w2.worker_id
        assert cm.total_parallelism == 4

        # a late heartbeat rejoins the worker
        cm.heartbeat(w2.worker_id)
        assert cm.total_parallelism == 8
        assert cm.check_heartbeats() == []


class TestNotification:
    def test_versioned_push_and_catchup(self):
        nm = NotificationManager()
        seen = []
        nm.notify("catalog", {"create": "t1"})
        nm.notify("catalog", {"create": "t2"})
        nm.notify("hummock", {"epoch": 5})
        # late subscriber catches up from version 0, then gets live pushes
        v = nm.subscribe("catalog", lambda ver, info: seen.append((ver, info)))
        assert v == 3
        assert seen == [(1, {"create": "t1"}), (2, {"create": "t2"})]
        nm.notify("catalog", {"drop": "t1"})
        assert seen[-1] == (4, {"drop": "t1"})


class TestFragmenter:
    def _plan(self, s, sql):
        stmt = parse_one(sql)
        return Planner(s.catalog).plan_select(stmt.query.select
                                              if hasattr(stmt, "query")
                                              else stmt.select)

    def test_agg_join_cut_points(self):
        s = Session()
        s.run_sql("CREATE TABLE a (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE TABLE b (k BIGINT PRIMARY KEY, w BIGINT)")
        plan = self._plan(
            s, "SELECT a.k, sum(w) FROM a JOIN b ON a.k = b.k GROUP BY a.k")
        g = fragment_plan(plan)
        # join cuts both inputs; agg cuts its input; + root = >= 4 fragments
        assert len(g.fragments) >= 4
        kinds = {f.distribution for f in g.fragments.values()}
        assert "source" in kinds
        fm = FragmentManager()
        fm.register("mv1", g)
        assert fm.all_jobs() == ["mv1"]
        assert "Fragment" in g.explain()
        fm.drop("mv1")
        assert fm.all_jobs() == []


class TestChaosSim:
    @pytest.mark.parametrize("seed", [1, 7])
    def test_chaos_converges_to_control(self, tmp_path, seed):
        """Seeded kills + client-retry DML: the chaos session's MVs must
        converge to a never-killed control session."""
        chaos = SimCluster(str(tmp_path / f"chaos{seed}"), seed=seed,
                           kill_rate=0.5)
        control = Session()

        ddl = [
            "CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)",
            "CREATE MATERIALIZED VIEW s AS SELECT sum(v) AS n FROM t",
            "CREATE MATERIALIZED VIEW g AS "
            "SELECT k % 3 AS grp, count(*) AS c FROM t GROUP BY k % 3",
        ]
        for stmt in ddl:
            chaos.run_sql(stmt)
            control.run_sql(stmt)
        chaos.flush()

        import random as _r
        data_rng = _r.Random(99)
        for step in range(12):
            k = step
            v = data_rng.randint(0, 100)
            sql = f"INSERT INTO t VALUES ({k}, {v})"
            chaos.run_sql(sql)
            control.run_sql(sql)
            if step % 3 == 2:
                chaos.flush()
                control.flush()
            chaos.maybe_kill()
        chaos.verify_against(control)
        assert chaos.kills > 0          # the harness actually killed


class TestChaosSimWorkers:
    @pytest.mark.slow
    def test_per_component_worker_kills_converge(self, tmp_path):
        """Chaos with worker PROCESSES: the kill step SIGKILLs individual
        workers (scoped recovery) as well as the whole cluster; MVs still
        converge to the never-killed control (VERDICT r4 weak #8 —
        per-component kills, madsim cluster.rs:498-510)."""
        chaos = SimCluster(str(tmp_path / "chaosw"), seed=3,
                           kill_rate=0.6, workers=1)
        control = Session()
        ddl = [
            "CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)",
            "CREATE MATERIALIZED VIEW s AS SELECT sum(v) AS n FROM t",
        ]
        for stmt in ddl:
            chaos.run_sql(stmt)
            control.run_sql(stmt)
        chaos.flush()

        import random as _r
        data_rng = _r.Random(5)
        for step in range(10):
            sql = f"INSERT INTO t VALUES ({step}, {data_rng.randint(0, 9)})"
            chaos.run_sql(sql)
            control.run_sql(sql)
            if step % 3 == 2:
                chaos.flush()
                control.flush()
            chaos.maybe_kill()
        chaos.verify_against(control)
        assert chaos.kills + chaos.worker_kills > 0
        assert chaos.worker_kills > 0, \
            "seed must exercise a per-component kill"
        chaos.session.close()
        control.close()


class TestMetaStore:
    def test_txn_cas_and_prefix(self):
        from risingwave_tpu.meta.store import MetaStore, TxnConflict
        ms = MetaStore()
        ms.put("catalog/t1", "v1")
        ms.txn([("catalog/t1", "v1")], [("put", "catalog/t1", "v2"),
                                        ("put", "catalog/t2", "x")])
        assert ms.get("catalog/t1") == "v2"
        assert [k for k, _ in ms.list_prefix("catalog/")] == \
            ["catalog/t1", "catalog/t2"]
        with pytest.raises(TxnConflict):
            ms.txn([("catalog/t1", "v1")], [("put", "catalog/t1", "v3")])
        assert ms.get("catalog/t1") == "v2"     # atomic: nothing applied

    def test_file_backend_replay_and_compact(self, tmp_path):
        from risingwave_tpu.meta.store import FileMetaStore
        p = str(tmp_path / "meta.jsonl")
        ms = FileMetaStore(p)
        ms.put("a", "1")
        ms.put("b", "2")
        ms.delete("a")
        ms.close()
        ms2 = FileMetaStore(p)
        assert ms2.get("a") is None and ms2.get("b") == "2"
        ms2.compact()
        ms2.close()
        ms3 = FileMetaStore(p)
        assert ms3.get("b") == "2" and ms3.get("a") is None


class TestDmlManager:
    def test_rendezvous_and_unregister(self):
        from risingwave_tpu.stream.dml import DmlManager, TableDmlHandle
        dm = DmlManager()
        got = []
        dm.register(7, TableDmlHandle(got.append))
        with pytest.raises(KeyError):
            dm.stage(99, "chunk")
        dm.stage(7, "c1")
        dm.stage(7, "c2")
        assert got == []                      # staged, not delivered
        assert dm.drain_into_epoch() == 2
        assert got == ["c1", "c2"]
        assert dm.drain_into_epoch() == 0     # drained
        dm.unregister_table(7)
        with pytest.raises(KeyError):
            dm.stage(7, "c3")


class TestMetaStoreTornTail:
    def test_torn_tail_line_truncated_on_replay(self, tmp_path):
        from risingwave_tpu.meta.store import FileMetaStore
        p = str(tmp_path / "meta.jsonl")
        ms = FileMetaStore(p)
        ms.put("a", "1")
        ms.close()
        with open(p, "a") as f:
            f.write('[["put", "b"')      # crash mid-append
        ms2 = FileMetaStore(p)           # replay tolerates the torn tail
        assert ms2.get("a") == "1" and ms2.get("b") is None
        ms2.put("c", "3")                # and the log keeps working
        ms2.close()
        ms3 = FileMetaStore(p)
        assert ms3.get("c") == "3"

    def test_valid_tail_missing_newline_not_destroyed(self, tmp_path):
        """A line torn exactly before its '\\n' must not cause a later
        append to concatenate (and a later replay to truncate both)."""
        from risingwave_tpu.meta.store import FileMetaStore
        p = str(tmp_path / "meta2.jsonl")
        ms = FileMetaStore(p)
        ms.put("a", "1")
        ms.close()
        # tear the trailing newline off the (valid) last line
        with open(p, "rb+") as f:
            f.seek(-1, 2)
            assert f.read(1) == b"\n"
            f.seek(-1, 2)
            f.truncate()
        ms2 = FileMetaStore(p)
        assert ms2.get("a") == "1"
        ms2.put("b", "2")
        ms2.close()
        ms3 = FileMetaStore(p)       # BOTH transactions survive
        assert ms3.get("a") == "1" and ms3.get("b") == "2"
