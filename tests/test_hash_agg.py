"""HashAggExecutor tests — chunk-in/chunk-out against MockSource, the
reference's executor test style (src/stream/src/executor/hash_agg.rs tests)."""

import asyncio

import pytest

from risingwave_tpu.common import (
    FLOAT64, INT64, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
    Schema, chunk_to_rows, make_chunk,
)
from risingwave_tpu.expr.agg import agg, count_star
from risingwave_tpu.storage import MemoryStateStore, StateTable
from risingwave_tpu.stream import (
    Barrier, HashAggExecutor, MaterializeExecutor, MockSource, wrap_debug,
    agg_state_schema,
)

IN_SCHEMA = Schema.of(("k", INT64), ("v", INT64))


def run(coro):
    return asyncio.run(coro)


async def drain(executor):
    chunks, barriers = [], []
    async for msg in executor.execute():
        from risingwave_tpu.stream import is_chunk
        if is_chunk(msg):
            chunks.append(msg)
        elif isinstance(msg, Barrier):
            barriers.append(msg)
    return chunks, barriers


def agg_rows(chunks, schema):
    out = []
    for c in chunks:
        out.extend(chunk_to_rows(c, schema, with_ops=True))
    return out


def test_count_sum_basic():
    src = MockSource(IN_SCHEMA, [
        Barrier.new(1),
        make_chunk(IN_SCHEMA, [(1, 10), (2, 20), (1, 5)]),
        Barrier.new(2),
    ])
    ex = HashAggExecutor(src, [0], [count_star(), agg("sum", 1, INT64)])
    chunks, _ = run(drain(wrap_debug(ex)))
    got = sorted(agg_rows(chunks, ex.schema))
    assert got == sorted([
        (OP_INSERT, (1, 2, 15)),
        (OP_INSERT, (2, 1, 20)),
    ])


def test_incremental_updates_and_deletes():
    c1 = make_chunk(IN_SCHEMA, [(1, 10), (2, 20)])
    c2 = make_chunk(IN_SCHEMA, [(1, 7), (2, 20)], ops=[OP_INSERT, OP_DELETE])
    src = MockSource(IN_SCHEMA, [Barrier.new(1), c1, Barrier.new(2), c2, Barrier.new(3)])
    ex = HashAggExecutor(src, [0], [count_star(), agg("sum", 1, INT64)])
    chunks, _ = run(drain(wrap_debug(ex)))
    rows = agg_rows(chunks, ex.schema)
    # epoch 2 flush: two inserts; epoch 3 flush: update for group 1, delete for group 2
    assert (OP_INSERT, (1, 1, 10)) in rows and (OP_INSERT, (2, 1, 20)) in rows
    assert (OP_UPDATE_DELETE, (1, 1, 10)) in rows
    assert (OP_UPDATE_INSERT, (1, 2, 17)) in rows
    assert (OP_DELETE, (2, 1, 20)) in rows
    assert len(rows) == 5


def test_avg_and_nulls():
    sch = Schema.of(("k", INT64), ("v", FLOAT64))
    c = make_chunk(sch, [(1, 4.0), (1, None), (1, 8.0)])
    src = MockSource(sch, [Barrier.new(1), c, Barrier.new(2)])
    ex = HashAggExecutor(src, [0], [count_star(), agg("avg", 1, FLOAT64)])
    chunks, _ = run(drain(ex))
    rows = agg_rows(chunks, ex.schema)
    assert rows == [(OP_INSERT, (1, 3, 6.0))]  # count counts null rows; avg skips


def test_group_cancel_between_barriers_emits_nothing():
    c = make_chunk(IN_SCHEMA, [(9, 1), (9, 1)], ops=[OP_INSERT, OP_DELETE])
    src = MockSource(IN_SCHEMA, [Barrier.new(1), c, Barrier.new(2)])
    ex = HashAggExecutor(src, [0], [count_star()])
    chunks, _ = run(drain(ex))
    assert agg_rows(chunks, ex.schema) == []


def test_null_group_key():
    sch = IN_SCHEMA
    c = make_chunk(sch, [(None, 1), (None, 2), (5, 3)])
    src = MockSource(sch, [Barrier.new(1), c, Barrier.new(2)])
    ex = HashAggExecutor(src, [0], [count_star(), agg("sum", 1, INT64)])
    chunks, _ = run(drain(ex))
    got = sorted(agg_rows(chunks, ex.schema), key=str)
    assert (OP_INSERT, (None, 2, 3)) in got
    assert (OP_INSERT, (5, 1, 3)) in got


def test_min_max_append_only():
    c = make_chunk(IN_SCHEMA, [(1, 10), (1, 3), (1, 25)])
    src = MockSource(IN_SCHEMA, [Barrier.new(1), c, Barrier.new(2)])
    ex = HashAggExecutor(src, [0], [agg("min", 1, INT64), agg("max", 1, INT64)])
    chunks, _ = run(drain(ex))
    assert agg_rows(chunks, ex.schema) == [(OP_INSERT, (1, 3, 25))]


def test_checkpoint_and_recovery():
    store = MemoryStateStore()
    calls = [count_star(), agg("sum", 1, INT64)]
    st_schema = agg_state_schema([IN_SCHEMA[0]], calls)
    c1 = make_chunk(IN_SCHEMA, [(1, 10), (2, 20)])
    src = MockSource(IN_SCHEMA, [
        Barrier.new(1),
        c1,
        Barrier.new(2, checkpoint=True),
    ])
    table = StateTable(store, 101, st_schema, [0])
    ex = HashAggExecutor(src, [0], calls, state_table=table)
    run(drain(ex))
    store.commit(2)
    assert store.table_len(101) == 2

    # "restart": new executor over the same store resumes the counts
    c2 = make_chunk(IN_SCHEMA, [(1, 5)])
    src2 = MockSource(IN_SCHEMA, [Barrier.new(3), c2, Barrier.new(4)])
    table2 = StateTable(store, 101, st_schema, [0])
    ex2 = HashAggExecutor(src2, [0], calls, state_table=table2)
    chunks, _ = run(drain(ex2))
    rows = agg_rows(chunks, ex2.schema)
    assert (OP_UPDATE_DELETE, (1, 1, 10)) in rows
    assert (OP_UPDATE_INSERT, (1, 2, 15)) in rows
    assert len(rows) == 2  # group 2 untouched -> not re-emitted


def test_many_groups_multi_chunk_flush():
    n = 700  # > groups_per_chunk for out_capacity 256 -> multiple flush chunks
    rows = [(i, i) for i in range(n)]
    chunks_in = [make_chunk(IN_SCHEMA, rows[i:i + 256], capacity=256)
                 for i in range(0, n, 256)]
    src = MockSource(IN_SCHEMA, [Barrier.new(1), *chunks_in, Barrier.new(2)])
    ex = HashAggExecutor(src, [0], [count_star()], out_capacity=256,
                         table_capacity=2048)
    chunks, _ = run(drain(ex))
    rows_out = agg_rows(chunks, ex.schema)
    assert len(rows_out) == n
    assert sorted(r[1][0] for r in rows_out) == list(range(n))


def test_materialized_pipeline():
    store = MemoryStateStore()
    c1 = make_chunk(IN_SCHEMA, [(1, 10), (2, 20), (1, 30)])
    src = MockSource(IN_SCHEMA, [Barrier.new(1), c1, Barrier.new(2, checkpoint=True)])
    ex = HashAggExecutor(src, [0], [count_star(), agg("sum", 1, INT64)])
    mv = MaterializeExecutor(ex, StateTable(store, 1, ex.schema, [0]))
    run(drain(mv))
    assert sorted(mv.rows()) == [(1, 2, 40), (2, 1, 20)]
