"""Extended NEXmark coverage: q0, q9, q10, q14-q18, q20-q22, q101-q106 —
the remainder of the reference's streaming suite (reference query texts:
e2e_test/streaming/nexmark/views/q*.slt.part and
src/tests/simulation/src/nexmark/q*.sql), each checked against an
independent Python recomputation of the same deterministic generator
stream (VERDICT r4 item 7)."""

import collections
import datetime

import pytest

from test_nexmark_queries import DDL, TICKS, make_session, replay


def run_mv(sql: str, name: str, ticks: int = TICKS):
    s = make_session()
    s.run_sql(sql)
    for _ in range(ticks):
        s.tick()
    rows = sorted(s.mv_rows(name))
    s.close()
    return rows


def day_of(us: int) -> str:
    d = datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=us)
    return f"{d.year:04d}-{d.month:02d}-{d.day:02d}"


def hhmi_of(us: int) -> str:
    d = datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=us)
    h12 = (d.hour % 12) or 12
    return f"{h12:02d}:{d.minute:02d}"


def test_q0_passthrough():
    got = run_mv("CREATE MATERIALIZED VIEW q0 AS SELECT auction, bidder, "
                 "price, date_time FROM bid", "q0")
    bids = replay("bid", TICKS)
    assert got == sorted((b[0], b[1], b[2], b[5]) for b in bids)


@pytest.mark.slow
def test_q9_winning_bids():
    got = run_mv("""CREATE MATERIALIZED VIEW q9 AS
        SELECT id, item_name, auction, bidder, price, bid_date_time FROM (
          SELECT A.id, A.item_name, B.auction, B.bidder, B.price,
                 B.date_time AS bid_date_time,
            ROW_NUMBER() OVER (PARTITION BY A.id
                ORDER BY B.price DESC, B.date_time ASC) AS rownum
          FROM auction A, bid B
          WHERE A.id = B.auction
            AND B.date_time BETWEEN A.date_time AND A.expires
        ) WHERE rownum <= 1""", "q9", ticks=6)
    auctions = replay("auction", 6)
    bids = replay("bid", 6)
    # auction ids repeat in the NEXmark stream, so a PARTITION BY A.id
    # partition can span several auction ROWS: the join rows of every
    # auction row with that id compete for rownum 1 together. item_name
    # is nondeterministic under order-by ties (two auction rows of one id
    # matching the same bid), so compare it by membership.
    per_id: dict = {}
    names: dict = {}
    for a in auctions:
        names.setdefault(a[0], set()).add(a[1])
        for b in bids:
            if b[0] == a[0] and a[5] <= b[5] <= a[6]:
                per_id.setdefault(a[0], []).append(b)
    exp = {}
    for aid, cands in per_id.items():
        w = min(cands, key=lambda b: (-b[2], b[5]))
        exp[aid] = (w[0], w[1], w[2], w[5])
    assert len(got) == len(exp) > 0
    for row in got:
        aid, item_name = row[0], row[1]
        assert row[2:] == exp[aid]
        assert item_name in names[aid]


def test_q10_log_format():
    got = run_mv("""CREATE MATERIALIZED VIEW q10 AS
        SELECT auction, bidder, price, date_time,
               TO_CHAR(date_time, 'YYYY-MM-DD') as date,
               TO_CHAR(date_time, 'HH:MI') as time FROM bid""", "q10")
    bids = replay("bid", TICKS)
    exp = sorted((b[0], b[1], b[2], b[5], day_of(b[5]), hhmi_of(b[5]))
                 for b in bids)
    assert got == exp


def test_q14_calculated_fields():
    got = run_mv("""CREATE MATERIALIZED VIEW q14 AS
        SELECT auction, bidder, 908 * price / 1000 as price,
          CASE WHEN extract(hour from date_time) >= 8
                AND extract(hour from date_time) <= 18 THEN 'dayTime'
               WHEN extract(hour from date_time) <= 6
                 OR extract(hour from date_time) >= 20 THEN 'nightTime'
          ELSE 'otherTime' END AS bidtimetype, date_time
        FROM bid WHERE 908 * price / 1000 > 1000""", "q14")
    bids = replay("bid", TICKS)
    exp = []
    for b in bids:
        p = 908 * b[2] // 1000
        if p > 1000:
            hour = ((b[5] // 3_600_000_000) % 24)
            if 8 <= hour <= 18:
                t = "dayTime"
            elif hour <= 6 or hour >= 20:
                t = "nightTime"
            else:
                t = "otherTime"
            exp.append((b[0], b[1], p, t, b[5]))
    assert got == sorted(exp) and len(got) > 0


def _rank_of(price: int) -> int:
    if price < 10_000:
        return 1
    if price < 1_000_000:
        return 2
    return 3


def test_q15_bidding_statistics():
    got = run_mv("""CREATE MATERIALIZED VIEW q15 AS
        SELECT TO_CHAR(date_time, 'yyyy-MM-dd') as day,
          count(*) AS total_bids,
          count(*) filter (where price < 10000) AS rank1_bids,
          count(*) filter (where price >= 10000 and price < 1000000)
            AS rank2_bids,
          count(*) filter (where price >= 1000000) AS rank3_bids,
          count(distinct bidder) AS total_bidders,
          count(distinct bidder) filter (where price < 10000)
            AS rank1_bidders,
          count(distinct auction) AS total_auctions,
          count(distinct auction) filter (where price >= 1000000)
            AS rank3_auctions
        FROM bid GROUP BY to_char(date_time, 'yyyy-MM-dd')""",
        "q15", ticks=6)
    bids = replay("bid", 6)
    per_day = collections.defaultdict(list)
    for b in bids:
        per_day[day_of(b[5])].append(b)
    exp = []
    for day, bs in per_day.items():
        exp.append((
            day, len(bs),
            sum(1 for b in bs if _rank_of(b[2]) == 1),
            sum(1 for b in bs if _rank_of(b[2]) == 2),
            sum(1 for b in bs if _rank_of(b[2]) == 3),
            len({b[1] for b in bs}),
            len({b[1] for b in bs if _rank_of(b[2]) == 1}),
            len({b[0] for b in bs}),
            len({b[0] for b in bs if _rank_of(b[2]) == 3}),
        ))
    assert got == sorted(exp) and len(got) > 0


def test_q16_channel_statistics():
    got = run_mv("""CREATE MATERIALIZED VIEW q16 AS
        SELECT channel, to_char(date_time, 'YYYY-MM-DD') as day,
          max(to_char(date_time, 'HH:MI')) as minute,
          count(*) AS total_bids,
          count(*) filter (where price < 10000) AS rank1_bids,
          count(distinct bidder) AS total_bidders,
          count(distinct auction) AS total_auctions
        FROM bid GROUP BY channel, to_char(date_time, 'YYYY-MM-DD')""",
        "q16", ticks=6)
    bids = replay("bid", 6)
    groups = collections.defaultdict(list)
    for b in bids:
        groups[(b[3], day_of(b[5]))].append(b)
    exp = []
    for (ch, day), bs in groups.items():
        exp.append((
            ch, day, max(hhmi_of(b[5]) for b in bs), len(bs),
            sum(1 for b in bs if _rank_of(b[2]) == 1),
            len({b[1] for b in bs}), len({b[0] for b in bs}),
        ))
    assert got == sorted(exp) and len(got) > 0


def test_q17_auction_statistics():
    got = run_mv("""CREATE MATERIALIZED VIEW q17 AS
        SELECT auction, to_char(date_time, 'YYYY-MM-DD') AS day,
          count(*) AS total_bids,
          count(*) filter (where price < 10000) AS rank1_bids,
          min(price) AS min_price, max(price) AS max_price,
          avg(price) AS avg_price, sum(price) AS sum_price
        FROM bid GROUP BY auction, to_char(date_time, 'YYYY-MM-DD')""",
        "q17", ticks=6)
    bids = replay("bid", 6)
    groups = collections.defaultdict(list)
    for b in bids:
        groups[(b[0], day_of(b[5]))].append(b)
    exp = []
    for (auc, day), bs in groups.items():
        prices = [b[2] for b in bs]
        exp.append((auc, day, len(bs),
                    sum(1 for p in prices if p < 10_000),
                    min(prices), max(prices),
                    sum(prices) / len(prices), sum(prices)))
    exp.sort()
    assert len(got) == len(exp) and len(got) > 0
    for g, e in zip(got, exp):
        assert g[:6] == e[:6] and g[7] == e[7]
        assert abs(g[6] - e[6]) < 1e-9


def test_q18_last_bid():
    got = run_mv("""CREATE MATERIALIZED VIEW q18 AS
        SELECT auction, bidder, price, date_time
        FROM (SELECT *, RANK() OVER (PARTITION BY bidder, auction
                  ORDER BY date_time DESC) AS rank_number
              FROM bid) WHERE rank_number <= 1""", "q18")
    bids = replay("bid", TICKS)
    last: dict = {}
    for b in bids:
        k = (b[1], b[0])
        if k not in last or b[5] > last[k][5]:
            last[k] = b
    exp = sorted((b[0], b[1], b[2], b[5]) for b in last.values())
    assert got == exp and len(got) > 0


@pytest.mark.slow
def test_q20_expand_bid():
    got = run_mv("""CREATE MATERIALIZED VIEW q20 AS
        SELECT auction, bidder, price, channel, item_name, seller, category
        FROM bid AS B INNER JOIN auction AS A on B.auction = A.id
        WHERE A.category = 10""", "q20", ticks=8)
    bids = replay("bid", 8)
    auctions = replay("auction", 8)
    exp = [(b[0], b[1], b[2], b[3], a[1], a[7], a[8])
           for b in bids for a in auctions
           if b[0] == a[0] and a[8] == 10]
    assert got == sorted(exp)


def test_q21_channel_id():
    got = run_mv("""CREATE MATERIALIZED VIEW q21 AS
        SELECT auction, bidder, price, channel,
          CASE WHEN LOWER(channel) = 'apple' THEN '0'
               WHEN LOWER(channel) = 'google' THEN '1'
               WHEN LOWER(channel) = 'facebook' THEN '2'
               WHEN LOWER(channel) = 'baidu' THEN '3'
          ELSE (regexp_match(url, '(&|^)channel_id=([^&]*)'))[2] END
            AS channel_id
        FROM bid
        WHERE (regexp_match(url, '(&|^)channel_id=([^&]*)'))[2]
                is not null
           or LOWER(channel) in ('apple', 'google', 'facebook', 'baidu')""",
        "q21")
    import re
    bids = replay("bid", TICKS)
    rx = re.compile(r"(&|^)channel_id=([^&]*)")
    known = {"apple": "0", "google": "1", "facebook": "2", "baidu": "3"}
    exp = []
    for b in bids:
        m = rx.search(b[4])
        low = b[3].lower()
        if low in known:
            exp.append((b[0], b[1], b[2], b[3], known[low]))
        elif m is not None:
            exp.append((b[0], b[1], b[2], b[3], m.group(2)))
    assert got == sorted(exp) and len(got) > 0


def test_q22_url_directories():
    got = run_mv("""CREATE MATERIALIZED VIEW q22 AS
        SELECT auction, bidder, price, channel,
          split_part(url, '/', 4) as dir1,
          split_part(url, '/', 5) as dir2,
          split_part(url, '/', 6) as dir3 FROM bid""", "q22")
    bids = replay("bid", TICKS)

    def part(u, n):
        ps = u.split("/")
        return ps[n - 1] if 0 <= n - 1 < len(ps) else ""

    exp = sorted((b[0], b[1], b[2], b[3], part(b[4], 4), part(b[4], 5),
                  part(b[4], 6)) for b in bids)
    assert got == exp


def test_q101_highest_bid_outer():
    got = run_mv("""CREATE MATERIALIZED VIEW q101 AS
        SELECT a.id AS auction_id, a.item_name, b.max_price
        FROM auction a LEFT OUTER JOIN (
          SELECT b1.auction, MAX(b1.price) max_price
          FROM bid b1 GROUP BY b1.auction
        ) b ON a.id = b.auction""", "q101", ticks=6)
    auctions = replay("auction", 6)
    bids = replay("bid", 6)
    best: dict = {}
    for b in bids:
        best[b[0]] = max(best.get(b[0], 0), b[2])
    exp = sorted(((a[0], a[1], best.get(a[0])) for a in auctions),
                 key=lambda r: (r[0], r[1], r[2] is not None, r[2] or 0))
    key = lambda r: (r[0], r[1], r[2] is not None, r[2] or 0)  # noqa: E731
    assert sorted(got, key=key) == exp and len(got) > 0
    assert any(r[2] is None for r in got)      # outer-ness exercised


def test_q102_bid_count_above_average():
    got = run_mv("""CREATE MATERIALIZED VIEW q102 AS
        SELECT a.id AS auction_id, a.item_name, COUNT(b.auction)
          AS bid_count
        FROM auction a JOIN bid b ON a.id = b.auction
        GROUP BY a.id, a.item_name
        HAVING COUNT(b.auction) >= (
          SELECT COUNT(*) / COUNT(DISTINCT auction) FROM bid)""",
        "q102", ticks=6)
    auctions = replay("auction", 6)
    bids = replay("bid", 6)
    n_bid = collections.Counter(b[0] for b in bids)
    avg = len(bids) // len({b[0] for b in bids})
    exp = sorted((a[0], a[1], n_bid[a[0]]) for a in auctions
                 if n_bid[a[0]] >= avg)
    assert got == exp and len(got) > 0


def test_q103_semi_join():
    got = run_mv("""CREATE MATERIALIZED VIEW q103 AS
        SELECT a.id AS auction_id, a.item_name FROM auction a
        WHERE a.id IN (
          SELECT b.auction FROM bid b GROUP BY b.auction
          HAVING COUNT(*) >= 2)""", "q103", ticks=6)
    auctions = replay("auction", 6)
    bids = replay("bid", 6)
    n_bid = collections.Counter(b[0] for b in bids)
    exp = sorted((a[0], a[1]) for a in auctions if n_bid[a[0]] >= 2)
    assert got == exp and len(got) > 0


def test_q104_anti_join():
    got = run_mv("""CREATE MATERIALIZED VIEW q104 AS
        SELECT a.id AS auction_id, a.item_name FROM auction a
        WHERE a.id NOT IN (
          SELECT b.auction FROM bid b GROUP BY b.auction
          HAVING COUNT(*) < 2)""", "q104", ticks=6)
    auctions = replay("auction", 6)
    bids = replay("bid", 6)
    n_bid = collections.Counter(b[0] for b in bids)
    exp = sorted((a[0], a[1]) for a in auctions
                 if not (0 < n_bid[a[0]] < 2))
    assert got == exp and len(got) > 0


def test_q105_top_auctions():
    got = run_mv("""CREATE MATERIALIZED VIEW q105 AS
        SELECT a.id AS auction_id, a.item_name, COUNT(b.auction)
          AS bid_count
        FROM auction a JOIN bid b ON a.id = b.auction
        GROUP BY a.id, a.item_name
        ORDER BY bid_count DESC LIMIT 1000""", "q105", ticks=6)
    auctions = replay("auction", 6)
    bids = replay("bid", 6)
    n_bid = collections.Counter(b[0] for b in bids)
    exp = sorted((a[0], a[1], n_bid[a[0]]) for a in auctions
                 if n_bid[a[0]] > 0)
    assert got == exp and len(got) > 0  # < 1000 groups: TopN keeps all


def test_q106_min_final_price():
    """Two-phase stateful agg: MIN over per-auction MAX. The outer MIN's
    input retracts (each new max replaces the old), so this exercises
    min-with-retraction (materialized input state — reference:
    AggStateStorage::MaterializedInput, agg_state.rs:65)."""
    got = run_mv("""CREATE MATERIALIZED VIEW q106 AS
        SELECT MIN(final) AS min_final FROM (
          SELECT auction.id, MAX(price) AS final FROM auction, bid
          WHERE bid.auction = auction.id
            AND bid.date_time BETWEEN auction.date_time AND auction.expires
          GROUP BY auction.id)""", "q106", ticks=6)
    auctions = replay("auction", 6)
    bids = replay("bid", 6)
    finals: dict = {}
    for a in auctions:
        for b in bids:
            if b[0] == a[0] and a[5] <= b[5] <= a[6]:
                finals[a[0]] = max(finals.get(a[0], 0), b[2])
    assert finals, "workload must produce at least one final price"
    assert got == [(min(finals.values()),)]
