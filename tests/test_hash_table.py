"""Device hash-table semantics tests: batch find-or-insert, duplicate keys,
null grouping keys, collision resolution, overflow, read-only lookup."""

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common import INT64, VARCHAR, Schema, make_chunk
from risingwave_tpu.ops import ht_lookup, ht_lookup_or_insert, ht_new, scatter_reduce

SCHEMA = Schema.of(("k", INT64),)


def _chunk(keys, capacity=16):
    return make_chunk(SCHEMA, [(k,) for k in keys], capacity=capacity)


def _insert(table, keys, capacity=16):
    chunk = _chunk(keys, capacity)
    return ht_lookup_or_insert(table, [chunk.columns[0]], chunk.vis)


def test_insert_then_find():
    t = ht_new([INT64], 64)
    t, slots1, new1, ovf = _insert(t, [10, 20, 30])
    assert not bool(ovf)
    assert list(np.asarray(new1)[:3]) == [True, True, True]
    t, slots2, new2, _ = _insert(t, [20, 30, 40])
    s1, s2 = np.asarray(slots1), np.asarray(slots2)
    assert s2[0] == s1[1] and s2[1] == s1[2]  # existing keys hit same slots
    assert list(np.asarray(new2)[:3]) == [False, False, True]
    assert int(t.num_occupied()) == 4


def test_intra_batch_duplicates_share_slot():
    t = ht_new([INT64], 64)
    t, slots, is_new, _ = _insert(t, [7, 7, 7, 8, 8])
    s = np.asarray(slots)[:5]
    assert s[0] == s[1] == s[2]
    assert s[3] == s[4] != s[0]
    assert int(np.asarray(is_new)[:5].sum()) == 2  # one winner per distinct key


def test_null_keys_group_together():
    t = ht_new([INT64], 64)
    chunk = make_chunk(SCHEMA, [(None,), (None,), (5,)], capacity=8)
    t, slots, is_new, _ = ht_lookup_or_insert(t, [chunk.columns[0]], chunk.vis)
    s = np.asarray(slots)
    assert s[0] == s[1] != s[2]


def test_collisions_resolve_in_tiny_table():
    # 8-slot table, 6 distinct keys -> guaranteed probing collisions
    t = ht_new([INT64], 8)
    t, slots, _, ovf = _insert(t, [1, 9, 17, 2, 10, 3])
    assert not bool(ovf)
    s = np.asarray(slots)[:6]
    assert len(set(s.tolist())) == 6  # distinct keys -> distinct slots


def test_overflow_reported():
    t = ht_new([INT64], 8)
    t, slots, _, ovf = _insert(t, list(range(1, 10)))  # 9 keys > 8 slots
    assert bool(ovf)


def test_invalid_rows_ignored():
    t = ht_new([INT64], 64)
    chunk = _chunk([1, 2, 3], capacity=8)
    vis = jnp.asarray([True, False, True, False, False, False, False, False])
    t, slots, is_new, _ = ht_lookup_or_insert(t, [chunk.columns[0]], vis)
    s = np.asarray(slots)
    assert s[1] == 64  # capacity sentinel for masked row
    assert int(t.num_occupied()) == 2


def test_lookup_without_insert():
    t = ht_new([INT64], 64)
    t, _, _, _ = _insert(t, [100, 200])
    chunk = _chunk([200, 300], capacity=8)
    slots, found = ht_lookup(t, [chunk.columns[0]], chunk.vis)
    f = np.asarray(found)
    assert f[0] and not f[1]
    assert int(t.num_occupied()) == 2  # lookup does not insert


def test_scatter_reduce_grouped_sum():
    t = ht_new([INT64], 64)
    chunk = _chunk([5, 6, 5, 5, 6], capacity=8)
    t, slots, _, _ = ht_lookup_or_insert(t, [chunk.columns[0]], chunk.vis)
    sums = jnp.zeros(64, jnp.int64)
    contrib = jnp.asarray([1, 10, 2, 3, 20, 999, 999, 999], jnp.int64)
    sums = scatter_reduce(sums, slots, contrib, "add")
    s = np.asarray(slots)
    assert int(sums[s[0]]) == 6   # 1+2+3 for key 5
    assert int(sums[s[1]]) == 30  # 10+20 for key 6
    assert int(np.asarray(sums).sum()) == 36  # masked rows dropped


def test_compound_string_key_and_jit():
    schema = Schema.of(("a", INT64), ("s", VARCHAR))
    t = ht_new([INT64, VARCHAR], 64)
    chunk = make_chunk(schema, [(1, "x"), (1, "y"), (1, "x")], capacity=8)

    @jax.jit
    def step(t, c):
        return ht_lookup_or_insert(t, [c.columns[0], c.columns[1]], c.vis)

    t, slots, is_new, ovf = step(t, chunk)
    s = np.asarray(slots)
    assert s[0] == s[2] != s[1]
    assert not bool(ovf)
