"""Meta as a process — the ISSUE 17 control-plane surface.

Four layers, mirroring docs/control-plane.md:

1. Pure units (tier-1): the pgwire AdmissionController's bounded-queue
   semantics, the ``[meta]`` config section, and the ``ALTER SYSTEM``
   parse — no Session, no sockets beyond a loopback meta roundtrip.
2. Meta wire protocol: a real MetaServer + MetaClient over loopback —
   store CAS transactions, notification push, placements, and the
   last-writer-wins leader lease.
3. Fleet semantics (slow): one writer + two serving sessions sharing a
   durable Hummock dir through one meta process — reads, plan-cache
   hits, DDL/ALTER SYSTEM propagation, read-only enforcement, fencing,
   and the kill -9 → restart → reconnect fault path.
4. Frontend overload (slow): 4x-quota pgwire load queues with zero
   dropped connections; beyond the bounded queue the server sheds with
   SQLSTATE 53300 instead of collapsing.  Plus the SSLRequest /
   GSSENCRequest plaintext-refusal probes and the zero-added-dispatch
   guard (a remote meta must not change the device story).
"""

import asyncio
import os
import signal
import socket
import struct
import subprocess
import sys
import time

import pytest

from risingwave_tpu.common.config import MetaConfig, load_config
from risingwave_tpu.frontend.pgwire import AdmissionController, QueryShed

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# =====================================================================
# 1. pure units — tier-1
# =====================================================================

class TestAdmissionController:
    def test_fast_path_admits_without_queueing(self):
        async def go():
            ac = AdmissionController(max_inflight=2, per_conn_inflight=2,
                                     queue_depth=4)
            conn = ac.conn_slot()
            await ac.acquire(conn)
            snap = ac.snapshot()
            assert snap["admitted"] == 1 and snap["inflight"] == 1
            assert snap["queued"] == 0 and snap["shed"] == 0
            ac.release(conn)
            assert ac.snapshot()["inflight"] == 0
        asyncio.run(go())

    def test_queue_then_shed_beyond_depth(self):
        """max_inflight=1, queue_depth=1: the second query queues, the
        third sheds — the queue is BOUNDED, overload cannot pile up."""
        async def go():
            ac = AdmissionController(max_inflight=1, per_conn_inflight=8,
                                     queue_depth=1)
            c1, c2, c3 = (ac.conn_slot() for _ in range(3))
            await ac.acquire(c1)                  # occupies the slot
            waiter = asyncio.ensure_future(ac.acquire(c2))
            await asyncio.sleep(0)                # let it enter the queue
            assert ac.snapshot()["waiting"] == 1
            with pytest.raises(QueryShed) as ei:
                await ac.acquire(c3)              # queue full: shed
            assert "overloaded" in str(ei.value)
            ac.release(c1)                        # waiter drains
            await asyncio.wait_for(waiter, timeout=5)
            ac.release(c2)
            snap = ac.snapshot()
            assert snap["shed"] == 1 and snap["queued"] == 1
            assert snap["max_queued"] == 1 and snap["waiting"] == 0
            assert snap["inflight"] == 0
        asyncio.run(go())

    def test_per_connection_cap_queues_own_conn_only(self):
        """A connection at its own in-flight cap queues even when the
        global pool has room; a different connection sails through."""
        async def go():
            ac = AdmissionController(max_inflight=8, per_conn_inflight=1,
                                     queue_depth=4)
            hog, other = ac.conn_slot(), ac.conn_slot()
            await ac.acquire(hog)
            second = asyncio.ensure_future(ac.acquire(hog))
            await asyncio.sleep(0)
            assert ac.snapshot()["waiting"] == 1   # same-conn query waits
            await asyncio.wait_for(ac.acquire(other), timeout=5)
            ac.release(hog)                        # unblock the hog's 2nd
            await asyncio.wait_for(second, timeout=5)
            ac.release(hog)
            ac.release(other)
            assert ac.snapshot()["inflight"] == 0
        asyncio.run(go())


class TestMetaConfigSection:
    def test_defaults_mean_in_process_meta(self):
        cfg = MetaConfig()
        assert cfg.addr == ""                      # playground default
        assert cfg.admission_max_inflight == 8
        assert cfg.admission_per_conn_inflight == 2
        assert cfg.admission_queue_depth == 64

    def test_meta_section_round_trips_from_toml(self, tmp_path):
        p = tmp_path / "risingwave.toml"
        p.write_text(
            '[meta]\naddr = "127.0.0.1:5690"\n'
            "admission_max_inflight = 4\n"
            "admission_queue_depth = 16\n")
        cfg = load_config(str(p))
        assert cfg.meta.addr == "127.0.0.1:5690"
        assert cfg.meta.admission_max_inflight == 4
        assert cfg.meta.admission_queue_depth == 16
        assert cfg.meta.admission_per_conn_inflight == 2   # untouched


class TestAlterSystemParse:
    def test_alter_system_set_is_system_scoped(self):
        from risingwave_tpu.frontend import sqlast as A
        from risingwave_tpu.frontend.parser import parse_sql
        (stmt,) = parse_sql("ALTER SYSTEM SET checkpoint_frequency = 4")
        assert isinstance(stmt, A.SetStatement)
        assert stmt.name.lower() == "checkpoint_frequency"
        assert stmt.system is True
        (plain,) = parse_sql("SET checkpoint_frequency = 4")
        assert plain.system is False               # session-local SET


# =====================================================================
# 2. meta wire protocol — server + client over loopback
# =====================================================================

class TestMetaWireProtocol:
    def _serve(self, tmp_path):
        from risingwave_tpu.meta.server import MetaServer
        server = MetaServer(data_dir=str(tmp_path / "meta"))
        return server, server.start()

    def test_store_ops_txn_conflict_and_notifications(self, tmp_path):
        from risingwave_tpu.meta.client import MetaClient
        from risingwave_tpu.meta.store import TxnConflict
        server, addr = self._serve(tmp_path)
        a = MetaClient(addr)
        b = MetaClient(addr)
        try:
            a.store.put("k/1", "v1")
            assert b.store.get("k/1") == "v1"
            assert ("k/1", "v1") in b.store.list_prefix("k/")
            # CAS: b's precondition stales out after a's write
            a.store.put("k/1", "v2")
            with pytest.raises(TxnConflict):
                b.store.txn(preconditions=[("k/1", "v1")],
                            ops=[("put", "k/1", "v3")])
            b.store.delete("k/1")
            assert a.store.get("k/1") is None
            # notification push crosses clients within one version
            got = []
            b.notifications.subscribe("catalog",
                                      lambda v, info: got.append(info))
            a.notifications.notify("catalog", {"ddl": "create"})
            deadline = time.monotonic() + 5
            while not got and time.monotonic() < deadline:
                time.sleep(0.01)
            assert got and got[0]["ddl"] == "create"
        finally:
            a.close()
            b.close()
            server.stop()

    def test_placements_survive_the_wire(self, tmp_path):
        from risingwave_tpu.meta.client import MetaClient
        from risingwave_tpu.meta.fragment import (ActorPlacement,
                                                  FragmentPlacement)
        server, addr = self._serve(tmp_path)
        c = MetaClient(addr)
        try:
            pl = FragmentPlacement(
                job="mv_q", root_worker=0,
                actors={1: [ActorPlacement(fragment_id=1, actor=0,
                                           worker=0, vnode_start=0,
                                           vnode_end=128)],
                        2: [ActorPlacement(fragment_id=2, actor=0,
                                           worker=1, vnode_start=128,
                                           vnode_end=256)]})
            c.save_placement(pl)
            back = c.load_placement("mv_q")
            assert back is not None and back.to_json() == pl.to_json()
            assert "mv_q" in c.all_placements()
            c.drop_placement("mv_q")
            assert c.load_placement("mv_q") is None
        finally:
            c.close()
            server.stop()

    def test_leader_lease_last_writer_wins(self, tmp_path):
        from risingwave_tpu.meta.client import MetaClient, MetaFenced
        server, addr = self._serve(tmp_path)
        old = MetaClient(addr)
        new = MetaClient(addr)
        try:
            old.acquire_leader(generation=1)
            old.assert_leader()                    # holds
            new.acquire_leader(generation=2)       # supersedes
            new.assert_leader()
            with pytest.raises(MetaFenced):
                old.assert_leader()
            with pytest.raises(MetaFenced):        # fenced publishes too
                old.publish_checkpoint(committed_epoch=7)
        finally:
            old.close()
            new.close()
            server.stop()


# =====================================================================
# 3. fleet semantics — writer + serving sessions over one meta
# =====================================================================

DDL = """
CREATE TABLE ft (k BIGINT PRIMARY KEY, v BIGINT);
CREATE MATERIALIZED VIEW fmv AS
  SELECT k, count(*) AS n, sum(v) AS s FROM ft GROUP BY k;
"""


def _writer(tmp_path, addr, **kw):
    from risingwave_tpu.frontend import Session
    return Session(data_dir=str(tmp_path), meta_addr=addr,
                   state_store="hummock", checkpoint_frequency=2, **kw)


def _reader(tmp_path, addr):
    from risingwave_tpu.frontend import Session
    return Session(data_dir=str(tmp_path), meta_addr=addr, role="serving")


def _poll(fn, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while True:
        try:
            out = fn()
            if out:
                return out
        except Exception:
            if time.monotonic() >= deadline:
                raise
        if time.monotonic() >= deadline:
            return fn()
        time.sleep(interval)


@pytest.mark.slow
class TestMultiTenantFleet:
    def test_writer_two_readers_share_one_store(self, tmp_path):
        from risingwave_tpu.frontend.session import SqlError
        from risingwave_tpu.meta.server import MetaServer
        server = MetaServer(data_dir=str(tmp_path / "meta"))
        addr = server.start()
        w = _writer(tmp_path, addr)
        readers = []
        try:
            w.run_sql(DDL)
            w.run_sql("INSERT INTO ft VALUES " + ", ".join(
                f"({i % 8}, {i})" for i in range(64)))
            w.run_sql("FLUSH")
            want = sorted(w.run_sql("SELECT k, n, s FROM fmv"))
            readers = [_reader(tmp_path, addr) for _ in range(2)]
            for r in readers:
                got = sorted(r.run_sql("SELECT k, n, s FROM fmv"))
                assert got == want
                # the second identical read comes out of the plan cache
                r.run_sql("SELECT k, n, s FROM fmv")
                assert r.metrics()["serving"]["cache_hits"] >= 1

            # serving sessions are read-only and never conduct barriers
            r0 = readers[0]
            with pytest.raises(SqlError, match="read-only"):
                r0.run_sql("INSERT INTO ft VALUES (99, 99)")
            with pytest.raises(SqlError, match="read-only"):
                r0.run_sql("CREATE TABLE rogue (k BIGINT PRIMARY KEY)")
            with pytest.raises(RuntimeError, match="writer session"):
                r0.tick()

            # writer DDL reaches every reader within one notification
            w.run_sql("CREATE MATERIALIZED VIEW fmv2 AS "
                      "SELECT count(*) AS total FROM ft")
            w.run_sql("FLUSH")
            total = w.run_sql("SELECT total FROM fmv2")
            for r in readers:
                got = _poll(lambda r=r: r.run_sql("SELECT total FROM fmv2"))
                assert got == total

            # ALTER SYSTEM propagates live to the whole fleet
            w.run_sql("ALTER SYSTEM SET checkpoint_frequency = 7")
            w.run_sql("ALTER SYSTEM SET barrier_interval_ms = 250")
            for s in [w] + readers:
                _poll(lambda s=s: s.checkpoint_frequency == 7
                      and s.barrier_interval_ms == 250)
                assert s.checkpoint_frequency == 7
                assert s.barrier_interval_ms == 250
        finally:
            for r in readers:
                r.close()
            w.close()
            server.stop()

    def test_new_writer_fences_the_old_one(self, tmp_path):
        """Last writer wins: a takeover attach under the next persisted
        generation fences the previous writer — the ex-writer can
        neither inject barriers nor commit checkpoints, while direct
        meta RPCs under its stale generation are refused server-side."""
        from risingwave_tpu.meta.client import MetaFenced
        from risingwave_tpu.meta.server import MetaServer
        server = MetaServer(data_dir=str(tmp_path / "meta"))
        addr = server.start()
        w1 = _writer(tmp_path, addr)
        w2 = None
        try:
            w1.run_sql("CREATE TABLE t1 (k BIGINT PRIMARY KEY, "
                       "v BIGINT)")
            w1.run_sql("INSERT INTO t1 VALUES (1, 1)")
            w1.run_sql("FLUSH")
            g1 = w1._generation
            w2 = _writer(tmp_path, addr)       # takeover: generation+1
            assert w2._generation > g1

            # the server refuses the stale generation outright ...
            with pytest.raises(MetaFenced):
                w1.meta.publish_checkpoint(committed_epoch=99)

            # ... and the ex-writer's own barrier path locks out (the
            # lease-loss notification or a refused publish, whichever
            # lands first) — then the session DEMOTES itself to a
            # working serving session instead of staying wedged
            def fenced():
                try:
                    w1.tick()
                    return False
                except MetaFenced:
                    return True
            assert _poll(fenced)
            assert w1.role == "serving"
            with pytest.raises(RuntimeError, match="serving sessions"):
                w1.tick()
            # the demoted session still answers reads
            assert sorted(w1.run_sql("SELECT k, v FROM t1")) == [(1, 1)]

            # the new writer owns conduction and keeps working
            w2.run_sql("INSERT INTO t1 VALUES (2, 2)")
            w2.run_sql("FLUSH")
            assert sorted(w2.run_sql("SELECT k, v FROM t1")) == [
                (1, 1), (2, 2)]
        finally:
            w1.close()
            if w2 is not None:
                w2.close()
            server.stop()


@pytest.mark.slow
class TestMetaKillDashNine:
    def _spawn_meta(self, metadir, port):
        env = dict(os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "risingwave_tpu.meta.server",
             "--data-dir", metadir, "--port", str(port)],
            stdout=subprocess.PIPE, text=True, cwd=ROOT, env=env)
        line = proc.stdout.readline()
        assert line.startswith("META_READY "), line
        return proc, line.split()[1].strip()

    def test_kill_restart_reconnect_resume(self, tmp_path):
        metadir = str(tmp_path / "meta")
        proc, addr = self._spawn_meta(metadir, 0)
        port = int(addr.rpartition(":")[2])
        w = None
        try:
            w = _writer(tmp_path, addr)
            w.run_sql(DDL)
            w.run_sql("INSERT INTO ft VALUES (1, 10), (2, 20)")
            w.run_sql("FLUSH")

            proc.kill()                     # SIGKILL: no goodbye frame
            proc.wait(timeout=10)
            proc, addr2 = self._spawn_meta(metadir, port)
            assert addr2 == addr            # same endpoint, same store

            # the writer reconnects transparently and resumes barriers
            w.run_sql("INSERT INTO ft VALUES (3, 30)")
            w.run_sql("FLUSH")
            assert sorted(w.run_sql("SELECT k, s FROM fmv")) == [
                (1, 10), (2, 20), (3, 30)]
            assert w.meta.stats["reconnects"] >= 1
            from risingwave_tpu.common.audit import ConsistencyAuditor
            ConsistencyAuditor(w).audit().assert_ok()

            # a fresh reader can attach to the restarted meta
            r = _reader(tmp_path, addr)
            try:
                assert sorted(r.run_sql("SELECT k, s FROM fmv")) == [
                    (1, 10), (2, 20), (3, 30)]
            finally:
                r.close()
        finally:
            if w is not None:
                w.close()
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=10)


# =====================================================================
# 4. frontend overload + protocol probes + dispatch parity
# =====================================================================

def _pg_recv_until_ready(sock):
    buf = b""
    while b"Z\x00\x00\x00\x05I" not in buf:
        d = sock.recv(65536)
        if not d:
            raise ConnectionError("server closed the connection")
        buf += d
    return buf


def _pg_connect(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    body = struct.pack("!I", 196608) + b"user\x00cp\x00\x00"
    s.sendall(struct.pack("!I", len(body) + 4) + body)
    _pg_recv_until_ready(s)
    return s


def _pg_query(sock, sql):
    body = sql.encode() + b"\x00"
    sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
    return _pg_recv_until_ready(sock)


@pytest.mark.slow
class TestPgwireFrontend:
    def _serve(self, admission=None):
        from risingwave_tpu.frontend import Session
        from risingwave_tpu.frontend.pgwire import PgWireServer
        sess = Session()
        sess.run_sql("CREATE TABLE pt (k BIGINT PRIMARY KEY, v BIGINT)")
        sess.run_sql("INSERT INTO pt VALUES " + ", ".join(
            f"({i}, {i * 2})" for i in range(32)))
        sess.run_sql("CREATE MATERIALIZED VIEW pmv AS "
                     "SELECT count(*) AS n, sum(v) AS s FROM pt")
        sess.run_sql("FLUSH")
        srv = PgWireServer(sess, "127.0.0.1", 0, admission=admission)
        loop = asyncio.new_event_loop()
        import threading
        started = threading.Event()

        def run():
            asyncio.set_event_loop(loop)
            loop.run_until_complete(srv.start())
            started.set()
            loop.run_forever()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert started.wait(timeout=30)
        port = srv._server.sockets[0].getsockname()[1]

        def stop():
            async def _close():
                await srv.close()
            fut = asyncio.run_coroutine_threadsafe(_close(), loop)
            fut.result(timeout=10)
            loop.call_soon_threadsafe(loop.stop)
            t.join(timeout=10)
            sess.close()

        return srv, port, stop

    def test_ssl_and_gssenc_probes_get_plaintext_refusal(self):
        """Satellite: psql-style clients probe SSLRequest (80877103)
        and GSSENCRequest (80877104) before StartupMessage; the server
        answers each with the single byte 'N' and keeps the connection
        usable for a plaintext startup on the same socket."""
        srv, port, stop = self._serve()
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=30)
            try:
                for code in (80877103, 80877104):   # SSL, then GSSENC
                    s.sendall(struct.pack("!II", 8, code))
                    assert s.recv(1) == b"N"
                body = struct.pack("!I", 196608) + b"user\x00cp\x00\x00"
                s.sendall(struct.pack("!I", len(body) + 4) + body)
                _pg_recv_until_ready(s)             # startup completes
                out = _pg_query(s, "SELECT n, s FROM pmv")
                assert b"E" != out[:1] and b"D" in out
            finally:
                s.close()
        finally:
            stop()

    def test_4x_quota_overload_queues_without_drops(self):
        """4x the in-flight quota: everything queues and completes —
        zero sheds, zero dropped connections, the queue high-water mark
        stays within the configured bound."""
        import threading
        cfg = MetaConfig(admission_max_inflight=2,
                         admission_per_conn_inflight=1,
                         admission_queue_depth=64)
        srv, port, stop = self._serve(admission=cfg)
        try:
            n_conns, per_conn = 8, 4                # 4x the quota of 2
            errors, oks = [], []
            lock = threading.Lock()

            def worker():
                try:
                    s = _pg_connect(port)
                    try:
                        for _ in range(per_conn):
                            out = _pg_query(s, "SELECT n, s FROM pmv")
                            with lock:
                                (errors if b"C53300" in out
                                 else oks).append(out)
                    finally:
                        s.close()
                except Exception as e:              # dropped connection
                    with lock:
                        errors.append(e)

            threads = [threading.Thread(target=worker)
                       for _ in range(n_conns)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors                       # no drops, no sheds
            assert len(oks) == n_conns * per_conn
            snap = srv.admission.snapshot()
            assert snap["shed"] == 0
            assert snap["max_inflight"] <= 2
            assert snap["max_queued"] <= cfg.admission_queue_depth
        finally:
            stop()

    def test_beyond_queue_depth_sheds_53300_not_collapse(self):
        """queue_depth=0 turns every would-wait query into a 53300
        shed — the connection survives and later queries succeed."""
        import threading
        cfg = MetaConfig(admission_max_inflight=1,
                         admission_per_conn_inflight=1,
                         admission_queue_depth=0)
        srv, port, stop = self._serve(admission=cfg)
        try:
            n_conns, per_conn = 6, 3
            shed, ok, broken = [], [], []
            lock = threading.Lock()
            gate = threading.Barrier(n_conns)

            def worker():
                try:
                    s = _pg_connect(port)
                    gate.wait(timeout=30)
                    try:
                        for _ in range(per_conn):
                            out = _pg_query(s, "SELECT n, s FROM pmv")
                            with lock:
                                (shed if b"C53300" in out
                                 else ok).append(out)
                    finally:
                        s.close()
                except Exception as e:
                    with lock:
                        broken.append(e)

            threads = [threading.Thread(target=worker)
                       for _ in range(n_conns)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not broken                   # shed ≠ disconnect
            assert len(shed) + len(ok) == n_conns * per_conn
            assert ok                           # service degraded, alive
            snap = srv.admission.snapshot()
            assert snap["shed"] == len(shed)
            assert snap["max_queued"] == 0      # nothing ever piled up
        finally:
            stop()


@pytest.mark.slow
class TestRemoteMetaDispatchParity:
    def test_zero_added_dispatches_depth_1_and_2(self, tmp_path):
        """Acceptance: attaching through a MetaServer instead of the
        in-process meta adds ZERO device dispatches on the tick path —
        per-qualname equality at pipeline_depth 1 and 2. Meta traffic
        is host-side wire IO; the fused epoch story must not notice."""
        from risingwave_tpu.common.dispatch_count import count_dispatches
        from risingwave_tpu.frontend import Session
        from risingwave_tpu.meta.server import MetaServer

        def run(d, depth, meta_addr):
            with count_dispatches() as c:
                s = Session(data_dir=str(d), meta_addr=meta_addr,
                            state_store="hummock", pipeline_depth=depth,
                            checkpoint_frequency=2)
                try:
                    s.run_sql(DDL)
                    for i in range(4):
                        s.run_sql(f"INSERT INTO ft VALUES "
                                  f"({i % 4}, {i})")
                        s.tick()
                    s.flush()
                finally:
                    s.close()
                return dict(c.counts)

        for depth in (1, 2):
            local = run(tmp_path / f"local{depth}", depth, None)
            rdir = tmp_path / f"remote{depth}"
            server = MetaServer(data_dir=str(rdir / "meta"))
            addr = server.start()
            try:
                remote = run(rdir, depth, addr)
            finally:
                server.stop()
            assert remote == local, (depth, remote, local)
            assert local                     # the guard saw real ticks
