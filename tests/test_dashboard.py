"""Meta dashboard endpoint (reference: src/meta/src/dashboard/ — cluster
overview, fragment graphs, await-tree dumps)."""

import json
import urllib.request

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.dashboard import serve_dashboard


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def test_dashboard_endpoints():
    s = Session()
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.run_sql("CREATE MATERIALIZED VIEW m AS "
              "SELECT k % 2 AS g, sum(v) AS sv FROM t GROUP BY k % 2")
    s.run_sql("CREATE INDEX ix ON t (v)")
    s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
    s.tick()
    dash = serve_dashboard(s)
    try:
        status, html = _get(dash.port, "/")
        assert status == 200 and "dashboard" in html

        status, body = _get(dash.port, "/api/cluster")
        info = json.loads(body)
        assert info["catalog"]["tables"] == ["t"]
        assert info["catalog"]["materialized_views"] == ["m"]
        assert info["catalog"]["indexes"] == ["ix"]
        assert "__idx_ix" not in info["catalog"]["materialized_views"]
        assert info["epoch"] >= 1

        status, frags = _get(dash.port, "/api/fragments")
        assert status == 200 and "-- m" in frags and "Fragment" in frags

        status, tree = _get(dash.port, "/api/await_tree")
        assert status == 200 and "epoch" in tree

        status, body = _get(dash.port, "/api/metrics")
        m = json.loads(body)
        assert "barrier_latency" in m and "jobs" in m
    finally:
        dash.close()
        s.close()


def test_dashboard_404():
    s = Session()
    dash = serve_dashboard(s)
    try:
        import urllib.error
        try:
            _get(dash.port, "/api/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        dash.close()
        s.close()


def test_dashboard_trace_and_slow_epoch_endpoints():
    from risingwave_tpu.common.tracing import GLOBAL_TRACE

    GLOBAL_TRACE.clear()
    s = Session()
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.run_sql("CREATE MATERIALIZED VIEW m AS "
              "SELECT k, sum(v) AS sv FROM t GROUP BY k")
    s.run_sql("SET slow_epoch_threshold_ms = 0.0001")
    s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
    s.tick()
    s.tick()
    dash = serve_dashboard(s)
    try:
        status, body = _get(dash.port, "/api/trace")
        assert status == 200
        obj = json.loads(body)
        events = [e for e in obj["traceEvents"] if e.get("ph") == "X"]
        assert any(e["name"].startswith("epoch ") for e in events)
        assert any(e["cat"] == "barrier" for e in events)

        status, body = _get(dash.port, "/api/slow_epochs")
        assert status == 200
        slow = json.loads(body)
        assert slow and slow[-1]["spans"]      # span tree captured

        # landing page links the trace download
        _, html = _get(dash.port, "/")
        assert "/api/trace" in html and "slow_epochs" in html
    finally:
        dash.close()
        s.close()


def _post(port, path):
    """POST returning (status, json) — HTTPError codes included."""
    import urllib.error

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


@pytest.mark.slow
def test_dashboard_profiler_double_start_is_409_not_500(tmp_path):
    """ISSUE 12 satellite: two /start POSTs must answer 200 then 409 —
    never raise out of the handler (500) — and /stop without a capture
    is 409. A full start→stop→start cycle works."""
    s = Session()
    dash = serve_dashboard(s, profiler_dir=str(tmp_path / "prof"))
    try:
        status, obj = _post(dash.port, "/api/profiler/stop")
        assert status == 409, obj                 # nothing running yet
        status, obj = _post(dash.port, "/api/profiler/start")
        assert status == 200, obj
        status, obj = _post(dash.port, "/api/profiler/start")
        assert status == 409 and "error" in obj, obj
        status, obj = _post(dash.port, "/api/profiler/stop")
        assert status == 200, obj
        status, obj = _post(dash.port, "/api/profiler/stop")
        assert status == 409, obj                 # stop ran exactly once
        status, obj = _post(dash.port, "/api/profiler/start")
        assert status == 200, obj                 # restartable
    finally:
        dash.close()                              # stops the live capture
        s.close()


@pytest.mark.slow
def test_dashboard_profiler_foreign_capture_is_409(tmp_path):
    """The jax profiler is process-global: a capture started OUTSIDE
    this server (another dashboard instance, user code) makes
    start_trace raise — that must surface as 409, not a 500 from the
    handler thread."""
    import jax

    s = Session()
    dash = serve_dashboard(s, profiler_dir=str(tmp_path / "a"))
    jax.profiler.start_trace(str(tmp_path / "foreign"))
    try:
        status, obj = _post(dash.port, "/api/profiler/start")
        assert status == 409 and "error" in obj, (status, obj)
    finally:
        jax.profiler.stop_trace()
        dash.close()
        s.close()


@pytest.mark.slow
def test_dashboard_close_races_live_capture(tmp_path):
    """Server shutdown during a live capture stops the device trace
    exactly once (no dangling capture buffering forever), a /start
    racing close() answers 503, and a second close() is a no-op."""
    import jax

    s = Session()
    dash = serve_dashboard(s, profiler_dir=str(tmp_path / "p"))
    status, obj = _post(dash.port, "/api/profiler/start")
    assert status == 200, obj
    dash.close()                       # must stop_trace exactly once
    # the capture really ended: a fresh process-global trace can start
    jax.profiler.start_trace(str(tmp_path / "after"))
    jax.profiler.stop_trace()
    dash.close()                       # idempotent
    s.close()


def test_dashboard_profiler_endpoint_gated():
    """The jax.profiler endpoints are POST-only (a GET must not mutate
    profiler state) and answer 403 without profiler_dir — device trace
    capture must be an explicit operator decision."""
    import urllib.error

    def _post(port, path):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status

    s = Session()
    dash = serve_dashboard(s)
    try:
        for path in ("/api/profiler/start", "/api/profiler/stop"):
            try:
                _get(dash.port, path)
                raise AssertionError("expected 405")
            except urllib.error.HTTPError as e:
                assert e.code == 405          # GET never mutates
            try:
                _post(dash.port, path)
                raise AssertionError("expected 403")
            except urllib.error.HTTPError as e:
                assert e.code == 403          # disabled without opt-in
    finally:
        dash.close()
        s.close()
