"""Multi-process execution: worker processes hosting MV jobs behind the
session's meta/frontend process (VERDICT r4 item 2).

What crosses the REAL process boundary here:
  * serialized plans + catalog defs (create_job),
  * permit-metered exchange frames (DML deltas / backfill snapshots),
  * barrier inject / collect / two-phase checkpoint commit,
  * kill -9 of a worker driving scoped recovery end-to-end.

Reference: src/compute/src/rpc/service/stream_service.rs:46-233,
exchange_service.rs:74-133, recovery src/meta/src/barrier/recovery.rs:110.
"""

import pytest

from risingwave_tpu.frontend import Session

BID_DDL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,
channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)
WITH (connector='nexmark', nexmark_table='bid', rows_per_chunk='128')"""

Q5ISH = ("CREATE MATERIALIZED VIEW q AS SELECT auction, count(*) AS n, "
         "max(price) AS mx FROM bid GROUP BY auction")


@pytest.fixture
def cluster(tmp_path):
    s = Session(workers=1, seed=11, data_dir=str(tmp_path / "cluster"))
    yield s
    s.close()


class TestRemoteExchange:
    def test_table_fed_mv_over_the_wire(self, cluster):
        s = cluster
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, v * 2 AS d FROM t")
        assert "m" in s._remote_specs          # placed on the worker
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(1, 20), (2, 40)]
        s.run_sql("INSERT INTO t VALUES (3, 30)")
        s.run_sql("DELETE FROM t WHERE k = 1")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(2, 40), (3, 60)]

    def test_snapshot_backfill_of_existing_table(self, cluster):
        s = cluster
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1, 1), (2, 2), (3, 3)")
        s.flush()
        # MV created AFTER data exists: snapshot ships over the channel
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, v + 100 AS v FROM t")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(1, 101), (2, 102), (3, 103)]

    def test_backpressure_permits_bound_outstanding_chunks(self, tmp_path):
        from risingwave_tpu.frontend.build import BuildConfig
        s = Session(workers=1, seed=3,
                    config=BuildConfig(exchange_permits=2))
        try:
            s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
            s.run_sql("CREATE MATERIALIZED VIEW m AS "
                      "SELECT sum(v) AS s FROM t")
            # many separate chunks through a 2-permit channel: the
            # forwarder must block on acks, never lose or reorder
            for i in range(30):
                s.run_sql(f"INSERT INTO t VALUES ({i}, {i})")
                if i % 5 == 0:
                    s.tick(generate=False)
            s.flush()
            assert s.mv_rows("m") == [(sum(range(30)),)]
            sem = s.workers[0]._sems[
                next(iter(s._remote_specs["m"]["channels"].values()))]
            assert sem._value <= 2             # permits never over-release
        finally:
            s.close()

    def test_batch_select_reads_worker_state(self, cluster):
        s = cluster
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, v FROM t WHERE v >= 20")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
        s.flush()
        got = sorted(s.run_sql("SELECT k FROM m"))
        assert got == [(2,), (3,)]

    def test_drop_remote_mv(self, cluster):
        s = cluster
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k FROM t")
        s.run_sql("DROP MATERIALIZED VIEW m")
        assert "m" not in s._remote_specs
        assert "m" not in s.jobs
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t")
        s.run_sql("INSERT INTO t VALUES (7, 70)")
        s.flush()
        assert s.mv_rows("m") == [(7, 70)]


class TestRemoteSource:
    def test_source_fed_mv_matches_local(self, tmp_path):
        remote = Session(workers=1, seed=7)
        remote.run_sql(BID_DDL)
        remote.run_sql(Q5ISH)
        for _ in range(6):
            remote.tick()
        remote.flush()
        r_rows = sorted(remote.mv_rows("q"))
        remote.close()

        local = Session(seed=7)
        local.run_sql(BID_DDL)
        local.run_sql(Q5ISH)
        for _ in range(6):
            local.tick()
        local.flush()
        l_rows = sorted(local.mv_rows("q"))
        local.close()
        assert r_rows == l_rows and len(r_rows) > 10


class TestWorkerKillRecovery:
    @pytest.mark.slow
    def test_kill9_source_fed_exactly_once(self, tmp_path):
        """SIGKILL the worker mid-stream; the heartbeat detector declares
        its jobs dead, scoped recovery respawns the process over the same
        durable directory, offsets seek, and the deterministic source
        replays the uncommitted gap — final state identical to an
        uninterrupted run with the same generated epochs."""
        s = Session(workers=1, seed=11,
                    data_dir=str(tmp_path / "cluster"))
        s.run_sql(BID_DDL)
        s.run_sql(Q5ISH)
        for _ in range(5):
            s.tick()
        s.flush()
        _ = s.mv_rows("q")        # round-trip: phase-2 commit processed
        pid0 = s.workers[0].proc.pid
        s.workers[0].kill9()
        for _ in range(10):       # TTL = 3 epochs, then recovery in-tick
            s.tick()
            if not s.workers[0].dead:
                break
        assert not s.workers[0].dead, "worker was not recovered"
        assert s.workers[0].proc.pid != pid0
        for _ in range(5):
            s.tick()
        s.flush()
        r_rows = sorted(s.mv_rows("q"))
        s.close()

        local = Session(seed=11)
        local.run_sql(BID_DDL)
        local.run_sql(Q5ISH)
        for _ in range(10):       # 5 pre-kill + 5 post-recovery generates
            local.tick()
        local.flush()
        l_rows = sorted(local.mv_rows("q"))
        local.close()
        assert r_rows == l_rows

    def test_kill9_table_fed_rebuilds_from_snapshot(self, tmp_path):
        """A channel-fed job killed mid-stream rebuilds FRESH from the
        upstream table's current state — including rows inserted while
        the worker was down."""
        s = Session(workers=1, seed=5, data_dir=str(tmp_path / "cluster"))
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, v * 10 AS d FROM t")
        s.run_sql("INSERT INTO t VALUES (1, 1), (2, 2)")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(1, 10), (2, 20)]
        s.workers[0].kill9()
        s.run_sql("INSERT INTO t VALUES (3, 3)")   # while worker is dead
        for _ in range(10):
            s.tick(generate=False)
            if not s.workers[0].dead:
                break
        assert not s.workers[0].dead
        s.run_sql("INSERT INTO t VALUES (4, 4)")   # after recovery
        s.flush()
        assert sorted(s.mv_rows("m")) == [
            (1, 10), (2, 20), (3, 30), (4, 40)]

    def test_session_restart_replays_remote_jobs(self, tmp_path):
        d = str(tmp_path / "cluster")
        s = Session(workers=1, seed=9, data_dir=d)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, v + 1 AS v1 FROM t")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        s.close()
        # fresh session over the same dir: DDL replays, the remote job is
        # re-created on a fresh worker and rebuilt from the recovered table
        s2 = Session(workers=1, seed=9, data_dir=d)
        try:
            assert sorted(s2.mv_rows("m")) == [(1, 11), (2, 21)]
            s2.run_sql("INSERT INTO t VALUES (3, 30)")
            s2.flush()
            assert sorted(s2.mv_rows("m")) == [(1, 11), (2, 21), (3, 31)]
        finally:
            s2.close()


class TestRemoteGuards:
    def test_mv_on_remote_mv_rejected(self, cluster):
        s = cluster
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t")
        from risingwave_tpu.frontend.session import SqlError
        with pytest.raises(SqlError, match="worker-hosted"):
            s.run_sql("CREATE MATERIALIZED VIEW m2 AS SELECT k FROM m")

    def test_worker_side_failure_isolated(self, cluster):
        """A create_job that fails ON THE WORKER (bad connector options)
        surfaces as a per-statement error, keeps the worker and its other
        jobs alive, and rolls the id counter back (replay determinism)."""
        s = cluster
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW ok AS SELECT k, v FROM t")
        s.run_sql("INSERT INTO t VALUES (1, 1)")
        s.flush()
        s.run_sql("CREATE SOURCE badsrc (x BIGINT) "
                  "WITH (connector='file')")      # no path: reader fails
        id_before = s.catalog._next_table_id
        with pytest.raises(Exception, match="path"):
            s.run_sql("CREATE MATERIALIZED VIEW bad AS "
                      "SELECT x FROM badsrc")
        assert s.catalog._next_table_id == id_before
        assert not s.workers[0].dead              # worker survived
        s.run_sql("INSERT INTO t VALUES (2, 2)")
        s.flush()
        assert sorted(s.mv_rows("ok")) == [(1, 1), (2, 2)]


class TestDistributedBatch:
    """Batch stages execute ON the worker hosting the state; only result
    rows cross the socket (reference: distributed batch scheduling,
    scheduler/distributed/query.rs:69,115 — VERDICT r4 missing #7)."""

    def test_stage_pushdown_filter_project(self, cluster):
        s = cluster
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30), (4, 5)")
        s.flush()
        # the plan cuts into a RemoteFragment (scan+filter+project on the
        # worker)
        from risingwave_tpu.frontend.parser import parse_sql
        from risingwave_tpu.frontend.planner import PRemoteFragment

        plan = s._plan(parse_sql(
            "SELECT k FROM m WHERE v >= 20")[0].select)
        cut = s._push_remote_fragments(plan)

        def frags(p):
            if isinstance(p, PRemoteFragment):
                return 1
            return sum(frags(c) for c in p.children)

        assert frags(cut) == 1, cut.explain()
        got = sorted(s.run_sql("SELECT k FROM m WHERE v >= 20"))
        assert got == [(2,), (3,)]

    def test_stage_feeds_sessionside_agg(self, cluster):
        s = cluster
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
        s.flush()
        got = s.run_sql("SELECT count(*) AS n, sum(v) AS sv FROM m "
                        "WHERE v > 10")
        assert got == [(2, 50)]

    def test_stage_error_is_per_request(self, cluster):
        """A malformed stage answers THIS request with an error frame —
        it must not tear down the worker (per-request isolation)."""
        s = cluster
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t")
        s.run_sql("INSERT INTO t VALUES (1, 10)")
        s.flush()
        worker = s._remote_specs["m"]["worker"]
        with pytest.raises(Exception):
            s._await(worker.request(
                {"type": "batch_task", "job": "m",
                 "plan": "{this is not json", "defs": "[]"}))
        with pytest.raises(Exception):
            s._await(worker.request(
                {"type": "batch_task", "job": "no_such_job",
                 "plan": "{}", "defs": "[]"}))
        # the worker survives both and keeps serving stages
        assert not worker.dead
        s.run_sql("INSERT INTO t VALUES (2, 20)")
        s.flush()
        assert sorted(s.run_sql("SELECT k FROM m")) == [(1,), (2,)]
