"""RetryPolicy unit contract (deadline, jitter bounds, classification,
counters) + the object-store retry/fault-injection wrappers (ISSUE 3
tentpole: the fault-tolerance primitive every boundary shares)."""

import random

import pytest

from risingwave_tpu.common.retry import (
    GLOBAL_RETRY_METRICS, RetryError, RetryPolicy, _RetryMetrics,
)
from risingwave_tpu.storage.object_store import (
    FaultInjectingObjectStore, MemObjectStore, PermanentObjectStoreError,
    RetryingObjectStore, TransientObjectStoreError, wrap_object_store,
)


def _no_sleep(_s):
    pass


class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self):
        m = _RetryMetrics()
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        p = RetryPolicy(max_attempts=5, base_delay_ms=1.0)
        assert p.run("t.site", flaky, sleep=_no_sleep, metrics=m) == "ok"
        assert len(calls) == 3
        snap = m.snapshot()["t.site"]
        assert snap["attempts"] == 3
        assert snap["retries"] == 2
        assert snap["successes"] == 1
        assert snap["give_ups"] == 0

    def test_attempt_cap_gives_up_with_cause(self):
        m = _RetryMetrics()

        def always():
            raise ConnectionError("down")

        p = RetryPolicy(max_attempts=3, base_delay_ms=0.0)
        with pytest.raises(RetryError) as ei:
            p.run("t.cap", always, sleep=_no_sleep, metrics=m)
        assert isinstance(ei.value.__cause__, ConnectionError)
        snap = m.snapshot()["t.cap"]
        assert snap["attempts"] == 3 and snap["give_ups"] == 1

    def test_deadline_cuts_attempts_short(self):
        m = _RetryMetrics()
        clock = {"t": 0.0}

        def slow_fail():
            clock["t"] += 1.0          # each attempt "takes" 1s
            raise OSError("slow boundary")

        import risingwave_tpu.common.retry as retry_mod
        real_monotonic = retry_mod.time.monotonic
        try:
            retry_mod.time.monotonic = lambda: clock["t"]
            p = RetryPolicy(max_attempts=100, base_delay_ms=0.0,
                            deadline_ms=2500.0)
            with pytest.raises(RetryError) as ei:
                p.run("t.deadline", slow_fail, sleep=_no_sleep, metrics=m)
        finally:
            retry_mod.time.monotonic = real_monotonic
        assert "deadline" in str(ei.value)
        # deadline of 2.5s with 1s attempts: attempt 3 crosses it —
        # far short of the 100-attempt cap
        assert m.snapshot()["t.deadline"]["attempts"] == 3

    def test_non_retryable_passes_straight_through(self):
        m = _RetryMetrics()
        calls = []

        def bad():
            calls.append(1)
            raise PermanentObjectStoreError("no such bucket")

        p = RetryPolicy(max_attempts=5,
                        retryable=(OSError, RuntimeError),
                        non_retryable=(PermanentObjectStoreError,))
        with pytest.raises(PermanentObjectStoreError):
            p.run("t.perm", bad, sleep=_no_sleep, metrics=m)
        assert len(calls) == 1        # no second attempt
        assert m.snapshot()["t.perm"]["non_retryable"] == 1

        def unexpected():
            raise ValueError("programming error")

        with pytest.raises(ValueError):   # unclassified != retryable
            p.run("t.perm", unexpected, sleep=_no_sleep, metrics=m)

    def test_jitter_bounds_full_jitter(self):
        p = RetryPolicy(base_delay_ms=10.0, max_delay_ms=100.0)
        rng = random.Random(7)
        for attempt in range(1, 12):
            cap = min(100.0, 10.0 * 2 ** (attempt - 1))
            for _ in range(50):
                d = p.backoff_ms(attempt, rng)
                assert 0.0 <= d <= cap
        # jitter actually spreads (not constant)
        samples = {round(p.backoff_ms(3, rng), 3) for _ in range(20)}
        assert len(samples) > 1

    def test_sleep_durations_respect_deadline(self):
        slept = []

        def always():
            raise OSError("x")

        p = RetryPolicy(max_attempts=4, base_delay_ms=5.0,
                        deadline_ms=10_000.0)
        with pytest.raises(RetryError):
            p.run("t.sleep", always, sleep=slept.append,
                  metrics=_RetryMetrics(), rng=random.Random(1))
        assert len(slept) == 3         # one backoff between attempts
        assert all(s >= 0 for s in slept)


class TestRetryingObjectStore:
    def test_transient_faults_absorbed(self):
        inner = FaultInjectingObjectStore(
            MemObjectStore(), seed=3, transient_rate=0.4)
        st = RetryingObjectStore(
            inner, RetryPolicy(max_attempts=10, base_delay_ms=0.0))
        for i in range(50):
            st.put(f"k{i}", b"v%d" % i)
        for i in range(50):
            assert st.get(f"k{i}") == b"v%d" % i
        assert st.list("k") and inner.faults_injected > 0
        snap = GLOBAL_RETRY_METRICS.snapshot()
        assert snap["object_store.put"]["retries"] > 0

    def test_torn_write_fully_overwritten_by_retry(self):
        inner = FaultInjectingObjectStore(
            MemObjectStore(), seed=1, torn_write_rate=1.0)
        st = RetryingObjectStore(
            inner, RetryPolicy(max_attempts=3, base_delay_ms=0.0))
        # every attempt tears: past the budget the torn object is visible
        # to the BACKEND but the caller got a loud error (the manifest
        # discipline above never references it)
        with pytest.raises(RetryError):
            st.put("seg", b"full-payload-bytes")
        assert inner.torn_writes == 3
        assert inner.inner.get("seg") != b"full-payload-bytes"
        # now the fault clears: the retry rewrites the WHOLE object
        inner.torn_write_rate = 0.0
        st.put("seg", b"full-payload-bytes")
        assert st.get("seg") == b"full-payload-bytes"

    def test_permanent_path_not_retried(self):
        inner = FaultInjectingObjectStore(
            MemObjectStore(), permanent_paths=("locked/",))
        st = wrap_object_store(
            inner, RetryPolicy(max_attempts=5, base_delay_ms=0.0,
                               non_retryable=(PermanentObjectStoreError,)))
        with pytest.raises(PermanentObjectStoreError):
            st.put("locked/x", b"v")
        st.put("open/x", b"v")         # other paths unaffected
        assert st.get("open/x") == b"v"

    def test_wrap_is_idempotent(self):
        st = wrap_object_store(MemObjectStore())
        assert wrap_object_store(st) is st

    def test_atomic_put_never_tears(self):
        inner = FaultInjectingObjectStore(
            MemObjectStore(), seed=5, transient_rate=0.5)
        inner.inner.put("m", b"old")
        st = wrap_object_store(
            inner, RetryPolicy(max_attempts=12, base_delay_ms=0.0))
        for i in range(30):
            st.atomic_put("m", b"new%03d" % i)
            raw = inner.inner.get("m")
            assert raw == b"new%03d" % i    # old or new, never a mix
        assert isinstance(TransientObjectStoreError("x"), OSError)
