"""Online reschedule (coverage #56/#78): rebuild a live MV job under a new
BuildConfig — including onto a device mesh — from durable state, without
losing or duplicating rows."""

import jax
import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.build import BuildConfig


def _mesh(n):
    import numpy as np
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]), ("shard",))


class TestReschedule:
    def test_rescale_onto_mesh_continues_exactly(self, tmp_path):
        s = Session(data_dir=str(tmp_path / "db"))
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW g AS "
                  "SELECT k % 4 AS grp, sum(v) AS sv FROM t GROUP BY k % 4")
        for i in range(8):
            s.run_sql(f"INSERT INTO t VALUES ({i}, {i * 10})")
        s.flush()
        before = sorted(s.mv_rows("g"))

        s.reschedule("g", BuildConfig(mesh=_mesh(4)))
        assert sorted(s.mv_rows("g")) == before
        # the rebuilt pipeline is the mesh-sharded executor
        ex = s.jobs["g"].pipeline
        names = set()
        while ex is not None:
            names.add(type(ex).__name__)
            ex = getattr(ex, "input", None)
        assert "ShardedHashAggExecutor" in names

        for i in range(8, 12):
            s.run_sql(f"INSERT INTO t VALUES ({i}, {i * 10})")
        s.flush()
        got = dict(s.mv_rows("g"))
        expect = {}
        for i in range(12):
            expect[i % 4] = expect.get(i % 4, 0) + i * 10
        assert got == expect

    def test_reschedule_preserves_downstream_subscription(self, tmp_path):
        s = Session(data_dir=str(tmp_path / "db"))
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW a AS SELECT k, v * 2 AS d FROM t")
        s.run_sql("CREATE MATERIALIZED VIEW b AS SELECT sum(d) AS s FROM a")
        s.run_sql("INSERT INTO t VALUES (1, 10)")
        s.flush()
        assert s.mv_rows("b") == [(20,)]
        s.reschedule("a")          # same config; exercises the rebuild
        s.run_sql("INSERT INTO t VALUES (2, 5)")
        s.flush()
        # downstream b kept receiving deltas through the rebuilt job's bus
        assert s.mv_rows("b") == [(30,)]
        assert sorted(s.mv_rows("a")) == [(1, 20), (2, 10)]

    def test_reschedule_source_job_seeks_offsets(self, tmp_path):
        s = Session(data_dir=str(tmp_path / "db"), source_chunk_capacity=4,
                    checkpoint_frequency=1)
        s.run_sql("""CREATE SOURCE g (k BIGINT)
                     WITH (connector='datagen',
                           'datagen.rows.per.chunk'=4)""")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k FROM g")
        for _ in range(3):
            s.tick()
        s._drain_inflight()
        n0 = len(s.mv_rows("m"))
        assert n0 == 12
        s.reschedule("m")
        s.tick()
        s._drain_inflight()
        rows = sorted(r[0] for r in s.mv_rows("m"))
        # no duplicates, no gaps: the reader resumed at its offset
        assert rows == list(range(len(rows)))
        assert len(rows) == n0 + 4
