"""Online reschedule (coverage #56/#78): rebuild a live MV job under a new
BuildConfig — including onto a device mesh — from durable state, without
losing or duplicating rows."""

import jax
import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.build import BuildConfig


def _mesh(n):
    import numpy as np
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(devs[:n]), ("shard",))


class TestReschedule:
    def test_rescale_onto_mesh_continues_exactly(self, tmp_path):
        s = Session(data_dir=str(tmp_path / "db"))
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW g AS "
                  "SELECT k % 4 AS grp, sum(v) AS sv FROM t GROUP BY k % 4")
        for i in range(8):
            s.run_sql(f"INSERT INTO t VALUES ({i}, {i * 10})")
        s.flush()
        before = sorted(s.mv_rows("g"))

        s.reschedule("g", BuildConfig(mesh=_mesh(4)))
        assert sorted(s.mv_rows("g")) == before
        # the rebuilt pipeline is the mesh-sharded executor
        ex = s.jobs["g"].pipeline
        names = set()
        while ex is not None:
            names.add(type(ex).__name__)
            ex = getattr(ex, "input", None)
        assert "ShardedHashAggExecutor" in names

        for i in range(8, 12):
            s.run_sql(f"INSERT INTO t VALUES ({i}, {i * 10})")
        s.flush()
        got = dict(s.mv_rows("g"))
        expect = {}
        for i in range(12):
            expect[i % 4] = expect.get(i % 4, 0) + i * 10
        assert got == expect

    def test_restart_preserves_mesh_layout(self, tmp_path):
        """Round-4 weak #5: a rescaled job must keep its layout across a
        restart. The reschedule persists the config's durable form (mesh
        topology) in the DDL log; recovery replays the CREATE under it."""
        d = str(tmp_path / "db")
        s = Session(data_dir=d)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW g AS "
                  "SELECT k % 4 AS grp, sum(v) AS sv FROM t GROUP BY k % 4")
        for i in range(8):
            s.run_sql(f"INSERT INTO t VALUES ({i}, {i * 10})")
        s.flush()
        before = sorted(s.mv_rows("g"))
        s.reschedule("g", BuildConfig(mesh=_mesh(4)))
        s.close()

        import warnings
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            s2 = Session(data_dir=d)
        # the legacy behavior warned "configs are not persisted" — now the
        # layout must restore silently
        assert not [w for w in caught if "reschedule" in str(w.message)]
        assert sorted(s2.mv_rows("g")) == before
        ex = s2.jobs["g"].pipeline
        names = set()
        while ex is not None:
            names.add(type(ex).__name__)
            ex = getattr(ex, "input", None)
        assert "ShardedHashAggExecutor" in names   # layout survived restart
        s2.run_sql("INSERT INTO t VALUES (100, 7)")
        s2.flush()
        got = dict(s2.mv_rows("g"))
        assert got[0] == sum(i * 10 for i in range(0, 8, 4)) + 7
        s2.close()

    def test_drop_voids_persisted_reschedule_config(self, tmp_path):
        """A DROP after a reschedule voids the persisted layout: a re-CREATE
        under the same name is a NEW job and must recover with the session
        default, not the stale rescaled config."""
        d = str(tmp_path / "db")
        s = Session(data_dir=d)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW g AS "
                  "SELECT k % 2 AS grp, sum(v) AS sv FROM t GROUP BY k % 2")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        s.reschedule("g", BuildConfig(mesh=_mesh(4)))
        s.run_sql("DROP MATERIALIZED VIEW g")
        s.run_sql("CREATE MATERIALIZED VIEW g AS "
                  "SELECT k % 2 AS grp, sum(v) AS sv FROM t GROUP BY k % 2")
        s.flush()
        want = sorted(s.mv_rows("g"))
        s.close()

        s2 = Session(data_dir=d)
        assert sorted(s2.mv_rows("g")) == want
        ex = s2.jobs["g"].pipeline
        names = set()
        while ex is not None:
            names.add(type(ex).__name__)
            ex = getattr(ex, "input", None)
        assert "ShardedHashAggExecutor" not in names   # default layout
        s2.close()

    def test_reschedule_preserves_downstream_subscription(self, tmp_path):
        s = Session(data_dir=str(tmp_path / "db"))
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW a AS SELECT k, v * 2 AS d FROM t")
        s.run_sql("CREATE MATERIALIZED VIEW b AS SELECT sum(d) AS s FROM a")
        s.run_sql("INSERT INTO t VALUES (1, 10)")
        s.flush()
        assert s.mv_rows("b") == [(20,)]
        s.reschedule("a")          # same config; exercises the rebuild
        s.run_sql("INSERT INTO t VALUES (2, 5)")
        s.flush()
        # downstream b kept receiving deltas through the rebuilt job's bus
        assert s.mv_rows("b") == [(30,)]
        assert sorted(s.mv_rows("a")) == [(1, 20), (2, 10)]

    def test_reschedule_source_job_seeks_offsets(self, tmp_path):
        s = Session(data_dir=str(tmp_path / "db"), source_chunk_capacity=4,
                    checkpoint_frequency=1)
        s.run_sql("""CREATE SOURCE g (k BIGINT)
                     WITH (connector='datagen',
                           'datagen.rows.per.chunk'=4)""")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k FROM g")
        for _ in range(3):
            s.tick()
        s._drain_inflight()
        n0 = len(s.mv_rows("m"))
        assert n0 == 12
        s.reschedule("m")
        s.tick()
        s._drain_inflight()
        rows = sorted(r[0] for r in s.mv_rows("m"))
        # no duplicates, no gaps: the reader resumed at its offset
        assert rows == list(range(len(rows)))
        assert len(rows) == n0 + 4
