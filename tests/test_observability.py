"""Cross-process metrics federation (VERDICT rec #9): a job scheduled
onto a WorkerHost must be as observable as a local one — its executor
tree and counters reach the dashboard HTTP payload, the /metrics
Prometheus exposition, and the Chrome trace export WHILE it runs
(reference: MonitorService.stack_trace + per-compute-node exporters,
src/compute/src/rpc/service/monitor_service.rs:46)."""

import json
import urllib.request

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.dashboard import serve_dashboard
from risingwave_tpu.frontend.prometheus import render_metrics


@pytest.fixture
def cluster(tmp_path):
    from risingwave_tpu.common.tracing import GLOBAL_TRACE

    GLOBAL_TRACE.clear()
    s = Session(workers=1, seed=11, data_dir=str(tmp_path / "cluster"))
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, v * 2 AS d FROM t")
    assert "m" in s._remote_specs          # placed on the worker
    s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
    s.flush()
    yield s
    s.close()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def test_worker_job_counters_federate_into_metrics(cluster):
    s = cluster
    m = s.metrics()
    # the worker-hosted job appears exactly like a local job
    assert "m" in m["jobs"]
    mat = next(v for k, v in m["jobs"]["m"].items()
               if k.startswith("Materialize"))
    assert mat["barriers"] >= 1 and mat["chunks_in"] >= 1
    assert "m" in m["state_bytes"]
    (w,) = m["workers"]
    assert w["worker"] == 0 and not w["dead"] and "m" in w["jobs"]


def test_worker_job_in_prometheus_exposition(cluster):
    text = render_metrics(cluster)
    assert 'rw_executor_counter{job="m"' in text
    assert 'rw_state_bytes{job="m"}' in text
    assert 'rw_worker_up{worker="0"} 1' in text


def test_worker_await_tree_visible_over_http(cluster):
    """The done-criterion: the await-tree of a worker-hosted job,
    visible over HTTP while it runs."""
    s = cluster
    dash = serve_dashboard(s)
    try:
        status, tree = _get(dash.port, "/api/await_tree")
        assert status == 200
        assert "job 'm' (worker 0)" in tree
        assert "Materialize" in tree           # the tree, not just a name

        status, body = _get(dash.port, "/api/metrics")
        dm = json.loads(body)
        assert "m" in dm["jobs"] and "m" in dm["state_bytes"]
        assert dm["workers"][0]["jobs"] == ["m"]
    finally:
        dash.close()


def test_worker_spans_merge_into_chrome_trace(cluster):
    """Worker barrier spans ship over the stats frame and land in the
    export as their own process, aligned on the shared wall clock."""
    s = cluster
    obj = s.export_chrome_trace()
    events = [e for e in obj["traceEvents"] if e.get("ph") == "X"]
    worker_events = [e for e in events if e["pid"] == 1]   # worker 0
    assert any(e["cat"] == "barrier" for e in worker_events)
    metas = [e for e in obj["traceEvents"] if e.get("ph") == "M"]
    names = {m["args"]["name"] for m in metas}
    assert {"session", "worker-0"} <= names


def test_slow_epoch_capture_includes_worker_spans(cluster):
    """The slow-epoch snapshot force-polls workers first, so a
    worker-hosted job's capture holds its executor spans — not just the
    conductor side."""
    s = cluster
    s.run_sql("SET slow_epoch_threshold_ms = 0.0001")   # everything trips
    s.run_sql("INSERT INTO t VALUES (3, 30)")
    s.flush()
    slow = s.slow_epochs()
    assert slow
    spans = slow[-1]["spans"]
    assert any(sp["pid"] == 1 for sp in spans), spans   # worker-0 spans


def test_stats_span_outbox_resends_until_acked(tmp_path):
    """A drained span batch is retained by the worker until the next
    stats request acknowledges its sequence number — a timed-out
    (discarded) stats reply resends spans instead of losing them."""
    from risingwave_tpu.common.tracing import GLOBAL_TRACE, Span
    from risingwave_tpu.worker.host import WorkerHost

    GLOBAL_TRACE.clear()
    h = WorkerHost(str(tmp_path), worker_id=0)
    GLOBAL_TRACE.record(Span("a", "barrier", 0.0, 0.001, epoch=1))
    r1 = h.handle_stats({"type": "stats"})
    assert [s["name"] for s in r1["spans"]] == ["a"]
    # reply lost: the next request carries a stale ack -> resend + new
    GLOBAL_TRACE.record(Span("b", "barrier", 0.0, 0.001, epoch=2))
    r2 = h.handle_stats({"type": "stats", "span_ack": r1["span_seq"] - 1})
    assert [s["name"] for s in r2["spans"]] == ["a", "b"]
    # reply processed: acking the current seq clears the outbox
    r3 = h.handle_stats({"type": "stats", "span_ack": r2["span_seq"]})
    assert r3["spans"] == []
    GLOBAL_TRACE.clear()


def test_dead_worker_keeps_last_snapshot(cluster):
    """A dead worker's last stats snapshot survives for post-hoc
    inspection, and the exposition flips its liveness gauge."""
    import time

    s = cluster
    s.metrics()                               # populate the cache
    s.workers[0].kill9()
    time.sleep(0.6)                           # past the poll rate-limit
    m = s.metrics()                           # federation skips the corpse
    assert "m" in m["jobs"]                   # cached snapshot retained
    assert m["workers"][0]["dead"]
    assert 'rw_worker_up{worker="0"} 0' in render_metrics(s)
