"""Project/Filter executor tests incl. update-pair consistency through
filters (reference: filter.rs op-fixup; dispatch.rs:635-650 pairing rules)."""

import asyncio

from risingwave_tpu.common import (
    BOOL, INT64, OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT,
    Schema, chunk_to_rows, make_chunk,
)
from risingwave_tpu.expr import col
from risingwave_tpu.stream import (
    Barrier, FilterExecutor, MockSource, ProjectExecutor, wrap_debug,
)

SCHEMA = Schema.of(("a", INT64), ("b", INT64))


async def drain_rows(ex):
    out = []
    async for msg in ex.execute():
        from risingwave_tpu.common import StreamChunk
        if isinstance(msg, StreamChunk):
            out.extend(chunk_to_rows(msg, ex.schema, with_ops=True))
    return out


def test_project():
    src = MockSource(SCHEMA, [
        Barrier.new(1),
        make_chunk(SCHEMA, [(1, 2), (3, 4)]),
        Barrier.new(2),
    ])
    ex = ProjectExecutor(src, [col(0, INT64) + col(1, INT64), col(0, INT64) * 10])
    rows = asyncio.run(drain_rows(wrap_debug(ex)))
    assert rows == [(OP_INSERT, (3, 10)), (OP_INSERT, (7, 30))]


def test_filter_simple():
    src = MockSource(SCHEMA, [
        Barrier.new(1),
        make_chunk(SCHEMA, [(1, 2), (5, 4), (9, 1)]),
        Barrier.new(2),
    ])
    ex = FilterExecutor(src, col(0, INT64) > 3)
    rows = asyncio.run(drain_rows(wrap_debug(ex)))
    assert [r for _, r in rows] == [(5, 4), (9, 1)]


def test_filter_degrades_broken_update_pairs():
    # update moves a=2->a=8; filter a>3 keeps only the U+ side -> must become Insert
    chunk = make_chunk(
        SCHEMA,
        [(2, 1), (8, 1), (5, 2), (6, 2)],
        ops=[OP_UPDATE_DELETE, OP_UPDATE_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT],
    )
    src = MockSource(SCHEMA, [Barrier.new(1), chunk, Barrier.new(2)])
    ex = FilterExecutor(src, col(0, INT64) > 3)
    rows = asyncio.run(drain_rows(wrap_debug(ex)))
    assert rows == [
        (OP_INSERT, (8, 1)),          # degraded: its U- was filtered
        (OP_UPDATE_DELETE, (5, 2)),   # intact pair passes through
        (OP_UPDATE_INSERT, (6, 2)),
    ]


def test_filter_null_predicate_drops_row():
    sch = Schema.of(("a", INT64), ("flag", BOOL))
    src = MockSource(sch, [
        Barrier.new(1),
        make_chunk(sch, [(1, True), (2, None), (3, False)]),
        Barrier.new(2),
    ])
    ex = FilterExecutor(src, col(1, BOOL))
    rows = asyncio.run(drain_rows(ex))
    assert [r for _, r in rows] == [(1, True)]
