"""Pallas kernel parity: the fused rank/total kernel must match the jnp
matmul formulation bit-for-bit (interpret mode on CPU; the same kernel
compiles for TPU — SURVEY.md §7 stage 3)."""

import numpy as np
import pytest

import jax.numpy as jnp

from risingwave_tpu.ops.pallas_rank import (
    rank_totals_jnp, rank_totals_pallas,
)


@pytest.mark.parametrize("n,w,seed", [(256, 8, 0), (512, 128, 1),
                                      (1024, 16, 2)])
def test_rank_totals_parity(n, w, seed):
    rng = np.random.default_rng(seed)
    # idents cluster heavily (hot keys) and include -1 (no-match rows)
    ident = rng.integers(-1, 12, size=n).astype(np.int32)
    matches = rng.random((n, w)) < 0.3
    r_ref, t_ref = rank_totals_jnp(jnp.asarray(ident),
                                   jnp.asarray(matches))
    r_k, t_k = rank_totals_pallas(jnp.asarray(ident),
                                  jnp.asarray(matches), interpret=True)
    np.testing.assert_array_equal(np.asarray(r_ref), np.asarray(r_k))
    np.testing.assert_array_equal(np.asarray(t_ref), np.asarray(t_k))


def test_rank_totals_semantics_small():
    """Hand-checked: rows 0,2 share key 7; row 3 shares with nobody."""
    ident = jnp.asarray([7, 1, 7, -1], jnp.int32)
    matches = jnp.asarray([[1], [1], [1], [1]], bool)
    r, t = rank_totals_pallas(ident, matches, interpret=True)
    # r counts EARLIER same-key matching rows; t counts all of them
    np.testing.assert_array_equal(np.asarray(r), [[0], [0], [1], [0]])
    np.testing.assert_array_equal(np.asarray(t), [[2], [1], [2], [0]])


def test_ragged_capacity_falls_back():
    ident = jnp.asarray(np.arange(100, dtype=np.int32))
    matches = jnp.ones((100, 4), bool)
    r, t = rank_totals_pallas(ident, matches)   # 100 % 256 != 0 → jnp
    r2, t2 = rank_totals_jnp(ident, matches)
    np.testing.assert_array_equal(np.asarray(r), np.asarray(r2))
    np.testing.assert_array_equal(np.asarray(t), np.asarray(t2))
