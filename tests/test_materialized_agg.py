"""Materialized-input aggregation (stream/materialized_agg.py): exact
DISTINCT, array_agg / string_agg / percentile_cont / mode, and
min/max under retraction — the reference's AggStateStorage::
MaterializedInput surface (reference: src/stream/src/executor/aggregation/
{agg_state.rs,minput.rs,distinct.rs}, src/expr/src/agg/)."""

import os
import tempfile

from risingwave_tpu.frontend import Session


DDL = """
CREATE TABLE t (id BIGINT PRIMARY KEY, k BIGINT, v BIGINT, s VARCHAR)
"""


def fresh(data_dir=None):
    s = Session(data_dir=data_dir) if data_dir else Session()
    s.run_sql(DDL)
    return s


def test_count_distinct_exact():
    s = fresh()
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, "
              "count(distinct v) AS dv, count(*) AS n FROM t GROUP BY k")
    s.run_sql("INSERT INTO t VALUES (1, 1, 10, 'a'), (2, 1, 10, 'b'), "
              "(3, 1, 20, 'c'), (4, 2, 5, 'd')")
    s.tick()
    assert sorted(s.mv_rows("m")) == [(1, 2, 3), (2, 1, 1)]
    # retraction: deleting one of the duplicated 10s must keep dv == 2
    s.run_sql("DELETE FROM t WHERE s = 'a'")
    s.tick()
    assert sorted(s.mv_rows("m")) == [(1, 2, 2), (2, 1, 1)]
    # deleting the last 10 drops it from the distinct set
    s.run_sql("DELETE FROM t WHERE s = 'b'")
    s.tick()
    assert sorted(s.mv_rows("m")) == [(1, 1, 1), (2, 1, 1)]
    s.close()


def test_min_max_with_retraction():
    """Monotone device lanes cannot retract an extremum; the materialized
    path must (q106 shape)."""
    s = fresh()
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, min(v) AS lo, "
              "max(v) AS hi FROM t GROUP BY k")
    s.run_sql("INSERT INTO t VALUES (1, 1, 10, 'a'), (2, 1, 30, 'b'), (3, 1, 20, 'c')")
    s.tick()
    assert s.mv_rows("m") == [(1, 10, 30)]
    s.run_sql("DELETE FROM t WHERE v = 10")          # retract the min
    s.tick()
    assert s.mv_rows("m") == [(1, 20, 30)]
    s.run_sql("DELETE FROM t WHERE v = 30")          # retract the max
    s.tick()
    assert s.mv_rows("m") == [(1, 20, 20)]
    s.close()


def test_array_agg_and_string_agg_retraction():
    s = fresh()
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, array_agg(v) AS vs, "
              "string_agg(s, ',') AS ss FROM t GROUP BY k")
    s.run_sql("INSERT INTO t VALUES (1, 1, 3, 'x'), (2, 1, 1, 'y'), (3, 1, 2, 'x')")
    s.tick()
    rows = s.mv_rows("m")
    assert rows == [(1, (1, 2, 3), "x,x,y")]
    s.run_sql("DELETE FROM t WHERE v = 2")
    s.tick()
    assert s.mv_rows("m") == [(1, (1, 3), "x,y")]
    # group death removes the output row entirely
    s.run_sql("DELETE FROM t WHERE k = 1")
    s.tick()
    assert s.mv_rows("m") == []
    s.close()


def test_percentile_and_mode():
    s = fresh()
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT "
              "percentile_cont(0.5) WITHIN GROUP (ORDER BY v) AS med, "
              "mode() WITHIN GROUP (ORDER BY v) AS md FROM t")
    s.run_sql("INSERT INTO t VALUES (1, 1, 10, 'a'), (2, 1, 20, 'b'), "
              "(3, 1, 20, 'c'), (4, 1, 40, 'd')")
    s.tick()
    assert s.mv_rows("m") == [(20.0, 20)]
    s.run_sql("INSERT INTO t VALUES (5, 1, 50, 'e')")
    s.tick()
    assert s.mv_rows("m") == [(20.0, 20)]
    s.run_sql("DELETE FROM t WHERE v = 20")
    s.tick()
    med = s.mv_rows("m")[0][0]
    assert abs(med - 40.0) < 1e-9                     # {10,40,50}
    s.close()


def test_agg_filter_clause():
    s = fresh()
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, "
              "count(*) FILTER (WHERE v > 10) AS big, "
              "sum(v) FILTER (WHERE v <= 10) AS small, "
              "count(distinct s) FILTER (WHERE v > 10) AS ds "
              "FROM t GROUP BY k")
    s.run_sql("INSERT INTO t VALUES (1, 1, 5, 'a'), (2, 1, 15, 'b'), "
              "(3, 1, 25, 'b'), (4, 1, 8, 'c')")
    s.tick()
    assert s.mv_rows("m") == [(1, 2, 13, 1)]
    s.close()


def test_materialized_state_recovery():
    """Multisets persist by content and reload exactly: a restarted
    session must produce identical distinct counts / arrays, including
    string values re-interned in a fresh dictionary."""
    with tempfile.TemporaryDirectory() as d:
        data = os.path.join(d, "data")
        s = Session(data_dir=data)
        s.run_sql(DDL)
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, "
                  "count(distinct v) AS dv, array_agg(v) AS vs, "
                  "string_agg(s, '-') AS ss, min(v) AS lo FROM t GROUP BY k")
        s.run_sql("INSERT INTO t VALUES (1, 1, 10, 'a'), (2, 1, 10, 'b'), "
                  "(3, 1, 30, 'c'), (4, 2, 7, 'z')")
        s.tick()
        s.run_sql("FLUSH")
        before = sorted(s.mv_rows("m"))
        s.close()

        s2 = Session(data_dir=data)
        assert sorted(s2.mv_rows("m")) == before
        # the reloaded multiset keeps retracting correctly
        s2.run_sql("DELETE FROM t WHERE s = 'a'")
        s2.tick()
        assert sorted(s2.mv_rows("m")) == [
            (1, 2, (10, 30), "b-c", 10), (2, 1, (7,), "z", 7)]
        s2.run_sql("DELETE FROM t WHERE s = 'b'")
        s2.tick()
        assert sorted(s2.mv_rows("m")) == [
            (1, 1, (30,), "c", 30), (2, 1, (7,), "z", 7)]
        s2.close()


def test_global_distinct_zero_row():
    """Global (no GROUP BY) materialized agg shows count = 0 before any
    input and returns to 0 after full retraction — never an empty MV
    (SimpleAggExecutor's first-barrier contract)."""
    s = fresh()
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT "
              "count(distinct v) AS dv, min(v) AS lo FROM t")
    s.tick()
    assert s.mv_rows("m") == [(0, None)]
    s.run_sql("INSERT INTO t VALUES (1, 1, 5, 'a'), (2, 1, 5, 'b')")
    s.tick()
    assert s.mv_rows("m") == [(1, 5)]
    s.run_sql("DELETE FROM t WHERE k = 1")
    s.tick()
    assert s.mv_rows("m") == [(0, None)]
    s.close()


def test_unnest_and_array_functions():
    s = fresh()
    assert s.run_sql("SELECT * FROM unnest(ARRAY[3, 1, 2])") == [
        (3,), (1,), (2,)]
    assert s.run_sql("SELECT (ARRAY[10, 20, 30])[2] AS x") == [(20,)]
    assert s.run_sql("SELECT array_length(ARRAY[1, 2, 3]) AS n") == [(3,)]
    s.run_sql("CREATE MATERIALIZED VIEW ag AS SELECT k, array_agg(v) AS vs "
              "FROM t GROUP BY k")
    s.run_sql("INSERT INTO t VALUES (1, 1, 4, 'a'), (2, 1, 6, 'b'), (3, 2, 9, 'c')")
    s.tick()
    s.run_sql("CREATE MATERIALIZED VIEW un AS SELECT k, unnest(vs) AS v "
              "FROM ag")
    s.tick()
    assert sorted(s.run_sql("SELECT * FROM un")) == [
        (1, 4), (1, 6), (2, 9)]
    # retraction flows through unnest: the array shrinks, rows retract
    s.run_sql("DELETE FROM t WHERE s = 'b'")
    s.tick()
    assert sorted(s.run_sql("SELECT * FROM un")) == [(1, 4), (2, 9)]
    s.close()


def test_approx_count_distinct_with_materialized_sibling():
    """A CREATE MV mixing approx_count_distinct with another
    materialized-input agg routes ALL calls to MaterializedAggExecutor
    (frontend/build.py sends the whole agg); the executor evaluates it
    there as exact len(counter) — a valid superset of the approximate
    contract. Regression: the missing branch used to kill the stream job
    on the first barrier."""
    s = fresh()
    s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k, "
              "approx_count_distinct(v) AS ad, count(distinct v) AS dv "
              "FROM t GROUP BY k")
    s.run_sql("INSERT INTO t VALUES (1, 1, 10, 'a'), (2, 1, 10, 'b'), "
              "(3, 1, 20, 'c'), (4, 2, 5, 'd')")
    s.tick()
    # evaluated over the exact multiset: ad == dv exactly
    assert sorted(s.mv_rows("m")) == [(1, 2, 2), (2, 1, 1)]
    # retraction flows through both calls (the device HLL can't retract;
    # the materialized path must)
    s.run_sql("DELETE FROM t WHERE s = 'c'")
    s.tick()
    assert sorted(s.mv_rows("m")) == [(1, 1, 1), (2, 1, 1)]
    # the job survived its barriers — counters still stream
    s.run_sql("INSERT INTO t VALUES (5, 2, 6, 'e')")
    s.tick()
    assert sorted(s.mv_rows("m")) == [(1, 1, 1), (2, 2, 2)]
    s.close()


def test_struct_agg_arg_rejected():
    """STRUCT agg args are rejected like LIST args: struct dictionary
    ids are process-local, so persisted raw ids would silently miscount
    DISTINCT/mode after recovery."""
    import pytest

    s = Session()
    s.run_sql("CREATE TABLE ts (id BIGINT PRIMARY KEY, "
              "st STRUCT<a BIGINT, b VARCHAR>)")
    with pytest.raises(Exception, match="struct column is not supported"):
        s.run_sql("CREATE MATERIALIZED VIEW bad AS "
                  "SELECT count(distinct st) AS d FROM ts")
    s.close()
