"""rwlint test surface (docs/static-analysis.md).

Three layers:

1. Fixture snippets — each rule fires on a minimal positive and stays
   quiet on the matching negative. For every migrated grep lint the
   fixtures include (a) a comment/docstring case where the OLD grep
   fired falsely (asserted by running the grep's own regex against the
   fixture) and the AST rule stays quiet, and (b) an aliased-import
   case the OLD grep missed and the AST rule catches — the
   "AST-beats-grep" proof the migration hangs on.
2. Coverage cross-check — the dispatch-discipline closure is computed
   from the STATIC registry parse; asserting it equals the RUNTIME
   ``EPOCH_BUILDERS``/``SHARDED_EPOCH_BUILDERS`` dicts proves every
   builder a tick can resolve is lint-covered.
3. Tier-1 wiring — the whole package lints clean inside the 10 s CI
   timing budget (scripts/check.sh enforces the same budget).
"""

import re
import textwrap
import time

import pytest

from risingwave_tpu.analysis import (RULES, all_rules, lint_package,
                                     load_package, package_root)

all_rules()  # populate the registry once


def lint_fixture(tmp_path, files, rules):
    """Write a throwaway package named risingwave_tpu (rule targets are
    qualified against the real package name) and lint it."""
    root = tmp_path / "risingwave_tpu"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    findings, counts, _ = lint_package(
        root, [RULES[r] for r in rules])
    return findings


DISPATCH_STUB = {
    "stream/dispatch.py": """
        class PermitChannel:
            def __init__(self, permits=8):
                self.permits = permits
        """,
    "stream/__init__.py": "from .dispatch import PermitChannel\n",
}


class TestExchangeBoundary:
    GREP = re.compile(r"PermitChannel\(")

    def test_aliased_import_caught_where_grep_missed(self, tmp_path):
        files = dict(DISPATCH_STUB)
        files["worker/rogue.py"] = """
            from ..stream.dispatch import PermitChannel as PC

            def wire():
                return PC(4)
            """
        src = textwrap.dedent(files["worker/rogue.py"])
        assert not self.GREP.search(src)  # the old grep is blind here
        found = lint_fixture(tmp_path, files, ["exchange-boundary"])
        assert [f.rule for f in found] == ["exchange-boundary"]
        assert found[0].path == "worker/rogue.py"

    def test_reexport_chain_caught(self, tmp_path):
        files = dict(DISPATCH_STUB)
        files["worker/rogue.py"] = """
            from ..stream import PermitChannel

            def wire():
                return PermitChannel(4)
            """
        found = lint_fixture(tmp_path, files, ["exchange-boundary"])
        assert len(found) == 1

    def test_docstring_mention_not_flagged(self, tmp_path):
        files = dict(DISPATCH_STUB)
        files["worker/clean.py"] = '''
            """Frames flow via open_channel, never raw PermitChannel(...)."""

            # a comment saying PermitChannel(8) is not a construction
            def wire(open_channel):
                return open_channel(4)
            '''
        src = textwrap.dedent(files["worker/clean.py"])
        assert self.GREP.search(src)  # the old grep false-positives
        assert lint_fixture(tmp_path, files, ["exchange-boundary"]) == []

    def test_exempt_modules_stay_quiet(self, tmp_path):
        files = dict(DISPATCH_STUB)
        files["frontend/fragments.py"] = """
            from ..stream.dispatch import PermitChannel

            def build():
                return PermitChannel(8)
            """
        assert lint_fixture(tmp_path, files, ["exchange-boundary"]) == []


class TestWireBoundary:
    GREP = re.compile(r"sock\.sendall\(|sock\.recv\(")

    def test_renamed_socket_caught_where_grep_missed(self, tmp_path):
        files = {"meta/rogue.py": """
            def push(conn, payload):
                conn.sendall(payload)
                return conn.recv(4096)
            """}
        src = textwrap.dedent(files["meta/rogue.py"])
        assert not self.GREP.search(src)  # receiver is not named sock
        found = lint_fixture(tmp_path, files, ["wire-boundary"])
        assert len(found) == 2

    def test_comment_and_channel_recv_not_flagged(self, tmp_path):
        files = {"stream/clean.py": '''
            """Raw sock.recv( / sock.sendall( belong to rpc/wire.py."""

            async def pump(ch):
                # not sock.sendall(frame) — the channel owns delivery
                return await ch.recv()
            '''}
        src = textwrap.dedent(files["stream/clean.py"])
        assert self.GREP.search(src)  # grep fired on prose
        assert lint_fixture(tmp_path, files, ["wire-boundary"]) == []

    def test_wire_module_exempt(self, tmp_path):
        files = {"rpc/wire.py": """
            def send_frame(sock, b):
                sock.sendall(b)
                return sock.recv(4)
            """}
        assert lint_fixture(tmp_path, files, ["wire-boundary"]) == []


class TestPlacementMutation:
    GREP = re.compile(r'"placement/')

    def test_fstring_key_and_save_placement_caught(self, tmp_path):
        files = {"worker/rogue.py": """
            def hijack(store, meta, job, p):
                store.put(f"placement/{job}", b"")
                meta.save_placement(p)
            """}
        found = lint_fixture(tmp_path, files, ["placement-mutation"])
        assert len(found) == 2

    def test_docstring_mention_not_flagged(self, tmp_path):
        files = {"worker/clean.py": '''
            """The "placement/" keyspace belongs to meta/service.py."""

            def read_only(meta, job):
                return meta.load_placement(job)
            '''}
        src = textwrap.dedent(files["worker/clean.py"])
        assert self.GREP.search(src)  # grep false-positived on docs
        assert lint_fixture(tmp_path, files, ["placement-mutation"]) == []

    def test_owning_modules_exempt(self, tmp_path):
        files = {
            "meta/service.py": """
                def save_placement(store, key, p):
                    store.put(f"placement/{key}", p)
                """,
            "meta/rescale.py": """
                def commit_placement(meta, p):
                    meta.save_placement(p)
                """,
        }
        assert lint_fixture(tmp_path, files, ["placement-mutation"]) == []


class TestServingCache:
    GREP = re.compile(r"lower_plan\(")

    def test_aliased_lower_plan_caught_where_grep_missed(self, tmp_path):
        files = {
            "batch/lower.py": "def lower_plan(plan, store):\n    pass\n",
            "frontend/session.py": """
                from ..batch.lower import lower_plan as _lp

                def run_select(plan, store):
                    return _lp(plan, store)
                """,
        }
        src = textwrap.dedent(files["frontend/session.py"])
        assert not self.GREP.search(src)  # grep only saw lower_plan(
        found = lint_fixture(tmp_path, files, ["serving-cache"])
        assert [f.rule for f in found] == ["serving-cache"]

    def test_serving_plane_itself_quiet(self, tmp_path):
        files = {
            "batch/lower.py": "def lower_plan(plan, store):\n    pass\n",
            "frontend/serving.py": """
                from ..batch.lower import lower_plan

                def execute(plan, store):
                    return lower_plan(plan, store)
                """,
            "frontend/session.py": '''
                """Selects lower via serving, never lower_plan( direct."""

                def run_select(serving, plan):
                    return serving.execute(plan)
                ''',
        }
        assert lint_fixture(tmp_path, files, ["serving-cache"]) == []


class TestBoundaryIO:
    GREP = re.compile(r"LocalFsObjectStore\(")

    def test_alias_caught_where_grep_missed(self, tmp_path):
        files = {
            "storage/object_store.py": """
                class LocalFsObjectStore:
                    def __init__(self, root):
                        self.root = root

                def open_object_store(root):
                    return LocalFsObjectStore(root)
                """,
            "worker/rogue.py": """
                from ..storage.object_store import LocalFsObjectStore as FS

                def open_raw(root):
                    return FS(root)
                """,
        }
        src = textwrap.dedent(files["worker/rogue.py"])
        assert not self.GREP.search(src)
        found = lint_fixture(tmp_path, files, ["boundary-io"])
        assert [f.rule for f in found] == ["boundary-io"]

    def test_docstring_and_wrapped_open_quiet(self, tmp_path):
        files = {
            "storage/object_store.py": """
                class LocalFsObjectStore:
                    def __init__(self, root):
                        self.root = root

                def open_object_store(root):
                    return LocalFsObjectStore(root)
                """,
            "worker/clean.py": '''
                """Never LocalFsObjectStore(...) — open_object_store."""
                from ..storage.object_store import open_object_store

                def open_ok(root):
                    return open_object_store(root)
                ''',
        }
        src = textwrap.dedent(files["worker/clean.py"])
        assert self.GREP.search(src)
        assert lint_fixture(tmp_path, files, ["boundary-io"]) == []


class TestMetaBoundary:
    GREP = re.compile(r"FileMetaStore\(")

    META_STUB = {
        "meta/store.py": """
            class FileMetaStore:
                def __init__(self, root):
                    self.root = root
            """,
        "meta/service.py": """
            from .store import FileMetaStore

            class MetaService:
                def __init__(self, root):
                    self.store = FileMetaStore(root)
            """,
    }

    def test_alias_caught_where_grep_missed(self, tmp_path):
        files = dict(self.META_STUB)
        files["frontend/rogue.py"] = """
            from ..meta.store import FileMetaStore as MS

            def open_raw(root):
                return MS(root)
            """
        src = textwrap.dedent(files["frontend/rogue.py"])
        assert not self.GREP.search(src)
        found = lint_fixture(tmp_path, files, ["meta-boundary"])
        assert [f.rule for f in found] == ["meta-boundary"]

    def test_meta_internal_and_docstring_quiet(self, tmp_path):
        files = dict(self.META_STUB)
        files["frontend/clean.py"] = '''
            """Never FileMetaStore(...) — go through MetaService."""
            from ..meta.service import MetaService

            def attach(root):
                return MetaService(root)
            '''
        src = textwrap.dedent(files["frontend/clean.py"])
        assert self.GREP.search(src)
        assert lint_fixture(tmp_path, files, ["meta-boundary"]) == []


FUSED_FIXTURE_PRELUDE = """
    import jax

    def agg_epoch_body(chunk_fn, core):
        def epoch(state, k):
            state = core.apply_chunk(state, k)
            {body_line}
            return state
        return epoch

    def fused_source_agg_epoch(chunk_fn, core):
        epoch = agg_epoch_body(chunk_fn, core)
        return jax.jit(epoch, static_argnums=(1,))

    EPOCH_BUILDERS = {{"source_agg": fused_source_agg_epoch}}
    """


class TestDispatchDiscipline:
    def _files(self, body_line, core_body="return state"):
        return {
            "ops/fused_epoch.py": FUSED_FIXTURE_PRELUDE.format(
                body_line=body_line),
            "ops/core.py": f"""
                class AggCore:
                    def apply_chunk(self, state, k):
                        {core_body}
                """,
        }

    @pytest.mark.parametrize("bad,needle", [
        ("state = jax.device_get(state)", "device_get"),
        ("jax.jit(lambda s: s)", "nested"),
        ("state.block_until_ready()", "block_until_ready"),
        ("n = state.item()", "item"),
        ("n = int(state[0])", "int()"),
    ])
    def test_positive_inside_epoch_body(self, tmp_path, bad, needle):
        found = lint_fixture(tmp_path, self._files(bad),
                             ["dispatch-discipline"])
        assert found, bad
        assert all(f.rule == "dispatch-discipline" for f in found)
        assert any(needle in f.message for f in found)

    def test_positive_through_unknown_receiver_method(self, tmp_path):
        # core.apply_chunk is only resolvable by method-name fallback —
        # the closure must still reach the numpy materialization there
        files = self._files(
            "pass", core_body="import numpy as np\n"
                    "                        return np.asarray(state)")
        found = lint_fixture(tmp_path, files, ["dispatch-discipline"])
        assert any("asarray" in f.message and f.path == "ops/core.py"
                   for f in found)

    def test_negative_pure_epoch_and_host_side_transfer(self, tmp_path):
        files = self._files("state = state + k")
        # host-side checkpointing may device_get freely: not reachable
        # from any builder
        files["ops/snapshot.py"] = """
            import jax

            def snapshot_host(state):
                return jax.device_get(state)
            """
        assert lint_fixture(tmp_path, files,
                            ["dispatch-discipline"]) == []

    def test_builders_own_jit_is_legitimate(self, tmp_path):
        # the ONE jax.jit in the builder body itself must not count as
        # nested
        files = self._files("state = state * 2")
        found = lint_fixture(tmp_path, files, ["dispatch-discipline"])
        assert found == []

    def test_lax_scan_body_is_a_root(self, tmp_path):
        files = {"ops/scanner.py": """
            import jax

            def run(xs):
                def body(carry, x):
                    carry = carry + jax.device_get(x)
                    return carry, x
                return jax.lax.scan(body, 0, xs)
            """}
        found = lint_fixture(tmp_path, files, ["dispatch-discipline"])
        assert len(found) == 1 and "device_get" in found[0].message


class TestDispatchCoverage:
    def test_static_roots_equal_runtime_registries(self):
        """The acceptance contract: the rule provably covers every
        function reachable from the registries. The static parse of the
        registry dicts must see exactly the entries the imported dicts
        hold, and each builder's closure must reach its epoch body and
        the device cores it dispatches into."""
        from risingwave_tpu.ops.fused_epoch import EPOCH_BUILDERS
        from risingwave_tpu.ops.fused_sharded import \
            SHARDED_EPOCH_BUILDERS
        from risingwave_tpu.analysis.rules_purity import \
            DispatchDiscipline
        pkg = load_package(package_root())
        cov = DispatchDiscipline().coverage(pkg)
        assert set(cov["EPOCH_BUILDERS"]) == set(EPOCH_BUILDERS)
        assert set(cov["SHARDED_EPOCH_BUILDERS"]) == \
            set(SHARDED_EPOCH_BUILDERS)
        for reg in ("EPOCH_BUILDERS", "SHARDED_EPOCH_BUILDERS"):
            for key, reach in cov[reg].items():
                # every builder's closure reaches its epoch body (named
                # "...epoch": the solo/sharded builders' <locals>.epoch,
                # the group builder's sharded_coscheduled_epoch)
                assert any(q.rsplit(".", 1)[-1].endswith("epoch")
                           for q in reach), (reg, key)
                assert len(reach) >= 5, (reg, key)
        everything = {q for d in cov.values() for v in d.values()
                      for q in v}
        for probe in ("ops.hash_table", "ops.session_window",
                      "ops.stream_q3", "ops.interval_join",
                      "parallel.sharded_agg.shard_map_compat"):
            assert any(probe in q for q in everything), probe


class TestTracePurity:
    def test_wall_clock_in_jitted_function(self, tmp_path):
        files = {"ops/impure.py": """
            import time

            import jax

            @jax.jit
            def stamp(x):
                return x + time.time()
            """}
        found = lint_fixture(tmp_path, files, ["trace-purity"])
        assert len(found) == 1 and "time.time" in found[0].message

    def test_host_rng_in_wrapped_function(self, tmp_path):
        files = {"ops/impure.py": """
            import random

            import jax

            def jitter(x):
                return x + random.random()

            jitter_v = jax.vmap(jitter)
            """}
        found = lint_fixture(tmp_path, files, ["trace-purity"])
        assert len(found) == 1 and "random.random" in found[0].message

    def test_mutable_default_on_traced_function(self, tmp_path):
        files = {"ops/impure.py": """
            import jax

            @jax.jit
            def accum(x, seen=[]):
                return x
            """}
        found = lint_fixture(tmp_path, files, ["trace-purity"])
        assert len(found) == 1 and "mutable default" in found[0].message

    def test_partial_jit_decorator_is_a_root(self, tmp_path):
        files = {"ops/impure.py": """
            import functools
            import time

            import jax

            @functools.partial(jax.jit, static_argnames=("k",))
            def stamp(x, k):
                return x + time.time()
            """}
        found = lint_fixture(tmp_path, files, ["trace-purity"])
        assert len(found) == 1 and "time.time" in found[0].message

    def test_pallas_kernel_is_a_root(self, tmp_path):
        files = {"ops/kernel.py": """
            import random

            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[...] = x_ref[...] * random.random()

            def run(x):
                return pl.pallas_call(_kernel,
                                      out_shape=x)(x)
            """}
        found = lint_fixture(tmp_path, files, ["trace-purity"])
        assert len(found) == 1 and "random.random" in found[0].message

    def test_jax_random_and_untraced_clock_are_fine(self, tmp_path):
        files = {"ops/pure.py": """
            import time

            import jax

            @jax.jit
            def step(state, key):
                return state + jax.random.uniform(key)

            def host_metrics():
                return time.time()
            """}
        assert lint_fixture(tmp_path, files, ["trace-purity"]) == []


SESSION_HEADER = """
    class Session:
        def __init__(self):
            self._data_version = 0
            self._mutation_depth = 0

        def _enter_mutation(self):
            self._mutation_depth += 1
            if self._mutation_depth == 1:
                self._data_version += 1

        def _exit_mutation(self):
            self._mutation_depth -= 1
            if self._mutation_depth == 0:
                self._data_version += 1
    """


class TestSeqlockDiscipline:
    def test_direct_version_write_flagged(self, tmp_path):
        files = {"frontend/session.py": SESSION_HEADER + """
            def sneak(self):
                self._data_version += 2
        """}
        found = lint_fixture(tmp_path, files, ["seqlock-discipline"])
        assert len(found) == 1 and "_data_version" in found[0].message

    def test_enter_without_finally_exit_flagged(self, tmp_path):
        files = {"frontend/session.py": SESSION_HEADER + """
            def tick(self):
                self._enter_mutation()
                work = 1
                self._exit_mutation()
                return work
        """}
        found = lint_fixture(tmp_path, files, ["seqlock-discipline"])
        assert len(found) == 1 and "finally" in found[0].message

    def test_bracketed_mutator_is_clean(self, tmp_path):
        files = {"frontend/session.py": SESSION_HEADER + """
            def tick(self):
                self._enter_mutation()
                try:
                    return 1
                finally:
                    self._exit_mutation()
        """}
        assert lint_fixture(tmp_path, files, ["seqlock-discipline"]) == []

    def test_enter_inside_try_body_is_clean(self, tmp_path):
        files = {"frontend/session.py": SESSION_HEADER + """
            def tick(self):
                try:
                    self._enter_mutation()
                    return 1
                finally:
                    self._exit_mutation()
        """}
        assert lint_fixture(tmp_path, files, ["seqlock-discipline"]) == []

    def test_balanced_counts_do_not_launder_unprotected_enter(
            self, tmp_path):
        # enters=1, exits=1, one exit in a finally — a per-function
        # COUNT check calls this clean, but the finally belongs to an
        # unrelated try: an exception after the enter leaves
        # _data_version odd forever. The check must be structural.
        files = {"frontend/session.py": SESSION_HEADER + """
            def tick(self):
                try:
                    prep = 1
                finally:
                    self._exit_mutation()
                self._enter_mutation()
                work = 2
                return work
        """}
        found = lint_fixture(tmp_path, files, ["seqlock-discipline"])
        assert len(found) == 1 and "finally" in found[0].message

    def test_foreign_module_write_flagged(self, tmp_path):
        files = {
            "frontend/session.py": SESSION_HEADER,
            "frontend/serving.py": """
                def corrupt(session):
                    session._data_version += 1
                """,
        }
        found = lint_fixture(tmp_path, files, ["seqlock-discipline"])
        assert len(found) == 1 and found[0].path == "frontend/serving.py"


FAILPOINT_STUB = """
    DECLARED_SITES = frozenset({{{sites}}})
    KNOWN_SITES = set(DECLARED_SITES)

    def fail_point(name):
        pass
    """


class TestFailpointHonesty:
    def _files(self, sites, caller_lines):
        body = "".join(f"    {line}\n" for line in caller_lines)
        return {
            "common/failpoint.py": FAILPOINT_STUB.format(sites=sites),
            "storage/io.py":
                "from ..common.failpoint import fail_point\n\n"
                "def write(b):\n" + body,
        }

    def test_declared_equals_executed_is_clean(self, tmp_path):
        files = self._files('"sst.write"',
                            ['fail_point("sst.write")'])
        assert lint_fixture(tmp_path, files, ["failpoint-honesty"]) == []

    def test_undeclared_site_flagged_at_call(self, tmp_path):
        files = self._files('"sst.write"',
                            ['fail_point("sst.write")',
                             'fail_point("sst.rogue")'])
        found = lint_fixture(tmp_path, files, ["failpoint-honesty"])
        msgs = [f.message for f in found]
        assert any("sst.rogue" in m and "not in DECLARED" in m
                   for m in msgs)
        assert any(f.path == "storage/io.py" for f in found)

    def test_stale_declared_site_flagged(self, tmp_path):
        files = self._files('"sst.write", "never.hit"',
                            ['fail_point("sst.write")'])
        found = lint_fixture(tmp_path, files, ["failpoint-honesty"])
        assert len(found) == 1
        assert "never.hit" in found[0].message
        assert found[0].path == "common/failpoint.py"

    def test_dynamic_site_name_flagged(self, tmp_path):
        files = self._files('"sst.write"',
                            ['site = "sst" + ".write"',
                             'fail_point(site)',
                             'fail_point("sst.write")'])
        found = lint_fixture(tmp_path, files, ["failpoint-honesty"])
        assert len(found) == 1 and "non-literal" in found[0].message

    def test_keyword_call_counts_as_executed(self, tmp_path):
        # fail_point(name="x") must satisfy the declared site, not be
        # reported as a stale registry entry
        files = self._files('"sst.write"',
                            ['fail_point(name="sst.write")'])
        assert lint_fixture(tmp_path, files, ["failpoint-honesty"]) == []

    def test_undeclared_keyword_site_flagged(self, tmp_path):
        files = self._files('"sst.write"',
                            ['fail_point("sst.write")',
                             'fail_point(name="sst.rogue")'])
        found = lint_fixture(tmp_path, files, ["failpoint-honesty"])
        assert any("sst.rogue" in f.message and "not in DECLARED"
                   in f.message for f in found)


class TestRootNameNormalisation:
    def test_foreign_root_dir_name_still_enforced(self, tmp_path):
        """Rule targets are written against the canonical package name;
        a tree rooted at any other directory name (fixture copy,
        vendored checkout) must lint identically — a mismatched root
        must not silently disable every boundary rule."""
        root = tmp_path / "pkgcopy"
        files = dict(DISPATCH_STUB)
        files["worker/rogue.py"] = """
            from ..stream.dispatch import PermitChannel as PC

            def wire():
                return PC(4)
            """
        for rel, src in files.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        findings, _, _ = lint_package(root, [RULES["exchange-boundary"]])
        assert len(findings) == 1
        assert findings[0].path == "worker/rogue.py"


class TestSyncFetchDiscipline:
    """sync-fetch-discipline: blocking device fetches on the tick path
    (Session._tick_impl + the fused engines' per-tick methods) must go
    through common/fetch.py (PR 14, docs/performance.md "Pipelined
    tick")."""

    FETCH_STUB = {
        "common/fetch.py": """
            import jax

            class FetchFuture:
                def __init__(self, tree, dispatch=None):
                    self._tree = tree

                def result(self):
                    return jax.device_get(self._tree)

            def async_fetch(tree, dispatch=None):
                return FetchFuture(tree)

            def fetch(tree, dispatch=None):
                return FetchFuture(tree).result()
            """,
    }

    def test_blocking_fetch_in_engine_flush_flagged(self, tmp_path):
        files = dict(self.FETCH_STUB)
        files["stream/coschedule.py"] = """
            import jax
            import numpy as np

            class CoGroup:
                def flush(self):
                    packed, ranks = self._probe(self.stacked)
                    return np.asarray(jax.device_get(packed))
            """
        found = lint_fixture(tmp_path, files, ["sync-fetch-discipline"])
        assert [f.rule for f in found] == ["sync-fetch-discipline"]
        assert found[0].path == "stream/coschedule.py"
        assert "device_get" in found[0].message

    def test_closure_from_tick_impl_through_helper_flagged(self, tmp_path):
        # the blocking fetch hides one helper away from the tick driver:
        # reachability (not per-line grep) must find it
        files = dict(self.FETCH_STUB)
        files["frontend/session.py"] = """
            import jax

            def _decode_stats(packed):
                return jax.device_get(packed)

            class Session:
                def _cosched_tick(self, epoch):
                    return _decode_stats(self._probe())

                def _tick_impl(self, generate):
                    return self._cosched_tick(1)
            """
        found = lint_fixture(tmp_path, files, ["sync-fetch-discipline"])
        assert [f.path for f in found] == ["frontend/session.py"]
        assert "_decode_stats" in found[0].message

    def test_block_until_ready_and_device_attr_asarray_flagged(
            self, tmp_path):
        files = dict(self.FETCH_STUB)
        files["parallel/fused.py"] = """
            import jax
            import numpy as np

            class ShardedCoGroup:
                def run_epoch(self, k):
                    jax.block_until_ready(self.stacked)

                def _settle(self):
                    return np.asarray(self._rovf)
            """
        found = lint_fixture(tmp_path, files, ["sync-fetch-discipline"])
        assert sorted(("block_until_ready" in f.message,
                       "asarray" in f.message)
                      for f in found) == [(False, True), (True, False)]

    def test_through_fetch_helper_is_clean(self, tmp_path):
        # the refactored shape: async_fetch at dispatch time, result()
        # at flush time — the helper module's own device_get is the one
        # blessed crossing and stays exempt
        files = dict(self.FETCH_STUB)
        files["stream/coschedule.py"] = """
            import numpy as np

            from ..common.fetch import async_fetch

            class CoGroup:
                def begin_flush(self):
                    packed, ranks = self._probe(self.stacked)
                    self.pending = async_fetch(packed)

                def finish_flush(self):
                    return np.asarray(self.pending.result())
            """
        assert lint_fixture(tmp_path, files,
                            ["sync-fetch-discipline"]) == []

    def test_non_tick_methods_stay_out_of_scope(self, tmp_path):
        # checkpoint/debug surfaces (export_host, merged_group_values)
        # legitimately materialize host copies — not per-tick work
        files = dict(self.FETCH_STUB)
        files["parallel/fused.py"] = """
            import jax

            class ShardedFusedAgg:
                def export_host(self):
                    return jax.device_get(self.stacked)

                def merged_group_values(self):
                    return jax.device_get(self.stacked)
            """
        assert lint_fixture(tmp_path, files,
                            ["sync-fetch-discipline"]) == []

    def test_real_package_has_exactly_one_reasoned_drain_allow(self):
        """The real tree keeps ONE deliberately blocking fetch — the
        sharded grow-retry drain — behind a reasoned allow pragma; the
        rule must see it raw and the driver must suppress it."""
        from risingwave_tpu.analysis.core import RULES as _R
        pkg = load_package(package_root())
        raw = list(_R["sync-fetch-discipline"].check(pkg))
        assert [f.path for f in raw] == ["parallel/fused.py"], \
            [f.render() for f in raw]
        findings, _, _ = lint_package(
            package_root(), [_R["sync-fetch-discipline"]])
        assert findings == []


class TestSuppressions:
    def test_allow_with_reason_suppresses(self, tmp_path):
        files = dict(DISPATCH_STUB)
        files["worker/rogue.py"] = """
            from ..stream.dispatch import PermitChannel as PC

            def wire():
                return PC(4)  # rwlint: allow(exchange-boundary): test harness channel, not a data path
            """
        assert lint_fixture(tmp_path, files, ["exchange-boundary"]) == []

    def test_allow_without_reason_is_itself_a_finding(self, tmp_path):
        files = dict(DISPATCH_STUB)
        files["worker/rogue.py"] = """
            from ..stream.dispatch import PermitChannel as PC

            def wire():
                return PC(4)  # rwlint: allow(exchange-boundary)
            """
        found = lint_fixture(tmp_path, files, ["exchange-boundary"])
        rules = sorted(f.rule for f in found)
        assert rules == ["exchange-boundary", "pragma"]

    def test_pragma_on_preceding_comment_line(self, tmp_path):
        files = dict(DISPATCH_STUB)
        files["worker/rogue.py"] = """
            from ..stream.dispatch import PermitChannel as PC

            def wire():
                # rwlint: allow(exchange-boundary): fixture exercises the pragma-above form
                return PC(4)
            """
        assert lint_fixture(tmp_path, files, ["exchange-boundary"]) == []


UDF_STUB = {
    "udf/__init__.py": "",
    "udf/runtime.py": """
        def eval_udf_batch(spec, datas, masks):
            return spec.fn(*datas)
        """,
    "udf/registry.py": """
        UDF_SPECS = {}

        def get_udf(name):
            return UDF_SPECS[name]
        """,
}


class TestUdfBoundary:
    def test_direct_eval_in_tick_module_caught(self, tmp_path):
        files = dict(UDF_STUB)
        files["stream/rogue.py"] = """
            from ..udf.runtime import eval_udf_batch as ev

            def on_chunk(spec, datas, masks):
                return ev(spec, datas, masks)
            """
        found = lint_fixture(tmp_path, files, ["udf-boundary"])
        assert [f.rule for f in found] == ["udf-boundary"]
        assert found[0].path == "stream/rogue.py"

    def test_server_side_eval_exempt(self, tmp_path):
        files = dict(UDF_STUB)
        files["udf/server.py"] = """
            from .runtime import eval_udf_batch

            def handle_call(spec, datas, masks):
                return eval_udf_batch(spec, datas, masks)
            """
        assert lint_fixture(tmp_path, files, ["udf-boundary"]) == []

    def test_registry_callable_grab_caught(self, tmp_path):
        files = dict(UDF_STUB)
        files["batch/rogue.py"] = """
            from ..udf.registry import UDF_SPECS, get_udf

            def fast_path(v):
                direct = get_udf("tax").fn(v)
                return direct + UDF_SPECS["tax"].fn(v)
            """
        found = lint_fixture(tmp_path, files, ["udf-boundary"])
        assert len(found) == 2
        assert all(f.path == "batch/rogue.py" for f in found)

    def test_docstring_mention_not_flagged(self, tmp_path):
        files = dict(UDF_STUB)
        files["stream/clean.py"] = '''
            """Never call eval_udf_batch(spec, ...) on the tick path."""

            def on_chunk(call_boundary, batch):
                return call_boundary(batch)
            '''
        assert lint_fixture(tmp_path, files, ["udf-boundary"]) == []

    def test_real_package_clean_with_exactly_one_reasoned_allow(self):
        """The shipped package carries exactly ONE udf-boundary allow —
        the client's opt-in inproc evaluator — and lints clean."""
        findings, counts, _ = lint_package(
            rules=[RULES["udf-boundary"]])
        assert counts["udf-boundary"] == 0, findings
        src = (package_root() / "udf" / "client.py").read_text()
        allows = [ln for ln in src.splitlines()
                  if "rwlint: allow(udf-boundary)" in ln]
        assert len(allows) == 1
        assert "inproc" in allows[0]    # the reason names the mode


class TestWiring:
    def test_package_lints_clean_within_budget(self):
        """Tier-1: the whole package is rwlint-clean, and the full run
        fits the <10 s CPU CI budget scripts/check.sh enforces."""
        t0 = time.monotonic()
        findings, counts, package = lint_package()
        elapsed = time.monotonic() - t0
        assert findings == [], "\n".join(f.render() for f in findings)
        assert len(package.modules) > 100
        assert set(counts) == {r.name for r in all_rules()}
        assert elapsed < 10.0, f"rwlint run took {elapsed:.1f}s"

    def test_json_output_shape(self):
        import json
        import subprocess
        import sys
        proc = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu.analysis", "--json"],
            capture_output=True, text=True,
            cwd=str(package_root().parent))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["ok"] is True and doc["findings"] == []
        assert doc["files"] > 100 and doc["elapsed_s"] < 10.0
        assert set(doc["rules"]) == {r.name for r in all_rules()}

    def test_ci_mode_keeps_historical_ok_lines(self):
        import subprocess
        import sys
        proc = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu.analysis", "--ci"],
            capture_output=True, text=True,
            cwd=str(package_root().parent))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        # the five migrated lints keep their exact check.sh OK lines
        for label in ("exchange-boundary", "wire-boundary",
                      "placement-mutation", "serving-cache",
                      "boundary-IO"):
            assert f"{label} lint: OK" in proc.stdout, label
