"""Debezium-JSON CDC parsing (format-parser layer — VERDICT r3 missing
#7): the {before, after, op} envelope becomes changelog entries, and the
file source emits them as op-carrying chunks.

pk-aware CDC sources (required to route these retractions through an MV)
are follow-up work; the parser + reader layer here is the reference's
src/connector/src/parser/debezium/ counterpart.
"""

import json

from risingwave_tpu.common.chunk import (
    OP_DELETE, OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, chunk_to_rows,
)
from risingwave_tpu.common.types import INT64, Schema, VARCHAR
from risingwave_tpu.connector.filesource import FileSourceReader
from risingwave_tpu.connector.parsers import (
    parse_debezium_line, parse_debezium_lines,
)

SCHEMA = Schema.of(("id", INT64), ("name", VARCHAR))


def _ev(op, before=None, after=None, wrap=False):
    payload = {"op": op, "before": before, "after": after}
    return json.dumps({"payload": payload} if wrap else payload)


class TestEnvelope:
    def test_create_read_update_delete(self):
        out = parse_debezium_lines("\n".join([
            _ev("c", after={"id": 1, "name": "a"}),
            _ev("r", after={"id": 2, "name": "b"}),
            _ev("u", before={"id": 1, "name": "a"},
                after={"id": 1, "name": "a2"}),
            _ev("d", before={"id": 2, "name": "b"}),
        ]), SCHEMA)
        ops = [op for op, _ in out]
        assert ops == [OP_INSERT, OP_INSERT, OP_UPDATE_DELETE,
                       OP_UPDATE_INSERT, OP_DELETE]
        assert out[2][1][0] == 1 and out[3][1][0] == 1

    def test_kafka_connect_wrapper_and_malformed(self):
        (op, row), = parse_debezium_line(
            _ev("c", after={"id": 7, "name": "x"}, wrap=True), SCHEMA)
        assert op == OP_INSERT and row[0] == 7
        import pytest
        with pytest.raises(ValueError, match="malformed"):
            parse_debezium_line(_ev("u", before=None, after=None), SCHEMA)

    def test_beforeless_update_is_upsert_insert(self):
        """REPLICA IDENTITY DEFAULT: op=u with before=null must not be
        dropped — it surfaces as an upsert insert."""
        (op, row), = parse_debezium_line(
            _ev("u", before=None, after={"id": 3, "name": "n"}), SCHEMA)
        assert op == OP_INSERT and row == (3, "n")

    def test_non_object_lines_raise_value_error(self):
        """Poisoned lines must raise the error class the file source
        catches (never AttributeError, which would wedge the source)."""
        import pytest
        for bad in ("[1,2]", "123",
                    '{"payload": {"op": "c", "after": "oops"}}'):
            with pytest.raises(ValueError):
                parse_debezium_line(bad, SCHEMA)

    def test_create_source_gates_debezium_format(self, tmp_path):
        from risingwave_tpu.frontend import Session
        from risingwave_tpu.frontend.session import SqlError
        import pytest
        s = Session()
        with pytest.raises(SqlError, match="PRIMARY KEY"):
            s.run_sql(
                "CREATE SOURCE c (id BIGINT, name VARCHAR) WITH ("
                f"connector = 'file', path = '{tmp_path}', "
                "format = 'debezium_json')")
        s.close()

    def test_public_qualified_relation_resolves(self):
        from risingwave_tpu.frontend import Session
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
        s.run_sql("INSERT INTO t VALUES (1), (2)")
        s.flush()
        assert sorted(s.run_sql("SELECT k FROM public.t")) == [(1,), (2,)]
        s.close()


class TestFileSourceDebezium:
    def test_reader_emits_changelog_ops(self, tmp_path):
        p = tmp_path / "cdc.jsonl"
        p.write_text("\n".join([
            _ev("c", after={"id": 1, "name": "a"}),
            _ev("u", before={"id": 1, "name": "a"},
                after={"id": 1, "name": "a2"}),
            _ev("d", before={"id": 1, "name": "a2"}),
        ]) + "\n")
        r = FileSourceReader(SCHEMA, str(p), fmt="debezium_json")
        chunk = r.next_chunk()
        rows = chunk_to_rows(chunk, SCHEMA, with_ops=True)
        assert [op for op, _ in rows] == [
            OP_INSERT, OP_UPDATE_DELETE, OP_UPDATE_INSERT, OP_DELETE]
        assert rows[1][1] == (1, "a") and rows[2][1] == (1, "a2")
        # offsets are line-based: 3 lines consumed, replay-safe
        assert sum(r.offsets.values()) == 3
        assert r.next_chunk() is None
