"""Asynchronous epoch pipeline (docs/performance.md "Pipelined tick").

Contract under test: ``pipeline_depth = 2`` may only REORDER work —
epoch N+1's dispatch before epoch N's packed flush fetch, checkpoint
encode on a worker thread — never change results. Pipelined sessions
must be bit-exact vs synchronous ones at every drain point (checkpoint
barriers, FLUSH, DDL), add zero dispatches, survive kill -9 between
checkpoints, and drain cleanly around membership changes.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from risingwave_tpu.common.dispatch_count import count_dispatches

CAP = 128

SRC_SQL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT,
    price BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
    extra VARCHAR) WITH (connector = 'nexmark', nexmark_table = 'bid')"""
MV_SQL = ("CREATE MATERIALIZED VIEW {n} AS SELECT auction, count(*) AS c "
          "FROM bid GROUP BY auction")
# min/max lanes dirty repeatedly per group → flush churn with U-/U+
# retraction pairs on every barrier
MV_CHURN_SQL = ("CREATE MATERIALIZED VIEW {n} AS SELECT auction, "
                "count(*) AS c, min(price) AS lo, max(price) AS hi "
                "FROM bid GROUP BY auction")

GROUP_EPOCH_FN = "build_group_epoch.<locals>.coscheduled_epoch"


def _session(tmp_path=None, pipeline_depth=1, mesh=None, **kw):
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig
    return Session(config=BuildConfig(coschedule=True, mesh=mesh,
                                      agg_table_capacity=1 << 12),
                   source_chunk_capacity=CAP,
                   data_dir=str(tmp_path) if tmp_path else None,
                   pipeline_depth=pipeline_depth, **kw)


def _mv_rows(s, names):
    return {n: sorted(tuple(r) for r in s.run_sql(f"SELECT * FROM {n}"))
            for n in names}


def _run(depth, n_mvs, ticks, tmp_path=None, churn=False, mesh=None,
         probe_each_checkpoint=False, checkpoint_frequency=4):
    """Tick a (solo|cosched) fused session at the given pipeline depth;
    returns (rows at each checkpoint tick, rows after the final FLUSH,
    pipeline metrics)."""
    sql = MV_CHURN_SQL if churn else MV_SQL
    names = [f"m{j}" for j in range(n_mvs)]
    s = _session(tmp_path, pipeline_depth=depth, mesh=mesh,
                 checkpoint_frequency=checkpoint_frequency,
                 chunks_per_tick=2)
    at_checkpoints = []
    try:
        s.run_sql(SRC_SQL)
        for n in names:
            s.run_sql(sql.format(n=n))
        for _ in range(ticks):
            s.tick()
            if probe_each_checkpoint and \
                    s.epoch % checkpoint_frequency == 0:
                # checkpoint ticks are drain points: the pipelined MV
                # must agree with the synchronous one HERE, not only
                # after the final flush
                at_checkpoints.append(_mv_rows(s, names))
        s.flush()
        final = _mv_rows(s, names)
        pipe = s.metrics()["pipeline"]
    finally:
        s.close()
    return at_checkpoints, final, pipe


@pytest.mark.parametrize("n_mvs", [1, 3])     # solo group + K=3 group
def test_pipelined_bit_exact_vs_sync(n_mvs):
    ck_sync, sync, _ = _run(1, n_mvs, 11, probe_each_checkpoint=True)
    ck_pipe, pipe, m = _run(2, n_mvs, 11, probe_each_checkpoint=True)
    assert sync == pipe
    assert ck_sync == ck_pipe            # equal at every drain point
    assert m["depth"] == 2 and m["deferred_flushes"] > 0
    assert m["pending_flushes"] == 0     # flush drained everything


def test_pipelined_bit_exact_with_flush_churn():
    # min/max lanes force U-/U+ retraction pairs in every barrier flush
    _, sync, _ = _run(1, 2, 9, churn=True)
    _, pipe, _ = _run(2, 2, 9, churn=True)
    assert sync == pipe


def test_pipelined_shardfused_bit_exact():
    from risingwave_tpu.parallel.sharded_agg import make_mesh
    _, sync, _ = _run(1, 2, 9, mesh=make_mesh(1))
    _, pipe, m = _run(2, 2, 9, mesh=make_mesh(1))
    assert sync == pipe
    assert m["deferred_flushes"] > 0


def test_pipelined_zero_added_dispatches():
    """Pipelining reorders dispatches across ticks; it must never add
    one (the live twin of bench.py --smoke's guard)."""
    def counts_for(depth):
        with count_dispatches() as c:
            _run(depth, 2, 9)
            return dict(c.counts)
    sync, pipe = counts_for(1), counts_for(2)
    for qn in (GROUP_EPOCH_FN, "multi_agg_probe.<locals>.probe",
               "multi_agg_finish.<locals>.finish",
               "gather_job_flush_chunk.<locals>.gather"):
        assert sync.get(qn) == pipe.get(qn) and sync.get(qn), \
            f"{qn}: sync={sync.get(qn)} pipe={pipe.get(qn)}"


def test_pipelined_ddl_mid_stream_drains(tmp_path):
    """CREATE/DROP between ticks restack the job axis: the deferred
    flush must drain first, and results stay exact vs the synchronous
    session doing the identical DDL dance."""
    def run(depth):
        s = _session(tmp_path / f"d{depth}", pipeline_depth=depth,
                     checkpoint_frequency=4, chunks_per_tick=2)
        try:
            s.run_sql(SRC_SQL)
            s.run_sql(MV_SQL.format(n="a"))
            for _ in range(3):
                s.tick()
            s.run_sql(MV_SQL.format(n="b"))     # joins the group mid-run
            for _ in range(3):
                s.tick()
            s.run_sql("DROP MATERIALIZED VIEW a")
            for _ in range(3):
                s.tick()
            s.flush()
            return _mv_rows(s, ["b"])
        finally:
            s.close()
    assert run(1) == run(2)


def test_pipelined_pause_resume_drains():
    def run(depth):
        s = _session(pipeline_depth=depth, checkpoint_frequency=4,
                     chunks_per_tick=2)
        try:
            s.run_sql(SRC_SQL)
            s.run_sql(MV_SQL.format(n="m0"))
            for _ in range(3):
                s.tick()
            s.pause()          # generate-off tick: pipeline empties
            assert s.metrics()["pipeline"]["pending_flushes"] == 0
            s.resume()
            for _ in range(3):
                s.tick()
            s.flush()
            return _mv_rows(s, ["m0"])
        finally:
            s.close()
    assert run(1) == run(2)


def test_pipelined_recovery_from_abandoned_session(tmp_path):
    """Crash-shaped recovery (no close, no drain): a pipelined session
    is abandoned mid-stream with a flush deferred and an async commit
    possibly un-joined; reopening recovers the last checkpoint cut and
    replays to the same rows a synchronous control produces."""
    def run(depth, d):
        s = _session(d, pipeline_depth=depth, checkpoint_frequency=2,
                     chunks_per_tick=2)
        s.run_sql(SRC_SQL)
        s.run_sql(MV_SQL.format(n="m0"))
        for _ in range(5):                 # checkpoint at epochs 2 and 4
            s.tick()
        return s                           # abandoned: NO close/flush

    s_sync = run(1, tmp_path / "sync")
    s_pipe = run(2, tmp_path / "pipe")
    del s_sync, s_pipe                     # crash: no graceful shutdown

    def recover(d):
        s = _session(d, checkpoint_frequency=2)
        try:
            rows = _mv_rows(s, ["m0"])
            for _ in range(3):             # deterministic replay onward
                s.tick()
            s.flush()
            return rows, _mv_rows(s, ["m0"])
        finally:
            s.close()

    assert recover(tmp_path / "sync") == recover(tmp_path / "pipe")


def test_commit_async_durability_and_ordering(tmp_path):
    """DurableStateStore.commit_async: memory-visible immediately,
    durable after join; ordering across consecutive async commits is
    strict; a reopened store recovers every joined epoch."""
    from risingwave_tpu.storage.checkpoint import DurableStateStore
    st = DurableStateStore(str(tmp_path))
    for e in (1, 2, 3):
        st.ingest(7, e, {b"k%d" % e: b"v%d" % e}, set())
        st.commit_async(e)
        assert st.get(7, b"k%d" % e) == b"v%d" % e     # visible now
    st.join_commits()
    st2 = DurableStateStore(str(tmp_path))
    assert st2.committed_epoch == 3
    assert sorted(dict(st2.iter_table(7))) == [b"k1", b"k2", b"k3"]


def test_commit_async_error_surfaces_at_join(tmp_path):
    from risingwave_tpu.storage.checkpoint import DurableStateStore
    st = DurableStateStore(str(tmp_path))
    st.ingest(7, 1, {b"k": b"v"}, set())

    def boom(*a, **k):
        raise OSError("disk gone")
    st.log.append_epoch = boom
    st.commit_async(1)
    with pytest.raises(RuntimeError, match="NOT durable"):
        st.join_commits()
    # the error is raised once, then cleared (store reusable for a
    # retry with the real log)
    st.join_commits()


def test_pipeline_metrics_and_prometheus():
    from risingwave_tpu.frontend.prometheus import render_metrics
    s = _session(pipeline_depth=2, checkpoint_frequency=4,
                 chunks_per_tick=2)
    try:
        s.run_sql(SRC_SQL)
        s.run_sql(MV_SQL.format(n="m0"))
        for _ in range(5):
            s.tick()
        m = s.metrics()["pipeline"]
        assert m["depth"] == 2
        assert m["deferred_flushes"] > 0
        assert m["completions"] > 0
        text = render_metrics(s)
        assert "rw_pipeline_depth 2" in text
        assert 'rw_pipeline_stat{stat="deferred_flushes"}' in text
        assert "rw_dispatch_complete_seconds" in text
        # profiler honesty: the group probe records completion latency
        rec = s.metrics()["profiling"]["dispatch"][
            "multi_agg_probe.<locals>.probe"]
        assert rec.get("complete_calls", 0) > 0
        assert rec.get("complete_s", 0) >= 0
    finally:
        s.close()


def test_fetch_future_semantics():
    import jax.numpy as jnp
    import numpy as np
    from risingwave_tpu.common.fetch import async_fetch, fetch
    tree = {"a": jnp.arange(4), "b": (jnp.ones(2), 3)}
    fut = async_fetch(tree)
    out = fut.result()
    assert np.array_equal(out["a"], np.arange(4))
    assert out["b"][1] == 3
    assert fut.done() and fut.result() is out       # idempotent
    assert np.array_equal(fetch(jnp.arange(3)), np.arange(3))


_KILL9_SCRIPT = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
from tests.test_pipeline import SRC_SQL, MV_SQL, _session
s = _session({data_dir!r}, pipeline_depth=2, checkpoint_frequency=2,
             chunks_per_tick=2)
s.run_sql(SRC_SQL)
s.run_sql(MV_SQL.format(n="m0"))
for _ in range(5):
    s.tick()
print("TICKED", flush=True)
os._exit(0)      # kill -9 shaped: no drain, no join, no close
"""


def _sync_history_rows(ticks: int):
    """Rows a FRESH synchronous session shows after consuming exactly
    ``ticks`` ticks of the deterministic bid stream — the ground truth
    any recovered cut must be a prefix of (no mid-run checkpoints, so
    only the event count matters)."""
    s = _session(None, pipeline_depth=1, checkpoint_frequency=10_000,
                 chunks_per_tick=2)
    try:
        s.run_sql(SRC_SQL)
        s.run_sql(MV_SQL.format(n="m0"))
        for _ in range(ticks):
            s.tick()
        s.flush()
        return _mv_rows(s, ["m0"])
    finally:
        s.close()


@pytest.mark.slow
def test_pipelined_kill9_recovery_e2e(tmp_path):
    """REAL process death mid-pipeline: the child dies via os._exit with
    a deferred flush outstanding and the last checkpoint's encode
    possibly un-joined. Recovery must land on SOME committed checkpoint
    cut that is bit-exact with the synchronous history at that offset
    (the deferred encode may legitimately cost the final checkpoint —
    that is the crash window a synchronous commit has too), and replay
    forward deterministically."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c", _KILL9_SCRIPT.format(
            repo=repo, data_dir=str(tmp_path / "pipe"))],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert "TICKED" in out.stdout, out.stderr

    s = _session(tmp_path / "pipe", checkpoint_frequency=2,
                 chunks_per_tick=2)
    try:
        recovered_epoch = s.epoch
        # checkpoints fell on even epochs 2/4/6; at least one beyond
        # the initial cut must have survived the kill
        assert recovered_epoch >= 4 and recovered_epoch % 2 == 0, \
            recovered_epoch
        rows = _mv_rows(s, ["m0"])
        # epoch E == E-1 ticks of the deterministic stream consumed
        assert rows == _sync_history_rows(recovered_epoch - 1)
        for _ in range(2):                 # deterministic replay onward
            s.tick()
        s.flush()
        assert _mv_rows(s, ["m0"]) == \
            _sync_history_rows(recovered_epoch + 1)
    finally:
        s.close()


@pytest.mark.slow
def test_pipelined_netsplit_auditor_green(tmp_path):
    """Chaos-plane composition: the q5 exchange-partition netsplit run
    with pipeline_depth = 2 on the session still converges bit-exact
    with the auditor green (the pipeline only touches local fused
    engines; its drain discipline must not disturb scoped recovery —
    run_netsplit itself asserts MV parity + the auditor)."""
    from risingwave_tpu.sim import run_netsplit
    report = run_netsplit("q5_exchange_partition", seed=7,
                          data_dir=str(tmp_path),
                          session_kw={"pipeline_depth": 2})
    assert report["recovered"], json.dumps(report)[:500]
    assert all(report["audit"].values()), report["audit"]
