"""ISSUE 3 acceptance: the exactly-once machinery holds under INJECTED
transient IO faults, not just clean kills.

* FaultInjectingObjectStore at a 20% transient rate under the full
  checkpoint → kill → recover → compact cycle (the test_hummock.py /
  test_compactor.py scenario shapes, unmodified semantics), with retry
  counters visible in ``Session.metrics()``.
* Sim chaos with seeded transient object-store faults + broker restarts
  armed during the workload; the control-session cross-check proves every
  MV still converges exactly-once.
"""

import json

from risingwave_tpu.common.config import FaultConfig
from risingwave_tpu.common.retry import RetryPolicy
from risingwave_tpu.frontend import Session
from risingwave_tpu.sim import SimCluster
from risingwave_tpu.storage.hummock import SST_PREFIX, HummockStateStore
from risingwave_tpu.storage.object_store import (
    FaultInjectingObjectStore, MemObjectStore,
)

#: 0.2**10 ≈ 1e-7 per op: hundreds of ops stay comfortably clear of a
#: spurious give-up while every ~5th op still exercises the retry path
_FAST = RetryPolicy(max_attempts=10, base_delay_ms=0.0, max_delay_ms=0.0)


def _faulty_store(seed, rate=0.2, torn=0.05):
    return FaultInjectingObjectStore(
        MemObjectStore(), seed=seed, transient_rate=rate,
        torn_write_rate=torn)


def _fill(st, table=7, epochs=range(1, 10)):
    for e in epochs:
        st.ingest(table, e, {b"k%03d" % e: b"v%d" % e}, set())
        st.commit(e)


class TestHummockUnder20PctFaults:
    def test_checkpoint_kill_recover_compact_cycle(self):
        """The full tier-1 crash-safety cycle over a 20%-flaky object
        store: every commit, the recovery fold, compaction, and vacuum
        ride the retry layer and converge to exactly the clean result."""
        fault = _faulty_store(seed=11)
        st = HummockStateStore(object_store=fault, retry_policy=_FAST,
                               inline_compaction=False)
        _fill(st)
        assert dict(st.iter_table(7)) == {
            b"k%03d" % e: b"v%d" % e for e in range(1, 10)}

        # "kill": abandon the store object; recover over the same store
        st2 = HummockStateStore(object_store=fault, retry_policy=_FAST,
                                inline_compaction=False)
        assert st2.committed_epoch == 9
        assert dict(st2.iter_table(7)) == {
            b"k%03d" % e: b"v%d" % e for e in range(1, 10)}

        # more commits + a full compact + vacuum under the same faults
        _fill(st2, epochs=range(10, 15))
        st2.compact()
        st2.vacuum()
        st3 = HummockStateStore(object_store=fault, retry_policy=_FAST)
        assert dict(st3.iter_table(7)) == {
            b"k%03d" % e: b"v%d" % e for e in range(1, 15)}
        # the injector really fired, repeatedly
        assert fault.faults_injected > 10
        # no orphans either: listed == referenced (vacuum-leak invariant)
        listed = set(st3.object_store.list(SST_PREFIX))
        assert listed == set(st3.manager.version.all_runs())

    def test_compact_task_under_faults_converges(self):
        """The compactor scenario (test_compactor.py shape) over a
        20%-flaky store: the merge task reads inputs and writes outputs
        through the retry layer; report + vacuum converge, and a task
        that exhausts its budget is cancelled cleanly (inputs intact)."""
        from risingwave_tpu.storage.hummock import run_compact_task
        fault = _faulty_store(seed=31)
        st = HummockStateStore(object_store=fault, retry_policy=_FAST,
                               inline_compaction=False)
        _fill(st, epochs=range(1, 12))
        task = st.manager.get_compact_task(force=True)
        outputs = run_compact_task(st.object_store, task)
        st.manager.report_compact_task(task.task_id, outputs)
        st.vacuum()
        st2 = HummockStateStore(object_store=fault, retry_policy=_FAST)
        assert dict(st2.iter_table(7)) == {
            b"k%03d" % e: b"v%d" % e for e in range(1, 12)}

        # a HOPELESS store (every op fails): the task dies loudly, the
        # cancel path leaves the version untouched and a later task over
        # the healthy store converges
        import pytest
        from risingwave_tpu.common.retry import RetryError
        fault.transient_rate = 1.0
        task2 = st2.manager.get_compact_task(force=True)
        with pytest.raises((RetryError, OSError)):
            run_compact_task(st2.object_store, task2)
        st2.manager.cancel_compact_task(task2.task_id)
        fault.transient_rate = 0.2
        st2.compact()
        st3 = HummockStateStore(object_store=fault, retry_policy=_FAST)
        assert dict(st3.iter_table(7)) == {
            b"k%03d" % e: b"v%d" % e for e in range(1, 12)}

    def test_session_e2e_with_retry_counters_in_metrics(self, tmp_path):
        """Session over the hummock tier with fault injection armed via
        FaultConfig: checkpoint → crash (abandoned session) → recover →
        compact; retry counters are visible in Session.metrics()."""
        d = str(tmp_path / "db")
        fc = FaultConfig(
            inject_object_store_transient_rate=0.2,
            inject_object_store_seed=23,
            io_retry_attempts=10, io_retry_base_ms=0.1,
            io_retry_max_ms=1.0)
        s = Session(data_dir=d, state_store="hummock",
                    checkpoint_frequency=2, fault_config=fc)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT sum(v) AS n FROM t")
        for i in range(6):
            s.run_sql(f"INSERT INTO t VALUES ({i}, {10 * i})")
        s.run_sql("FLUSH")
        m = s.metrics()
        assert "retry" in m
        os_sites = {k: v for k, v in m["retry"].items()
                    if k.startswith("object_store.")}
        assert os_sites, "object-store retry sites missing from metrics"
        assert sum(v["attempts"] for v in os_sites.values()) > 0

        # crash: no graceful shutdown (the sim's kill discipline)
        s.loop.close()
        s2 = Session(data_dir=d, fault_config=fc)   # tier auto-detected
        assert s2.mv_rows("m") == [(150,)]
        s2.run_sql("INSERT INTO t VALUES (100, 1)")
        s2.flush()
        s2.store.compact()
        s2.store.vacuum()
        assert s2.mv_rows("m") == [(151,)]
        retries = sum(v["retries"]
                      for k, v in s2.metrics()["retry"].items()
                      if k.startswith("object_store."))
        assert retries > 0
        s2.close()


class TestSimChaosTransientFaults:
    def test_sim_converges_under_faults_kills_and_broker_restarts(
            self, tmp_path):
        """Seeded chaos: transient object-store faults armed for the WHOLE
        workload, random cluster kills, and broker restarts — the chaos
        cluster's MVs (fed by both DML and a broker source) converge to a
        never-faulted control's."""
        from risingwave_tpu.connector.broker import BrokerClient, BrokerServer
        broker = BrokerServer(
            n_partitions=1, data_dir=str(tmp_path / "broker")).start()
        chaos = SimCluster(str(tmp_path / "chaos"), seed=7, kill_rate=0.4,
                           transient_fault_rate=0.15,
                           broker=broker, broker_restart_rate=0.5)
        control = Session()
        ddl = [
            "CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)",
            "CREATE MATERIALIZED VIEW s AS SELECT sum(v) AS n FROM t",
            "CREATE MATERIALIZED VIEW g AS "
            "SELECT k % 3 AS grp, count(*) AS c FROM t GROUP BY k % 3",
            f"""CREATE SOURCE bid (auction BIGINT, price BIGINT)
                WITH (connector = 'broker',
                      'broker.address' = '{broker.address}',
                      topic = 'bids')""",
            "CREATE MATERIALIZED VIEW b AS "
            "SELECT auction, price FROM bid",
        ]
        for stmt in ddl:
            chaos.run_sql(stmt)
            control.run_sql(stmt)
        chaos.flush()

        import random as _r
        data_rng = _r.Random(99)
        producer = BrokerClient(broker.address)
        for step in range(12):
            sql = (f"INSERT INTO t VALUES "
                   f"({step}, {data_rng.randint(0, 100)})")
            chaos.run_sql(sql)
            control.run_sql(sql)
            # the producer itself must survive broker restarts
            # (reconnect + offset-dedup publish path)
            producer.publish("bids", 0, json.dumps(
                {"auction": step, "price": 100 + step}).encode())
            if step % 3 == 2:
                chaos.flush()
                control.flush()
            chaos.maybe_kill()
            # address the CURRENT broker (restart keeps host:port)
        # drain the source on both sides, then cross-check
        for _ in range(4):
            chaos.tick()
            control.tick()
        chaos.verify_against(control)
        assert chaos.kills + chaos.broker_restarts > 0
        assert sorted(chaos.mv_rows("b")) == [
            (i, 100 + i) for i in range(12)]
        producer.close()
        chaos.broker.close()
        control.close()
        chaos.session.close()
