"""The heterogeneous tick compiler (stream/tick_compiler.py +
ops/fused_hetero.py): UNEQUAL jobs fused into a minimal dispatch
schedule — shape-class padded supergroups (tier 1) + jitted mega-epochs
(tier 2). These tests pin the ISSUE 19 contract: bucketing/padding
rules, one dispatch per compiled group per epoch, bit-exact per-job
results vs the solo fused path (including U-/U+ retraction churn),
DDL-driven recompilation with the epochs-retired ledger, recovery onto
a recompiled schedule, and pipeline_depth=2 bit-exactness at drain
points. The 200-small-MVs ≤ 8-dispatch acceptance case is @slow."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import INT64, TIMESTAMP
from risingwave_tpu.common.chunk import OP_UPDATE_DELETE, OP_UPDATE_INSERT
from risingwave_tpu.common.dispatch_count import count_dispatches
from risingwave_tpu.connector import BID_SCHEMA, NexmarkConfig
from risingwave_tpu.connector.nexmark import DeviceBidGenerator
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import agg as agg_call, count_star
from risingwave_tpu.ops.fused_epoch import fused_source_agg_epoch
from risingwave_tpu.stream import HashAggExecutor, ProjectExecutor
from risingwave_tpu.stream.coschedule import FusedJobSpec
from risingwave_tpu.stream.source import MockSource
from risingwave_tpu.stream.tick_compiler import (
    MEGA_EPOCH_FN, PADDED_EPOCH_FN, TickCompiler, shape_class,
    skeletonize_exprs,
)

CAP = 128
N_SOURCE_COLS = len(BID_SCHEMA)


def _parts(window_us=1_000_000, calls=None, table_capacity=1 << 10,
           group_keys=(0, 1), cap=CAP):
    """One q5-shaped job: tumble-window projection (the window literal
    is the knob that varies WITHIN a shape class) + a HashAggExecutor
    whose core/probe/gather are the solo flush reference."""
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(window_us, INT64)),
        col(0, INT64),
        col(2, INT64),
    ]
    proj = ProjectExecutor(MockSource(BID_SCHEMA, []), exprs,
                           names=("ws", "auction", "price"))
    agg = HashAggExecutor(
        proj, list(group_keys), list(calls or [count_star()]),
        table_capacity=table_capacity, out_capacity=cap)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=cap))
    return exprs, agg, gen.chunk_fn()


def _spec(exprs, agg, chunk_fn, seed=0, cap=CAP):
    return FusedJobSpec("agg", ("agg", ("nexmark_bid", cap)), chunk_fn,
                        tuple(exprs), agg.core, cap, seed=seed)


def _solo_epoch_and_flush(solo, agg, state, start, key, k):
    """The solo fused path's full epoch + flush (the parity oracle)."""
    state = solo(state, jnp.int64(start), key, k)
    packed, rank = agg._probe(state)
    n_dirty, overflow, _ = (int(x) for x in jax.device_get(packed))
    assert not overflow
    chunks = []
    lo = 0
    while lo < n_dirty:
        chunks.append(agg._gather(state, rank, jnp.int64(lo)))
        lo += agg.core.groups_per_chunk
    return agg._finish(state), chunks


def _rows(chunks):
    """Visible (op, *values) multiset of a flush — padding changes slot
    LAYOUT (hence chunk row order) but never per-key values, so parity
    is order-insensitive row equality."""
    out = []
    for c in chunks:
        ops, vis = np.asarray(c.ops), np.asarray(c.vis)
        cols = [(np.asarray(cc.data), np.asarray(cc.mask))
                for cc in c.columns]
        for i in np.nonzero(vis)[0]:
            out.append((int(ops[i]),) + tuple(
                int(d[i]) if m[i] else None for d, m in cols))
    return sorted(out, key=repr)


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# skeletonization + shape classes (the bucketing rules)
# ---------------------------------------------------------------------------


def test_skeletonize_lifts_numeric_literals():
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(777, INT64)),
        col(0, INT64),
    ]
    skel, hole_types, params = skeletonize_exprs(tuple(exprs),
                                                 N_SOURCE_COLS)
    assert len(hole_types) == len(params) == 1
    assert params[0] == INT64.to_physical(777)
    # the hole is an InputRef just past the source columns
    hole = skel[0].args[1]
    assert hole.index == N_SOURCE_COLS
    # two window widths share a skeleton; the plain column is untouched
    skel2, _, params2 = skeletonize_exprs(
        (call("tumble_start", col(5, TIMESTAMP), Literal(999, INT64)),
         col(0, INT64)), N_SOURCE_COLS)
    assert repr(skel) == repr(skel2) and params2[0] != params[0]


def test_skeletonize_keeps_non_numeric_literals_inline():
    from risingwave_tpu.common.types import BOOL, VARCHAR
    exprs = (Literal(True, BOOL), Literal("x", VARCHAR),
             Literal(None, INT64))
    skel, hole_types, params = skeletonize_exprs(exprs, N_SOURCE_COLS)
    # nothing lifts: walk returns the very same nodes (Expr __eq__ is
    # overloaded, so compare by identity)
    assert all(a is b for a, b in zip(skel, exprs))
    assert not hole_types and not params


def test_shape_class_ignores_capacities_and_literal_values():
    exprs_a, agg_a, _ = _parts(window_us=1_000_000,
                               table_capacity=1 << 10)
    exprs_b, agg_b, _ = _parts(window_us=5_000_000,
                               table_capacity=1 << 12)

    def sc(exprs, agg):
        skel, holes, _ = skeletonize_exprs(tuple(exprs), N_SOURCE_COLS)
        return shape_class(agg.core, skel, holes, CAP,
                           ("nexmark_bid", CAP))

    assert sc(exprs_a, agg_a) == sc(exprs_b, agg_b)
    # different agg calls => different class
    exprs_c, agg_c, _ = _parts(
        calls=[count_star(), agg_call("max", 2, INT64)])
    assert sc(exprs_a, agg_a) != sc(exprs_c, agg_c)


def test_compiler_buckets_classes_and_chunks_singletons():
    tc = TickCompiler(mega_max_jobs=2)
    for j in range(3):                       # one padded class of 3
        exprs, agg, chunk_fn = _parts(window_us=1_000_000 + j)
        tc.add(f"p{j}", _spec(exprs, agg, chunk_fn, seed=j),
               agg.core.init_state(), n_source_cols=N_SOURCE_COLS)
    singles = [
        _parts(calls=[count_star(), agg_call("max", 2, INT64)]),
        _parts(calls=[count_star(), agg_call("sum", 2, INT64)]),
        _parts(calls=[agg_call("min", 2, INT64)]),
    ]
    for j, (exprs, agg, chunk_fn) in enumerate(singles):
        tc.add(f"s{j}", _spec(exprs, agg, chunk_fn, seed=10 + j),
               agg.core.init_state(), n_source_cols=N_SOURCE_COLS)
    assert tc.dirty
    tc.ensure_compiled()
    st = tc.stats()
    assert not st["dirty"] and st["jobs"] == 6
    kinds = sorted(g["kind"] for g in st["groups"])
    # 3 same-skeleton jobs => 1 padded group; 3 unlike singletons chunk
    # into ceil(3/2) mega groups under mega_max_jobs=2
    assert kinds == ["mega", "mega", "padded"]
    assert st["dispatches_per_tick"] == 3
    assert st["schedule_compiles"] == 1
    # idempotent until the next DDL
    tc.ensure_compiled()
    assert tc.stats()["schedule_compiles"] == 1


# ---------------------------------------------------------------------------
# tier 1: padded supergroups — dispatch count + parity vs solo
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "n_jobs", [2, pytest.param(8, marks=pytest.mark.slow)])
def test_padded_group_one_dispatch_bit_exact_vs_solo(n_jobs):
    """UNEQUAL jobs (distinct window literals AND table capacities) in
    one shape class: exactly ONE vmapped dispatch per epoch, and every
    job's flush stream — inserts and U-/U+ churn — row-equal to its own
    solo fused run (padding changes slot layout, never values)."""
    with count_dispatches() as c:
        tc = TickCompiler()
        parts = []
        for j in range(n_jobs):
            exprs, agg, chunk_fn = _parts(
                window_us=1_000_000 * (j + 1),
                table_capacity=1 << (9 + (j % 3)))
            parts.append((exprs, agg, chunk_fn))
            tc.add(f"mv{j}", _spec(exprs, agg, chunk_fn, seed=100 + j),
                   agg.core.init_state(), n_source_cols=N_SOURCE_COLS)
        tc.ensure_compiled()
        assert [g.kind for g in tc.groups] == ["padded"]
        group = tc.groups[0]
        # class capacity is the max declared member capacity
        assert group.core.capacity == max(
            a.core.capacity for _, a, _ in parts)
        k = 4
        group.run_epoch(k)
        flushes = [group.flush()]
        c.reset()
        group.run_epoch(k)
        assert c.counts[PADDED_EPOCH_FN] == 1
        assert c.total == 1
        flushes.append(group.flush())
    for j, (exprs, agg, chunk_fn) in enumerate(parts):
        solo = fused_source_agg_epoch(chunk_fn, exprs, agg.core, CAP)
        st, start = agg.core.init_state(), 0
        for e in range(2):
            key = jax.random.fold_in(jax.random.PRNGKey(100 + j), e)
            st, chunks = _solo_epoch_and_flush(solo, agg, st, start,
                                               key, k)
            start += k * CAP
            assert _rows(flushes[e][f"mv{j}"]) == _rows(chunks)


def test_padded_flush_emits_retraction_churn():
    tc = TickCompiler()
    for j in range(2):
        exprs, agg, chunk_fn = _parts(window_us=1_000_000 * (j + 1))
        tc.add(f"mv{j}", _spec(exprs, agg, chunk_fn, seed=j),
               agg.core.init_state(), n_source_cols=N_SOURCE_COLS)
    tc.ensure_compiled()
    group = tc.groups[0]
    group.run_epoch(4)
    group.flush()
    group.run_epoch(4)
    outs = group.flush()
    ops = np.concatenate([np.asarray(c.ops)[np.asarray(c.vis)]
                          for c in outs["mv0"]])
    assert (ops == OP_UPDATE_DELETE).any()
    assert (ops == OP_UPDATE_INSERT).any()


# ---------------------------------------------------------------------------
# tier 2: mega-epochs — dispatch count + parity vs solo
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "n_jobs", [2, pytest.param(8, marks=pytest.mark.slow)])
def test_mega_group_one_dispatch_bit_exact_vs_solo(n_jobs):
    """Jobs sharing NO skeleton (different agg-call lists) concatenated
    into one compiled dispatch: per-job states AND flush chunks are
    bit-identical to the solo fused path — tier 2 never pads, so even
    the slot layout coincides."""
    callsets = [
        [count_star()],
        [count_star(), agg_call("max", 2, INT64)],
        [count_star(), agg_call("sum", 2, INT64)],
        [agg_call("min", 2, INT64)],
        [agg_call("sum", 2, INT64)],
        [agg_call("max", 2, INT64)],
        [count_star(), agg_call("min", 2, INT64)],
        [agg_call("sum", 2, INT64), agg_call("max", 2, INT64)],
    ][:n_jobs]
    with count_dispatches() as c:
        tc = TickCompiler()
        parts = []
        for j, calls in enumerate(callsets):
            exprs, agg, chunk_fn = _parts(calls=calls)
            parts.append((exprs, agg, chunk_fn))
            tc.add(f"mv{j}", _spec(exprs, agg, chunk_fn, seed=200 + j),
                   agg.core.init_state(), n_source_cols=N_SOURCE_COLS)
        tc.ensure_compiled()
        assert [g.kind for g in tc.groups] == ["mega"]
        group = tc.groups[0]
        k = 4
        group.run_epoch(k)
        flushes = [group.flush()]
        c.reset()
        group.run_epoch(k)
        assert c.counts[MEGA_EPOCH_FN] == 1
        assert c.total == 1
        flushes.append(group.flush())
    for j, (exprs, agg, chunk_fn) in enumerate(parts):
        solo = fused_source_agg_epoch(chunk_fn, exprs, agg.core, CAP)
        st, start = agg.core.init_state(), 0
        for e in range(2):
            key = jax.random.fold_in(jax.random.PRNGKey(200 + j), e)
            st, chunks = _solo_epoch_and_flush(solo, agg, st, start,
                                               key, k)
            start += k * CAP
            got = flushes[e][f"mv{j}"]
            assert len(got) == len(chunks)
            for ca, cb in zip(got, chunks):
                _assert_tree_equal(ca, cb)
        _assert_tree_equal(group.state_of(f"mv{j}"), st)


# ---------------------------------------------------------------------------
# DDL: recompilation, drop-one-member, the epochs-retired ledger
# ---------------------------------------------------------------------------


def test_ddl_recompile_drop_one_member_and_retire_ledger():
    """Dropping ONE member of a padded group dissolves the schedule,
    retires its epochs under the dispatch qualname, and the survivors
    recompile + continue from their written-back cursors/states —
    per-job results stay row-equal to uninterrupted solo runs."""
    tc = TickCompiler()
    parts = []
    for j in range(3):
        exprs, agg, chunk_fn = _parts(window_us=1_000_000 * (j + 1))
        parts.append((exprs, agg, chunk_fn))
        tc.add(f"mv{j}", _spec(exprs, agg, chunk_fn, seed=300 + j),
               agg.core.init_state(), n_source_cols=N_SOURCE_COLS)
    tc.ensure_compiled()
    k = 4
    tc.groups[0].run_epoch(k)
    flush0 = tc.groups[0].flush()
    dropped_state = tc.remove("mv1")
    assert dropped_state is not None and tc.dirty
    assert tc.take_retired() == {PADDED_EPOCH_FN: 1}
    assert tc.take_retired() == {}               # drained
    tc.ensure_compiled()
    assert tc.stats()["schedule_compiles"] == 2
    group = tc.groups[0]
    assert group.names == ["mv0", "mv2"]
    group.run_epoch(k)
    flush1 = group.flush()
    for j in (0, 2):
        exprs, agg, chunk_fn = parts[j]
        solo = fused_source_agg_epoch(chunk_fn, exprs, agg.core, CAP)
        st, start = agg.core.init_state(), 0
        for e in range(2):
            key = jax.random.fold_in(jax.random.PRNGKey(300 + j), e)
            st, chunks = _solo_epoch_and_flush(solo, agg, st, start,
                                               key, k)
            start += k * CAP
            got = (flush0 if e == 0 else flush1)[f"mv{j}"]
            assert _rows(got) == _rows(chunks)


def test_recovery_onto_recompiled_schedule_ops_level():
    """Checkpoint-shaped round trip: export every member's (padded)
    state + cursors, rebuild a FRESH compiler from the exports,
    continue both — row-equal flushes. Proves padded states re-enter a
    recompiled schedule exactly (class capacity is monotone: a member
    padded by the old schedule never shrinks)."""
    def build():
        tc = TickCompiler()
        for j in range(2):
            exprs, agg, chunk_fn = _parts(
                window_us=1_000_000 * (j + 1),
                table_capacity=1 << (9 + j))
            tc.add(f"mv{j}", _spec(exprs, agg, chunk_fn, seed=400 + j),
                   agg.core.init_state(), n_source_cols=N_SOURCE_COLS)
        tc.ensure_compiled()
        return tc

    tc = build()
    g = tc.groups[0]
    g.run_epoch(4)
    g.flush()

    tc2 = TickCompiler()
    for j in range(2):
        exprs, agg, chunk_fn = _parts(
            window_us=1_000_000 * (j + 1), table_capacity=1 << (9 + j))
        host = jax.device_get(g.state_of(f"mv{j}"))      # checkpoint
        state = jax.tree_util.tree_map(jnp.asarray, host)  # recovery
        tc2.add(f"mv{j}", _spec(exprs, agg, chunk_fn, seed=400 + j),
                state, n_source_cols=N_SOURCE_COLS,
                start=g.starts[j], batch_no=g.batch_nos[j])
    tc2.ensure_compiled()
    g.run_epoch(4)
    f1 = g.flush()
    g2 = tc2.groups[0]
    g2.run_epoch(4)
    f2 = g2.flush()
    for name in f1:
        assert _rows(f1[name]) == _rows(f2[name])


# ---------------------------------------------------------------------------
# the 200-small-MVs acceptance case (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_200_small_mvs_compile_to_at_most_8_dispatches():
    """ISSUE 19 acceptance: 200 mixed small dissimilar MVs tick in <= 8
    dispatches, and sampled members stay row-equal to their solo runs."""
    cap, k = 64, 2
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=cap))
    chunk_fn = gen.chunk_fn()

    def job(j):
        kind = j % 4
        if kind == 0:
            return _parts(window_us=1_000_000 + j, table_capacity=256,
                          cap=cap)
        if kind == 1:
            return _parts(window_us=2_000_000 + j, table_capacity=256,
                          cap=cap,
                          calls=[count_star(),
                                 agg_call("sum", 2, INT64)])
        if kind == 2:
            return _parts(table_capacity=256, cap=cap, group_keys=(1,),
                          calls=[agg_call("max", 2, INT64)])
        return _parts(table_capacity=256, cap=cap, group_keys=(1, 2),
                      calls=[count_star()])

    with count_dispatches() as c:
        tc = TickCompiler()
        parts = {}
        for j in range(200):
            exprs, agg, _ = job(j)
            parts[j] = (exprs, agg)
            tc.add(f"mv{j}", _spec(exprs, agg, chunk_fn, seed=j,
                                   cap=cap),
                   agg.core.init_state(), n_source_cols=N_SOURCE_COLS)
        tc.ensure_compiled()
        n_groups = tc.stats()["dispatches_per_tick"]
        assert n_groups <= 8, f"200 MVs need {n_groups} dispatches"
        c.reset()
        for g in tc.groups:
            g.run_epoch(k)
        assert (c.counts.get(PADDED_EPOCH_FN, 0)
                + c.counts.get(MEGA_EPOCH_FN, 0)) == n_groups
        flushes = {}
        for g in tc.groups:
            flushes.update(g.flush())
    for j in (0, 1, 2, 3, 101):                  # one per class + extra
        exprs, agg = parts[j]
        solo = fused_source_agg_epoch(chunk_fn, exprs, agg.core, cap)
        key = jax.random.fold_in(jax.random.PRNGKey(j), 0)
        _, chunks = _solo_epoch_and_flush(solo, agg,
                                          agg.core.init_state(), 0,
                                          key, k)
        assert _rows(flushes[f"mv{j}"]) == _rows(chunks)


# ---------------------------------------------------------------------------
# Session integration: routing, recovery, pipeline depth
# ---------------------------------------------------------------------------

SRC_SQL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT,
    price BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
    extra VARCHAR) WITH (connector = 'nexmark', nexmark_table = 'bid')"""

# three MVs, two shape classes: h0/h1 differ only in a literal (padded
# supergroup); h2 has different agg calls (mega singleton)
MV_SQLS = (
    "CREATE MATERIALIZED VIEW h0 AS SELECT auction, "
    "sum(price + 100) AS s FROM bid GROUP BY auction",
    "CREATE MATERIALIZED VIEW h1 AS SELECT auction, "
    "sum(price + 999) AS s FROM bid GROUP BY auction",
    "CREATE MATERIALIZED VIEW h2 AS SELECT bidder, count(*) AS c, "
    "max(price) AS m FROM bid GROUP BY bidder",
)


def _session(tmp_path=None, tick_compiler=True, **kw):
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig
    return Session(config=BuildConfig(tick_compiler=tick_compiler,
                                      agg_table_capacity=1 << 12),
                   source_chunk_capacity=CAP,
                   data_dir=str(tmp_path) if tmp_path else None, **kw)


def test_session_schedule_and_dispatch_counts():
    with count_dispatches() as c:
        s = _session()
        try:
            s.run_sql(SRC_SQL)
            for sql in MV_SQLS:
                s.run_sql(sql)
            s.tick()                      # compiles the schedule
            st = s.metrics()["hetero"]
            assert st["jobs"] == 3
            assert st["dispatches_per_tick"] == 2
            assert sorted(g["kind"] for g in st["groups"]) == \
                ["mega", "padded"]
            c.reset()
            s.tick()
            assert c.counts[PADDED_EPOCH_FN] == 1
            assert c.counts[MEGA_EPOCH_FN] == 1
            # attribution weights cover every member
            attr = st["attribution"]
            assert set(attr[PADDED_EPOCH_FN]) == {"h0", "h1"}
            assert set(attr[MEGA_EPOCH_FN]) == {"h2"}
            # h0 vs h1: same groups, different literal => values differ
            r0 = dict(s.run_sql("SELECT auction, s FROM h0"))
            r1 = dict(s.run_sql("SELECT auction, s FROM h1"))
            assert set(r0) == set(r1)
            assert any(r0[a] != r1[a] for a in r0)
        finally:
            s.close()


@pytest.mark.slow
def test_session_matches_coscheduler_results():
    """The compiled schedule must agree with the PROVEN engine: the
    same CREATEs under [streaming] coschedule = true (signature-equal
    grouping) produce identical MV contents."""
    def run(**flags):
        from risingwave_tpu.frontend import Session
        from risingwave_tpu.frontend.build import BuildConfig
        s = Session(config=BuildConfig(agg_table_capacity=1 << 12,
                                       **flags),
                    source_chunk_capacity=CAP)
        try:
            s.run_sql(SRC_SQL)
            for sql in MV_SQLS:
                s.run_sql(sql)
            for _ in range(3):
                s.tick()
            return [sorted(map(tuple, s.run_sql(f"SELECT * FROM h{j}")))
                    for j in range(3)]
        finally:
            s.close()

    het = run(tick_compiler=True)
    cos = run(coschedule=True)
    assert het == cos


def test_session_recovery_onto_recompiled_schedule(tmp_path):
    """Checkpoint → close → reopen: the -- hetero markers route every
    MV back through the compiler, recovered MV contents match the
    committed ones, and ticking continues on the recompiled schedule
    (including after a DROP between the two sessions)."""
    s = _session(tmp_path, checkpoint_frequency=2)
    s.run_sql(SRC_SQL)
    for sql in MV_SQLS:
        s.run_sql(sql)
    for _ in range(5):                 # epochs 2..6; checkpoints at 2,4,6
        s.tick()
    committed = [sorted(map(tuple, s.run_sql(f"SELECT * FROM h{j}")))
                 for j in range(3)]
    s.close()

    s2 = _session(tmp_path, checkpoint_frequency=2)
    try:
        got = [sorted(map(tuple, s2.run_sql(f"SELECT * FROM h{j}")))
               for j in range(3)]
        assert got == committed
        st = s2.metrics()["hetero"]
        assert st["jobs"] == 3
        for _ in range(3):
            s2.tick()
        st = s2.metrics()["hetero"]
        assert sorted(g["kind"] for g in st["groups"]) == \
            ["mega", "padded"]
        after = [sorted(map(tuple, s2.run_sql(f"SELECT * FROM h{j}")))
                 for j in range(3)]
        assert sum(len(r) for r in after) > 0
        # drop one padded member; the survivor set recompiles cleanly
        s2.run_sql("DROP MATERIALIZED VIEW h1")
        for _ in range(2):
            s2.tick()
        assert s2.metrics()["hetero"]["jobs"] == 2
    finally:
        s2.close()


def test_session_recovery_refuses_without_flag(tmp_path):
    s = _session(tmp_path, checkpoint_frequency=2)
    s.run_sql(SRC_SQL)
    s.run_sql(MV_SQLS[0])
    s.tick()
    s.close()
    from risingwave_tpu.frontend.session import SqlError
    with pytest.raises(SqlError, match="tick-compiled"):
        _session(tmp_path, tick_compiler=False, checkpoint_frequency=2)


@pytest.mark.slow
def test_session_pipeline_depth2_bit_exact_at_drain():
    """pipeline_depth=2 defers each group's packed flush one tick; at
    the drain (flush) the MV contents must be bit-exact vs depth 1, and
    the per-qualname dispatch counts identical (reordered, never
    added)."""
    def run(depth):
        with count_dispatches() as c:
            s = _session(chunks_per_tick=2, checkpoint_frequency=4,
                         pipeline_depth=depth)
            try:
                s.run_sql(SRC_SQL)
                for sql in MV_SQLS:
                    s.run_sql(sql)
                for _ in range(7):
                    s.tick()
                s.flush()
                rows = [sorted(map(tuple,
                                   s.run_sql(f"SELECT * FROM h{j}")))
                        for j in range(3)]
                counts = dict(c.counts)
            finally:
                s.close()
        return rows, counts

    rows1, counts1 = run(1)
    rows2, counts2 = run(2)
    assert rows1 == rows2
    for qn in (PADDED_EPOCH_FN, MEGA_EPOCH_FN):
        assert counts1.get(qn) == counts2.get(qn) and counts1.get(qn)
