"""Registry-coverage cross-check (PR 13 satellite): every fused-epoch
builder — the ``EPOCH_BUILDERS``/``SHARDED_EPOCH_BUILDERS`` registry
entries plus the co-scheduled group builders resolved outside the dicts
— must be known to all three guard planes at once:

* ``common/dispatch_count.py`` — the runtime counter keys dispatches by
  the wrapped callable's ``__qualname__``; a builder whose jit escapes
  the wrapping convention would count under a garbage name and every
  ``c.counts[qualname] == 1`` regression would silently pass on 0.
* ``common/profiling.py`` — the profiler wrapper must sit on every
  builder's return value (same qualname key), or the live per_epoch
  invariant and the roofline lose the surface.
* rwlint's dispatch-discipline closure — the static registry parse must
  resolve exactly the runtime entries, or an edit inside a new builder
  could smuggle a host sync past the lint.

A future builder added to a registry without the profile_dispatch +
stable-qualname convention fails HERE, in tier-1, not in a bench round.
"""

import jax
import jax.numpy as jnp
import pytest

from risingwave_tpu.common import INT64, TIMESTAMP
from risingwave_tpu.common.dispatch_count import count_dispatches
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.connector import NexmarkConfig
from risingwave_tpu.connector.nexmark import DeviceBidGenerator
from risingwave_tpu.connector.tpch import (
    DeviceQ3Generator, Q3_CUTOFF_DAYS, TpchQ3Config,
)
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import count_star
from risingwave_tpu.ops.fused_epoch import EPOCH_BUILDERS
from risingwave_tpu.ops.fused_hetero import HETERO_EPOCH_BUILDERS
from risingwave_tpu.ops.fused_multi import (
    build_group_epoch, fused_multi_agg_epoch, fused_multi_join_epoch,
    stack_states,
)
from risingwave_tpu.ops.fused_sharded import SHARDED_EPOCH_BUILDERS
from risingwave_tpu.ops.grouped_agg import AggCore
from risingwave_tpu.ops.interval_join import IntervalJoinCore
from risingwave_tpu.ops.join_state import JoinCore, JoinType
from risingwave_tpu.ops.session_window import SessionWindowCore
from risingwave_tpu.ops.stream_q3 import Q3Core
from risingwave_tpu.parallel.sharded_agg import make_mesh

CAP, K, JOBS, MESH_N = 128, 2, 2, 2

#: the group-epoch builders stream/coschedule.py resolves directly
#: (rwlint's EXTRA_BUILDERS twin — cross-checked below)
COSCHEDULED_BUILDERS = {
    "fused_multi_agg_epoch": fused_multi_agg_epoch,
    "fused_multi_join_epoch": fused_multi_join_epoch,
    "build_group_epoch": build_group_epoch,
}


def _q5_parts():
    exprs = [call("tumble_start", col(5, TIMESTAMP),
                  Literal(1_000_000, INT64)), col(0, INT64)]
    core = AggCore([INT64, INT64], [0, 1], [count_star()], 1 << 10, CAP)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    return exprs, core, gen.chunk_fn()


def _q7_parts():
    exprs = [call("tumble_start", col(5, TIMESTAMP),
                  Literal(5_000, INT64)), col(0, INT64), col(2, INT64)]
    core = IntervalJoinCore(
        Schema((Field("ws", TIMESTAMP), Field("auction", INT64),
                Field("price", INT64))),
        ts_col=0, val_col=2, window_us=5_000, n_buckets=128,
        lane_width=32)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    return exprs, core, gen.chunk_fn()


def _q8_parts():
    core = SessionWindowCore(
        Schema((Field("bidder", INT64), Field("ts", TIMESTAMP))),
        key_col=0, ts_col=1, gap_us=5_000, capacity=1 << 10,
        closed_capacity=1 << 10)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    return [col(1, INT64), col(5, TIMESTAMP)], core, gen.chunk_fn()


def _q3_parts():
    core = Q3Core(Q3_CUTOFF_DAYS, orders_capacity=1 << 10,
                  agg_capacity=1 << 10)
    gen = DeviceQ3Generator(TpchQ3Config(chunk_capacity=CAP))
    return core, gen.chunk_fn()


def _stack(core, n):
    return stack_states([core.init_state() for _ in range(n)])


def _group_stack(core, n, jobs):
    per_job = [_stack(core, n) for _ in range(jobs)]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=1),
                                  *per_job)


def _job_args():
    starts = jnp.zeros(JOBS, jnp.int64)
    keys = jnp.stack([jax.random.PRNGKey(j) for j in range(JOBS)])
    nos = jnp.zeros(JOBS, jnp.int64)
    return starts, keys, nos


def _zero_join_batch(schema, n):
    from risingwave_tpu.common.chunk import Column, StreamChunk
    cols = tuple(Column(jnp.zeros((n, K, CAP), f.type.dtype),
                        jnp.zeros((n, K, CAP), jnp.bool_))
                 for f in schema)
    return StreamChunk(jnp.zeros((n, K, CAP), jnp.int8),
                       jnp.zeros((n, K, CAP), jnp.bool_), cols)


def _build_and_call_all(mesh):
    """Build ONE instance of every registered surface inside the active
    count_dispatches patch and drive it once. Returns {registry key:
    wrapped callable} keyed '<registry>:<key>'."""
    start, key = jnp.int64(0), jax.random.PRNGKey(0)
    out = {}

    exprs, core, fn = _q5_parts()
    f = EPOCH_BUILDERS["source_agg"](fn, exprs, core, CAP, donate=False)
    f(core.init_state(), start, key, K)
    out["EPOCH_BUILDERS:source_agg"] = f

    exprs, core, fn = _q7_parts()
    f = EPOCH_BUILDERS["source_join"](fn, exprs, core, CAP, donate=False)
    f(core.init_state(), start, key, K)
    out["EPOCH_BUILDERS:source_join"] = f

    exprs, core, fn = _q8_parts()
    f = EPOCH_BUILDERS["source_session"](fn, exprs, core, CAP,
                                         donate=False)
    f(core.init_state(), start, key, K, jnp.int64(0))
    out["EPOCH_BUILDERS:source_session"] = f

    core, fn = _q3_parts()
    f = EPOCH_BUILDERS["source_q3"](fn, core, CAP, donate=False)
    f(core.init_state(), start, key, K)
    out["EPOCH_BUILDERS:source_q3"] = f

    exprs, core, fn = _q5_parts()
    f = SHARDED_EPOCH_BUILDERS["source_agg"](fn, exprs, core, CAP, mesh)
    f(_stack(core, MESH_N), start, key, K)
    out["SHARDED_EPOCH_BUILDERS:source_agg"] = f

    exprs, core, fn = _q7_parts()
    f = SHARDED_EPOCH_BUILDERS["source_join"](fn, exprs, core, CAP, mesh)
    f(_stack(core, MESH_N), start, key, K)
    out["SHARDED_EPOCH_BUILDERS:source_join"] = f

    exprs, core, fn = _q8_parts()
    f = SHARDED_EPOCH_BUILDERS["source_session"](fn, exprs, core, CAP,
                                                 mesh)
    f(_stack(core, MESH_N), start, key, K, jnp.int64(0))
    out["SHARDED_EPOCH_BUILDERS:source_session"] = f

    core, fn = _q3_parts()
    f = SHARDED_EPOCH_BUILDERS["source_q3"](fn, core, CAP, mesh)
    f(_stack(core, MESH_N), start, key, K)
    out["SHARDED_EPOCH_BUILDERS:source_q3"] = f

    ls = Schema((Field("k", INT64), Field("v", INT64)))
    rs = Schema((Field("k", INT64), Field("w", INT64)))
    jcore = JoinCore(ls, rs, [0], [0], JoinType.INNER,
                     key_capacity=1 << 6, bucket_width=4)
    f = SHARDED_EPOCH_BUILDERS["equi_join"](jcore, mesh, [0], [0])
    f(_stack(jcore, MESH_N), _zero_join_batch(ls, MESH_N), side="left")
    out["SHARDED_EPOCH_BUILDERS:equi_join"] = f

    exprs, core, fn = _q5_parts()
    f = SHARDED_EPOCH_BUILDERS["group_agg"](fn, exprs, core, CAP, mesh)
    f(_group_stack(core, MESH_N, JOBS), *_job_args(), K)
    out["SHARDED_EPOCH_BUILDERS:group_agg"] = f

    exprs, core, fn = _q5_parts()
    f = COSCHEDULED_BUILDERS["fused_multi_agg_epoch"](fn, exprs, core,
                                                      CAP, donate=False)
    starts, keys, _ = _job_args()
    f(stack_states([core.init_state() for _ in range(JOBS)]), starts,
      keys, K)
    out["COSCHEDULED_BUILDERS:fused_multi_agg_epoch"] = f

    exprs, core, fn = _q7_parts()
    f = COSCHEDULED_BUILDERS["fused_multi_join_epoch"](fn, exprs, core,
                                                       CAP, donate=False)
    f(stack_states([core.init_state() for _ in range(JOBS)]), starts,
      keys, K)
    out["COSCHEDULED_BUILDERS:fused_multi_join_epoch"] = f

    exprs, core, fn = _q5_parts()
    f = COSCHEDULED_BUILDERS["build_group_epoch"]("agg", fn, exprs, core,
                                                  CAP, donate=False)
    f(stack_states([core.init_state() for _ in range(JOBS)]),
      *_job_args(), K)
    out["COSCHEDULED_BUILDERS:build_group_epoch"] = f

    # the tick compiler's two dispatch tiers (ISSUE 19)
    from risingwave_tpu.connector import BID_SCHEMA
    from risingwave_tpu.stream.coschedule import FusedJobSpec
    from risingwave_tpu.stream.tick_compiler import skeletonize_exprs
    import numpy as np

    exprs, core, fn = _q5_parts()
    skel, hole_types, params = skeletonize_exprs(tuple(exprs),
                                                 len(BID_SCHEMA))
    f = HETERO_EPOCH_BUILDERS["padded_agg"](fn, skel, core, CAP,
                                            donate=False)
    starts, keys, nos = _job_args()
    param_cols = tuple(jnp.asarray(np.full(JOBS, params[h], t.np_dtype))
                       for h, t in enumerate(hole_types))
    f(stack_states([core.init_state() for _ in range(JOBS)]), starts,
      keys, nos, param_cols, K)
    out["HETERO_EPOCH_BUILDERS:padded_agg"] = f

    exprs, core, fn = _q5_parts()
    other = AggCore([INT64], [1], [count_star()], 1 << 10, CAP)
    specs = [FusedJobSpec("agg", ("agg", ("nexmark_bid", CAP)), fn,
                          tuple(exprs), c, CAP, seed=j)
             for j, c in enumerate((core, other))]
    f = HETERO_EPOCH_BUILDERS["mega_agg"](specs, donate=False)
    f((core.init_state(), other.init_state()), starts, keys, nos, K)
    out["HETERO_EPOCH_BUILDERS:mega_agg"] = f

    return out


def test_rwlint_closure_covers_every_registry_entry():
    """The static dispatch-discipline coverage map resolves EXACTLY the
    runtime registries — including the group builders outside the dicts
    — and each builder's closure is non-trivial (reaches its epoch body
    and device core)."""
    from risingwave_tpu.analysis import load_package, package_root
    from risingwave_tpu.analysis.rules_purity import DispatchDiscipline

    cov = DispatchDiscipline().coverage(load_package(package_root()))
    assert set(cov["EPOCH_BUILDERS"]) == set(EPOCH_BUILDERS)
    assert set(cov["SHARDED_EPOCH_BUILDERS"]) == \
        set(SHARDED_EPOCH_BUILDERS)
    assert set(cov["COSCHEDULED_BUILDERS"]) == set(COSCHEDULED_BUILDERS)
    assert set(cov["HETERO_EPOCH_BUILDERS"]) == set(HETERO_EPOCH_BUILDERS)
    for reg, entries in cov.items():
        for entry_key, reach in entries.items():
            assert len(reach) >= 5, (reg, entry_key)


@pytest.mark.slow
def test_every_builder_counts_and_profiles_under_its_qualname():
    """Drive one epoch of EVERY registered surface with BOTH guard
    planes active: the dispatch counter and the profiler must each
    record exactly that call under the same stable qualname the tests,
    bench --smoke, and the metrics per_epoch ratio key on — and that
    qualname must follow the builder-name convention the retirement
    bookkeeping in frontend/session.py assumes."""
    from risingwave_tpu.common.profiling import GLOBAL_PROFILER

    mesh = make_mesh(MESH_N)
    GLOBAL_PROFILER.reset()
    with count_dispatches() as c:
        wrapped = _build_and_call_all(mesh)
    prof = GLOBAL_PROFILER.counts()
    registries = {"EPOCH_BUILDERS": EPOCH_BUILDERS,
                  "SHARDED_EPOCH_BUILDERS": SHARDED_EPOCH_BUILDERS,
                  "COSCHEDULED_BUILDERS": COSCHEDULED_BUILDERS,
                  "HETERO_EPOCH_BUILDERS": HETERO_EPOCH_BUILDERS}
    for reg_key, f in wrapped.items():
        reg_name, builder_name = reg_key.split(":")
        qn = f.__qualname__
        # convention: '<builder fn name>.<locals>.<epoch fn>' — the
        # registry's builder is always the qualname prefix
        assert qn.startswith(
            registries[reg_name][builder_name].__name__ + "."), \
            (reg_key, qn)
        assert c.counts.get(qn, 0) == 1, (reg_key, qn, dict(c.counts))
        assert prof.get(qn, 0) == 1, (reg_key, qn, prof)
