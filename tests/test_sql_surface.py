"""Broadened SQL surface (VERDICT r3 item 10): approx_count_distinct as a
device HLL agg, regexp scalar functions, regexp_split_to_table, and the
schema-check sanitizer wrapper.
"""

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.build import BuildConfig


class TestApproxCountDistinct:
    def test_streaming_and_batch_agree_and_are_close(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, "
                  "v BIGINT)")
        vals = ", ".join(f"({i}, {i % 2}, {i % 37})" for i in range(400))
        s.run_sql(f"INSERT INTO t VALUES {vals}")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT g, approx_count_distinct(v) AS d "
                  "FROM t GROUP BY g")
        s.flush()
        mv = dict(s.mv_rows("m"))
        sel = dict(s.run_sql(
            "SELECT g, approx_count_distinct(v) AS d FROM t GROUP BY g"))
        assert mv == sel                     # same HLL, same registers
        for g in (0, 1):
            # true distinct count is 37 per group; m=16 registers => the
            # estimate must land within a generous +/-40% band
            assert 22 <= mv[g] <= 52, mv

    def test_global_and_incremental(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT approx_count_distinct(v) AS d FROM t")
        s.run_sql("INSERT INTO t VALUES (1, 7), (2, 7), (3, 7)")
        s.flush()
        one = s.mv_rows("m")[0][0]
        assert 1 <= one <= 2                 # ~1 distinct value
        vals = ", ".join(f"({i}, {i})" for i in range(10, 110))
        s.run_sql(f"INSERT INTO t VALUES {vals}")
        s.flush()
        many = s.mv_rows("m")[0][0]
        assert 60 <= many <= 160             # ~101 distinct values

    def test_distinct_strings_by_content(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, name VARCHAR)")
        s.run_sql("INSERT INTO t VALUES (1, 'x'), (2, 'x'), (3, 'y'), "
                  "(4, 'y'), (5, 'x')")
        s.flush()
        got = s.run_sql("SELECT approx_count_distinct(name) FROM t")[0][0]
        assert 1 <= got <= 4                 # ~2 distinct strings


class TestRegexp:
    def _t(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, u VARCHAR)")
        s.run_sql("INSERT INTO t VALUES "
                  "(1, 'https://a.example.com/x'), "
                  "(2, 'http://b.org/y'), (3, 'ftp://c.net/z')")
        s.flush()
        return s

    def test_regexp_like_filter(self):
        s = self._t()
        got = sorted(r[0] for r in s.run_sql(
            "SELECT k FROM t WHERE regexp_like(u, '^https?://')"))
        assert got == [1, 2]

    def test_regexp_replace_and_count(self):
        s = self._t()
        got = sorted(s.run_sql(
            "SELECT k, regexp_replace(u, '^[a-z]+://', ''), "
            "regexp_count(u, '/') FROM t"))
        assert got == [(1, "a.example.com/x", 3), (2, "b.org/y", 3),
                       (3, "c.net/z", 3)]

    def test_regexp_match_null_on_miss(self):
        s = self._t()
        got = dict(s.run_sql(
            "SELECT k, regexp_match(u, 'example[.]com') FROM t"))
        assert got == {1: "example.com", 2: None, 3: None}

    def test_regexp_replace_first_match_only_by_default(self):
        # PG semantics: without the 'g' flag only the FIRST match is
        # replaced (advisor r4 medium finding)
        s = Session()
        got = s.run_sql("SELECT regexp_replace('aaa', 'a', 'b')")[0][0]
        assert got == "baa"

    def test_regexp_replace_g_and_i_flags(self):
        s = Session()
        assert s.run_sql(
            "SELECT regexp_replace('aaa', 'a', 'b', 'g')")[0][0] == "bbb"
        assert s.run_sql(
            "SELECT regexp_replace('AaA', 'a', 'b', 'gi')")[0][0] == "bbb"
        # first case-insensitive match is the leading 'A'
        assert s.run_sql(
            "SELECT regexp_replace('AaA', 'a', 'b', 'i')")[0][0] == "baA"

    def test_regexp_match_returns_first_capture_group(self):
        s = Session()
        got = s.run_sql(
            "SELECT regexp_match('https://a.io/x', '^([a-z]+)://')")[0][0]
        assert got == "https"

    def test_regexp_in_streaming_mv(self):
        s = self._t()
        s.run_sql("CREATE MATERIALIZED VIEW secure AS "
                  "SELECT k, u FROM t WHERE regexp_like(u, '^https://')")
        s.flush()
        assert [r[0] for r in s.mv_rows("secure")] == [1]
        s.run_sql("INSERT INTO t VALUES (9, 'https://d.io/q')")
        s.flush()
        assert sorted(r[0] for r in s.mv_rows("secure")) == [1, 9]


class TestRegexpSplitToTable:
    def test_from_position_constant(self):
        s = Session()
        got = [r[0] for r in s.run_sql(
            "SELECT * FROM regexp_split_to_table('a,b,,c', ',')")]
        assert got == ["a", "b", "", "c"]

    def test_project_set_over_rows(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, csv VARCHAR)")
        s.run_sql("INSERT INTO t VALUES (1, 'x;y'), (2, 'z')")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, regexp_split_to_table(csv, ';') AS part "
                  "FROM t")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(1, "x"), (1, "y"), (2, "z")]


class TestSchemaCheckWrapper:
    def test_sanity_checked_mv_runs_clean(self):
        s = Session(config=BuildConfig(sanity_checks=True))
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, "
                  "v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT g, count(*) AS n, sum(v) AS sv FROM t GROUP BY g")
        s.run_sql("INSERT INTO t VALUES (1, 0, 10), (2, 1, 20)")
        s.run_sql("UPDATE t SET g = 1 WHERE k = 1")
        s.flush()
        assert sorted(s.mv_rows("m")) == [(1, 2, 30)]

    def test_schema_check_catches_width_mismatch(self):
        import asyncio

        import jax.numpy as jnp

        from risingwave_tpu.common.chunk import Column, StreamChunk
        from risingwave_tpu.common.types import INT64, Schema
        from risingwave_tpu.stream import SchemaCheckExecutor
        from risingwave_tpu.stream.message import Barrier
        from risingwave_tpu.stream.source import MockSource

        schema = Schema.of(("a", INT64), ("b", INT64))
        bad = StreamChunk(jnp.zeros(2, jnp.int8), jnp.ones(2, jnp.bool_),
                          (Column(jnp.zeros(2, jnp.int64),
                                  jnp.ones(2, jnp.bool_)),))  # 1 col != 2
        src = MockSource(schema, [Barrier.new(1), bad, Barrier.new(2)])
        chk = SchemaCheckExecutor(src)

        async def drive():
            async for _ in chk.execute():
                pass

        with pytest.raises(AssertionError, match="schema check"):
            asyncio.run(drive())
