"""Out-of-process UDF plane (ISSUE 15, docs/robustness.md).

Fast tier: wire codecs, function shipping, bit-exact parity inproc vs
out-of-process, restart semantics (deadline trip, deterministic
kill -9 mid-batch, reply-after-fence dropped, retry-exhausted typed
error, user exceptions not burning respawns), backpressure, metrics.

Slow tier (scripts/check.sh UDF subset): the seeded udf-link chaos
scenario + replay determinism, the kill-mid-epoch acceptance run under
pipeline_depth=2 with a co-scheduled group, the crash-point sweep over
the udf.* failpoint sites, `ctl udf serve` + external attach, and the
soak seed whose record `ctl bench trend` folds.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from risingwave_tpu.common.config import UdfConfig
from risingwave_tpu.common.types import (
    BOOL, FLOAT64, INT64, VARCHAR, DataType, TypeKind,
)
from risingwave_tpu.expr.udf import drop_udf, register_udf
from risingwave_tpu.frontend import Session
from risingwave_tpu.udf.client import (
    UdfOverloadedError, UdfServerError, UdfTimeoutError, udf_plane,
)
from risingwave_tpu.udf.registry import (
    UdfNotPortableError, UdfSpec, load_function, ship_function,
)


@pytest.fixture(autouse=True)
def _restore_plane_config():
    """Every test gets the default plane config back (the plane is
    process-global; tests tune deadlines/backpressure)."""
    plane = udf_plane()
    old_cfg, old_trace = plane.config, plane.trace_dir
    yield
    plane.configure(old_cfg)
    plane.trace_dir = old_trace


def _register(name, fn, args, ret, **kw):
    register_udf(name, fn, args, ret, **kw)
    return name


# ---------------------------------------------------------------------------
# wire codecs (common/interchange.py)
# ---------------------------------------------------------------------------

class TestWireCodec:
    def test_fixed_width_round_trip(self):
        from risingwave_tpu.common.interchange import (
            udf_batch_to_wire, wire_to_udf_batch,
        )
        types = [INT64, FLOAT64, BOOL]
        datas = [np.array([1, -7, 2**40], np.int64),
                 np.array([0.5, -1.25, 3.0]),
                 np.array([True, False, True])]
        masks = [np.array([True, True, False]),
                 np.array([True, False, True]),
                 np.array([True, True, True])]
        wire = udf_batch_to_wire(datas, masks, types)
        out_d, out_m = wire_to_udf_batch(
            json.loads(json.dumps(wire)), types)  # must be JSON-safe
        for d, od in zip(datas, out_d):
            assert od.tolist() == d.tolist()
        for m, om in zip(masks, out_m):
            assert om.tolist() == m.tolist()

    def test_decimal_rides_physical_scaled_int(self):
        from risingwave_tpu.common.interchange import (
            udf_col_to_wire, wire_to_udf_col,
        )
        from risingwave_tpu.common.types import decimal
        t = decimal(2)
        d, m = wire_to_udf_col(
            udf_col_to_wire(np.array([125, -50], np.int64),
                            np.array([True, True]), t), t)
        assert d.tolist() == [125, -50] and d.dtype == np.int64

    def test_string_col_decodes_and_nulls(self):
        from risingwave_tpu.common.interchange import (
            udf_col_to_wire, wire_to_udf_col,
        )
        ids = np.array([VARCHAR.to_physical("hey"),
                        0,
                        VARCHAR.to_physical("yo")], np.int64)
        mask = np.array([True, False, True])
        wire = udf_col_to_wire(ids, mask, VARCHAR)
        assert wire["enc"] == "utf8"
        assert wire["values"] == ["hey", None, "yo"]
        d, m = wire_to_udf_col(wire, VARCHAR)
        assert list(d) == ["hey", None, "yo"]
        assert m.tolist() == [True, False, True]

    def test_list_struct_refuse_with_remediation(self):
        from risingwave_tpu.common.interchange import udf_type_to_wire
        t = DataType(TypeKind.LIST, elem_kind=TypeKind.INT64)
        with pytest.raises(TypeError, match="inproc"):
            udf_type_to_wire(t)


# ---------------------------------------------------------------------------
# function shipping (udf/registry.py)
# ---------------------------------------------------------------------------

class TestShipping:
    def test_module_function_ships_by_reference(self):
        from risingwave_tpu.sim import _chaos_tax
        d = ship_function(_chaos_tax)
        assert d["how"] == "ref" and d["module"] == "risingwave_tpu.sim"
        assert load_function(d)(5) == _chaos_tax(5)

    def test_lambda_ships_by_code(self):
        d = ship_function(lambda v: v * 10)
        assert d["how"] == "code"
        assert load_function(json.loads(json.dumps(d)))(4) == 40

    def test_closure_ships_cell_values(self):
        rate = 3

        def taxed(v):
            return v * rate

        d = ship_function(taxed)
        assert d["how"] == "code"
        assert load_function(d)(2) == 6

    def test_unmarshalable_closure_refuses_loudly(self):
        sock = threading.Lock()   # no marshal encoding exists

        def bad(v):
            return v if sock else None

        with pytest.raises(UdfNotPortableError, match="inproc"):
            ship_function(bad)

    def test_registration_validates_eagerly(self):
        lock = threading.Lock()
        with pytest.raises(UdfNotPortableError):
            register_udf("bad_udf", lambda v: v if lock else None,
                         [INT64], INT64)
        from risingwave_tpu.expr.expr import _REGISTRY
        assert "bad_udf" not in _REGISTRY


# ---------------------------------------------------------------------------
# parity: out-of-process bit-exact vs inproc (shared evaluator)
# ---------------------------------------------------------------------------

class TestParity:
    DDL = ("CREATE TABLE pt (k BIGINT PRIMARY KEY, v BIGINT, "
           "s VARCHAR, x DOUBLE)")
    ROWS = ("INSERT INTO pt VALUES (1, 100, 'hey', 3.0), "
            "(2, NULL, 'yo', 4.0), (3, 300, NULL, NULL)")
    Q = "SELECT k, p_tax(v), p_shout(s), p_sq(x) FROM pt"

    def _run(self, mode):
        udf_plane().configure(UdfConfig(mode=mode))
        register_udf("p_tax", lambda v: int(v * 1.1), [INT64], INT64)
        register_udf("p_shout", lambda s: s.upper() + "!",
                     [VARCHAR], VARCHAR)
        register_udf("p_sq", lambda a: a * a, [FLOAT64], FLOAT64,
                     vectorized=True)
        try:
            s = Session()
            s.run_sql(self.DDL)
            s.run_sql(self.ROWS)
            s.flush()
            rows = sorted(s.run_sql(self.Q))
            s.close()
            return rows
        finally:
            for n in ("p_tax", "p_shout", "p_sq"):
                drop_udf(n)

    def test_process_bit_exact_vs_inproc(self):
        got_proc = self._run("process")
        got_inproc = self._run("inproc")
        assert got_proc == got_inproc
        assert got_proc == [(1, 110, "HEY!", 9.0),
                            (2, None, "YO!", 16.0),
                            (3, 330, None, None)]

    def test_strict_null_never_calls_fn(self):
        calls = []

        def spy(v):
            calls.append(v)
            return v

        udf_plane().configure(UdfConfig(mode="inproc"))
        register_udf("p_spy", spy, [INT64], INT64)
        try:
            s = Session()
            s.run_sql("CREATE TABLE nt (k BIGINT PRIMARY KEY, v BIGINT)")
            s.run_sql("INSERT INTO nt VALUES (1, NULL), (2, 5)")
            s.flush()
            rows = dict(s.run_sql("SELECT k, p_spy(v) FROM nt"))
            assert rows == {1: None, 2: 5}
            assert calls == [5]
            s.close()
        finally:
            drop_udf("p_spy")


# ---------------------------------------------------------------------------
# restart semantics
# ---------------------------------------------------------------------------

class TestRestartSemantics:
    @pytest.mark.slow   # 2 deliberate deadline trips + 3 server spawns
    def test_deadline_trip_exhausts_to_typed_error_session_survives(self):
        udf_plane().configure(UdfConfig(call_timeout_s=0.4,
                                        max_retries=1,
                                        spawn_timeout_s=30.0))
        register_udf("hang", lambda v: time.sleep(30) or v,
                     [INT64], INT64)
        register_udf("fine", lambda v: v + 1, [INT64], INT64)
        try:
            s = Session()
            s.run_sql("CREATE TABLE ht (k BIGINT PRIMARY KEY, v BIGINT)")
            s.run_sql("INSERT INTO ht VALUES (1, 10)")
            s.flush()
            base = udf_plane().snapshot()
            with pytest.raises(UdfTimeoutError, match="hang"):
                s.run_sql("SELECT hang(v) FROM ht")
            snap = udf_plane().snapshot()
            assert snap["timeouts"] - base["timeouts"] == 2  # 2 attempts
            assert snap["respawns"] - base["respawns"] == 2
            # the STATEMENT failed; the session/epoch loop did not:
            s.tick()
            assert s.run_sql("SELECT fine(v) FROM ht") == [(11,)]
            s.close()
        finally:
            drop_udf("hang")
            drop_udf("fine")

    @pytest.mark.slow   # 2 real server spawns (one dies at the site)
    def test_server_killed_mid_batch_respawn_replays(self, tmp_path):
        """Deterministic kill -9 mid-batch: RWTPU_FAILPOINTS arms a real
        os._exit at udf.server.eval in the SERVER process (once via
        marker); the client detects the death, respawns a seeded server,
        replays the batch, and the statement SUCCEEDS."""
        marker = str(tmp_path / "udf_died.marker")
        os.environ["RWTPU_FAILPOINTS"] = json.dumps(
            {"udf.server.eval": {"action": "exit",
                                 "once_marker": marker}})
        udf_plane().shutdown_server()   # next spawn inherits the env
        register_udf("k9", lambda v: v * 2, [INT64], INT64)
        try:
            base = udf_plane().snapshot()
            s = Session()
            s.run_sql("CREATE TABLE kt (k BIGINT PRIMARY KEY, v BIGINT)")
            s.run_sql("INSERT INTO kt VALUES (1, 21)")
            s.flush()
            assert s.run_sql("SELECT k9(v) FROM kt") == [(42,)]
            assert os.path.exists(marker), "server never died at the site"
            snap = udf_plane().snapshot()
            assert snap["spawns"] - base["spawns"] >= 2
            s.close()
        finally:
            os.environ.pop("RWTPU_FAILPOINTS", None)
            drop_udf("k9")
            udf_plane().shutdown_server()   # drop the armed-env server

    def test_reply_after_fence_dropped(self):
        """A chaos-duplicated reply (same rid, stale by the time it
        arrives) is dropped by the (gen, rid) fence, never returned to
        a later call."""
        from risingwave_tpu.rpc.faults import (
            ChaosRule, ChaosSchedule, install,
        )
        udf_plane().configure(UdfConfig())
        udf_plane().shutdown_server()
        register_udf("fence", lambda v: v + 5, [INT64], INT64)
        try:
            plane = udf_plane()
            spec_args = ([np.array([1, 2], np.int64)],
                         [np.ones(2, bool)])
            # server spawns WITHOUT chaos env; the SESSION-side plane
            # duplicates the server's... replies are server-side, so
            # duplicate the REQUEST instead: the server evaluates twice
            # and sends two replies with the same rid — the second must
            # be dropped, not taken for call #2's answer.
            install(ChaosSchedule(3, [ChaosRule(
                kind="duplicate", link="s->udf", types=["udf_call"],
                count=1)]))
            try:
                d1, _ = plane.call("fence", *spec_args)
                base_stale = plane.snapshot()["stale_replies_dropped"]
                d2, _ = plane.call(
                    "fence", [np.array([10, 20], np.int64)],
                    [np.ones(2, bool)])
                assert d1.tolist() == [6, 7]
                assert d2.tolist() == [15, 25]
                assert plane.snapshot()["stale_replies_dropped"] \
                    >= base_stale + 1
            finally:
                install(None)
        finally:
            drop_udf("fence")

    def test_user_exception_typed_no_respawn_burn(self):
        register_udf("boom", lambda v: 1 // 0, [INT64], INT64)
        try:
            plane = udf_plane()
            base = plane.snapshot()
            with pytest.raises(UdfServerError, match="ZeroDivision"):
                plane.call("boom", [np.array([1], np.int64)],
                           [np.ones(1, bool)])
            snap = plane.snapshot()
            assert snap["respawns"] == base["respawns"]
            assert snap["user_errors"] == base["user_errors"] + 1
        finally:
            drop_udf("boom")

    def test_backpressure_overload_typed(self):
        udf_plane().configure(UdfConfig(max_inflight=1,
                                        queue_timeout_s=0.05,
                                        call_timeout_s=10.0))
        register_udf("slow", lambda v: time.sleep(0.6) or v,
                     [INT64], INT64)
        try:
            plane = udf_plane()
            plane.call("slow", [np.array([0], np.int64)],
                       [np.ones(1, bool)])   # warm spawn outside timing
            errs, oks = [], []

            def one():
                try:
                    plane.call("slow", [np.array([1], np.int64)],
                               [np.ones(1, bool)])
                    oks.append(1)
                except UdfOverloadedError as e:
                    errs.append(e)

            ts = [threading.Thread(target=one) for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert len(oks) == 1 and len(errs) == 1
        finally:
            drop_udf("slow")

    def test_drop_and_reregister(self):
        register_udf("cycle", lambda v: v, [INT64], INT64)
        drop_udf("cycle")
        register_udf("cycle", lambda v: v + 1, [INT64], INT64)
        try:
            d, _ = udf_plane().call("cycle",
                                    [np.array([1], np.int64)],
                                    [np.ones(1, bool)])
            assert d.tolist() == [2]
        finally:
            drop_udf("cycle")


# ---------------------------------------------------------------------------
# wiring: metrics, config, placement routing
# ---------------------------------------------------------------------------

class TestWiring:
    def test_metrics_section(self):
        s = Session()
        m = s.metrics()["udf"]
        for k in ("mode", "generation", "calls", "respawns", "timeouts",
                  "stale_replies_dropped", "registered", "server_alive"):
            assert k in m
        s.close()

    def test_rw_config_udf_section_round_trip(self, tmp_path):
        from risingwave_tpu.common.config import load_config
        p = tmp_path / "rw.toml"
        p.write_text("[udf]\nmode = \"inproc\"\ncall_timeout_s = 1.5\n"
                     "max_retries = 7\n")
        cfg = load_config(str(p))
        assert cfg.udf.mode == "inproc"
        assert cfg.udf.call_timeout_s == 1.5
        assert cfg.udf.max_retries == 7
        with pytest.raises(ValueError):
            load_config(str(p), **{"udf.nonsense": 1})

    def test_session_only_imposes_explicit_udf_config(self):
        plane = udf_plane()
        plane.configure(UdfConfig(call_timeout_s=1.25))
        s = Session()          # no rw_config: must NOT clobber
        assert plane.config.call_timeout_s == 1.25
        s.close()
        from risingwave_tpu.common.config import RwConfig
        rw = RwConfig()
        rw.udf.call_timeout_s = 9.0
        s2 = Session(rw_config=rw)
        assert plane.config.call_timeout_s == 9.0
        s2.close()

    @pytest.mark.slow
    def test_udf_mv_stays_local_with_workers(self):
        """A UDF-projecting MV must build session-local: worker
        processes hold no UDF registrations (ISSUE 15 routing rule)."""
        register_udf("loc_tax", lambda v: v * 2, [INT64], INT64)
        try:
            s = Session(workers=2)
            try:
                s.run_sql("CREATE TABLE wt (k BIGINT PRIMARY KEY, "
                          "v BIGINT)")
                s.run_sql("CREATE MATERIALIZED VIEW wmu AS "
                          "SELECT k, loc_tax(v) AS tv FROM wt")
                assert "wmu" not in s._remote_specs
                assert "wmu" not in s._spanning_specs
                s.run_sql("INSERT INTO wt VALUES (1, 5)")
                s.flush()
                assert s.mv_rows("wmu") == [(1, 10)]
            finally:
                s.close()
        finally:
            drop_udf("loc_tax")


# ---------------------------------------------------------------------------
# slow tier: chaos scenario + sweep + soak + ctl serve
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestUdfChaosSlow:
    def test_udf_link_chaos_audited_and_replayable(self, tmp_path):
        from risingwave_tpu.sim import run_udf_chaos
        r1 = run_udf_chaos(seed=13, data_dir=str(tmp_path / "a"))
        assert all(r1["audit"].values())
        assert r1["timeouts"] >= 1          # drops actually struck
        assert r1["spawns"] >= 2            # kill + respawn happened
        r2 = run_udf_chaos(seed=13, data_dir=str(tmp_path / "b"))
        assert r1["trace"] == r2["trace"], "seeded replay diverged"

    def test_kill_mid_epoch_pipeline_depth2_cosched_green(self, tmp_path):
        """THE acceptance run: UDF server killed mid-run while a
        co-scheduled fused group ticks under pipeline_depth=2 — the
        epoch loop keeps ticking, results land bit-exact vs control,
        ConsistencyAuditor green."""
        from risingwave_tpu.sim import run_udf_chaos
        r = run_udf_chaos(seed=10, data_dir=str(tmp_path),
                          pipeline_depth=2, coschedule=True)
        assert all(r["audit"].values())
        assert r["cosched_groups"] >= 1, \
            "co-scheduled group never engaged — the run proved nothing"
        assert r["pipeline_depth"] == 2
        assert r["spawns"] >= 2

    def test_crash_point_sweep_covers_udf_sites(self, tmp_path):
        from risingwave_tpu.sim import crash_point_sweep
        res = crash_point_sweep(
            str(tmp_path), sites=["udf.spawn", "udf.call", "udf.reply"])
        for site, st in res.items():
            assert st["hit"], f"{site} never fired in the sweep workload"
            assert st.get("audit") == "ok", f"{site}: {st}"

    def test_ctl_udf_serve_external_attach(self, tmp_path):
        """`ctl udf serve` + [udf] addr: sessions attach to an
        operator-managed persistent server instead of auto-spawning."""
        import subprocess
        import sys
        proc = subprocess.Popen(
            [sys.executable, "-m", "risingwave_tpu", "ctl", "udf",
             "serve", "--port", "0"],
            stdout=subprocess.PIPE,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        try:
            line = proc.stdout.readline().decode()
            assert line.startswith("UDF_READY"), line
            port = int(line.split()[1])
            udf_plane().configure(
                UdfConfig(addr=f"127.0.0.1:{port}"))
            udf_plane().shutdown_server()
            register_udf("ext_tax", lambda v: v + 100, [INT64], INT64)
            try:
                d, _ = udf_plane().call(
                    "ext_tax", [np.array([1], np.int64)],
                    [np.ones(1, bool)])
                assert d.tolist() == [101]
                assert udf_plane().server_pid() is None  # not ours
            finally:
                drop_udf("ext_tax")
        finally:
            proc.kill()
            proc.wait()
            udf_plane().shutdown_server()

    def test_soak_seed_record_folds_into_bench_trend(self, tmp_path):
        """The ~60s soak composition (satellite): RPC chaos + UDF-server
        kills + serving readers live together, auditor green, and the
        emitted record is schema-stable + `ctl bench trend`-foldable."""
        from risingwave_tpu.common.profiling import (
            bench_trend, load_bench_history,
        )
        from risingwave_tpu.sim import run_udf_soak
        rec = run_udf_soak(duration_s=40.0, seed=5,
                           data_dir=str(tmp_path / "soak"),
                           kill_every=5, min_ticks=10)
        assert rec["audit_ok"] == 1
        assert rec["reader_errors"] == 0
        assert rec["udf_spawns"] >= 2          # kills were absorbed
        assert rec["chaos_injections"] >= 1    # rpc chaos actually ran
        assert rec["reader_queries"] > 0
        # schema-stable: the exact field set bench trend folds
        assert sorted(rec) == sorted([
            "seed", "duration_s", "ticks", "rows_per_sec", "udf_calls",
            "udf_spawns", "udf_respawns", "udf_timeouts",
            "udf_stale_drops", "reader_queries", "reader_errors",
            "chaos_injections", "mv_rows", "audit_ok"])
        bench_dir = tmp_path / "bench"
        bench_dir.mkdir()
        with open(bench_dir / "BENCH_partial.json", "w") as f:
            f.write(json.dumps({"phase": "udf_soak", "record": rec})
                    + "\n")
        hist = load_bench_history(str(bench_dir))
        assert hist and hist[-1]["label"] == "partial:udf_soak"
        trend = bench_trend(hist)
        assert "rows_per_sec" in trend["fields"]
