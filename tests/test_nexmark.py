"""NEXmark generator sanity + q1/q5-core pipelines end-to-end."""

import asyncio

import pytest

import numpy as np

from risingwave_tpu.common import INT64, TIMESTAMP, Schema, chunk_to_rows
from risingwave_tpu.connector import (
    BID_SCHEMA, NexmarkConfig, NexmarkGenerator,
)
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import count_star
from risingwave_tpu.stream import (
    Barrier, HashAggExecutor, MaterializeExecutor, MockSource, ProjectExecutor,
)
from risingwave_tpu.storage import MemoryStateStore, StateTable


def test_bid_chunk_shape_and_monotonic_time():
    gen = NexmarkGenerator(NexmarkConfig(chunk_capacity=256))
    c1 = gen.next_bid_chunk()
    c2 = gen.next_bid_chunk()
    rows1 = chunk_to_rows(c1, BID_SCHEMA)
    rows2 = chunk_to_rows(c2, BID_SCHEMA)
    assert len(rows1) == 256 and len(rows2) == 256
    ts1 = [r[5] for r in rows1]
    ts2 = [r[5] for r in rows2]
    assert ts1 == sorted(ts1) and ts1[-1] <= ts2[0]
    channels = {r[3] for r in rows1}
    assert channels <= {"Google", "Facebook", "Baidu", "Apple"}
    # hot-auction skew: top auction takes a large share
    auctions = np.array([r[0] for r in rows1])
    top_share = np.bincount(auctions - auctions.min()).max() / len(auctions)
    assert top_share > 0.3


def test_q1_style_projection():
    # q1: SELECT auction, bidder, 0.908 * price, date_time FROM bid
    gen = NexmarkGenerator(NexmarkConfig(chunk_capacity=128))
    chunk = gen.next_bid_chunk()
    src = MockSource(BID_SCHEMA, [Barrier.new(1), chunk, Barrier.new(2)])
    from risingwave_tpu.common import FLOAT64
    from risingwave_tpu.expr import cast
    ex = ProjectExecutor(src, [
        col(0, INT64), col(1, INT64),
        cast(col(2, INT64), FLOAT64) * 0.908, col(5, TIMESTAMP),
    ])

    async def drain():
        out = []
        async for m in ex.execute():
            from risingwave_tpu.common import StreamChunk
            if isinstance(m, StreamChunk):
                out.extend(chunk_to_rows(m, ex.schema))
        return out

    rows = asyncio.run(drain())
    src_rows = chunk_to_rows(chunk, BID_SCHEMA)
    assert len(rows) == len(src_rows)
    # TPU f64 is emulated (ulp-level rounding differs from host), so approx.
    assert rows[0][2] == pytest.approx(src_rows[0][2] * 0.908, rel=1e-12)


def test_q5_core_counts_match_numpy():
    """Windowed per-auction counts == offline numpy groupby."""
    gen = NexmarkGenerator(NexmarkConfig(chunk_capacity=256))
    chunks = [gen.next_bid_chunk() for _ in range(4)]
    window = 10_000_000
    src = MockSource(BID_SCHEMA, [Barrier.new(1), *chunks, Barrier.new(2, checkpoint=True)])
    proj = ProjectExecutor(src, [
        call("tumble_start", col(5, TIMESTAMP), Literal(window, INT64)),
        col(0, INT64),
    ], names=("window_start", "auction"))
    agg = HashAggExecutor(proj, [0, 1], [count_star()], table_capacity=1 << 12)
    store = MemoryStateStore()
    mv = MaterializeExecutor(agg, StateTable(store, 1, agg.schema, [0, 1]))

    async def drain():
        async for _ in mv.execute():
            pass

    asyncio.run(drain())
    got = {(r[0], r[1]): r[2] for r in mv.rows()}

    expected: dict = {}
    for c in chunks:
        for r in chunk_to_rows(c, BID_SCHEMA):
            key = ((r[5] // window) * window, r[0])
            expected[key] = expected.get(key, 0) + 1
    assert got == expected
