"""The finished sharded-fused ladder (PR 13): mesh-sharded q8 session
windows and TPC-H q3 (ops/fused_sharded.sharded_session_epoch /
sharded_q3_epoch + parallel/fused.ShardedFusedSession / ShardedFusedQ3),
the K-jobs × S-shards co-scheduled group (fusion surface 6:
build_sharded_group_epoch + ShardedCoGroup), and the generic
sharded-fused equi-join (ShardedHashJoin.step_epoch). Each surface is
pinned the same three ways the q5/q7 sharded surfaces were: bit-exact
against its solo fused counterpart at shard counts {1, 4, 8} (flush
churn and retraction pairs included), exactly ONE dispatch per epoch
independent of k / shard count / job count, and checkpoint export →
kill → import re-sharding onto a different mesh size (8→4) with
identical continuations. Heavy K×S parity/recovery cases are
slow-marked and run in scripts/check.sh's fused subset (tier-2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import INT64, TIMESTAMP, chunk_to_rows
from risingwave_tpu.common.chunk import OP_DELETE, OP_INSERT
from risingwave_tpu.common.dispatch_count import count_dispatches
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.connector import NexmarkConfig
from risingwave_tpu.connector.nexmark import DeviceBidGenerator
from risingwave_tpu.connector.tpch import (
    DeviceQ3Generator, Q3_CUTOFF_DAYS, TpchQ3Config,
)
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import count_star
from risingwave_tpu.ops.fused_epoch import (
    fused_source_agg_epoch, fused_source_q3_epoch,
    fused_source_session_epoch,
)
from risingwave_tpu.ops.grouped_agg import AggCore
from risingwave_tpu.ops.session_window import SessionWindowCore
from risingwave_tpu.ops.stream_q3 import Q3Core
from risingwave_tpu.parallel.fused import (
    ShardedCoGroup, ShardedFusedAgg, ShardedFusedQ3, ShardedFusedSession,
    load_shard_states, reshard_q3_payloads, reshard_session_payloads,
)
from risingwave_tpu.parallel.sharded_agg import make_mesh
from risingwave_tpu.stream.coschedule import FusedJobSpec

CAP = 256
N_DEV = 8
GAP = 100_000
TIME_BASE = 1_600_000_000_000_000

Q8_EPOCH_FN = "sharded_session_epoch.<locals>.epoch"
Q3_EPOCH_FN = "sharded_q3_epoch.<locals>.epoch"
GROUP_EPOCH_FN = \
    "build_sharded_group_epoch.<locals>.sharded_coscheduled_epoch"
EQUI_EPOCH_FN = "sharded_equi_join_epoch.<locals>.epoch"


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= N_DEV, "conftest must force 8 CPU devices"
    return make_mesh(N_DEV)


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# q8 sharded: parity, dispatch count, checkpoint/re-shard
# ---------------------------------------------------------------------------


def _q8_parts(capacity=1 << 12, closed=1 << 13):
    exprs = [col(1, INT64), col(5, TIMESTAMP)]     # bidder, date_time
    schema = Schema((Field("bidder", INT64), Field("ts", TIMESTAMP)))
    core = SessionWindowCore(schema, key_col=0, ts_col=1, gap_us=GAP,
                             capacity=capacity, closed_capacity=closed)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    return exprs, core, gen


def _closed_rows(chunks):
    out = []
    for ch in chunks:
        vis = np.asarray(ch.vis)
        cols = [np.asarray(c.data) for c in ch.columns]
        for r in np.nonzero(vis)[0]:
            out.append(tuple(int(c[r]) for c in cols))
    return sorted(out)


def _solo_closed(snap, packed):
    n = int(packed[0])
    ck, cs, ce, cn = (np.asarray(a) for a in snap)
    return sorted((int(ck[j]), int(cs[j]), int(ce[j]), int(cn[j]))
                  for j in range(n))


def _open_state(payloads):
    """{key: (start, last, cnt)} over exported per-shard payloads."""
    out = {}
    for p in payloads:
        occ = np.asarray(p["table_occupied"])
        live = occ & (np.asarray(p["sess_start"]) >= 0)
        kd = np.asarray(p["table_key_data"][0])
        for s in np.nonzero(live)[0]:
            out[int(kd[s])] = (int(p["sess_start"][s]),
                               int(p["last_ts"][s]), int(p["count"][s]))
    return out


def _solo_open(state):
    host = jax.device_get(state)
    occ = np.asarray(host.table.occupied)
    live = occ & (np.asarray(host.sess_start) >= 0)
    kd = np.asarray(host.table.key_data[0])
    return {int(kd[s]): (int(host.sess_start[s]), int(host.last_ts[s]),
                         int(host.count[s]))
            for s in np.nonzero(live)[0]}


@pytest.mark.parametrize("n_shards,k", [
    (8, 8),
    pytest.param(4, 6, marks=pytest.mark.slow),   # tier-2 (wall budget)
    pytest.param(1, 4, marks=pytest.mark.slow),
])
def test_sharded_session_bit_exact_vs_solo(mesh8, n_shards, k):
    """Closed-session multisets AND per-key open state equal the solo
    fused q8 epoch's over two epochs — epoch 1 with a non-closing
    watermark (cross-epoch session continuation), epoch 2 with a
    closing one — for full/partial/1-shard meshes and k not divisible
    by the shard count."""
    exprs, core, gen = _q8_parts()
    mesh = mesh8 if n_shards == N_DEV else make_mesh(n_shards)
    sf = ShardedFusedSession(mesh, core, gen.chunk_fn(), exprs, CAP)
    solo = fused_source_session_epoch(gen.chunk_fn(), exprs, core, CAP,
                                      donate=False)
    st = core.init_state()
    start = 0
    watermarks = (0, TIME_BASE + 2 * k * CAP * 1_000)
    saw_closed = False
    for epoch, wm in enumerate(watermarks):
        key = jax.random.fold_in(jax.random.PRNGKey(7), epoch)
        sf.run_epoch(start, key, k, wm)
        got = _closed_rows(sf.flush(out_capacity=CAP))
        st, snap, packed = solo(st, jnp.int64(start), key, k,
                                jnp.int64(wm))
        start += k * CAP
        assert not any(int(x) for x in jax.device_get(packed)[1:])
        assert got == _solo_closed(snap, packed)
        saw_closed |= bool(got)
    assert saw_closed
    got_open = _open_state(sf.export_host())
    want_open = _solo_open(st)
    assert got_open == want_open


def test_sharded_session_epoch_dispatch_count():
    """Exactly ONE dispatch per sharded q8 epoch, independent of k and
    shard count — the per-epoch non-gather total must not move."""
    with count_dispatches() as c:
        exprs, core, gen = _q8_parts()
        sf = ShardedFusedSession(make_mesh(4), core, gen.chunk_fn(),
                                 exprs, CAP, recv_width=4)
        key = jax.random.PRNGKey(17)
        sf.run_epoch(0, key, 4, 0)
        sf.flush(out_capacity=CAP)
        c.reset()
        sf.run_epoch(4 * CAP, key, 4, 0)
        assert c.counts[Q8_EPOCH_FN] == 1
        sf.flush(out_capacity=CAP)
        n4 = sum(n for name, n in c.counts.items()
                 if "gather" not in name)
        c.reset()
        sf.run_epoch(8 * CAP, key, 8, 0)
        assert c.counts[Q8_EPOCH_FN] == 1
        sf.flush(out_capacity=CAP)
        n8 = sum(n for name, n in c.counts.items()
                 if "gather" not in name)
        assert n4 == n8


@pytest.mark.slow
def test_sharded_session_checkpoint_cycle_and_reshard(mesh8):
    """export_host → kill → import_host (8→8) AND re-shard onto a
    4-shard mesh (reshard_session_payloads replays the vnode mapping
    over every open session's key): both continuations emit the solo
    path's exact closed-session multiset."""
    exprs, core, gen = _q8_parts()
    sf = ShardedFusedSession(mesh8, core, gen.chunk_fn(), exprs, CAP)
    key = jax.random.PRNGKey(5)
    sf.run_epoch(0, key, 8, 0)
    sf.flush(out_capacity=CAP)
    payloads = sf.export_host()

    solo = fused_source_session_epoch(gen.chunk_fn(), exprs, core, CAP,
                                      donate=False)
    st = solo(core.init_state(), jnp.int64(0), key, 8, jnp.int64(0))[0]
    key2 = jax.random.fold_in(jax.random.PRNGKey(5), 1)
    wm2 = TIME_BASE + 16 * CAP * 1_000
    st, snap, packed = solo(st, jnp.int64(8 * CAP), key2, 8,
                            jnp.int64(wm2))
    want = _solo_closed(snap, packed)
    assert want

    # same-size import cycle is bit-exact state-wise
    sf2 = ShardedFusedSession(mesh8, core, gen.chunk_fn(), exprs, CAP)
    sf2.import_host(payloads)
    _assert_tree_equal(sf.stacked, sf2.stacked)
    sf2.run_epoch(8 * CAP, key2, 8, wm2)
    assert _closed_rows(sf2.flush(out_capacity=CAP)) == want

    # shrink to 4 shards by vnode replay: identical emissions
    states4 = reshard_session_payloads(core, payloads, 4)
    sf4 = ShardedFusedSession(make_mesh(4), core, gen.chunk_fn(), exprs,
                              CAP, states=states4)
    assert _open_state(sf4.export_host()) == _open_state(payloads)
    sf4.run_epoch(8 * CAP, key2, 8, wm2)
    assert _closed_rows(sf4.flush(out_capacity=CAP)) == want


# ---------------------------------------------------------------------------
# q3 sharded: parity (incl. retraction churn), dispatch count, re-shard
# ---------------------------------------------------------------------------


def _q3_parts(orders=1 << 11, agg=1 << 11):
    gen = DeviceQ3Generator(TpchQ3Config(chunk_capacity=CAP))
    core = Q3Core(Q3_CUTOFF_DAYS, orders_capacity=orders,
                  agg_capacity=agg)
    return gen, core


@pytest.mark.parametrize("n_shards", [
    8,
    pytest.param(4, marks=pytest.mark.slow),      # tier-2 (wall budget)
    pytest.param(1, marks=pytest.mark.slow),
])
def test_sharded_q3_bit_exact_vs_solo(mesh8, n_shards):
    """The global top-10 churn chunk — deletes AND inserts, epoch 2
    retracting epoch 1's departed rows — is BIT-IDENTICAL to the solo
    fused q3 epoch's, and the replicated emitted buffer matches on
    every shard."""
    gen, core = _q3_parts()
    mesh = mesh8 if n_shards == N_DEV else make_mesh(n_shards)
    sf = ShardedFusedQ3(mesh, core, gen.chunk_fn(), CAP)
    solo = fused_source_q3_epoch(gen.chunk_fn(), core, CAP, donate=False)
    st = core.init_state()
    start = 0
    for epoch in range(2):
        key = jax.random.fold_in(jax.random.PRNGKey(3), epoch)
        sf.run_epoch(start, key, 8)
        got = sf.flush()
        st, out, packed = solo(st, jnp.int64(start), key, 8)
        start += 8 * CAP
        assert not any(int(x) for x in jax.device_get(packed)[1:])
        assert len(got) == 1
        _assert_tree_equal(got[0], out)
        ops = np.asarray(out.ops)[np.asarray(out.vis)]
        if epoch == 0:
            assert (ops == OP_INSERT).all() and len(ops) == 10
        else:
            # the top-n output carries retraction pairs even though
            # both inputs are append-only
            assert (ops == OP_DELETE).any() and (ops == OP_INSERT).any()
    # the emitted top-n buffer is replicated identically across shards
    solo_h = jax.device_get(st)
    for p in sf.export_host():
        np.testing.assert_array_equal(np.asarray(p["emitted_key"]),
                                      np.asarray(solo_h.emitted_key))
        np.testing.assert_array_equal(np.asarray(p["emitted_valid"]),
                                      np.asarray(solo_h.emitted_valid))


def test_sharded_q3_epoch_dispatch_count():
    with count_dispatches() as c:
        gen, core = _q3_parts()
        sf = ShardedFusedQ3(make_mesh(4), core, gen.chunk_fn(), CAP,
                            recv_width=4)
        key = jax.random.PRNGKey(19)
        sf.run_epoch(0, key, 4)
        sf.flush()
        c.reset()
        sf.run_epoch(4 * CAP, key, 4)
        assert c.counts[Q3_EPOCH_FN] == 1
        sf.flush()
        n4 = c.total
        c.reset()
        sf.run_epoch(8 * CAP, key, 8)
        assert c.counts[Q3_EPOCH_FN] == 1
        sf.flush()
        assert c.total == n4     # per-epoch dispatches independent of k


@pytest.mark.slow
def test_sharded_q3_checkpoint_cycle_and_reshard(mesh8):
    """export_host → kill → import (8→8) and vnode-replay re-shard onto
    4 shards (orders + revenue groups follow the orderkey hash, the
    replicated emitted buffer copies everywhere): both continuations
    produce the solo path's exact churn."""
    gen, core = _q3_parts()
    sf = ShardedFusedQ3(mesh8, core, gen.chunk_fn(), CAP)
    key = jax.random.PRNGKey(2)
    sf.run_epoch(0, key, 8)
    sf.flush()
    payloads = sf.export_host()

    solo = fused_source_q3_epoch(gen.chunk_fn(), core, CAP, donate=False)
    st = core.init_state()
    st, _, _ = solo(st, jnp.int64(0), key, 8)
    key2 = jax.random.fold_in(jax.random.PRNGKey(2), 1)
    st, want_out, want_packed = solo(st, jnp.int64(8 * CAP), key2, 8)

    sf2 = ShardedFusedQ3(mesh8, core, gen.chunk_fn(), CAP)
    sf2.import_host(payloads)
    _assert_tree_equal(sf.stacked, sf2.stacked)
    sf2.run_epoch(8 * CAP, key2, 8)
    _assert_tree_equal(sf2.flush()[0], want_out)

    states4 = reshard_q3_payloads(core, payloads, 4)
    sf4 = ShardedFusedQ3(make_mesh(4), core, gen.chunk_fn(), CAP,
                         states=states4)
    sf4.run_epoch(8 * CAP, key2, 8)
    _assert_tree_equal(sf4.flush()[0], want_out)


# ---------------------------------------------------------------------------
# K×S co-scheduled group: parity, dispatch count, checkpoint/re-shard
# ---------------------------------------------------------------------------


def _group_parts(table_capacity=1 << 12):
    exprs = [
        call("tumble_start", col(5, TIMESTAMP), Literal(1_000_000, INT64)),
        col(0, INT64),
    ]
    core = AggCore([INT64, INT64], [0, 1], [count_star()],
                   table_capacity, CAP)
    gen = DeviceBidGenerator(NexmarkConfig(chunk_capacity=CAP))
    spec = FusedJobSpec("agg", ("ladder-test",), gen.chunk_fn(),
                        tuple(exprs), core, CAP, seed=0)
    return exprs, core, gen, spec


def _merged(states):
    out = {}
    for st in states:
        h = jax.device_get(st)
        occ = np.asarray(h.table.occupied)
        live = np.asarray(h.lanes[0]) > 0
        kd = [np.asarray(x) for x in h.table.key_data]
        km = [np.asarray(x) for x in h.table.key_mask]
        lanes = [np.asarray(x) for x in h.lanes]
        for s in np.nonzero(occ & live)[0]:
            key = tuple(kd[c][s].item() if km[c][s] else None
                        for c in range(len(kd)))
            out[key] = tuple(l[s].item() for l in lanes)
    return out


def _rows(chunks, schema):
    out = []
    for c in chunks:
        out.extend(chunk_to_rows(c, schema, with_ops=True, physical=True))
    return sorted(out)


@pytest.mark.parametrize("n_jobs,n_shards", [(1, 8), (3, 4), (3, 8)])
@pytest.mark.slow
def test_sharded_group_bit_exact_vs_single_job_sharded(mesh8, n_jobs,
                                                       n_shards):
    """Every (job, shard) slice of the K×S group — per-group values AND
    flush churn (U-/U+ retraction pairs across epochs) — equals what a
    single-job ShardedFusedAgg produces for that job's seed/cursor,
    which test_fused_sharded.py pins bit-exact against the solo fused
    path: the composition inherits both anchors."""
    exprs, core, gen, spec = _group_parts()
    mesh = mesh8 if n_shards == N_DEV else make_mesh(n_shards)
    group = ShardedCoGroup(mesh, spec)
    for j in range(n_jobs):
        group.add(f"mv{j}", seed=100 + j)
    flush_schema = Schema((Field("ws", INT64), Field("auction", INT64),
                           Field("cnt", INT64)))
    flushes = []
    for _ in range(2):
        group.run_epoch(4)
        flushes.append(group.flush())
    for j in range(n_jobs):
        sf = ShardedFusedAgg(mesh, core, gen.chunk_fn(), exprs, CAP)
        for e in range(2):
            key = jax.random.fold_in(jax.random.PRNGKey(100 + j), e)
            sf.run_epoch(e * 4 * CAP, key, 4)
            want_chunks = sf.flush()
            assert _rows(flushes[e][f"mv{j}"], flush_schema) == \
                _rows(want_chunks, flush_schema)
        assert _merged(group.shard_states_of(f"mv{j}")) == \
            sf.merged_group_values()


def test_sharded_group_one_dispatch_independent_of_k_and_jobs():
    """THE tentpole invariant: K jobs × S shards = exactly ONE dispatch
    per epoch, for K ∈ {1, 4}, and per-epoch dispatch totals that move
    with neither k nor K."""
    with count_dispatches() as c:
        exprs, core, gen, spec = _group_parts()
        mesh = make_mesh(4)
        group = ShardedCoGroup(mesh, spec)
        group.add("mv0", seed=1)
        group.run_epoch(4)
        group.flush()
        c.reset()
        group.run_epoch(4)
        assert c.counts[GROUP_EPOCH_FN] == 1
        assert c.total == 1
        group.flush()
        n1 = sum(n for name, n in c.counts.items()
                 if "gather" not in name)
        for j in range(1, 4):
            group.add(f"mv{j}", seed=1 + j)
        group.run_epoch(4)       # recompile at the new [J]; warm
        group.flush()
        c.reset()
        group.run_epoch(8)       # J and k both changed: still 1
        assert c.counts[GROUP_EPOCH_FN] == 1
        assert c.total == 1
        group.flush()
        n4 = sum(n for name, n in c.counts.items()
                 if "gather" not in name)
        assert n1 == n4


def test_sharded_group_membership_change_between_epoch_and_flush():
    """CREATE/DROP between run_epoch and the next flush: the job axis
    changes shape mid-stream, so the retry flag from the previous epoch
    must not survive the restack (regression: a stale [n, J_old] rovf
    crashed the next probe's vmap)."""
    exprs, core, gen, spec = _group_parts()
    group = ShardedCoGroup(make_mesh(2), spec)
    group.add("mv0", seed=1)
    group.add("mv1", seed=2)
    group.run_epoch(2)
    group.add("mv2", seed=3)         # joins mid-stream, J: 2 -> 3
    outs = group.flush()
    assert set(outs) == {"mv0", "mv1", "mv2"}
    group.run_epoch(2)
    group.remove("mv1")              # leaves mid-stream, J: 3 -> 2
    outs = group.flush()
    assert set(outs) == {"mv0", "mv2"}
    # the latecomer ticked once, the founders twice — cursors say so
    assert group.batch_nos == [2, 1]


@pytest.mark.slow
def test_sharded_group_route_overflow_grows_and_stays_exact():
    """Hot-key skew under a width-1 receive buffer: the group driver
    grows + retries the WHOLE K×S epoch from the untouched pre-epoch
    state and every member stays exact."""
    exprs, core, gen, spec = _group_parts()
    mesh = make_mesh(N_DEV)
    group = ShardedCoGroup(mesh, spec, recv_width=1)
    for j in range(2):
        group.add(f"mv{j}", seed=50 + j)
    group.run_epoch(8)
    group.flush()
    assert group.route_grows > 0 and group.recv_width > 1
    solo = fused_source_agg_epoch(gen.chunk_fn(), exprs, core, CAP,
                                  donate=False)
    for j in range(2):
        st = solo(core.init_state(), jnp.int64(0),
                  jax.random.fold_in(jax.random.PRNGKey(50 + j), 0), 8)
        host = jax.device_get(st)
        want = _merged([host])
        assert _merged(group.shard_states_of(f"mv{j}")) == want


@pytest.mark.slow
def test_sharded_group_checkpoint_cycle_and_reshard(mesh8):
    """Each member job checkpoints through its OWN HashAggExecutor
    persistence engine into its own state table; 'kill'; recover the
    whole group TWICE — onto 8 shards and onto a 4-shard mesh — by
    replaying the vnode mapping per job (load_shard_states). Both
    continuations match the single-job sharded path exactly."""
    from risingwave_tpu.connector import BID_SCHEMA
    from risingwave_tpu.storage.state_store import MemoryStateStore
    from risingwave_tpu.storage.state_table import StateTable
    from risingwave_tpu.stream import HashAggExecutor, ProjectExecutor
    from risingwave_tpu.stream.hash_agg import agg_state_schema
    from risingwave_tpu.stream.source import MockSource

    exprs, core, gen, spec = _group_parts()
    n_jobs = 2
    store = MemoryStateStore()
    engines = {}
    for j in range(n_jobs):
        proj = ProjectExecutor(MockSource(BID_SCHEMA, []), exprs,
                               names=("ws", "auction"))
        st_table = StateTable(
            store, 10 + j,
            agg_state_schema([proj.schema[0], proj.schema[1]],
                             core.agg_calls), [0, 1])
        eng = HashAggExecutor(proj, [0, 1], list(core.agg_calls),
                              state_table=None, table_capacity=1 << 12,
                              out_capacity=CAP)
        eng.state_table = st_table
        engines[f"mv{j}"] = eng

    group = ShardedCoGroup(mesh8, spec)
    for j in range(n_jobs):
        group.add(f"mv{j}", seed=100 + j)
    group.run_epoch(8)
    group.flush()
    group.checkpoint(engines, epoch=2)
    store.commit(2)
    committed = {f"mv{j}": _merged(group.shard_states_of(f"mv{j}"))
                 for j in range(n_jobs)}

    # expected continuation per job: the single-job sharded driver
    want = {}
    for j in range(n_jobs):
        sf = ShardedFusedAgg(mesh8, core, gen.chunk_fn(), exprs, CAP)
        for e in range(2):
            key = jax.random.fold_in(jax.random.PRNGKey(100 + j), e)
            sf.run_epoch(e * 8 * CAP, key, 8)
            sf.flush()
        want[f"mv{j}"] = sf.merged_group_values()

    for new_n in (8, 4):
        mesh = mesh8 if new_n == N_DEV else make_mesh(new_n)
        g2 = ShardedCoGroup(mesh, spec)
        for j in range(n_jobs):
            rows = list(engines[f"mv{j}"].state_table.scan_all())
            states = load_shard_states(core, rows, new_n)
            g2.add(f"mv{j}", shard_states=states, start=8 * CAP,
                   seed=100 + j, batch_no=1)
            assert _merged(g2.shard_states_of(f"mv{j}")) == \
                committed[f"mv{j}"]
        g2.run_epoch(8)
        g2.flush()
        for j in range(n_jobs):
            assert _merged(g2.shard_states_of(f"mv{j}")) == want[f"mv{j}"]


# ---------------------------------------------------------------------------
# generic sharded-fused equi-join: epoch == per-chunk steps, 1 dispatch
# ---------------------------------------------------------------------------


def _join_parts(n_dev):
    from risingwave_tpu.common.chunk import physical_chunk
    from risingwave_tpu.ops.join_state import JoinType
    from risingwave_tpu.parallel.sharded_join import ShardedHashJoin

    ls = Schema((Field("k", INT64), Field("v", INT64)))
    rs = Schema((Field("k", INT64), Field("w", INT64)))
    join = ShardedHashJoin(make_mesh(n_dev), ls, rs, [0], [0],
                           JoinType.INNER, key_capacity=1 << 8,
                           bucket_width=8)

    def batch(lo, side_schema):
        return join.batch_chunks([
            physical_chunk(side_schema,
                           [(lo + 8 * s + r, lo + r) for r in range(8)],
                           8)
            for s in range(n_dev)])

    return ls, rs, join, batch


@pytest.mark.slow
def test_equi_join_epoch_matches_per_chunk_steps():
    """step_epoch(side, [c1, c2, c3]) — one dispatch — emits exactly
    what three sequential step() calls emit, state included."""
    ls, rs, join_a, batch_a = _join_parts(4)
    _, _, join_b, batch_b = _join_parts(4)
    join_a.step("right", batch_a(0, rs))
    join_b.step("right", batch_b(0, rs))
    outs_a = join_a.step_epoch(
        "left", [batch_a(0, ls), batch_a(4, ls), batch_a(100, ls)])
    outs_b = [join_b.step("left", batch_b(0, ls)),
              join_b.step("left", batch_b(4, ls)),
              join_b.step("left", batch_b(100, ls))]
    rows_a = sorted(r for big in outs_a for r in join_a.collect_rows(big))
    rows_b = sorted(r for big in outs_b for r in join_b.collect_rows(big))
    assert rows_a == rows_b and rows_a
    _assert_tree_equal(join_a.state, join_b.state)


@pytest.mark.slow
def test_equi_join_epoch_dispatch_count_and_grow_retry():
    """k chunks = ONE dispatch regardless of k; a lane overflow grows
    geometry and replays the whole batch exactly. Slow-marked per the
    tier-1 wall budget (several shard_map compiles); bench --smoke
    keeps a tier-2 1-dispatch assert on this surface too."""
    with count_dispatches() as c:
        ls, rs, join, batch = _join_parts(4)
        join.step_epoch("right", [batch(0, rs)])
        c.reset()
        join.step_epoch("left", [batch(0, ls), batch(4, ls)])
        assert c.counts[EQUI_EPOCH_FN] == 1
        c.reset()
        join.step_epoch("left", [batch(8, ls), batch(12, ls),
                                 batch(16, ls), batch(20, ls)])
        assert c.counts[EQUI_EPOCH_FN] == 1

    # grow-retry: a build side wider than the bucket width must grow
    # and still join exactly (hot single key on every row)
    from risingwave_tpu.common.chunk import physical_chunk
    from risingwave_tpu.ops.join_state import JoinType
    from risingwave_tpu.parallel.sharded_join import ShardedHashJoin
    join2 = ShardedHashJoin(make_mesh(2), ls, rs, [0], [0],
                            JoinType.INNER, key_capacity=1 << 4,
                            bucket_width=2)
    W0 = join2.core.W
    hot = join2.batch_chunks([
        physical_chunk(rs, [(7, 8 * s + r) for r in range(8)], 8)
        for s in range(2)])
    join2.step_epoch("right", [hot])
    assert join2.core.W > W0      # geometry grew, batch replayed
    probe = join2.batch_chunks([
        physical_chunk(ls, [(7, 1)], 1) for _ in range(2)])
    out = join2.step_epoch("left", [probe])[0]
    rows = join2.collect_rows(out)
    # both shards' build chunks carried the hot key → 16 resident build
    # rows, probed once per source shard
    assert len(rows) == 32


# ---------------------------------------------------------------------------
# Session integration: K signature-equal MVs share ONE K×S group
# ---------------------------------------------------------------------------

SRC_SQL = """CREATE SOURCE bid (auction BIGINT, bidder BIGINT,
    price BIGINT, channel VARCHAR, url VARCHAR, date_time TIMESTAMP,
    extra VARCHAR) WITH (connector = 'nexmark', nexmark_table = 'bid')"""
MV_SQL = ("CREATE MATERIALIZED VIEW {n} AS SELECT auction, count(*) AS c "
          "FROM bid GROUP BY auction")


def _session(tmp_path=None, mesh_n=0, coschedule=True, **kw):
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend.build import BuildConfig
    return Session(
        config=BuildConfig(coschedule=coschedule,
                           mesh=make_mesh(mesh_n) if mesh_n else None,
                           agg_table_capacity=1 << 12),
        source_chunk_capacity=CAP,
        data_dir=str(tmp_path) if tmp_path else None, **kw)


@pytest.mark.slow
def test_session_two_mvs_share_one_group(tmp_path):
    """Two signature-equal MVs on a mesh session land in the SAME K×S
    group (one dispatch per tick for both), their contents match the
    mesh-less co-scheduled session's, the live per_epoch invariant
    reads 1.0, and recovery re-shards the whole group onto a smaller
    mesh with both MVs resuming deterministically."""
    from risingwave_tpu.common.profiling import GLOBAL_PROFILER
    GLOBAL_PROFILER.reset()     # per_epoch reads the process-global
    s = _session(tmp_path, mesh_n=8, checkpoint_frequency=2)
    s.run_sql(SRC_SQL)
    s.run_sql(MV_SQL.format(n="m0"))
    s.run_sql(MV_SQL.format(n="m1"))
    m = s.metrics()["shardfused"]
    assert m["m0"]["group_jobs"] == 2 and m["m1"]["group_jobs"] == 2
    assert m["m0"]["shards"] == 8
    for _ in range(3):
        s.tick()
    got0 = sorted(s.run_sql("SELECT auction, c FROM m0"))
    got1 = sorted(s.run_sql("SELECT auction, c FROM m1"))
    md = s.metrics()["dispatch"]
    qn = GROUP_EPOCH_FN
    assert md["per_epoch"][qn] == 1.0, md["per_epoch"]
    s.close()

    c = _session(mesh_n=0)
    try:
        c.run_sql(SRC_SQL)
        c.run_sql(MV_SQL.format(n="m0"))
        for _ in range(3):
            c.tick()
        want = sorted(c.run_sql("SELECT auction, c FROM m0"))
    finally:
        c.close()
    # same seed + same device stream per job: both MVs equal the
    # co-scheduled session's MV exactly
    assert got0 == want and got1 == want and len(want) > 10

    # reopen on a SMALLER mesh: the whole 2-job group re-shards
    s2 = _session(tmp_path, mesh_n=4, checkpoint_frequency=2)
    try:
        m2 = s2.metrics()["shardfused"]
        assert m2["m0"]["shards"] == 4 and m2["m0"]["group_jobs"] == 2
        assert sorted(s2.run_sql("SELECT auction, c FROM m0")) == got0
        assert sorted(s2.run_sql("SELECT auction, c FROM m1")) == got1
        base = sum(v for _, v in got0)
        for _ in range(2):
            s2.tick()
        assert s2.run_sql("SELECT sum(c) FROM m0") == \
            [(base + 2 * CAP,)]
    finally:
        s2.close()


@pytest.mark.slow
def test_session_drop_one_group_member_keeps_the_other():
    """DROP of one group member keeps the survivor ticking (job-axis
    restack), and the dropped job's epochs retire into the dispatch
    per_epoch ratio instead of skewing it."""
    from risingwave_tpu.common.profiling import GLOBAL_PROFILER
    GLOBAL_PROFILER.reset()     # per_epoch reads the process-global
    s = _session(mesh_n=4)
    try:
        s.run_sql(SRC_SQL)
        s.run_sql(MV_SQL.format(n="m0"))
        s.run_sql(MV_SQL.format(n="m1"))
        s.tick()
        s.run_sql("DROP MATERIALIZED VIEW m1")
        m = s.metrics()["shardfused"]
        assert set(m) == {"m0"} and m["m0"]["group_jobs"] == 1
        s.tick()
        s.tick()
        assert s.metrics()["shardfused"]["m0"]["epochs_run"] >= 3
        md = s.metrics()["dispatch"]
        assert md["per_epoch"][GROUP_EPOCH_FN] == 1.0
    finally:
        s.close()
