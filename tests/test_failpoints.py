"""Storage failpoints (coverage #46/#87): injected IO faults during the
checkpoint write path must never corrupt the durable state — a failed
commit is simply absent after recovery, and the session can retry."""

import pytest

from risingwave_tpu.common.failpoint import failpoints
from risingwave_tpu.frontend import Session


class TestCheckpointFailpoints:
    @pytest.mark.parametrize("site", [
        "checkpoint.segment.write",
        "checkpoint.segment.write.partial",   # torn segment on disk
        "checkpoint.manifest.write",
        "checkpoint.manifest.rename",         # torn manifest tmp on disk
    ])
    def test_io_fault_is_atomic(self, tmp_path, site):
        d = str(tmp_path / f"db_{site.replace('.', '_')}")
        s = Session(data_dir=d)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1, 10)")
        s.flush()                                  # durable baseline

        s.run_sql("INSERT INTO t VALUES (2, 20)")
        with failpoints(**{site: OSError}):
            with pytest.raises(Exception):
                s.flush()                          # fault mid-commit

        # recovery from disk: only the pre-fault state is visible
        s2 = Session(data_dir=d)
        assert s2.run_sql("SELECT k, v FROM t") == [(1, 10)]

        # the recovered session can write and checkpoint normally
        s2.run_sql("INSERT INTO t VALUES (3, 30)")
        s2.flush()
        s3 = Session(data_dir=d)
        assert sorted(s3.run_sql("SELECT k, v FROM t")) == [(1, 10), (3, 30)]

    def test_transient_fault_then_retry_in_process(self, tmp_path):
        """'once' faults clear after firing: the same session retries the
        commit and succeeds."""
        d = str(tmp_path / "db_retry")
        s = Session(data_dir=d)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
        s.run_sql("INSERT INTO t VALUES (1)")
        from risingwave_tpu.common.failpoint import arm, disarm
        arm("checkpoint.segment.write", OSError, once=True)
        try:
            with pytest.raises(Exception):
                s.flush()
        finally:
            disarm()
        s.flush()                                  # retry succeeds
        s2 = Session(data_dir=d)
        assert s2.run_sql("SELECT k FROM t") == [(1,)]
