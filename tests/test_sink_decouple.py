"""Sink delivery decoupled from the barrier path (ISSUE 3 acceptance): a
sink whose backend is down for N consecutive epochs no longer blocks
barrier commit — co-resident MVs keep checkpointing, the sink job reports
DEGRADED in metrics, and once the backend returns every logged row is
delivered exactly once (failpoint-driven; reference: sink decouple via
log store, src/stream/src/common/log_store/mod.rs)."""

import json

import pytest

from risingwave_tpu.common.config import FaultConfig
from risingwave_tpu.common.failpoint import arm, disarm, failpoints
from risingwave_tpu.frontend import Session


#: fast-failing delivery so degraded epochs cost milliseconds
_FC = FaultConfig(sink_retry_attempts=2, sink_retry_base_ms=0.5,
                  sink_retry_deadline_ms=50.0, sink_degrade_after=2)


def _mk(tmp_path, **kw):
    kw.setdefault("fault_config", _FC)
    kw.setdefault("checkpoint_frequency", 2)
    return Session(data_dir=str(tmp_path / "db"), **kw)


def _sink_rows(path):
    with open(path, "r", encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


class TestSinkDecouple:
    def test_down_backend_degrades_not_stalls(self, tmp_path):
        out = str(tmp_path / "out.jsonl")
        s = _mk(tmp_path)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT sum(v) AS n FROM t")
        s.run_sql(f"CREATE SINK snk FROM m WITH "
                  f"(connector = 'file', path = '{out}')")
        s.run_sql("INSERT INTO t VALUES (1, 10)")
        s.run_sql("FLUSH")
        # changelog of an agg MV: insert NULL at init, then U-/U+ pairs
        assert [r["n"] for r in _sink_rows(out)
                if r["__op"] == "update_insert"] == [10]

        # backend goes down for several epochs: barriers + checkpoints
        # MUST keep committing and the co-resident MV keeps advancing
        arm("sink.deliver", OSError)
        try:
            epoch0 = s.epoch
            for i in range(2, 7):
                s.run_sql(f"INSERT INTO t VALUES ({i}, {10 * i})")
                s.run_sql("FLUSH")         # checkpoint epochs still commit
            assert s.epoch > epoch0
            assert s.mv_rows("m") == [(10 + 20 + 30 + 40 + 50 + 60,)]
            m = s.metrics()
            h = m["sinks"]["snk"]
            assert h["degraded"] is True
            assert h["pending_rows"] > 0
            assert h["delivery_failures"] >= _FC.sink_degrade_after
            assert h["last_error"]
            # retry counters surfaced too
            assert m["retry"]["sink.deliver"]["give_ups"] > 0
            # Prometheus exposition carries the health gauges
            from risingwave_tpu.frontend.prometheus import render_metrics
            text = render_metrics(s)
            assert 'rw_sink_degraded{sink="snk"} 1' in text
        finally:
            disarm()

        # backend returns: resume drains the whole backlog exactly once
        s.resume_sink("snk")
        s.tick(generate=False)
        h = s.metrics()["sinks"]["snk"]
        assert h["degraded"] is False and h["pending_rows"] == 0
        # every running sum appears EXACTLY once (no replays, no gaps),
        # and the changelog folds to the MV's final row
        ups = [r["n"] for r in _sink_rows(out)
               if r["__op"] == "update_insert"]
        assert ups == [10, 30, 60, 100, 150, 210]
        fold: dict = {}
        for r in _sink_rows(out):
            if r["__op"] in ("insert", "update_insert"):
                fold[r["n"]] = fold.get(r["n"], 0) + 1
            else:
                fold[r["n"]] = fold.get(r["n"], 0) - 1
        assert {k for k, c in fold.items() if c} == {210}
        s.close()

    def test_degraded_backlog_survives_crash_and_delivers_once(
            self, tmp_path):
        out = str(tmp_path / "out.jsonl")
        s = _mk(tmp_path)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql(f"CREATE SINK snk AS SELECT k, v FROM t WITH "
                  f"(connector = 'file', path = '{out}')")
        s.run_sql("INSERT INTO t VALUES (1, 10)")
        s.run_sql("FLUSH")
        arm("sink.deliver", OSError)
        try:
            for i in range(2, 5):
                s.run_sql(f"INSERT INTO t VALUES ({i}, {10 * i})")
                s.run_sql("FLUSH")         # backlog durably logged
            assert s.metrics()["sinks"]["snk"]["degraded"] is True
        finally:
            disarm()
        # crash: no graceful close — the logged-undelivered rows and the
        # committed sink position must both recover
        s.loop.close()

        s2 = Session(data_dir=str(tmp_path / "db"), fault_config=_FC,
                     checkpoint_frequency=2)
        s2.tick(generate=False)            # fresh executor is not degraded
        rows = _sink_rows(out)
        keys = sorted(r["k"] for r in rows if r["__op"] == "insert")
        assert keys == [1, 2, 3, 4]        # every row exactly once
        assert s2.metrics()["sinks"]["snk"]["pending_rows"] == 0
        s2.close()

    def test_log_cap_backpressure_fails_loudly(self, tmp_path):
        s = _mk(tmp_path)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE SINK snk AS SELECT k, v FROM t WITH "
                  "(connector = 'blackhole', 'sink.log_cap_rows' = '3', "
                  "'sink.degrade_after' = '1')")
        with failpoints(**{"sink.deliver": OSError}):
            s.run_sql("INSERT INTO t VALUES (1, 1), (2, 2)")
            s.tick()                       # degrade (cap not hit yet)
            s.run_sql("INSERT INTO t VALUES (3, 3), (4, 4)")
            with pytest.raises(RuntimeError) as ei:
                s.tick()
            # the job failure wraps the loud cap error
            assert "log_cap_rows" in str(ei.value.__cause__ or ei.value)
        s.close()

    def test_transient_hiccup_absorbed_by_retry(self, tmp_path):
        """A once-off delivery fault is absorbed INSIDE the barrier by
        the bounded retry: no degrade, no lost rows."""
        out = str(tmp_path / "out.jsonl")
        s = _mk(tmp_path)
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql(f"CREATE SINK snk AS SELECT k, v FROM t WITH "
                  f"(connector = 'file', path = '{out}')")
        arm("sink.deliver", OSError, once=True)
        try:
            s.run_sql("INSERT INTO t VALUES (1, 10)")
            s.run_sql("FLUSH")
        finally:
            disarm()
        h = s.metrics()["sinks"]["snk"]
        assert h["degraded"] is False and h["pending_rows"] == 0
        assert [r["k"] for r in _sink_rows(out)] == [1]
        assert s.metrics()["retry"]["sink.deliver"]["retries"] >= 1
        s.close()
