"""Regression tests for code-review findings (round 1)."""

import asyncio

import numpy as np

from risingwave_tpu.common import (
    FLOAT64, INT64, TIMESTAMP, Schema, chunk_to_rows, make_chunk, decimal,
)
from risingwave_tpu.expr import Literal, call, col
from risingwave_tpu.expr.agg import agg, count_star
from risingwave_tpu.storage import MemoryStateStore, StateTable
from risingwave_tpu.stream import (
    Barrier, HashAggExecutor, MaterializeExecutor, MockSource,
)

S = Schema.of(("k", INT64), ("v", INT64))


def run(coro):
    return asyncio.run(coro)


async def drain(ex):
    async for _ in ex.execute():
        pass


def test_non_checkpoint_epochs_survive_to_next_checkpoint():
    """Finding 1: epoch-2 (non-checkpoint) MV writes must be committed by the
    epoch-3 checkpoint, not stranded."""
    store = MemoryStateStore()
    src = MockSource(S, [
        Barrier.new(1),
        make_chunk(S, [(1, 10)]),
        Barrier.new(2),                      # non-checkpoint
        make_chunk(S, [(2, 20)]),
        Barrier.new(3, checkpoint=True),     # checkpoint commits epoch 2 + 3
    ])
    mv = MaterializeExecutor(src, StateTable(store, 7, S, [0]))
    run(drain(mv))
    store.commit(3)   # the barrier conductor's sync_epoch commit
    assert sorted(mv.rows()) == [(1, 10), (2, 20)]
    assert store.committed_epoch == 3


def test_avg_decimal_descaled():
    """Finding 2: avg over DECIMAL must descale."""
    sch = Schema.of(("k", INT64), ("d", decimal(2)))
    c = make_chunk(sch, [(1, 1.00), (1, 3.00)])
    src = MockSource(sch, [Barrier.new(1), c, Barrier.new(2)])
    ex = HashAggExecutor(src, [0], [agg("avg", 1, decimal(2))])
    chunks = []

    async def d():
        async for m in ex.execute():
            from risingwave_tpu.common import StreamChunk
            if isinstance(m, StreamChunk):
                chunks.append(m)
    run(d())
    rows = [r for ch in chunks for r in chunk_to_rows(ch, ex.schema)]
    assert rows == [(1, 2.0)]


def test_minmax_int64_exact_above_2_53():
    """Finding 3: min/max on int64 must be exact beyond 2^53."""
    big = 9007199254740993  # 2^53 + 1
    c = make_chunk(S, [(1, big), (1, big - 1)])
    src = MockSource(S, [Barrier.new(1), c, Barrier.new(2)])
    ex = HashAggExecutor(src, [0], [agg("max", 1, INT64), agg("min", 1, INT64)])
    chunks = []

    async def d():
        async for m in ex.execute():
            from risingwave_tpu.common import StreamChunk
            if isinstance(m, StreamChunk):
                chunks.append(m)
    run(d())
    rows = [r for ch in chunks for r in chunk_to_rows(ch, ex.schema)]
    assert rows == [(1, big, big - 1)]


def test_mixed_operand_order_timestamp_plus_int():
    """Finding 5: int + timestamp must type-infer regardless of order."""
    sch = Schema.of(("ts", TIMESTAMP),)
    c = make_chunk(sch, [(100,)])
    e1 = col(0, TIMESTAMP) + 5
    e2 = Literal(5, INT64) + col(0, TIMESTAMP)
    assert e1.type.kind == e2.type.kind == TIMESTAMP.kind
    assert int(e2.eval(c).data[0]) == 105


def test_sql_truncating_division_and_modulus():
    """Finding 6: -5/2 == -2 and -5%2 == -1 (SQL), not floor semantics."""
    c = make_chunk(S, [(-5, 2)])
    q = (col(0, INT64) / col(1, INT64)).eval(c)
    r = (col(0, INT64) % col(1, INT64)).eval(c)
    assert int(q.data[0]) == -2
    assert int(r.data[0]) == -1


def test_state_table_len_no_double_count():
    """Finding 7: overwriting a committed pk must not inflate len()."""
    store = MemoryStateStore()
    t = StateTable(store, 1, S, [0])
    t.insert((1, 10))
    t.commit(1)
    store.commit(1)
    t.insert((1, 99))  # overwrite, uncommitted
    assert len(t) == 1
    assert len(list(t.scan_all())) == 1
