"""Per-executor metrics + session barrier-latency observability
(VERDICT r2 item 8)."""

from risingwave_tpu.frontend import Session

DDL = """
CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,
  channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'bid');
CREATE SOURCE auction (id BIGINT, item_name VARCHAR, description VARCHAR,
  initial_bid BIGINT, reserve BIGINT, date_time TIMESTAMP,
  expires TIMESTAMP, seller BIGINT, category BIGINT, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'auction')
"""


def test_session_metrics_surface():
    s = Session(source_chunk_capacity=64)
    s.run_sql(DDL)
    s.run_sql("""CREATE MATERIALIZED VIEW q AS
        SELECT auction, COUNT(*) AS c FROM bid GROUP BY auction""")
    s.run_sql("""CREATE MATERIALIZED VIEW j AS
        SELECT B.auction, A.seller FROM bid B
        INNER JOIN auction A ON B.auction = A.id""")
    for _ in range(4):
        s.tick()
    m = s.metrics()
    assert m["epoch"] == s.epoch
    bl = m["barrier_latency"]
    assert bl["count"] >= 4 and bl["p99_ms"] is not None
    assert bl["p50_ms"] <= bl["p99_ms"] <= bl["max_ms"]

    q = m["jobs"]["q"]
    # the materialize + agg stage both saw chunks and barriers
    agg = next(v for k, v in q.items() if k.startswith("HashAgg"))
    mat = next(v for k, v in q.items() if k.startswith("Materialize"))
    assert agg["chunks_in"] == 4
    assert agg["capacity_rows_in"] == 4 * 64
    assert agg["barriers"] >= 4
    assert agg["chunks_out"] >= 1
    assert mat["chunks_in"] >= 1
    assert mat["barrier_seconds"] >= 0.0

    j = m["jobs"]["j"]
    join = next(v for k, v in j.items() if k.startswith("HashJoin"))
    assert join["chunks_in"] == 8        # both sides
    assert join["barriers"] >= 4
    assert join["chunks_out"] >= 1


def test_metrics_count_batches():
    import asyncio
    from risingwave_tpu.common import INT64, Schema, make_chunk
    from risingwave_tpu.common.chunk import stack_chunks
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.stream import Barrier, HashAggExecutor, MockSource

    S = Schema.of(("k", INT64), ("v", INT64))
    chunks = [make_chunk(S, [(i, i)], capacity=8) for i in range(4)]
    src = MockSource(S, [Barrier.new(1), stack_chunks(chunks), Barrier.new(2)])
    agg = HashAggExecutor(src, [0], [count_star()], table_capacity=64,
                          out_capacity=16)

    async def drain():
        async for _ in agg.execute():
            pass

    asyncio.run(drain())
    st = agg.stats.snapshot()
    assert st["batches_in"] == 1
    assert st["batch_chunks_in"] == 4
    assert st["capacity_rows_in"] == 4 * 8


# -- epoch-aware tracing spans (common/tracing.py) ----------------------------

def test_trace_recorder_ring_and_drain():
    from risingwave_tpu.common.tracing import Span, TraceRecorder

    rec = TraceRecorder(capacity=4)
    for i in range(10):
        rec.record(Span(f"s{i}", "epoch", float(i), 0.001, epoch=i))
    spans = rec.snapshot()
    assert [s.name for s in spans] == ["s6", "s7", "s8", "s9"]  # bounded
    assert rec.epochs() == [6, 7, 8, 9]
    assert [s.epoch for s in rec.snapshot(epoch=7)] == [7]
    drained = rec.drain()
    assert len(drained) == 4 and rec.snapshot() == []           # take+clear
    # wire round-trip (Span.to_dict/from_dict is the stats-frame codec)
    back = [Span.from_dict(s.to_dict()) for s in drained]
    assert [(s.name, s.epoch) for s in back] == [
        (s.name, s.epoch) for s in drained]
    # unknown keys from a newer worker are ignored, not fatal; ingest
    # re-records shipped dicts tagged with the sender's pid
    d = drained[0].to_dict()
    d["new_field_from_the_future"] = 1
    rec.ingest([d], pid=3)
    (got,) = rec.snapshot()
    assert got.name == drained[0].name and got.pid == 3


def test_chrome_trace_export_covers_epochs_and_executors():
    """Acceptance: after a NEXmark-source run, the Chrome trace-event
    export is valid JSON whose spans cover >= 2 epochs, each with
    per-executor child spans on their own tracks."""
    import json

    from risingwave_tpu.common.tracing import GLOBAL_TRACE

    GLOBAL_TRACE.clear()
    s = Session(source_chunk_capacity=64, checkpoint_frequency=2)
    s.run_sql(DDL)
    s.run_sql("""CREATE MATERIALIZED VIEW q AS
        SELECT auction, count(*) AS n, max(price) AS mx
        FROM bid GROUP BY auction""")
    for _ in range(4):
        s.tick()
    s._drain_inflight()
    obj = json.loads(json.dumps(s.export_chrome_trace()))  # JSON-clean
    events = [e for e in obj["traceEvents"] if e.get("ph") == "X"]
    assert all({"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
               for e in events)
    epoch_spans = {e["args"]["epoch"] for e in events
                   if e["name"].startswith("epoch ")}
    assert len(epoch_spans) >= 2
    for ep in epoch_spans:
        per_exec = {e["tid"] for e in events
                    if e["cat"] == "barrier" and e["args"].get("epoch") == ep}
        assert {"HashAgg", "Materialize"} <= per_exec
    # conductor phases present and storage commits attributed
    names = {e["name"] for e in events}
    assert {"barrier.inject", "barrier.collect"} <= names
    assert any(e["cat"] == "storage" for e in events)
    # process metadata names the session track
    metas = [e for e in obj["traceEvents"] if e.get("ph") == "M"]
    assert any(m["args"]["name"] == "session" for m in metas)
    s.close()


def test_slow_epoch_threshold_captures_span_tree():
    """An epoch whose barrier latency meets slow_epoch_threshold_ms gets
    its span tree snapshotted into the session's slow-epoch ring."""
    s = Session(source_chunk_capacity=64)
    s.run_sql(DDL)
    s.run_sql("""CREATE MATERIALIZED VIEW q AS
        SELECT auction, count(*) AS c FROM bid GROUP BY auction""")
    s.tick()
    s._drain_inflight()
    assert s.slow_epochs() == []               # disabled by default
    s.run_sql("SET slow_epoch_threshold_ms = 0.0001")   # everything trips
    s.tick()
    s._drain_inflight()
    caught = s.slow_epochs()
    assert caught and caught[-1]["latency_ms"] > 0
    spans = caught[-1]["spans"]
    assert any(sp["name"].startswith("epoch ") for sp in spans)
    assert any(sp["cat"] == "barrier" for sp in spans)  # executor children
    m = s.metrics()
    assert m["slow_epoch_total"] == len(caught)
    # metrics() summarizes without the heavy span payload
    assert all("spans" not in se for se in m["slow_epochs"])
    s.close()
