"""Per-executor metrics + session barrier-latency observability
(VERDICT r2 item 8)."""

from risingwave_tpu.frontend import Session

DDL = """
CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,
  channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'bid');
CREATE SOURCE auction (id BIGINT, item_name VARCHAR, description VARCHAR,
  initial_bid BIGINT, reserve BIGINT, date_time TIMESTAMP,
  expires TIMESTAMP, seller BIGINT, category BIGINT, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'auction')
"""


def test_session_metrics_surface():
    s = Session(source_chunk_capacity=64)
    s.run_sql(DDL)
    s.run_sql("""CREATE MATERIALIZED VIEW q AS
        SELECT auction, COUNT(*) AS c FROM bid GROUP BY auction""")
    s.run_sql("""CREATE MATERIALIZED VIEW j AS
        SELECT B.auction, A.seller FROM bid B
        INNER JOIN auction A ON B.auction = A.id""")
    for _ in range(4):
        s.tick()
    m = s.metrics()
    assert m["epoch"] == s.epoch
    bl = m["barrier_latency"]
    assert bl["count"] >= 4 and bl["p99_ms"] is not None
    assert bl["p50_ms"] <= bl["p99_ms"] <= bl["max_ms"]

    q = m["jobs"]["q"]
    # the materialize + agg stage both saw chunks and barriers
    agg = next(v for k, v in q.items() if k.startswith("HashAgg"))
    mat = next(v for k, v in q.items() if k.startswith("Materialize"))
    assert agg["chunks_in"] == 4
    assert agg["capacity_rows_in"] == 4 * 64
    assert agg["barriers"] >= 4
    assert agg["chunks_out"] >= 1
    assert mat["chunks_in"] >= 1
    assert mat["barrier_seconds"] >= 0.0

    j = m["jobs"]["j"]
    join = next(v for k, v in j.items() if k.startswith("HashJoin"))
    assert join["chunks_in"] == 8        # both sides
    assert join["barriers"] >= 4
    assert join["chunks_out"] >= 1


def test_metrics_count_batches():
    import asyncio
    from risingwave_tpu.common import INT64, Schema, make_chunk
    from risingwave_tpu.common.chunk import stack_chunks
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.stream import Barrier, HashAggExecutor, MockSource

    S = Schema.of(("k", INT64), ("v", INT64))
    chunks = [make_chunk(S, [(i, i)], capacity=8) for i in range(4)]
    src = MockSource(S, [Barrier.new(1), stack_chunks(chunks), Barrier.new(2)])
    agg = HashAggExecutor(src, [0], [count_star()], table_capacity=64,
                          out_capacity=16)

    async def drain():
        async for _ in agg.execute():
            pass

    asyncio.run(drain())
    st = agg.stats.snapshot()
    assert st["batches_in"] == 1
    assert st["batch_chunks_in"] == 4
    assert st["capacity_rows_in"] == 4 * 8
