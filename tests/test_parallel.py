"""Mesh-sharded execution tests (virtual 8-device CPU mesh from conftest).

Covers VERDICT r2 weak #3: the sharded path previously had zero pytest
coverage. Every test cross-checks against either an independent host model
or a single-chip session running the identical deterministic workload."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import INT64, Schema, chunk_to_rows, make_chunk
from risingwave_tpu.common.chunk import OP_DELETE, OP_INSERT
from risingwave_tpu.expr.agg import agg as agg_call, count_star
from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.build import BuildConfig
from risingwave_tpu.ops.join_state import JoinType
from risingwave_tpu.parallel import (
    ShardedHashAgg, ShardedHashJoin, build_sharded_q5_step,
    build_sharded_q7_step, make_mesh,
)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_DEV, "conftest must force 8 CPU devices"
    return make_mesh(N_DEV)


SCHEMA2 = Schema.of(("k", INT64), ("v", INT64))


def _chunks_for(mesh, rows_per_shard, ops_per_shard=None, cap=16):
    out = []
    for s in range(N_DEV):
        rows = rows_per_shard[s]
        ops = ops_per_shard[s] if ops_per_shard else None
        out.append(make_chunk(SCHEMA2, rows, ops=ops, capacity=cap))
    return out


def test_sharded_q5_step_dryrun():
    build_sharded_q5_step(N_DEV)


def test_sharded_q7_step_dryrun():
    build_sharded_q7_step(N_DEV)


def test_sharded_agg_insert_delete(mesh):
    agg = ShardedHashAgg(mesh, [INT64], [0], [count_star(), agg_call("sum", 1, INT64)],
                         table_capacity=256, out_capacity=32)
    ins = [[(k % 5, k) for k in range(s, s + 10)] for s in range(N_DEV)]
    batch = agg.batch_chunks(_chunks_for(mesh, ins))
    agg.step(batch)
    # retract a few rows from different shards
    dels = [[(s % 5, s)] for s in range(N_DEV)]
    ops = [[OP_DELETE] for _ in range(N_DEV)]
    agg.step(agg.batch_chunks(_chunks_for(mesh, dels, ops)))

    expected: dict = {}
    for s in range(N_DEV):
        for k, v in ins[s]:
            c, t = expected.get((k,), (0, 0))
            expected[(k,)] = (c + 1, t + v)
        k, v = dels[s][0]
        c, t = expected[(k,)]
        expected[(k,)] = (c - 1, t - v)
    expected = {k: v for k, v in expected.items() if v[0] > 0}
    got = agg.merged_group_values()
    got = {k: (v[1], v[2]) for k, v in got.items()}
    assert got == expected


def host_join(l_rows, r_rows):
    return sorted((0, lr + rr) for lr in l_rows for rr in r_rows
                  if lr[0] == rr[0])


def test_sharded_join_basic(mesh):
    join = ShardedHashJoin(mesh, SCHEMA2, SCHEMA2, [0], [0], JoinType.INNER,
                           key_capacity=256, bucket_width=4)
    l_rows = [[(k % 7, 100 * s + k) for k in range(8)] for s in range(N_DEV)]
    r_rows = [[(k % 7, 200 * s + k) for k in range(4)] for s in range(N_DEV)]
    out_r = join.step("right", join.batch_chunks(_chunks_for(mesh, r_rows)))
    out_l = join.step("left", join.batch_chunks(_chunks_for(mesh, l_rows)))
    got = sorted(join.collect_rows(out_r) + join.collect_rows(out_l))
    exp = host_join([r for s in l_rows for r in s],
                    [r for s in r_rows for r in s])
    assert got == exp
    assert len(got) > 0


def test_sharded_join_growth_on_hot_key(mesh):
    """All rows share ONE key -> one shard's bucket must grow far past the
    initial width; growth retries must not lose or duplicate rows."""
    join = ShardedHashJoin(mesh, SCHEMA2, SCHEMA2, [0], [0], JoinType.INNER,
                           key_capacity=64, bucket_width=2)
    l_rows = [[(1, 100 * s + k) for k in range(6)] for s in range(N_DEV)]
    r_rows = [[(1, 7000 + s)] for s in range(N_DEV)]
    out_r = join.step("right", join.batch_chunks(_chunks_for(mesh, r_rows)))
    out_l = join.step("left", join.batch_chunks(_chunks_for(mesh, l_rows)))
    got = sorted(join.collect_rows(out_r) + join.collect_rows(out_l))
    exp = host_join([r for s in l_rows for r in s],
                    [r for s in r_rows for r in s])
    assert got == exp
    assert join.core.W > 2  # growth actually happened
    assert len(got) == 6 * N_DEV * N_DEV


def test_sharded_join_retraction(mesh):
    """Deletes on the build side retract previously emitted join rows."""
    join = ShardedHashJoin(mesh, SCHEMA2, SCHEMA2, [0], [0], JoinType.INNER,
                           key_capacity=256, bucket_width=4)
    r_rows = [[(s, 10 + s)] for s in range(N_DEV)]
    l_rows = [[(s, 20 + s)] for s in range(N_DEV)]
    join.step("right", join.batch_chunks(_chunks_for(mesh, r_rows)))
    out_l = join.step("left", join.batch_chunks(_chunks_for(mesh, l_rows)))
    ins = sorted(join.collect_rows(out_l))
    assert len(ins) == N_DEV
    # retract all right rows -> every joined row is deleted
    ops = [[OP_DELETE] for _ in range(N_DEV)]
    out_d = join.step("right", join.batch_chunks(_chunks_for(mesh, r_rows, ops)))
    dels = sorted(join.collect_rows(out_d))
    assert [(OP_DELETE, r) for _, r in ins] == dels


# ---------------------------------------------------------------------------
# End-to-end: CREATE MV runs data-parallel over the mesh and matches the
# single-chip session on the identical deterministic NEXmark stream.
# ---------------------------------------------------------------------------

DDL = """
CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,
  channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'bid');
CREATE SOURCE auction (id BIGINT, item_name VARCHAR, description VARCHAR,
  initial_bid BIGINT, reserve BIGINT, date_time TIMESTAMP,
  expires TIMESTAMP, seller BIGINT, category BIGINT, extra VARCHAR)
WITH (connector = 'nexmark', nexmark_table = 'auction')
"""


def _run(sql: str, name: str, mesh=None, ticks: int = 3):
    cfg = BuildConfig(mesh=mesh, agg_table_capacity=1 << 10,
                      join_key_capacity=1 << 9, join_bucket_width=8)
    s = Session(source_chunk_capacity=64, config=cfg)
    s.run_sql(DDL)
    s.run_sql(sql)
    for _ in range(ticks):
        s.tick()
    return sorted(s.mv_rows(name))


def test_sharded_mv_q5_core_equivalence(mesh):
    sql = """CREATE MATERIALIZED VIEW q5c AS
        SELECT auction, COUNT(*) AS cnt, SUM(price) AS total
        FROM bid GROUP BY auction"""
    assert _run(sql, "q5c", mesh=mesh) == _run(sql, "q5c", mesh=None)


@pytest.mark.slow
def test_sharded_mv_q7_core_equivalence(mesh):
    sql = """CREATE MATERIALIZED VIEW q7c AS
        SELECT B.auction, B.price, A.seller
        FROM bid B INNER JOIN auction A ON B.auction = A.id
        WHERE B.date_time <= A.expires"""
    got = _run(sql, "q7c", mesh=mesh)
    want = _run(sql, "q7c", mesh=None)
    assert got == want
    assert len(got) > 0


def test_sharded_mv_checkpoint_recovery(mesh):
    """Sharded agg state survives: checkpoint, rebuild executor from the
    state table, verify groups."""
    from risingwave_tpu.parallel.executors import ShardedHashAggExecutor
    from risingwave_tpu.storage.state_store import MemoryStateStore
    from risingwave_tpu.storage.state_table import StateTable
    from risingwave_tpu.stream.hash_agg import agg_state_schema
    from risingwave_tpu.stream.source import MockSource
    from risingwave_tpu.stream.message import Barrier
    from risingwave_tpu.stream.executor import collect_until_barrier

    store = MemoryStateStore()
    schema = agg_state_schema([SCHEMA2[0]], [count_star(), agg_call("sum", 1, INT64)])
    table = StateTable(store, 7, schema, [0])
    rows = [(k % 11, k) for k in range(100)]
    msgs = [make_chunk(SCHEMA2, rows, capacity=128),
            Barrier.new(2, checkpoint=True)]
    src = MockSource(SCHEMA2, [Barrier.new(1)] + msgs)
    ex = ShardedHashAggExecutor(src, mesh, [0],
                                [count_star(), agg_call("sum", 1, INT64)],
                                state_table=table, table_capacity=256,
                                out_capacity=32)

    async def drain():
        chunks = []
        async for m in ex.execute():
            from risingwave_tpu.common.chunk import StreamChunk
            if isinstance(m, StreamChunk):
                chunks.append(m)
        return chunks

    import asyncio
    chunks = asyncio.run(drain())
    store.commit(2)
    emitted = sorted(r for c in chunks
                     for r in chunk_to_rows(c, ex.schema, physical=True))
    expected: dict = {}
    for k, v in rows:
        c, t = expected.get(k, (0, 0))
        expected[k] = (c + 1, t + v)
    assert emitted == sorted((k, c, t) for k, (c, t) in expected.items())

    # recover a fresh executor from the durable tier
    table2 = StateTable(store, 7, schema, [0])
    src2 = MockSource(SCHEMA2, [Barrier.new(3)])
    ex2 = ShardedHashAggExecutor(src2, mesh, [0],
                                 [count_star(), agg_call("sum", 1, INT64)],
                                 state_table=table2, table_capacity=256,
                                 out_capacity=32)
    got = {k[0]: (v[1], v[2])
           for k, v in ex2.agg.merged_group_values().items()}
    assert got == expected
