"""The dedicated compactor role + crash safety of compaction on BOTH
durable tiers.

Acceptance (ISSUE 2): ``kill -9`` of the compactor mid-task leaves the
store recoverable — restart replays the last committed version and a
rescheduled compaction converges."""

import threading

import pytest

from risingwave_tpu.common.failpoint import failpoints
from risingwave_tpu.storage.checkpoint import CheckpointLog
from risingwave_tpu.storage.hummock import (
    SST_PREFIX, HummockStateStore, run_compact_task,
)
from risingwave_tpu.worker.compactor import CompactorClient, CompactorDied


def _fill(st, table=7, epochs=range(1, 8)):
    for e in epochs:
        st.ingest(table, e, {b"k%03d" % e: b"v%d" % e}, set())
        st.commit(e)


def _expect(epochs):
    return {b"k%03d" % e: b"v%d" % e for e in epochs}


class TestCompactorWorker:
    def test_task_roundtrip_and_stats(self, tmp_path):
        d = str(tmp_path / "hm")
        st = HummockStateStore(data_dir=d, inline_compaction=False)
        _fill(st)
        c = CompactorClient(d)
        c.spawn()
        try:
            task = st.manager.get_compact_task(force=True)
            outputs = c.compact(task)
            assert outputs
            st.manager.report_compact_task(task.task_id, outputs)
            st.vacuum()
            stats = c.get_stats()
            assert stats["compactor"]["tasks_completed"] == 1
            assert stats["compactor"]["ssts_written"] == len(outputs)
        finally:
            c.shutdown()
        st2 = HummockStateStore(data_dir=d)
        assert dict(st2.iter_table(7)) == _expect(range(1, 8))

    def test_kill9_mid_task_store_recoverable(self, tmp_path):
        """The acceptance test: SIGKILL the compactor process while it is
        compacting; the store recovers at the last committed version and
        a rescheduled task (fresh process) converges."""
        d = str(tmp_path / "hm")
        st = HummockStateStore(data_dir=d, inline_compaction=False)
        _fill(st, epochs=range(1, 10))
        pre_version = st.manager.version
        c = CompactorClient(d)
        c.spawn()
        task = st.manager.get_compact_task(force=True)
        err = []

        def run():
            try:
                c.compact(task, delay_ms=5000)   # widen the kill window
            except (CompactorDied, RuntimeError) as e:
                err.append(e)

        t = threading.Thread(target=run)
        t.start()
        import time
        time.sleep(0.5)
        c.kill9()                                # mid-task
        t.join(timeout=30)
        assert err, "compact() must fail when the worker is SIGKILLed"
        st.manager.cancel_compact_task(task.task_id)

        # restart replays the last committed (pre-compaction) version
        st2 = HummockStateStore(data_dir=d, inline_compaction=False)
        assert st2.committed_epoch == 9
        assert dict(st2.iter_table(7)) == _expect(range(1, 10))
        assert set(st2.manager.version.all_runs()) == set(
            pre_version.all_runs())

        # rescheduled compaction (fresh worker) converges
        c.respawn()
        try:
            task2 = st2.manager.get_compact_task(force=True)
            outputs = c.compact(task2)
            st2.manager.report_compact_task(task2.task_id, outputs)
            st2.vacuum()
        finally:
            c.shutdown()
        st3 = HummockStateStore(data_dir=d)
        assert dict(st3.iter_table(7)) == _expect(range(1, 10))
        assert set(st3.object_store.list(SST_PREFIX)) == set(
            st3.manager.version.all_runs())

    def test_session_compactor_death_and_respawn(self, tmp_path):
        """Session-level: the compaction pump survives a dead compactor —
        it respawns the stateless worker and a later checkpoint's
        rescheduled task converges."""
        from risingwave_tpu.frontend import Session
        d = str(tmp_path / "db")
        s = Session(data_dir=d, state_store="hummock", compactors=1,
                    checkpoint_frequency=1)
        try:
            s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
            s.compactors[0].kill9()          # dies BEFORE any task
            for i in range(10):
                s.run_sql(f"INSERT INTO t VALUES ({i}, {i})")
                s.flush()
            s.wait_compaction()
            # the pump respawned the worker and compaction converged
            mgr = s.store.manager
            assert mgr.stats["compact_tasks_completed"] >= 1
            assert not s.compactors[0].dead
            assert sorted(s.run_sql("SELECT k, v FROM t")) == [
                (i, i) for i in range(10)]
        finally:
            s.close()


class TestCompactionCrashSafety:
    """Failpoint kills mid-compaction (ISSUE 2 satellite): both the
    legacy segment fold and the new compactor task must leave a
    consistent pre-compaction version with no lost epochs."""

    def test_segment_fold_killed_mid_write(self, tmp_path):
        log = CheckpointLog(str(tmp_path), compact_after=1000)
        for e in range(1, 6):
            log.append_epoch(e, {7: {b"k%03d" % e: b"v%d" % e}})
        manifest_before = log._read_manifest()
        with failpoints(**{"checkpoint.segment.write": OSError}):
            # the fold writes its folded segment through _write_segment;
            # the manifest swap never happens
            with pytest.raises(OSError):
                log.compact()
        assert log._read_manifest() == manifest_before
        epoch, tables = log.load_tables()
        assert epoch == 5
        assert tables[7] == _expect(range(1, 6))
        # retry converges
        log.compact()
        epoch, tables = log.load_tables()
        assert epoch == 5 and tables[7] == _expect(range(1, 6))
        assert len(log._read_manifest()["segments"]) == 1

    def test_segment_fold_failure_then_retry_converges(self, tmp_path):
        """A fold that dies mid-write leaves old segments valid; the next
        fold attempt (failpoint cleared) converges."""
        log = CheckpointLog(str(tmp_path), compact_after=1000)
        for e in range(1, 6):
            log.append_epoch(e, {7: {b"k%03d" % e: b"v%d" % e}})
        from risingwave_tpu.common.failpoint import arm, disarm
        arm("checkpoint.segment.write", OSError, once=True)
        try:
            with pytest.raises(OSError):
                log.compact()
        finally:
            disarm()
        log.compact()                          # retry converges
        epoch, tables = log.load_tables()
        assert epoch == 5 and tables[7] == _expect(range(1, 6))
        assert len(log._read_manifest()["segments"]) == 1

    @pytest.mark.parametrize("site", ["compactor.task.start",
                                      "compactor.output.write",
                                      "compactor.merge.step"])
    def test_hummock_task_killed_at_any_point(self, tmp_path, site):
        d = str(tmp_path / f"hm_{site.replace('.', '_')}")
        st = HummockStateStore(data_dir=d, inline_compaction=False)
        _fill(st)
        pre_runs = set(st.manager.version.all_runs())
        task = st.manager.get_compact_task(force=True)
        with failpoints(**{site: OSError}):
            with pytest.raises(OSError):
                run_compact_task(st.object_store, task)
        st.manager.cancel_compact_task(task.task_id)
        # consistent pre-compaction version, no lost epochs
        st2 = HummockStateStore(data_dir=d, inline_compaction=False)
        assert st2.committed_epoch == 7
        assert dict(st2.iter_table(7)) == _expect(range(1, 8))
        assert set(st2.manager.version.all_runs()) == pre_runs
        # half-written outputs (if any) are orphans: vacuum removes them,
        # then a rescheduled task converges
        st2.vacuum()
        assert set(st2.object_store.list(SST_PREFIX)) == pre_runs
        st2.compact()
        st3 = HummockStateStore(data_dir=d)
        assert dict(st3.iter_table(7)) == _expect(range(1, 8))

    def test_inline_background_compaction_failure_contained(self):
        from risingwave_tpu.storage.object_store import MemObjectStore
        st = HummockStateStore(object_store=MemObjectStore(),
                               l0_compact_trigger=3,
                               inline_compaction=True)
        from risingwave_tpu.common.failpoint import arm, disarm
        arm("compactor.output.write", OSError)
        try:
            _fill(st)                  # triggers background compaction
            st.wait_compaction()
        finally:
            disarm()
        # the fold failed; the store still answers and later compaction
        # converges
        assert dict(st.iter_table(7)) == _expect(range(1, 8))
        st.compact()
        st2 = HummockStateStore(object_store=st.object_store)
        assert dict(st2.iter_table(7)) == _expect(range(1, 8))
