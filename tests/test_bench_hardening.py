"""bench.py hardening (ROADMAP item 4a): per-phase persistence — every
completed phase's record lands in BENCH_partial.json the moment the
phase finishes, so a mid-run wedge/kill of the parent still leaves every
completed phase on disk — plus the cheap smoke probe and the shared
compilation cache wiring."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


def _read_partial(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_persist_phase_appends_jsonl(tmp_path, monkeypatch):
    p = tmp_path / "BENCH_partial.json"
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(p))
    bench._persist_phase("cpu_standin", {"value": 1.5, "unit": "rows/s"})
    bench._persist_phase("tpu_attempt1", {"rc": "timeout"})
    recs = _read_partial(p)
    assert [r["phase"] for r in recs] == ["cpu_standin", "tpu_attempt1"]
    assert recs[0]["record"]["value"] == 1.5
    assert all("ts" in r for r in recs)


def test_completed_phase_is_on_disk_before_run_ends(tmp_path, monkeypatch):
    """The parent persists each phase AS IT COMPLETES — the file holds the
    record even though no later phase (and no final emit) ever ran, which
    is exactly the mid-run-kill scenario."""
    p = tmp_path / "BENCH_partial.json"
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(p))
    env = {"JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": None, "TPU_LIBRARY_PATH": None}
    rec = bench._spawn_phase("cpu_probe", env, ["--probe"],
                             timeout=bench.PROBE_TIMEOUT)
    assert rec["probe"] == "ok" and rec["backend"] == "cpu"
    # ... parent is "killed" here; the completed phase already persisted
    recs = _read_partial(p)
    assert recs[-1]["phase"] == "cpu_probe"
    assert recs[-1]["record"]["probe"] == "ok"


def test_failed_phase_rc_also_persisted(tmp_path, monkeypatch):
    p = tmp_path / "BENCH_partial.json"
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(p))
    env = {"JAX_PLATFORMS": "definitely_not_a_backend",
           "PALLAS_AXON_POOL_IPS": None, "TPU_LIBRARY_PATH": None}
    with pytest.raises(RuntimeError):
        bench._spawn_phase("tpu_probe1", env, ["--probe"],
                           timeout=bench.PROBE_TIMEOUT)
    recs = _read_partial(p)
    assert recs[-1]["phase"] == "tpu_probe1"
    assert recs[-1]["record"]["rc"] != 0       # failure attributed on disk


def test_tpu_cache_env_is_stable_across_attempts(monkeypatch):
    env1 = bench._tpu_cache_env()
    assert env1["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] == "0"
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/tmp/fixed_cache")
    env2 = bench._tpu_cache_env()
    assert env2["JAX_COMPILATION_CACHE_DIR"] == "/tmp/fixed_cache"


@pytest.mark.slow
def test_kill_mid_run_leaves_partial(tmp_path):
    """End-to-end: run the real parent, SIGKILL it after the first phase
    record appears, verify BENCH_partial.json survives with that record.
    Slow (runs a real CPU measurement phase)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("TPU_LIBRARY_PATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    partial = os.path.join(repo, "BENCH_partial.json")
    proc = subprocess.Popen([sys.executable, "bench.py"], cwd=repo,
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    try:
        deadline = time.time() + 1200
        while time.time() < deadline:
            if os.path.exists(partial) and os.path.getsize(partial) > 0:
                break
            if proc.poll() is not None:
                break
            time.sleep(2)
        else:
            pytest.fail("no phase completed within deadline")
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
            proc.wait()
    recs = _read_partial(partial)
    assert len(recs) >= 1
    assert recs[0]["phase"] == "cpu_standin"
