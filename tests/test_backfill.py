"""Concurrent backfill with progress (VERDICT r3 item 8).

Creating an MV over a large upstream proceeds in bounded per-barrier
batches while the upstream keeps ticking; live deltas for already-
backfilled pks flow immediately; progress is published via meta
notifications; a crash mid-backfill resumes from the persisted cursor
(reference: executor/backfill.rs:48-69, barrier/progress.rs).
"""

import pytest

from risingwave_tpu.frontend import Session


def _big_table_session(n_rows=2000, **kw):
    from risingwave_tpu.frontend.build import BuildConfig
    s = Session(source_chunk_capacity=64, checkpoint_frequency=2,
                config=BuildConfig(backfill_batch_rows=256), **kw)
    s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, v BIGINT)")
    for lo in range(0, n_rows, 500):
        vals = ", ".join(f"({k}, {k % 7}, {k})"
                         for k in range(lo, min(lo + 500, n_rows)))
        s.run_sql(f"INSERT INTO t VALUES {vals}")
        s.flush()
    return s


class TestConcurrentBackfill:
    def test_large_upstream_backfills_across_barriers(self):
        s = _big_table_session()
        progress = []
        s.meta.notifications.subscribe(
            "backfill", lambda v, i: progress.append(i))
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT g, count(*) AS n, sum(v) AS sv FROM t GROUP BY g")
        # batch_rows = 4*64 = 256 < 2000 rows: backfill MUST span barriers
        assert progress and not progress[-1]["done"]
        while not progress[-1]["done"]:
            s.tick()
        s.flush()
        got = {r[0]: r for r in s.mv_rows("m")}
        for g in range(7):
            ks = [k for k in range(2000) if k % 7 == g]
            assert got[g] == (g, len(ks), sum(ks))
        # multiple bounded batches were reported, monotonically
        dones = [p["rows_done"] for p in progress]
        assert len(dones) >= 4 and dones == sorted(dones)
        s.close()

    def test_live_deltas_during_backfill_are_exact(self):
        s = _big_table_session()
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT g, count(*) AS n, sum(v) AS sv FROM t GROUP BY g")
        # mutate rows at BOTH ends of the key space mid-backfill: k=0 is
        # already backfilled (delta must flow), k=1999 is not yet (its new
        # value must arrive via a later snapshot batch, not twice)
        s.run_sql("UPDATE t SET v = 100000 WHERE k = 0")
        s.run_sql("UPDATE t SET v = 200000 WHERE k = 1999")
        for _ in range(12):
            s.tick()
        s.flush()
        got = {r[0]: r for r in s.mv_rows("m")}
        ks0 = [k for k in range(2000) if k % 7 == 0]
        want_sv0 = sum(ks0) - 0 + 100000
        g1999 = 1999 % 7
        ks1 = [k for k in range(2000) if k % 7 == g1999]
        want_sv1 = sum(ks1) - 1999 + 200000
        assert got[0] == (0, len(ks0), want_sv0)
        assert got[g1999] == (g1999, len(ks1), want_sv1)
        s.close()

    def test_pipelined_barriers_with_live_updates_stay_exact(self):
        """With in_flight_barriers > 1 an upstream could run ahead of the
        backfill's snapshot reads (double-apply hazard, r4 review): the
        session pins barriers to synchronous completion while a backfill
        is active, so updates land exactly once."""
        from risingwave_tpu.frontend.build import BuildConfig
        s = Session(source_chunk_capacity=64, checkpoint_frequency=2,
                    in_flight_barriers=4,
                    config=BuildConfig(backfill_batch_rows=128))
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, g BIGINT, "
                  "v BIGINT)")
        vals = ", ".join(f"({k}, {k % 5}, {k})" for k in range(800))
        s.run_sql(f"INSERT INTO t VALUES {vals}")
        s.flush()
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT g, count(*) AS n, sum(v) AS sv FROM t GROUP BY g")
        # mutate while backfilling, under a pipelined barrier budget
        s.run_sql("UPDATE t SET v = 1000000 WHERE k = 0")
        s.run_sql("UPDATE t SET v = 2000000 WHERE k = 799")
        for _ in range(12):
            s.tick()
        s.flush()
        got = {r[0]: r for r in s.mv_rows("m")}
        ks0 = [k for k in range(800) if k % 5 == 0]
        ks4 = [k for k in range(800) if k % 5 == 799 % 5]
        assert got[0] == (0, len(ks0), sum(ks0) - 0 + 1000000)
        assert got[799 % 5] == (799 % 5, len(ks4),
                                sum(ks4) - 799 + 2000000)
        s.close()

    def test_crash_mid_backfill_resumes_from_cursor(self, tmp_path):
        d = str(tmp_path / "db")
        s = _big_table_session(n_rows=1500, data_dir=d)
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT g, count(*) AS n, sum(v) AS sv FROM t GROUP BY g")
        # advance a couple of checkpoints so a mid-backfill cursor persists
        s.tick()
        s.tick()
        s._drain_inflight()
        s.close()

        from risingwave_tpu.frontend.build import BuildConfig
        s2 = Session(source_chunk_capacity=64, checkpoint_frequency=2,
                     config=BuildConfig(backfill_batch_rows=256), data_dir=d)
        progress = []
        s2.meta.notifications.subscribe(
            "backfill", lambda v, i: progress.append(i))
        for _ in range(30):
            s2.tick()
        s2.flush()
        got = {r[0]: r for r in s2.mv_rows("m")}
        for g in range(7):
            ks = [k for k in range(1500) if k % 7 == g]
            assert got[g] == (g, len(ks), sum(ks))
        s2.close()
