"""pgwire server (coverage #61): a minimal v3-protocol client in the test
exercises startup, simple query, SHOW, errors, and NULL/date formatting."""

import asyncio
import struct

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.pgwire import PgWireServer


class MiniPgClient:
    """Just enough of the Postgres v3 protocol to drive the server."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer

    @staticmethod
    async def connect(host, port):
        reader, writer = await asyncio.open_connection(host, port)
        c = MiniPgClient(reader, writer)
        params = b"user\x00test\x00database\x00dev\x00\x00"
        body = struct.pack("!I", 196608) + params
        writer.write(struct.pack("!I", len(body) + 4) + body)
        await writer.drain()
        # drain until ReadyForQuery
        while True:
            tag, payload = await c.read_msg()
            if tag == b"Z":
                return c

    async def read_msg(self):
        hdr = await self.reader.readexactly(5)
        ln = struct.unpack("!I", hdr[1:5])[0]
        return hdr[0:1], await self.reader.readexactly(ln - 4)

    async def query(self, sql):
        body = sql.encode() + b"\x00"
        self.writer.write(b"Q" + struct.pack("!I", len(body) + 4) + body)
        await self.writer.drain()
        cols, rows, err = [], [], None
        while True:
            tag, payload = await self.read_msg()
            if tag == b"T":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                row = []
                for _ in range(n):
                    ln = struct.unpack("!i", payload[off:off + 4])[0]
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif tag == b"E":
                fields = payload.split(b"\x00")
                for f in fields:
                    if f.startswith(b"M"):
                        err = f[1:].decode()
            elif tag == b"Z":
                return cols, rows, err

    def close(self):
        self.writer.write(b"X" + struct.pack("!I", 4))
        self.writer.close()


async def _with_server(fn):
    session = Session()
    server = PgWireServer(session, "127.0.0.1", 0)
    await server.start()
    port = server._server.sockets[0].getsockname()[1]
    try:
        client = await MiniPgClient.connect("127.0.0.1", port)
        try:
            return await fn(client)
        finally:
            client.close()
    finally:
        await server.close()


class TestPgWire:
    def test_ddl_query_roundtrip(self):
        async def go(c):
            _, _, err = await c.query(
                "CREATE TABLE t (k BIGINT PRIMARY KEY, v VARCHAR, d DATE)")
            assert err is None
            _, _, err = await c.query(
                "INSERT INTO t VALUES (1, 'hello', DATE '1995-03-15'), "
                "(2, NULL, NULL)")
            assert err is None
            await c.query("FLUSH")
            cols, rows, err = await c.query("SELECT k, v, d FROM t")
            assert err is None
            assert cols == ["k", "v", "d"]
            assert sorted(rows) == [("1", "hello", "1995-03-15"),
                                    ("2", None, None)]
        asyncio.run(_with_server(go))

    def test_show_and_error(self):
        async def go(c):
            await c.query("CREATE TABLE t1 (k BIGINT PRIMARY KEY)")
            cols, rows, err = await c.query("SHOW TABLES")
            assert err is None and rows == [("t1",)]
            _, _, err = await c.query("SELECT * FROM missing_table")
            assert err is not None and "missing_table" in err
            # connection still usable after an error
            _, rows, err = await c.query("SHOW TABLES")
            assert err is None and rows == [("t1",)]
        asyncio.run(_with_server(go))

    def test_show_parameters_two_columns(self):
        async def go(c):
            cols, rows, err = await c.query("SHOW PARAMETERS")
            assert err is None
            assert cols == ["Name", "Value"]
            assert all(len(r) == 2 for r in rows)
            assert ("checkpoint_frequency", "10") in rows
        asyncio.run(_with_server(go))

    def test_mv_over_wire(self):
        async def go(c):
            await c.query("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
            _, _, err = await c.query(
                "CREATE MATERIALIZED VIEW m AS SELECT sum(v) AS s FROM t")
            assert err is None
            await c.query("INSERT INTO t VALUES (1, 10), (2, 32)")
            await c.query("FLUSH")
            _, rows, err = await c.query("SELECT s FROM m")
            assert err is None and rows == [("42",)]
        asyncio.run(_with_server(go))
