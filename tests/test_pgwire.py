"""pgwire server (coverage #61): a minimal v3-protocol client in the test
exercises startup, simple query, SHOW, errors, and NULL/date formatting."""

import asyncio
import struct

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.pgwire import PgWireServer


class MiniPgClient:
    """Just enough of the Postgres v3 protocol to drive the server."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer

    @staticmethod
    async def connect(host, port):
        reader, writer = await asyncio.open_connection(host, port)
        c = MiniPgClient(reader, writer)
        params = b"user\x00test\x00database\x00dev\x00\x00"
        body = struct.pack("!I", 196608) + params
        writer.write(struct.pack("!I", len(body) + 4) + body)
        await writer.drain()
        # drain until ReadyForQuery
        while True:
            tag, payload = await c.read_msg()
            if tag == b"Z":
                return c

    async def read_msg(self):
        hdr = await self.reader.readexactly(5)
        ln = struct.unpack("!I", hdr[1:5])[0]
        return hdr[0:1], await self.reader.readexactly(ln - 4)

    async def query(self, sql):
        body = sql.encode() + b"\x00"
        self.writer.write(b"Q" + struct.pack("!I", len(body) + 4) + body)
        await self.writer.drain()
        cols, rows, err = [], [], None
        while True:
            tag, payload = await self.read_msg()
            if tag == b"T":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                row = []
                for _ in range(n):
                    ln = struct.unpack("!i", payload[off:off + 4])[0]
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif tag == b"E":
                fields = payload.split(b"\x00")
                for f in fields:
                    if f.startswith(b"M"):
                        err = f[1:].decode()
            elif tag == b"Z":
                return cols, rows, err

    # -- extended-query flow (Parse/Bind/Describe/Execute/Sync) ------------

    def _send(self, tag: bytes, body: bytes):
        self.writer.write(tag + struct.pack("!I", len(body) + 4) + body)

    async def extended(self, sql, params=(), oids=(), stmt="", portal=""):
        """One full extended round: returns (cols, rows, err)."""
        self._send(b"P", stmt.encode() + b"\x00" + sql.encode() + b"\x00"
                   + struct.pack(f"!H{len(oids)}I", len(oids), *oids))
        bind = portal.encode() + b"\x00" + stmt.encode() + b"\x00"
        bind += struct.pack("!H", 0)                    # all-text params
        bind += struct.pack("!H", len(params))
        for p in params:
            if p is None:
                bind += struct.pack("!i", -1)
            else:
                raw = str(p).encode()
                bind += struct.pack("!i", len(raw)) + raw
        bind += struct.pack("!H", 0)
        self._send(b"B", bind)
        self._send(b"D", b"P" + portal.encode() + b"\x00")
        self._send(b"E", portal.encode() + b"\x00" + struct.pack("!i", 0))
        self._send(b"S", b"")
        await self.writer.drain()
        cols, rows, err = [], [], None
        while True:
            tag, payload = await self.read_msg()
            if tag == b"T":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                row = []
                for _ in range(n):
                    ln = struct.unpack("!i", payload[off:off + 4])[0]
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif tag == b"E":
                for f in payload.split(b"\x00"):
                    if f.startswith(b"M"):
                        err = f[1:].decode()
            elif tag == b"Z":
                return cols, rows, err

    def close(self):
        self.writer.write(b"X" + struct.pack("!I", 4))
        self.writer.close()


async def _with_server(fn):
    session = Session()
    server = PgWireServer(session, "127.0.0.1", 0)
    await server.start()
    port = server._server.sockets[0].getsockname()[1]
    try:
        client = await MiniPgClient.connect("127.0.0.1", port)
        try:
            return await fn(client)
        finally:
            client.close()
    finally:
        await server.close()


class TestPgWire:
    def test_ddl_query_roundtrip(self):
        async def go(c):
            _, _, err = await c.query(
                "CREATE TABLE t (k BIGINT PRIMARY KEY, v VARCHAR, d DATE)")
            assert err is None
            _, _, err = await c.query(
                "INSERT INTO t VALUES (1, 'hello', DATE '1995-03-15'), "
                "(2, NULL, NULL)")
            assert err is None
            await c.query("FLUSH")
            cols, rows, err = await c.query("SELECT k, v, d FROM t")
            assert err is None
            assert cols == ["k", "v", "d"]
            assert sorted(rows) == [("1", "hello", "1995-03-15"),
                                    ("2", None, None)]
        asyncio.run(_with_server(go))

    def test_show_and_error(self):
        async def go(c):
            await c.query("CREATE TABLE t1 (k BIGINT PRIMARY KEY)")
            cols, rows, err = await c.query("SHOW TABLES")
            assert err is None and rows == [("t1",)]
            _, _, err = await c.query("SELECT * FROM missing_table")
            assert err is not None and "missing_table" in err
            # connection still usable after an error
            _, rows, err = await c.query("SHOW TABLES")
            assert err is None and rows == [("t1",)]
        asyncio.run(_with_server(go))

    def test_show_parameters_two_columns(self):
        async def go(c):
            cols, rows, err = await c.query("SHOW PARAMETERS")
            assert err is None
            assert cols == ["Name", "Value"]
            assert all(len(r) == 2 for r in rows)
            assert ("checkpoint_frequency", "10") in rows
        asyncio.run(_with_server(go))

    def test_mv_over_wire(self):
        async def go(c):
            await c.query("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
            _, _, err = await c.query(
                "CREATE MATERIALIZED VIEW m AS SELECT sum(v) AS s FROM t")
            assert err is None
            await c.query("INSERT INTO t VALUES (1, 10), (2, 32)")
            await c.query("FLUSH")
            _, rows, err = await c.query("SELECT s FROM m")
            assert err is None and rows == [("42",)]
        asyncio.run(_with_server(go))


class TestExtendedQuery:
    """Parse/Bind/Describe/Execute/Sync (VERDICT r4 item 3; reference:
    pg_protocol.rs:220-259 extended dispatch)."""

    def test_parameterized_select(self):
        async def go(c):
            await c.query("CREATE TABLE t (k BIGINT PRIMARY KEY, v VARCHAR)")
            await c.query("INSERT INTO t VALUES (1, 'a'), (2, 'b'), "
                          "(3, 'a')")
            await c.query("FLUSH")
            cols, rows, err = await c.extended(
                "SELECT k FROM t WHERE v = $1 AND k > $2", params=["a", 1])
            assert err is None
            assert cols == ["k"]
            assert sorted(rows) == [("3",)]
        asyncio.run(_with_server(go))

    def test_declared_oids_and_null_param(self):
        async def go(c):
            await c.query("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
            await c.query("INSERT INTO t VALUES (1, 5), (2, NULL)")
            await c.query("FLUSH")
            # oid 20 = int8: text value inlines numerically
            cols, rows, err = await c.extended(
                "SELECT k, v + $1 AS w FROM t", params=["100"], oids=[20])
            assert err is None
            assert sorted(rows) == [("1", "105"), ("2", None)]
        asyncio.run(_with_server(go))

    def test_dollar_in_string_literal_untouched(self):
        async def go(c):
            cols, rows, err = await c.extended(
                "SELECT '$1 costs $2' AS label, $1 AS v", params=["7"])
            assert err is None
            assert rows == [("$1 costs $2", "7")]
        asyncio.run(_with_server(go))

    def test_introspect_information_schema(self):
        async def go(c):
            await c.query("CREATE TABLE widgets (k BIGINT PRIMARY KEY)")
            cols, rows, err = await c.extended(
                "SELECT table_name FROM information_schema.tables "
                "WHERE table_name = $1", params=["widgets"])
            assert err is None
            assert rows == [("widgets",)]
        asyncio.run(_with_server(go))

    def test_error_then_sync_recovers(self):
        async def go(c):
            _, _, err = await c.extended("SELECT * FROM missing", params=[])
            assert err is not None
            # after Sync the connection serves the next round cleanly
            cols, rows, err = await c.extended("SELECT 1 + 1", params=[])
            assert err is None and rows == [("2",)]
        asyncio.run(_with_server(go))

    def test_named_statement_reuse(self):
        async def go(c):
            await c.query("CREATE TABLE t (k BIGINT PRIMARY KEY)")
            await c.query("INSERT INTO t VALUES (1), (2), (3)")
            await c.query("FLUSH")
            for want in ("1", "2"):
                _, rows, err = await c.extended(
                    "SELECT k FROM t WHERE k = $1", params=[want],
                    stmt="s1")
                assert err is None and rows == [(want,)]
        asyncio.run(_with_server(go))


class TestAuth:
    """Password authentication on startup (reference:
    pg_protocol.rs:220-259; md5 = psql/psycopg2's default non-TLS flow)."""

    @staticmethod
    async def _connect_auth(host, port, user, password, method):
        import hashlib
        reader, writer = await asyncio.open_connection(host, port)
        c = MiniPgClient(reader, writer)
        params = f"user\x00{user}\x00database\x00dev\x00\x00".encode()
        body = struct.pack("!I", 196608) + params
        writer.write(struct.pack("!I", len(body) + 4) + body)
        await writer.drain()
        tag, payload = await c.read_msg()
        assert tag == b"R"
        (code,) = struct.unpack("!I", payload[:4])
        if code == 5 and method == "md5":
            salt = payload[4:8]
            inner = hashlib.md5(
                (password + user).encode()).hexdigest().encode()
            pw = "md5" + hashlib.md5(inner + salt).hexdigest()
        elif code == 3:
            pw = password
        else:
            raise AssertionError(f"unexpected auth code {code}")
        body = pw.encode() + b"\x00"
        writer.write(b"p" + struct.pack("!I", len(body) + 4) + body)
        await writer.drain()
        while True:
            tag, payload = await c.read_msg()
            if tag == b"E":
                return None
            if tag == b"Z":
                return c

    def _with_auth_server(self, fn, method="md5"):
        async def run():
            session = Session()
            server = PgWireServer(session, "127.0.0.1", 0,
                                  auth={"ada": "s3cret"},
                                  auth_method=method)
            await server.start()
            port = server._server.sockets[0].getsockname()[1]
            try:
                return await fn(port)
            finally:
                await server.close()
        return asyncio.run(run())

    def test_md5_auth_success_and_query(self):
        async def go(port):
            c = await self._connect_auth(
                "127.0.0.1", port, "ada", "s3cret", "md5")
            assert c is not None
            try:
                cols, rows, err = await c.query("SELECT 1 + 1")
                assert err is None and rows == [("2",)]
            finally:
                c.close()
        self._with_auth_server(go)

    def test_md5_auth_wrong_password_rejected(self):
        async def go(port):
            c = await self._connect_auth(
                "127.0.0.1", port, "ada", "wrong", "md5")
            assert c is None
        self._with_auth_server(go)

    def test_cleartext_auth(self):
        async def go(port):
            ok = await self._connect_auth(
                "127.0.0.1", port, "ada", "s3cret", "cleartext")
            assert ok is not None
            bad = await self._connect_auth(
                "127.0.0.1", port, "nobody", "s3cret", "cleartext")
            assert bad is None
        self._with_auth_server(go, method="cleartext")
