"""Optimizer rule engine: golden plan tests + semantic preservation.

planner_test-style (reference: src/frontend/planner_test/tests/testdata/
— yaml of sql → expected plan): each query's optimized EXPLAIN output is
compared against tests/plans/golden_plans.txt. Regenerate with
``UPDATE_GOLDEN=1 python -m pytest tests/test_optimizer.py``.
"""

import os

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.optimizer import (
    expr_refs, optimize, prune_columns, remap_expr, rewrite_fixpoint,
    PUSHDOWN_RULES,
)

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "plans",
                           "golden_plans.txt")

DDL = [
    "CREATE TABLE customer (c_custkey BIGINT PRIMARY KEY, c_name VARCHAR, "
    "c_acctbal DOUBLE, c_nationkey BIGINT, c_mktsegment VARCHAR)",
    "CREATE TABLE orders (o_orderkey BIGINT PRIMARY KEY, o_custkey BIGINT, "
    "o_orderdate TIMESTAMP, o_shippriority INT, o_totalprice DOUBLE)",
    "CREATE TABLE lineitem (l_orderkey BIGINT, l_linenumber BIGINT, "
    "l_extendedprice DOUBLE, l_discount DOUBLE, l_quantity DOUBLE, "
    "PRIMARY KEY (l_orderkey, l_linenumber))",
    "CREATE TABLE nation (n_nationkey BIGINT PRIMARY KEY, n_name VARCHAR)",
]

# name → SQL. The golden file keys on the name.
QUERIES = {
    # filter pushdown through a projection
    "filter_through_project":
        "SELECT c FROM (SELECT c_custkey AS c, c_acctbal AS b "
        "FROM customer) t WHERE c > 10",
    # conjunct routing into both join sides
    "filter_into_join_both_sides":
        "SELECT o_orderkey FROM orders JOIN customer "
        "ON o_custkey = c_custkey "
        "WHERE c_mktsegment = 'BUILDING' AND o_shippriority = 1",
    # left join: only the preserved side's predicate may push
    "filter_left_join_preserved_only":
        "SELECT o_orderkey FROM orders LEFT JOIN customer "
        "ON o_custkey = c_custkey "
        "WHERE o_shippriority = 1 AND c_acctbal > 0",
    # group-key predicate pushes below the agg; HAVING stays above
    "filter_below_agg":
        "SELECT o_custkey, count(*) AS n FROM orders "
        "GROUP BY o_custkey HAVING count(*) > 1",
    "filter_key_pred_below_agg":
        "SELECT k, n FROM (SELECT o_custkey AS k, count(*) AS n "
        "FROM orders GROUP BY o_custkey) t WHERE k = 7",
    # filter through UNION ALL arms
    "filter_through_union":
        "SELECT * FROM (SELECT o_orderkey AS k FROM orders UNION ALL "
        "SELECT c_custkey AS k FROM customer) t WHERE k < 100",
    # column pruning: wide scans narrow to what the query reads
    "prune_scan_columns":
        "SELECT c_name FROM customer",
    "prune_join_columns":
        "SELECT c_name, o_totalprice FROM orders JOIN customer "
        "ON o_custkey = c_custkey",
    "prune_unused_agg":
        "SELECT k FROM (SELECT o_custkey AS k, count(*) AS n, "
        "sum(o_totalprice) AS s FROM orders GROUP BY o_custkey) t",
    # merged stacked projections
    "project_merge":
        "SELECT a + 1 AS b FROM (SELECT c_custkey * 2 AS a "
        "FROM customer) t",
    # comparison scalar subquery still lowers to DynamicFilter
    "dynamic_filter_subquery":
        "SELECT o_orderkey FROM orders WHERE o_totalprice > "
        "(SELECT max(c_acctbal) FROM customer)",
    # TPC-H q3 shape (join-join-agg-topn)
    "tpch_q3":
        "SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) "
        "AS revenue, o_orderdate, o_shippriority "
        "FROM customer, orders, lineitem "
        "WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey "
        "AND l_orderkey = o_orderkey "
        "GROUP BY l_orderkey, o_orderdate, o_shippriority "
        "ORDER BY revenue DESC LIMIT 10",
    # TPC-H q10 shape
    "tpch_q10":
        "SELECT c_custkey, c_name, "
        "sum(l_extendedprice * (1 - l_discount)) AS revenue, n_name "
        "FROM customer, orders, lineitem, nation "
        "WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey "
        "AND c_nationkey = n_nationkey "
        "GROUP BY c_custkey, c_name, n_name "
        "ORDER BY revenue DESC LIMIT 20",
    # semi-hidden pk column kept alive by pruning
    "prune_keeps_stream_key":
        "SELECT c_mktsegment FROM customer WHERE c_acctbal > 0",
    "topn_order_col_kept":
        "SELECT c_name, c_acctbal FROM customer "
        "ORDER BY c_acctbal DESC LIMIT 3",
}


@pytest.fixture(scope="module")
def session():
    s = Session()
    for ddl in DDL:
        s.run_sql(ddl)
    return s


def _render(session) -> str:
    blocks = []
    for name in sorted(QUERIES):
        rows = session.run_sql("EXPLAIN " + QUERIES[name])
        plan = "\n".join(r[0] for r in rows)
        blocks.append(f"== {name}\n{plan}\n")
    return "\n".join(blocks)


def test_golden_plans(session):
    rendered = _render(session)
    if os.environ.get("UPDATE_GOLDEN"):
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as f:
            f.write(rendered)
        pytest.skip("golden file regenerated")
    with open(GOLDEN_PATH) as f:
        expected = f.read()
    assert rendered == expected, (
        "optimized plans changed; review the diff and regenerate with "
        "UPDATE_GOLDEN=1 if intended")


def test_pushdown_reaches_scan(session):
    rows = session.run_sql(
        "EXPLAIN " + QUERIES["filter_into_join_both_sides"])
    lines = [r[0] for r in rows]
    # both predicates sit below the join (deeper indent), none above
    join_at = next(i for i, l in enumerate(lines) if "HashJoin" in l)
    filters = [i for i, l in enumerate(lines) if "Filter" in l]
    assert filters and all(i > join_at for i in filters)


def test_prune_narrows_wide_scan(session):
    rows = session.run_sql("EXPLAIN " + QUERIES["prune_scan_columns"])
    text = "\n".join(r[0] for r in rows)
    # customer has 5 columns; the scan-narrowing projection keeps 2
    # (c_name + the pk), visible as a 2-expr Project over the scan
    assert "exprs=['$1', '$0']" in text or "exprs=['$0', '$1']" in text


def test_prune_drops_unused_agg_call(session):
    rows = session.run_sql("EXPLAIN " + QUERIES["prune_unused_agg"])
    text = "\n".join(r[0] for r in rows)
    assert "count" not in text and "sum" not in text


class TestSemanticsPreserved:
    """Optimized plans must return the same rows (batch path)."""

    @pytest.fixture(scope="class")
    def data_session(self):
        s = Session()
        for ddl in DDL:
            s.run_sql(ddl)
        s.run_sql(
            "INSERT INTO customer VALUES "
            "(1, 'alice', 100.0, 10, 'BUILDING'), "
            "(2, 'bob', -5.0, 20, 'AUTO'), "
            "(3, 'carol', 50.0, 10, 'BUILDING')")
        s.run_sql(
            "INSERT INTO orders VALUES "
            "(100, 1, timestamp '1995-03-01 00:00:00', 1, 1000.0), "
            "(101, 1, timestamp '1995-03-02 00:00:00', 2, 500.0), "
            "(102, 3, timestamp '1995-03-03 00:00:00', 1, 700.0), "
            "(103, 2, timestamp '1995-03-04 00:00:00', 1, 900.0)")
        s.run_sql(
            "INSERT INTO lineitem VALUES "
            "(100, 1, 1000.0, 0.1, 1.0), (100, 2, 500.0, 0.0, 2.0), "
            "(101, 1, 800.0, 0.05, 3.0), (102, 1, 700.0, 0.2, 1.0)")
        s.run_sql("INSERT INTO nation VALUES (10, 'GERMANY'), (20, 'FRANCE')")
        s.flush()
        return s

    def test_join_filter(self, data_session):
        out = data_session.run_sql(
            "SELECT o_orderkey FROM orders JOIN customer "
            "ON o_custkey = c_custkey "
            "WHERE c_mktsegment = 'BUILDING' AND o_shippriority = 1")
        assert sorted(out) == [(100,), (102,)]

    def test_left_join_filter(self, data_session):
        out = data_session.run_sql(
            "SELECT o_orderkey, c_name FROM orders LEFT JOIN customer "
            "ON o_custkey = c_custkey WHERE o_shippriority = 1")
        assert sorted(out) == [(100, "alice"), (102, "carol"),
                               (103, "bob")]

    def test_agg_pushdown(self, data_session):
        out = data_session.run_sql(
            "SELECT k, n FROM (SELECT o_custkey AS k, count(*) AS n "
            "FROM orders GROUP BY o_custkey) t WHERE k = 1")
        assert out == [(1, 2)]

    def test_pruned_join_agg(self, data_session):
        out = data_session.run_sql(
            "SELECT c_name, count(*) AS n FROM orders JOIN customer "
            "ON o_custkey = c_custkey GROUP BY c_name")
        assert sorted(out) == [("alice", 2), ("bob", 1), ("carol", 1)]

    def test_union_filter(self, data_session):
        out = data_session.run_sql(
            "SELECT * FROM (SELECT o_orderkey AS k FROM orders UNION ALL "
            "SELECT c_custkey AS k FROM customer) t WHERE k < 101")
        assert sorted(out) == [(1,), (2,), (3,), (100,)]

    def test_streaming_mv_on_optimized_plan(self, data_session):
        s = data_session
        s.run_sql(
            "CREATE MATERIALIZED VIEW opt_mv AS "
            "SELECT c_name, count(*) AS n FROM orders JOIN customer "
            "ON o_custkey = c_custkey "
            "WHERE c_mktsegment = 'BUILDING' GROUP BY c_name")
        s.flush()
        assert sorted(s.mv_rows("opt_mv")) == [("alice", 2), ("carol", 1)]
        s.run_sql(
            "INSERT INTO orders VALUES "
            "(104, 3, timestamp '1995-04-01 00:00:00', 2, 50.0)")
        s.flush()
        assert sorted(s.mv_rows("opt_mv")) == [("alice", 2), ("carol", 2)]
        s.run_sql("DELETE FROM orders WHERE o_orderkey = 100")
        s.flush()
        assert sorted(s.mv_rows("opt_mv")) == [("alice", 1), ("carol", 2)]
