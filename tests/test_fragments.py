"""Multi-fragment pipelines over the dispatch fabric (VERDICT r3 item 4).

A grouped-agg MV built with ``fragment_parallelism > 1`` runs as real
fragments: upstream → HashDispatcher → PermitChannels → N agg actors →
MergeExecutor → Materialize. Equivalence vs the fused single-fragment
build is the oracle; the update-pair splitting rule, permit backpressure,
and recovery across a parallelism change are each exercised end-to-end
(reference: dispatch.rs:532,635-650; merge.rs:114; exchange/permit.rs:35).
"""

import pytest

from risingwave_tpu.frontend import Session
from risingwave_tpu.frontend.build import BuildConfig

NEXMARK_DDL = """CREATE SOURCE bid (auction BIGINT, price BIGINT)
    WITH (connector = 'nexmark', nexmark_table = 'bid')"""


class TestFragmentedJoin:
    """A streaming equi-join built as TWO upstream fragments hash-dispatching
    both sides by join key to N join actors (dispatch.rs:532); equivalence
    vs the fused single-fragment build is the oracle."""

    def _run_join(self, cfg):
        s = Session(config=cfg)
        s.run_sql("CREATE TABLE l (k BIGINT PRIMARY KEY, a BIGINT)")
        s.run_sql("CREATE TABLE r (k BIGINT PRIMARY KEY, b BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW j AS "
                  "SELECT l.k AS k, l.a AS a, r.b AS b "
                  "FROM l JOIN r ON l.k = r.k")
        s.run_sql("INSERT INTO l VALUES (1, 10), (2, 20), (3, 30), "
                  "(4, 40), (5, 50), (6, 60)")
        s.run_sql("INSERT INTO r VALUES (2, 200), (3, 300), (6, 600), "
                  "(7, 700)")
        s.flush()
        # deletes + key-moving updates cross shard boundaries
        s.run_sql("DELETE FROM r WHERE k = 2")
        s.run_sql("UPDATE l SET k = 7 WHERE k = 1")
        s.flush()
        rows = sorted(s.mv_rows("j"))
        s.close()
        return rows

    def test_two_fragments_equal_fused(self):
        fused = self._run_join(BuildConfig())
        frag = self._run_join(_frag_cfg(2))
        assert frag == fused and len(fused) > 0

    def test_three_fragments_outer_join(self):
        def run(cfg):
            s = Session(config=cfg)
            s.run_sql("CREATE TABLE l (k BIGINT PRIMARY KEY, a BIGINT)")
            s.run_sql("CREATE TABLE r (k BIGINT PRIMARY KEY, b BIGINT)")
            s.run_sql("CREATE MATERIALIZED VIEW j AS "
                      "SELECT l.k AS k, r.b AS b "
                      "FROM l LEFT JOIN r ON l.k = r.k")
            s.run_sql("INSERT INTO l VALUES (1, 1), (2, 2), (3, 3), (4, 4)")
            s.run_sql("INSERT INTO r VALUES (2, 20), (4, 40)")
            s.flush()
            s.run_sql("DELETE FROM r WHERE k = 4")   # revert to null-padded
            s.flush()
            rows = sorted(s.mv_rows("j"), key=repr)
            s.close()
            return rows

        assert run(_frag_cfg(3)) == run(BuildConfig())

MV_SQL = ("CREATE MATERIALIZED VIEW m AS "
          "SELECT auction, count(*) AS n, sum(price) AS s, max(price) AS p "
          "FROM bid GROUP BY auction")


def _frag_cfg(n=2, permits=32):
    return BuildConfig(fragment_parallelism=n, exchange_permits=permits)


def _run(cfg, ticks=6):
    s = Session(config=cfg, source_chunk_capacity=128,
                checkpoint_frequency=3)
    s.run_sql(NEXMARK_DDL)
    s.run_sql(MV_SQL)
    for _ in range(ticks):
        s.tick()
    s.flush()
    rows = sorted(s.mv_rows("m"))
    s.close()
    return rows


class TestFragmentedAgg:
    def test_two_fragments_equal_fused(self):
        fused = _run(BuildConfig())
        frag = _run(_frag_cfg(2))
        assert frag == fused and len(fused) > 0

    def test_four_fragments_equal_fused(self):
        fused = _run(BuildConfig())
        frag = _run(_frag_cfg(4))
        assert frag == fused

    def test_permit_backpressure_tight_budget(self):
        """permits=1 forces the upstream actor to block on channel credit
        every chunk; the job must still complete correctly (barriers never
        queue behind data — exchange/permit.rs:35 contract)."""
        fused = _run(BuildConfig())
        frag = _run(_frag_cfg(2, permits=1))
        assert frag == fused

    def test_update_pair_splitting_end_to_end(self):
        """An UPDATE that moves a row's group key across shards sends the
        U-/U+ pair to different agg actors as plain Delete+Insert
        (dispatch.rs:635-650); totals stay exact."""
        def run(cfg):
            s = Session(config=cfg)
            s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, cat BIGINT, "
                      "v BIGINT)")
            s.run_sql("CREATE MATERIALIZED VIEW m AS "
                      "SELECT cat, count(*) AS n, sum(v) AS sv "
                      "FROM t GROUP BY cat")
            s.run_sql("INSERT INTO t VALUES (1, 0, 10), (2, 1, 20), "
                      "(3, 2, 30), (4, 3, 40), (5, 0, 50)")
            s.flush()
            # move k=1 and k=3 into other groups: U-/U+ pairs whose keys
            # hash to different shards must split
            s.run_sql("UPDATE t SET cat = 3 WHERE k = 1")
            s.run_sql("UPDATE t SET cat = 1 WHERE k = 3")
            s.flush()
            rows = sorted(s.mv_rows("m"))
            s.close()
            return rows

        fused = run(BuildConfig())
        frag = run(_frag_cfg(2))
        assert frag == fused
        assert fused == [(0, 1, 50), (1, 2, 50), (3, 2, 50)]

    def test_recovery_across_parallelism_change_join(self, tmp_path):
        """Fragmented JOIN state persists through a crash and reloads under
        a DIFFERENT fragment parallelism: each join actor filters the two
        shared state tables by the vnode of its join key."""
        d = str(tmp_path / "jdb")
        s = Session(config=_frag_cfg(2), data_dir=d, checkpoint_frequency=1)
        s.run_sql("CREATE TABLE l (k BIGINT PRIMARY KEY, a BIGINT)")
        s.run_sql("CREATE TABLE r (k BIGINT PRIMARY KEY, b BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW j AS "
                  "SELECT l.k AS k, l.a AS a, r.b AS b "
                  "FROM l JOIN r ON l.k = r.k")
        s.run_sql("INSERT INTO l VALUES (1, 10), (2, 20), (3, 30), (4, 40)")
        s.run_sql("INSERT INTO r VALUES (2, 200), (3, 300), (5, 500)")
        s.flush()
        want = sorted(s.mv_rows("j"))
        assert want == [(2, 20, 200), (3, 30, 300)]
        s.close()

        s2 = Session(config=_frag_cfg(3), data_dir=d, checkpoint_frequency=1)
        assert sorted(s2.mv_rows("j")) == want
        # joins keep maintaining incrementally after recovery — new rows on
        # BOTH sides must probe recovered state on the right shard
        s2.run_sql("INSERT INTO r VALUES (1, 100)")
        s2.run_sql("INSERT INTO l VALUES (5, 50)")
        s2.flush()
        assert sorted(s2.mv_rows("j")) == [
            (1, 10, 100), (2, 20, 200), (3, 30, 300), (5, 50, 500)]
        s2.close()

    def test_recovery_across_parallelism_change(self, tmp_path):
        """Fragmented MV state persists through a crash and reloads under a
        DIFFERENT fragment parallelism: every actor filters the shared
        state table by its shard (the vnode-reassignment reload)."""
        d = str(tmp_path / "db")
        s = Session(config=_frag_cfg(2), data_dir=d,
                    source_chunk_capacity=128, checkpoint_frequency=2)
        s.run_sql(NEXMARK_DDL)
        s.run_sql(MV_SQL)
        for _ in range(4):
            s.tick()
        s.flush()
        want = sorted(s.mv_rows("m"))
        s.close()

        # recover under parallelism 3 (recovery rebuilds with the
        # session's config — shard layout changes; reload must follow)
        s2 = Session(config=_frag_cfg(3), data_dir=d,
                     source_chunk_capacity=128, checkpoint_frequency=2)
        assert sorted(s2.mv_rows("m")) == want
        # and it keeps maintaining incrementally after recovery
        s2.tick()
        s2.flush()
        after = sorted(s2.mv_rows("m"))
        assert sum(r[1] for r in after) > sum(r[1] for r in want)
        s2.close()
