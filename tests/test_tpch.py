"""TPC-H q3/q10 as streaming MVs (VERDICT r2 item 10; BASELINE.md config 4)
plus the expression surface they need: date literals, EXTRACT, LIKE,
string functions over dictionary ids, and fixed-point decimal arithmetic.
Expected outputs are recomputed by plain-Python host models in the tests.
Reference workloads: /root/reference e2e_test/tpch/, streaming q3/q10.
"""

import datetime as dt

import pytest

from risingwave_tpu.frontend import Session

EPOCH = dt.date(1970, 1, 1)


def d(s):
    return (dt.date.fromisoformat(s) - EPOCH).days


CUSTOMERS = [
    # c_custkey, c_name, c_address, c_nationkey, c_phone, c_acctbal,
    # c_mktsegment, c_comment
    (1, "Customer#1", "addr1", 10, "11-123", 100.25, "BUILDING", "c1"),
    (2, "Customer#2", "addr2", 20, "22-456", 200.50, "AUTOMOBILE", "c2"),
    (3, "Customer#3", "addr3", 10, "33-789", 300.75, "BUILDING", "c3"),
]

ORDERS = [
    # o_orderkey, o_custkey, o_orderdate, o_shippriority
    (100, 1, "1995-03-01", 1),
    (101, 1, "1995-04-01", 2),    # after cutoff for q3
    (102, 3, "1995-03-10", 3),
    (103, 2, "1993-11-15", 4),    # in q10 window
    (104, 1, "1993-12-20", 5),    # in q10 window
]

LINEITEM = [
    # l_orderkey, l_linenumber, l_extendedprice, l_discount, l_shipdate,
    # l_returnflag
    (100, 1, 1000.00, 0.10, "1995-03-20", "N"),
    (100, 2, 500.00, 0.00, "1995-03-10", "N"),    # shipdate too early for q3
    (101, 1, 800.00, 0.05, "1995-04-10", "N"),
    (102, 1, 700.00, 0.20, "1995-03-25", "R"),
    (103, 1, 900.00, 0.10, "1993-12-01", "R"),
    (104, 1, 600.00, 0.00, "1994-01-05", "R"),
]

NATION = [(10, "GERMANY"), (20, "FRANCE")]


def _setup():
    s = Session()
    s.run_sql("""CREATE TABLE customer (
        c_custkey BIGINT PRIMARY KEY, c_name VARCHAR, c_address VARCHAR,
        c_nationkey BIGINT, c_phone VARCHAR, c_acctbal DECIMAL,
        c_mktsegment VARCHAR, c_comment VARCHAR)""")
    s.run_sql("""CREATE TABLE orders (
        o_orderkey BIGINT PRIMARY KEY, o_custkey BIGINT,
        o_orderdate DATE, o_shippriority BIGINT)""")
    s.run_sql("""CREATE TABLE lineitem (
        l_orderkey BIGINT, l_linenumber BIGINT, l_extendedprice DECIMAL,
        l_discount DECIMAL, l_shipdate DATE, l_returnflag VARCHAR,
        PRIMARY KEY (l_orderkey, l_linenumber))""")
    s.run_sql("""CREATE TABLE nation (
        n_nationkey BIGINT PRIMARY KEY, n_name VARCHAR)""")
    for c in CUSTOMERS:
        s.run_sql(
            "INSERT INTO customer VALUES "
            f"({c[0]}, '{c[1]}', '{c[2]}', {c[3]}, '{c[4]}', {c[5]}, "
            f"'{c[6]}', '{c[7]}')")
    for o in ORDERS:
        s.run_sql("INSERT INTO orders VALUES "
                  f"({o[0]}, {o[1]}, DATE '{o[2]}', {o[3]})")
    for l in LINEITEM:
        s.run_sql("INSERT INTO lineitem VALUES "
                  f"({l[0]}, {l[1]}, {l[2]}, {l[3]}, DATE '{l[4]}', "
                  f"'{l[5]}')")
    for n in NATION:
        s.run_sql(f"INSERT INTO nation VALUES ({n[0]}, '{n[1]}')")
    s.flush()
    return s


def _q3_host():
    cut = dt.date.fromisoformat("1995-03-15")
    rev = {}
    for c in CUSTOMERS:
        if c[6] != "BUILDING":
            continue
        for o in ORDERS:
            if o[1] != c[0] or dt.date.fromisoformat(o[2]) >= cut:
                continue
            for l in LINEITEM:
                if l[0] != o[0] or dt.date.fromisoformat(l[4]) <= cut:
                    continue
                key = (o[0], o[2], o[3])
                rev[key] = round(rev.get(key, 0.0)
                                 + l[2] * (1 - l[3]), 4)
    return rev


class TestQ3:
    def test_q3_streaming_mv(self):
        s = _setup()
        s.run_sql("""CREATE MATERIALIZED VIEW q3 AS
            SELECT o_orderkey, sum(l_extendedprice * (1 - l_discount))
                       AS revenue,
                   o_orderdate, o_shippriority
            FROM customer, orders, lineitem
            WHERE c_mktsegment = 'BUILDING'
              AND c_custkey = o_custkey
              AND l_orderkey = o_orderkey
              AND o_orderdate < DATE '1995-03-15'
              AND l_shipdate > DATE '1995-03-15'
            GROUP BY o_orderkey, o_orderdate, o_shippriority""")
        s.flush()
        got = {(r[0], r[2], r[3]): round(float(r[1]), 4)
               for r in s.mv_rows("q3")}
        expect = {(k[0], d(k[1]), k[2]): v for k, v in _q3_host().items()}
        assert got == expect
        # incremental: a new qualifying lineitem updates the revenue
        s.run_sql("INSERT INTO lineitem VALUES "
                  "(100, 3, 200.00, 0.00, DATE '1995-03-18', 'N')")
        s.flush()
        got = {r[0]: round(float(r[1]), 4) for r in s.mv_rows("q3")}
        assert got[100] == round(1000.00 * 0.9 + 200.00, 4)


def _q10_host():
    lo, hi = dt.date.fromisoformat("1993-10-01"), dt.date.fromisoformat("1994-01-01")
    nations = dict(NATION)
    rev = {}
    for c in CUSTOMERS:
        for o in ORDERS:
            if o[1] != c[0]:
                continue
            od = dt.date.fromisoformat(o[2])
            if not (lo <= od < hi):
                continue
            for l in LINEITEM:
                if l[0] != o[0] or l[5] != "R":
                    continue
                key = (c[0], c[1], nations[c[3]])
                rev[key] = round(rev.get(key, 0.0) + l[2] * (1 - l[3]), 4)
    return rev


class TestQ10:
    def test_q10_streaming_mv(self):
        s = _setup()
        s.run_sql("""CREATE MATERIALIZED VIEW q10 AS
            SELECT c_custkey, c_name,
                   sum(l_extendedprice * (1 - l_discount)) AS revenue,
                   n_name
            FROM customer, orders, lineitem, nation
            WHERE c_custkey = o_custkey
              AND l_orderkey = o_orderkey
              AND o_orderdate >= DATE '1993-10-01'
              AND o_orderdate < DATE '1994-01-01'
              AND l_returnflag = 'R'
              AND c_nationkey = n_nationkey
            GROUP BY c_custkey, c_name, n_name""")
        s.flush()
        got = {(r[0], r[1], r[3]): round(float(r[2]), 4)
               for r in s.mv_rows("q10")}
        assert got == _q10_host()


class TestExprSurface:
    def test_like_and_strings(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, s VARCHAR)")
        s.run_sql("INSERT INTO t VALUES (1, 'hello world'), (2, 'HELLO'), "
                  "(3, 'spark'), (4, 'h_x')")
        s.flush()
        assert sorted(r[0] for r in s.run_sql(
            "SELECT k FROM t WHERE s LIKE 'h%'")) == [1, 4]
        # case-sensitive: 'HELLO' has no lowercase 'o'
        assert sorted(r[0] for r in s.run_sql(
            "SELECT k FROM t WHERE s NOT LIKE '%o%'")) == [2, 3, 4]
        assert sorted(r[0] for r in s.run_sql(
            "SELECT k FROM t WHERE lower(s) LIKE 'hello%'")) == [1, 2]
        rows = dict(s.run_sql("SELECT k, upper(s) FROM t"))
        assert rows[1] == "HELLO WORLD" and rows[3] == "SPARK"
        rows = dict(s.run_sql("SELECT k, s || '!' FROM t"))
        assert rows[2] == "HELLO!"
        rows = dict(s.run_sql("SELECT k, length(s) FROM t"))
        assert rows[1] == 11 and rows[4] == 3
        rows = dict(s.run_sql("SELECT k, substr(s, 1, 5) FROM t"))
        assert rows[1] == "hello"

    def test_substr_pg_semantics_and_like_escape(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, s VARCHAR)")
        s.run_sql("INSERT INTO t VALUES (1, 'hello'), (2, 'a%b')")
        s.flush()
        # start below 1 consumes length before the string begins (PG)
        rows = dict(s.run_sql("SELECT k, substr(s, 0, 3) FROM t"))
        assert rows[1] == "he"
        # backslash escapes a literal % in LIKE
        got = sorted(r[0] for r in s.run_sql(
            r"SELECT k FROM t WHERE s LIKE 'a\%b'"))
        assert got == [2]

    def test_like_rejects_non_varchar(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY)")
        with pytest.raises(Exception, match="varchar"):
            s.run_sql("SELECT k FROM t WHERE k LIKE '1%'")
        with pytest.raises(Exception, match="varchar"):
            s.run_sql("SELECT k || 'x' FROM t")

    def test_extract_fields(self):
        s = Session()
        s.run_sql("CREATE TABLE e (k BIGINT PRIMARY KEY, dd DATE, "
                  "ts TIMESTAMP)")
        s.run_sql("INSERT INTO e VALUES (1, DATE '1995-03-15', "
                  "TIMESTAMP '1995-03-15 13:45:30')")
        s.flush()
        row = s.run_sql(
            "SELECT extract(year FROM dd), extract(month FROM dd), "
            "extract(day FROM dd), extract(quarter FROM dd), "
            "extract(dow FROM dd), extract(hour FROM ts), "
            "extract(minute FROM ts), extract(second FROM ts) FROM e")[0]
        # 1995-03-15 was a Wednesday (dow=3)
        assert row == (1995, 3, 15, 1, 3, 13, 45, 30)

    def test_decimal_arithmetic(self):
        s = Session()
        s.run_sql("CREATE TABLE p (k BIGINT PRIMARY KEY, price DECIMAL, "
                  "disc DECIMAL)")
        s.run_sql("INSERT INTO p VALUES (1, 100.50, 0.10), (2, 99.99, 0.00)")
        s.flush()
        rows = dict(s.run_sql("SELECT k, price * (1 - disc) FROM p"))
        assert rows[1] == pytest.approx(90.45)
        assert rows[2] == pytest.approx(99.99)
        rows = dict(s.run_sql("SELECT k, price + disc FROM p"))
        assert rows[1] == pytest.approx(100.60)
        # comparisons align scales
        assert sorted(r[0] for r in s.run_sql(
            "SELECT k FROM p WHERE price > 100")) == [1]

    def test_string_predicate_in_join_condition(self):
        """Host-tier LIKE in an inner-join ON clause is hoisted into a
        post-join filter (it cannot run inside the jitted join core)."""
        s = Session()
        s.run_sql("CREATE TABLE a (k BIGINT PRIMARY KEY, nm VARCHAR)")
        s.run_sql("CREATE TABLE b (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("INSERT INTO a VALUES (1, 'xray'), (2, 'young')")
        s.run_sql("INSERT INTO b VALUES (1, 10), (2, 20)")
        s.flush()
        rows = s.run_sql("SELECT a.k, v FROM a JOIN b ON a.k = b.k "
                         "AND nm LIKE 'x%'")
        assert rows == [(1, 10)]

    def test_decimal_case_and_coalesce_alignment(self):
        s = Session()
        s.run_sql("CREATE TABLE p (k BIGINT PRIMARY KEY, d DECIMAL)")
        s.run_sql("INSERT INTO p VALUES (1, 2.50), (2, NULL)")
        s.flush()
        rows = dict(s.run_sql(
            "SELECT k, CASE WHEN d > 2 THEN d ELSE 1 END FROM p"))
        assert rows[1] == pytest.approx(2.5)
        assert rows[2] == pytest.approx(1.0)     # int ELSE scaled correctly
        rows = dict(s.run_sql("SELECT k, coalesce(d, 5) FROM p"))
        assert rows[2] == pytest.approx(5.0)

    def test_date_timestamp_cast_units(self):
        s = Session()
        s.run_sql("CREATE TABLE e (k BIGINT PRIMARY KEY, dd DATE)")
        s.run_sql("INSERT INTO e VALUES (1, DATE '1995-03-15')")
        s.flush()
        rows = s.run_sql("SELECT extract(year FROM CAST(dd AS TIMESTAMP)), "
                         "extract(hour FROM CAST(dd AS TIMESTAMP)) FROM e")
        assert rows == [(1995, 0)]

    def test_decimal_narrowing_rounds(self):
        s = Session()
        s.run_sql("CREATE TABLE p (k BIGINT PRIMARY KEY, d DECIMAL)")
        s.run_sql("INSERT INTO p VALUES (1, 9.99), (2, -9.99)")
        s.flush()
        rows = dict(s.run_sql("SELECT k, CAST(d AS BIGINT) FROM p"))
        assert rows[1] == 10 and rows[2] == -10   # round, not truncate

    def test_date_comparison_and_topn_desc(self):
        s = Session()
        s.run_sql("CREATE TABLE o (k BIGINT PRIMARY KEY, od DATE)")
        s.run_sql("INSERT INTO o VALUES (1, DATE '1995-01-01'), "
                  "(2, DATE '1995-06-01'), (3, DATE '1994-01-01')")
        s.flush()
        assert sorted(r[0] for r in s.run_sql(
            "SELECT k FROM o WHERE od < DATE '1995-03-15'")) == [1, 3]
        # (ORDER BY must reference an output column — planner limitation)
        rows = s.run_sql("SELECT k, od FROM o ORDER BY od DESC LIMIT 2")
        assert [r[0] for r in rows] == [2, 1]
