"""Source connector framework + sinks (VERDICT r2 item 6).

Covers: datagen split reader determinism + seek, format parsers, file
source offsets, CREATE SINK (blackhole + file) e2e, split-state recovery
(source offsets survive a crash), and file-sink exactly-once across a real
process kill.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from risingwave_tpu.common.chunk import chunk_to_rows
from risingwave_tpu.common.types import (
    INT64, FLOAT64, VARCHAR, Field, Schema,
)
from risingwave_tpu.connector.datagen import DatagenReader
from risingwave_tpu.connector.filesource import FileSourceReader
from risingwave_tpu.connector.parsers import parse_csv_lines, parse_json_lines
from risingwave_tpu.frontend import Session

SCHEMA = Schema((Field("k", INT64), Field("x", FLOAT64)))


def _rows(reader, chunk):
    return chunk_to_rows(chunk, reader.schema)


class TestDatagen:
    def test_sequence_and_seek_determinism(self):
        opts = {"datagen.split.num": 2, "datagen.rows.per.chunk": 4}
        r1 = DatagenReader(SCHEMA, opts)
        first = _rows(r1, r1.next_chunk())
        mark = r1.offsets
        rest = [_rows(r1, r1.next_chunk()) for _ in range(3)]

        r2 = DatagenReader(SCHEMA, opts)
        r2.seek(mark)
        rest2 = [_rows(r2, r2.next_chunk()) for _ in range(3)]
        assert rest == rest2
        # sequence fields interleave across splits: union is contiguous
        allk = sorted(r[0] for rows in [first] + rest for r in rows)
        assert allk == list(range(len(allk)))

    def test_bounded(self):
        r = DatagenReader(SCHEMA, {"datagen.rows.per.chunk": 4,
                                   "datagen.max.rows": 10})
        total = 0
        while (c := r.next_chunk()) is not None:
            total += len(_rows(r, c))
        assert total == 10
        assert r.next_chunk() is None


class TestParsers:
    def test_json(self):
        text = '{"k": 1, "x": 2.5}\n\n{"x": 1.0, "k": 2, "junk": 9}\n{"k": 3}'
        rows = parse_json_lines(text, SCHEMA)
        assert rows == [(1, 2.5), (2, 1.0), (3, None)]

    def test_csv(self):
        text = "x,k\n2.5,1\n,2"
        assert parse_csv_lines(text, SCHEMA) == [(1, 2.5), (2, None)]
        text2 = "1,2.5\n2,"
        assert parse_csv_lines(text2, SCHEMA, has_header=False) == \
            [(1, 2.5), (2, None)]


class TestFileSource:
    def test_jsonl_offsets_and_growth(self, tmp_path):
        p = tmp_path / "events.jsonl"
        p.write_text("\n".join(json.dumps({"k": i, "x": i * 0.5})
                               for i in range(5)))
        r = FileSourceReader(SCHEMA, str(p), rows_per_chunk=3)
        c1 = _rows(r, r.next_chunk())
        assert [row[0] for row in c1] == [0, 1, 2]
        assert r.offsets[str(p)] == 3
        c2 = _rows(r, r.next_chunk())
        assert [row[0] for row in c2] == [3, 4]
        assert r.next_chunk() is None
        # appended lines are picked up from the stored offset
        with open(p, "a") as f:
            f.write("\n" + json.dumps({"k": 99, "x": 0.0}))
        c3 = _rows(r, r.next_chunk())
        assert [row[0] for row in c3] == [99]


class TestSinkSql:
    def test_blackhole_sink_from_table(self):
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE SINK snk FROM t WITH (connector = 'blackhole')")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        sink = s.sink_of("snk")
        assert sink.rows_written == 2
        assert s.run_sql("SHOW SINKS") == [("snk",)]
        s.run_sql("DROP SINK snk")
        assert s.run_sql("SHOW SINKS") == []

    def test_file_sink_changelog(self, tmp_path):
        out = tmp_path / "out.jsonl"
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("CREATE MATERIALIZED VIEW m AS "
                  "SELECT k, v * 2 AS d FROM t")
        s.run_sql(f"CREATE SINK snk FROM m WITH (connector = 'file', "
                  f"path = '{out}')")
        s.run_sql("INSERT INTO t VALUES (1, 10), (2, 20)")
        s.flush()
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        inserts = [(l["k"], l["d"]) for l in lines if l["__op"] == "insert"]
        assert sorted(inserts) == [(1, 20), (2, 40)]

    def test_sink_as_select(self, tmp_path):
        out = tmp_path / "sel.jsonl"
        s = Session()
        s.run_sql("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        s.run_sql("INSERT INTO t VALUES (1, 5), (2, 50)")
        s.flush()
        s.run_sql(f"CREATE SINK snk AS SELECT k FROM t WHERE v > 10 "
                  f"WITH (connector = 'file', path = '{out}')")
        s.flush()
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert [(l["k"], l["__op"]) for l in lines] == [(2, "insert")]


class TestDatagenSourceSql:
    def test_datagen_source_mv(self):
        s = Session(source_chunk_capacity=8)
        s.run_sql("""CREATE SOURCE g (k BIGINT, x DOUBLE)
                     WITH (connector = 'datagen',
                           'datagen.rows.per.chunk' = 8)""")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k FROM g")
        for _ in range(3):
            s.tick()
        rows = sorted(r[0] for r in s.mv_rows("m"))
        assert rows == list(range(len(rows)))
        assert len(rows) >= 8


def _run_child(code: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class TestReviewRegressions:
    def test_sink_from_pkless_table_hides_row_id(self, tmp_path):
        out = tmp_path / "o.jsonl"
        s = Session()
        s.run_sql("CREATE TABLE t (a BIGINT)")   # hidden _row_id pk
        s.run_sql(f"CREATE SINK snk FROM t WITH (connector='file', "
                  f"path='{out}')")
        s.run_sql("INSERT INTO t VALUES (7)")
        s.flush()
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert lines and all("_row_id" not in l for l in lines)

    def test_drop_mv_stops_feed_and_frees_split_state(self):
        s = Session(source_chunk_capacity=4)
        s.run_sql("""CREATE SOURCE g (k BIGINT)
                     WITH (connector='datagen',
                           'datagen.rows.per.chunk'=4)""")
        s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k FROM g")
        s.tick()
        assert len(s.feeds) == 1
        tid = s.feeds[0].state_table.table_id
        s.run_sql("DROP MATERIALIZED VIEW m")
        assert s.feeds == []
        assert s.store.table_len(tid) == 0
        s.tick()   # no dangling queue/readers

    def test_phantom_sink_output_truncated_on_recovery(self, tmp_path):
        """Crash after a delivery but before ANY progress row committed:
        the delivered bytes are phantom output and must be rolled back."""
        d = str(tmp_path / "db")
        out = str(tmp_path / "o.jsonl")
        child = textwrap.dedent(f"""
            import os
            from risingwave_tpu.frontend import Session
            s = Session(data_dir={d!r}, checkpoint_frequency=100)
            s.run_sql("CREATE TABLE t (a BIGINT PRIMARY KEY)")
            s.run_sql("CREATE SINK snk FROM t WITH (connector='file', "
                      "path='{out}')")
            s.run_sql("INSERT INTO t VALUES (1)")
            s.tick(checkpoint=False)   # delivers without durability
            s._drain_inflight()
            assert open({out!r}).read().strip(), "file should have bytes"
            os._exit(0)
        """)
        res = _run_child(child)
        assert res.returncode == 0, res.stderr[-2000:]
        s = Session(data_dir=d, checkpoint_frequency=100)
        # recovered table is empty (nothing checkpointed) → sink empty too
        assert s.run_sql("SELECT * FROM t") == []
        assert open(out).read() == ""

    def test_sink_as_select_agg_recovers_in_window(self, tmp_path):
        """Crash between CREATE SINK AS SELECT count(*) and its first
        checkpoint: recovery must re-backfill, not restart from zero."""
        d = str(tmp_path / "db")
        out = str(tmp_path / "o.jsonl")
        child = textwrap.dedent(f"""
            import os
            from risingwave_tpu.frontend import Session
            s = Session(data_dir={d!r})
            s.run_sql("CREATE TABLE t (a BIGINT PRIMARY KEY)")
            s.run_sql("INSERT INTO t VALUES (1), (2), (3)")
            s.flush()                  # rows durable
            s.run_sql("CREATE SINK snk AS SELECT count(*) AS n FROM t "
                      "WITH (connector='file', path='{out}')")
            os._exit(0)                # before any checkpoint of snk state
        """)
        res = _run_child(child)
        assert res.returncode == 0, res.stderr[-2000:]
        s = Session(data_dir=d)
        s.run_sql("INSERT INTO t VALUES (4)")
        s.flush()
        lines = [json.loads(l) for l in open(out).read().splitlines()]
        # fold the changelog: final count must be 4 (3 backfilled + 1)
        final = None
        for l in lines:
            if l["__op"] in ("insert", "update_insert"):
                final = l["n"]
        assert final == 4


class TestCrashRecovery:
    def test_split_state_resumes_after_kill(self, tmp_path):
        """Source offsets persisted at checkpoints are sought on recovery:
        the MV keeps extending the sequence with no duplicates/gaps."""
        d = str(tmp_path / "db")
        child = textwrap.dedent(f"""
            import os
            from risingwave_tpu.frontend import Session
            s = Session(data_dir={d!r}, source_chunk_capacity=4,
                        checkpoint_frequency=1)
            s.run_sql('''CREATE SOURCE g (k BIGINT)
                         WITH (connector = 'datagen',
                               'datagen.rows.per.chunk' = 4)''')
            s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k FROM g")
            for _ in range(3):
                s.tick()          # every tick checkpoints
            s._drain_inflight()
            print(len(s.mv_rows("m")))
            os._exit(0)           # no graceful shutdown
        """)
        res = _run_child(child)
        assert res.returncode == 0, res.stderr[-2000:]
        n_before = int(res.stdout.strip().splitlines()[-1])
        assert n_before == 12

        s = Session(data_dir=d, source_chunk_capacity=4,
                    checkpoint_frequency=1)
        rows = sorted(r[0] for r in s.mv_rows("m"))
        assert rows == list(range(n_before))
        for _ in range(2):
            s.tick()
        rows = sorted(r[0] for r in s.mv_rows("m"))
        # resumed exactly where it left off: still contiguous, no dups
        assert rows == list(range(len(rows)))
        assert len(rows) == n_before + 8

    def test_file_sink_exactly_once_across_kill(self, tmp_path):
        """Kill between checkpoints: delivered-but-uncommitted sink bytes
        are truncated on recovery and re-delivered exactly once."""
        d = str(tmp_path / "db")
        out = str(tmp_path / "out.jsonl")
        child = textwrap.dedent(f"""
            import os
            from risingwave_tpu.frontend import Session
            s = Session(data_dir={d!r}, source_chunk_capacity=4,
                        checkpoint_frequency=2)
            s.run_sql('''CREATE SOURCE g (k BIGINT)
                         WITH (connector = 'datagen',
                               'datagen.rows.per.chunk' = 4)''')
            s.run_sql("CREATE MATERIALIZED VIEW m AS SELECT k FROM g")
            s.run_sql("CREATE SINK snk FROM m WITH (connector='file', "
                      "path='{out}')")
            s.flush()
            for _ in range(5):
                s.tick()          # epochs 2..: ckpt every 2nd
            s._drain_inflight()
            os._exit(0)           # die with non-checkpointed deliveries
        """)
        res = _run_child(child)
        assert res.returncode == 0, res.stderr[-2000:]

        s = Session(data_dir=d, source_chunk_capacity=4,
                    checkpoint_frequency=2)
        for _ in range(2):
            s.tick()
        s.flush()
        lines = [json.loads(l) for l in open(out).read().splitlines()]
        ks = [l["k"] for l in lines if l["__op"] == "insert"]
        # exactly-once: every k delivered once, contiguous from 0
        assert len(ks) == len(set(ks))
        assert sorted(ks) == list(range(len(ks)))
        assert len(ks) == len(s.mv_rows("m"))
